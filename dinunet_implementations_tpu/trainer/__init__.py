from .checkpoint import (
    CorruptCheckpointError,
    load_checkpoint,
    load_inference_state,
    load_params,
    save_checkpoint,
)
from .loop import FederatedTrainer
from .metrics import Averages, ClassificationMetrics, is_improvement
from .steps import (
    FederatedTask,
    TrainState,
    compile_epoch_aot,
    epoch_program_artifacts,
    eval_forward,
    init_train_state,
    make_eval_fn,
    make_optimizer,
    make_train_epoch_fn,
)
