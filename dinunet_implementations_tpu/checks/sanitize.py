"""Runtime sanitizer: compile-counter guard + leak/NaN checking for a fit.

The static rules (rules.py) catch what an AST can see; this module checks the
dynamic halves of the same invariants while a real fit runs:

- **compile counter** — the one-compilation-per-(engine, topology) property.
  PR 2 asserted it in one test via the jitted function's private
  ``_cache_size``; here that becomes a reusable guard: a fit whose
  ``epoch_fn`` compiles more than once (shape drift, a traced value baked
  static, a per-fault-pattern recompile) fails loudly with the round/site
  context from ``TrainState.health``.
- **leak checking** — ``jax.checking_leaks`` around the fit surfaces tracer
  leaks out of the jitted epoch/eval closures.
- **debug-NaN** — ``jax_debug_nans`` pinpoints the op that produced a
  non-finite value (NOT for FaultPlan NaN-injection runs, where NaNs are the
  test stimulus — use ``DINUNET_SANITIZE=compile,leaks`` there).

Activation: ``DINUNET_SANITIZE=1`` (all checks) or a comma subset
(``compile``, ``leaks``, ``nans``); the CLI and bench.py expose ``--sanitize``
as sugar for the env var. Disabled (the default) every hook below is a no-op
costing one dict lookup — the sanitizer is a debug mode, not a tax.
"""

from __future__ import annotations

import contextlib
import os
from contextlib import contextmanager

ALL_FLAGS = ("compile", "leaks", "nans")
ENV_VAR = "DINUNET_SANITIZE"


class SanitizerViolation(RuntimeError):
    """A runtime invariant the sanitizer guards was violated."""


def sanitize_flags(value: str | None = None) -> frozenset[str]:
    """Parse ``DINUNET_SANITIZE`` (or an explicit ``value``) into the active
    check set. ``""``/``0``/``false`` → none; ``1``/``true``/``all`` → all;
    otherwise a comma list of flag names."""
    raw = os.environ.get(ENV_VAR, "") if value is None else value
    raw = (raw or "").strip().lower()
    if raw in ("", "0", "false", "off", "no"):
        return frozenset()
    if raw in ("1", "true", "on", "yes", "all"):
        return frozenset(ALL_FLAGS)
    flags = frozenset(t.strip() for t in raw.split(",") if t.strip())
    unknown = flags - set(ALL_FLAGS)
    if unknown:
        raise ValueError(
            f"{ENV_VAR}: unknown sanitizer flag(s) {sorted(unknown)}; "
            f"valid: {ALL_FLAGS} (or 1/0)"
        )
    return flags


def sanitize_enabled() -> bool:
    return bool(sanitize_flags())


def jit_cache_size(fn) -> int | None:
    """Number of compiled programs cached on a jitted callable, or ``None``
    when this jax build does not expose the counter (the guard then degrades
    to a no-op rather than failing spuriously)."""
    cs = getattr(fn, "_cache_size", None)
    if callable(cs):
        try:
            return int(cs())
        except (TypeError, ValueError):
            return None
    return None


class CompileGuard:
    """Reusable compile-counter guard over named jitted callables.

    Snapshot the cache sizes at construction, run the workload, then
    :meth:`check` — more than ``max_compiles`` NEW programs per callable
    raises :class:`SanitizerViolation`. This is the no-recompile property as
    a harness: one guard per (engine, topology) fit, or around a bench chain,
    or in a test.
    """

    def __init__(self, fns: dict, max_compiles: int = 1, label: str = ""):
        self.max_compiles = max_compiles
        self.label = label
        self._fns = {
            name: f for name, f in fns.items()
            if f is not None and jit_cache_size(f) is not None
        }
        self._start = {name: jit_cache_size(f) for name, f in self._fns.items()}

    def counts(self) -> dict:
        """New compilations per guarded callable since construction."""
        return {
            name: (jit_cache_size(f) or 0) - self._start[name]
            for name, f in self._fns.items()
        }

    def check(self, context: str = "") -> dict:
        counts = self.counts()
        for name, delta in counts.items():
            if delta > self.max_compiles:
                where = f" [{self.label}]" if self.label else ""
                ctx = f"\n  context: {context}" if context else ""
                raise SanitizerViolation(
                    f"compile-counter guard{where}: '{name}' compiled "
                    f"{delta} programs (expected <= {self.max_compiles}). "
                    f"The epoch program must compile once per (engine, "
                    f"topology); extra compilations mean shape drift or a "
                    f"traced value being treated as static.{ctx}"
                )
        return counts


class SanitizeReport:
    """Mutable holder the fit's caller feeds results into, so a violation
    message can carry the round/site context from ``TrainState.health``."""

    def __init__(self, label: str = "fit"):
        self.label = label
        self.result: dict | None = None

    def note_result(self, result) -> None:
        if isinstance(result, dict):
            self.result = result

    def context(self) -> str:
        if not self.result:
            return ""
        parts = []
        state = self.result.get("state")
        rnd = getattr(state, "round", None)
        if rnd is not None:
            try:
                parts.append(f"round={int(rnd)}")
            except (TypeError, ValueError):
                pass
        health = self.result.get("site_health")
        if health:
            parts.append(f"site_health={health}")
        if self.result.get("best_val_epoch") is not None:
            parts.append(f"best_val_epoch={self.result['best_val_epoch']}")
        return " ".join(parts)


@contextmanager
def sanitized_fit(trainer, label: str = "fit", max_epoch_compiles: int = 1,
                  flags: frozenset[str] | None = None):
    """Wrap one ``FederatedTrainer.fit`` in the active sanitizer checks.

    Yields a :class:`SanitizeReport` (feed ``fit``'s result dict into
    ``note_result`` for violation context), or ``None`` when the sanitizer is
    disabled. The compile counter is checked AFTER the leak/NaN contexts
    close, so all compilations — including any the debug modes themselves
    force — happen under one consistent jax config.
    """
    flags = sanitize_flags() if flags is None else frozenset(flags)
    if not flags:
        yield None
        return
    import jax

    report = SanitizeReport(label=label)
    with contextlib.ExitStack() as stack:
        if "nans" in flags:
            prev = jax.config.jax_debug_nans
            jax.config.update("jax_debug_nans", True)
            stack.callback(jax.config.update, "jax_debug_nans", prev)
        if "leaks" in flags:
            stack.enter_context(jax.checking_leaks())
        # epoch_fn only: eval_fn legitimately compiles once per split shape
        # (validation vs test step counts differ), so its count is not an
        # invariant — the epoch program's is.
        guard = (
            CompileGuard(
                {"epoch_fn": getattr(trainer, "epoch_fn", None)},
                max_compiles=max_epoch_compiles, label=label,
            )
            if "compile" in flags else None
        )
        yield report
    if guard is not None:
        guard.check(context=report.context())
