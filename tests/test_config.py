"""Config system tests (reference parity: compspec.json + inputspec.json)."""

import json
import os

import pytest

from dinunet_implementations_tpu import (
    AggEngine,
    NNComputation,
    TrainConfig,
    export_compspec,
    load_inputspec,
)


# parity pins that READ the mounted reference tree skip when it's absent
# (same convention as tests/test_golden.py needs_fsl)
needs_reference = pytest.mark.skipif(
    not os.path.isdir("/root/reference"), reason="reference tree not mounted"
)


def test_defaults_match_reference_compspec():
    """Defaults mirror reference compspec.json:32-224."""
    cfg = TrainConfig()
    assert cfg.task_id == "FS-Classification"
    assert cfg.mode == "train"
    assert cfg.agg_engine == "dSGD"
    assert cfg.batch_size == 16
    assert cfg.local_iterations == 1
    assert cfg.learning_rate == 1e-3
    assert cfg.epochs == 101
    assert cfg.precision_bits == "32"
    assert cfg.patience == 35
    assert cfg.split_ratio == (0.8, 0.1, 0.1)
    assert cfg.num_folds is None
    assert cfg.fs_args.input_size == 66
    assert cfg.fs_args.hidden_sizes == (256, 128, 64, 32)
    assert cfg.fs_args.num_class == 2
    assert cfg.fs_args.dad_reduction_rank == 10
    assert cfg.fs_args.dad_num_pow_iters == 5
    assert cfg.fs_args.dad_tol == 1e-3
    assert cfg.ica_args.window_size == 10
    # the workload value (datasets/icalstm/inputspec.json, both sites), not the
    # compspec template's 384 — config, bench, and fixtures must agree
    assert cfg.ica_args.hidden_size == 348
    assert cfg.ica_args.seq_len == 13  # dead compspec field, kept for parity


@needs_reference
def test_defaults_match_reference_ica_inputspec():
    """Pin ICA defaults against the reference's actual shipped inputspec."""
    import json as _json

    with open("/root/reference/datasets/icalstm/inputspec.json") as f:
        spec = _json.load(f)
    cfg = TrainConfig()
    for site in spec:
        assert cfg.ica_args.hidden_size == site["hidden_size"]["value"]
        assert cfg.ica_args.input_size == site["input_size"]["value"]
        assert cfg.ica_args.window_size == site["window_size"]["value"]
        assert cfg.ica_args.window_stride == site["window_stride"]["value"]
        assert cfg.ica_args.temporal_size == site["temporal_size"]["value"]
        assert cfg.ica_args.num_components == site["num_components"]["value"]


def test_registry_enums():
    assert NNComputation.TASK_FREE_SURFER == "FS-Classification"
    assert NNComputation.TASK_ICA == "ICA-Classification"
    assert AggEngine.DECENTRALIZED_SGD == "dSGD"
    assert AggEngine.RANK_DAD == "rankDAD"
    assert AggEngine.POWER_SGD == "powerSGD"


def test_with_overrides_routes_task_args():
    cfg = TrainConfig().with_overrides(
        {"batch_size": 32, "input_size": 100, "hidden_sizes": [64, 32], "window_size": 20}
    )
    assert cfg.batch_size == 32
    assert cfg.fs_args.input_size == 100
    assert cfg.fs_args.hidden_sizes == (64, 32)
    assert cfg.ica_args.input_size == 100  # shared field name lands in both blocks
    assert cfg.ica_args.window_size == 20


def test_load_inputspec(tmp_path):
    spec = [
        {"labels_file": {"value": "site1_Covariate.csv"}, "input_size": {"value": 66}},
        {"labels_file": {"value": "site2_Covariate.csv"}, "input_size": {"value": 66}},
    ]
    p = tmp_path / "inputspec.json"
    p.write_text(json.dumps(spec))
    sites = load_inputspec(str(p))
    assert len(sites) == 2
    assert sites[0]["labels_file"] == "site1_Covariate.csv"
    assert sites[1]["input_size"] == 66


@needs_reference
def test_load_reference_fixture_inputspec():
    """Our loader parses the reference's actual simulator spec unchanged."""
    sites = load_inputspec("/root/reference/datasets/test_fsl/inputspec.json")
    assert len(sites) == 5
    for i, s in enumerate(sites):
        assert s["data_column"] == "freesurferfile"
        assert s["labels_column"] == "isControl"
        assert s["input_size"] == 66
        assert s["hidden_sizes"] == [256, 128, 64, 32]
    cfg = TrainConfig().with_overrides(sites[0])
    assert cfg.fs_args.labels_file == "site1_Covariate.csv"
    assert cfg.fs_args.hidden_sizes == (256, 128, 64, 32)


def test_export_compspec_roundtrip():
    spec = export_compspec()
    inputs = spec["computation"]["input"]
    assert inputs["task_id"]["default"] == "FS-Classification"
    assert inputs["agg_engine"]["conditional"] == {"variable": "mode", "value": "train"}
    assert inputs["FS-Classification_args"]["default"]["dad_reduction_rank"] == 10
    json.dumps(spec)  # must be JSON-serializable


def test_block_dict_overrides():
    """Review finding: dict overrides for dataclass-typed fields must merge."""
    cfg = TrainConfig().with_overrides({"pretrain_args": {"epochs": 5}})
    assert cfg.pretrain_args.epochs == 5
    assert cfg.pretrain_args.patience == 51  # default preserved
    cfg = TrainConfig().with_overrides({"fs_args": {"input_size": 99}})
    assert cfg.fs_args.input_size == 99
    assert cfg.fs_args.hidden_sizes == (256, 128, 64, 32)
    cfg = TrainConfig().with_overrides({"FS-Classification_args": {"input_size": 42}})
    assert cfg.fs_args.input_size == 42


def test_all_tasks_have_args():
    for task in NNComputation.ALL:
        args = TrainConfig(task_id=task).task_args()
        assert args.num_class == 2


@needs_reference
def test_resolve_site_configs_cycles():
    import dinunet_implementations_tpu as dt

    cfgs = dt.resolve_site_configs(TrainConfig(), "/root/reference/datasets/icalstm", num_sites=4)
    # 2-entry spec cycles 0,1,0,1 — entry 1 has no data_file, entry 0 does
    assert cfgs[0].ica_args.data_file == cfgs[2].ica_args.data_file == "HCP_AllData_sess1.npz"
    assert cfgs[1].ica_args.hidden_size == 348


def test_with_overrides_keeps_unset_pretrain_args_none():
    cfg = TrainConfig().with_overrides({"batch_size": 8})
    assert cfg.pretrain_args is None


def test_r6_perf_knobs_defaults_and_overrides():
    """r6 knobs: rounds_scan_xs (the steps.py peak-HBM escape hatch, ADVICE
    r5) and dad_warm_start (rankDAD warm-started subspaces) must exist with
    their documented defaults and accept inputspec-style overrides."""
    cfg = TrainConfig()
    assert cfg.rounds_scan_xs is True
    for args in (cfg.fs_args, cfg.ica_args, cfg.smri3d_args,
                 cfg.multimodal_args):
        assert args.dad_warm_start is True
    cfg = TrainConfig().with_overrides(
        {"rounds_scan_xs": False, "dad_warm_start": False}
    )
    assert cfg.rounds_scan_xs is False
    # flat keys route into every matching task-args block (reference cache
    # semantics), so the engine factory sees the override via task_args()
    assert cfg.task_args().dad_warm_start is False
