// Native batch reader for FreeSurfer aseg-stats TSV files.
//
// The reference outsources file I/O to torch DataLoader worker processes and
// re-reads every TSV per item per epoch (reference comps/fs/__init__.py:33-39
// via torch's native worker pool; SURVEY.md §3.5 flags the re-read as the
// ingest pathology). The TPU build reads each file once into a dense matrix
// (data/freesurfer.py as_arrays); this module is the native equivalent of the
// reference's native-worker ingest path: a threaded C++ parser that fills the
// [n_files, n_feats] batch in one call.
//
// Semantics are bit-identical to data/freesurfer.py::read_aseg_stats:
//   - skip the first (header) line;
//   - per remaining nonempty line, parse the text after the first '\t' with
//     strtod (same correctly-rounded double as Python's float());
//   - max-normalize in double precision, then cast to float32.
//
// C ABI only (loaded via ctypes — no pybind11 in this image). Thread-safe,
// no Python involvement during parsing, deterministic output placement.

#include <charconv>
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

// Parse one file into out[0..n_feats). Returns empty string on success,
// else a human-readable reason (the Python wrapper falls back on any error).
std::string parse_one(const char* path, long n_feats, float* out) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return std::string("cannot open ") + path;
  std::string content;
  {
    char buf[1 << 16];
    size_t got;
    while ((got = std::fread(buf, 1, sizeof buf, f)) > 0) content.append(buf, got);
    std::fclose(f);
  }
  std::vector<double> vals;
  vals.reserve(n_feats);
  size_t pos = 0, end = content.size();
  bool header = true;
  while (pos < end) {
    size_t nl = content.find('\n', pos);
    if (nl == std::string::npos) nl = end;
    size_t line_end = nl;
    while (line_end > pos && (content[line_end - 1] == '\r' ||
                              content[line_end - 1] == ' ' ||
                              content[line_end - 1] == '\t'))
      --line_end;  // strip(): trailing CR / whitespace
    size_t lbeg = pos;  // strip(): leading whitespace too — a leading-tab
    while (lbeg < line_end && (content[lbeg] == ' ' || content[lbeg] == '\t' ||
                               content[lbeg] == '\r'))
      ++lbeg;  // line like "\t1.5" must fail "no value column" as in Python
    if (header) {
      header = false;
    } else if (line_end > lbeg) {
      size_t tab = content.find('\t', lbeg);
      if (tab == std::string::npos || tab >= line_end)
        return std::string("no value column in ") + path;
      // value token = between the first tab and the next tab / line end.
      // std::from_chars, NOT strtod: strtod honors LC_NUMERIC, so a
      // decimal-comma locale would silently truncate "123.45" to 123
      // without tripping the error path — from_chars is locale-free and
      // matches Python float() (which is what read_aseg_stats uses).
      size_t vbeg = tab + 1;
      size_t vend = content.find('\t', vbeg);
      if (vend == std::string::npos || vend > line_end) vend = line_end;
      while (vbeg < vend && (content[vbeg] == ' ' || content[vbeg] == '\t'))
        ++vbeg;  // float() tolerates surrounding whitespace
      const char* s = content.c_str() + vbeg;
      const char* se = content.c_str() + vend;
      if (s < se && *s == '+') ++s;  // from_chars rejects the leading '+'
      double v = 0.0;
      auto res = std::from_chars(s, se, v);
      // the FULL token must parse (trailing spaces aside): "1.5abc" or a
      // leading-tab line must error like Python's float(), not truncate
      const char* rest = res.ptr;
      while (rest < se && (*rest == ' ')) ++rest;
      if (res.ec != std::errc() || res.ptr == s || rest != se)
        return std::string("bad number in ") + path;
      // NaN/inf would make the max-normalize below diverge from numpy's
      // NaN-propagating np.max (advisor finding r3) — error out so the
      // wrapper falls back to the bit-identical Python reader for the batch
      if (!std::isfinite(v))
        return std::string("non-finite value in ") + path;
      vals.push_back(v);
    }
    pos = nl + 1;
  }
  if ((long)vals.size() != n_feats) {
    return std::string(path) + ": expected " + std::to_string(n_feats) +
           " features, got " + std::to_string(vals.size());
  }
  double mx = vals[0];
  for (double v : vals)
    if (v > mx) mx = v;
  for (long i = 0; i < n_feats; ++i) out[i] = (float)(vals[i] / mx);
  return std::string();
}

}  // namespace

extern "C" {

// Fill out[n_files, n_feats] from the given paths. Returns 0 on success;
// on failure returns 1 with the first error message copied into errbuf.
int fastio_read_aseg_batch(const char** paths, long n_files, long n_feats,
                           float* out, char* errbuf, long errlen) {
  unsigned hw = std::thread::hardware_concurrency();
  long n_threads = (long)(hw ? hw : 2);
  if (n_threads > n_files) n_threads = n_files;
  if (n_threads < 1) n_threads = 1;
  std::vector<std::string> errors((size_t)n_threads);
  std::vector<std::thread> workers;
  workers.reserve((size_t)n_threads);
  for (long t = 0; t < n_threads; ++t) {
    workers.emplace_back([=, &errors]() {
      for (long i = t; i < n_files; i += n_threads) {
        std::string err = parse_one(paths[i], n_feats, out + i * n_feats);
        if (!err.empty() && errors[(size_t)t].empty()) errors[(size_t)t] = err;
      }
    });
  }
  for (auto& w : workers) w.join();
  for (auto& e : errors) {
    if (!e.empty()) {
      std::snprintf(errbuf, (size_t)errlen, "%s", e.c_str());
      return 1;
    }
  }
  return 0;
}

}  // extern "C"
