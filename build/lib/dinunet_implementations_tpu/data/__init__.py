from .api import DataHandle, SiteArrays, SiteDataset, build_site_dataset
from .batching import FedBatches, plan_epoch, plan_eval
from .freesurfer import FreeSurferDataset, FSVDataHandle, coerce_label, read_aseg_stats
from .ica import ICADataHandle, ICADataset, load_timecourses, window_timecourses
from .splits import kfold_splits, load_split_file, resolve_splits, split_by_ratio
