"""Fused Pallas TPU kernel for the LSTM recurrence (forward + BPTT backward).

The ICA-LSTM's hot loop (SURVEY.md §3.4) is the time recurrence: per step a
small ``h @ W_hh`` matmul plus gate math. The XLA scan path (models/icalstm.py)
already hoists the input projection; this kernel goes further and keeps the
carry (h, c) and all four recurrence matrices resident in VMEM across the
whole sequence, streaming per-step inputs/outputs HBM↔VMEM via the grid
pipeline — no per-step HBM round trip for the carry, no per-step kernel
launches.

Layout choice: gates live in four separate ``[T, B, H]`` arrays (not one
``[T, B, 4H]``) so every block's lane dimension is H and no slice ever crosses
a lane boundary (Mosaic-friendly; see pallas_guide.md pitfall #2).

Grid: ``(batch_tiles, T)`` — TPU grids execute sequentially, so VMEM scratch
carries (h, c) across the T dimension; time-reversed index maps drive the
backward kernel.

Four measured design points (flagship shape, 32 vmapped sites, v5e):

- **The i2h projection is fused into the forward kernel** (round 3): W_ih
  lives in VMEM beside W_hh and the kernel streams the raw ``x [T, B, D]``
  once — D=256 inbound values per step-row instead of the 4H=696 of a
  pre-projected gate layout, and no ``[T, B, 4H]`` XLA materialization at
  all. dx/dW_ih/db remain XLA einsums over the streamed dpreact cotangents.
- **dW lives OUTSIDE the kernel.** The weight gradient is the only cross-row
  reduction in BPTT; accumulating it in-kernel forced 4 extra outer-product
  dots per backward step AND made the kernel's outputs non-row-wise. Instead
  the backward kernel streams out the gate pre-activation cotangents, which
  concatenate on the FEATURE axis ([T, B, 4H]) so dx/dW_ih/dW_hh are plain
  696-wide MXU matmuls — the k-batched einsum forms canonicalize into dots
  XLA lowered through a ~3× slower convolution emitter (round 3 profiling;
  einsum spelling alone cannot dodge it, only the concat's different
  structure does).
- **The backward takes PRE-transposed recurrent weights.** ``w[k].T`` inside
  the kernel re-ran a lane/sublane transpose on every one of the T grid
  steps and made the backward ~20× slower than the forward; transposing once
  in XLA and keeping W_hhᵀ resident removed the entire gap (round 3 — this
  was the single largest perf bug in the build).
- **vmap folds into kernel rows, not grid steps.** jax's default vmap rule
  for ``pallas_call`` prepends a grid dimension, which executes
  SEQUENTIALLY on a TPU core — 32 vmapped sites ran as 32 serial passes of
  [16, H] matmuls. Both kernel entry points carry a ``custom_vmap`` rule that
  folds the mapped axis into the batch-row dimension instead ([512, H]
  matmuls, full MXU rows), padding rows to the kernel tile as needed. The
  fold is valid because every kernel output is row-wise (see previous point).

The terminal carry (hT, cT) is emitted from the f32 VMEM scratch — never
quantized to the bf16 streams — because the ring LSTM (parallel/sequence.py)
relays it across sequence chunks.

Semantics: standard LSTM gates (single sigmoid). The reference's
double-sigmoid quirk mode stays on the XLA scan path (models/icalstm.py) —
the kernel is the fast path for the default configuration.
``compute_dtype=bfloat16`` runs the matmuls in bf16 with f32 accumulation;
``None`` (default) is full f32, bit-comparable with the scan path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.custom_batching import custom_vmap
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

B_TILE = 128


def _interpret() -> bool:
    # Pallas TPU kernels run in interpreter mode on CPU (tests / simulators)
    return jax.default_backend() == "cpu"


def _cdt_name(compute_dtype) -> str | None:
    return jnp.dtype(compute_dtype).name if compute_dtype is not None else None


# ---------------------------------------------------------------------------
# fused forward: the i2h projection runs IN-kernel (W_ih resident in VMEM),
# so the kernel streams the raw input x [T, B, D] once instead of four
# pre-projected [T, B, H] gate arrays — D=256 vs 4H=696 inbound values per
# step-row on the flagship shape, ~2.7× less inbound HBM traffic, and the
# [B*T, D] @ [D, 4H] XLA matmul plus its [T, B, 4H] HBM materialization
# disappear entirely (VERDICT r2 #2).
# ---------------------------------------------------------------------------


def _fwd_fused_kernel(
    x, wih, b, whh, h0, c0, hs, cs, ai, af, ao, ag, hT, cT, h_s, c_s
):
    t = pl.program_id(1)

    @pl.when(t == 0)
    def _():
        h_s[:] = h0[:]
        c_s[:] = c0[:]

    f32 = jnp.float32
    xt = x[0]  # [bt, D] this step's input block, at stream dtype
    h = h_s[:].astype(whh.dtype)
    # preact_k = x_t @ Wih_k + b_k + h @ Whh_k  (both W stacks VMEM-resident)
    pre = [
        jnp.dot(xt, wih[k], preferred_element_type=f32)
        + jnp.dot(h, whh[k], preferred_element_type=f32)
        + b[k].astype(f32)
        for k in range(4)
    ]
    i = jax.nn.sigmoid(pre[0])
    f = jax.nn.sigmoid(pre[1])
    o = jax.nn.sigmoid(pre[2])
    g = jnp.tanh(pre[3])
    c = f * c_s[:] + i * g
    h = o * jnp.tanh(c)
    h_s[:] = h
    c_s[:] = c
    hs[0] = h.astype(hs.dtype)
    cs[0] = c.astype(cs.dtype)
    ai[0] = i.astype(ai.dtype)
    af[0] = f.astype(af.dtype)
    ao[0] = o.astype(ao.dtype)
    ag[0] = g.astype(ag.dtype)

    # terminal carry at FULL f32 (straight from VMEM scratch, not the possibly
    # bf16 hs/cs streams): the ring-LSTM relays this carry between sequence
    # chunks, and quantizing it at each chunk boundary would silently diverge
    # the sharded run from the dense one (review finding, round 3)
    @pl.when(t == pl.num_programs(1) - 1)
    def _():
        hT[:] = h_s[:]
        cT[:] = c_s[:]


def _fwd_fused_call(x, wih4, b4, whh4, h0, c0, compute_dtype=None):
    T, B, D = x.shape
    H = wih4.shape[-1]
    bt = min(B_TILE, B)
    assert B % bt == 0, (
        f"batch {B} must be a multiple of the kernel tile {bt}; "
        "use lstm_forward_fused(), which pads"
    )
    if compute_dtype is not None:
        x = x.astype(compute_dtype)
        wih4 = wih4.astype(compute_dtype)
        whh4 = whh4.astype(compute_dtype)
    grid = (B // bt, T)
    spec_x = pl.BlockSpec((1, bt, D), lambda b, t: (t, b, 0), memory_space=pltpu.VMEM)
    spec_t = pl.BlockSpec((1, bt, H), lambda b, t: (t, b, 0), memory_space=pltpu.VMEM)
    spec_b = pl.BlockSpec((bt, H), lambda b, t: (b, 0), memory_space=pltpu.VMEM)
    spec_wih = pl.BlockSpec((4, D, H), lambda b, t: (0, 0, 0), memory_space=pltpu.VMEM)
    spec_whh = pl.BlockSpec((4, H, H), lambda b, t: (0, 0, 0), memory_space=pltpu.VMEM)
    spec_bias = pl.BlockSpec((4, H), lambda b, t: (0, 0), memory_space=pltpu.VMEM)
    stream_dtype = jnp.dtype(compute_dtype) if compute_dtype is not None else jnp.float32
    out_shape = jax.ShapeDtypeStruct((T, B, H), stream_dtype)
    carry_shape = jax.ShapeDtypeStruct((B, H), jnp.float32)
    return pl.pallas_call(
        _fwd_fused_kernel,
        grid=grid,
        in_specs=[spec_x, spec_wih, spec_bias, spec_whh, spec_b, spec_b],
        out_specs=[spec_t] * 6 + [spec_b] * 2,
        out_shape=[out_shape] * 6 + [carry_shape] * 2,
        scratch_shapes=[pltpu.VMEM((bt, H), jnp.float32)] * 2,
        interpret=_interpret(),
    )(x, wih4, b4, whh4, h0, c0)


# ---------------------------------------------------------------------------
# backward (dW is computed OUTSIDE the kernel — see module docstring)
# ---------------------------------------------------------------------------


def _bwd_kernel(
    T_total,
    ai, af, ao, ag, cs, cs_prev, wT, c0, dhs, dhT, dcT,
    dxi_i, dxi_f, dxi_o, dxi_g, dh0, dc0,
    dh_s, dc_s,
):
    t = pl.program_id(1)  # 0..T-1, walking time backwards: time = T-1-t
    first_time = t == 0  # time T-1
    last_time = t == T_total - 1  # time 0

    @pl.when(first_time)
    def _():
        # seed the carries with the terminal-state cotangents (exact dcT/dhT);
        # re-seeded at the start of every batch tile (per-tile state)
        dh_s[:] = dhT[:].astype(jnp.float32)
        dc_s[:] = dcT[:].astype(jnp.float32)

    f32 = jnp.float32
    i, f, o, g = (ai[0].astype(f32), af[0].astype(f32),
                  ao[0].astype(f32), ag[0].astype(f32))
    c = cs[0].astype(f32)
    c_prev = jnp.where(last_time, c0[:].astype(f32), cs_prev[0].astype(f32))

    tanh_c = jnp.tanh(c)
    dh = dhs[0].astype(f32) + dh_s[:]
    do = dh * tanh_c
    dc = dh * o * (1.0 - tanh_c * tanh_c) + dc_s[:]
    di = dc * g
    df = dc * c_prev
    dg = dc * i

    dpi = di * i * (1.0 - i)
    dpf = df * f * (1.0 - f)
    dpo = do * o * (1.0 - o)
    dpg = dg * (1.0 - g * g)

    dxi_i[0] = dpi.astype(dxi_i.dtype)
    dxi_f[0] = dpf.astype(dxi_f.dtype)
    dxi_o[0] = dpo.astype(dxi_o.dtype)
    dxi_g[0] = dpg.astype(dxi_g.dtype)

    # dh_{t-1} = Σ_k dp_k @ W_kᵀ (matmuls in w's dtype, f32 accumulation).
    # wT holds the PRE-transposed weights: transposing inside the kernel
    # (w[k].T) re-ran a lane/sublane transpose on every one of the T grid
    # steps and dominated the whole backward pass — measured ~20× slower
    # than this resident-transpose layout on v5e.
    cdt = wT.dtype
    dh_prev = (
        jnp.dot(dpi.astype(cdt), wT[0], preferred_element_type=jnp.float32)
        + jnp.dot(dpf.astype(cdt), wT[1], preferred_element_type=jnp.float32)
        + jnp.dot(dpo.astype(cdt), wT[2], preferred_element_type=jnp.float32)
        + jnp.dot(dpg.astype(cdt), wT[3], preferred_element_type=jnp.float32)
    )

    dh_s[:] = dh_prev
    dc_s[:] = dc * f

    @pl.when(last_time)
    def _():
        dh0[:] = dh_s[:].astype(dh0.dtype)
        dc0[:] = dc_s[:].astype(dc0.dtype)


def _bwd_call(acts, cs, w4, c0, dhs, dhT, dcT, compute_dtype=None):
    T, B, H = cs.shape
    bt = min(B_TILE, B)
    assert B % bt == 0, f"batch {B} must be a multiple of the kernel tile {bt}"
    if compute_dtype is not None:
        w4 = w4.astype(compute_dtype)
    w4T = jnp.swapaxes(w4, 1, 2)  # transpose ONCE in XLA, resident in VMEM
    grid = (B // bt, T)

    rev = lambda b, t: (T - 1 - t, b, 0)
    b_block = lambda b, t: (b, 0)
    spec_rev = pl.BlockSpec((1, bt, H), rev, memory_space=pltpu.VMEM)
    spec_prev = pl.BlockSpec(
        (1, bt, H), lambda b, t: (jnp.maximum(T - 2 - t, 0), b, 0),
        memory_space=pltpu.VMEM,
    )
    spec_b = pl.BlockSpec((bt, H), b_block, memory_space=pltpu.VMEM)
    spec_w = pl.BlockSpec((4, H, H), lambda b, t: (0, 0, 0), memory_space=pltpu.VMEM)
    # dxi dtype must match the xi primal dtype (= the streamed act dtype);
    # dh0/dc0 match the f32 h0/c0 primals
    t_shape = jax.ShapeDtypeStruct((T, B, H), acts[0].dtype)
    b_shape = jax.ShapeDtypeStruct((B, H), jnp.float32)

    outs = pl.pallas_call(
        functools.partial(_bwd_kernel, T),
        grid=grid,
        in_specs=[spec_rev] * 4  # i, f, o, g
        + [spec_rev, spec_prev, spec_w, spec_b, spec_rev, spec_b, spec_b],
        out_specs=[spec_rev] * 4 + [spec_b, spec_b],
        out_shape=[t_shape] * 4 + [b_shape, b_shape],
        scratch_shapes=[pltpu.VMEM((bt, H), jnp.float32)] * 2,
        interpret=_interpret(),
    )(*acts, cs, cs, w4T, c0, dhs, dhT, dcT)
    return outs  # dxi_i, dxi_f, dxi_o, dxi_g, dh0, dc0


# ---------------------------------------------------------------------------
# vmap folding: mapped axes become kernel batch rows, not serial grid steps
# ---------------------------------------------------------------------------


def _broadcast_unbatched(args, in_batched, axis_size):
    return [
        a if b else jnp.broadcast_to(a[None], (axis_size,) + a.shape)
        for a, b in zip(args, in_batched)
    ]


def _fold_rows(a):
    """[S, T, B, H] → [T, S*B, H]"""
    S, T, B, H = a.shape
    return jnp.moveaxis(a, 0, 1).reshape(T, S * B, H)


def _unfold_rows(a, S, B):
    """[T, S*B, H] → [S, T, B, H]"""
    T, SB, H = a.shape
    return jnp.moveaxis(a.reshape(T, S, B, H), 1, 0)


def _pad_rows(arrs, rows, axis):
    """Pad the row dim of each array up to a kernel-tile multiple."""
    bt = min(B_TILE, rows)
    pad = (-rows) % bt
    if pad == 0:
        return arrs, rows
    padded = []
    for a in arrs:
        widths = [(0, 0)] * a.ndim
        widths[axis] = (0, pad)
        padded.append(jnp.pad(a, widths))
    return padded, rows + pad


@functools.lru_cache(maxsize=None)
def _fwd_fused_callable(cdt_name: str | None):
    cdt = jnp.dtype(cdt_name) if cdt_name else None

    @custom_vmap
    def f(x, wih4, b4, whh4, h0, c0):
        return tuple(_fwd_fused_call(x, wih4, b4, whh4, h0, c0, cdt))

    @f.def_vmap
    def _rule(axis_size, in_batched, *args):
        if any(in_batched[k] for k in (1, 2, 3)):  # per-element weights
            batched = _broadcast_unbatched(args, in_batched, axis_size)
            outs = jax.lax.map(lambda a: f(*a), tuple(batched))
            return tuple(outs), (True,) * 8
        S = axis_size
        batched = _broadcast_unbatched(
            args, [b or i in (1, 2, 3) for i, b in enumerate(in_batched)], S
        )
        x = _fold_rows(batched[0])  # [S, T, B, D] → [T, S*B, D]
        B = batched[4].shape[1]
        h0 = batched[4].reshape(S * B, -1)
        c0 = batched[5].reshape(S * B, -1)
        (x, h0, c0), _ = _pad_rows([x, h0, c0], S * B, axis=-2)
        outs = f(x, args[1], args[2], args[3], h0, c0)
        t_outs = [_unfold_rows(o[:, : S * B], S, B) for o in outs[:6]]
        b_outs = [o[: S * B].reshape(S, B, -1) for o in outs[6:]]
        return tuple(t_outs + b_outs), (True,) * 8

    return f


@functools.lru_cache(maxsize=None)
def _bwd_callable(cdt_name: str | None):
    cdt = jnp.dtype(cdt_name) if cdt_name else None

    @custom_vmap
    def f(ai, af, ao, ag, cs, w4, c0, dhs, dhT, dcT):
        return tuple(_bwd_call((ai, af, ao, ag), cs, w4, c0, dhs, dhT, dcT, cdt))

    @f.def_vmap
    def _rule(axis_size, in_batched, *args):
        if in_batched[5]:  # per-element weights
            batched = _broadcast_unbatched(args, in_batched, axis_size)
            outs = jax.lax.map(lambda a: f(*a), tuple(batched))
            return tuple(outs), (True,) * 6
        S = axis_size
        batched = _broadcast_unbatched(
            args, [b or i == 5 for i, b in enumerate(in_batched)], S
        )
        t_arrs = [_fold_rows(batched[i]) for i in (0, 1, 2, 3, 4, 7)]
        w4 = args[5]
        B = batched[6].shape[1]
        b_arrs = [batched[i].reshape(S * B, -1) for i in (6, 8, 9)]
        rows = S * B
        (ai, af, ao, ag, cs, dhs), _ = _pad_rows(t_arrs, rows, axis=-2)
        (c0, dhT, dcT), _ = _pad_rows(b_arrs, rows, axis=-2)
        outs = f(ai, af, ao, ag, cs, w4, c0, dhs, dhT, dcT)
        dxi = [_unfold_rows(o[:, :rows], S, B) for o in outs[:4]]
        db = [o[:rows].reshape(S, B, -1) for o in outs[4:]]
        return tuple(dxi + db), (True,) * 6

    return f


# ---------------------------------------------------------------------------
# public op with custom VJP
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(6,))
def lstm_recurrence_fused(x, wih4, b4, whh4, h0, c0, compute_dtype=None):
    """Fused LSTM: i2h projection + recurrence in ONE kernel pass.

    Args:
      x: ``[T, B, D]`` raw per-step inputs (at compute_dtype or f32).
      wih4: ``[4, D, H]`` f32 input-projection weights (i, f, o, g).
      b4: ``[4, H]`` f32 combined bias (``b_ih + b_hh`` per gate).
      whh4: ``[4, H, H]`` f32 recurrent weights.
      h0, c0: ``[B, H]`` f32 initial carry.

    Returns ``(hs [T, B, H], (hT, cT))`` — the terminal carry is always f32
    (written straight from the kernel's f32 VMEM scratch, never quantized to
    the stream dtype; the ring LSTM relays it between chunks). The backward
    runs the BPTT kernel (dxi ≡ dpreact); dx / dW_ih / db / dW_hh are
    MXU-shaped XLA einsums over the streamed cotangents.
    """
    hs, cs, i, f, o, g, hT, cT = _fwd_fused_callable(_cdt_name(compute_dtype))(
        x, wih4, b4, whh4, h0, c0
    )
    return hs, (hT, cT)


def _vjp_fused_fwd(x, wih4, b4, whh4, h0, c0, compute_dtype):
    hs, cs, i, f, o, g, hT, cT = _fwd_fused_callable(_cdt_name(compute_dtype))(
        x, wih4, b4, whh4, h0, c0
    )
    # b4 rides along only for its dtype: custom_vjp cotangent avals must
    # match the primal avals even when a caller passes non-f32 weights
    return (hs, (hT, cT)), (x, wih4, b4, whh4, h0, c0, hs, cs, (i, f, o, g))


def _vjp_fused_bwd(compute_dtype, res, grads):
    x, wih4, b4, whh4, h0, c0, hs, cs, acts = res
    dhs, (dhT, dcT) = grads
    cdt_name = _cdt_name(compute_dtype)
    dp_i, dp_f, dp_o, dp_g, dh0, dc0 = _bwd_callable(cdt_name)(
        *acts, cs, whh4, c0, dhs, dhT, dcT
    )
    cdt = jnp.dtype(cdt_name) if cdt_name else x.dtype
    # Concatenate the four gate cotangents on the FEATURE axis ([T, B, 4H])
    # so dx / dW_ih / dW_hh are plain 696-wide matmuls. The k-batched einsum
    # forms ('tbh,ktbg->khg' etc.) canonicalize to [4,·,·]-batched dots that
    # XLA's cost model lowers through a convolution emitter measured ~3x
    # slower in-context on v5e; the stack-axis spelling is canonicalized
    # away, only a genuine concat changes the structure.
    dpc = jnp.concatenate([dp_i, dp_f, dp_o, dp_g], axis=-1).astype(cdt)
    H = dp_i.shape[-1]
    wih_cat = jnp.swapaxes(wih4, 0, 1).reshape(wih4.shape[1], -1)  # [D, 4H]
    dx = jnp.einsum(
        "tbg,dg->tbd", dpc, wih_cat.astype(cdt),
        preferred_element_type=jnp.float32,
    ).astype(x.dtype)
    dwih = jnp.einsum(
        "tbd,tbg->dg", x.astype(cdt), dpc, preferred_element_type=jnp.float32,
    ).reshape(-1, 4, H).swapaxes(0, 1).astype(wih4.dtype)
    db = dpc.astype(jnp.float32).sum(axis=(0, 1)).reshape(4, H).astype(b4.dtype)
    h_prev = jnp.concatenate([h0[None].astype(hs.dtype), hs[:-1]], 0)
    dwhh = jnp.einsum(
        "tbh,tbg->hg", h_prev.astype(cdt), dpc, preferred_element_type=jnp.float32,
    ).reshape(H, 4, H).swapaxes(0, 1).astype(whh4.dtype)
    return dx, dwih, db, dwhh, dh0, dc0


lstm_recurrence_fused.defvjp(_vjp_fused_fwd, _vjp_fused_bwd)


# ---------------------------------------------------------------------------
# fused BIDIRECTIONAL kernels (VERDICT r3 #3): both directions advance in ONE
# grid sweep — the fwd direction consumes x block t while the rev direction
# consumes x block T-1-t (the time flip lives in the index map; no flipped
# copy of x is ever materialized). Each direction's recurrence is a serial
# dependency chain on its own carry; interleaving two independent chains in
# one kernel gives the MXU a second stream of ready matmuls while the other
# chain's h@W_hh waits on its carry — the single-direction kernel ran the
# directions as two back-to-back passes with that latency exposed twice.
# Both weight stacks stay VMEM-resident ([2, 4, D, H] + [2, 4, H, H]).
#
# EVERY rev-direction stream is stored in X-TIME convention (the rev state
# computed while consuming x[t] lands at block t, via the same flipped index
# map that reads x): the VJP then pairs dpc_rev with x/W by plain identity
# index — no jnp.flip of any [T, B, ·] array anywhere (the first cut kept
# rev streams in flipped-s order and paid ~0.7 ms/step of pure reverse-copy
# traffic in the epoch, measured on v5e). It also lets dx/dW_ih consume the
# two directions' cotangents as ONE [T, B, 8H]-wide concat matmul.
# The backward walks fwd time descending (blocks T-1-t) while the rev chain
# drains through identity maps (block t) — one kernel, both chains.
# ---------------------------------------------------------------------------


def _fwd_bidir_kernel(
    xf, xr, wih, b, whh, h0, c0,
    hsf, csf, aif, aff, aof, agf, hsr, csr, air, afr, aor, agr, hT, cT,
    hf_s, cf_s, hr_s, cr_s,
):
    t = pl.program_id(1)

    @pl.when(t == 0)
    def _():
        hf_s[:] = h0[0]
        cf_s[:] = c0[0]
        hr_s[:] = h0[1]
        cr_s[:] = c0[1]

    f32 = jnp.float32

    def advance(xt, h_s, c_s, d):
        h = h_s[:].astype(whh.dtype)
        pre = [
            jnp.dot(xt, wih[d, k], preferred_element_type=f32)
            + jnp.dot(h, whh[d, k], preferred_element_type=f32)
            + b[d, k].astype(f32)
            for k in range(4)
        ]
        i = jax.nn.sigmoid(pre[0])
        f = jax.nn.sigmoid(pre[1])
        o = jax.nn.sigmoid(pre[2])
        g = jnp.tanh(pre[3])
        c = f * c_s[:] + i * g
        h = o * jnp.tanh(c)
        h_s[:] = h
        c_s[:] = c
        return h, c, i, f, o, g

    h, c, i, f, o, g = advance(xf[0], hf_s, cf_s, 0)
    hsf[0] = h.astype(hsf.dtype)
    csf[0] = c.astype(csf.dtype)
    aif[0] = i.astype(aif.dtype)
    aff[0] = f.astype(aff.dtype)
    aof[0] = o.astype(aof.dtype)
    agf[0] = g.astype(agf.dtype)

    h, c, i, f, o, g = advance(xr[0], hr_s, cr_s, 1)
    hsr[0] = h.astype(hsr.dtype)
    csr[0] = c.astype(csr.dtype)
    air[0] = i.astype(air.dtype)
    afr[0] = f.astype(afr.dtype)
    aor[0] = o.astype(aor.dtype)
    agr[0] = g.astype(agr.dtype)

    # terminal carries at full f32 (same contract as the single-direction
    # kernel: straight from VMEM scratch, never the possibly-bf16 streams)
    @pl.when(t == pl.num_programs(1) - 1)
    def _():
        hT[0] = hf_s[:]
        cT[0] = cf_s[:]
        hT[1] = hr_s[:]
        cT[1] = cr_s[:]


def _fwd_bidir_call(x, wih2, b2, whh2, h02, c02, compute_dtype=None):
    T, B, D = x.shape
    H = wih2.shape[-1]
    bt = min(B_TILE, B)
    assert B % bt == 0, (
        f"batch {B} must be a multiple of the kernel tile {bt}; "
        "use bilstm_forward_fused(), which pads"
    )
    if compute_dtype is not None:
        x = x.astype(compute_dtype)
        wih2 = wih2.astype(compute_dtype)
        whh2 = whh2.astype(compute_dtype)
    grid = (B // bt, T)
    spec_xf = pl.BlockSpec((1, bt, D), lambda b, t: (t, b, 0), memory_space=pltpu.VMEM)
    spec_xr = pl.BlockSpec(
        (1, bt, D), lambda b, t: (T - 1 - t, b, 0), memory_space=pltpu.VMEM
    )
    spec_t = pl.BlockSpec((1, bt, H), lambda b, t: (t, b, 0), memory_space=pltpu.VMEM)
    # rev streams land at the SAME time index their x block came from
    # (x-time convention; see the section comment)
    spec_tr = pl.BlockSpec(
        (1, bt, H), lambda b, t: (T - 1 - t, b, 0), memory_space=pltpu.VMEM
    )
    spec_b2 = pl.BlockSpec((2, bt, H), lambda b, t: (0, b, 0), memory_space=pltpu.VMEM)
    spec_wih = pl.BlockSpec(
        (2, 4, D, H), lambda b, t: (0, 0, 0, 0), memory_space=pltpu.VMEM
    )
    spec_whh = pl.BlockSpec(
        (2, 4, H, H), lambda b, t: (0, 0, 0, 0), memory_space=pltpu.VMEM
    )
    spec_bias = pl.BlockSpec((2, 4, H), lambda b, t: (0, 0, 0), memory_space=pltpu.VMEM)
    stream = jnp.dtype(compute_dtype) if compute_dtype is not None else jnp.float32
    t_shape = jax.ShapeDtypeStruct((T, B, H), stream)
    carry_shape = jax.ShapeDtypeStruct((2, B, H), jnp.float32)
    return pl.pallas_call(
        _fwd_bidir_kernel,
        grid=grid,
        in_specs=[spec_xf, spec_xr, spec_wih, spec_bias, spec_whh, spec_b2, spec_b2],
        out_specs=[spec_t] * 6 + [spec_tr] * 6 + [spec_b2] * 2,
        out_shape=[t_shape] * 12 + [carry_shape] * 2,
        scratch_shapes=[pltpu.VMEM((bt, H), jnp.float32)] * 4,
        interpret=_interpret(),
    )(x, x, wih2, b2, whh2, h02, c02)


def _bwd_bidir_kernel(
    T_total,
    aif, aff, aof, agf, air, afr, aor, agr,
    csf, csf_prev, csr, csr_prev, wT, c0, dhsf, dhsr, dhT, dcT,
    dxf_i, dxf_f, dxf_o, dxf_g, dxr_i, dxr_f, dxr_o, dxr_g, dh0, dc0,
    dhf_s, dcf_s, dhr_s, dcr_s,
):
    t = pl.program_id(1)  # both directions walk their own time backwards
    first_time = t == 0
    last_time = t == T_total - 1

    @pl.when(first_time)
    def _():
        dhf_s[:] = dhT[0].astype(jnp.float32)
        dcf_s[:] = dcT[0].astype(jnp.float32)
        dhr_s[:] = dhT[1].astype(jnp.float32)
        dcr_s[:] = dcT[1].astype(jnp.float32)

    f32 = jnp.float32
    cdt = wT.dtype

    def drain(acts, c, c_prev, dhs_blk, dh_s, dc_s, d, outs):
        i, f, o, g = (a[0].astype(f32) for a in acts)
        c = c[0].astype(f32)
        c_prev = jnp.where(last_time, c0[d].astype(f32), c_prev[0].astype(f32))
        tanh_c = jnp.tanh(c)
        dh = dhs_blk[0].astype(f32) + dh_s[:]
        do = dh * tanh_c
        dc = dh * o * (1.0 - tanh_c * tanh_c) + dc_s[:]
        di = dc * g
        df = dc * c_prev
        dg = dc * i
        dpi = di * i * (1.0 - i)
        dpf = df * f * (1.0 - f)
        dpo = do * o * (1.0 - o)
        dpg = dg * (1.0 - g * g)
        outs[0][0] = dpi.astype(outs[0].dtype)
        outs[1][0] = dpf.astype(outs[1].dtype)
        outs[2][0] = dpo.astype(outs[2].dtype)
        outs[3][0] = dpg.astype(outs[3].dtype)
        dh_s[:] = (
            jnp.dot(dpi.astype(cdt), wT[d, 0], preferred_element_type=f32)
            + jnp.dot(dpf.astype(cdt), wT[d, 1], preferred_element_type=f32)
            + jnp.dot(dpo.astype(cdt), wT[d, 2], preferred_element_type=f32)
            + jnp.dot(dpg.astype(cdt), wT[d, 3], preferred_element_type=f32)
        )
        dc_s[:] = dc * f

    drain((aif, aff, aof, agf), csf, csf_prev, dhsf, dhf_s, dcf_s, 0,
          (dxf_i, dxf_f, dxf_o, dxf_g))
    drain((air, afr, aor, agr), csr, csr_prev, dhsr, dhr_s, dcr_s, 1,
          (dxr_i, dxr_f, dxr_o, dxr_g))

    @pl.when(last_time)
    def _():
        dh0[0] = dhf_s[:].astype(dh0.dtype)
        dc0[0] = dcf_s[:].astype(dc0.dtype)
        dh0[1] = dhr_s[:].astype(dh0.dtype)
        dc0[1] = dcr_s[:].astype(dc0.dtype)


def _bwd_bidir_call(actsf, actsr, csf, csr, whh2, c02, dhsf, dhsr, dhT2, dcT2,
                    compute_dtype=None):
    """``dhsf``/``dhsr`` may be full ``[T, B, H]`` cotangent streams or
    ``[1, B, H]`` per-row constants (the mean-pool backward: every step gets
    the same ``dpool/T`` block through a constant index map — no broadcast
    materialization, no stream traffic)."""
    T, B, H = csf.shape
    bt = min(B_TILE, B)
    assert B % bt == 0, f"batch {B} must be a multiple of the kernel tile {bt}"
    if compute_dtype is not None:
        whh2 = whh2.astype(compute_dtype)
    w2T = jnp.swapaxes(whh2, 2, 3)  # transpose ONCE in XLA, VMEM-resident
    grid = (B // bt, T)

    # fwd streams walk time descending; rev streams are stored in x-time
    # convention, so the rev chain (its own time also descending) walks
    # x-time ASCENDING — identity maps. rev's c_prev (one step earlier in
    # its own time) sits one x-time block LATER.
    rev = lambda b, t: (T - 1 - t, b, 0)
    fwd = lambda b, t: (t, b, 0)
    spec_rev = pl.BlockSpec((1, bt, H), rev, memory_space=pltpu.VMEM)
    spec_fwd = pl.BlockSpec((1, bt, H), fwd, memory_space=pltpu.VMEM)
    spec_prev_f = pl.BlockSpec(
        (1, bt, H), lambda b, t: (jnp.maximum(T - 2 - t, 0), b, 0),
        memory_space=pltpu.VMEM,
    )
    spec_prev_r = pl.BlockSpec(
        (1, bt, H), lambda b, t: (jnp.minimum(t + 1, T - 1), b, 0),
        memory_space=pltpu.VMEM,
    )
    spec_b2 = pl.BlockSpec((2, bt, H), lambda b, t: (0, b, 0), memory_space=pltpu.VMEM)
    spec_w = pl.BlockSpec(
        (2, 4, H, H), lambda b, t: (0, 0, 0, 0), memory_space=pltpu.VMEM
    )
    t_shape = jax.ShapeDtypeStruct((T, B, H), actsf[0].dtype)
    b2_shape = jax.ShapeDtypeStruct((2, B, H), jnp.float32)
    spec_const = pl.BlockSpec(
        (1, bt, H), lambda b, t: (0, b, 0), memory_space=pltpu.VMEM
    )
    spec_dhf = spec_const if dhsf.shape[0] == 1 else spec_rev
    spec_dhr = spec_const if dhsr.shape[0] == 1 else spec_fwd

    return pl.pallas_call(
        functools.partial(_bwd_bidir_kernel, T),
        grid=grid,
        in_specs=[spec_rev] * 4 + [spec_fwd] * 4
        + [spec_rev, spec_prev_f, spec_fwd, spec_prev_r, spec_w, spec_b2,
           spec_dhf, spec_dhr, spec_b2, spec_b2],
        out_specs=[spec_rev] * 4 + [spec_fwd] * 4 + [spec_b2, spec_b2],
        out_shape=[t_shape] * 8 + [b2_shape, b2_shape],
        scratch_shapes=[pltpu.VMEM((bt, H), jnp.float32)] * 4,
        interpret=_interpret(),
    )(*actsf, *actsr, csf, csf, csr, csr, w2T, c02, dhsf, dhsr, dhT2, dcT2)


@functools.lru_cache(maxsize=None)
def _fwd_bidir_callable(cdt_name: str | None):
    cdt = jnp.dtype(cdt_name) if cdt_name else None

    @custom_vmap
    def f(x, wih2, b2, whh2, h02, c02):
        return tuple(_fwd_bidir_call(x, wih2, b2, whh2, h02, c02, cdt))

    @f.def_vmap
    def _rule(axis_size, in_batched, *args):
        if any(in_batched[k] for k in (1, 2, 3)):  # per-element weights
            batched = _broadcast_unbatched(args, in_batched, axis_size)
            outs = jax.lax.map(lambda a: f(*a), tuple(batched))
            return tuple(outs), (True,) * 14
        S = axis_size
        batched = _broadcast_unbatched(
            args, [b or i in (1, 2, 3) for i, b in enumerate(in_batched)], S
        )
        x = _fold_rows(batched[0])  # [S, T, B, D] → [T, S*B, D]
        B = batched[4].shape[2]  # [S, 2, B, H]
        h02 = jnp.moveaxis(batched[4], 0, 1).reshape(2, S * B, -1)
        c02 = jnp.moveaxis(batched[5], 0, 1).reshape(2, S * B, -1)
        (x,), _ = _pad_rows([x], S * B, axis=-2)
        (h02, c02), _ = _pad_rows([h02, c02], S * B, axis=-2)
        outs = f(x, args[1], args[2], args[3], h02, c02)
        t_outs = [_unfold_rows(o[:, : S * B], S, B) for o in outs[:12]]
        b_outs = [
            jnp.moveaxis(o[:, : S * B].reshape(2, S, B, -1), 1, 0)
            for o in outs[12:]
        ]
        return tuple(t_outs + b_outs), (True,) * 14

    return f


@functools.lru_cache(maxsize=None)
def _bwd_bidir_callable(cdt_name: str | None):
    cdt = jnp.dtype(cdt_name) if cdt_name else None

    @custom_vmap
    def f(aif, aff, aof, agf, air, afr, aor, agr, csf, csr, whh2, c02,
          dhsf, dhsr, dhT2, dcT2):
        return tuple(_bwd_bidir_call(
            (aif, aff, aof, agf), (air, afr, aor, agr), csf, csr, whh2, c02,
            dhsf, dhsr, dhT2, dcT2, cdt,
        ))

    @f.def_vmap
    def _rule(axis_size, in_batched, *args):
        if in_batched[10]:  # per-element weights
            batched = _broadcast_unbatched(args, in_batched, axis_size)
            outs = jax.lax.map(lambda a: f(*a), tuple(batched))
            return tuple(outs), (True,) * 10
        S = axis_size
        batched = _broadcast_unbatched(
            args, [b or i == 10 for i, b in enumerate(in_batched)], S
        )
        t_arrs = [_fold_rows(batched[i]) for i in (0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 12, 13)]
        B = batched[11].shape[2]  # [S, 2, B, H]
        b_arrs = [
            jnp.moveaxis(batched[i], 0, 1).reshape(2, S * B, -1)
            for i in (11, 14, 15)
        ]
        rows = S * B
        t_arrs, _ = _pad_rows(t_arrs, rows, axis=-2)
        b_arrs, _ = _pad_rows(b_arrs, rows, axis=-2)
        outs = f(*t_arrs[:10], args[10], b_arrs[0], t_arrs[10], t_arrs[11],
                 b_arrs[1], b_arrs[2])
        dxi = [_unfold_rows(o[:, :rows], S, B) for o in outs[:8]]
        db = [
            jnp.moveaxis(o[:, :rows].reshape(2, S, B, -1), 1, 0)
            for o in outs[8:]
        ]
        return tuple(dxi + db), (True,) * 10

    return f


@functools.partial(jax.custom_vjp, nondiff_argnums=(6,))
def bilstm_recurrence_fused(x, wih2, b2, whh2, h02, c02, compute_dtype=None):
    """Fused BIDIRECTIONAL LSTM: both directions in ONE kernel sweep.

    Args:
      x: ``[T, B, D]`` raw per-step inputs. The reverse direction reads x
        through a time-flipped index map — callers never materialize a
        flipped copy (the reference flips in torch, ``models.py:60-65``).
      wih2: ``[2, 4, D, H]`` per-direction input projections (fwd, rev).
      b2: ``[2, 4, H]`` combined biases; whh2: ``[2, 4, H, H]``.
      h02, c02: ``[2, B, H]`` initial carries.

    Returns ``(hs_f [T, B, H], hs_r [T, B, H], (hT2, cT2) [2, B, H] f32)``.
    ``hs_r`` is in X-TIME convention: ``hs_r[t]`` is the rev state computed
    while consuming ``x[t]`` (i.e. after seeing ``x[T-1..t]``) — the
    cuDNN-style bidirectional alignment, equal to ``flip(rev_cell(flip(x)))``.
    Time-order-invariant consumers (the model's mean-pool) use it directly;
    a caller needing the reference's no-flip-back concat order must flip.
    This convention is what lets the VJP run entirely flip-free (see the
    section comment above).
    """
    outs = _fwd_bidir_callable(_cdt_name(compute_dtype))(
        x, wih2, b2, whh2, h02, c02
    )
    hsf, hsr, hT2, cT2 = outs[0], outs[6], outs[12], outs[13]
    return hsf, hsr, (hT2, cT2)


def _vjp_bidir_fwd(x, wih2, b2, whh2, h02, c02, compute_dtype):
    outs = _fwd_bidir_callable(_cdt_name(compute_dtype))(
        x, wih2, b2, whh2, h02, c02
    )
    (hsf, csf, aif, aff, aof, agf,
     hsr, csr, air, afr, aor, agr, hT2, cT2) = outs
    res = (x, wih2, b2, whh2, h02, c02, hsf, csf, (aif, aff, aof, agf),
           hsr, csr, (air, afr, aor, agr))
    return (hsf, hsr, (hT2, cT2)), res


def _bidir_weight_grads(cdt_name, x, wih2, b2, whh2, h02, hsf, hsr, outs):
    """The XLA-side einsums shared by both bidir VJPs: turn the backward
    kernel's pre-activation cotangents into (dx, dwih2, db2, dwhh2, dh02,
    dc02). All inputs are in folded/x-time layout."""
    dpf = outs[0:4]
    dpr = outs[4:8]
    dh02, dc02 = outs[8], outs[9]
    cdt = jnp.dtype(cdt_name) if cdt_name else x.dtype
    H = dpf[0].shape[-1]

    # Same concat-on-feature-axis trick as the single-direction VJP (see
    # _vjp_fused_bwd), doubled: BOTH directions' cotangents are already in
    # x-time convention (the kernels' flipped index maps paid for this), so
    # they concat into ONE [T, B, 8H] array and dx / dW_ih are single
    # 1392-wide MXU matmuls — no jnp.flip of any time array.
    dpc = jnp.concatenate([*dpf, *dpr], axis=-1).astype(cdt)

    def cat_w(w4):  # [4, D, H] → [D, 4H]
        return jnp.swapaxes(w4, 0, 1).reshape(w4.shape[1], -1)

    w_cat8 = jnp.concatenate(
        [cat_w(wih2[0]), cat_w(wih2[1])], axis=-1
    ).astype(cdt)  # [D, 8H]
    xc = x.astype(cdt)
    dx = jnp.einsum(
        "tbg,dg->tbd", dpc, w_cat8, preferred_element_type=jnp.float32,
    ).astype(x.dtype)
    dwih_cat = jnp.einsum(
        "tbd,tbg->dg", xc, dpc, preferred_element_type=jnp.float32,
    )  # [D, 8H]
    dwih2 = jnp.stack([
        dwih_cat[:, : 4 * H].reshape(-1, 4, H).swapaxes(0, 1),
        dwih_cat[:, 4 * H:].reshape(-1, 4, H).swapaxes(0, 1),
    ]).astype(wih2.dtype)
    db_cat = dpc.astype(jnp.float32).sum(axis=(0, 1))
    db2 = jnp.stack([
        db_cat[: 4 * H].reshape(4, H), db_cat[4 * H:].reshape(4, H),
    ]).astype(b2.dtype)

    # h_prev in x-time convention: fwd is the usual shift-right with h0 in
    # front; rev state one step earlier in ITS time sits one x-time step
    # LATER (hs_r[t+1]), with h0 at the tail.
    h_prevf = jnp.concatenate([h02[0][None].astype(hsf.dtype), hsf[:-1]], 0)
    h_prevr = jnp.concatenate([hsr[1:], h02[1][None].astype(hsr.dtype)], 0)
    dpcf, dpcr = dpc[..., : 4 * H], dpc[..., 4 * H:]

    def dwhh_of(h_prev, dpc_dir):
        return jnp.einsum(
            "tbh,tbg->hg", h_prev.astype(cdt), dpc_dir,
            preferred_element_type=jnp.float32,
        ).reshape(H, 4, H).swapaxes(0, 1)

    dwhh2 = jnp.stack([
        dwhh_of(h_prevf, dpcf), dwhh_of(h_prevr, dpcr),
    ]).astype(whh2.dtype)
    return dx, dwih2, db2, dwhh2, dh02, dc02


def _vjp_bidir_bwd(compute_dtype, res, grads):
    (x, wih2, b2, whh2, h02, c02, hsf, csf, actsf, hsr, csr, actsr) = res
    dhsf, dhsr, (dhT2, dcT2) = grads
    cdt_name = _cdt_name(compute_dtype)
    outs = _bwd_bidir_callable(cdt_name)(
        *actsf, *actsr, csf, csr, whh2, c02, dhsf, dhsr, dhT2, dcT2
    )
    return _bidir_weight_grads(cdt_name, x, wih2, b2, whh2, h02, hsf, hsr, outs)


bilstm_recurrence_fused.defvjp(_vjp_bidir_fwd, _vjp_bidir_bwd)


# ---------------------------------------------------------------------------
# pooled bidirectional op — ICALstm's opt-in fused path (mean-pool of the
# hidden sequence, reference ``models.py:109``). NOTE (r5): the flagship A/B
# measured this fused path 27% SLOWER than two single-direction sweeps
# (80,531 vs 110,009 samples/sec/chip, docs/bench_ab_bidir_r5.jsonl), so the
# per-direction path is the model default and this op is reached only via
# ``ICALstm(fused_bidir=True)``. Two structural ideas on top of the
# bidirectional kernels above (kept for the record and for shapes where the
# trade may flip):
#
# 1. The mean-pool lives INSIDE the op: the forward kernel accumulates the
#    time-sum in VMEM scratch and emits [B, H] per direction (the hidden
#    sequences are still written — they are BPTT residuals — but nothing
#    re-reads them to pool), and the backward kernel consumes the pool
#    cotangent as a per-row CONSTANT block (``dpool/T`` through a constant
#    index map) instead of a broadcast [T, B, H] stream.
# 2. Residual layout is SITE-NATIVE under the trainer's vmap. The plain ops
#    above fold the vmapped site axis into kernel rows with moveaxis+reshape
#    copies — and because vmap applies that rule per op, every ~17 MB
#    residual stream was unfolded after the forward and refolded before the
#    backward (~400 MB of relayout copies per flagship training step; this,
#    not kernel time, dominated the round-3 epoch profile). Here the
#    custom_vmap rules dispatch to 4D kernels over ``[S, T, B, ·]`` arrays
#    whose BLOCKS gather ``s_tile × B`` rows per (site-tile, time) grid
#    step — every residual is WRITTEN by the forward kernel and READ by the
#    backward kernel in that one layout; only x/dx pay one transpose each
#    ([S, B, T, D] ↔ [S, T, B, D]). Mosaic constrains the last two block
#    dims to (8·, 128·) or the full array dims, which (B, H) satisfies —
#    this is why the site axis tiles the FIRST block dim, time sits second,
#    and rows are (s_tile · B). (A packed [.., 4, H] gate layout was tried
#    and rejected: Mosaic cannot shape-cast stores that insert singleton
#    dims mid-vector; the separate-array gate streams keep every store a
#    plain leading-dim split, and the VJP's feature-axis concat is cheap.)
#
# Logical layouts (what the custom_vjp-level code sees): x [B, T, D] in,
# residual streams [T, Bp, H] (Bp = row-padded batch; under vmap these
# batch to [S, T, B, H] with NO row padding — site padding is handled
# privately inside each rule), carries [2, B, H]. The dW/dx einsums are
# _bidir_weight_grads, shared with the sequence-returning op.
# ---------------------------------------------------------------------------


def _pool_s_tile(S: int, B: int) -> int:
    """Sites per kernel block: fill ~B_TILE rows (padding covers any
    non-dividing remainder of S)."""
    return max(1, min(S, B_TILE // max(B, 1) or 1))


def _fwd_pool_kernel4(
    xf, xr, wih, b, whh, h0, c0,
    hsf, csf, aif, aff, aof, agf, hsr, csr, air, afr, aor, agr,
    hT, cT, poolf, poolr,
    hf_s, cf_s, hr_s, cr_s, pf_s, pr_s,
):
    t = pl.program_id(1)
    st, _, B, H = hsf.shape
    rows = st * B
    f32 = jnp.float32

    @pl.when(t == 0)
    def _():
        hf_s[:] = h0[0].reshape(rows, H)
        cf_s[:] = c0[0].reshape(rows, H)
        hr_s[:] = h0[1].reshape(rows, H)
        cr_s[:] = c0[1].reshape(rows, H)
        pf_s[:] = jnp.zeros_like(pf_s)
        pr_s[:] = jnp.zeros_like(pr_s)

    def advance(xblk, h_s, c_s, p_s, d):
        xt = xblk[:, 0].reshape(rows, xblk.shape[-1])
        h = h_s[:].astype(whh.dtype)
        pre = [
            jnp.dot(xt, wih[d, k], preferred_element_type=f32)
            + jnp.dot(h, whh[d, k], preferred_element_type=f32)
            + b[d, k].astype(f32)
            for k in range(4)
        ]
        i = jax.nn.sigmoid(pre[0])
        f = jax.nn.sigmoid(pre[1])
        o = jax.nn.sigmoid(pre[2])
        g = jnp.tanh(pre[3])
        c = f * c_s[:] + i * g
        h = o * jnp.tanh(c)
        h_s[:] = h
        c_s[:] = c
        p_s[:] = p_s[:] + h
        return h, c, i, f, o, g

    def put(ref, v):
        ref[:, 0] = v.reshape(st, B, H).astype(ref.dtype)

    h, c, i, f, o, g = advance(xf, hf_s, cf_s, pf_s, 0)
    put(hsf, h), put(csf, c), put(aif, i), put(aff, f), put(aof, o), put(agf, g)
    h, c, i, f, o, g = advance(xr, hr_s, cr_s, pr_s, 1)
    put(hsr, h), put(csr, c), put(air, i), put(afr, f), put(aor, o), put(agr, g)

    @pl.when(t == pl.num_programs(1) - 1)
    def _():
        inv_T = 1.0 / pl.num_programs(1)
        hT[0] = hf_s[:].reshape(st, B, H)
        cT[0] = cf_s[:].reshape(st, B, H)
        hT[1] = hr_s[:].reshape(st, B, H)
        cT[1] = cr_s[:].reshape(st, B, H)
        poolf[:] = (pf_s[:] * inv_T).reshape(st, B, H)
        poolr[:] = (pr_s[:] * inv_T).reshape(st, B, H)


def _fwd_pool_call4(x, wih2, b2, whh2, h02, c02, compute_dtype=None):
    # x [S, T, B, D]; h02/c02 [2, S, B, H] — S pre-padded to an s_tile multiple
    S, T, B, D = x.shape
    H = wih2.shape[-1]
    st = _pool_s_tile(S, B)
    assert S % st == 0
    rows = st * B
    if compute_dtype is not None:
        x = x.astype(compute_dtype)
        wih2 = wih2.astype(compute_dtype)
        whh2 = whh2.astype(compute_dtype)
    grid = (S // st, T)
    V = pltpu.VMEM
    spec_xf = pl.BlockSpec((st, 1, B, D), lambda r, t: (r, t, 0, 0), memory_space=V)
    spec_xr = pl.BlockSpec(
        (st, 1, B, D), lambda r, t: (r, T - 1 - t, 0, 0), memory_space=V
    )
    spec_tf = pl.BlockSpec((st, 1, B, H), lambda r, t: (r, t, 0, 0), memory_space=V)
    spec_tr = pl.BlockSpec(
        (st, 1, B, H), lambda r, t: (r, T - 1 - t, 0, 0), memory_space=V
    )
    spec_c2 = pl.BlockSpec((2, st, B, H), lambda r, t: (0, r, 0, 0), memory_space=V)
    spec_p = pl.BlockSpec((st, B, H), lambda r, t: (r, 0, 0), memory_space=V)
    spec_wih = pl.BlockSpec(
        (2, 4, D, H), lambda r, t: (0, 0, 0, 0), memory_space=V
    )
    spec_whh = pl.BlockSpec(
        (2, 4, H, H), lambda r, t: (0, 0, 0, 0), memory_space=V
    )
    spec_bias = pl.BlockSpec((2, 4, H), lambda r, t: (0, 0, 0), memory_space=V)
    stream = jnp.dtype(compute_dtype) if compute_dtype is not None else jnp.float32
    t_shape = jax.ShapeDtypeStruct((S, T, B, H), stream)
    c2_shape = jax.ShapeDtypeStruct((2, S, B, H), jnp.float32)
    p_shape = jax.ShapeDtypeStruct((S, B, H), jnp.float32)
    return pl.pallas_call(
        _fwd_pool_kernel4,
        grid=grid,
        in_specs=[spec_xf, spec_xr, spec_wih, spec_bias, spec_whh,
                  spec_c2, spec_c2],
        out_specs=[spec_tf] * 6 + [spec_tr] * 6
        + [spec_c2, spec_c2, spec_p, spec_p],
        out_shape=[t_shape] * 12 + [c2_shape, c2_shape, p_shape, p_shape],
        scratch_shapes=[pltpu.VMEM((rows, H), jnp.float32)] * 6,
        interpret=_interpret(),
    )(x, x, wih2, b2, whh2, h02, c02)


def _bwd_pool_kernel4(
    T_total,
    aif, aff, aof, agf, air, afr, aor, agr,
    csf, csf_prev, csr, csr_prev, wT, c0, dpoolf, dpoolr, dhT, dcT,
    dxf_i, dxf_f, dxf_o, dxf_g, dxr_i, dxr_f, dxr_o, dxr_g, dh0, dc0,
    dhf_s, dcf_s, dhr_s, dcr_s,
):
    t = pl.program_id(1)
    st, _, B, H = dxf_i.shape
    rows = st * B
    first_time = t == 0
    last_time = t == T_total - 1
    f32 = jnp.float32
    cdt = wT.dtype

    @pl.when(first_time)
    def _():
        dhf_s[:] = dhT[0].reshape(rows, H).astype(f32)
        dcf_s[:] = dcT[0].reshape(rows, H).astype(f32)
        dhr_s[:] = dhT[1].reshape(rows, H).astype(f32)
        dcr_s[:] = dcT[1].reshape(rows, H).astype(f32)

    def drain(acts, c_blk, c_prev_blk, dpool, dh_s, dc_s, d, outs):
        i, f, o, g = (a[:, 0].reshape(rows, H).astype(f32) for a in acts)
        c = c_blk[:, 0].reshape(rows, H).astype(f32)
        c_prev = jnp.where(
            last_time,
            c0[d].reshape(rows, H).astype(f32),
            c_prev_blk[:, 0].reshape(rows, H).astype(f32),
        )
        tanh_c = jnp.tanh(c)
        dh = dpool[:].reshape(rows, H) + dh_s[:]
        do = dh * tanh_c
        dc = dh * o * (1.0 - tanh_c * tanh_c) + dc_s[:]
        di = dc * g
        df = dc * c_prev
        dg = dc * i
        dpi = di * i * (1.0 - i)
        dpf = df * f * (1.0 - f)
        dpo = do * o * (1.0 - o)
        dpg = dg * (1.0 - g * g)
        for ref, v in zip(outs, (dpi, dpf, dpo, dpg)):
            ref[:, 0] = v.reshape(st, B, H).astype(ref.dtype)
        dh_s[:] = (
            jnp.dot(dpi.astype(cdt), wT[d, 0], preferred_element_type=f32)
            + jnp.dot(dpf.astype(cdt), wT[d, 1], preferred_element_type=f32)
            + jnp.dot(dpo.astype(cdt), wT[d, 2], preferred_element_type=f32)
            + jnp.dot(dpg.astype(cdt), wT[d, 3], preferred_element_type=f32)
        )
        dc_s[:] = dc * f

    drain((aif, aff, aof, agf), csf, csf_prev, dpoolf, dhf_s, dcf_s, 0,
          (dxf_i, dxf_f, dxf_o, dxf_g))
    drain((air, afr, aor, agr), csr, csr_prev, dpoolr, dhr_s, dcr_s, 1,
          (dxr_i, dxr_f, dxr_o, dxr_g))

    @pl.when(last_time)
    def _():
        dh0[0] = dhf_s[:].reshape(st, B, H)
        dc0[0] = dcf_s[:].reshape(st, B, H)
        dh0[1] = dhr_s[:].reshape(st, B, H)
        dc0[1] = dcr_s[:].reshape(st, B, H)


def _bwd_pool_call4(actsf, actsr, csf, csr, whh2, c02, dpoolf, dpoolr,
                    dhT2, dcT2, compute_dtype=None):
    # all [S, T, B, H] site-native; dpool* [S, B, H] f32 (pre-divided by T)
    S, T, B, H = csf.shape
    st = _pool_s_tile(S, B)
    assert S % st == 0
    rows = st * B
    if compute_dtype is not None:
        whh2 = whh2.astype(compute_dtype)
    w2T = jnp.swapaxes(whh2, 2, 3)
    grid = (S // st, T)
    V = pltpu.VMEM
    # fwd-direction streams walk their time DESCENDING (block T-1-t); rev
    # streams are x-time stored, so the rev chain walks blocks ASCENDING
    spec_f = pl.BlockSpec(
        (st, 1, B, H), lambda r, t: (r, T - 1 - t, 0, 0), memory_space=V
    )
    spec_r = pl.BlockSpec((st, 1, B, H), lambda r, t: (r, t, 0, 0), memory_space=V)
    spec_f_prev = pl.BlockSpec(
        (st, 1, B, H), lambda r, t: (r, jnp.maximum(T - 2 - t, 0), 0, 0),
        memory_space=V,
    )
    spec_r_prev = pl.BlockSpec(
        (st, 1, B, H), lambda r, t: (r, jnp.minimum(t + 1, T - 1), 0, 0),
        memory_space=V,
    )
    spec_c2 = pl.BlockSpec((2, st, B, H), lambda r, t: (0, r, 0, 0), memory_space=V)
    spec_p = pl.BlockSpec((st, B, H), lambda r, t: (r, 0, 0), memory_space=V)
    spec_w = pl.BlockSpec(
        (2, 4, H, H), lambda r, t: (0, 0, 0, 0), memory_space=V
    )
    t_shape = jax.ShapeDtypeStruct((S, T, B, H), actsf[0].dtype)
    c2_shape = jax.ShapeDtypeStruct((2, S, B, H), jnp.float32)
    return pl.pallas_call(
        functools.partial(_bwd_pool_kernel4, T),
        grid=grid,
        in_specs=[spec_f] * 4 + [spec_r] * 4
        + [spec_f, spec_f_prev, spec_r, spec_r_prev, spec_w, spec_c2,
           spec_p, spec_p, spec_c2, spec_c2],
        out_specs=[spec_f] * 4 + [spec_r] * 4 + [spec_c2, spec_c2],
        out_shape=[t_shape] * 8 + [c2_shape, c2_shape],
        scratch_shapes=[pltpu.VMEM((rows, H), jnp.float32)] * 4,
        interpret=_interpret(),
    )(*actsf, *actsr, csf, csf, csr, csr, w2T, c02, dpoolf, dpoolr,
      dhT2, dcT2)


def _pad_sites(arrs, S, st, axis=0):
    pad = (-S) % st
    if pad == 0:
        return arrs
    out = []
    for a in arrs:
        widths = [(0, 0)] * a.ndim
        widths[axis] = (0, pad)
        out.append(jnp.pad(a, widths))
    return out


@functools.lru_cache(maxsize=None)
def _pool_fwd_kcall(cdt_name: str | None):
    """custom_vmap forward. Unbatched → the 3D kernels above (row padding,
    one x transpose — the single-site debug path); vmapped → site-native 4D
    kernels (one x transpose, zero residual copies)."""
    cdt = jnp.dtype(cdt_name) if cdt_name else None

    @custom_vmap
    def f(x, wih2, b2, whh2, h02, c02):
        B, T, D = x.shape
        H = wih2.shape[-1]
        bt = min(B_TILE, B)
        pad = (-B) % bt
        xp = x.astype(cdt if cdt is not None else jnp.float32)
        h02p, c02p = h02.astype(jnp.float32), c02.astype(jnp.float32)
        if pad:
            xp = jnp.concatenate([xp, jnp.zeros((pad, T, D), xp.dtype)], 0)
            zb = jnp.zeros((2, pad, H), jnp.float32)
            h02p = jnp.concatenate([h02p, zb], 1)
            c02p = jnp.concatenate([c02p, zb], 1)
        xT = jnp.swapaxes(xp, 0, 1)  # [T, Bp, D]
        outs = _fwd_bidir_call(xT, wih2, b2, whh2, h02p, c02p, cdt)
        hsf, hsr, hT2, cT2 = outs[0], outs[6], outs[12], outs[13]
        poolf = hsf[:, :B].mean(axis=0, dtype=jnp.float32)
        poolr = hsr[:, :B].mean(axis=0, dtype=jnp.float32)
        return (poolf, poolr, hT2[:, :B], cT2[:, :B], xT) + tuple(outs[:12])

    @f.def_vmap
    def _rule(axis_size, in_batched, *args):
        if any(in_batched[k] for k in (1, 2, 3)):  # per-element weights
            batched = _broadcast_unbatched(args, in_batched, axis_size)
            outs = jax.lax.map(lambda a: f(*a), tuple(batched))
            return tuple(outs), (True,) * 17
        S = axis_size
        batched = _broadcast_unbatched(
            args, [b or i in (1, 2, 3) for i, b in enumerate(in_batched)], S
        )
        B = batched[0].shape[1]
        st = _pool_s_tile(S, B)
        # THE one x relayout: [S, B, T, D] → [S, T, B, D] (XLA can often
        # fuse it into the producing matmul's epilogue)
        xT = jnp.swapaxes(batched[0], 1, 2)
        xT = xT.astype(cdt if cdt is not None else jnp.float32)
        h02 = jnp.moveaxis(batched[4], 0, 1)  # [2, S, B, H] (small)
        c02 = jnp.moveaxis(batched[5], 0, 1)
        (xTp,) = _pad_sites([xT], S, st)
        h02, c02 = _pad_sites([h02, c02], S, st, axis=1)
        outs = _fwd_pool_call4(xTp, args[1], args[2], args[3], h02, c02, cdt)
        streams = [a[:S] for a in outs[:12]]
        hT2, cT2, poolf, poolr = outs[12], outs[13], outs[14], outs[15]
        mv = lambda a: jnp.moveaxis(a[:, :S], 0, 1)  # [2,S,·]→[S,2,·] (small)
        return (
            (poolf[:S], poolr[:S], mv(hT2), mv(cT2), xT) + tuple(streams),
            (True,) * 17,
        )

    return f


@functools.lru_cache(maxsize=None)
def _pool_bwd_kcall(cdt_name: str | None):
    """custom_vmap backward: kernel-only (row-wise outputs). dW einsums live
    OUTSIDE in the custom_vjp bwd (_bidir_weight_grads) — they batch
    per-site under vmap and JAX sums the cotangent for the shared
    (unbatched) weights."""
    cdt = jnp.dtype(cdt_name) if cdt_name else None

    @custom_vmap
    def f(aif, aff, aof, agf, air, afr, aor, agr, csf, csr, whh2, c02,
          dpoolf, dpoolr, dhT2, dcT2):
        Bp = csf.shape[1]
        B = dpoolf.shape[0]
        pad = Bp - B
        stream = csf.dtype

        def padb(a, axis=1):  # pad the row axis of [2, B, H] / [1, B, H]
            if not pad:
                return a
            widths = [(0, 0)] * a.ndim
            widths[axis] = (0, pad)
            return jnp.pad(a, widths)

        return _bwd_bidir_call(
            (aif, aff, aof, agf), (air, afr, aor, agr), csf, csr, whh2,
            padb(c02.astype(jnp.float32)),
            padb(dpoolf.astype(stream)[None]), padb(dpoolr.astype(stream)[None]),
            padb(dhT2.astype(jnp.float32)), padb(dcT2.astype(jnp.float32)),
            cdt,
        )

    @f.def_vmap
    def _rule(axis_size, in_batched, *args):
        if in_batched[10]:  # per-element weights
            batched = _broadcast_unbatched(args, in_batched, axis_size)
            outs = jax.lax.map(lambda a: f(*a), tuple(batched))
            return tuple(outs), (True,) * 10
        S = axis_size
        batched = _broadcast_unbatched(
            args, [b or i == 10 for i, b in enumerate(in_batched)], S
        )
        B = batched[8].shape[2]  # csf [S, T, B, H]
        st = _pool_s_tile(S, B)
        c02 = jnp.moveaxis(batched[11], 0, 1).astype(jnp.float32)  # [2,S,B,H]
        dhT2 = jnp.moveaxis(batched[14], 0, 1).astype(jnp.float32)
        dcT2 = jnp.moveaxis(batched[15], 0, 1).astype(jnp.float32)
        dpoolf = batched[12].astype(jnp.float32)
        dpoolr = batched[13].astype(jnp.float32)
        streams = _pad_sites(list(batched[:10]) + [dpoolf, dpoolr], S, st)
        c02, dhT2, dcT2 = _pad_sites([c02, dhT2, dcT2], S, st, axis=1)
        outs = _bwd_pool_call4(
            tuple(streams[0:4]), tuple(streams[4:8]), streams[8], streams[9],
            args[10], c02, streams[10], streams[11], dhT2, dcT2, cdt,
        )
        mv = lambda a: jnp.moveaxis(a[:, :S], 0, 1)
        return (
            tuple(a[:S] for a in outs[:8]) + (mv(outs[8]), mv(outs[9])),
            (True,) * 10,
        )

    return f


@functools.partial(jax.custom_vjp, nondiff_argnums=(6,))
def bilstm_pool_fused_op(x, wih2, b2, whh2, h02, c02, compute_dtype=None):
    """Fused bidirectional LSTM + time mean-pool (stacked-weight layout).

    x [B, T, D]; wih2 [2, 4, D, H]; b2 [2, 4, H]; whh2 [2, 4, H, H];
    h02/c02 [2, B, H]. Returns ``(pooled [B, 2H] f32, (hT2, cT2) f32)``
    where ``pooled = concat([hs_f.mean(time), hs_r.mean(time)], -1)``.
    See the section comment for the layout/batching design.
    """
    outs = _pool_fwd_kcall(_cdt_name(compute_dtype))(
        x, wih2, b2, whh2, h02, c02
    )
    poolf, poolr, hT2, cT2 = outs[:4]
    return jnp.concatenate([poolf, poolr], axis=-1), (hT2, cT2)


def _vjp_pool_fwd(x, wih2, b2, whh2, h02, c02, compute_dtype):
    outs = _pool_fwd_kcall(_cdt_name(compute_dtype))(
        x, wih2, b2, whh2, h02, c02
    )
    (poolf, poolr, hT2, cT2, xT,
     hsf, csf, aif, aff, aof, agf, hsr, csr, air, afr, aor, agr) = outs
    # xT (the transposed/padded input actually fed to the kernel) is the
    # residual — the dW einsums need x in stream layout, and saving the
    # transposed copy avoids a second transpose in the backward. x_wit is a
    # zero-size dtype witness so dx can be cast back to the primal dtype.
    x_wit = jnp.zeros((0,), x.dtype)
    res = (xT, x_wit, wih2, b2, whh2, h02, c02, hsf, csf,
           (aif, aff, aof, agf), hsr, csr, (air, afr, aor, agr))
    return (jnp.concatenate([poolf, poolr], axis=-1), (hT2, cT2)), res


def _vjp_pool_bwd(compute_dtype, res, grads):
    (xT, x_wit, wih2, b2, whh2, h02, c02,
     hsf, csf, actsf, hsr, csr, actsr) = res
    dpooled, (dhT2, dcT2) = grads
    B = dpooled.shape[0]
    T = xT.shape[0]
    H = hsf.shape[-1]
    cdt_name = _cdt_name(compute_dtype)
    dpoolf = dpooled[:, :H] / T
    dpoolr = dpooled[:, H:] / T
    outs = _pool_bwd_kcall(cdt_name)(
        *actsf, *actsr, csf, csr, whh2, c02, dpoolf, dpoolr, dhT2, dcT2
    )
    # row-pad h0 to the streams' padded width for the h_prev shift (no-op
    # under vmap, where rows are never padded)
    pad = hsf.shape[1] - h02.shape[1]
    h02p = jnp.pad(h02, ((0, 0), (0, pad), (0, 0))) if pad else h02
    dxT, dwih2, db2, dwhh2, dh02, dc02 = _bidir_weight_grads(
        cdt_name, xT, wih2, b2, whh2, h02p, hsf, hsr, outs
    )
    dx = jnp.swapaxes(dxT, 0, 1)[:B].astype(x_wit.dtype)
    # The kernel streams are row-padded to the batch tile; dx is sliced back
    # above, and the carry cotangents need the same trim (pad rows carry
    # exactly-zero gradient, so slicing is exact).
    dh02 = dh02[:, :B]
    dc02 = dc02[:, :B]
    return dx, dwih2, db2, dwhh2, dh02, dc02


bilstm_pool_fused_op.defvjp(_vjp_pool_fwd, _vjp_pool_bwd)


def bilstm_pool_forward_fused(x, params_fwd, params_rev, h02=None, c02=None,
                              compute_dtype=None):
    """Model-layout wrapper over :func:`bilstm_pool_fused_op`.

    Args:
      x: ``[B, T, D]`` raw inputs (shared by both directions).
      params_fwd / params_rev: ``(w_ih [D, 4H], b [4H], w_hh [H, 4H])`` in
        LSTMCell blocked layout (b = b_ih + b_hh).
      h02, c02: optional ``[2, B, H]`` initial carries (zeros by default).

    Returns ``(pooled [B, 2H] f32, (hT2, cT2) [2, B, H] f32)``.
    """
    B = x.shape[0]
    H = params_fwd[2].shape[0]

    def stack_dir(p):
        w_ih, b, w_hh = (a.astype(jnp.float32) for a in p)
        wih4 = jnp.stack([w_ih[:, k * H: (k + 1) * H] for k in range(4)])
        b4 = jnp.stack([b[k * H: (k + 1) * H] for k in range(4)])
        whh4 = jnp.stack([w_hh[:, k * H: (k + 1) * H] for k in range(4)])
        return wih4, b4, whh4

    wf, bf, whf = stack_dir(params_fwd)
    wr, br, whr = stack_dir(params_rev)
    if h02 is None:
        h02 = jnp.zeros((2, B, H), jnp.float32)
    if c02 is None:
        c02 = jnp.zeros((2, B, H), jnp.float32)
    return bilstm_pool_fused_op(
        x, jnp.stack([wf, wr]), jnp.stack([bf, br]), jnp.stack([whf, whr]),
        h02.astype(jnp.float32), c02.astype(jnp.float32), compute_dtype,
    )




def bilstm_forward_fused(x, params_fwd, params_rev, h02=None, c02=None,
                         compute_dtype=None):
    """Model-layout convenience wrapper over :func:`bilstm_recurrence_fused`.

    Args:
      x: ``[B, T, D]`` raw inputs (shared by both directions).
      params_fwd / params_rev: ``(w_ih [D, 4H], b [4H], w_hh [H, 4H])`` in
        LSTMCell blocked layout (b = b_ih + b_hh).
      h02, c02: optional ``[2, B, H]`` initial carries (zeros by default).

    Returns ``(hs_f [B, T, H], hs_r [B, T, H], (hT2, cT2) [2, B, H] f32)``
    with ``hs_r`` in x-time convention (see the op docstring). Pads the
    batch to the kernel tile.
    """
    B, T, D = x.shape
    H = params_fwd[2].shape[0]
    in_dtype = x.dtype
    x = x.astype(compute_dtype if compute_dtype is not None else jnp.float32)

    def stack_dir(p):
        w_ih, b, w_hh = (a.astype(jnp.float32) for a in p)
        wih4 = jnp.stack([w_ih[:, k * H: (k + 1) * H] for k in range(4)])
        b4 = jnp.stack([b[k * H: (k + 1) * H] for k in range(4)])
        whh4 = jnp.stack([w_hh[:, k * H: (k + 1) * H] for k in range(4)])
        return wih4, b4, whh4

    wf, bf, whf = stack_dir(params_fwd)
    wr, br, whr = stack_dir(params_rev)
    wih2 = jnp.stack([wf, wr])
    b2 = jnp.stack([bf, br])
    whh2 = jnp.stack([whf, whr])
    if h02 is None:
        h02 = jnp.zeros((2, B, H), jnp.float32)
    if c02 is None:
        c02 = jnp.zeros((2, B, H), jnp.float32)
    h02 = h02.astype(jnp.float32)
    c02 = c02.astype(jnp.float32)

    bt = min(B_TILE, B)
    pad = (-B) % bt
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad, T, D), x.dtype)], 0)
        zb = jnp.zeros((2, pad, H), jnp.float32)
        h02 = jnp.concatenate([h02, zb], 1)
        c02 = jnp.concatenate([c02, zb], 1)
    x_t = jnp.swapaxes(x, 0, 1)  # [T, B, D]
    hsf, hsr, (hT2, cT2) = bilstm_recurrence_fused(
        x_t, wih2, b2, whh2, h02, c02, compute_dtype
    )
    hsf = jnp.swapaxes(hsf, 0, 1)
    hsr = jnp.swapaxes(hsr, 0, 1)
    if pad:
        hsf, hsr = hsf[:B], hsr[:B]
        hT2, cT2 = hT2[:, :B], cT2[:, :B]
    return hsf.astype(in_dtype), hsr.astype(in_dtype), (hT2, cT2)


def lstm_forward_fused(x, w_ih, b, w_hh, h0, c0, compute_dtype=None):
    """Model-layout convenience wrapper over :func:`lstm_recurrence_fused`.

    Args:
      x: ``[B, T, D]`` raw inputs (the encoder output — no pre-projection).
      w_ih: ``[D, 4H]`` blocked input projection, b: ``[4H]`` combined bias,
      w_hh: ``[H, 4H]`` blocked recurrent weight (LSTMCell layout).
      h0, c0: ``[B, H]``.

    Returns ``(hs [B, T, H] at x's dtype, (hT, cT) at f32)`` — the carry
    contract is "always f32" (matches the scan path; the ring LSTM relays it
    between chunks). Pads the batch to the kernel tile.
    """
    B, T, D = x.shape
    H = w_hh.shape[0]
    in_dtype = x.dtype
    x = x.astype(compute_dtype if compute_dtype is not None else jnp.float32)
    w_ih = w_ih.astype(jnp.float32)
    w_hh = w_hh.astype(jnp.float32)
    b = b.astype(jnp.float32)
    h0 = h0.astype(jnp.float32)
    c0 = c0.astype(jnp.float32)
    bt = min(B_TILE, B)
    pad = (-B) % bt
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad, T, D), x.dtype)], 0)
        h0 = jnp.concatenate([h0, jnp.zeros((pad, H), h0.dtype)], 0)
        c0 = jnp.concatenate([c0, jnp.zeros((pad, H), c0.dtype)], 0)
    x_t = jnp.swapaxes(x, 0, 1)  # [T, B, D]
    wih4 = jnp.stack([w_ih[:, k * H : (k + 1) * H] for k in range(4)])
    b4 = jnp.stack([b[k * H : (k + 1) * H] for k in range(4)])
    whh4 = jnp.stack([w_hh[:, k * H : (k + 1) * H] for k in range(4)])
    hs, (hT, cT) = lstm_recurrence_fused(x_t, wih4, b4, whh4, h0, c0, compute_dtype)
    hs = jnp.swapaxes(hs, 0, 1)
    if pad:
        hs, hT, cT = hs[:B], hT[:B], cT[:B]
    return hs.astype(in_dtype), (hT, cT)
