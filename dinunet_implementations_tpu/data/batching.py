"""SPMD batch planning: sites × steps × batch dense arrays with masks.

The reference hides heterogeneous site sizes (73–120 subjects in the FS
fixture) behind round-based orchestration: every round each site pulls
``local_iterations`` batches from its own cycling DataLoader with
``drop_last=True`` for train (``local.py:29``). In one SPMD program all sites
must take the same number of steps per epoch, so we make the step grid dense:

- ``inputs  [S, steps, B, ...]``
- ``labels  [S, steps, B]``
- ``weights [S, steps, B]`` — 1.0 for real examples, 0.0 for padding; the
  trainer weighs per-site gradients by ``weights.sum()`` so aggregation is
  exactly example-weighted (dSGD == pooled SGD invariant).

``pad_mode``:
- ``"wrap"`` (train default): sites with fewer batches than the epoch's
  ``steps`` recycle their shuffled data — every site contributes every round,
  like the reference's cycling DataLoader.
- ``"mask"`` (eval): padding gets weight 0; no sample is seen twice (AUC /
  metric correctness).

Two layers since the device-resident pipeline landed:

- :func:`plan_epoch_positions` — the compact plan: ``positions [S, steps, B]``
  int32 sample positions into each site's inventory (``-1`` = padding). This
  is the only thing the device pipeline ships to the mesh per epoch
  (trainer/steps.py gathers batches on-device from the resident inventory).
- :func:`materialize_plan` — the host path: expand a plan to the dense
  :class:`FedBatches` arrays. ``plan_epoch`` composes the two, so the host
  and device pipelines are bit-exact by construction: one plan, two
  realizations.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .api import SiteArrays


@dataclass
class EpochPlan:
    """A compact epoch plan: per-(site, step, slot) sample positions into each
    site's own inventory; ``-1`` marks a padding slot (zero inputs/labels,
    zero weight in the materialized batch)."""

    positions: np.ndarray  # [S, steps, B] int32; -1 = padding

    @property
    def num_sites(self):
        return self.positions.shape[0]

    @property
    def steps(self):
        return self.positions.shape[1]

    @property
    def batch_size(self):
        return self.positions.shape[2]

    @property
    def nbytes(self) -> int:
        return self.positions.nbytes


@dataclass
class FedBatches:
    inputs: np.ndarray  # [S, steps, B, ...]
    labels: np.ndarray  # [S, steps, B]
    weights: np.ndarray  # [S, steps, B] float32
    indices: np.ndarray  # [S, steps, B] int32 (position in site inventory; -1 pad)

    @property
    def num_sites(self):
        return self.inputs.shape[0]

    @property
    def steps(self):
        return self.inputs.shape[1]

    @property
    def batch_size(self):
        return self.inputs.shape[2]


def _site_batches(arr, batch_size: int, order: np.ndarray, drop_last: bool):
    """Chunk one site's (ordered) samples into batches; returns list of index
    arrays, each of length ``batch_size`` except possibly the last."""
    n = len(order)
    if drop_last:
        n = (n // batch_size) * batch_size
    return [order[i : i + batch_size] for i in range(0, n, batch_size)]


def _site_batch_count(n: int, batch_size: int, drop_last: bool) -> int:
    return n // batch_size if drop_last else -(-n // batch_size)


def epoch_steps(sites: list[SiteArrays], batch_size: int,
                drop_last: bool = True) -> int:
    """Steps per epoch for this site set — the max per-site batch count (the
    dense step grid every site is padded/wrapped to). Pure arithmetic, shared
    with :func:`plan_epoch_positions` so callers (the prefetching planner) can
    predict round counts without building a plan."""
    return max(_site_batch_count(len(s), batch_size, drop_last) for s in sites)


def plan_epoch_positions(
    sites: list[SiteArrays],
    batch_size: int,
    seed: int = 0,
    shuffle: bool = True,
    drop_last: bool = True,
    pad_mode: str = "wrap",
    steps: int | None = None,
) -> EpochPlan:
    """Build the compact ``[S, steps, B]`` epoch plan (see module docstring).

    Wrap-mode recycling is a single computed tiling of reshuffled orders:
    draw exactly the permutations the epoch needs, concatenate their
    batch-aligned prefixes, and reshape — no per-batch list concatenation
    (the RNG draw sequence is identical to the historical loop, so plans are
    bit-stable across the refactor).

    ``steps`` PINS the step-grid height instead of deriving it from the site
    set (elastic rounds, r13): the daemon-mode runner's membership churns
    between epochs, and a joining site with more batches than anyone before
    it would otherwise grow the plan's traced shape and force a retrace. A
    taller target recycles every site's shuffled order (wrap semantics); a
    shorter one truncates the epoch's tail batches. The RNG draw sequence
    for the natural prefix is unchanged, so ``steps=None`` callers are
    byte-identical to before."""
    assert pad_mode in ("wrap", "mask")
    target_steps = steps
    assert target_steps is None or target_steps > 0, target_steps
    S = len(sites)
    feat_shape = None
    for s in sites:
        if len(s):
            fs = s.inputs.shape[1:]
            assert feat_shape is None or fs == feat_shape, "heterogeneous feature shapes"
            feat_shape = fs
    assert feat_shape is not None, "all sites empty"

    rng = np.random.default_rng(seed)

    def draw_order(n: int) -> np.ndarray:
        return rng.permutation(n) if shuffle else np.arange(n)

    first_orders = [draw_order(len(s)) for s in sites]
    counts = [
        _site_batch_count(len(s), batch_size, drop_last) for s in sites
    ]
    steps = max(counts)
    assert steps > 0, (
        f"no site yields a batch: batch_size={batch_size} exceeds every "
        f"site's sample count {[len(s) for s in sites]} with "
        f"drop_last={drop_last} — lower batch_size to at most "
        f"{max(len(s) for s in sites)} (FederatedTrainer.fit clamps this "
        "automatically)"
    )

    positions = np.full((S, steps, batch_size), -1, np.int32)
    for si, (site, order, nb) in enumerate(zip(sites, first_orders, counts)):
        n = len(site)
        if nb == 0:
            continue  # mask-only site: all padding (zero weight downstream)
        if pad_mode == "wrap" and nb < steps:
            if drop_last:
                # full batches only: tile (first + extra) orders' batch-aligned
                # prefixes and reshape — one vectorized fill per site
                usable = (n // batch_size) * batch_size
                extra = -(-(steps - nb) // nb)  # ceil: reshuffles needed
                tiled = np.concatenate(
                    [order[:usable]] + [draw_order(n)[:usable] for _ in range(extra)]
                )
                positions[si] = (
                    tiled[: steps * batch_size].reshape(steps, batch_size)
                )
                continue
            # drop_last=False wrap (unused by the trainer, kept for API
            # parity): ragged batches — linear list extension
            batches = _site_batches(site, batch_size, order, drop_last)
            while len(batches) < steps:
                batches.extend(
                    _site_batches(site, batch_size, draw_order(n), drop_last)
                )
            for bi, ix in enumerate(batches[:steps]):
                positions[si, bi, : len(ix)] = ix
            continue
        for bi, ix in enumerate(
            _site_batches(site, batch_size, order, drop_last)
        ):
            positions[si, bi, : len(ix)] = ix
    if target_steps is not None and target_steps != steps:
        if target_steps < steps:
            # pinned grid shorter than natural: drop the tail batches
            positions = positions[:, :target_steps]
        else:
            # pinned grid taller: recycle the epoch's batch sequence
            # cyclically (wrap semantics at plan granularity; an all-padding
            # mask row stays all padding). Deterministic — a pure function
            # of (sites, seed, target), so prefetch/resume stay bit-exact.
            reps = -(-target_steps // steps)
            positions = np.tile(positions, (1, reps, 1))[:, :target_steps]
    return EpochPlan(positions)


def materialize_plan(sites: list[SiteArrays], plan: EpochPlan) -> FedBatches:
    """Expand a compact plan to the dense host arrays (the host pipeline /
    eval path). Padding slots (-1) are zero-filled with zero weight — the
    exact semantics the device gather reproduces on-chip."""
    S, steps, B = plan.positions.shape
    feat_shape = next(s.inputs.shape[1:] for s in sites if len(s))
    inputs = np.zeros((S, steps, B) + feat_shape, np.float32)
    labels = np.zeros((S, steps, B), np.int32)
    weights = np.zeros((S, steps, B), np.float32)
    indices = np.full((S, steps, B), -1, np.int32)
    for si, site in enumerate(sites):
        flat = plan.positions[si].reshape(-1)
        valid = flat >= 0
        if not valid.any():
            continue
        sel = flat[valid]
        inputs[si].reshape((steps * B,) + feat_shape)[valid] = site.inputs[sel]
        labels[si].reshape(-1)[valid] = site.labels[sel]
        weights[si].reshape(-1)[valid] = 1.0
        indices[si].reshape(-1)[valid] = site.indices[sel]
    return FedBatches(inputs, labels, weights, indices)


def plan_epoch(
    sites: list[SiteArrays],
    batch_size: int,
    seed: int = 0,
    shuffle: bool = True,
    drop_last: bool = True,
    pad_mode: str = "wrap",
    steps: int | None = None,
) -> FedBatches:
    """Build the dense [S, steps, B, ...] epoch plan (see module docstring)."""
    return materialize_plan(
        sites,
        plan_epoch_positions(
            sites, batch_size, seed=seed, shuffle=shuffle,
            drop_last=drop_last, pad_mode=pad_mode, steps=steps,
        ),
    )


def plan_eval(sites: list[SiteArrays], batch_size: int) -> FedBatches:
    """Deterministic full pass: no shuffle, no drop, mask padding."""
    return plan_epoch(
        sites, batch_size, shuffle=False, drop_last=False, pad_mode="mask"
    )
