from .base import Engine, available_engines, make_engine
from . import dsgd, powersgd, rankdad  # noqa: F401 — register engines
from .lowrank import (
    is_compressible,
    orthonormalize,
    subspace_iteration,
    subspace_iteration_grouped,
    subspace_iteration_multi,
    to_matrix,
)
