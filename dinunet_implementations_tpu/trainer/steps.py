"""The SPMD federated train/eval steps — where the reference's entire
local↔remote round trip collapses into one compiled program.

Reference execution (SURVEY.md §3.1): per round, every site container steps
``local_iterations`` batches with gradient accumulation, JSON-ships its
(possibly compressed) gradient to the remote, the remote reduces across sites
on an mp.Pool and broadcasts the update back. ~97% of wall-clock was that
transport. Here:

- one epoch = ``jax.lax.scan`` over rounds *inside* a single ``shard_map``
  over the ``(site,)`` mesh — zero host round trips;
- gradient accumulation = inner ``lax.scan`` over ``local_iterations``
  micro-batches (``compspec.json:88-95``);
- the engine's collectives (psum / all-gather, engines/) are the only
  cross-site communication, riding ICI;
- parameters & optimizer state are replicated (every site applies the same
  aggregated update — the invariant the reference maintains by broadcast).

BatchNorm running stats (ICALstm head) are psum-averaged across sites each
round ("sync-BN across sites"): the reference lets per-site buffers drift and
never reconciles them; averaging is the principled SPMD equivalent and keeps
eval single-model. Documented TPU-design divergence.
"""

from __future__ import annotations

from typing import Any

import flax.struct
import jax
import jax.numpy as jnp
import optax
from dinunet_implementations_tpu.core.jaxcompat import shard_map
from jax.sharding import PartitionSpec as P

from ..engines.base import Engine, default_async_buffers, staleness_weights
from ..parallel.collectives import (
    PackedAxis,
    site_weight_scale,
    two_level_psum,
    weighted_site_sum,
)
from ..parallel.mesh import (
    FOLD_AXIS,
    MODEL_AXIS,
    SITE_AXIS,
    SLICE_AXIS,
    site_axis_of,
    slice_count,
)
from ..robustness.health import default_health
from ..telemetry.metrics import (
    TELEMETRY_KEYS,
    dcn_bytes_of,
    default_round_telemetry,
    payload_bytes_of,
    tree_sq_sum,
)


def _model_axis_of(mesh) -> str | None:
    """The bound model/sequence axis name, when the mesh has one of size > 1.

    With a ``(site, model)`` mesh the data stays partitioned over ``site``
    only — every model-axis member sees the full per-site batch and the model
    internally shards its sequence axis (models/icalstm.py sequence_axis,
    models/transformer.py attention="ring")."""
    if mesh is not None and dict(getattr(mesh, "shape", {})).get(MODEL_AXIS, 1) > 1:
        return MODEL_AXIS
    return None


@flax.struct.dataclass
class TrainState:
    params: Any
    batch_stats: Any  # {} when the model tracks no running stats
    opt_state: Any
    engine_state: Any  # PER-SITE: leaves carry a leading [num_sites] axis
    rng: jax.Array
    round: jax.Array  # global round counter (int32)
    # PER-SITE health counters (robustness/health.py): non-finite streak,
    # skipped-round count, sticky quarantine flag. None only for states built
    # by hand pre-0.3 code paths — the epoch fn fills in zeros then.
    health: Any = None
    # PER-SITE round-metric accumulators (telemetry/metrics.py): grad/update
    # norms, engine residual, payload bytes. None whenever
    # TrainConfig.telemetry="off" — the epoch program then carries no
    # telemetry ops at all (bitwise-equal to the pre-telemetry program).
    telemetry: Any = None
    # PER-SLOT staleness buffers (engines/base.py default_async_buffers):
    # each virtual site's last deposited update + its weight + arrival age —
    # the carry of the buffered-async aggregation mode (r13). None whenever
    # TrainConfig.staleness_bound == 0 — the epoch program then carries no
    # buffering ops at all (bitwise-equal to the bulk-sync program, the
    # telemetry=off pattern; S005-gated).
    buffers: Any = None
    # PER-SITE double-buffered round payload (r14 compute/comm overlap,
    # :func:`default_overlap_stash`): the previous round's gradients /
    # weights / loss / liveness, whose aggregation collective is issued
    # while the NEXT round's batch gather + forward/backward compute — the
    # one-round-delayed pipelined update. Riding TrainState (not just the
    # scan carry) means no round is ever dropped at an epoch boundary: the
    # epoch's last stash applies at the next epoch's first round, and a
    # checkpointed fit resumes with its in-flight round intact. None
    # whenever TrainConfig.overlap_rounds is off — the epoch program then
    # carries no overlap ops at all (bitwise-equal legacy program,
    # S005-gated).
    overlap: Any = None
    # PER-SITE personalized-head state (r20, privacy/personalize.py):
    # {"params": head-subtree with [S, ...] leaves, "opt": the per-site
    # optimizer state over it}. Head leaves named by TrainConfig.personalize
    # are partitioned OUT of aggregation entirely — each site trains and
    # evaluates its own row; the global params tree keeps full structure
    # with those leaves frozen at init. Sharded P(site) like health,
    # checkpointed (R006), rejoin-reset via reset_slot_state. None whenever
    # personalization is off — the epoch program then carries no
    # personalization ops at all (bitwise-equal legacy program,
    # S005-gated).
    personal: Any = None


def _state_specs(state: TrainState, site_axis=SITE_AXIS):
    """shard_map partition specs: everything replicated except the per-site
    engine state — powerSGD's error-feedback residual/Q and rankDAD's
    warm-start subspace Ω (engines/rankdad.py) — which is sharded over the
    site axis; collapsing it to one site's copy would silently break error
    feedback (and subspace warm starts) across epoch boundaries. The health
    counters are per-site for the same reason. ``site_axis`` is the leading
    per-site partition entry — the ``(slice, site)`` pair on sliced meshes
    (parallel/mesh.py ``site_axis_of``), plain ``site`` otherwise."""
    return TrainState(
        params=jax.tree.map(lambda _: P(), state.params),
        batch_stats=jax.tree.map(lambda _: P(), state.batch_stats),
        opt_state=jax.tree.map(lambda _: P(), state.opt_state),
        engine_state=jax.tree.map(lambda _: P(site_axis), state.engine_state),
        rng=P(),
        round=P(),
        health=jax.tree.map(lambda _: P(site_axis), state.health),
        telemetry=jax.tree.map(lambda _: P(site_axis), state.telemetry),
        buffers=jax.tree.map(lambda _: P(site_axis), state.buffers),
        overlap=jax.tree.map(lambda _: P(site_axis), state.overlap),
        personal=jax.tree.map(lambda _: P(site_axis), state.personal),
    )


def make_optimizer(name: str, learning_rate: float) -> optax.GradientTransformation:
    """Reference trains with Adam at ``learning_rate`` (coinstac-dinunet
    default); SGD kept as an option."""
    if name == "adam":
        return optax.adam(learning_rate)
    if name == "sgd":
        return optax.sgd(learning_rate)
    raise ValueError(f"unknown optimizer {name!r}")


def cross_entropy(logits, labels, weights):
    """Masked mean cross-entropy. FS uses log_softmax+NLL, ICA uses
    cross_entropy — identical math (``comps/fs/__init__.py:54-55``,
    ``comps/icalstm/__init__.py:60``)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    ce = -jnp.take_along_axis(logp, labels[..., None].astype(jnp.int32), axis=-1)[..., 0]
    denom = jnp.maximum(weights.sum(), 1.0)
    return (ce * weights).sum() / denom


class FederatedTask:
    """Bundles a flax model with its loss/apply plumbing for the trainer."""

    def __init__(self, model, has_batch_stats: bool | None = None):
        self.model = model
        self.has_batch_stats = has_batch_stats  # resolved at init_variables

    def init_variables(self, rng, sample_x):
        # init runs OUTSIDE shard_map (no mesh axis bound), so a model
        # configured for sequence parallelism initializes via a dense twin —
        # submodule names/shapes are identical by construction, only the
        # collective plumbing differs
        model = self.model
        dense_kw = {}
        if getattr(model, "sequence_axis", None) is not None:
            dense_kw["sequence_axis"] = None
        if getattr(model, "attention", None) == "ring":
            dense_kw.update(attention="local", axis_name=None)
        if dense_kw:
            model = model.clone(**dense_kw)
        variables = model.init(
            {"params": rng, "dropout": rng}, sample_x, train=True
        )
        self.has_batch_stats = "batch_stats" in variables
        return variables["params"], variables.get("batch_stats", {})

    def apply(self, params, batch_stats, x, train, rng=None, mask=None, mutable=False):
        variables = {"params": params}
        if self.has_batch_stats:
            variables["batch_stats"] = batch_stats
        rngs = {"dropout": rng} if rng is not None else None
        if mutable and self.has_batch_stats:
            logits, upd = self.model.apply(
                variables, x, train=train, mask=mask, rngs=rngs, mutable=["batch_stats"]
            )
            return logits, upd["batch_stats"]
        logits = self.model.apply(variables, x, train=train, mask=mask, rngs=rngs)
        return logits, batch_stats


def init_train_state(
    task: FederatedTask,
    engine: Engine,
    optimizer: optax.GradientTransformation,
    rng,
    sample_x,
    num_sites: int = 1,
    telemetry: bool = False,
    staleness_bound: int = 0,
    overlap_rounds: bool = False,
    reputation: bool = False,
    personalize: tuple = (),
) -> TrainState:
    params, batch_stats = task.init_variables(rng, sample_x)
    # personalized heads (r20): the engine only ever aggregates (and its
    # state/wire models only ever see) the SHARED subtree — head leaves
    # never ship, so engine state must not carry rows for them
    personal = None
    if personalize:
        from ..privacy.personalize import (
            default_personal,
            head_leaf_paths,
            strip_tree,
        )

        paths = head_leaf_paths(params, personalize)
        site_state = engine.init(strip_tree(params, paths, keep_head=False))
        personal = default_personal(num_sites, params, paths, optimizer)
    else:
        site_state = engine.init(params)
    return TrainState(
        params=params,
        batch_stats=batch_stats,
        opt_state=optimizer.init(params),
        # per-site engine state: one copy per site, leading [num_sites] axis
        engine_state=jax.tree.map(
            lambda a: jnp.stack([a] * num_sites), site_state
        ),
        rng=rng,
        round=jnp.zeros((), jnp.int32),
        # reputation=True adds the r17 anomaly-score fields so the robust
        # epoch program's carry structure matches from the first call (the
        # _ensure_health fill would otherwise cost one extra compile)
        health=default_health(num_sites, reputation=reputation),
        # telemetry accumulators only when the epoch fn will maintain them —
        # a telemetry-carrying state fed to a telemetry-off program would
        # force a structure change (and a recompile) at the jit boundary
        telemetry=default_round_telemetry(num_sites) if telemetry else None,
        # staleness buffers only for the buffered-async mode (same structural
        # reasoning as telemetry: the carried state must match the program)
        buffers=(
            default_async_buffers(num_sites, params)
            if staleness_bound > 0 else None
        ),
        # overlap stash only for the pipelined-rounds mode (same structural
        # reasoning: the carried state must match the program)
        overlap=(
            default_overlap_stash(num_sites, params, batch_stats)
            if overlap_rounds else None
        ),
        # per-site head rows only when personalization is on (the telemetry
        # structural reasoning: the carried state must match the program)
        personal=personal,
    )


def default_overlap_stash(num_sites: int, params, batch_stats) -> dict:
    """Fresh (empty) double-buffered round payload for the overlapped-rounds
    mode (r14): per-site ``grads``/``stats``/``weight``/``loss``/``live``
    slots holding the round whose aggregation is still in flight, plus
    ``valid`` (0 = nothing stashed yet — the very first round of a fit
    applies no update). All leaves carry the ``[num_sites]`` leading axis,
    ride ``TrainState.overlap`` sharded ``P(site)``, are checkpointed
    (trainer/checkpoint.py — a resumed fit continues its in-flight round),
    and are distinct arrays so state donation never aliases a buffer
    twice."""
    return {
        "grads": jax.tree.map(
            lambda p: jnp.zeros((num_sites,) + p.shape, p.dtype), params
        ),
        "stats": jax.tree.map(
            lambda s: jnp.zeros((num_sites,) + s.shape, s.dtype), batch_stats
        ),
        "weight": jnp.zeros((num_sites,), jnp.float32),
        "loss": jnp.zeros((num_sites,), jnp.float32),
        "live": jnp.zeros((num_sites,), jnp.float32),
        "valid": jnp.zeros((num_sites,), jnp.float32),
    }


def _gather_batch(inv_x, inv_y, ixs, poison):
    """On-device batch gather for ONE site: ``ixs [L, B]`` sample positions
    into the site's resident inventory (``inv_x [N, ...]``, ``inv_y [N]``);
    ``-1`` marks padding. Reproduces the host materialization bit-for-bit:
    padding slots become zero inputs / zero labels / zero weight, and
    ``poison`` (the round's NaN-injection gate, robustness/faults.py — a
    traced scalar, non-None only when the epoch was compiled for a
    NaN-carrying FaultPlan) overwrites the whole round block with NaN exactly
    like ``poison_inputs`` does on host arrays."""
    valid = ixs >= 0
    flat = jnp.maximum(ixs, 0).reshape(-1)
    xb = jnp.take(inv_x, flat, axis=0).reshape(ixs.shape + inv_x.shape[1:])
    yb = jnp.take(inv_y, flat, axis=0).reshape(ixs.shape)
    mask = valid.reshape(valid.shape + (1,) * (xb.ndim - valid.ndim))
    xb = jnp.where(mask, xb, jnp.zeros((), xb.dtype))
    yb = jnp.where(valid, yb, 0)
    if poison is not None:
        xb = jnp.where(poison > 0, jnp.full((), jnp.nan, xb.dtype), xb)
    return xb, yb, valid.astype(jnp.float32)


def make_train_epoch_fn(
    task: FederatedTask,
    engine: Engine,
    optimizer: optax.GradientTransformation,
    mesh=None,
    local_iterations: int = 1,
    rounds_scan_xs: bool = True,
    quarantine_rounds: int | None = 3,
    pipeline: str = "host",
    donate_state: bool = False,
    telemetry: bool = False,
    staleness_bound: int = 0,
    staleness_decay: float = 0.5,
    overlap_rounds: bool = False,
    attack_plan=None,
    robust_agg: str = "none",
    reputation_z: float = 2.0,
    reputation_rounds: int = 8,
    min_slices: int = 1,
    dp_clip: float = 0.0,
    dp_noise_multiplier: float = 0.0,
    dp_seed: int = 0,
    personalize: tuple = (),
):
    """Build the jitted epoch function.

    Takes ``(state, inputs [S,steps,B,...], labels [S,steps,B],
    weights [S,steps,B], live=None)``; consumes ``steps`` in rounds of
    ``local_iterations`` micro-batches (trailing remainder < local_iterations
    is dropped, mirroring drop_last at round granularity); returns
    ``(state, per-round weighted loss [rounds])``.

    ``pipeline="device"`` swaps the dense epoch inputs for the
    device-resident form: the returned function takes ``(state,
    inv_x [S, N_max, ...], inv_y [S, N_max], idx [S, steps, B], live=None,
    poison=None)`` — the inventory is uploaded once per fit and reused every
    epoch, the per-epoch transfer is the int32 index plan
    (data/batching.py EpochPlan), and batches are gathered on-device
    round-by-round inside the scan (``jnp.take`` along the inventory axis;
    weights/padding derived from ``idx``, bit-exact with the host
    materialization). ``poison [S, rounds]`` is the FaultPlan NaN-injection
    mask (a traced input like ``live`` — one compiled program per fit
    regardless of the fault pattern). The device path always delivers rounds
    as scan xs (the index plan is KB-sized; ``rounds_scan_xs`` only governs
    the host path's dense arrays).

    ``donate_state=True`` donates the carried ``state`` argument's buffers to
    the epoch (``jax.jit(donate_argnums=0)``): the update writes in place
    instead of allocating a second params+optimizer copy per epoch. Callers
    must treat the passed-in state as CONSUMED — rebind to the returned state
    and snapshot (copy) anything kept longer (the trainer's best-state
    tracking does exactly that).

    Fault tolerance (robustness/): ``live [S, rounds]`` is the optional
    scheduled-liveness mask — a TRACED input, so a different fault pattern
    never recompiles the epoch. Each round a site contributes iff it is
    scheduled live AND its round gradient is finite AND it is not
    quarantined; dead sites are zero-weighted inside every engine's
    ``aggregate`` (``jnp.where``-masked payloads, weighted mean renormalized
    over live weight only) and their engine state is frozen for the round. A
    site whose gradient stays non-finite for ``quarantine_rounds``
    consecutive rounds trips a sticky quarantine flag (``TrainState.health``;
    ``quarantine_rounds == 0`` disables the sticky flag but keeps the
    per-round skip). A round with NO live weight leaves
    params/optimizer/batch-stats untouched. ``quarantine_rounds < 0`` with no
    mask statically compiles the fault machinery OUT — the exact
    pre-robustness program, for benchmarking the machinery's cost.
    ``quarantine_rounds=None`` means the default (3).

    Buffered-async aggregation (r13 — elastic rounds): ``staleness_bound >
    0`` switches the aggregation semantics from bulk-synchronous to
    staleness-bounded buffered-async. Each virtual site owns a per-slot
    update buffer riding ``TrainState.buffers`` through the rounds scan: a
    round where the site ARRIVES (scheduled live AND finite AND not
    quarantined) deposits its fresh gradient + example weight and resets the
    slot's age to 0; a round where it doesn't (drop, straggler ``delay_at``,
    membership hole) leaves the buffer and ages it. Aggregation then runs
    over the BUFFERS, each slot's weight scaled by ``staleness_decay^age``
    (engines/base.py ``staleness_weights``) and hard-masked past
    ``staleness_bound`` exactly like a dead site — so a straggling update
    keeps pulling the model with fading weight instead of being lost, and a
    site that stops arriving fades out instead of stalling the round. The
    round loss / sync-BN / health counters stay keyed on FRESH arrivals; a
    round with no in-bound buffered weight holds params/optimizer exactly
    like an all-dead bulk-sync round. ``staleness_bound == 0`` (default)
    statically compiles ALL of it out — the exact bulk-sync program
    (lowering-identical; checks/semantic.py S005 "async-off"), and since
    ``decay^0 == 1`` an async round where EVERY site arrives is bit-identical
    to the bulk-sync round anyway. Arrival masks are traced inputs, so churn
    and straggle patterns never recompile.

    Overlapped rounds (r14 — compute/communication overlap):
    ``overlap_rounds=True`` software-pipelines the rounds scan so round
    *t*'s aggregation collective is issued against a double-buffered stash
    (``TrainState.overlap``) while round *t+1*'s batch gather and
    forward/backward run — the two are data-independent, so XLA's
    latency-hiding scheduler can split the collective into start/done and
    hide ICI/DCN time under the compute (the TPUv4 pjit overlap playbook;
    an ``optimization_barrier`` pins the stash read ahead of the batch
    block). The cost is ONE ROUND of update delay: round *t*'s gradients
    are computed at parameters that do not yet include round *t−1*'s
    update (classic pipelined/delayed SGD — momentum smooths the one-step
    staleness exactly as it does for the buffered-async mode). The stash
    rides ``TrainState`` rather than the bare scan carry, so nothing is
    dropped at epoch boundaries (the last round of epoch *e* applies at
    the first round of epoch *e+1*) and checkpoint/resume keeps the
    in-flight round. The very first round of a fit applies nothing
    (``valid=0`` — reported as a NaN loss, like an all-dead round).
    Mutually exclusive with ``staleness_bound > 0`` (two different
    staleness semantics over one buffer would compound); implies the
    guarded round form. ``overlap_rounds=False`` (default) statically
    compiles ALL of it out — the exact legacy program (S005
    "overlap-off").

    Telemetry (telemetry/metrics.py): ``telemetry=True`` accumulates, every
    round, per-site grad/update norms, the engine aggregation residual and
    modeled payload bytes into ``state.telemetry`` — traced values riding the
    same rounds scan (zero extra host syncs, zero recompiles).
    ``telemetry=False`` (default) statically compiles all of it out and
    carries ``state.telemetry=None``: the exact pre-telemetry program, same
    pattern as ``quarantine_rounds=-1``.

    Hostile sites (r17 — robustness/attacks.py, parallel/collectives.py):
    ``attack_plan`` is an optional :class:`~..robustness.attacks.AttackPlan`
    whose STATIC transform parameters (scale factor, noise σ, seeds) are
    closed over at trace time; the per-(site, round) attack pattern arrives
    as ``attack [S, rounds]`` — an int32 CODE mask fed as a traced input
    exactly like ``live``, so one compiled program per fit covers every
    pattern of the plan. The transform applies to each site's ROUND
    GRADIENT inside the per-site phase (before engine compression), and
    composes freely with FaultPlan drops/delays/NaN poisoning and packing.
    ``robust_agg`` selects the engines' byzantine-robust site reducer (the
    engine must be built with the SAME mode — engines/base.py); any value
    other than ``"none"`` also switches on the anomaly-scored REPUTATION
    layer: per round, each live site's distance-to-robust-aggregate and
    gradient-norm z-scores (across the live cohort, on-device scalar psums
    only) drive ``health.suspect_streak``/``health.anomaly``, and a site
    whose score exceeds ``reputation_z`` for ``reputation_rounds``
    CONSECUTIVE rounds trips the same sticky ``quarantined`` flag as a NaN
    site (``reputation_rounds=0`` scores without quarantining). z-scores
    need a cohort to stand out from: the threshold must be below
    ``(S_live - 1)/sqrt(S_live)`` to be reachable at all, so small-S runs
    lower ``reputation_z`` or rely on the robust reducer alone.
    ``robust_agg="none"`` (default) compiles ALL of it out — the exact
    legacy program (S005 "robust-off"); the mask input is rejected unless
    an attack plan was given.

    Slice elasticity (r19 — robustness/faults.py slice windows): on a
    sliced mesh the epoch accepts an optional ``slice_live [num_slices,
    rounds]`` TRACED input (replicated — it is tiny), the whole-slice twin
    of ``live``. Each round, every member multiplies its own slice's gate
    into the site-level contribute mask, so a dead slice's members are
    excluded from every engine's aggregate, sync-BN, the round loss and
    the weight renormalization EXACTLY as if the mask had zeroed its sites
    outright — bit-identical params, per engine, packed and unpacked
    (tests/test_multislice.py pins it). ``min_slices`` is the slice-quorum
    floor: a round with fewer live slices HOLDS — params, optimizer,
    engine state, health, buffers, stats and the overlap stash all freeze,
    the loss reports NaN, and (telemetry on) the per-site ``held_rounds``
    accumulator counts it — rather than training on a rump cohort. The
    quorum count is a local reduction of the replicated mask, so slice
    faults add ZERO collectives to the program (the wire proofs — S002 —
    hold unchanged on slice-fault cells). ``slice_live=None`` compiles the
    exact r18 program (S005 "slicefaults-off"), and since ``×1.0`` is
    exact an all-slices-live mask reproduces it value-for-value; changing
    WHICH slices die WHEN never retraces. The mask is rejected on unsliced
    topologies (there is no slice tier to fault).

    Site-axis realization (all forms run the *same* per-site program):

    - ``mesh`` given → ``shard_map`` over the mesh's ``site`` axis, with
      ``K = S / mesh_sites`` virtual sites PACKED per device (K=1 is the
      classic one-site-per-slice case): the per-site phase runs under an
      inner vmap over the device's ``[K, …]`` block and aggregation is the
      two-level packed reduction (parallel/collectives.py PackedAxis) —
      local in-register reduce over the packed axis, ONE cross-device
      collective of the partial over ICI. The multi-chip path; how an
      8-device mesh trains 512+ sites in one compiled program (r12).
    - ``mesh=None`` → ``jax.vmap(axis_name="site")``: all S sites fold onto
      the local device as a batched dimension; ``psum``/``all_gather`` resolve
      over the vmapped axis. This is how one TPU chip simulates 32 federated
      sites (BASELINE.json north star) at full MXU utilization.
    """

    assert pipeline in ("host", "device"), pipeline
    model_axis = _model_axis_of(mesh)
    # multi-slice (r18): a mesh built by parallel/mesh.py sliced_site_mesh
    # carries the outer DCN axis — per-site data then shards over the
    # (slice, site) pair and aggregation grows the inter-slice tier
    # (parallel/collectives.py three_level_psum). Single-slice meshes keep
    # the exact legacy program: site_part is the plain site axis and the
    # PackedAxis carries no slice name.
    n_slices = slice_count(mesh)
    sliced = mesh is not None and SLICE_AXIS in mesh.axis_names
    site_part = site_axis_of(mesh) if mesh is not None else SITE_AXIS
    mesh_site_members = (
        dict(mesh.shape)[SITE_AXIS] if mesh is not None else 1
    )
    if quarantine_rounds is None:
        quarantine_rounds = 3  # the default threshold
    if staleness_bound < 0:
        raise ValueError(
            f"staleness_bound must be >= 0, got {staleness_bound}"
        )
    if not 0.0 < staleness_decay <= 1.0:
        raise ValueError(
            f"staleness_decay must be in (0, 1], got {staleness_decay}"
        )
    # trace-time static: the buffered-async machinery exists iff the bound is
    # positive — staleness_bound=0 compiles the exact bulk-sync program
    buffered = staleness_bound > 0
    # builder kwarg, never a tracer: the static TrainConfig.overlap_rounds
    overlap = bool(overlap_rounds)  # jaxlint: disable=R005
    from ..parallel.collectives import ROBUST_AGGS

    if robust_agg not in ROBUST_AGGS:
        raise ValueError(
            f"robust_agg must be one of {ROBUST_AGGS}, got {robust_agg!r}"
        )
    # trace-time static: the reputation layer exists iff a robust reducer is
    # active — robust_agg="none" compiles the exact legacy program
    reputation = robust_agg != "none"
    if reputation_rounds < 0:
        raise ValueError(
            f"reputation_rounds must be >= 0, got {reputation_rounds}"
        )
    # the attack transform's static parameters, closed over at trace time
    # (robustness/attacks.py); the per-(site, round) pattern is a traced
    # mask, so changing WHO attacks WHEN never recompiles
    atk = None
    if attack_plan is not None and attack_plan.injects_attacks():
        from ..robustness.attacks import make_attack_fn

        atk = make_attack_fn(attack_plan)
    # privacy plane (r20) trace-time statics: DP clip/noise parameters are
    # closed over (noise is counter-keyed by (dp_seed, site, round), like
    # AttackPlan noise — chunk/resume/packing-independent); the head
    # partition patterns resolve to leaf paths at trace time from the real
    # params structure. Both off (the defaults) build NOTHING — the epoch
    # program is lowering-identical to the legacy one (S005 "dp-off" /
    # "personalize-off").
    from ..privacy.dpsgd import dp_enabled

    dp_on = dp_enabled(dp_clip, dp_noise_multiplier)
    # builder kwarg, never a tracer: the static TrainConfig.personalize
    personal_on = bool(tuple(personalize))  # jaxlint: disable=R005
    # rnd-aware engine dispatch (r20): the trainer always has the traced
    # global round counter to offer, but legacy/fixture engines keep the
    # pre-r20 aggregate signature — resolve from the signature like
    # telemetry's _accepts_pack (never `except TypeError`, which would
    # swallow a genuine TypeError raised inside an rnd-aware engine)
    import inspect

    try:
        _agg_sig = inspect.signature(engine.aggregate).parameters
    except (TypeError, ValueError):  # builtins/C callables: assume legacy
        _agg_sig = {}
    _agg_takes_rnd = "rnd" in _agg_sig or any(
        p.kind is inspect.Parameter.VAR_KEYWORD for p in _agg_sig.values()
    )

    def engine_aggregate(grads, es, weight, axis, live, rnd):
        if _agg_takes_rnd:
            return engine.aggregate(grads, es, weight, axis, live=live,
                                    rnd=rnd)
        return engine.aggregate(grads, es, weight, axis, live=live)

    if min_slices < 1:
        raise ValueError(f"min_slices must be >= 1, got {min_slices}")
    if min_slices > 1 and not sliced:
        raise ValueError(
            f"min_slices={min_slices} needs a sliced mesh (num_slices > 1) "
            "— there is no slice quorum on a single-slice topology"
        )
    if min_slices > 1 and min_slices > n_slices:
        raise ValueError(
            f"min_slices={min_slices} exceeds the mesh's {n_slices} slices "
            "— every round would hold"
        )
    if overlap and buffered:
        raise ValueError(
            "overlap_rounds and staleness_bound > 0 are mutually exclusive: "
            "both buffer per-site updates with their own staleness "
            "semantics (one-round pipeline delay vs decay^age weighting) "
            "and composing them would compound the delays"
        )

    def loss_fn(params, batch_stats, rng, x, y, w):
        logits, new_stats = task.apply(
            params, batch_stats, x, train=True, rng=rng, mask=w, mutable=True
        )
        loss = cross_entropy(logits, y, w)
        if model_axis is not None:
            # The forward runs on every model-axis member (sequence-sharded
            # inside the model, logits replicated by its final gather), so an
            # unmasked loss would seed the head cotangent once PER member and
            # the later grad psum would count head grads n×. Keep member 0's
            # loss only: its cotangent reaches every member's sequence chunk
            # through the transposed collectives (reduce-scatter / ppermute),
            # and the psum over the axis then assembles the exact full grad.
            keep = (jax.lax.axis_index(model_axis) == 0).astype(loss.dtype)
            loss = loss * keep
        return loss, new_stats

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def epoch_over_sites(state: TrainState, x, y, w, live, site_axes,
                         inner_axis, inventory=None, poison=None,
                         attack=None, slice_live=None):
        """Run one epoch for the k in-device sites in ``x [k, steps, B, ...]``.

        Device pipeline (``inventory`` given): ``x`` is the ``[k, steps, B]``
        int32 index plan instead (``y``/``w`` are None) and each round's batch
        is gathered on-device from the resident ``inventory = (inv_x, inv_y)``
        just before its gradients — only one round's ``[k, L, B, ...]`` block
        is ever materialized, so peak HBM holds the inventory, not the dense
        epoch tensor.

        Only the per-site work (grads, engine factorization, stat
        accumulation) runs under the inner vmap; the optimizer update applies
        ONCE per round on the (replicated) aggregate. The scan carry
        therefore holds a single copy of params/opt_state — vmapping the
        whole round used to replicate them per site, costing ~k× the
        params+Adam-state in HBM writes every round (measured ~half the
        epoch time at 32 folded sites).

        ``site_axes`` is the bound axis (or (mesh, vmap-fold) pair) that the
        per-site phase's ``axis_index`` linearizes over (the same global,
        device-major site order as the data layout); ``inner_axis`` is the
        vmap axis name for the in-device block.

        Site packing (r12): on a mesh (``site_axes`` a tuple), aggregation
        is a TWO-LEVEL reduction. The per-site gradient phase stays under
        the inner vmap, but everything that communicates — the engine's
        ``aggregate``, sync-BN, the round loss — runs OUTSIDE it on the
        device's ``[k, …]`` virtual-site block with a
        :class:`~..parallel.collectives.PackedAxis`: payloads reduce over
        the packed axis in-register and ONE cross-device collective ships
        the unbatched partial. The legacy form (collectives inside the vmap,
        resolved through jax's batching rules) shipped the whole ``[k, …]``
        block over the mesh — k× the wire bytes per round; at the 512-site
        pack factors that is the difference between aggregation costing one
        model's worth of traffic per device and 64 of them. The folded-vmap
        topology (``mesh=None``) is unchanged — its "collectives" are local
        register reductions with no wire either way.
        """
        k, steps = x.shape[0], x.shape[1]
        # trace-time static: mesh topologies carry the (mesh, fold) pair and
        # take the packed two-level aggregation path; the vmap-folded
        # single-device topology keeps the classic in-vmap form. Sliced
        # meshes (r18) hand the PackedAxis the slice axis too — the same
        # engine calls then lower the three-tier reduction.
        packed = isinstance(site_axes, tuple)
        pax = (
            PackedAxis(SITE_AXIS, k, slice_name=SLICE_AXIS if sliced else None)
            if packed else None
        )
        rounds = steps // local_iterations
        L = rounds * local_iterations
        # privacy plane (r20): the head partition resolves against the REAL
        # params structure at trace time; the DP transform (clip + counter-
        # keyed noise) skips head leaves — they never ship, so the
        # mechanism has nothing to protect there. Both are trace-time
        # presence branches: off builds nothing (S005).
        head_paths = frozenset()
        if personal_on:
            from ..privacy.personalize import (
                graft_shared,
                head_leaf_paths,
                strip_tree,
            )

            head_paths = head_leaf_paths(state.params, personalize)
        dp = None
        if dp_on:
            from ..privacy.dpsgd import make_dp_fn

            dp = make_dp_fn(dp_clip, dp_noise_multiplier, dp_seed, head_paths)

        def _eng_grads(tree):
            """What the engine aggregates: the SHARED subtree under
            personalization (head leaves never reach the wire), the full
            tree otherwise."""
            if not personal_on:
                return tree
            return strip_tree(tree, head_paths, keep_head=False)

        def _full_agg(agg_shared):
            """The optimizer-facing aggregate at full params structure:
            shared leaves from the engine, head leaves exact zeros — the
            frozen global head copies provably never move (zero grad →
            zero Adam moments → zero update)."""
            if not personal_on:
                return agg_shared
            return graft_shared(state.params, agg_shared, head_paths)

        # split the steps axis in place ([k, rounds, L, B, ...] — a free
        # reshape). Each round's block then arrives either as rounds-leading
        # scan xs (default; see the moveaxis note below) or via a per-round
        # dynamic-slice on axis 1 (rounds_scan_xs=False, the measured-slower
        # A/B arm kept for re-benchmarks).
        def split_rounds(a):
            return a[:, :L].reshape((k, rounds, local_iterations) + a.shape[2:])

        # device pipeline: x IS the index plan; one split covers it. The
        # index plan is KB-sized, so it always rides as scan xs regardless of
        # the rounds_scan_xs arm (which exists for multi-GB dense inputs).
        x_rounds = split_rounds(x)
        y_rounds, w_rounds = (
            (None, None) if inventory is not None
            else (split_rounds(y), split_rounds(w))
        )
        poison_rounds = None if poison is None else poison[:, :rounds]
        use_scan_xs = rounds_scan_xs or inventory is not None
        # scheduled liveness, [k, rounds] f32 (None → all live; the branch is
        # trace-time static, so both forms compile once each, never per mask)
        live_rounds = (
            None if live is None else live[:, :rounds].astype(jnp.float32)
        )
        # hostile-site attack codes, [k, rounds] int32 (robustness/attacks.py
        # — 0 = honest; a traced input like `live`, trace-time presence
        # branch). The mask only works with the plan's static transform
        # params closed over above.
        if attack is not None and atk is None:
            raise ValueError(
                "an attack mask was fed but no attack_plan was given to "
                "make_train_epoch_fn (the plan carries the static transform "
                "parameters)"
            )
        attack_rounds = (
            None if (attack is None or atk is None)
            else attack[:, :rounds].astype(jnp.int32)
        )
        attack_on = attack_rounds is not None
        # slice-liveness gate (r19, robustness/faults.py slice windows): the
        # [num_slices, rounds] whole-slice mask arrives REPLICATED (it is
        # tiny); each member reads its OWN slice's row by axis index — no
        # collective — and multiplies it into the per-round site gate, so a
        # dead slice's members mask out exactly like site-level drops. The
        # per-round live-slice count (a local reduction of the replicated
        # mask, again no collective) drives the min_slices quorum hold.
        # Trace-time presence branch like `live`: slice_live=None compiles
        # the exact r18 program, and changing WHO dies WHEN never retraces.
        if slice_live is not None and not sliced:
            raise ValueError(
                "a slice_live mask was fed on an unsliced topology — slice "
                "faults need a (slice, site, model) mesh "
                "(TrainConfig.num_slices > 1)"
            )
        if slice_live is not None and slice_live.shape[0] != n_slices:
            # a wrong-row-count mask would otherwise be silently accepted:
            # XLA clamps the out-of-bounds own-row gather, so extra slices
            # would inherit the LAST row's liveness instead of erroring
            raise ValueError(
                f"slice_live has {slice_live.shape[0]} slice rows but the "
                f"mesh has {n_slices} slices"
            )
        slice_gate = slice_live is not None
        # quorum machinery exists iff a floor above 1 is configured AND the
        # mask is fed — min_slices with no mask adds nothing (S005
        # "slicefaults-off")
        quorum_on = slice_gate and min_slices > 1
        sl_own_rounds = quorum_rounds = None
        if slice_gate:
            sl_full = slice_live[:, :rounds].astype(jnp.float32)
            sl_own_rounds = sl_full[jax.lax.axis_index(SLICE_AXIS)]
            if quorum_on:
                quorum_rounds = jnp.sum(sl_full, axis=0)
        # trace-time static gate: the fault machinery (isfinite reduction over
        # the gradient tree, where-freezes/selects on engine state, params,
        # opt state, BN stats) compiles in only when quarantine is enabled OR
        # a liveness mask is fed; quarantine_rounds=-1 with no mask restores
        # the exact pre-robustness program (the bench escape hatch). The
        # buffered-async mode needs the arrival gates, so it implies guard;
        # so does the overlapped-rounds mode (its empty-stash first round is
        # a zero-live-weight round, which only the guarded form holds).
        # the reputation layer needs the health-updating guarded round; so
        # does an attack mask (an attacked round must be skippable/scorable)
        guard = (
            quarantine_rounds >= 0 or live is not None or buffered or overlap
            or reputation or attack_on or slice_gate
        )
        health = state.health  # filled by epoch_fn before any shard_map
        # trace-time static: telemetry accumulators exist iff the epoch was
        # built with telemetry=True (_ensure_aux normalizes the state), so a
        # telemetry-off program carries zero telemetry ops
        telem = state.telemetry is not None
        # modeled per-round PER-DEVICE collective payload — pure shape
        # arithmetic over the gradient pytree, folded in as a constant. On a
        # packed mesh the pack factor k is what makes the figure honest:
        # psum-shaped exchanges reduce over the packed axis before the wire
        # (k-invariant), only the factor gather scales with k — the model is
        # verified against the traced program by checks/semantic.py S002.
        # under personalization the wire carries the SHARED subtree only —
        # the model must charge exactly what ships (S002 proves it)
        wire_tmpl = _eng_grads(state.params)
        wire_b = (
            payload_bytes_of(engine, wire_tmpl, pack=k if packed else 1)
            if telem else 0.0
        )
        # per-tier split (r18): the inter-slice hop's modeled PER-SLICE
        # bytes — 0.0 on single-slice meshes and the vmap fold (no DCN
        # tier); like wire_b a trace-time constant, verified by the sliced
        # semantic cells rather than merely modeled
        dcn_b = (
            dcn_bytes_of(
                engine, wire_tmpl, pack=k,
                sites_per_slice=k * mesh_site_members, slices=n_slices,
            )
            if telem and packed else 0.0
        )

        def _ts_round(ts, gsq, rsq):
            """Per-site accumulator update for this round from the (already
            reduced) squared grad/residual norms — scalars in the classic
            in-vmap form, ``[k]`` vectors in the packed form. ``grad_sq_last``
            keeps the raw value (NaN = "this site blew up", the signal);
            the sums/max take finite rounds only, or one bad round would
            poison them for the rest of the fit. The update-norm slots are
            filled after the (global) optimizer step in ``one_round``."""
            if ts is None:
                return None
            gsq_f = jnp.where(jnp.isfinite(gsq), gsq, 0.0)
            return {
                "dcn_bytes": ts["dcn_bytes"] + dcn_b,
                "grad_sq_last": gsq,
                "grad_sq_max": jnp.maximum(ts["grad_sq_max"], gsq_f),
                "grad_sq_sum": ts["grad_sq_sum"] + gsq_f,
                # held rounds are counted at the quorum-hold select in
                # one_round (this whole update reverts on a held round)
                "held_rounds": ts["held_rounds"],
                "payload_bytes": ts["payload_bytes"] + wire_b,
                "residual_sq_sum": ts["residual_sq_sum"]
                + jnp.where(jnp.isfinite(rsq), rsq, 0.0),
                "rounds": ts["rounds"] + 1,
                "update_sq_last": ts["update_sq_last"],
                "update_sq_sum": ts["update_sq_sum"],
            }

        def one_round(carry, xs):
            (params, batch_stats, opt_state, engine_state, health, telem_st,
             buffers, ov, personal, rng, rnd) = carry
            pz = None
            if use_scan_xs:
                parts = list(xs)
                if inventory is not None:
                    ib = parts.pop(0)  # [k, L, B] — this round's index block
                    if poison_rounds is not None:
                        pz = parts.pop(0)  # [k] — this round's NaN gate
                else:
                    xb, yb, wb = parts[:3]  # [k, L, B, ...] — this round
                    parts = parts[3:]
                lb = (
                    parts.pop(0) if live_rounds is not None
                    else jnp.ones((k,), jnp.float32)
                )
                ab = parts.pop(0) if attack_on else None
                sl_t = parts.pop(0) if slice_gate else None
                q_t = parts.pop(0) if quorum_on else None
            else:
                xb, yb, wb = (
                    jax.lax.dynamic_index_in_dim(a, xs, axis=1, keepdims=False)
                    for a in (x_rounds, y_rounds, w_rounds)
                )
                lb = (
                    jnp.ones((k,), jnp.float32) if live_rounds is None
                    else jax.lax.dynamic_index_in_dim(
                        live_rounds, xs, axis=1, keepdims=False
                    )
                )
                ab = (
                    jax.lax.dynamic_index_in_dim(
                        attack_rounds, xs, axis=1, keepdims=False
                    ) if attack_on else None
                )
                sl_t = (
                    jax.lax.dynamic_index_in_dim(
                        sl_own_rounds, xs, axis=0, keepdims=False
                    ) if slice_gate else None
                )
                q_t = (
                    jax.lax.dynamic_index_in_dim(
                        quorum_rounds, xs, axis=0, keepdims=False
                    ) if quorum_on else None
                )
            if slice_gate:
                # a dead slice == its sites dead: ×1.0 is exact, ×0 masks —
                # everything downstream (engine aggregate, sync-BN, loss,
                # weight renormalization) then excludes the slice exactly
                # like a site-level mask zeroing its band
                lb = lb * sl_t
            if quorum_on:
                # the quorum HOLD gate, decided before any compute: the
                # round's results are computed and then select-reverted —
                # branchless, so any slice-fault pattern is one program
                held = q_t < jnp.float32(min_slices)
                hold_prev = (
                    batch_stats, engine_state, health, telem_st, buffers, ov,
                    personal,
                )
            if overlap:
                # overlapped rounds: tie the stashed (previous-round) payload
                # and this round's batch block into one availability point.
                # The stash aggregation collectives and the gather+forward
                # are data-independent; the barrier keeps XLA from sinking
                # the stash read below the compute, so the latency-hiding
                # scheduler is free to issue collective-start first and hide
                # the ICI/DCN time under phase B (TPUv4 pjit overlap
                # playbook — the async start/done split happens in XLA).
                if inventory is not None:
                    ov, ib = jax.lax.optimization_barrier((ov, ib))
                else:
                    ov, xb = jax.lax.optimization_barrier((ov, xb))
            if inventory is not None:
                # on-device batch gather from the resident inventory — only
                # this round's [k, L, B, ...] block is materialized
                inv_x, inv_y = inventory
                if pz is None:
                    xb, yb, wb = jax.vmap(
                        lambda ex, ey, ixs: _gather_batch(ex, ey, ixs, None)
                    )(inv_x, inv_y, ib)
                else:
                    xb, yb, wb = jax.vmap(_gather_batch)(inv_x, inv_y, ib, pz)
            rng, sub = jax.random.split(rng)

            def site_micro(xs, ys, ws, ab_site=None, pr_site=None):
                """One site's micro-batch gradient phase — shared by the
                packed and classic forms (always under the inner vmap;
                ``axis_index`` linearizes to the global, device-major site id
                for the dropout-RNG fold, so packed and unpacked runs draw
                identical keys). ``ab_site`` is this site's attack code for
                the round (robustness/attacks.py) — the byzantine transform
                applies to the finished round gradient, before any engine
                compression, keyed by the GLOBAL site id and round so the
                attack replays bit-identically across topologies.
                ``pr_site`` is this site's personalized head subtree (r20,
                privacy/personalize.py) — the forward runs on the merged
                params, so the gradient covers head AND shared leaves (the
                apply half partitions them). The DP-SGD transform (r20,
                privacy/dpsgd.py) runs on the finished round gradient
                BEFORE the attack: an honest site privatizes what it ships,
                a hostile one lies about the privatized quantity."""
                site_ix = jax.lax.axis_index(site_axes)
                p_site = params
                if pr_site is not None:
                    from ..privacy.personalize import merge_head

                    p_site = merge_head(params, pr_site)

                def micro(acc, mb):
                    g_sum, n_sum, stats = acc
                    xm, ym, wm, i = mb
                    key_i = jax.random.fold_in(jax.random.fold_in(sub, site_ix), i)
                    (loss, new_stats), grads = grad_fn(p_site, stats, key_i, xm, ym, wm)
                    if model_axis is not None:
                        # assemble the full gradient (and un-mask the loss
                        # scalar) from the per-member pieces — see loss_fn
                        grads = jax.lax.psum(grads, model_axis)
                        loss = jax.lax.psum(loss, model_axis)
                    n = wm.sum()
                    g_sum = jax.tree.map(lambda a, g: a + g * n, g_sum, grads)
                    return (g_sum, n_sum + n, new_stats), loss * n

                g0 = jax.tree.map(jnp.zeros_like, p_site)
                (g_sum, n_sum, new_stats), loss_sums = jax.lax.scan(
                    micro,
                    (g0, jnp.zeros(()), batch_stats),
                    (xs, ys, ws, jnp.arange(local_iterations)),
                )
                site_grad = jax.tree.map(
                    lambda g: g / jnp.maximum(n_sum, 1.0), g_sum
                )
                if dp is not None:
                    site_grad = dp(site_grad, rnd, site_ix)
                if attack_on:
                    site_grad = atk(site_grad, ab_site, rnd, site_ix)
                return site_grad, n_sum, new_stats, loss_sums.sum()

            def _ts_round_site(ts, site_grad, agg):
                """Classic (in-vmap) accumulator update: scalar norms per
                site, reduced in tree order (telemetry.metrics.tree_sq_sum —
                the host-recompute tests depend on that order). The residual
                covers the SHARED (shipped) subtree — see packed_apply's
                res_sq note; identical trees when personalization is off."""
                if ts is None:
                    return None
                return _ts_round(
                    ts,
                    tree_sq_sum(site_grad),
                    tree_sq_sum(jax.tree.map(
                        lambda g, a: g - a,
                        _eng_grads(site_grad), _eng_grads(agg),
                    )),
                )

            def _rows_sq_sum(tree):
                """Per-virtual-site Σx² over a [k, …]-leading pytree — the
                batched twin of tree_sq_sum, same f32 leaf-order
                accumulation, one [k] vector out."""
                s = jnp.zeros((k,), jnp.float32)
                for leaf in jax.tree.leaves(tree):
                    s = s + jnp.sum(
                        jnp.square(leaf.astype(jnp.float32)).reshape(k, -1),
                        axis=1,
                    )
                return s

            def _per_site(vec, like):
                """Broadcast a [k] per-virtual-site gate against a [k, …]
                leaf."""
                return vec.reshape((k,) + (1,) * (like.ndim - 1))

            # -- fault-pipeline pieces shared by the packed ([k]-vector) and
            # classic (in-vmap scalar) round forms. ONE definition of the
            # liveness/quarantine/loss semantics — only the collective
            # placement (two_level_psum outside the vmap vs lax.psum inside
            # it) stays in the two callers below.

            def _liveness_gate(ls, site_grad, hs, rows=None):
                """scheduled-live AND finite AND not quarantined. ``rows``
                None = scalar per site (under the inner vmap); ``rows=k`` =
                one [k] vector over the device's virtual-site block."""
                if rows is None:
                    finite = jnp.array(True)
                    for leaf in jax.tree.leaves(site_grad):
                        finite &= jnp.isfinite(leaf).all()
                else:
                    finite = jnp.ones((rows,), bool)
                    for leaf in jax.tree.leaves(site_grad):
                        finite &= jnp.isfinite(leaf).reshape(rows, -1).all(axis=1)
                contribute = (
                    ls * finite.astype(jnp.float32)
                    * (1.0 - (hs["quarantined"] > 0).astype(jnp.float32))
                )
                return finite, contribute

            def _freeze_dead(new_tree, old_tree, gate):
                """Hold a dead site's state for the round: its error-feedback
                residual / warm-start subspace must resume where it left off
                when the site returns, not absorb a round it never
                participated in. ``gate(leaf)`` broadcasts the contribute
                mask against a leaf (identity for scalars-in-vmap,
                ``_per_site`` for [k, …] blocks)."""
                return jax.tree.map(
                    lambda new, old: jnp.where(gate(new), new, old),
                    new_tree, old_tree,
                )

            def _deposit(bf, site_grad, n_sum, arrived, gate):
                """Buffered-async arrival: a contributing site deposits this
                round's fresh gradient + weight and resets its age; everyone
                else's buffer survives and ages one round. ``arrived`` is the
                bool arrival mask (scalar per site under the inner vmap, [k]
                on the packed block); ``gate(leaf)`` broadcasts it against a
                gradient leaf — the same shape-polymorphic convention as
                ``_freeze_dead``. Only FINITE gradients are ever deposited
                (arrival requires finiteness), so the buffers stay NaN-free
                by construction."""
                return {
                    "grads": jax.tree.map(
                        lambda g, b: jnp.where(gate(g), g, b),
                        site_grad, bf["grads"],
                    ),
                    "weight": jnp.where(arrived, n_sum, bf["weight"]),
                    "age": jnp.where(arrived, 0, bf["age"] + 1),
                }

            def _personal_apply(pr, site_grad, gate, batched):
                """Per-site personalized-head update (r20): each site's own
                optimizer row advances on its own head gradient — heads
                never enter the engine aggregate. ``gate(leaf)`` broadcasts
                the round's contribute mask like :func:`_freeze_dead`
                (None = the unguarded program: always update); ``batched``
                selects the packed [k]-leading form (rows vmapped) vs the
                classic in-vmap scalar form."""
                if pr is None:
                    return pr
                from ..privacy.personalize import strip_tree as _strip

                hg = _strip(site_grad, head_paths, keep_head=True)

                def upd(hp, ho, g):
                    u, no = optimizer.update(g, ho, hp)
                    return optax.apply_updates(hp, u), no

                if batched:
                    new_p, new_o = jax.vmap(upd)(pr["params"], pr["opt"], hg)
                else:
                    new_p, new_o = upd(pr["params"], pr["opt"], hg)
                if gate is None:
                    return {"params": new_p, "opt": new_o}

                def keep(new, old):
                    return jnp.where(gate(new), new, old)

                return {
                    "params": jax.tree.map(keep, new_p, pr["params"]),
                    "opt": jax.tree.map(keep, new_o, pr["opt"]),
                }

            def _round_loss(loss_sum, contribute, total_live, psum):
                """Round-weighted global loss over LIVE sites (for logs);
                NaN-safe: a dead site's loss sum is where-excluded. An
                all-dead round has no training loss — report NaN, not a
                spurious 0.0 that would drag the epoch mean down (the
                trainer nan-means per-round losses into the epoch figure)."""
                return jnp.where(
                    total_live > 0,
                    psum(jnp.where(contribute > 0, loss_sum, 0.0))
                    / jnp.maximum(total_live, 1.0),
                    jnp.nan,
                )

            def _health_round(hs, finite, contribute):
                """Health counters: streak of consecutive non-finite rounds,
                sticky quarantine once it reaches the threshold, lifetime
                skip count — elementwise, so the same code serves the scalar
                and [k]-vector forms."""
                streak = jnp.where(finite, 0, hs["streak"] + 1)
                quarantined = hs["quarantined"]
                if quarantine_rounds > 0:
                    quarantined = jnp.maximum(
                        quarantined,
                        (streak >= quarantine_rounds).astype(jnp.int32),
                    )
                return {
                    "streak": streak,
                    "skips": hs["skips"] + (contribute <= 0).astype(jnp.int32),
                    "quarantined": quarantined,
                }

            def _reputation_round(hs_prev, hs_new, dsq, nsq, contribute, rsum):
                """Anomaly-scored reputation (r17): z-scores of this round's
                distance-to-(robust)-aggregate and gradient norm across the
                LIVE cohort — cross-site exchange is four scalar psums, so
                the engines' wire models are untouched. A live site whose
                max z exceeds ``reputation_z`` extends its suspect streak;
                ``reputation_rounds`` CONSECUTIVE suspect rounds latch the
                same sticky quarantine flag a NaN streak does. A site
                sitting the round out (drop, straggle, quarantine) holds
                both its streak and its EMA score — absence is not
                evidence either way. Elementwise over the scalar and
                [k]-vector forms like :func:`_health_round`."""
                livef = (contribute > 0).astype(jnp.float32)
                n_live = jnp.maximum(rsum(livef), 1.0)

                def z_of(x):
                    xf = jnp.where(livef > 0, x, 0.0)
                    m1 = rsum(xf) / n_live
                    m2 = rsum(xf * xf) / n_live
                    std = jnp.sqrt(jnp.maximum(m2 - m1 * m1, 0.0))
                    return (x - m1) / jnp.maximum(std, 1e-12)

                # norms, not squares: closer to Gaussian, so the z threshold
                # means the same thing across engines and models. A
                # non-finite site's NaN score propagates to z = NaN, which
                # fails every comparison — it is scored by the NaN streak
                # machinery, not the reputation layer.
                z = jnp.maximum(
                    z_of(jnp.sqrt(jnp.maximum(dsq, 0.0))),
                    z_of(jnp.sqrt(jnp.maximum(nsq, 0.0))),
                )
                suspect = (z > reputation_z) & (contribute > 0)
                streak = jnp.where(
                    suspect, hs_prev["suspect_streak"] + 1,
                    jnp.where(contribute > 0, 0, hs_prev["suspect_streak"]),
                )
                quarantined = hs_new["quarantined"]
                if reputation_rounds > 0:
                    quarantined = jnp.maximum(
                        quarantined,
                        (streak >= reputation_rounds).astype(jnp.int32),
                    )
                anomaly = jnp.where(
                    contribute > 0,
                    0.9 * hs_prev["anomaly"] + 0.1 * jnp.maximum(z, 0.0),
                    hs_prev["anomaly"],
                )
                return {
                    **hs_new, "suspect_streak": streak,
                    "quarantined": quarantined, "anomaly": anomaly,
                }

            def packed_apply(hs, ts, bf, pr, ls, es, site_grad, n_sum,
                             stats_k, loss_site):
                """The communicate/apply half of the two-level round, on an
                already-computed per-site payload: engine aggregate, sync-BN,
                round loss and health on the [k]-batched block with
                PackedAxis collectives — one cross-device collective per
                payload, k-invariant psum wire. In the overlapped-rounds
                mode the payload comes from the previous round's stash
                instead of this round's fresh gradients. Under
                personalization the engine sees (and ships) the SHARED
                subtree only; head gradients update each site's own
                ``pr`` row."""
                gsq = _rows_sq_sum(site_grad) if ts is not None else None
                if not guard:
                    agg, es_new = engine_aggregate(
                        _eng_grads(site_grad), es, n_sum, pax, None, rnd
                    )
                    agg = _full_agg(agg)
                    pr_new = _personal_apply(pr, site_grad, None, batched=True)
                    if task.has_batch_stats:
                        scale = site_weight_scale(n_sum, pax)
                        stats_out = jax.tree.map(
                            lambda s: weighted_site_sum(s, scale, pax).astype(
                                s.dtype
                            ),
                            stats_k,
                        )
                    else:
                        stats_out = batch_stats
                    loss_round = two_level_psum(loss_site, pax) / jnp.maximum(
                        two_level_psum(n_sum, pax), 1.0
                    )
                    ts_new = (
                        None if ts is None
                        else _ts_round(
                            ts, gsq,
                            _rows_sq_sum(jax.tree.map(
                                lambda g, a: g - a[None],
                                _eng_grads(site_grad), _eng_grads(agg),
                            )),
                        )
                    )
                    return (agg, es_new, hs, ts_new, bf, pr_new, stats_out,
                            loss_round, None)
                finite, contribute = _liveness_gate(ls, site_grad, hs, rows=k)
                n_eff = n_sum * contribute
                if buffered:
                    # buffered-async: arrivals deposit, everyone aggregates
                    # from the buffers at staleness-decayed weight; the
                    # engine's collectives (and therefore the S002-proven
                    # wire) are identical to the bulk-sync form
                    arrived = contribute > 0
                    bf = _deposit(
                        bf, site_grad, n_sum, arrived,
                        lambda leaf: _per_site(arrived, leaf),
                    )
                    stale_w = staleness_weights(
                        bf["age"], staleness_bound, staleness_decay
                    )
                    eff_w = bf["weight"] * stale_w
                    agg, es_new = engine_aggregate(
                        _eng_grads(bf["grads"]), es, eff_w, pax,
                        (stale_w > 0).astype(jnp.float32), rnd,
                    )
                    agg = _full_agg(agg)
                    es_new = _freeze_dead(
                        es_new, es, lambda leaf: _per_site(stale_w > 0, leaf)
                    )
                    # params-hold gate: total in-bound buffered weight; the
                    # loss/BN gates stay keyed on FRESH arrivals below.
                    # Heads update from FRESH arrivals only — they are not
                    # buffered (a head never leaves its site, so there is
                    # no in-flight copy to age).
                    total_live = two_level_psum(eff_w, pax)
                    total_fresh = two_level_psum(n_eff, pax)
                else:
                    agg, es_new = engine_aggregate(
                        _eng_grads(site_grad), es, n_sum, pax, contribute,
                        rnd,
                    )
                    agg = _full_agg(agg)
                    es_new = _freeze_dead(
                        es_new, es, lambda leaf: _per_site(contribute > 0, leaf)
                    )
                    total_live = two_level_psum(n_eff, pax)
                    total_fresh = total_live
                pr_new = _personal_apply(
                    pr, site_grad,
                    lambda leaf: _per_site(contribute > 0, leaf), batched=True,
                )
                if task.has_batch_stats:
                    scale = site_weight_scale(n_eff, pax)
                    zeroed = jax.tree.map(
                        lambda s: jnp.where(
                            _per_site(contribute > 0, s), s, jnp.zeros_like(s)
                        ),
                        stats_k,
                    )
                    syn = jax.tree.map(
                        lambda s: weighted_site_sum(s, scale, pax).astype(
                            s.dtype
                        ),
                        zeroed,
                    )
                    stats_out = jax.tree.map(
                        lambda sn, old: jnp.where(total_fresh > 0, sn, old),
                        syn, batch_stats,
                    )
                else:
                    stats_out = batch_stats
                loss_round = _round_loss(
                    loss_site, contribute, total_fresh,
                    lambda v: two_level_psum(v, pax),
                )
                hs_new = _health_round(hs, finite, contribute)
                # ONE distance-to-aggregate figure serves both consumers:
                # the reputation z-score and the telemetry residual
                # ONE distance-to-aggregate figure serves both consumers —
                # computed over the SHARED (shipped) subtree: under
                # personalization a site's legitimately-divergent head
                # gradient never reaches the engine, so it must count
                # neither as compression residual nor as reputation anomaly
                res_sq = (
                    _rows_sq_sum(jax.tree.map(
                        lambda g, a: g - a[None],
                        _eng_grads(site_grad), _eng_grads(agg),
                    ))
                    if (reputation or ts is not None) else None
                )
                if reputation:
                    hs_new = _reputation_round(
                        hs, hs_new, res_sq,
                        _rows_sq_sum(_eng_grads(site_grad)),
                        contribute, lambda v: two_level_psum(v, pax),
                    )
                ts_new = (
                    None if ts is None else _ts_round(ts, gsq, res_sq)
                )
                return (agg, es_new, hs_new, ts_new, bf, pr_new, stats_out,
                        loss_round, total_live)

            def packed_round(hs, ts, bf, pr, ls, es):
                """The two-level round: per-site grads under the inner vmap,
                then :func:`packed_apply` on this round's fresh payload.
                (None arguments — no attack mask, no personal rows — are
                empty pytrees; vmap maps nothing over them.)"""
                site_grad, n_sum, stats_k, loss_site = jax.vmap(
                    site_micro, axis_name=inner_axis
                )(xb, yb, wb, ab, None if pr is None else pr["params"])
                return packed_apply(
                    hs, ts, bf, pr, ls, es, site_grad, n_sum, stats_k,
                    loss_site,
                )

            def site_apply(es, hs, ts, bf, pr, ls, site_grad, n_sum,
                           new_stats, loss_sum):
                """The communicate/apply half of the classic (in-vmap) round
                on an already-computed per-site payload — the scalar twin of
                :func:`packed_apply`."""
                if not guard:
                    # fault machinery statically compiled out: the exact
                    # legacy round (no finite check, no selects, no counters)
                    agg, es_new = engine_aggregate(
                        _eng_grads(site_grad), es, n_sum, site_axes, None,
                        rnd,
                    )
                    agg = _full_agg(agg)
                    pr_new = _personal_apply(
                        pr, site_grad, None, batched=False
                    )
                    if task.has_batch_stats:
                        scale = site_weight_scale(n_sum, site_axes)
                        new_stats = jax.tree.map(
                            lambda s: jax.lax.psum(s * scale, site_axes),
                            new_stats,
                        )
                    loss_round = jax.lax.psum(
                        loss_sum, site_axes
                    ) / jnp.maximum(jax.lax.psum(n_sum, site_axes), 1.0)
                    return (agg, es_new, hs, _ts_round_site(ts, site_grad, agg),
                            bf, pr_new, new_stats, loss_round, None)
                # -- liveness: a poisoned batch (data corruption, overflow,
                # fault injection) yields a non-finite site gradient; that
                # site is skipped this round and its streak counter advances
                # toward quarantine. All jnp.where / traced — no
                # recompilation.
                finite, contribute = _liveness_gate(ls, site_grad, hs)
                n_eff = n_sum * contribute
                if buffered:
                    # buffered-async (scalar-per-site twin of packed_round's
                    # branch): deposit on arrival, aggregate the buffers at
                    # staleness-decayed weight
                    arrived = contribute > 0
                    bf = _deposit(
                        bf, site_grad, n_sum, arrived, lambda _: arrived
                    )
                    stale_w = staleness_weights(
                        bf["age"], staleness_bound, staleness_decay
                    )
                    eff_w = bf["weight"] * stale_w
                    agg, es_new = engine_aggregate(
                        _eng_grads(bf["grads"]), es, eff_w, site_axes,
                        (stale_w > 0).astype(jnp.float32), rnd,
                    )
                    agg = _full_agg(agg)
                    es_new = _freeze_dead(es_new, es, lambda _: stale_w > 0)
                    total_live = jax.lax.psum(eff_w, site_axes)
                    total_fresh = jax.lax.psum(n_eff, site_axes)
                else:
                    agg, es_new = engine_aggregate(
                        _eng_grads(site_grad), es, n_sum, site_axes,
                        contribute, rnd,
                    )
                    agg = _full_agg(agg)
                    es_new = _freeze_dead(es_new, es, lambda _: contribute > 0)
                    total_live = jax.lax.psum(n_eff, site_axes)
                    total_fresh = total_live
                pr_new = _personal_apply(
                    pr, site_grad, lambda _: contribute > 0, batched=False
                )
                # sync-BN: example-weighted average of FRESHLY-ARRIVED sites'
                # running stats (dead sites' stats may be NaN → where-zeroed,
                # and their weight is already 0); a round with no arrivals
                # keeps the previous stats (stats are not buffered)
                if task.has_batch_stats:
                    scale = site_weight_scale(n_eff, site_axes)
                    new_stats = jax.tree.map(
                        lambda s: jnp.where(contribute > 0, s, jnp.zeros_like(s)),
                        new_stats,
                    )
                    new_stats = jax.tree.map(
                        lambda s: jax.lax.psum(s * scale, site_axes), new_stats
                    )
                    new_stats = jax.tree.map(
                        lambda syn, old: jnp.where(total_fresh > 0, syn, old),
                        new_stats, batch_stats,
                    )
                loss_round = _round_loss(
                    loss_sum, contribute, total_fresh,
                    lambda v: jax.lax.psum(v, site_axes),
                )
                hs_new = _health_round(hs, finite, contribute)
                if reputation:
                    hs_new = _reputation_round(
                        hs, hs_new,
                        tree_sq_sum(jax.tree.map(
                            lambda g, a: g - a,
                            _eng_grads(site_grad), _eng_grads(agg),
                        )),
                        tree_sq_sum(_eng_grads(site_grad)),
                        contribute,
                        lambda v: jax.lax.psum(v, site_axes),
                    )
                return (agg, es_new, hs_new, _ts_round_site(ts, site_grad, agg),
                        bf, pr_new, new_stats, loss_round, total_live)

            def site_part(es, hs, ts, bf, pr, ls, xs, ys, ws, ab_site=None):
                site_grad, n_sum, new_stats, loss_sum = site_micro(
                    xs, ys, ws, ab_site,
                    None if pr is None else pr["params"],
                )
                return site_apply(
                    es, hs, ts, bf, pr, ls, site_grad, n_sum, new_stats,
                    loss_sum,
                )

            if overlap:
                # -- overlapped rounds (r14): phase B computes THIS round's
                # per-site gradients at the carried (pre-update) params;
                # phase A aggregates and applies the STASHED previous round.
                # The two phases share no data, so the stash collectives
                # overlap the gather+forward in the XLA schedule (barrier
                # above). Health/telemetry are valid-gated: the empty-stash
                # first round must not count skips or accumulate rounds.
                fresh_grad, fresh_n, fresh_stats, fresh_loss = jax.vmap(
                    site_micro, axis_name=inner_axis
                )(xb, yb, wb, ab,
                  None if personal is None else personal["params"])
                ls_prev = ov["live"] * ov["valid"]
                if packed:
                    (agg, es_new, hs_new, ts_new, buffers, personal,
                     batch_stats, loss_round, total_live) = packed_apply(
                        health, telem_st, buffers, personal, ls_prev,
                        engine_state,
                        ov["grads"], ov["weight"], ov["stats"], ov["loss"],
                    )
                else:
                    (agg, es_new, hs_new, ts_new, buffers, personal, stats_k,
                     loss_k, tl_k) = jax.vmap(
                        site_apply,
                        in_axes=(0,) * 10,
                        out_axes=(0,) * 9,
                        axis_name=inner_axis,
                    )(engine_state, health, telem_st, buffers, personal,
                      ls_prev, ov["grads"], ov["weight"], ov["stats"],
                      ov["loss"])
                    agg = jax.tree.map(lambda a: a[0], agg)
                    batch_stats = jax.tree.map(lambda a: a[0], stats_k)
                    loss_round = loss_k[0]
                    total_live = tl_k[0]
                vgate = ov["valid"] > 0
                engine_state = es_new
                health = jax.tree.map(
                    lambda new, old: jnp.where(vgate, new, old), hs_new, health
                )
                telem_k = (
                    None if telem_st is None else jax.tree.map(
                        lambda new, old: jnp.where(vgate, new, old),
                        ts_new, telem_st,
                    )
                )
                # refill the stash with this round's fresh payload — its
                # aggregation is issued at the NEXT scan step (or the next
                # epoch's first round: the stash rides TrainState)
                ov = {
                    "grads": fresh_grad,
                    "stats": fresh_stats,
                    "weight": fresh_n,
                    "loss": fresh_loss,
                    "live": lb,
                    "valid": jnp.ones((k,), jnp.float32),
                }
            elif packed:
                # mesh topologies: the two-level form — engine/BN/loss
                # collectives run ONCE per device on the [k]-batched block
                # (agg/stats/loss come back unbatched and replicated)
                (agg, engine_state, health, telem_k, buffers, personal,
                 batch_stats, loss_round, total_live) = packed_round(
                    health, telem_st, buffers, personal, lb, engine_state
                )
            else:
                (agg, engine_state, health, telem_k, buffers, personal,
                 stats_k, loss_k, tl_k) = jax.vmap(
                    site_part, in_axes=(0,) * 10,
                    out_axes=(0,) * 9, axis_name=inner_axis,
                )(engine_state, health, telem_st, buffers, personal, lb,
                  xb, yb, wb, ab)
                # agg/stats/loss are psum'd over site_axes → identical across
                # the k in-device rows; collapse to one copy and update once
                agg = jax.tree.map(lambda a: a[0], agg)
                batch_stats = jax.tree.map(lambda a: a[0], stats_k)
                loss_round = loss_k[0]
                total_live = tl_k[0] if guard else None
            if quorum_on:
                # slice-quorum HOLD (r19): below min_slices live slices the
                # round never happened — every carried piece reverts to its
                # pre-round value (params/opt freeze through the zeroed
                # total_live below), the loss reports NaN like an all-dead
                # round, and the per-site held_rounds accumulator counts it
                def _hold(new, old):
                    return jax.tree.map(
                        lambda n, o: jnp.where(held, o, n), new, old
                    )

                st0, es0, hs0, ts0, bf0, ov0, pr0 = hold_prev
                batch_stats = _hold(batch_stats, st0)
                engine_state = _hold(engine_state, es0)
                health = _hold(health, hs0)
                if personal is not None:
                    personal = _hold(personal, pr0)
                if telem_k is not None:
                    telem_k = _hold(telem_k, ts0)
                    telem_k = {
                        **telem_k,
                        "held_rounds": telem_k["held_rounds"]
                        + held.astype(jnp.int32),
                    }
                if buffers is not None:
                    buffers = _hold(buffers, bf0)
                if ov is not None:
                    ov = _hold(ov, ov0)
                loss_round = jnp.where(held, jnp.nan, loss_round)
                total_live = jnp.where(
                    held, jnp.zeros_like(total_live), total_live
                )
            updates, new_opt_state = optimizer.update(agg, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            if guard:
                # a round with zero live weight advances nothing: params AND
                # optimizer state hold (Adam's moment decay on a zero
                # gradient would otherwise drift the update direction)
                params = jax.tree.map(
                    lambda new, old: jnp.where(total_live > 0, new, old),
                    new_params, params,
                )
                opt_state = jax.tree.map(
                    lambda new, old: jnp.where(total_live > 0, new, old),
                    new_opt_state, opt_state,
                )
            else:
                params, opt_state = new_params, new_opt_state
            if telem:
                # the applied optimizer update's squared norm — global (the
                # update is replicated), broadcast into every site's row; a
                # zero-live round applied nothing, so it records 0
                usq = tree_sq_sum(updates)
                if guard:
                    usq = jnp.where(total_live > 0, usq, 0.0)
                telem_k = {
                    **telem_k,
                    "update_sq_last": jnp.zeros_like(
                        telem_k["update_sq_last"]
                    ) + usq,
                    "update_sq_sum": telem_k["update_sq_sum"] + usq,
                }
            return (
                params, batch_stats, opt_state, engine_state, health,
                telem_k, buffers, ov, personal, rng, rnd + 1,
            ), loss_round

        carry0 = (
            state.params,
            state.batch_stats,
            state.opt_state,
            state.engine_state,
            health,
            state.telemetry,
            state.buffers,
            state.overlap,
            state.personal,
            jax.random.fold_in(state.rng, state.round),
            state.round,
        )
        # Scan over rounds-LEADING xs instead of dynamic-indexing axis 1 of
        # the resident arrays per round: lax.scan slices its xs' leading
        # axis, which is contiguous, and under compile_epoch_aot's AUTO
        # input layouts XLA can choose a rounds-major storage order that
        # makes the moveaxis a layout assignment rather than a copy
        # (interleaved A/B on the flagship: +9.5%/+21%,
        # docs/bench_scanxs_ab_r5.jsonl; the r4 profile showed the strided
        # per-round slice costing 3-7x its raw bytes). Without AOT layouts
        # (plain jit, as the Trainer uses) the moveaxis may materialize one
        # whole-epoch copy — no more bytes MOVED than the strided slices it
        # replaces, but the copy coexists with the (non-donated) original,
        # so peak HBM residency grows by ~1x the epoch-input size. For
        # epoch inputs big enough for that to matter (multi-GB), pass
        # rounds_scan_xs=False.
        if use_scan_xs:
            if inventory is not None:
                xs = (jnp.moveaxis(x_rounds, 1, 0),)
                if poison_rounds is not None:
                    xs = xs + (jnp.moveaxis(poison_rounds, 1, 0),)
            else:
                xs = tuple(
                    jnp.moveaxis(a, 1, 0)
                    for a in (x_rounds, y_rounds, w_rounds)
                )
            if live_rounds is not None:
                xs = xs + (jnp.moveaxis(live_rounds, 1, 0),)
            if attack_rounds is not None:
                xs = xs + (jnp.moveaxis(attack_rounds, 1, 0),)
            if slice_gate:
                # own-slice gate + (quorum on) live-slice count, one scalar
                # each per round — already rounds-leading
                xs = xs + (sl_own_rounds,)
                if quorum_on:
                    xs = xs + (quorum_rounds,)
        else:
            xs = jnp.arange(rounds)
        (params, stats, opt_state, engine_state, health, telem_out, buf_out,
         ov_out, pr_out, rng, rnd), losses = jax.lax.scan(one_round, carry0, xs)
        new_state = TrainState(
            params=params,
            batch_stats=stats,
            opt_state=opt_state,
            engine_state=engine_state,
            rng=state.rng,
            round=rnd,
            health=health,
            telemetry=telem_out,
            buffers=buf_out,
            overlap=ov_out,
            personal=pr_out,
        )
        return new_state, losses

    def _ensure_health(state: TrainState, inputs) -> TrainState:
        # states built by pre-0.3 code paths carry health=None (or, like
        # dSGD's leafless engine state, a site count the data overrides);
        # fill fresh counters at the jit boundary so specs/carry structures
        # are uniform. Counters only survive when the site count matches —
        # per-site bookkeeping is meaningless across a site-count change.
        if (
            state.health is None
            or state.health["streak"].shape[0] != inputs.shape[0]
        ):
            state = state.replace(
                health=default_health(inputs.shape[0], reputation=reputation)
            )
        # the reputation fields (r17) mirror the robust_agg flag this epoch
        # was built with, same trace-time normalization as telemetry: a
        # robust run resumed from a legacy checkpoint gains fresh zero
        # scores (the 3 legacy counters survive), a legacy run resumed from
        # a robust checkpoint drops them — the program form is stable per
        # flag either way
        elif reputation and "suspect_streak" not in state.health:
            from ..robustness.health import reputation_fields

            state = state.replace(health={
                **state.health,
                **reputation_fields(state.health["streak"].shape[0]),
            })
        elif not reputation and "suspect_streak" in state.health:
            from ..robustness.health import REPUTATION_KEYS

            state = state.replace(health={
                k: v for k, v in state.health.items()
                if k not in REPUTATION_KEYS
            })
        # telemetry accumulators mirror the flag this epoch was built with:
        # off drops any carried accumulators (a checkpoint from a telemetry
        # run resumed with telemetry off — the program stays the legacy
        # one), on fills/resizes them like health. Trace-time structure
        # normalization, so the compiled form is stable per flag.
        if not telemetry:
            if state.telemetry is not None:
                state = state.replace(telemetry=None)
        elif (
            state.telemetry is None
            or state.telemetry["rounds"].shape[0] != inputs.shape[0]
            # key-set drift (e.g. a pre-r18 checkpoint without the per-tier
            # dcn_bytes accumulator): refill fresh — per-site sums are
            # meaningless across a schema change anyway
            or set(state.telemetry) != set(TELEMETRY_KEYS)
        ):
            state = state.replace(
                telemetry=default_round_telemetry(inputs.shape[0])
            )
        # staleness buffers mirror the bound this epoch was built with, same
        # trace-time normalization: bound 0 drops any carried buffers (an
        # async checkpoint resumed in bulk-sync mode — the program stays the
        # legacy one), bound > 0 fills/resizes fresh never-deposited buffers
        if not buffered:
            if state.buffers is not None:
                state = state.replace(buffers=None)
        elif (
            state.buffers is None
            or state.buffers["age"].shape[0] != inputs.shape[0]
        ):
            state = state.replace(
                buffers=default_async_buffers(inputs.shape[0], state.params)
            )
        # the overlap stash mirrors the overlap_rounds flag the same
        # trace-time way: off drops any carried stash (an overlapped fit's
        # checkpoint resumed in the plain mode — the program stays legacy,
        # the in-flight round is dropped once), on fills/resizes an EMPTY
        # (valid=0) stash whose first round applies nothing
        if not overlap:
            if state.overlap is not None:
                state = state.replace(overlap=None)
        elif (
            state.overlap is None
            or state.overlap["valid"].shape[0] != inputs.shape[0]
        ):
            state = state.replace(
                overlap=default_overlap_stash(
                    inputs.shape[0], state.params, state.batch_stats
                )
            )
        # personalized-head rows mirror the personalize patterns this epoch
        # was built with, same trace-time normalization: off drops any
        # carried rows (a personalized checkpoint resumed plain — the
        # program stays legacy), on fills/resizes fresh rows seeded from
        # the CURRENT global params (a new cohort size starts every head
        # from the common model)
        if not personal_on:
            if state.personal is not None:
                state = state.replace(personal=None)
        elif (
            state.personal is None
            or jax.tree.leaves(state.personal["params"])[0].shape[0]
            != inputs.shape[0]
        ):
            from ..privacy.personalize import (
                default_personal,
                head_leaf_paths,
            )

            state = state.replace(personal=default_personal(
                inputs.shape[0], state.params,
                head_leaf_paths(state.params, personalize), optimizer,
            ))
        return state

    # donate the carried state's buffers to the epoch program: the update
    # aliases in place instead of allocating a second params+opt copy. The
    # caller contract (rebind, snapshot what you keep) is documented above.
    jit_kw = {"donate_argnums": (0,)} if donate_state else {}

    if pipeline == "device" and mesh is not None:

        def epoch_fn_impl(state: TrainState, inv_x, inv_y, idx, live=None,
                          poison=None, attack=None, slice_live=None):
            state = _ensure_health(state, idx)
            specs = _state_specs(state, site_part)
            # optional traced inputs (liveness / NaN gate / attack codes /
            # slice mask): trace-time presence branches, one compiled
            # program per form — a fit feeds a fixed form, so the compile
            # counter still sees one program
            extras = [a for a in (live, poison, attack) if a is not None]
            extra_specs = [P(site_part)] * len(extras)
            if slice_live is not None:
                # the [num_slices, rounds] whole-slice mask rides
                # REPLICATED (tiny); members index their own slice's row
                extras.append(slice_live)
                extra_specs.append(P())
            has_live, has_poison = live is not None, poison is not None
            has_attack = attack is not None
            has_slice = slice_live is not None
            axes = (
                (SLICE_AXIS, SITE_AXIS, FOLD_AXIS) if sliced
                else (SITE_AXIS, FOLD_AXIS)
            )

            def wrapped(st, ex, ey, ix, *opt):
                opt = list(opt)
                lv = opt.pop(0) if has_live else None
                pz = opt.pop(0) if has_poison else None
                ak = opt.pop(0) if has_attack else None
                sm = opt.pop(0) if has_slice else None
                return epoch_over_sites(
                    st, ix, None, None, lv, site_axes=axes,
                    inner_axis=FOLD_AXIS, inventory=(ex, ey), poison=pz,
                    attack=ak, slice_live=sm,
                )

            return shard_map(
                wrapped,
                mesh=mesh,
                in_specs=(specs, P(site_part), P(site_part), P(site_part))
                + tuple(extra_specs),
                out_specs=(specs, P()),
                check_vma=False,
            )(state, inv_x, inv_y, idx, *extras)

        epoch_fn = jax.jit(epoch_fn_impl, **jit_kw)

    elif pipeline == "device":

        def epoch_fn_impl(state: TrainState, inv_x, inv_y, idx, live=None,
                          poison=None, attack=None, slice_live=None):
            # all S sites fold onto the local device: the inner vmap IS the
            # site axis; the gather vmaps over the same leading site dim
            # (slice_live is rejected inside epoch_over_sites — the vmap
            # fold has no slice tier)
            return epoch_over_sites(
                _ensure_health(state, idx), idx, None, None, live,
                site_axes=SITE_AXIS, inner_axis=SITE_AXIS,
                inventory=(inv_x, inv_y), poison=poison, attack=attack,
                slice_live=slice_live,
            )

        epoch_fn = jax.jit(epoch_fn_impl, **jit_kw)

    elif mesh is not None:

        def epoch_fn_impl(state: TrainState, inputs, labels, weights,
                          live=None, attack=None, slice_live=None):
            state = _ensure_health(state, inputs)
            specs = _state_specs(state, site_part)
            has_live, has_attack = live is not None, attack is not None
            has_slice = slice_live is not None
            axes = (
                (SLICE_AXIS, SITE_AXIS, FOLD_AXIS) if sliced
                else (SITE_AXIS, FOLD_AXIS)
            )

            def shard_wrapped(st, x, y, w, *opt):
                # x: [k, steps, B, ...] — this device's block of k sites.
                # k > 1 is the folded case (cfg.sites_per_device: more
                # simulated sites than devices); cross-site collectives span
                # the (mesh site, fold) axis pair — plus the outer slice
                # axis on sliced meshes. k == 1 is the one-site-per-device
                # case, same program.
                opt = list(opt)
                lv = opt.pop(0) if has_live else None
                ak = opt.pop(0) if has_attack else None
                sm = opt.pop(0) if has_slice else None
                return epoch_over_sites(
                    st, x, y, w, lv, site_axes=axes,
                    inner_axis=FOLD_AXIS, attack=ak, slice_live=sm,
                )

            extras = [a for a in (live, attack) if a is not None]
            extra_specs = [P(site_part)] * len(extras)
            if slice_live is not None:
                extras.append(slice_live)
                extra_specs.append(P())
            in_specs = (
                (specs, P(site_part), P(site_part), P(site_part))
                + tuple(extra_specs)
            )
            return shard_map(
                shard_wrapped,
                mesh=mesh,
                in_specs=in_specs,
                out_specs=(specs, P()),
                check_vma=False,
            )(state, inputs, labels, weights, *extras)

        epoch_fn = jax.jit(epoch_fn_impl, **jit_kw)

    else:

        def epoch_fn_impl(state: TrainState, inputs, labels, weights,
                          live=None, attack=None, slice_live=None):
            # all S sites fold onto the local device: the inner vmap IS the
            # site axis (slice_live is rejected inside epoch_over_sites)
            return epoch_over_sites(
                _ensure_health(state, inputs), inputs, labels, weights, live,
                site_axes=SITE_AXIS, inner_axis=SITE_AXIS, attack=attack,
                slice_live=slice_live,
            )

        epoch_fn = jax.jit(epoch_fn_impl, **jit_kw)

    return epoch_fn


def epoch_program_artifacts(epoch_fn, *args, lowered: bool = False,
                            compiled: bool = False):
    """The traced/lowered/compiled forms of one epoch program, for semantic
    auditing (checks/semantic.py): ``(ClosedJaxpr, Lowered | None,
    Compiled | None)``.

    The jaxpr is what rules S001/S002/S004 walk (collective axes, payload
    operand shapes/dtypes, precision flow); the lowering feeds the
    program-identity differ (checks/lowering.py, S005); the compiled
    executable exposes the input-output aliasing S003 proves donation
    against. Tracing only — no execution; safe on CPU for any topology the
    epoch builder supports."""
    trace = getattr(epoch_fn, "trace", None)
    if trace is not None and (lowered or compiled):
        # one trace serves both artifacts (the AOT Traced stage lowers from
        # the jaxpr it already holds); older jax lacks .trace and pays two
        traced = trace(*args)
        closed, low = traced.jaxpr, traced.lower()
    else:
        closed = jax.make_jaxpr(epoch_fn)(*args)
        low = epoch_fn.lower(*args) if (lowered or compiled) else None
    comp = low.compile() if compiled else None
    return closed, low, comp


def compile_epoch_aot(epoch_fn, state: TrainState, x, y, w, live=None,
                      attack=None):
    """AOT-compile an epoch function letting XLA choose the INPUT layout for
    the (large, resident) epoch inputs.

    Fed default-layout inputs, the compiled epoch relayouts + copies the
    whole input array on-device every call (profiled ~8% of the 32-site ICA
    bench epoch); with the input layout AUTO-chosen the copy moves into the
    one-time ``device_put``. Only ``x`` gets AUTO — AUTO on the carried
    ``state`` makes each chained call relayout the state (output layouts are
    default), measured strictly slower.

    Returns ``(compiled, put_x)``: call ``put_x(x)`` once on the resident
    inputs, then ``compiled(state, put_x(x), y, w)`` exactly like
    ``epoch_fn``. Single-device path (``mesh=None``) — the shard_map path
    distributes inputs instead of keeping them resident. Pass ``live``
    (``[S, rounds]``) to compile the fault-injected program (bench
    ``--faults``); the compiled callable then takes it as a fifth argument.
    ``attack`` (``[S, rounds]`` int32, robustness/attacks.py) likewise
    compiles the attack-injected program (bench ``--attacks``) — it rides
    after ``live`` in the positional order, so an attack-only build passes
    ``live=None`` explicitly at call time.
    """
    from ..core.jaxcompat import auto_input_format, input_formats_of

    in_sh = (jax.tree.map(lambda _: None, state), auto_input_format(), None, None)
    args = (state, x, y, w)
    if live is not None or attack is not None:
        in_sh = in_sh + (None,)
        args = args + (live,)
    if attack is not None:
        in_sh = in_sh + (None,)
        args = args + (attack,)
    comp = jax.jit(epoch_fn, in_shardings=in_sh).lower(*args).compile()
    x_fmt = input_formats_of(comp)[0][1]
    return comp, lambda xs: jax.device_put(xs, x_fmt)


def eval_forward(task: FederatedTask, params, batch_stats, x, y=None, w=None):
    """THE per-task inference forward — the single definition both the
    trainer's eval path (:func:`make_eval_fn`) and the serving engine
    (serving/engine.py) compile, so a served checkpoint reproduces the
    trainer's recorded eval scores bit-for-bit on identical batches
    (tests/test_serving.py; the S005 serving identity cell proves the two
    programs lower identically).

    ``x [B, ...]`` is one batch; ``w [B]`` is the per-example valid mask
    (serving's request padding and eval's plan padding share these
    semantics — for batch-stat models like MSANNet the mask also keeps pad
    rows out of the BatchNorm statistics, exactly as in training). With
    labels ``y`` also returns the per-example cross-entropy (the eval loss
    path); ``y=None`` (serving) returns probs only — a trace-time branch,
    so the serving program carries no label ops at all."""
    logits, _ = task.apply(params, batch_stats, x, train=False, mask=w)
    probs = jax.nn.softmax(logits, -1)
    if y is None:
        return probs
    logp = jax.nn.log_softmax(logits, -1)
    ce = -jnp.take_along_axis(logp, y[..., None].astype(jnp.int32), -1)[..., 0]
    return probs, ce


def make_eval_fn(task: FederatedTask, mesh=None, personalize: tuple = ()):
    """Jitted full-pass eval: returns per-site ``probs [S, steps, B, C]``,
    ``loss_sum [S]``, ``weight_sum [S]`` — metric scalars are computed
    host-side (trainer/metrics.py). ``mesh=None`` folds sites via vmap, as in
    :func:`make_train_epoch_fn`. The per-batch forward is
    :func:`eval_forward` — shared verbatim with the serving engine.

    ``personalize`` (r20, privacy/personalize.py): with patterns set AND a
    state carrying ``personal`` rows, each site evaluates on its OWN merged
    head — eval is per-site by construction, so the per-site scores in
    ``logs.json`` measure each site's personalized model. A personalized
    build fed a personal-less state (``mode="test"`` from a params-only
    restore) evaluates the frozen global heads — a trace-time presence
    branch, like every other optional input."""
    # builder kwarg, never a tracer: the static TrainConfig.personalize
    personal_on = bool(tuple(personalize))  # jaxlint: disable=R005

    def per_site_eval(params, batch_stats, x, y, w, head=None):
        if head is not None:
            from ..privacy.personalize import merge_head

            params = merge_head(params, head)

        def step(_, batch):
            xb, yb, wb = batch
            probs, ce = eval_forward(task, params, batch_stats, xb, yb, wb)
            return None, (probs, (ce * wb).sum())

        _, (probs, loss_sums) = jax.lax.scan(step, None, (x, y, w))
        return probs, loss_sums.sum(), w.sum()

    if mesh is not None:
        part = site_axis_of(mesh)  # (slice, site) on sliced meshes (r18)

        @jax.jit
        def eval_fn(state: TrainState, inputs, labels, weights):
            heads = (
                state.personal["params"]
                if personal_on and state.personal is not None else None
            )
            extras = () if heads is None else (heads,)
            extra_specs = () if heads is None else (
                jax.tree.map(lambda _: P(part), heads),
            )
            return shard_map(
                # inner vmap over the device's site block (k ≥ 1 folded sites)
                lambda p, s, x, y, w, *h: jax.vmap(
                    per_site_eval, in_axes=(None, None, 0, 0, 0, 0)
                )(p, s, x, y, w, h[0] if h else None),
                mesh=mesh,
                in_specs=(
                    jax.tree.map(lambda _: P(), state.params),
                    jax.tree.map(lambda _: P(), state.batch_stats),
                    P(part),
                    P(part),
                    P(part),
                ) + extra_specs,
                out_specs=(P(part), P(part), P(part)),
                check_vma=False,
            )(state.params, state.batch_stats, inputs, labels, weights,
              *extras)

    else:

        @jax.jit
        def eval_fn(state: TrainState, inputs, labels, weights):
            heads = (
                state.personal["params"]
                if personal_on and state.personal is not None else None
            )
            return jax.vmap(
                per_site_eval, in_axes=(None, None, 0, 0, 0, 0)
            )(state.params, state.batch_stats, inputs, labels, weights, heads)

    return eval_fn
