"""Program-identity regression harness — the "off == compiled out" claims.

ONE parametrized harness over checks/lowering.py's normalized differ
replaces the ad-hoc ``lowered.as_text() == ...`` comparisons that used to be
duplicated across tests/test_telemetry.py and tests/test_robustness.py:

- every OFF-form (telemetry off, faults at their default resolution, the
  sanitizer's leak-checking observation mode, wire_quant="none", the fused
  power-iteration kernel off, overlap_rounds off) must be lowering-identical
  to the baseline epoch program;
- every static OPT-OUT/OPT-IN (``quarantine_rounds=-1``, ``telemetry=True``,
  a quantized wire codec, the fused kernel, overlapped rounds) must
  genuinely diverge — if these become identical, "compiled out" has
  silently stopped being true.

The same pairs gate the CLI via rule S005
(``python -m dinunet_implementations_tpu.checks --semantic``); this file is
the fast tier-1 mirror with per-pair failure reports. The engine-knob cases
(``{"engine": {...}}``) and the rankDAD corner ride the semantic tier's
``identity_text_fn``/table definitions, so the two gates can never test
different pair sets.
"""

import jax
import pytest

from dinunet_implementations_tpu.checks.lowering import diff_report
from dinunet_implementations_tpu.checks.semantic import (
    IDENTITY_CASES,
    IDENTITY_CASES_RANKDAD,
    RANKDAD_IDENTITY_CELL,
    TraceCell,
    identity_text_fn,
)


@pytest.fixture(scope="module")
def corner():
    """The flagship matrix corner (dSGD / folded sites / host pipeline),
    built by the semantic tier's shared corner builder — the same programs
    the S005 CLI gate compares."""
    text = identity_text_fn(TraceCell("dSGD", "vmap", "host"))
    # the default build's text once, not once per test
    return text(), text


@pytest.fixture(scope="module")
def rankdad_corner():
    """The rankDAD corner the fused-power-iteration pairs run on."""
    text = identity_text_fn(RANKDAD_IDENTITY_CELL)
    return text(), text


def _split(cases):
    identical = {
        label: kw for label, (kw, ident) in cases.items()
        if ident and kw is not None
    }
    divergent = {
        label: kw for label, (kw, ident) in cases.items() if not ident
    }
    return identical, divergent


#: derived from the semantic tier's tables so this harness and the S005 CLI
#: gate can never test different pair sets. kwargs=None is the
#: checking_leaks observation mode (its own test below).
IDENTICAL_CASES, DIVERGENT_CASES = _split(IDENTITY_CASES)
IDENTICAL_RD, DIVERGENT_RD = _split(IDENTITY_CASES_RANKDAD)


@pytest.mark.parametrize("case", sorted(IDENTICAL_CASES))
def test_off_form_is_lowering_identical(corner, case):
    base, text = corner
    report = diff_report(
        base, text(**IDENTICAL_CASES[case]), "default-build", case
    )
    assert report is None, report


@pytest.mark.parametrize("case", sorted(DIVERGENT_CASES))
def test_opt_out_really_changes_the_program(corner, case):
    """The inverse gate: if the opt-out stops diverging, the machinery is no
    longer being compiled in/out and every 'zero overhead when off' claim is
    untested."""
    base, text = corner
    assert diff_report(
        base, text(**DIVERGENT_CASES[case]), "default-build", case
    ) is not None


@pytest.mark.parametrize("case", sorted(IDENTICAL_RD))
def test_rankdad_off_form_is_lowering_identical(rankdad_corner, case):
    """fused_poweriter=False (and the CPU auto default) must compile the
    exact legacy XLA power-iteration loop."""
    base, text = rankdad_corner
    report = diff_report(
        base, text(**IDENTICAL_RD[case]), "default-build", case
    )
    assert report is None, report


@pytest.mark.parametrize("case", sorted(DIVERGENT_RD))
def test_rankdad_opt_in_really_changes_the_program(rankdad_corner, case):
    """fused_poweriter=True must genuinely inject the Pallas kernel."""
    base, text = rankdad_corner
    assert diff_report(
        base, text(**DIVERGENT_RD[case]), "default-build", case
    ) is not None


def test_sanitizer_leak_mode_does_not_perturb_the_program(corner):
    """DINUNET_SANITIZE=leaks wraps the fit in jax.checking_leaks — an
    observation mode that must not alter what it observes."""
    assert IDENTITY_CASES["sanitize-leaks"] == (None, True)
    base, text = corner
    with jax.checking_leaks():
        leaks_text = text()
    report = diff_report(base, leaks_text, "plain", "under-checking_leaks")
    assert report is None, report
