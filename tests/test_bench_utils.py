"""The bench measurement utilities (bench.py) — the estimator math must be
right, because every recorded throughput number flows through it."""

import importlib.util
import os


def _bench():
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(__file__), "..", "bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_least_contended_marginal_recovers_truth_under_contention():
    """Synthetic chains: T(k) = k·c + fetch + contention-noise. The estimator
    must recover c when at least one run per endpoint is uncontended."""
    bench = _bench()
    c, fetch = 0.010, 4.5
    # deterministic "contention" schedule: some runs get hit, some don't
    hits = iter([3.0, 0.0, 1.2, 0.0, 2.0, 0.4])

    def run_chain(k):
        return k * c + fetch + next(hits)

    dt = bench.least_contended_marginal(run_chain, n=100, repeats=3)
    assert abs(dt - c) < 1e-9, dt


def test_least_contended_marginal_uses_pre_full_sample():
    bench = _bench()
    c, fetch = 0.010, 4.5
    # every fresh full-chain run is contended; only the pre-observed one is clean
    def run_chain(k):
        return k * c + fetch + (0.0 if k < 60 else 5.0)

    clean_full = 101 * c + fetch
    dt = bench.least_contended_marginal(run_chain, n=100, repeats=2,
                                        pre_full=clean_full)
    assert abs(dt - c) < 1e-9, dt


def test_least_contended_marginal_floor_guards_nonpositive():
    bench = _bench()
    # pathological: full chain faster than half chain → clamped, not negative
    times = {51: 10.0, 101: 9.0}
    dt = bench.least_contended_marginal(lambda k: times[k], n=100, repeats=1)
    assert dt == 1e-9


def test_marginal_distribution_headline_and_spread():
    """The headline must be the least-contended (endpoint-minimum) estimator;
    min/median/spread summarize the per-observation paired marginals."""
    bench = _bench()
    n, c, fetch = 100, 0.010, 4.5
    half, denom = n // 2, n - n // 2
    # observation 2 is contended on the full chain only
    pairs = [
        (half * c + fetch, n * c + fetch),
        (half * c + fetch + 0.5, n * c + fetch + 3.0),
        (half * c + fetch, n * c + fetch + 1.0),
    ]
    d = bench.marginal_distribution(pairs, n)
    assert abs(d["marginal_seconds_per_epoch"] - c) < 1e-12
    assert d["observations"] == 3
    assert abs(d["min"] - c) < 1e-12
    per = [(f - h) / denom for h, f in pairs]
    assert abs(d["median"] - sorted(per)[1]) < 1e-12
    assert abs(d["spread"] - (max(per) - min(per))) < 1e-12


def test_throughput_stats_converts_distribution():
    bench = _bench()
    d = {"marginal_seconds_per_epoch": 0.01, "observations": 2,
         "per_observation": [0.01, 0.02], "min": 0.01, "median": 0.015,
         "spread": 0.01}
    s = bench.throughput_stats(d, samples_per_epoch=100.0)
    assert s["value"] == 10000.0
    assert s["min"] == 5000.0  # slowest observation
    assert s["median"] == 7500.0
    assert s["spread"] == 5000.0


def test_marginal_distribution_contended_excluded_and_unreliable_gated():
    """A contended observation (full <= half) is recorded verbatim, counted,
    and EXCLUDED from min/median/spread; when even the endpoint-min estimate
    is non-positive the record is flagged unreliable and throughput_stats
    reports value None instead of the 1e-9 clamp's absurd throughput."""
    bench = _bench()
    n, c, fetch = 100, 0.010, 4.5
    half = n // 2
    # observation 1's half chain ate a 4 s contention hit → negative marginal
    pairs = [
        (half * c + fetch + 4.0, n * c + fetch),
        (half * c + fetch, n * c + fetch + 0.5),
    ]
    d = bench.marginal_distribution(pairs, n)
    assert d["contended"] == 1
    assert d["per_observation"][0] < 0  # recorded verbatim
    assert "unreliable" not in d  # endpoint-min still positive (obs 2's half)
    assert abs(d["min"] - (c + 0.5 / (n - half))) < 1e-12
    # every half chain contended → endpoint-min non-positive → unreliable
    bad = [(n * c + fetch + 9.0, n * c + fetch), (n * c + fetch + 9.0, n * c + fetch)]
    db = bench.marginal_distribution(bad, n)
    assert db.get("unreliable") is True
    s = bench.throughput_stats(db, samples_per_epoch=100.0)
    assert s["value"] is None and s["unreliable"] is True


def test_marginal_distribution_pre_full_headline_only():
    """The calibration full chain feeds the HEADLINE endpoint minimum but is
    not paired into the distribution (cross-window pairing)."""
    bench = _bench()
    n, c, fetch = 100, 0.010, 4.5
    half = n // 2
    pairs = [(half * c + fetch, n * c + fetch + 2.0)] * 2  # both fulls contended
    clean_full = n * c + fetch
    d = bench.marginal_distribution(pairs, n, pre_full=clean_full)
    assert abs(d["marginal_seconds_per_epoch"] - c) < 1e-12  # pre_full won
    assert d["observations"] == 2  # pre_full did NOT become an observation
    assert all(v > c for v in d["per_observation"])


def test_interleaved_ab_pairs_and_alternates_arm_order():
    """Every arm gets N (half, full) pairs, and within each observation round
    the arms are timed adjacently with the order alternating between rounds
    (the contention-fairness property the A/B recipe depends on)."""
    bench = _bench()
    calls = []

    def mk(name, c):
        def run(k):
            calls.append((name, k))
            return k * c + 1.0
        return run

    out = bench.interleaved_ab({"a": mk("a", 0.01), "b": mk("b", 0.03)},
                               n=10, obs=3)
    assert abs(out["a"]["marginal_seconds_per_epoch"] - 0.01) < 1e-12
    assert abs(out["b"]["marginal_seconds_per_epoch"] - 0.03) < 1e-12
    assert out["a"]["observations"] == out["b"]["observations"] == 3
    # 2 arms × 3 rounds × (half + full) = 12 calls; round order alternates
    assert len(calls) == 12
    first_round = [c[0] for c in calls[:2]]
    second_round = [c[0] for c in calls[4:6]]
    assert first_round == ["a", "b"] and second_round == ["b", "a"]


def test_flops_per_sample_matches_hand_count():
    """The MFU denominator, pinned against an INDEPENDENT hand count (not
    the module's own formula) for the flagship dims: 98 windows, encoder
    1000→256, biLSTM H=174/direction, head 348→256→64→2, train = 3× fwd.

    enc  = 98·1000·256·2                         =  50,176,000
    lstm = 98·2dirs·(256·(4·174) + 174·(4·174))·2 = 117,317,760
    head = 348·256·2 + 256·64·2 + 64·2·2          =     211,200
    """
    bench = _bench()
    assert bench.flops_per_sample() == 3.0 * (50_176_000 + 117_317_760 + 211_200)


def test_compile_epoch_aot_matches_epoch_fn():
    """AOT + AUTO input layout is a pure perf knob: same math, same outputs."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dinunet_implementations_tpu.engines import make_engine
    from dinunet_implementations_tpu.models import MSANNet
    from dinunet_implementations_tpu.trainer import (
        FederatedTask,
        compile_epoch_aot,
        init_train_state,
        make_optimizer,
        make_train_epoch_fn,
    )

    model = MSANNet(in_size=6, hidden_sizes=(8,), out_size=2)
    task = FederatedTask(model)
    engine = make_engine("dSGD")
    opt = make_optimizer("adam", 1e-2)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(3, 2, 4, 6)).astype(np.float32))
    y = jnp.asarray((rng.random((3, 2, 4)) > 0.5).astype(np.int32))
    w = jnp.ones((3, 2, 4), jnp.float32)
    state0 = init_train_state(task, engine, opt, jax.random.PRNGKey(0), x[0, 0],
                              num_sites=3)
    epoch_fn = make_train_epoch_fn(task, engine, opt, mesh=None)
    ref_state, ref_losses = epoch_fn(state0, x, y, w)
    comp, put_x = compile_epoch_aot(epoch_fn, state0, x, y, w)
    aot_state, aot_losses = comp(state0, put_x(x), y, w)
    np.testing.assert_allclose(np.asarray(aot_losses), np.asarray(ref_losses),
                               rtol=1e-6)
    for a, b in zip(jax.tree.leaves(ref_state.params),
                    jax.tree.leaves(aot_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
