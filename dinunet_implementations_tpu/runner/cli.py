"""Command-line entry point — the operational surface of the build.

The reference is driven by ``python entry.py`` inside a COINSTAC container
(``Dockerfile:20``) or by the standalone ``comps/*/site_run.py`` scripts.
Here one CLI covers both:

    # federated run over a simulator tree (the COINSTAC-simulator replacement)
    dinunet-tpu --data-path datasets/test_fsl --task FS-Classification \
        --engine dSGD --epochs 101 --out-dir out

    # single-site debug harness (SiteRunner parity)
    dinunet-tpu --data-path datasets/test_fsl --site 0 --epochs 20

    # resume / inference-only
    dinunet-tpu --data-path ... --resume
    dinunet-tpu --data-path ... --mode test

Any TrainConfig field (or task-args field) can be overridden with
``--set key=value`` (repeatable; values parse as JSON when possible, e.g.
``--set split_ratio=[0.7,0.15,0.15]`` or ``--set hidden_size=348``).
"""

from __future__ import annotations

import argparse
import json
import sys

from ..core.config import AggEngine, NNComputation, TrainConfig


def _parse_set(pairs: list[str]) -> dict:
    out = {}
    for pair in pairs:
        if "=" not in pair:
            raise SystemExit(f"--set expects key=value, got {pair!r}")
        k, v = pair.split("=", 1)
        try:
            out[k] = json.loads(v)
        except json.JSONDecodeError:
            out[k] = v  # bare string
    return out


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dinunet-tpu",
        description="TPU-native federated training (dinunet capabilities).",
    )
    p.add_argument("--data-path", required=True,
                   help="dataset tree (reference simulator layout: "
                        "input/local*/simulatorRun + inputspec.json)")
    p.add_argument("--task", default=None, choices=list(NNComputation.ALL),
                   help="task id (default: TrainConfig/inputspec default)")
    p.add_argument("--engine", default=None, choices=list(AggEngine.ALL),
                   help="aggregation engine")
    p.add_argument("--mode", default=None, choices=["train", "test"])
    p.add_argument("--epochs", type=int, default=None)
    p.add_argument("--batch-size", type=int, default=None)
    p.add_argument("--num-folds", type=int, default=None)
    p.add_argument("--model-axis-size", type=int, default=None,
                   help="sequence parallelism: shard the model's sequence "
                        "axis over this many devices per site")
    p.add_argument("--sites-per-device", type=int, default=None,
                   help="site packing: K virtual sites per mesh device with "
                        "two-level aggregation (512+ sites on an 8-device "
                        "mesh; see docs/ARCHITECTURE.md Site virtualization)")
    p.add_argument("--slices", type=int, default=None,
                   help="multi-slice scale-out (r18): lay the site tier "
                        "over this many slices — intra-slice aggregation "
                        "rides ICI, one inter-slice hop per round crosses "
                        "DCN (docs/ARCHITECTURE.md Multi-slice)")
    p.add_argument("--dcn-wire-quant", default=None,
                   choices=["none", "bf16", "int8", "fp8"],
                   help="inter-slice wire codec, independent of "
                        "--wire-quant (default: follow it); quantizes the "
                        "per-slice partial on the slow DCN hop only")
    p.add_argument("--min-slices", type=int, default=None,
                   help="slice-quorum floor (r19): a round with fewer LIVE "
                        "slices than this HOLDS (params/opt frozen, NaN "
                        "loss, held_rounds telemetry) instead of training "
                        "on a rump cohort; needs --slices > 1 and a "
                        "--faults plan with slice windows "
                        "(slice_drop_at / slice_delay_at / kill_slice_at)")
    p.add_argument("--out-dir", default=None,
                   help="output root (default <data-path>/output)")
    p.add_argument("--site", type=int, default=None,
                   help="single-site mode: run only this site index "
                        "(SiteRunner parity)")
    p.add_argument("--serve", action="store_true",
                   help="daemon mode (elastic rounds, r13): a persistent "
                        "service over one compiled epoch program with a "
                        "fixed virtual-site axis; sites join/leave/rejoin "
                        "via JSON events in the ingest spool "
                        "(runner/fed_runner.py FedDaemon). The tree's "
                        "local* sites pre-join; combine with --set "
                        "staleness_bound=N for buffered-async aggregation")
    p.add_argument("--serve-spool", default=None, metavar="DIR",
                   help="ingest spool directory (default "
                        "<data-path>/spool): join/leave/shutdown events as "
                        "*.json files, processed in sorted order")
    p.add_argument("--serve-capacity", type=int, default=None,
                   help="virtual-site slots (S_max) — fixes every traced "
                        "shape for the life of the service; default: the "
                        "discovered site count")
    p.add_argument("--serve-quorum", type=int, default=1,
                   help="minimum occupied slots; below it rounds HOLD "
                        "rather than aggregate (default 1)")
    p.add_argument("--serve-epochs", type=int, default=None,
                   help="stop after this many trained epochs (default: "
                        "serve until a shutdown event or SIGTERM)")
    p.add_argument("--serve-poll", type=float, default=0.5,
                   help="idle spool poll interval in seconds (default 0.5)")
    p.add_argument("--serve-rows", type=int, default=None,
                   help="pinned inventory rows per slot (headroom for "
                        "bigger sites joining later; default: the first "
                        "admitted site's size)")
    p.add_argument("--schedule", action="store_true",
                   help="fleet-scheduler mode (r22): pack multiple "
                        "concurrent studies (tenants) onto the shared "
                        "slice pool with weighted fair share, "
                        "checkpoint-then-yield preemption and serving "
                        "backfill. --data-path is the scheduler ROOT: "
                        "tenants register via <root>/spool/*.json events "
                        "and live under <root>/tenants/<id>/ "
                        "(runner/scheduler.py FleetScheduler)")
    p.add_argument("--pod-slices", type=int, default=1, metavar="N",
                   help="scheduler mode: width of the shared slice pool "
                        "the fair-share loop allocates (default 1)")
    p.add_argument("--sched-wall-s", type=float, default=None, metavar="S",
                   help="scheduler mode: stop after S wall-clock seconds "
                        "(default: run until every tenant is done or a "
                        "shutdown event/signal arrives)")
    p.add_argument("--sched-ticks", type=int, default=None, metavar="N",
                   help="scheduler mode: stop after N scheduling ticks")
    p.add_argument("--statusz-port", type=int, default=None, metavar="PORT",
                   help="daemon mode: serve live observability endpoints on "
                        "127.0.0.1:PORT — /metrics (Prometheus text), "
                        "/healthz (per-subsystem readiness), /statusz "
                        "(JSON snapshot incl. SLO burn), /tracez (recent "
                        "spans). PORT 0 picks a free port (printed at "
                        "startup). telemetry/exporter.py")
    p.add_argument("--slo-p99-ms", type=float, default=None, metavar="MS",
                   help="p99 target for the /statusz SLO error-budget burn, "
                        "computed over the live epoch-latency histogram "
                        "(daemon) — burn > 1.0 means the error budget is "
                        "being spent faster than allowed")
    p.add_argument("--folds", type=int, nargs="*", default=None,
                   help="run only these fold indices")
    p.add_argument("--resume", action="store_true",
                   help="resume each fold from its latest checkpoint")
    p.add_argument("--faults", default=None, metavar="JSON|@FILE",
                   help="deterministic fault injection (robustness/faults.py "
                        "FaultPlan): inline JSON or @path — e.g. "
                        '\'{"drop": [[3, 10, -1]], "nan_at": [[5, 1]], '
                        '"kill_at_round": 20}\'. Site drops / NaN poisoning / '
                        "simulated preemption replay identically run to run")
    p.add_argument("--profile-dir", default=None,
                   help="write a jax.profiler trace per fold here")
    p.add_argument("--telemetry", default=None, choices=["on", "off"],
                   help="unified telemetry (telemetry/): span tracer + "
                        "on-device per-round per-site metrics + "
                        "manifest.json/metrics.jsonl/Perfetto trace under "
                        "<out-dir>/telemetry/fold_<k>. 'off' (default) "
                        "compiles the device metrics out entirely")
    p.add_argument("--xprof-dir", default=None, metavar="DIR",
                   help="jax.profiler capture around a configurable epoch "
                        "window only (TrainConfig.xprof_window, default "
                        "epoch 1; override via --set xprof_window=[3,5]). "
                        "Windowed alternative to --profile-dir")
    p.add_argument("--pipeline", default=None, choices=["device", "host"],
                   help="input pipeline: 'device' (default) keeps the site "
                        "inventory resident on the mesh and ships only a "
                        "compact int32 index plan per epoch; 'host' is the "
                        "legacy dense per-epoch transfer (A/B fallback)")
    p.add_argument("--compile-cache", default=None, metavar="DIR",
                   help="persistent XLA compilation cache directory: re-runs "
                        "and per-fold re-fits load the compiled epoch from "
                        "disk instead of recompiling (TrainConfig."
                        "compile_cache_dir)")
    p.add_argument("--sanitize", nargs="?", const="1", default=None,
                   metavar="FLAGS",
                   help="runtime sanitizer (checks/sanitize.py): compile-"
                        "counter guard + jax leak checking + debug-NaN "
                        "around every fit. Optional comma subset of "
                        "compile,leaks,nans (default: all). Equivalent to "
                        "DINUNET_SANITIZE=<FLAGS>")
    p.add_argument("--coordinator", default=None, metavar="HOST:PORT",
                   help="multi-host runs: the jax.distributed coordinator "
                        "(the COINSTAC-pipeline-coordinator equivalent); "
                        "every process passes the same address")
    p.add_argument("--num-processes", type=int, default=None,
                   help="multi-host runs: total process count")
    p.add_argument("--process-id", type=int, default=None,
                   help="multi-host runs: this process's rank")
    p.add_argument("--quiet", action="store_true")
    p.add_argument("--attacks", default=None, metavar="JSON|@FILE",
                   help="declarative byzantine-site attack injection "
                        "(robustness/attacks.py AttackPlan): inline JSON or "
                        '@path — e.g. \'{"sign_flip": [[2, 0, -1]], '
                        '"scale": [[5, 10, 20]], "scale_factor": 10}\'. '
                        "Sign-flip / gradient-scaling / additive-noise / "
                        "free-rider / colluding-clique attacks replay "
                        "identically run to run and compose with --faults; "
                        "pair with --robust-agg for the defense")
    p.add_argument("--robust-agg", default=None,
                   choices=["none", "norm_clip", "trimmed_mean",
                            "coordinate_median"],
                   help="byzantine-robust site-axis aggregation "
                        "(parallel/collectives.py): norm_clip bounds each "
                        "site's gradient norm at the robust median "
                        "(psum wire unchanged); trimmed_mean / "
                        "coordinate_median reduce per coordinate over a "
                        "cross-site gather. Non-none also enables the "
                        "anomaly-scored reputation quarantine "
                        "(robustness/health.py)")
    p.add_argument("--wire-quant", default=None,
                   choices=["none", "bf16", "int8", "fp8"],
                   help="quantize collective payloads to this wire grid "
                        "(scale per payload, dequant after reduce; ~4x "
                        "fewer wire bytes at int8/fp8 — "
                        "parallel/collectives.py WireCodec)")
    p.add_argument("--overlap-rounds", action="store_true", default=None,
                   help="overlap round t's aggregation collective with "
                        "round t+1's batch gather + compute (one-round-"
                        "delayed pipelined update; trainer/steps.py)")
    p.add_argument("--fused-poweriter", default=None,
                   choices=["auto", "on", "off"],
                   help="fused Pallas power-iteration kernel for the "
                        "rankDAD subspace iteration (default auto: on for "
                        "the TPU backend; ops/poweriter_pallas.py)")
    p.add_argument("--dp-clip", type=float, default=None, metavar="C",
                   help="privacy plane (r20, privacy/dpsgd.py): clip each "
                        "site's round-gradient L2 norm to C inside the "
                        "rounds scan (before engine compression); 0 = off")
    p.add_argument("--dp-noise", type=float, default=None, metavar="SIGMA",
                   help="DP-SGD noise multiplier σ: adds σ·C Gaussian "
                        "noise per site per round, counter-keyed by "
                        "(dp_seed, site, round). Needs --dp-clip > 0. The "
                        "RDP accountant surfaces (ε, δ) per epoch in "
                        "telemetry, logs.json, the report CLI and the "
                        "train_epsilon /statusz gauge")
    p.add_argument("--dp-epsilon-budget", type=float, default=None,
                   metavar="EPS",
                   help="stop the fit cleanly (checkpointed, best-state "
                        "test still runs) once the accountant's ε reaches "
                        "this budget; 0 = unbounded")
    p.add_argument("--secure-agg", default=None,
                   choices=["off", "mask", "mask-nopads"],
                   help="secure-aggregation masked wires (r20, "
                        "privacy/secure_agg.py, dSGD only): 'mask' "
                        "one-time-pads each site's fixed-point delta with "
                        "pairwise antisymmetric int32 masks that cancel "
                        "EXACTLY in the unchanged psum wire; "
                        "'mask-nopads' is the pads-zeroed verification "
                        "arm (bit-identical params — the CI smoke asserts "
                        "it). Refuses int8/fp8 wire codecs")
    p.add_argument("--personalize", default=None, metavar="PATTERNS",
                   help="personalized per-site heads (r20, "
                        "privacy/personalize.py): comma-separated "
                        "param-path substrings (e.g. 'cls_fc3' for the "
                        "ICA-LSTM classifier) kept OUT of aggregation — "
                        "each site trains and evaluates its own head row")
    p.add_argument("--set", dest="overrides", action="append", default=[],
                   metavar="KEY=VALUE",
                   help="override any TrainConfig / task-args field "
                        "(repeatable; value parsed as JSON when possible)")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    overrides = _parse_set(args.overrides)
    for key, val in (
        ("task_id", args.task), ("agg_engine", args.engine),
        ("mode", args.mode), ("epochs", args.epochs),
        ("batch_size", args.batch_size), ("num_folds", args.num_folds),
        ("model_axis_size", args.model_axis_size),
        ("sites_per_device", args.sites_per_device),
        ("num_slices", args.slices),
        ("dcn_wire_quant", args.dcn_wire_quant),
        ("min_slices", args.min_slices),
        ("profile_dir", args.profile_dir),
        ("telemetry", args.telemetry),
        ("xprof_dir", args.xprof_dir),
        ("pipeline", args.pipeline),
        ("compile_cache_dir", args.compile_cache),
        ("wire_quant", args.wire_quant),
        ("robust_agg", args.robust_agg),
        ("overlap_rounds", args.overlap_rounds),
        ("fused_poweriter", (
            None if args.fused_poweriter in (None, "auto")
            else args.fused_poweriter == "on"
        )),
        ("dp_clip", args.dp_clip),
        ("dp_noise_multiplier", args.dp_noise),
        ("dp_epsilon_budget", args.dp_epsilon_budget),
        ("secure_agg", args.secure_agg),
        ("personalize", (
            None if args.personalize is None
            else tuple(p for p in args.personalize.split(",") if p)
        )),
    ):
        if val is not None:
            overrides[key] = val
    cfg = TrainConfig().with_overrides(overrides)
    verbose = not args.quiet

    if args.sanitize is not None:
        # the runner layer reads the env var, so the flag is just sugar —
        # validate it here for an early, readable error
        import os

        from ..checks.sanitize import ENV_VAR, sanitize_flags

        try:
            sanitize_flags(args.sanitize)
        except ValueError as e:
            raise SystemExit(f"--sanitize: {e}")
        os.environ[ENV_VAR] = args.sanitize

    mh_flags = (args.coordinator, args.num_processes, args.process_id)
    if any(f is not None for f in mh_flags):
        complete = all(f is not None for f in mh_flags)
        solo = (args.num_processes == 1 and args.coordinator is None
                and args.process_id is None)
        if not complete and not solo:
            # a worker with a partial spec must not silently fall back to an
            # independent single-process run on the full data (and a partial
            # spec reaching jax.distributed.initialize dies with an obscure
            # error instead of this one)
            raise SystemExit(
                "multi-host runs need all of --coordinator, --num-processes "
                "and --process-id together (--num-processes 1 alone runs "
                "single-process)"
            )
        from ..parallel.distributed import distributed_init

        distributed_init(
            coordinator_address=args.coordinator,
            num_processes=args.num_processes,
            process_id=args.process_id,
        )

    fault_plan = None
    if args.faults:
        from ..robustness.faults import parse_fault_plan

        try:
            fault_plan = parse_fault_plan(args.faults)
        except (ValueError, OSError, TypeError) as e:
            raise SystemExit(f"--faults: {e}")

    attack_plan = None
    if args.attacks:
        from ..robustness.attacks import parse_attack_plan

        try:
            attack_plan = parse_attack_plan(args.attacks)
        except (ValueError, OSError, TypeError) as e:
            raise SystemExit(f"--attacks: {e}")

    if args.schedule:
        if args.serve or args.site is not None or args.folds is not None:
            raise SystemExit(
                "--schedule is the fleet-scheduler mode; --serve/--site/"
                "--folds are single-fit options"
            )
        from ..checks.sanitize import SanitizerViolation
        from .scheduler import FleetScheduler

        sched = FleetScheduler(
            args.data_path,
            pod_slices=args.pod_slices,
            poll_s=args.serve_poll,
            verbose=verbose,
        )
        exporter = None
        if args.statusz_port is not None:
            from ..telemetry.collector import PodCollector
            from ..telemetry.exporter import StatusExporter

            # pod-scope plane (r23): the scheduler's own bus plus any
            # worker exporters advertising themselves via heartbeats
            # under the schedule root, merged behind ONE /statusz
            collector = PodCollector(
                args.data_path,
                local_bus=sched.bus,
                local_labels={"process": "scheduler"},
                status_extra=sched.status,
            )
            exporter = StatusExporter(
                collector, port=args.statusz_port,
                health=sched.health_probes(), statusz=collector.status,
                slo=(
                    {"histogram": "serve_epoch_ms",
                     "p99_target_ms": args.slo_p99_ms}
                    if args.slo_p99_ms is not None else None
                ),
            )
            port = exporter.start()
            if verbose:
                print(json.dumps({
                    "statusz": f"http://127.0.0.1:{port}",
                    "endpoints": ["/metrics", "/healthz", "/statusz",
                                  "/tracez"],
                }))
        try:
            summary = sched.run(
                max_wall_s=args.sched_wall_s, max_ticks=args.sched_ticks,
            )
        except SanitizerViolation as v:
            print(json.dumps({"sanitizer_violation": str(v)}),
                  file=sys.stderr)
            return 70
        finally:
            if exporter is not None:
                exporter.stop()
        from ..telemetry.sink import _finite

        print(json.dumps(_finite(summary), default=str))
        return 0

    if args.serve:
        if args.site is not None or args.folds is not None:
            raise SystemExit(
                "--serve is the daemon mode; --site/--folds are batch-mode "
                "options"
            )
        from ..checks.sanitize import SanitizerViolation
        from .fed_runner import FedDaemon, discover_site_dirs

        capacity = args.serve_capacity or len(discover_site_dirs(args.data_path))
        daemon = FedDaemon(
            cfg,
            capacity=capacity,
            spool_dir=args.serve_spool,
            out_dir=args.out_dir,
            data_path=args.data_path,
            quorum=args.serve_quorum,
            poll_s=args.serve_poll,
            fault_plan=fault_plan,
            attack_plan=attack_plan,
            inventory_rows=args.serve_rows,
            resume=args.resume,
            verbose=verbose,
        )
        # live observability plane (r16): /metrics /healthz /statusz
        # /tracez over the process bus, and crash hooks so an unhandled
        # exception dumps the flight ring (SIGTERM/SIGINT dump rides the
        # daemon's cooperative PreemptionGuard path — signals=() here,
        # the guard owns those handlers during serve())
        daemon.flight.install(signals=())
        exporter = None
        if args.statusz_port is not None:
            from ..telemetry.exporter import StatusExporter

            exporter = StatusExporter(
                daemon.bus, port=args.statusz_port,
                tracer=daemon.trainer.tracer, flight=daemon.flight,
                health=daemon.health_probes(), statusz=daemon.status,
                slo=(
                    {"histogram": "serve_epoch_ms",
                     "p99_target_ms": args.slo_p99_ms}
                    if args.slo_p99_ms is not None else None
                ),
            )
            port = exporter.start()
            if verbose:
                print(json.dumps({
                    "statusz": f"http://127.0.0.1:{port}",
                    "endpoints": ["/metrics", "/healthz", "/statusz",
                                  "/tracez"],
                }))
        try:
            # DINUNET_SANITIZE / --sanitize: the one-epoch-compile guard
            # wraps the WHOLE service — any churn-induced retrace trips it
            from ..checks.sanitize import sanitized_fit

            with sanitized_fit(daemon.trainer, label="serve"):
                summary = daemon.serve(max_epochs=args.serve_epochs)
        except SanitizerViolation as v:
            daemon.flight.dump("sanitizer-violation")
            print(json.dumps({"sanitizer_violation": str(v)}), file=sys.stderr)
            return 70
        finally:
            # the excepthook stays installed on the failure path — an
            # exception unwinding past here still dumps the flight ring
            # at interpreter exit
            if exporter is not None:
                exporter.stop()
        daemon.flight.uninstall()
        from ..telemetry.sink import _finite

        print(json.dumps(_finite(summary), default=str))
        return 0

    if args.site is not None:
        if args.folds is not None or args.resume:
            raise SystemExit(
                "--folds/--resume are federated-mode options; "
                "not supported together with --site"
            )
        if fault_plan is not None:
            raise SystemExit(
                "--faults targets federated rounds; not supported with --site"
            )
        if attack_plan is not None:
            raise SystemExit(
                "--attacks targets federated rounds; not supported with "
                "--site"
            )
        from .fed_runner import SiteRunner

        from ..checks.sanitize import SanitizerViolation

        runner = SiteRunner(
            task_id=cfg.task_id, data_path=args.data_path,
            mode=cfg.mode, site_index=args.site, out_dir=args.out_dir,
            # drop the keys passed explicitly above — they already carry any
            # override (cfg.mode includes --mode / --set mode=...)
            **{k: v for k, v in overrides.items()
               if k not in ("task_id", "mode", "site_index", "out_dir")},
        )
        try:
            results = runner.run(verbose=verbose)
        except SanitizerViolation as v:
            print(json.dumps({"sanitizer_violation": str(v)}), file=sys.stderr)
            return 70  # EX_SOFTWARE: an internal invariant broke
    else:
        from ..checks.sanitize import SanitizerViolation
        from ..robustness.preemption import Preempted
        from .fed_runner import FedRunner

        runner = FedRunner(cfg, data_path=args.data_path, out_dir=args.out_dir,
                           fault_plan=fault_plan, attack_plan=attack_plan)
        try:
            results = runner.run(
                folds=args.folds, verbose=verbose, resume=args.resume
            )
        except SanitizerViolation as v:
            print(json.dumps({"sanitizer_violation": str(v)}), file=sys.stderr)
            return 70  # EX_SOFTWARE: an internal invariant broke
        except Preempted as p:
            # cooperative shutdown (SIGTERM/SIGINT or FaultPlan kill): state
            # was checkpointed before the raise — rerun with --resume to
            # continue bit-exact from the saved epoch boundary
            print(json.dumps({
                "preempted": True, "reason": p.reason, "epoch": p.epoch,
                "resume_with": "--resume",
            }), file=sys.stderr)
            return p.exit_code

    for k, res in enumerate(results):
        loss, metric = res["test_metrics"][0]
        print(json.dumps({
            "fold": (args.folds or list(range(len(results))))[k],
            "test_loss": loss,
            f"test_{cfg.monitor_metric}": metric,
            "best_val_epoch": res["best_val_epoch"],
        }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
