"""Program-identity regression harness — the "off == compiled out" claims.

ONE parametrized harness over checks/lowering.py's normalized differ
replaces the ad-hoc ``lowered.as_text() == ...`` comparisons that used to be
duplicated across tests/test_telemetry.py and tests/test_robustness.py:

- every OFF-form (telemetry off, faults at their default resolution, the
  sanitizer's leak-checking observation mode) must be lowering-identical to
  the baseline epoch program;
- every static OPT-OUT/OPT-IN (``quarantine_rounds=-1``, ``telemetry=True``)
  must genuinely diverge — if these become identical, "compiled out" has
  silently stopped being true.

The same pairs gate the CLI via rule S005
(``python -m dinunet_implementations_tpu.checks --semantic``); this file is
the fast tier-1 mirror with per-pair failure reports.
"""

import jax
import pytest

from dinunet_implementations_tpu.checks.lowering import diff_report
from dinunet_implementations_tpu.checks.semantic import (
    IDENTITY_CASES,
    TraceCell,
    build_cell_inputs,
)
from dinunet_implementations_tpu.trainer import make_train_epoch_fn


@pytest.fixture(scope="module")
def corner():
    """The flagship matrix corner (dSGD / folded sites / host pipeline),
    built by the semantic tier's shared corner builder — the same programs
    the S005 CLI gate compares."""
    task, engine, opt, _, args, mesh = build_cell_inputs(
        TraceCell("dSGD", "vmap", "host")
    )

    def text(**kw):
        fn = make_train_epoch_fn(task, engine, opt, mesh=mesh, **kw)
        return fn.lower(*args).as_text()

    # the default build's text once, not once per test
    return text(), text


#: derived from the semantic tier's IDENTITY_CASES so this harness and the
#: S005 CLI gate can never test different pair sets. kwargs=None is the
#: checking_leaks observation mode (its own test below).
IDENTICAL_CASES = {
    label: kw for label, (kw, identical) in IDENTITY_CASES.items()
    if identical and kw is not None
}
DIVERGENT_CASES = {
    label: kw for label, (kw, identical) in IDENTITY_CASES.items()
    if not identical
}


@pytest.mark.parametrize("case", sorted(IDENTICAL_CASES))
def test_off_form_is_lowering_identical(corner, case):
    base, text = corner
    report = diff_report(
        base, text(**IDENTICAL_CASES[case]), "default-build", case
    )
    assert report is None, report


@pytest.mark.parametrize("case", sorted(DIVERGENT_CASES))
def test_opt_out_really_changes_the_program(corner, case):
    """The inverse gate: if the opt-out stops diverging, the machinery is no
    longer being compiled in/out and every 'zero overhead when off' claim is
    untested."""
    base, text = corner
    assert diff_report(
        base, text(**DIVERGENT_CASES[case]), "default-build", case
    ) is not None


def test_sanitizer_leak_mode_does_not_perturb_the_program(corner):
    """DINUNET_SANITIZE=leaks wraps the fit in jax.checking_leaks — an
    observation mode that must not alter what it observes."""
    assert IDENTITY_CASES["sanitize-leaks"] == (None, True)
    base, text = corner
    with jax.checking_leaks():
        leaks_text = text()
    report = diff_report(base, leaks_text, "plain", "under-checking_leaks")
    assert report is None, report
