from .lstm_pallas import lstm_forward, lstm_recurrence
