"""Runner tests: the full federated pipeline on the reference's real fixture,
plus the notebook-parse parity check (SURVEY.md §7: 'the reference notebooks
run unmodified against our outputs')."""

import json
import os
import zipfile

import numpy as np
import pytest

from dinunet_implementations_tpu import TrainConfig
from dinunet_implementations_tpu.runner import (
    FedRunner,
    SiteRunner,
    discover_site_dirs,
    get_task,
)

FSL = "/root/reference/datasets/test_fsl"

needs_reference = pytest.mark.skipif(
    not os.path.isdir(FSL), reason="reference fixture not mounted"
)


@needs_reference
def test_discover_site_dirs_ordering():
    dirs = discover_site_dirs(FSL)
    assert len(dirs) == 5
    assert [d.split("/")[-2] for d in dirs] == [f"local{i}" for i in range(5)]


def test_get_task_dispatch_parity():
    with pytest.raises(ValueError, match="Invalid task"):
        get_task("bogus")
    spec = get_task("FS-Classification")
    assert spec.dataset_cls.__name__ == "FreeSurferDataset"


@pytest.mark.slow
@needs_reference
def test_fed_runner_fixture_end_to_end(tmp_path):
    cfg = TrainConfig(epochs=4, patience=10, split_ratio=(0.7, 0.15, 0.15))
    r = FedRunner(cfg, data_path=FSL, out_dir=str(tmp_path))
    assert len(r.site_dirs) == 5
    # per-site inputspec overrides resolved (site 0 ← site1_Covariate.csv)
    assert r.cfg.fs_args.labels_file == "site1_Covariate.csv"
    results = r.run(verbose=False)
    res = results[0]
    loss, auc = res["test_metrics"][0]
    assert 0 < loss < 2
    assert 0 <= auc <= 1

    # --- notebook-parse parity (nnlogs.ipynb cell 2 / NB.ipynb cells 6, 34)
    local_log = json.load(
        open(tmp_path / "local0/simulatorRun/FS-Classification/fold_0/logs.json")
    )
    assert local_log["agg_engine"] == "dSGD"
    assert isinstance(local_log["cumulative_total_duration"][-1], float)
    assert sum(local_log["time_spent_on_computation"]) > 0
    assert len(local_log["local_iter_duration"]) >= 4

    with zipfile.ZipFile(tmp_path / "remote/simulatorRun/global_results.zip") as zf:
        zf.extractall(tmp_path / "GLOBAL_res")
    remote_log = json.load(
        open(tmp_path / "GLOBAL_res/fold_0/logs.json")
    )
    assert remote_log["test_metrics"] == res["test_metrics"]
    assert "remote_iter_duration" in remote_log

    line = open(
        tmp_path / "remote/simulatorRun/FS-Classification/fold_0/test_metrics.csv"
    ).readlines()[1].split(",")
    acc, f1 = float(line[1]), float(line[2])
    assert 0 <= acc <= 1 and 0 <= f1 <= 1


@pytest.mark.slow
@needs_reference
def test_fed_runner_vmap_fold_mode(tmp_path):
    cfg = TrainConfig(epochs=2, split_ratio=(0.7, 0.15, 0.15))
    r = FedRunner(cfg, data_path=FSL, out_dir=str(tmp_path), mesh=None)
    res = r.run(verbose=False)[0]
    assert 0 <= res["test_metrics"][0][1] <= 1


@needs_reference
def test_site_runner_parity_signature(tmp_path):
    """Reference call shape: SiteRunner(taks_id='FSL', data_path=..., mode='Train',
    split_ratio=[...]).run(Trainer, Dataset, Handle) — comps/fs/site_run.py:5-6."""
    runner = SiteRunner(
        taks_id="FSL", data_path=FSL, mode="train", split_ratio=[0.8, 0.1, 0.1],
        out_dir=str(tmp_path),
    )
    runner.cfg = runner.cfg.replace(epochs=2, batch_size=8)
    results = runner.run(None, None, None, verbose=False)
    assert len(results) == 1
    assert 0 <= results[0]["test_metrics"][0][1] <= 1


# ---------------------------------------------------------------------------
# ICA federated end-to-end (the flagship/bench workload) on a synthetic
# multi-site tree mirroring the reference fixture layout
# (datasets/icalstm/inputspec.json; data itself is git-ignored upstream)
# ---------------------------------------------------------------------------


def _make_ica_tree(root, n_sites=3, subjects=24, comps=4, temporal=20,
                   window=5, stride=5, seed=7):
    """Reference simulator layout: <root>/inputspec.json +
    <root>/input/local{i}/simulatorRun/{timecourses.npz, labels.csv}."""
    rng = np.random.default_rng(seed)
    spec = []
    for i in range(n_sites):
        d = root / "input" / f"local{i}" / "simulatorRun"
        d.mkdir(parents=True)
        y = rng.integers(0, 2, subjects)
        X = rng.normal(size=(subjects, comps, temporal)).astype(np.float32)
        X += (y[:, None, None] * 2.0).astype(np.float32)  # learnable shift
        np.savez(d / "timecourses.npz", X)
        with open(d / "labels.csv", "w") as fh:
            fh.write("index,label\n")
            for j in range(subjects):
                fh.write(f"{j},{int(y[j])}\n")
        spec.append({
            "data_file": {"value": "timecourses.npz"},
            "labels_file": {"value": "labels.csv"},
            "temporal_size": {"value": temporal},
            "window_size": {"value": window},
            "window_stride": {"value": stride},
            "num_components": {"value": comps},
            "input_size": {"value": 16},
            "hidden_size": {"value": 12},
            "num_class": {"value": 2},
        })
    (root / "inputspec.json").write_text(json.dumps(spec))


@pytest.mark.slow
def test_ica_fed_runner_end_to_end(tmp_path):
    """VERDICT #4: the flagship (bench) workload federated across 3 sites —
    trains, learns the signal, writes reference-schema outputs."""
    _make_ica_tree(tmp_path)
    cfg = TrainConfig(
        task_id="ICA-Classification", epochs=8, batch_size=8, patience=10,
        split_ratio=(0.7, 0.15, 0.15),
    )
    r = FedRunner(cfg, data_path=str(tmp_path), out_dir=str(tmp_path / "output"))
    assert len(r.site_dirs) == 3
    # per-site inputspec overrides resolved into ica_args
    assert r.cfg.ica_args.data_file == "timecourses.npz"
    assert r.cfg.ica_args.hidden_size == 12
    res = r.run(verbose=False)[0]
    loss, auc = res["test_metrics"][0]
    assert 0 < loss < 2
    assert auc > 0.65, f"ICA federation failed to learn (auc={auc})"
    log = json.load(
        open(tmp_path / "output/local1/simulatorRun/ICA-Classification/fold_0/logs.json")
    )
    assert log["agg_engine"] == "dSGD"
    assert len(log["local_iter_duration"]) >= 1


@pytest.mark.slow
def test_ica_site_runner_reference_signature(tmp_path):
    """Reference call shape (comps/icalstm/site_run.py:6-9): SiteRunner with
    seed, site_index, monitor_metric='auc', batch_size — single-site ICA."""
    _make_ica_tree(tmp_path, n_sites=2)
    runner = SiteRunner(
        taks_id="ICA", data_path=str(tmp_path), mode="train", seed=3,
        site_index=1, split_ratio=[0.6, 0.2, 0.2], monitor_metric="auc",
        log_header="Loss|AUC", batch_size=8,
    )
    runner.cfg = runner.cfg.replace(epochs=2)
    results = runner.run(None, None, None, verbose=False)
    assert len(results) == 1
    assert 0 <= results[0]["test_metrics"][0][1] <= 1


@pytest.mark.slow
@needs_reference
def test_fed_runner_kfold(tmp_path):
    cfg = TrainConfig(epochs=2, num_folds=3)
    r = FedRunner(cfg, data_path=FSL, out_dir=str(tmp_path))
    results = r.run(folds=[0, 1], verbose=False)
    assert len(results) == 2
    assert os.path.isdir(tmp_path / "remote/simulatorRun/FS-Classification/fold_1")


@pytest.mark.slow
@needs_reference
def test_fed_runner_mode_test_roundtrip(tmp_path):
    """Train once, then a mode='test' run on the same output tree reproduces
    the stored test metrics without training (compspec mode field)."""
    cfg = TrainConfig(epochs=3, split_ratio=(0.7, 0.15, 0.15))
    r = FedRunner(cfg, data_path=FSL, out_dir=str(tmp_path))
    res_train = r.run(verbose=False)[0]

    r2 = FedRunner(cfg.replace(mode="test"), data_path=FSL, out_dir=str(tmp_path))
    res_test = r2.run(verbose=False)[0]
    assert res_test["test_metrics"] == res_train["test_metrics"]


@needs_reference
def test_fed_runner_explicit_fold_ids_write_correct_dirs(tmp_path):
    """run(folds=[1]) must write fold_1 (not remap to fold_0)."""
    cfg = TrainConfig(epochs=1, num_folds=3)
    r = FedRunner(cfg, data_path=FSL, out_dir=str(tmp_path))
    r.run(folds=[1], verbose=False)
    assert os.path.isdir(tmp_path / "remote/simulatorRun/FS-Classification/fold_1")
    assert not os.path.isdir(tmp_path / "remote/simulatorRun/FS-Classification/fold_0")


@pytest.mark.slow
@needs_reference
def test_fed_runner_kfold_k2_empty_validation(tmp_path):
    """kfold k==2 has no validation fold by design (splits.py:41-45): fit
    must skip validation-based selection (final state selected, no early
    stop) instead of crashing — review finding r5."""
    cfg = TrainConfig(epochs=2, num_folds=2)
    r = FedRunner(cfg, data_path=FSL, out_dir=str(tmp_path))
    results = r.run(folds=[0], verbose=False)
    assert len(results) == 1
    assert results[0]["best_val_metric"] is None
    assert results[0]["best_val_epoch"] == 2  # final epoch selected
    assert 0 <= results[0]["test_scores"]["auc"] <= 1
