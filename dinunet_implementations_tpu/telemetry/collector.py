"""Pod-scope metrics federation (r23): discover, scrape, exact-merge.

PRs 13–19 turned one process into a pod, but every worker still runs its
own MetricsBus behind its own ``/statusz`` — a fleet question ("what is the
pod-wide epoch p99?") meant N scrapes and hand-merging. This module closes
that gap with three pure pieces and one collector:

- **Discovery** (:func:`discover_targets`) — scrape targets come from the
  r19 heartbeat files (``<out>/heartbeats/slice_<i>.json``): each worker's
  slice lead advertises its auto-picked ``/statusz`` port in its own
  heartbeat (``Heartbeat.beat(statusz_port=...)``), so federation needs
  ZERO extra configuration. A target is valid only when its pid is alive
  AND its scraped ``/statusz`` pid matches the heartbeat's (with
  ``started_unix`` agreement guarding against pid reuse).
- **Label stamping** (:func:`stamp_snapshot`) — a scraped snapshot's gauge
  and histogram series get the target's identity stamped in
  (``{process=,slice=}``; tenant/replica labels published by the worker
  itself pass through untouched). Stamping a label that the series already
  carries with a DIFFERENT value raises :class:`LabelCollisionError` — a
  worker cannot impersonate another's identity, accidentally or otherwise.
- **Merging** (:func:`merge_snapshots`) — counters with equal keys SUM
  (pod totals), gauges UNION (an equal-key/unequal-value collision is an
  error, which is what makes the merge commutative), histograms merge via
  the :class:`~.hist.LogHistogram` exact elementwise merge — so the pod
  rollup's quantiles are IDENTICAL whatever the merge tree, the property
  the r16 histograms were built for.
- :class:`PodCollector` — glues the three together and duck-types the
  MetricsBus read API (``snapshot()`` / ``merged_histogram()``), so the
  EXISTING :class:`~.exporter.StatusExporter` serves the federated pod
  ``/statusz`` + ``/metrics`` (and the fleet-wide SLO burn) unchanged —
  one exporter implementation for process scope and pod scope.

Deliberately stdlib-only (urllib for the scrapes): the supervisor that
hosts the pod exporter must not pull jax in.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.request

from .bus import series_key
from .hist import LogHistogram

#: labels the collector owns; a scraped series carrying one of these with a
#: conflicting value is an identity spoof, not data
RESERVED_LABELS = ("process", "slice")


class LabelCollisionError(ValueError):
    """Two series (or a series and a stamp) claim the same identity with
    different values — merging would silently corrupt attribution."""


# ---------------------------------------------------------------------------
# series-key parsing (inverse of bus.series_key)
# ---------------------------------------------------------------------------


def _unescape_label(value: str) -> str:
    out = []
    i = 0
    while i < len(value):
        c = value[i]
        if c == "\\" and i + 1 < len(value):
            nxt = value[i + 1]
            out.append("\n" if nxt == "n" else nxt)
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def parse_series(key: str) -> tuple[str, dict]:
    """A rendered bus series key back into ``(name, labels)`` — the exact
    inverse of :func:`~.bus.series_key` (round-trip tested), so stamping
    can compose new labels with whatever the publisher already set."""
    brace = key.find("{")
    if brace < 0:
        return key, {}
    name, blob = key[:brace], key[brace + 1:-1]
    labels: dict = {}
    i = 0
    while i < len(blob):
        eq = blob.find('="', i)
        if eq < 0:
            break
        k = blob[i:eq]
        j = eq + 2
        val = []
        while j < len(blob):
            c = blob[j]
            if c == "\\" and j + 1 < len(blob):
                val.append(blob[j:j + 2])
                j += 2
                continue
            if c == '"':
                break
            val.append(c)
            j += 1
        labels[k] = _unescape_label("".join(val))
        i = j + 2  # past the closing quote and the comma
    return name, labels


def _stamp_key(key: str, labels: dict) -> str:
    name, existing = parse_series(key)
    for k, v in labels.items():
        if k in existing and existing[k] != str(v):
            raise LabelCollisionError(
                f"series {key!r} already carries {k}={existing[k]!r}; "
                f"refusing to restamp as {v!r}"
            )
    return series_key(name, {**existing, **{
        k: v for k, v in labels.items() if k not in existing
    }})


def stamp_snapshot(snap: dict, **labels) -> dict:
    """A bus snapshot with ``labels`` stamped onto every GAUGE and
    HISTOGRAM series key (module docstring: counters stay unstamped — they
    sum into pod totals; per-process counter attribution is the stamped
    gauges' job). Raises :class:`LabelCollisionError` when a series
    already carries one of the labels with a different value."""
    return {
        "counters": dict(snap.get("counters", {})),
        "gauges": {
            _stamp_key(k, labels): v
            for k, v in snap.get("gauges", {}).items()
        },
        "histograms": {
            _stamp_key(k, labels): dict(v)
            for k, v in snap.get("histograms", {}).items()
        },
    }


# ---------------------------------------------------------------------------
# the exact merge
# ---------------------------------------------------------------------------


def merge_snapshots(a: dict, b: dict) -> dict:
    """Merge two bus snapshots: counters summed, gauges unioned (an
    equal-key collision with unequal values raises — that is what keeps
    the merge commutative), histograms exact-merged elementwise (shape
    mismatches raise :class:`~.hist.HistogramShapeError`). Associative and
    commutative on integer-count state, so any merge tree over any number
    of scrapes lands on the same pod rollup."""
    counters = dict(a.get("counters", {}))
    for k, v in b.get("counters", {}).items():
        counters[k] = counters.get(k, 0) + v
    gauges = dict(a.get("gauges", {}))
    for k, v in b.get("gauges", {}).items():
        if k in gauges and gauges[k] != v:
            raise LabelCollisionError(
                f"gauge {k!r} published by two processes with different "
                f"values ({gauges[k]!r} vs {v!r}) — stamp process labels "
                f"before merging"
            )
        gauges[k] = v
    hists = {k: dict(v) for k, v in a.get("histograms", {}).items()}
    for k, hd in b.get("histograms", {}).items():
        if k in hists:
            merged = LogHistogram.from_dict(hists[k])
            merged.merge(LogHistogram.from_dict(hd))
            hists[k] = merged.to_dict()
        else:
            hists[k] = dict(hd)
    return {"counters": counters, "gauges": gauges, "histograms": hists}


def merged_histogram_of(snapshot: dict, name: str) -> LogHistogram | None:
    """All label variants of ``name`` in a snapshot merged into one
    histogram — :meth:`~.bus.MetricsBus.merged_histogram` over a plain
    snapshot dict (the collector's SLO-burn read path)."""
    parts = [
        LogHistogram.from_dict(hd)
        for key, hd in snapshot.get("histograms", {}).items()
        if key == name or key.startswith(name + "{")
    ]
    if not parts:
        return None
    out = LogHistogram(parts[0].lo, parts[0].hi, parts[0].per_decade)
    for h in parts:
        out.merge(h)
    return out


# ---------------------------------------------------------------------------
# discovery + scraping
# ---------------------------------------------------------------------------

HEARTBEAT_DIR = "heartbeats"  # mirrors runner/supervisor.py (stdlib-only
#                               here: importing the runner would pull jax)

#: started_unix disagreement past this between heartbeat and /statusz is a
#: recycled pid wearing a dead worker's heartbeat, not clock jitter
START_TIME_SLOP_S = 60.0


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except (PermissionError, OSError):
        return True  # exists, just not ours to signal
    return True


def read_heartbeats(out_dir: str) -> list[dict]:
    """Every parseable heartbeat pulse under ``<out_dir>/heartbeats/``."""
    hb_dir = os.path.join(out_dir, HEARTBEAT_DIR)
    try:
        names = sorted(n for n in os.listdir(hb_dir) if n.endswith(".json"))
    except OSError:
        return []
    out = []
    for n in names:
        try:
            with open(os.path.join(hb_dir, n)) as fh:
                out.append(json.load(fh))
        except (OSError, json.JSONDecodeError, ValueError):
            continue
    return out


def discover_targets(out_dir: str) -> list[dict]:
    """Scrape targets from the heartbeat files: pulses that advertise a
    ``statusz_port`` and whose pid is still alive. Validation against the
    scraped endpoint's own pid/started_unix happens at scrape time."""
    targets = []
    for hb in read_heartbeats(out_dir):
        port = hb.get("statusz_port")
        pid = hb.get("pid")
        if not port or not isinstance(pid, int):
            continue
        if not _pid_alive(pid):
            continue
        targets.append(hb)
    return targets


def scrape_statusz(port: int, timeout_s: float = 2.0,
                   host: str = "127.0.0.1") -> dict:
    """One ``GET /statusz`` — the full JSON payload (bus snapshot under
    ``"metrics"``, caller status under ``"status"``)."""
    with urllib.request.urlopen(
        f"http://{host}:{port}/statusz", timeout=timeout_s
    ) as resp:
        return json.loads(resp.read().decode())


class PodCollector:
    """Federate the pod's per-process buses behind the MetricsBus read API.

    Each read (``snapshot()`` / ``merged_histogram()``) runs
    discover → scrape → stamp → merge over the heartbeat-advertised
    targets plus the optional ``local_bus`` (the supervisor's own bus,
    stamped with ``local_labels``), then caches the result for
    ``cache_s`` — so one ``/statusz`` request's SLO burn and snapshot see
    the SAME scrape (the exporter reads both), and a scrape storm cannot
    amplify against the workers. Unreachable or invalid targets are
    skipped and surfaced in :meth:`status`, never fatal: the pod view
    degrades to the reachable subset, exactly like a real fleet scrape.
    """

    def __init__(self, out_dir: str, *, local_bus=None,
                 local_labels: dict | None = None, timeout_s: float = 2.0,
                 cache_s: float = 0.5, status_extra=None):
        self.out_dir = out_dir
        self.local_bus = local_bus
        self.local_labels = dict(local_labels or {})
        self.timeout_s = timeout_s
        self.cache_s = cache_s
        self.status_extra = status_extra
        self._lock = threading.Lock()
        self._cached: dict | None = None
        self._cached_at = 0.0

    # -- one federation pass ----------------------------------------------

    def _target_labels(self, hb: dict) -> dict:
        labels = {}
        if hb.get("process") is not None:
            labels["process"] = str(hb["process"])
        elif hb.get("pid") is not None:
            labels["process"] = f"pid{hb['pid']}"
        if hb.get("slice") is not None:
            labels["slice"] = str(hb["slice"])
        return labels

    def _validate(self, hb: dict, payload: dict) -> str | None:
        """None when the scraped endpoint IS the heartbeat's writer, else
        the rejection reason."""
        if payload.get("pid") != hb.get("pid"):
            return (f"pid mismatch: heartbeat {hb.get('pid')} vs "
                    f"statusz {payload.get('pid')}")
        hb_start = hb.get("started_unix")
        st_start = (payload.get("status") or {}).get("started_unix")
        if (isinstance(hb_start, (int, float))
                and isinstance(st_start, (int, float))
                and abs(hb_start - st_start) > START_TIME_SLOP_S):
            return (f"start-time mismatch: heartbeat {hb_start:.0f} vs "
                    f"statusz {st_start:.0f} (recycled pid?)")
        return None

    def collect(self) -> dict:
        """Discover + scrape + merge now (no cache): ``{"snapshot",
        "targets", "errors"}``."""
        merged = {"counters": {}, "gauges": {}, "histograms": {}}
        targets, errors = [], []
        for hb in discover_targets(self.out_dir):
            where = (f"slice {hb.get('slice')} pid {hb.get('pid')} "
                     f"port {hb.get('statusz_port')}")
            try:
                payload = scrape_statusz(
                    int(hb["statusz_port"]), timeout_s=self.timeout_s
                )
            except (OSError, ValueError, json.JSONDecodeError) as e:
                errors.append(f"{where}: scrape failed ({e})")
                continue
            bad = self._validate(hb, payload)
            if bad is not None:
                errors.append(f"{where}: {bad}")
                continue
            try:
                stamped = stamp_snapshot(
                    payload.get("metrics") or {}, **self._target_labels(hb)
                )
                merged = merge_snapshots(merged, stamped)
            except (LabelCollisionError, ValueError) as e:
                errors.append(f"{where}: {e}")
                continue
            targets.append({
                "pid": hb.get("pid"),
                "slice": hb.get("slice"),
                "process": hb.get("process"),
                "statusz_port": hb.get("statusz_port"),
                "epoch": hb.get("epoch"),
                "round": hb.get("round"),
                "heartbeat_unix": hb.get("time_unix"),
                "status": payload.get("status"),
            })
        if self.local_bus is not None:
            merged = merge_snapshots(merged, stamp_snapshot(
                self.local_bus.snapshot(), **self.local_labels
            ))
        # the collector's own vitals ride the merged snapshot, so the pod
        # /metrics exposition reports its coverage alongside the data
        merged["gauges"][series_key(
            "pod_scrape_targets", {}
        )] = len(targets)
        merged["gauges"][series_key(
            "pod_scrape_errors", {}
        )] = len(errors)
        return {"snapshot": merged, "targets": targets, "errors": errors}

    def _collected(self) -> dict:
        with self._lock:
            now = time.monotonic()
            if (self._cached is None
                    or now - self._cached_at > self.cache_s):
                self._cached = self.collect()
                self._cached_at = now
            return self._cached

    # -- the MetricsBus read API (what StatusExporter consumes) ------------

    def snapshot(self) -> dict:
        return self._collected()["snapshot"]

    def merged_histogram(self, name: str) -> LogHistogram | None:
        return merged_histogram_of(self._collected()["snapshot"], name)

    def status(self) -> dict:
        """The pod ``/statusz`` caller-status payload: reachable targets,
        scrape errors, plus whatever ``status_extra`` contributes (the
        scheduler's tenant table, the supervisor's generation)."""
        got = self._collected()
        out = {
            "mode": "pod",
            "targets": got["targets"],
            "scrape_errors": got["errors"],
        }
        if self.status_extra is not None:
            try:
                out.update(self.status_extra() or {})
            except Exception as e:  # a broken extra IS the finding
                out["status_extra_error"] = str(e)
        return out
