"""Serving path (r15): AOT-compiled, continuously-batched inference.

The first surface that ANSWERS a request (ROADMAP item 5): an
:class:`~.engine.InferenceEngine` loads a trained checkpoint (params +
batch_stats only), AOT-compiles one executable per (lane, shape bucket) at
startup against the persistent XLA compile cache, and serves through a
continuous microbatcher with max-batch/max-delay admission — plus an O(1)
per-session streaming lane for causal recurrent heads (device-resident
session-slot carry table, models/icalstm.py ICALstmStream).

    python -m dinunet_implementations_tpu.serving \
        --data-path datasets/demo --checkpoint out/.../checkpoint_best.msgpack \
        --smoke 100 --out-dir out

See docs/ARCHITECTURE.md "Serving (r15)".
"""

from .engine import InferenceEngine, ServingError
from .microbatch import Microbatcher, RequestError, RequestFuture
from .session import SessionError, SessionTable, init_carry_table

__all__ = [
    "InferenceEngine",
    "Microbatcher",
    "RequestError",
    "RequestFuture",
    "ServingError",
    "SessionError",
    "SessionTable",
    "init_carry_table",
]
