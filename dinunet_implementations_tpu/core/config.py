"""Configuration system.

TPU-native re-design of the reference's two-tier config:

1. ``compspec.json`` — the owner/member-scoped, typed flag schema rendered by the
   COINSTAC GUI (reference ``compspec.json:10-297``). Here it becomes a plain
   dataclass :class:`TrainConfig` whose fields carry the same names and defaults,
   with the GUI metadata (``source``, ``conditional``, ``group``) preserved in
   :data:`COMPSPEC_META` so a compspec-compatible JSON schema can be emitted via
   :func:`export_compspec`.
2. Per-site ``inputspec.json`` simulator files (reference
   ``datasets/test_fsl/inputspec.json:1-187``, ``datasets/icalstm/inputspec.json:1-88``)
   — loaded by :func:`load_inputspec`, which unwraps the ``{"key": {"value": v}}``
   envelope and returns one override dict per site.

Config resolution order (mirrors ``COINNLocal`` kwargs being overridden by GUI
``data['input']``, reference ``local.py:31-37``): dataclass defaults < programmatic
kwargs < per-site inputspec values.
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import Any, Sequence

# ---------------------------------------------------------------------------
# Task / engine registry (reference comps/__init__.py:7-16)
# ---------------------------------------------------------------------------


class NNComputation:
    """Available tasks (reference ``comps/__init__.py:7-10``)."""

    TASK_FREE_SURFER = "FS-Classification"
    TASK_ICA = "ICA-Classification"
    # TPU-build extensions (BASELINE.json configs):
    TASK_SMRI_3D = "sMRI-3D-Classification"
    TASK_MULTIMODAL = "Multimodal-Classification"

    ALL = (TASK_FREE_SURFER, TASK_ICA, TASK_SMRI_3D, TASK_MULTIMODAL)


class AggEngine:
    """Aggregation engines (reference ``comps/__init__.py:13-16``)."""

    DECENTRALIZED_SGD = "dSGD"
    RANK_DAD = "rankDAD"
    POWER_SGD = "powerSGD"

    ALL = (DECENTRALIZED_SGD, RANK_DAD, POWER_SGD)


# ---------------------------------------------------------------------------
# Task-specific argument blocks
# ---------------------------------------------------------------------------


@dataclass
class FSArgs:
    """FreeSurfer classification parameters (reference ``compspec.json:225-250``)."""

    labels_file: str = "site0_covariates.csv"
    data_column: str = "freesurferfile"
    labels_column: str = "isControl"
    input_size: int = 66
    hidden_sizes: tuple = (256, 128, 64, 32)
    num_class: int = 2
    dad_reduction_rank: int = 10
    dad_num_pow_iters: int = 5
    dad_tol: float = 1e-3
    # warm-start rankDAD's subspace Ω from the previous round (engine state;
    # engines/rankdad.py) — the tol early-exit then fires after 1-2 power
    # iterations instead of dad_num_pow_iters. False = stateless cold starts.
    dad_warm_start: bool = True
    split_files: tuple = ()
    # reproduce the reference's string-label bug bit-for-bit: EVERY string
    # maps via (s.lower() == 'true'), so "1" → 0 (comps/fs/__init__.py:25-26);
    # default False parses numeric strings numerically (documented deviation,
    # data/freesurfer.py coerce_label)
    bug_compatible_labels: bool = False


@dataclass
class ICAArgs:
    """ICA classification parameters (reference ``compspec.json:251-281``,
    ``datasets/icalstm/inputspec.json:1-88``)."""

    data_file: str = ""
    labels_file: str = ""
    num_class: int = 2
    monitor_metric: str = "auc"
    metric_direction: str = "maximize"
    log_header: str = "Loss|AUC"
    num_components: int = 100
    temporal_size: int = 980
    window_size: int = 10
    window_stride: int = 10
    input_size: int = 256
    # The compspec template default is 384 (compspec.json:267) but the actual
    # shipped workload uses 348 (datasets/icalstm/inputspec.json, both sites) —
    # we default to the workload value so config, bench, and fixtures agree.
    hidden_size: int = 348
    num_layers: int = 1
    bidirectional: bool = True
    dad_reduction_rank: int = 10
    dad_num_pow_iters: int = 5
    dad_tol: float = 1e-3
    dad_warm_start: bool = True  # see FSArgs.dad_warm_start
    split_files: tuple = ()
    # parity-only fields: present in compspec.json:261-264 but never read by
    # the reference trainers (grep: no seq_len/components_file use in comps/)
    seq_len: int = 13
    components_file: str = ""
    # TPU extension: "bfloat16" runs encoder/LSTM matmuls in bf16 with f32
    # accumulation (~MXU-native mixed precision); "" = full f32 (parity)
    compute_dtype: str = ""


@dataclass
class SMRI3DArgs:
    """3D sMRI classification parameters (TPU-build extension; BASELINE.json
    configs: '3D-CNN sMRI (T1w volumes) federated classifier, 8 sites')."""

    data_file: str = ""
    labels_file: str = ""
    num_class: int = 2
    volume_shape: tuple = (64, 64, 64)
    channels: tuple = (16, 32, 64, 128)
    # "bfloat16" = bf16 convolutions with f32 BatchNorm/head; "" = full f32
    compute_dtype: str = ""
    # fold 2x2x2 spatial blocks into 8 channels before conv_0 (3.7-6.9x
    # faster on TPU; changes the architecture, so old checkpoints need False)
    space_to_depth: bool = False
    dad_reduction_rank: int = 10
    dad_num_pow_iters: int = 5
    dad_tol: float = 1e-3
    dad_warm_start: bool = True  # see FSArgs.dad_warm_start
    split_files: tuple = ()


@dataclass
class MultimodalArgs:
    """Multimodal FS+ICA transformer parameters (TPU-build extension;
    BASELINE.json configs: 'Multimodal FS+ICA Transformer, 64-site DP-SGD')."""

    data_file: str = ""
    labels_file: str = ""
    data_column: str = "freesurferfile"
    labels_column: str = "isControl"
    num_class: int = 2
    fs_input_size: int = 66
    num_components: int = 100
    temporal_size: int = 980
    window_size: int = 10
    window_stride: int = 10
    embed_dim: int = 256
    num_heads: int = 8
    num_layers: int = 4
    mlp_ratio: int = 4
    # "" = auto: ring attention iff model_axis_size > 1; "local"/"ring" force
    attention: str = ""
    # "bfloat16" = bf16 matmuls with f32 softmax/LayerNorm; "" = full f32
    compute_dtype: str = ""
    dad_reduction_rank: int = 10
    dad_num_pow_iters: int = 5
    dad_tol: float = 1e-3
    dad_warm_start: bool = True  # see FSArgs.dad_warm_start
    split_files: tuple = ()


@dataclass
class PretrainArgs:
    """Pretraining arguments (reference ``compspec.json:128-148``)."""

    epochs: int = 0
    learning_rate: float = 1e-3
    batch_size: int = 16
    local_iterations: int = 1
    validation_epochs: int = 1
    pin_memory: bool = False
    num_workers: int = 0
    patience: int = 51


# ---------------------------------------------------------------------------
# The main config
# ---------------------------------------------------------------------------


@dataclass
class TrainConfig:
    """Full training configuration.

    Field names and defaults mirror the reference compspec
    (``compspec.json:32-224``) plus the ``COINNLocal`` call-site kwargs
    (``local.py:31-37``). One flat dataclass replaces the reference's
    cache-dict-of-everything.
    """

    # --- task selection (compspec.json:32-55)
    task_id: str = NNComputation.TASK_FREE_SURFER
    mode: str = "train"  # train | test
    # --- aggregation (compspec.json:56-79)
    agg_engine: str = AggEngine.DECENTRALIZED_SGD
    num_reducers: int = 2  # no-op on TPU (reduction is a collective); kept for parity
    # --- training loop (compspec.json:80-224, local.py:31-37)
    batch_size: int = 16
    local_iterations: int = 1  # gradient accumulation steps
    learning_rate: float = 1e-3
    epochs: int = 101
    pretrain: bool = False
    pretrain_args: PretrainArgs | None = None
    validation_epochs: int = 1
    # payload dtype for gradient exchange: "32" | "16" (bf16 — the TPU-native
    # 16-bit type) | "16-ieee" (the reference's literal fp16, compat mode —
    # compspec.json:161-176)
    precision_bits: str = "32"
    pin_memory: bool = False  # torch DataLoader parity no-op
    num_workers: int = 0  # torch DataLoader parity no-op
    patience: int = 35
    split_ratio: tuple = (0.8, 0.1, 0.1)
    num_folds: int | None = None  # mutually exclusive with split_ratio
    # --- trainer extras (local.py:31-37)
    num_class: int = 2
    monitor_metric: str = "auc"
    metric_direction: str = "maximize"
    log_header: str = "loss|auc"
    # warm start from a saved checkpoint's params (the reference library's
    # load-pretrained capability implied by best_val_epoch/pretrain semantics,
    # SURVEY.md §5 checkpoint/resume); "" = train from init
    pretrained_path: str = ""
    dataloader_args: dict = field(default_factory=lambda: {"train": {"drop_last": True}})
    seed: int = 0
    optimizer: str = "adam"  # coinstac-dinunet trains with Adam at `learning_rate`
    # --- task args
    fs_args: FSArgs = field(default_factory=FSArgs)
    ica_args: ICAArgs = field(default_factory=ICAArgs)
    smri3d_args: SMRI3DArgs = field(default_factory=SMRI3DArgs)
    multimodal_args: MultimodalArgs = field(default_factory=MultimodalArgs)
    # --- TPU-build extras
    num_sites: int = 2
    sites_per_device: int = 1  # >1 folds several simulated sites onto one chip
    # multi-slice scale-out (r18, parallel/mesh.py sliced_site_mesh): > 1
    # lays an OUTER `slice` mesh axis over the site tier — sites spread
    # num_slices ways, intra-slice aggregation rides ICI and ONE inter-slice
    # hop per round crosses DCN carrying the already-reduced per-slice
    # partial (quantized by dcn_wire_quant). 1 (default) is the legacy
    # single-mesh program, bit-identical (S005 "slices-off"). Emulated on
    # virtual CPU devices in one process; real hosts launch one
    # runner/dcn_worker.py process per slice.
    num_slices: int = 1
    # the INTER-SLICE wire codec, independent of the intra-slice `wire_quant`
    # ("" = follow wire_quant): "none" ships the per-slice partial fused with
    # the intra-slice reduce (no slice-boundary re-quantization — sliced
    # trajectories stay bit-exact vs unsliced); "bf16"/"int8"/"fp8" re-
    # quantize the partial before the DCN hop, landing the shrink exactly
    # where bandwidth is scarcest (S002-proven per-tier wire models).
    dcn_wire_quant: str = ""
    # slice-quorum floor (r19 slice elasticity, trainer/steps.py): on a
    # sliced mesh with a slice-fault plan, a round with fewer LIVE slices
    # than this HOLDS — params/optimizer/engine/health frozen, NaN loss,
    # held_rounds telemetry — instead of training on a rump cohort. 1
    # (default) trains whenever any slice survives; only meaningful with
    # num_slices > 1 (rejected otherwise).
    min_slices: int = 1
    # sequence/model parallelism (SURVEY.md §2.2 TPU extension): >1 builds a
    # (site, model) mesh; each site's model shards its sequence axis over the
    # model axis — ICALstm runs its BiLSTM as a ring LSTM, the multimodal
    # transformer uses ring attention (runner/registry.py wires both). Needs
    # num_sites × model_axis_size devices.
    model_axis_size: int = 1
    # ring-LSTM wavefront pipelining (parallel/sequence.py): number of batch
    # microbatches per ring stage. 0 = auto (minimize 8-row MXU tile work);
    # 1 = the unpipelined masked wavefront; must divide the batch size.
    # Only meaningful with model_axis_size > 1 on an LSTM task.
    sequence_microbatches: int = 0
    # rounds-leading scan xs for the epoch loop (trainer/steps.py): the
    # default trades ~1x the epoch-input size in peak HBM residency for
    # +9.5-21% throughput (docs/bench_scanxs_ab_r5.jsonl). False switches to
    # the per-round dynamic-slice arm — the escape hatch for multi-GB epoch
    # inputs where that residency bump matters more than the speed.
    rounds_scan_xs: bool = True
    # input pipeline (trainer/loop.py): "device" (default) uploads each
    # site's inventory to the mesh once per fit and drives every epoch from a
    # compact [S, steps, B] int32 index plan — the jitted epoch gathers
    # batches on-device, so per-epoch host→device traffic is index-plan
    # bytes, not dataset bytes (plus a double-buffered background planner
    # building epoch N+1's plan while epoch N runs). "host" is the legacy
    # dense path: plan_epoch re-materializes [S, steps, B, ...] on the host
    # and ships it every epoch (the A/B arm, and the escape hatch if the
    # padded inventory grid itself cannot fit in HBM).
    pipeline: str = "device"
    # donate the carried TrainState's buffers to the epoch program
    # (jax.jit donate_argnums): the update writes in place instead of
    # allocating a second params+optimizer copy per epoch. The trainer
    # snapshots best-state selections, so donation is transparent; False
    # restores the copying behavior.
    donate_epoch_state: bool = True
    # non-empty → persistent XLA compilation cache at this directory
    # (jax compilation_cache): re-runs and later folds of the same
    # (engine, topology) program load the compiled epoch from disk instead
    # of recompiling. CLI: --compile-cache DIR.
    compile_cache_dir: str = ""
    # non-empty → wrap each fit() in jax.profiler.trace(profile_dir) and
    # write a TensorBoard-compatible device trace there (SURVEY.md §5: the
    # reference only has wall-clock duration lists; this is the TPU upgrade)
    profile_dir: str = ""
    # unified telemetry (telemetry/): "on" threads the span tracer through
    # the fit, accumulates on-device per-round per-site metrics (grad/update
    # norms, engine residual, payload bytes) in TrainState.telemetry, and
    # writes manifest.json / metrics.jsonl / Perfetto-loadable trace files
    # under <out_dir>/telemetry/fold_<k>. "off" (default) statically
    # compiles the device metrics out — the epoch program is bitwise-equal
    # to the pre-telemetry one (same pattern as quarantine_rounds=-1).
    telemetry: str = "off"
    # non-empty → telemetry artifacts land here instead of
    # <out_dir>/telemetry (useful when out_dir is unset or shared)
    telemetry_dir: str = ""
    # non-empty → jax.profiler capture around the xprof_window epoch range
    # only (CLI --xprof-dir). Windowed alternative to profile_dir (which
    # traces the WHOLE fit); the two are mutually exclusive per fit.
    xprof_dir: str = ""
    # (first, last) epochs of the xprof capture window, 1-based inclusive
    xprof_window: tuple = (1, 1)
    # buffered-async aggregation (r13 elastic rounds, trainer/steps.py): a
    # positive bound switches every engine to staleness-bounded buffered
    # aggregation — each virtual site's LAST deposited update keeps
    # contributing, weighted by staleness_decay^age, until its age exceeds
    # the bound (then masked exactly like a dead site). 0 (default) is the
    # bulk-synchronous path, statically compiled to the exact legacy program
    # (lowering-identical; checks/semantic.py S005 "async-off").
    staleness_bound: int = 0
    # per-round-of-age weight multiplier for buffered contributions; 1.0
    # keeps stale updates at full weight until the bound cuts them off
    staleness_decay: float = 0.5
    # quantized collective wires (r14, parallel/collectives.py WireCodec):
    # "none" (default) keeps the legacy precision_bits wire byte-for-byte
    # (program-identical; S005-gated); "bf16" forces a bf16 wire; "int8" /
    # "fp8" quantize every engine payload (dSGD deltas, rankDAD/powerSGD
    # factors) to a 1-byte grid with a scale per payload before the
    # collective, dequantizing after the reduce — ~4x fewer wire bytes than
    # f32, proven exactly by checks/semantic.py S002 against the traced
    # program. Matmul precision stays governed by precision_bits.
    wire_quant: str = "none"
    # stochastic rounding on the int8 wire grid (unbiased in expectation;
    # value-hashed dither, no RNG state): False = round-to-nearest-even
    wire_stochastic: bool = False
    # fused Pallas power-iteration kernel (r14, ops/poweriter_pallas.py):
    # one VMEM-resident kernel per rank class for the rankDAD subspace
    # iteration — no HBM round trips between power refinements. None =
    # auto (on for the TPU backend, off elsewhere); False = the exact
    # legacy XLA loop (program-identical, S005-gated); True forces the
    # kernel (interpret-mode on CPU — parity tests / A/B bench).
    fused_poweriter: bool | None = None
    # overlapped rounds (r14, trainer/steps.py): issue round t's
    # aggregation collective while round t+1's batch gather + compute run
    # (double-buffered TrainState.overlap stash; one-round-delayed
    # pipelined update). False (default) compiles the exact legacy round
    # (S005-gated). Mutually exclusive with staleness_bound > 0.
    overlap_rounds: bool = False
    # fault tolerance (robustness/): a site whose round gradient is
    # non-finite for this many CONSECUTIVE rounds is quarantined — zero
    # weight for the rest of the fit, params advance on the live sites'
    # aggregate. 0 keeps the per-round non-finite skip but never quarantines;
    # -1 statically compiles the whole fault machinery out of the epoch
    # program (exact pre-robustness program; liveness masks still work when a
    # FaultPlan is given).
    quarantine_rounds: int = 3
    # byzantine-robust aggregation (r17, parallel/collectives.py
    # ROBUST_AGGS): "none" (default) keeps the renormalizing weighted mean
    # program-identically (S005-gated); "norm_clip" clips each site's
    # gradient norm to robust_clip_mult × the live-weighted median site norm
    # before the UNCHANGED weighted-mean wire (composes with wire_quant);
    # "trimmed_mean" / "coordinate_median" swap the psum-shaped exchange for
    # a cross-site gather + per-coordinate robust reduce (wire grows —
    # S002-proven per engine). Any non-"none" mode also switches on the
    # anomaly-scored reputation layer (robustness/health.py).
    robust_agg: str = "none"
    # fraction of total live weight trimmed from EACH tail by the
    # trimmed-mean reducer; must exceed the hostile weight fraction for the
    # defense to hold (f attackers of S equal sites need trim_frac > f/S)
    robust_trim_frac: float = 0.2
    # norm_clip threshold multiplier over the live-weighted median site norm
    robust_clip_mult: float = 2.5
    # reputation layer (robust_agg != "none"): a live site whose per-round
    # anomaly z-score (max of distance-to-robust-aggregate and gradient-norm
    # z across the live cohort) exceeds reputation_z for reputation_rounds
    # CONSECUTIVE rounds trips the same sticky quarantine flag as a NaN
    # streak. reputation_rounds=0 scores without quarantining. z-scores top
    # out at (S_live-1)/sqrt(S_live), so small cohorts need a lower z.
    reputation_z: float = 2.0
    reputation_rounds: int = 8
    # --- privacy plane (r20, privacy/) ---------------------------------
    # in-scan DP-SGD (privacy/dpsgd.py): dp_clip > 0 clips each site's
    # round-gradient L2 norm to this C inside the per-site phase (before
    # engine compression); dp_noise_multiplier > 0 then adds σ·C Gaussian
    # noise per leaf, counter-keyed by (dp_seed, site, round) so replays
    # are chunk/resume/packing-independent. Both 0 (default) statically
    # compiles the mechanism out — the epoch program is bit-identical to
    # the legacy one (S005 "dp-off"). Noise needs a clip (rejected
    # otherwise: unbounded sensitivity has no DP guarantee).
    dp_clip: float = 0.0
    dp_noise_multiplier: float = 0.0
    dp_seed: int = 0
    # δ for the reported (ε, δ); the RDP accountant (privacy/accounting.py)
    # surfaces ε per epoch in telemetry rows, logs.json, the report CLI and
    # the train_epsilon /statusz gauge
    dp_delta: float = 1e-5
    # > 0: stop the fit cleanly once the accountant's ε reaches this budget
    # — the epoch completes, its rotating checkpoint lands, a "dp-budget"
    # event is recorded, and the fit proceeds to best-state test (the
    # Preempted-style checkpointed exit, minus the nonzero exit code)
    dp_epsilon_budget: float = 0.0
    # secure-aggregation masked wires (privacy/secure_agg.py, dSGD only):
    # "mask" encodes each site's weighted delta on a shared fixed-point
    # grid and one-time-pads it with pairwise antisymmetric int32 masks
    # that cancel EXACTLY (integer arithmetic) in the unchanged psum-shaped
    # wire — masked == unmasked bit-exact, wire bytes unchanged
    # (S002-proven), int8/fp8 codecs refused (float grids shred the pads;
    # bf16 composes by pre-rounding the payload). "mask-nopads" is the
    # pads-zeroed VERIFICATION arm the bit-exactness claim is asserted
    # against; "off" (default) is the bit-identical legacy program
    # (S005 "secureagg-off").
    secure_agg: str = "off"
    secure_agg_seed: int = 0
    # personalized per-site heads (privacy/personalize.py): param-path
    # substring patterns naming head leaves kept OUT of aggregation
    # entirely — per-site head rows ride TrainState.personal (P(site),
    # checkpointed, rejoin-reset), each site trains and evaluates its own
    # head. () (default) compiles none of it (S005 "personalize-off").
    personalize: tuple = ()

    # -- helpers ---------------------------------------------------------

    def task_args(self):
        if self.task_id == NNComputation.TASK_FREE_SURFER:
            return self.fs_args
        if self.task_id == NNComputation.TASK_ICA:
            return self.ica_args
        if self.task_id == NNComputation.TASK_SMRI_3D:
            return self.smri3d_args
        if self.task_id == NNComputation.TASK_MULTIMODAL:
            return self.multimodal_args
        raise ValueError(f"Invalid task: {self.task_id}")

    def replace(self, **kw) -> "TrainConfig":
        return dataclasses.replace(self, **kw)

    def with_overrides(self, overrides: dict) -> "TrainConfig":
        """Apply a flat override dict (e.g. one site's inputspec values).

        Unknown keys are routed into the active task-args block when they match
        one of its fields (the reference dumps everything into one cache dict;
        we keep the namespacing but accept the flat form).
        """
        # Accept compspec-style block keys ("FS-Classification_args") as well
        # as our field names ("fs_args").
        overrides = {_COMPSPEC_KEY_ALIASES.get(k, k): v for k, v in overrides.items()}
        cfg = self
        flat = {}
        for k, v in overrides.items():
            if k in _TRAIN_FIELDS and k not in _BLOCK_FIELDS:
                flat[k] = _coerce(_TRAIN_FIELDS[k], v)
        cfg = dataclasses.replace(cfg, **flat)

        # Dataclass-typed blocks: a dict override merges into the block
        # (the GUI sends plain JSON objects for type="object" fields).
        for args_name, args_cls in _BLOCK_FIELDS.items():
            current = getattr(cfg, args_name)
            if current is None and overrides.get(args_name) is None:
                continue  # don't materialize an unset optional block (even on
                # an explicit JSON null override)
            block = current or args_cls()
            fields = {f.name: f for f in dataclasses.fields(args_cls)}
            upd = {}
            if isinstance(overrides.get(args_name), dict):
                upd.update(
                    {k: _coerce(fields[k], v) for k, v in overrides[args_name].items() if k in fields}
                )
            elif dataclasses.is_dataclass(overrides.get(args_name)):
                block = overrides[args_name]
            if args_name != "pretrain_args":
                # flat keys route into every matching task-args block (the
                # reference dumps everything into one cache dict)
                upd.update(
                    {k: _coerce(fields[k], v) for k, v in overrides.items() if k in fields}
                )
            if upd:
                block = dataclasses.replace(block, **upd)
            if block is not getattr(cfg, args_name):
                cfg = dataclasses.replace(cfg, **{args_name: block})
        return cfg

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


_TRAIN_FIELDS = {f.name: f for f in dataclasses.fields(TrainConfig)}
_COMPSPEC_KEY_ALIASES = {
    "FS-Classification_args": "fs_args",
    "ICA-Classification_args": "ica_args",
    "sMRI-3D-Classification_args": "smri3d_args",
    "Multimodal-Classification_args": "multimodal_args",
}
#: dataclass-typed TrainConfig fields that take dict merges, not raw replacement
_BLOCK_FIELDS = {
    "fs_args": FSArgs,
    "ica_args": ICAArgs,
    "smri3d_args": SMRI3DArgs,
    "multimodal_args": MultimodalArgs,
    "pretrain_args": PretrainArgs,
}


def _coerce(f: dataclasses.Field, v: Any) -> Any:
    """Light type coercion: lists → tuples for tuple-typed fields, GUI string
    numbers → numbers are left as-is (the reference treats precision_bits as a
    string select)."""
    if isinstance(v, list) and (f.type or "").startswith("tuple"):
        return tuple(v)
    return v


# ---------------------------------------------------------------------------
# inputspec.json loading (simulator per-site overrides)
# ---------------------------------------------------------------------------


def load_inputspec(path: str) -> list[dict]:
    """Load a COINSTAC simulator ``inputspec.json``.

    The file is a list (one entry per site) of ``{"key": {"value": v}}``
    envelopes (reference ``datasets/test_fsl/inputspec.json``). A single dict is
    accepted as a 1-site spec. Returns a list of flat per-site override dicts.
    """
    with open(path) as fh:
        spec = json.load(fh)
    if isinstance(spec, dict):
        spec = [spec]
    out = []
    for site in spec:
        flat = {}
        for k, v in site.items():
            flat[k] = v.get("value") if isinstance(v, dict) and "value" in v else v
        out.append(flat)
    return out


def resolve_site_configs(
    base: TrainConfig, dataset_dir: str, num_sites: int | None = None
) -> list[TrainConfig]:
    """Build per-site configs for a ``datasets/<name>`` tree.

    Reads ``<dataset_dir>/inputspec.json`` if present; site i gets entry
    ``i % len(spec)``, cycling through the spec entries when there are more
    sites than entries.
    """
    spec_path = os.path.join(dataset_dir, "inputspec.json")
    overrides: Sequence[dict] = [{}]
    if os.path.exists(spec_path):
        overrides = load_inputspec(spec_path)
    n = num_sites if num_sites is not None else len(overrides)
    return [base.with_overrides(overrides[i % len(overrides)]) for i in range(n)]


# ---------------------------------------------------------------------------
# compspec schema export (GUI metadata parity)
# ---------------------------------------------------------------------------

#: GUI metadata for each flag: (type, source, group, order, conditional, label)
#: — preserved from reference ``compspec.json`` so the schema can be re-emitted.
COMPSPEC_META: dict[str, dict] = {
    "task_id": dict(type="select", source="owner", group="NN Params", order=3,
                    values=list(NNComputation.ALL),
                    label="Pick a NN task:"),
    "mode": dict(type="select", source="owner", group="NN Params", order=4,
                 values=["train", "test"], label="NN Mode:"),
    "agg_engine": dict(type="select", source="owner", group="NN Params", order=5,
                       values=list(AggEngine.ALL),
                       conditional=dict(variable="mode", value="train"),
                       label="Pick aggregation engine:"),
    "num_reducers": dict(type="number", source="owner", group="NN Params", order=6,
                         label="Number of reducers in the aggregator(Depends on number of sites):"),
    "batch_size": dict(type="number", source="owner", group="NN Params", order=7,
                       label="Batch size:"),
    "local_iterations": dict(
        type="number", source="owner", group="NN Params", order=8,
        label="Local gradient accumulation iterations"
              "(effective batch size = batch size * gradient accumulation iterations)"),
    "learning_rate": dict(type="number", source="owner", group="NN Params", order=9,
                          conditional=dict(variable="mode", value="train"),
                          label="Learning rate:"),
    "epochs": dict(type="number", source="owner", group="NN Params", order=10,
                   conditional=dict(variable="mode", value="train"), label="Epochs:"),
    "pretrain": dict(type="boolean", source="owner", group="NN Params", order=11,
                     label="Use the site with maximum data to pre-train locally as starting point:"),
    "pretrain_args": dict(type="object", source="owner", group="NN Params", order=12,
                          conditional=dict(variable="pretrain", value=True),
                          label="Pretraining arguments:"),
    "validation_epochs": dict(type="number", source="owner", group="NN Params", order=13,
                              conditional=dict(variable="mode", value="train"),
                              label="Run validation after every epochs:"),
    "precision_bits": dict(type="select", source="owner", group="NN Params", order=14,
                           # "16" = bf16 on TPU; "16-ieee" = the reference's
                           # literal fp16 payload (compat)
                           values=["32", "16", "16-ieee"],
                           conditional=dict(variable="mode", value="train"),
                           label="Floating point precision for payload:"),
    "pin_memory": dict(type="boolean", source="member", group="NN Params", order=15,
                       label="Pin Memory:"),
    "num_workers": dict(type="number", source="member", group="NN Params", order=16,
                        label="Number of workers:"),
    "patience": dict(type="number", source="owner", group="NN Params", order=17,
                     conditional=dict(variable="mode", value="train"),
                     label="Early stopping patience epochs:"),
    "split_ratio": dict(type="object", source="owner", group="NN Params", order=21,
                        label="Data split ratio for train, validation, test in the same order:"),
    "num_folds": dict(type="number", source="owner", group="NN Params", order=22,
                      label="Number of folds for K-Fold Cross Validation"
                            "(Mutually exclusive with split ratio):"),
    "fs_args": dict(type="object", source="owner", group="Computation", order=23,
                    conditional=dict(variable="task_id", value="FS-Classification"),
                    label="FreeSurfer classification parameters.",
                    compspec_key="FS-Classification_args"),
    "ica_args": dict(type="object", source="owner", group="Computation", order=26,
                     conditional=dict(variable="task_id", value="ICA-Classification"),
                     label="ICA classification parameters.",
                     compspec_key="ICA-Classification_args"),
    "smri3d_args": dict(type="object", source="owner", group="Computation", order=27,
                        conditional=dict(variable="task_id", value="sMRI-3D-Classification"),
                        label="3D sMRI classification parameters.",
                        compspec_key="sMRI-3D-Classification_args"),
    "multimodal_args": dict(type="object", source="owner", group="Computation", order=28,
                            conditional=dict(variable="task_id", value="Multimodal-Classification"),
                            label="Multimodal FS+ICA transformer parameters.",
                            compspec_key="Multimodal-Classification_args"),
}


def export_compspec(cfg: TrainConfig | None = None) -> dict:
    """Emit a COINSTAC-style compspec dict (schema + defaults) for this build."""
    cfg = cfg or TrainConfig()
    inputs: dict[str, Any] = {}
    for name, meta in COMPSPEC_META.items():
        default = getattr(cfg, name)
        if dataclasses.is_dataclass(default):
            default = dataclasses.asdict(default)
        entry = {"default": _jsonable(default), **{k: v for k, v in meta.items() if k != "compspec_key"}}
        inputs[meta.get("compspec_key", name)] = entry
    return {
        "meta": {
            "name": "Decentralized Deep Artificial Neural Networks on TPU",
            "id": "dinunet-tpu",
            "version": "v1.0.0",
            "repository": "local",
            "description": "TPU-native federated NN training: sites on a mesh axis, "
                           "aggregation via XLA collectives.",
        },
        "computation": {"input": inputs, "output": {}, "type": "tpu-spmd"},
    }


def _jsonable(v):
    if isinstance(v, tuple):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {k: _jsonable(x) for k, x in v.items()}
    return v
