"""Multi-host (DCN) runtime — scaling the site mesh past one host.

The reference scales out by running one Docker container per site on
whatever machines the COINSTAC pipeline coordinator can reach, shipping
JSON payloads over the network every round (reference ``entry.py:5``,
``compspec.json:284-296``). The TPU-native equivalent keeps the exact same
trust topology — one coordinator, N workers — but swaps the transport for
XLA collectives:

- :func:`distributed_init` is the COINSTAC-coordinator equivalent: it brings
  up JAX's multi-process runtime so every host's chips join one global device
  set (DCN between hosts, ICI within).
- :func:`multihost_site_mesh` lays the ``(site, model)`` mesh over that
  device set **hybrid-style**: the ``model`` (sequence/tensor) axis is packed
  inside a host's ICI domain where bandwidth is highest, while the ``site``
  axis spans hosts — so the only traffic that crosses DCN is the once-per-round
  gradient aggregation, mirroring the reference's site-local-compute /
  central-aggregation split (SURVEY.md §2.2 "Communication backend").

Everything downstream (trainer/steps.py, engines/) is topology-agnostic:
collectives take the axis *name*, so the same compiled program runs on a
single chip, an 8-chip slice, or a multi-host pod — only the mesh changes.
"""

from __future__ import annotations

import jax
import numpy as np

from ..robustness.retry import with_retry
from .mesh import MODEL_AXIS, SITE_AXIS, SLICE_AXIS, site_axis_of
from jax.sharding import NamedSharding, PartitionSpec as P

_initialized = False

#: wall-clock budget for the whole coordinator join (retries included): a
#: coordinator that never comes up fails the worker in ~2 minutes instead of
#: retrying forever — preemptible fleets must recycle the slot, not camp on it
JOIN_DEADLINE_S = 120.0
#: per-attempt cap: one hung initialize (half-open TCP, wedged coordinator)
#: is abandoned to its worker thread and retried, instead of blocking the
#: process indefinitely (robustness/retry.py timeout_s semantics)
JOIN_ATTEMPT_TIMEOUT_S = 45.0


def distributed_init(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    join_deadline_s: float | None = JOIN_DEADLINE_S,
    join_timeout_s: float | None = JOIN_ATTEMPT_TIMEOUT_S,
    **kwargs,
) -> bool:
    """Join (or skip joining) the multi-host runtime.

    Returns ``True`` when a multi-process runtime was initialized, ``False``
    for the single-process case (``num_processes`` in (None, 1) with no
    coordinator given) — callers can branch on it for logging only; nothing
    else changes downstream.

    With all arguments ``None``, JAX's own cluster autodetection applies
    (TPU pod metadata, SLURM, etc.), so on a real pod this is simply
    ``distributed_init(coordinator_address="host0:1234", num_processes=N,
    process_id=rank)`` or no args at all.

    A worker that comes up before its coordinator (pod rollout races, spot
    restarts) retries the join under jittered exponential backoff
    (robustness/retry.py) instead of dying on the first refused connection —
    but fail-FAST, not forever: ``join_deadline_s`` bounds the whole join
    wall-clock and ``join_timeout_s`` abandons a single hung attempt (a
    wedged coordinator that accepts the TCP connect and then never
    completes the handshake used to hang the worker indefinitely). Pass
    ``None`` for either to restore the unbounded behavior.
    """
    global _initialized
    if coordinator_address is None and num_processes in (None, 1):
        return False
    if _initialized:  # idempotent use — NB: probing jax.process_count()
        return True   # here would initialize the backend and make
    # jax.distributed.initialize() below raise ("must be called before any
    # JAX calls"), so idempotency is tracked by module flag only

    if _jax_distributed_client() is not None:
        # the runtime was initialized by code OUTSIDE this module (our flag is
        # False but jax's global client exists): we don't own it, so no retry
        # and ABSOLUTELY no reset — let jax raise its own clear
        # "should only be called once" error, exactly as before this wrapper
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            **kwargs,
        )

    def _attempt_initialize():
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
                **kwargs,
            )
        except (RuntimeError, OSError):
            _dcn_counter("dcn_join_retries_total")
            # the retryable failure modes: coordinator not up yet (refused
            # connect → ConnectionError ⊂ OSError), DNS/socket errors, and
            # jaxlib surfacing a failed join as RuntimeError/XlaRuntimeError.
            # A failed connect leaves jax's module-global client/service SET
            # (State.initialize assigns self.client before connect() and has
            # no failure cleanup), so a bare retry would die on "initialize
            # should only be called once" instead of retrying the join —
            # clear the partial state first. Non-retryable errors (bad
            # arguments → ValueError/TypeError) propagate untouched: no
            # retry will follow, so there is no partial state to clear for.
            _reset_partial_distributed_state()
            raise

    from ..robustness.retry import RetryTimeout

    try:
        with_retry(
            _attempt_initialize,
            attempts=3,
            base_delay=0.5,
            retry_on=(RuntimeError, OSError, ConnectionError),
            describe="jax.distributed.initialize",
            deadline_s=join_deadline_s,
            timeout_s=join_timeout_s,
            # a TIMED-OUT join is fatal, not retryable: the abandoned
            # attempt's thread may still be mutating jax's global
            # distributed state, and a concurrent re-initialize would race
            # it — fast failures (refused connect) still retry via retry_on
            retry_on_timeout=False,
        )()
    except RetryTimeout:
        # the hung-coordinator fail-fast path: surfaced on the live bus so
        # a fleet supervisor sees "joins are timing out", not just dying
        _dcn_counter("dcn_join_timeouts_total")
        raise
    _initialized = True
    return True


def _dcn_counter(name: str, **labels) -> None:
    """Best-effort live-bus counter for DCN runtime events (join retries,
    join timeouts — the r19 dcn_timeout observability). The bus is never
    load-bearing here: a half-imported telemetry layer (early interpreter
    teardown, exotic embedding) must not turn a join failure into a
    different failure."""
    try:
        from ..telemetry.bus import global_bus

        # API-boundary forward: NAME is a literal at every call site
        global_bus().counter(name, **labels)  # jaxlint: disable=R007
    # observability only — the join path's own exception must propagate,
    # never be replaced by a bus import/publish error
    except Exception:  # jaxlint: disable=R002
        pass


def _jax_distributed_client():
    """jax's module-global distributed client, or None (guarded private-API
    probe — used only to detect a runtime initialized outside this module)."""
    state = getattr(getattr(jax, "_src", None), "distributed", None)
    state = getattr(state, "global_state", None)
    return getattr(state, "client", None)


def _reset_partial_distributed_state() -> None:
    """Best-effort teardown of a PARTIALLY-initialized jax.distributed state
    (client constructed, connect failed), so the next initialize attempt
    starts clean. ``shutdown()`` is the public reset, but it can itself raise
    on a never-connected client (``client.shutdown()`` precedes ``client =
    None``); fall back to nulling the global state's handles directly."""
    try:
        jax.distributed.shutdown()
        return
    except (RuntimeError, OSError, AttributeError):
        # the shutdown-on-partial-state failure modes: RuntimeError (incl.
        # XlaRuntimeError) from a never-connected client's shutdown(),
        # OSError from the socket teardown, AttributeError when the state
        # object predates/postdates the private-API shape we probe — in all
        # of them we fall through to nulling the handles directly
        pass
    state = getattr(getattr(jax, "_src", None), "distributed", None)
    state = getattr(state, "global_state", None)
    if state is not None:
        for attr in ("client", "service", "preemption_sync_manager"):
            try:
                setattr(state, attr, None)
            except AttributeError:
                # a jax version exposing this as a read-only/absent slot:
                # skip that handle, best-effort by design
                pass


def distributed_shutdown() -> None:
    """Tear down the multi-host runtime and clear the idempotency flag, so
    ``distributed_init`` is re-entrant (worker restarts within one process,
    coordinated test harnesses). A no-op when nothing was initialized."""
    global _initialized
    try:
        if _initialized:
            jax.distributed.shutdown()
    finally:
        # clear the flag even when shutdown() raises (wedged peer, never-
        # connected client): the runtime is gone either way, and a stale True
        # would make every later distributed_init a silent no-op
        _initialized = False


def multihost_site_mesh(
    sites_per_process: int | None = None,
    model_axis_size: int = 1,
    devices: list | None = None,
) -> jax.sharding.Mesh:
    """A global ``(site, model)`` mesh over every process's devices.

    The ``model`` axis is contiguous within each process's ICI domain; the
    ``site`` axis tiles processes outer-most, so cross-site collectives (the
    per-round aggregation) are the only DCN traffic. Single-process callers
    get the same mesh :func:`parallel.mesh.make_site_mesh` would build.

    ``sites_per_process`` defaults to ``local devices // model_axis_size``.
    """
    n_proc = jax.process_count()
    devices = devices if devices is not None else jax.devices()
    per_proc = len(devices) // n_proc
    if sites_per_process is None:
        sites_per_process = max(per_proc // model_axis_size, 1)
    need = sites_per_process * model_axis_size
    if need > per_proc:
        raise ValueError(
            f"{sites_per_process} sites × model={model_axis_size} needs "
            f"{need} devices per process, have {per_proc}"
        )
    if need < per_proc:
        # surplus chips idle (same contract as make_site_mesh's devices[:need]
        # subset on one host): take each process's leading devices
        by_proc: dict[int, list] = {}
        for d in devices:
            by_proc.setdefault(d.process_index, []).append(d)
        devices = [d for p in sorted(by_proc) for d in by_proc[p][:need]]
    if n_proc == 1:
        from .mesh import make_site_mesh

        return make_site_mesh(sites_per_process, devices, model_axis_size)
    from jax.experimental import mesh_utils

    # per-ICI-slice shape × DCN shape: sites stack across processes (outer),
    # the model axis never leaves a process. The DCN granule is the TPU
    # slice when slices map 1:1 to processes (the usual pod config — gives
    # ICI-topology-aware ordering within each slice); otherwise the process
    # itself (mesh_utils' documented fallback for platforms without usable
    # slice_index — e.g. multi-process CPU, where every device reports
    # slice 0 and slice-granule mode would reject the (n_proc, 1) shape).
    slice_ids = {getattr(d, "slice_index", None) for d in devices}
    by_process = None in slice_ids or len(slice_ids) != n_proc
    arr = mesh_utils.create_hybrid_device_mesh(
        mesh_shape=(sites_per_process, model_axis_size),
        dcn_mesh_shape=(n_proc, 1),
        devices=devices,
        process_is_granule=by_process,
    )
    return jax.sharding.Mesh(arr, (SITE_AXIS, MODEL_AXIS))


def multihost_sliced_site_mesh(
    num_slices: int | None = None,
    sites_per_slice: int | None = None,
    sites_per_device: int = 1,
    model_axis_size: int = 1,
    devices: list | None = None,
) -> jax.sharding.Mesh:
    """The real-host form of ``parallel/mesh.py sliced_site_mesh``: a global
    ``(slice, site, model)`` mesh where the SLICE axis tiles processes —
    the multi-slice deployment shape (one ``runner/dcn_worker.py`` process
    per TPU slice), so the ONLY traffic that crosses DCN is the per-round
    inter-slice hop of the three-tier aggregation, and the intra-slice
    psum + the model axis never leave a process's ICI domain.

    ``num_slices`` defaults to ``jax.process_count()`` (the 1:1
    process-per-slice deployment) and must divide it; ``sites_per_slice``
    is the VIRTUAL site count per slice (defaults to packing every local
    device: ``local_devices // model_axis_size × sites_per_device``).
    Single-process callers collapse to :func:`sliced_site_mesh` over the
    local devices — the CPU-emulation path."""
    n_proc = jax.process_count()
    if num_slices is None:
        num_slices = n_proc if n_proc > 1 else 1
    devices = devices if devices is not None else jax.devices()
    per_proc = len(devices) // max(n_proc, 1)
    if sites_per_slice is None:
        procs_per_slice = max(n_proc // max(num_slices, 1), 1)
        sites_per_slice = max(
            per_proc // model_axis_size, 1
        ) * sites_per_device * procs_per_slice
    if n_proc == 1:
        from .mesh import sliced_site_mesh

        return sliced_site_mesh(
            num_slices, sites_per_slice, sites_per_device, devices,
            model_axis_size,
        )
    if n_proc % num_slices:
        raise ValueError(
            f"num_slices={num_slices} must divide the process count "
            f"({n_proc}) — slices are process granules over DCN"
        )
    if sites_per_slice % sites_per_device:
        raise ValueError(
            f"sites_per_device={sites_per_device} must divide the per-slice "
            f"site count ({sites_per_slice})"
        )
    procs_per_slice = n_proc // num_slices
    site_members = sites_per_slice // sites_per_device  # per slice
    if site_members % procs_per_slice:
        raise ValueError(
            f"{site_members} site-axis members per slice must divide over "
            f"{procs_per_slice} processes per slice"
        )
    per_proc_sites = site_members // procs_per_slice
    need = per_proc_sites * model_axis_size
    if need > per_proc:
        raise ValueError(
            f"{per_proc_sites} sites × model={model_axis_size} needs "
            f"{need} devices per process, have {per_proc}"
        )
    if need < per_proc:
        by_proc: dict[int, list] = {}
        for d in devices:
            by_proc.setdefault(d.process_index, []).append(d)
        devices = [d for p in sorted(by_proc) for d in by_proc[p][:need]]
    from jax.experimental import mesh_utils

    # DCN granules: slices stack processes outermost (the slice axis), any
    # surplus processes extend the site axis within a slice; the model axis
    # never leaves a process. Same granule fallback as multihost_site_mesh.
    slice_ids = {getattr(d, "slice_index", None) for d in devices}
    by_process = None in slice_ids or len(slice_ids) != n_proc
    arr = mesh_utils.create_hybrid_device_mesh(
        mesh_shape=(1, per_proc_sites, model_axis_size),
        dcn_mesh_shape=(num_slices, procs_per_slice, 1),
        devices=devices,
        process_is_granule=by_process,
    )
    return jax.sharding.Mesh(arr, (SLICE_AXIS, SITE_AXIS, MODEL_AXIS))


def spans_processes(mesh) -> bool:
    """True when ``mesh`` includes devices of other processes (a real
    multi-host mesh) — the cases where plain host-local arrays can neither
    feed a shard_map nor be fetched with ``np.asarray``."""
    if mesh is None:
        return False
    me = jax.process_index()
    return any(d.process_index != me for d in mesh.devices.flat)


def put_site_batch(mesh, arr, dtype=None):
    """Ship a host-side ``[S, ...]`` per-site batch onto the mesh, split over
    the site axis.

    Single-process meshes: a plain committed ``device_put``. Multi-host
    meshes: every process holds the full global batch (the runner loads the
    same dataset tree on each host) and
    ``jax.make_array_from_process_local_data`` takes each process's
    addressable slices — the documented JAX recipe for feeding pjit across
    hosts."""
    a = np.asarray(arr)
    if dtype is not None:
        a = a.astype(dtype)
    # the leading per-site dim splits over (slice, site) on sliced meshes
    sh = NamedSharding(mesh, P(site_axis_of(mesh)))
    if spans_processes(mesh):
        return jax.make_array_from_process_local_data(sh, a, global_shape=a.shape)
    return jax.device_put(a, sh)


def put_site_inventory(mesh, inventory, input_dtype=None):
    """One-shot placement of a padded ``[S, N_max, ...]`` site inventory
    (data/api.py SiteInventory) onto the mesh, split over the site axis —
    the upload the device-resident pipeline pays ONCE per fit (inputs cast to
    the compute dtype here, so no per-epoch convert+copy ever runs
    on-device). ``mesh=None`` is the vmap-folded single-device path (plain
    committed local arrays); multi-host meshes take each process's
    addressable slices exactly like the per-epoch batches used to
    (:func:`put_site_batch`)."""
    import jax.numpy as jnp

    if mesh is None:
        return (
            jnp.asarray(inventory.inputs, dtype=input_dtype),
            jnp.asarray(inventory.labels),
        )
    return (
        put_site_batch(mesh, inventory.inputs, input_dtype),
        put_site_batch(mesh, inventory.labels),
    )


def put_replicated(mesh, arr, dtype=None):
    """Ship a small host array to the mesh FULLY REPLICATED — the r19
    slice-liveness mask's placement (every member reads its own slice's row
    from the same tiny ``[num_slices, rounds]`` array; sharding it would
    buy nothing and cost a spec). Multi-host meshes feed it per process
    like the batches — every process holds the identical mask, so the
    process-local data IS the global array."""
    a = np.asarray(arr)
    if dtype is not None:
        a = a.astype(dtype)
    sh = NamedSharding(mesh, P())
    if spans_processes(mesh):
        return jax.make_array_from_process_local_data(sh, a, global_shape=a.shape)
    return jax.device_put(a, sh)


def put_epoch_plan(mesh, positions, live=None, poison=None, attack=None,
                   slice_live=None):
    """Ship one epoch's compact plan — the ``[S, steps, B]`` int32 index
    grid plus the optional ``[S, rounds]`` fault masks, attack-code mask
    (robustness/attacks.py, r17) and ``[num_slices, rounds]`` slice-
    liveness mask (r19, replicated) — to the mesh. This is the ENTIRE
    per-epoch host→device traffic of the device pipeline: index-plan bytes,
    not dataset bytes."""
    import jax.numpy as jnp

    def put(a):
        return jnp.asarray(a) if mesh is None else put_site_batch(mesh, a)

    return (
        put(positions),
        None if live is None else put(live),
        None if poison is None else put(poison),
        None if attack is None else put(attack),
        None if slice_live is None else (
            jnp.asarray(slice_live) if mesh is None
            else put_replicated(mesh, slice_live)
        ),
    )


def fetch_site_outputs(tree, mesh):
    """Bring per-site (``P(site)``-sharded) outputs back to host numpy on
    every process. Multi-host meshes need a ``process_allgather`` first —
    ``np.asarray`` on an array spanning non-addressable devices raises."""
    if not spans_processes(mesh):
        return jax.tree.map(np.asarray, tree)
    from jax.experimental import multihost_utils

    return jax.tree.map(
        np.asarray, multihost_utils.process_allgather(tree, tiled=True)
    )
