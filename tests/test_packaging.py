"""Package smoke (VERDICT r2 #8): the wheel installs into a clean target and
the README quick-start runs without the repo checkout on sys.path."""

import os
import subprocess

import pytest

SCRIPT = os.path.join(os.path.dirname(__file__), "..", "scripts", "package_smoke.sh")

pytestmark = pytest.mark.skipif(
    not os.path.isdir("/root/reference/datasets/test_fsl"),
    reason="reference fixture not mounted",
)


@pytest.mark.golden
def test_wheel_install_and_quickstart(tmp_path):
    proc = subprocess.run(
        ["bash", SCRIPT, str(tmp_path)], capture_output=True, text=True,
        timeout=600,
    )
    assert proc.returncode == 0, f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    assert "package smoke OK" in proc.stdout
