"""Secure-aggregation masked wires — pairwise antisymmetric one-time pads
over the site axis, canceling EXACTLY in the weighted site sum.

Why fixed point: real secure aggregation (Bonawitz et al.) operates in
``ℤ_R`` for a reason — floating-point addition is not associative, so float
pads can never cancel bit-exactly through a reduction. This module keeps
that structure: each site's weighted delta ``y_s = scale_s·g_s`` is encoded
onto a SHARED power-of-two fixed-point grid (per payload leaf, per round),
masked by int32 pads that are antisymmetric per unordered pair
(``pad(i,j) = −pad(j,i)``, drawn from counter keys ``(seed, i, j, round,
leaf)``), and summed by the engine's UNCHANGED psum-shaped collective —
int32 arithmetic wraps mod 2³², where pad cancellation is exact in ANY
reduction order. Decoding the summed grid value is a cast and a
power-of-two multiply. Consequences, all tested:

- **masked == unmasked bit-exact**: ``secure_agg="mask"`` and the
  pads-zeroed verification arm (``"mask-nopads"``) produce BIT-IDENTICAL
  trajectories at any liveness pattern, topology, or pack factor — the
  pads provably never touch the result (tests/test_privacy.py; the CI
  smoke asserts params sha256 equality).
- **wire bytes unchanged**: the wire carries one int32 grid value per f32
  element — 4 bytes either way, K-invariant under packing exactly like the
  legacy psum partial; S002 proves the int32 model against the traced
  program on the ``+secureagg`` matrix cells. On the wire the masked value
  is ``q + pad mod 2³²`` with full-range uniform pads — a one-time pad;
  only the per-leaf magnitude scale (a cross-site max) is public, exactly
  like the quantized-wire codecs' scale scalar.
- **dead sites renormalize**: pads are gated per PAIR on the round's
  liveness (both partners exclude a pair with a dead member — every member
  knows the traced liveness vector, gathered like norm_clip's bookkeeping),
  so cancellation is exact over the SURVIVING masked cohort and the
  weighted mean renormalizes over live weight per the existing contract.
- **codec refusal**: int8/fp8 wire codecs re-quantize the psum operand
  through a float grid — that would shred the integer pads, so the
  combination is REFUSED at engine construction (tested); "bf16" (and
  ``precision_bits="16"``) compose by rounding the PAYLOAD to bf16 before
  fixed-point encoding (the wire itself stays the int32 grid). The DCN
  tier must stay the fused exact form: any ``dcn_wire_quant`` codec is
  refused too.

The mode itself is NOT value-identical to the legacy float program — the
fixed-point grid quantizes the aggregate to ``~2^-fb`` of each leaf's
cross-site amax (``fb = 30 − ⌈log2 S⌉`` fractional bits, so the int32 sum
cannot overflow at S sites) — which is why the ICA hard-SNR golden floor is
re-measured under the full privacy stack (tests/test_golden.py) instead of
asserted by identity. ``secure_agg="off"`` lowers the bit-identical legacy
program (S005 "secureagg-off").
"""

from __future__ import annotations

import math

#: accepted TrainConfig.secure_agg values. "off" keeps the legacy program
#: byte-for-byte (S005-gated). "mask" is the real mode. "mask-nopads" is the
#: VERIFICATION arm: the identical fixed-point program with the pads zeroed
#: — the masked==unmasked bit-exactness claim is asserted by comparing fits
#: of the two (CI privacy smoke, tests/test_privacy.py); never deploy it.
SECURE_AGGS = ("off", "mask", "mask-nopads")


def secure_agg_enabled(secure_agg: str) -> bool:
    if secure_agg not in SECURE_AGGS:
        raise ValueError(
            f"secure_agg must be one of {SECURE_AGGS}, got {secure_agg!r}"
        )
    return secure_agg != "off"


def fraction_bits(total_sites: int) -> int:
    """Fixed-point fractional bits for an S-site cohort: the sum of S grid
    values bounded by ±2^fb must stay inside int32, so
    ``fb = 30 − ⌈log2 S⌉`` (floored at 8 — a cohort past ~4M sites has
    bigger problems than grid resolution)."""
    s = max(int(total_sites), 1)
    return max(30 - math.ceil(math.log2(max(s, 2))), 8)


def _global_site_ids(axis_name):
    """Global virtual site ids for this member's rows: a scalar under the
    classic vmapped axes, the ``[K]`` id vector under a PackedAxis — the
    same device-major order every other per-site input uses."""
    import jax.numpy as jnp

    from ..parallel.collectives import PackedAxis, site_index

    if isinstance(axis_name, PackedAxis):
        return site_index(axis_name) + jnp.arange(axis_name.pack)
    return site_index(axis_name)


def _site_max(local, axis_name):
    """Cross-site max of a per-member scalar (exact — max is associative):
    the shared grid scale must be identical on every member."""
    import jax

    from ..parallel.collectives import PackedAxis

    if isinstance(axis_name, PackedAxis):
        if axis_name.name is None:
            return local
        return jax.lax.pmax(local, axis_name.reduce_axes())
    return jax.lax.pmax(local, axis_name)


def _gather_live(live, axis_name, total: int):
    """The round's ``[S]`` liveness vector, known to every member (the
    secure-agg dropout contract: survivors must agree on which pads to
    exclude). ``None`` live = all-live, no gather (and no extra wire)."""
    import jax.numpy as jnp

    from ..parallel.collectives import site_all_gather

    if live is None:
        return None
    vec = jnp.asarray(live, jnp.float32)
    if vec.ndim == 0:
        vec = vec[None]
    return site_all_gather(vec, axis_name).reshape(total)


def _pair_pads(shape, leaf_ix: int, s_ix, live_all, seed: int, rnd,
               total: int):
    """One site's summed pairwise pads for one leaf: ``Σ_{j>s} P(s,j) −
    Σ_{j<s} P(j,s)`` in int32 wraparound arithmetic, each ``P`` drawn
    full-range uniform from the counter key ``(seed, min, max, round,
    leaf)``. A ``lax.fori_loop`` over partners keeps the program size
    O(1) in the cohort size. ``live_all`` gates each pair on BOTH members'
    liveness (None = all live)."""
    import jax
    import jax.numpy as jnp

    base = jax.random.fold_in(jax.random.PRNGKey(seed), leaf_ix)
    s = jnp.asarray(s_ix, jnp.int32)

    def body(j, acc):
        lo = jnp.minimum(s, j)
        hi = jnp.maximum(s, j)
        key = jax.random.fold_in(
            jax.random.fold_in(jax.random.fold_in(base, lo), hi), rnd
        )
        bits = jax.lax.bitcast_convert_type(
            jax.random.bits(key, shape, jnp.uint32), jnp.int32
        )
        sign = jnp.where(j > s, jnp.int32(1),
                         jnp.where(j < s, jnp.int32(-1), jnp.int32(0)))
        if live_all is not None:
            gate = (live_all[s] > 0) & (live_all[j] > 0)
            sign = jnp.where(gate, sign, jnp.int32(0))
        return acc + sign * bits

    return jax.lax.fori_loop(
        0, total, body, jnp.zeros(shape, jnp.int32)
    )


def masked_weighted_mean(tree, weight, axis_name, seed: int, rnd, live=None,
                         pads: bool = True):
    """The secure-aggregation replacement for
    :func:`~..parallel.collectives.site_weighted_mean` on dSGD's dense
    exchange: weighted deltas fixed-point-encoded on a shared per-leaf grid,
    pad-masked, summed through the engine's unchanged psum-shaped collective
    (int32 on the wire), decoded after. Dead sites arrive zero-weighted
    (mask_dead_site upstream) and pad-excluded; the scale renormalizes over
    live weight exactly like the legacy mean. ``rnd`` is the traced global
    round counter (mask keys are chunk/resume-independent); ``pads=False``
    is the "mask-nopads" verification arm — the IDENTICAL program with the
    pad accumulator zeroed."""
    import jax
    import jax.numpy as jnp

    from ..parallel.collectives import (
        PackedAxis,
        _bcast,
        site_count,
        site_weight_scale,
        two_level_psum,
    )

    if rnd is None:
        raise ValueError(
            "secure aggregation needs the traced round counter (rnd=) — "
            "masks are keyed per (pair, round)"
        )
    total = site_count(axis_name)
    fb = fraction_bits(total)
    packed = isinstance(axis_name, PackedAxis)
    scale = site_weight_scale(weight, axis_name)
    ids = _global_site_ids(axis_name)
    live_all = _gather_live(live, axis_name, total)

    leaves, treedef = jax.tree.flatten(tree)
    out = []
    for leaf_ix, g in enumerate(leaves):
        y = g.astype(jnp.float32)
        y = y * _bcast(scale, y) if packed else y * scale
        # shared power-of-two grid: exp2(ceil(log2 amax)) ≥ amax, so
        # |y/Δ| ≤ 2^fb; all-zero / non-finite amax falls back to 1.0 (the
        # codec's guard — never a 0/0)
        amax = _site_max(jnp.max(jnp.abs(y)), axis_name)
        ok = jnp.isfinite(amax) & (amax > 0)
        ex = jnp.where(ok, jnp.exp2(jnp.ceil(jnp.log2(jnp.where(ok, amax, 1.0)))), 1.0)
        delta = ex * jnp.float32(2.0 ** -fb)
        q = jnp.round(y / delta).astype(jnp.int32)
        if pads:
            if packed:
                pad = jax.vmap(
                    lambda s: _pair_pads(
                        g.shape[1:], leaf_ix, s, live_all, seed, rnd, total
                    )
                )(ids)
            else:
                pad = _pair_pads(
                    g.shape, leaf_ix, ids, live_all, seed, rnd, total
                )
            q = q + pad
        if packed:
            tot = two_level_psum(q, axis_name)
        else:
            tot = jax.lax.psum(q, axis_name)
        out.append(tot.astype(jnp.float32) * delta)
    return jax.tree.unflatten(treedef, out)
