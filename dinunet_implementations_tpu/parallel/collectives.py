"""Cross-site collectives — the aggregation transport.

The reference ships JSON-serialized gradients from every site container to the
remote container, which reduces them on an ``mp.Pool`` of ``num_reducers``
processes and broadcasts the result back (reference ``local.py:26-27,49``,
``remote.py:20-21,37``; payloads optionally cast to fp16 via ``precision_bits``,
``compspec.json:161-176``). Here each of those becomes a single XLA collective
over the ``site`` mesh axis: reduction rides ICI, the "broadcast back" is simply
the collective's replicated result. ~97% of reference wall-clock was this
transport (SURVEY.md §3.1); these primitives delete that cost class.

All functions are designed for use *inside* ``shard_map``/``pjit`` with a bound
axis name.

Axis forms (r12 — site packing). ``axis_name`` may be:

- a ``str`` mesh/vmap axis name — the classic one-site-per-collective-member
  form (one site per device, or all sites vmapped onto one device);
- a ``(mesh_axis, vmap_axis)`` tuple — the legacy folded form, kept for
  compatibility: collectives resolve the vmapped half through jax's batching
  rules, which ships the whole ``[K, ...]`` batched block over the mesh axis
  (K× wire inflation — the reason PackedAxis exists);
- a :class:`PackedAxis` — the packed two-level form: every payload leaf
  carries a LEADING ``[K]`` virtual-site axis, reductions run **local
  in-register sum over the packed axis first**, the partial is (optionally)
  quantized to the wire dtype, and ONE cross-device collective ships the
  unbatched partial over the mesh axis. Per-device wire bytes are then
  independent of K for every psum-shaped exchange; only genuine per-site
  payloads (the low-rank factor all-gather) scale with K.

Three-tier form (r18 multi-slice, ``PackedAxis.slice_name`` set): the mesh
carries an OUTER ``slice`` axis whose collectives cross DCN, the slow
inter-slice fabric (parallel/mesh.py ``sliced_site_mesh``). Reductions grow
a tier: the in-register pack sum (tier 0) and the intra-slice psum over ICI
(tier 1) as before, then an inter-slice hop (tier 2) that ships only the
already-reduced per-slice partial. The tier-2 payload treatment is the
``dcn_wire`` argument, independent of the intra-slice codec:

- ``dcn_wire=None`` (``dcn_wire_quant`` resolves to "none") — the FUSED
  form: tiers 1+2 are ONE collective naming ``(slice, site)`` together.
  Value-wise this is exactly the flat single-mesh reduce (same members,
  same reduction order — sliced==unsliced trajectories stay bit-exact
  site-for-site), and it is what XLA/the TPU runtime hierarchically
  decomposes over ICI+DCN on real multi-slice hardware. Bookkeeping
  reductions (losses, weight totals, sync-BN) always take this form —
  they must never be re-quantized at a slice boundary.
- ``dcn_wire=WireCodec`` — the SPLIT form: psum over ``site`` completes the
  per-slice partial, the partial re-quantizes through the DCN codec (scale
  per payload), and ONE psum naming only ``slice`` ships it across DCN.
  int8/fp8 then land their 4x shrink exactly where bandwidth is scarcest:
  the expensive hop carries one codec-grid payload per slice per round
  instead of one dense payload per device.

Gathers are always hierarchical under a sliced axis (gather over ``site``,
optionally DCN-re-quantize the per-slice block, gather over ``slice``) —
gathering is exact, so the site order and values match the flat form
bit-for-bit when no DCN codec is set.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from ..core.jaxcompat import axis_size
from .mesh import SITE_AXIS


@dataclasses.dataclass(frozen=True)
class PackedAxis:
    """The packed (K-sites-per-device) site axis: payload pytree leaves carry
    a leading ``[pack]`` virtual-site axis; reductions are two-level (local
    sum over that axis, then one cross-device collective over ``name``).
    ``name=None`` means no mesh half (every virtual site on one device — the
    cross-device collective degenerates to the identity); trace-time static,
    safe to close over in jitted code.

    ``slice_name`` (r18 multi-slice) names the OUTER inter-slice mesh axis
    when the mesh has one — reductions then grow the DCN tier (module
    docstring: fused vs split forms, picked per call by ``dcn_wire``).
    ``slice_name=None`` keeps the exact legacy two-level program."""

    name: str | None  # the mesh axis (from parallel/mesh.py constants)
    pack: int  # K — virtual sites per device (the leading payload axis)
    slice_name: str | None = None  # the DCN mesh axis (sliced meshes only)

    def reduce_axes(self):
        """The axis names a FUSED (bookkeeping / dcn_wire=None) reduction
        spans: ``(slice, site)`` on the sliced form — one collective over
        both tiers, bit-identical to the flat single-mesh reduce — else
        just ``name``."""
        if self.slice_name is not None:
            return (self.slice_name, self.name)
        return self.name


def _bcast(scale, like):
    """Reshape a per-virtual-site ``[K]`` vector to broadcast against a
    ``[K, ...]``-leading payload leaf."""
    return scale.reshape(scale.shape + (1,) * (like.ndim - scale.ndim))

# precision_bits payload casting (compspec.json:161-176). On TPU, "16" means
# bfloat16 (the native 16-bit type; same byte count on the wire, wider
# exponent); "16-ieee" opts into the reference's literal IEEE fp16 payload for
# bit-level compat runs. The reduction itself always accumulates in fp32.
_PAYLOAD_DTYPES = {
    "32": jnp.float32, 32: jnp.float32,
    "16": jnp.bfloat16, 16: jnp.bfloat16,
    "16-ieee": jnp.float16,
}


def payload_dtype(precision_bits="32"):
    """Resolve the ``precision_bits`` flag to the payload dtype."""
    return _PAYLOAD_DTYPES[precision_bits]


def site_weight_scale(weight, axis_name=SITE_AXIS):
    """Per-site normalized weight ``w_s / Σ w`` with a zero-total guard (an
    all-masked round yields scale 0, keeping updates finite). Packed form:
    ``weight`` is the ``[K]`` virtual-site vector and the total spans the
    local pack AND the mesh axis; the returned scale is ``[K]``."""
    w = jnp.asarray(weight, jnp.float32)
    if isinstance(axis_name, PackedAxis):
        total = jnp.sum(w)
        if axis_name.name is not None:
            # bookkeeping reduce: spans the slice tier FUSED when present
            # (never re-quantized at a slice boundary — module docstring)
            total = jax.lax.psum(total, axis_name.reduce_axes())
    else:
        total = jax.lax.psum(w, axis_name)
    return jnp.where(total > 0, w / jnp.maximum(total, 1e-12), 0.0)


def payload_cast(tree, precision_bits="32"):
    """Cast a gradient pytree to the configured payload dtype before the
    collective — the TPU equivalent of the reference's fp16 payload compression."""
    dtype = _PAYLOAD_DTYPES[precision_bits]
    return jax.tree.map(lambda g: g.astype(dtype), tree)


def payload_uncast(tree, like):
    """Restore original dtypes after the collective."""
    return jax.tree.map(lambda g, l: g.astype(l.dtype), tree, like)


def _pack_partial(x, wire_dtype):
    """Tier 0 + intra-slice wire quantization: in-register sum over the
    leading ``[K]`` virtual-site axis, the partial optionally quantized to
    ``wire_dtype`` (plain dtype round-trip or a :class:`WireCodec`)."""
    part = jnp.sum(x, axis=0)
    if isinstance(wire_dtype, WireCodec):
        part = wire_dtype.compress(part)
    elif wire_dtype is not None:
        part = wire_compress(part, wire_dtype)
    return part


def _dcn_hop(partial, axes: PackedAxis, dcn_wire):
    """Tier 2 (the SPLIT form): re-quantize the completed per-slice partial
    through the DCN codec and ship it in ONE psum naming only the slice
    axis — the only collective form that crosses DCN alone, which is what
    checks/semantic.py's DCN-tier rules key on."""
    return jax.lax.psum(dcn_wire.compress(partial), axes.slice_name)


def three_level_psum(x, axes: PackedAxis, wire_dtype=None, dcn_wire=None,
                     slice_live=None):
    """The hierarchical reduction primitive (module docstring): tier 0 is
    the in-register pack sum, tier 1 the intra-slice psum of the UNBATCHED
    partial (quantized to ``wire_dtype`` — what the device ships over ICI;
    f32 accumulation resumes after the collective), tier 2 the inter-slice
    DCN hop. With ``axes.slice_name=None`` this IS the legacy two-level
    reduction, op for op. With a slice axis, ``dcn_wire=None`` fuses tiers
    1+2 into one ``(slice, site)`` collective (bit-identical values to the
    flat reduce); a :class:`WireCodec` splits them, re-quantizing the
    per-slice partial before the slice-only psum. The ICI wire cost is
    K-independent and the DCN hop ships one partial per slice per round.

    ``slice_live`` (r19 slice elasticity) is this member's OWN slice's
    per-round liveness gate — a traced 0/1 scalar. The local partial is
    zeroed before any cross-member tier, so a dead slice contributes
    EXACTLY nothing to the DCN reduce and the surviving slices' sum equals
    the reduce that excluded the dead slice's members outright (``×1.0`` is
    bit-exact, ``×0`` is exclusion). The epoch's production rounds route
    slice death through the site-level contribute gate (trainer/steps.py —
    value-equivalent, proven by tests/test_multislice.py); this explicit
    form is the primitive-level contract the slice-fault unit tests pin."""
    part = _pack_partial(x, wire_dtype)
    if slice_live is not None and axes.slice_name is not None:
        part = part * slice_live
    if axes.name is None:
        return part
    if axes.slice_name is None:
        return jax.lax.psum(part, axes.name)
    if dcn_wire is None:
        return jax.lax.psum(part, axes.reduce_axes())
    return _dcn_hop(jax.lax.psum(part, axes.name), axes, dcn_wire)


def two_level_psum(x, axes: PackedAxis, wire_dtype=None, dcn_wire=None):
    """The r12 name for :func:`three_level_psum` — kept because every packed
    call site reads naturally as "two-level" on single-slice meshes, where
    the lowering is unchanged op for op; sliced axes route the same call
    through the DCN tier."""
    return three_level_psum(x, axes, wire_dtype, dcn_wire)


def weighted_site_sum(g, scale, axis_name, wire_dtype=None, dcn_wire=None,
                      slice_live=None):
    """One dense payload leaf of a weighted exchange: ``Σ_s scale_s · g_s``
    accumulated in f32. Classic axes psum the per-site scaled value; a
    :class:`PackedAxis` takes the two-level route (``scale`` is then the
    ``[K]`` vector and ``g`` carries the leading pack axis), growing the
    DCN tier on sliced axes (``dcn_wire`` — :func:`three_level_psum`).
    ``wire_dtype`` quantizes the packed partial only — on the classic path
    the per-member payload is whatever the caller already cast it to.
    ``slice_live`` gates this member's slice out of the reduce
    (:func:`three_level_psum` — sliced axes only)."""
    gf = g.astype(jnp.float32)
    if isinstance(axis_name, PackedAxis):
        return three_level_psum(
            gf * _bcast(scale, gf), axis_name, wire_dtype, dcn_wire,
            slice_live,
        )
    return jax.lax.psum(gf * scale, axis_name)


def weighted_tree_sum(tree, scale, axes: PackedAxis, wire_dtype=None,
                      dcn_wire=None, slice_live=None):
    """A whole pytree's weighted exchange with ONE inter-slice collective.

    Per leaf, tiers 0+1 run exactly like :func:`weighted_site_sum`; the DCN
    tier then ships the ENTIRE tree of per-slice partials in a single
    slice-only psum — every leaf DCN-re-quantized (scale per payload),
    raveled and concatenated, so the expensive hop pays one collective
    launch per round instead of one per leaf. Single-slice axes (or
    ``dcn_wire=None``) reduce per leaf exactly like the mapped
    :func:`weighted_site_sum` — same ops, so the legacy program is
    untouched. dSGD's whole dense exchange rides this (engines/dsgd.py).
    ``slice_live`` gates the per-slice partial out of the DCN reduce like
    :func:`three_level_psum` — the reduce then renormalizes over surviving
    slices only (the weights of a dead slice's members carry zero through
    ``scale``, so the denominator excludes them too)."""
    if not isinstance(axes, PackedAxis):
        return jax.tree.map(
            lambda g: weighted_site_sum(g, scale, axes, wire_dtype), tree
        )
    if axes.slice_name is None or dcn_wire is None or axes.name is None:
        return jax.tree.map(
            lambda g: weighted_site_sum(
                g, scale, axes, wire_dtype, dcn_wire, slice_live
            ),
            tree,
        )
    partials = jax.tree.map(
        lambda g: jax.lax.psum(
            _pack_partial(
                g.astype(jnp.float32) * _bcast(scale, g), wire_dtype
            ),
            axes.name,
        ),
        tree,
    )
    if slice_live is not None:
        partials = jax.tree.map(lambda p: p * slice_live, partials)
    leaves, treedef = jax.tree.flatten(partials)
    comp = [dcn_wire.compress(leaf).reshape(-1) for leaf in leaves]
    flat = comp[0] if len(comp) == 1 else jnp.concatenate(comp)
    tot = jax.lax.psum(flat, axes.slice_name)
    outs, off = [], 0
    for leaf in leaves:
        n = leaf.size
        outs.append(tot[off:off + n].reshape(leaf.shape))
        off += n
    return jax.tree.unflatten(treedef, outs)


def site_sum(tree, axis_name=SITE_AXIS):
    """Sum a pytree across sites (the remote's reduce)."""
    if isinstance(axis_name, PackedAxis):
        return jax.tree.map(lambda g: two_level_psum(g, axis_name), tree)
    return jax.tree.map(lambda g: jax.lax.psum(g, axis_name), tree)


def site_mean(tree, axis_name=SITE_AXIS):
    """Unweighted mean across sites."""
    if isinstance(axis_name, PackedAxis):
        n = axis_name.pack
        if axis_name.name is not None:
            n = n * axis_size(axis_name.name)
        if axis_name.slice_name is not None:
            n = n * axis_size(axis_name.slice_name)
        return jax.tree.map(
            lambda g: two_level_psum(g, axis_name) / n, tree
        )
    return jax.tree.map(lambda g: jax.lax.pmean(g, axis_name), tree)


def site_weighted_mean(tree, weight, axis_name=SITE_AXIS, wire_dtype=None,
                       dcn_wire=None):
    """Example-count-weighted mean across sites.

    dSGD semantics: each site contributes its gradient weighted by how many
    examples produced it (sites hold 73–120 subjects in the FS fixture —
    heterogeneous), so the aggregate equals the pooled-data gradient. ``weight``
    is a scalar per site (e.g. this round's example count) — the ``[K]``
    vector under a :class:`PackedAxis`, where the local weighted partial is
    reduced in-register and quantized to ``wire_dtype`` before the single
    cross-device psum (the two-level form; per-device wire bytes do not scale
    with K). On a sliced axis with a DCN codec, the whole tree's per-slice
    partials ship across DCN in ONE fused slice-only collective
    (:func:`weighted_tree_sum`) — one payload per slice per round.
    """
    scale = site_weight_scale(weight, axis_name)
    # Accumulate in fp32 even for bf16 payloads; cast back only after the psum.
    agg = weighted_tree_sum(tree, scale, axis_name, wire_dtype, dcn_wire)
    return jax.tree.map(lambda a, g: a.astype(g.dtype), agg, tree)


def site_all_gather(x, axis_name=SITE_AXIS, axis: int = 0, tiled: bool = False,
                    dcn_wire=None):
    """Gather per-site values to every site (used by the low-rank engines to
    share rank-r factors instead of full gradients).

    ``axis_name`` may be a (mesh_axis, vmap_axis) tuple — the folded-sites
    case, where several simulated sites ride one device as a vmapped block.
    ``jax.lax.all_gather`` rejects mixed mesh/vmap axis tuples (unlike
    ``psum``), so gather each axis in turn, innermost first, and flatten: the
    leading dim comes out in global site order (outer*fold_size + inner),
    matching ``jax.lax.axis_index(axes)``.

    A :class:`PackedAxis` gathers the device's whole ``[K, ...]`` virtual-site
    block in ONE collective and flattens to the same global (device-major)
    site order — this is the one exchange whose wire bytes genuinely scale
    with K (every virtual site's factors must reach every device).

    Sliced axes (``slice_name`` set) gather hierarchically: the intra-slice
    gather assembles the slice's ``[S/slices, ...]`` block over ICI, then ONE
    inter-slice gather ships that block across DCN — re-quantized per
    virtual-site row through ``dcn_wire`` when a DCN codec is set (payload
    gathers only; bookkeeping gathers pass ``dcn_wire=None`` and cross
    exact). The flattened result is the same slice-major global site order
    as the data layout — gathering is exact, so without a DCN codec the
    values match the flat single-mesh gather bit-for-bit."""
    if isinstance(axis_name, str):
        return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)
    if isinstance(axis_name, PackedAxis):
        assert axis == 0 and not tiled, "packed gather stacks the leading dim only"
        if axis_name.name is None:
            return x  # every virtual site already local: [S, ...] as-is
        out = jax.lax.all_gather(x, axis_name.name, axis=0)
        out = out.reshape((-1,) + x.shape[1:])
        if axis_name.slice_name is not None:
            if dcn_wire is not None:
                # per-virtual-site-row DCN re-quantization of the slice's
                # block before the expensive hop (batched: scale per row)
                out = dcn_wire.compress(out, batched=True)
            out = jax.lax.all_gather(out, axis_name.slice_name, axis=0)
            out = out.reshape((-1,) + x.shape[1:])
        return out
    assert axis == 0 and not tiled, "tuple-axis gather supports leading-dim stacking only"
    out = x
    for ax in reversed(tuple(axis_name)):
        out = jax.lax.all_gather(out, ax, axis=0)
    return out.reshape((-1,) + x.shape)


def site_all_gather_packed(parts, axis_name=SITE_AXIS, dcn_wire=None):
    """ONE ``all_gather`` for a list of same-dtype ``[k_i, ...]`` arrays
    (matching trailing dims): concatenate along axis 0, gather, re-split into
    ``[S, k_i, ...]`` views.

    The low-rank engines otherwise issue two gathers per compressible leaf
    (P and Q); packing turns a whole rank group's factor exchange into a
    single collective launch — comm volume unchanged (``r·Σ(m_i+n_i)`` per
    site), launch count divided by ``2·|group|`` (the flagship ICA-LSTM's
    r=10 group goes from 12 gathers per round to 1).

    Under a :class:`PackedAxis` the parts carry a leading ``[K]`` virtual-site
    axis (``[K, k_i, ...]``); they concatenate on axis 1, the device's whole
    ``[K, Σk_i, ...]`` block ships in one gather, and the splits come back in
    the same global-site-order ``[S, k_i, ...]`` views as the classic form —
    downstream reconstruction code is identical either way."""
    packed = isinstance(axis_name, PackedAxis)
    cat_axis = 1 if packed else 0
    if len(parts) == 1:
        return [site_all_gather(parts[0], axis_name, dcn_wire=dcn_wire)]
    sizes = [p.shape[cat_axis] for p in parts]
    gathered = site_all_gather(
        jnp.concatenate(parts, axis=cat_axis), axis_name, dcn_wire=dcn_wire
    )
    outs, off = [], 0
    for k in sizes:
        outs.append(gathered[:, off:off + k])
        off += k
    return outs


def wire_compress(x, pdtype):
    """Round-trip ``x`` through the wire payload dtype (``precision_bits``):
    the value a collective actually transports, restored to f32 so the
    reduction itself accumulates at full precision (policy above: psum never
    runs in bf16)."""
    return x.astype(pdtype).astype(jnp.float32)


# ---------------------------------------------------------------------------
# quantized wire codecs (r14)
# ---------------------------------------------------------------------------

#: accepted TrainConfig.wire_quant values. "none" keeps the legacy
#: precision_bits wire byte-for-byte (program-identical, S005-gated);
#: "bf16" forces a bf16 wire regardless of precision_bits; "int8"/"fp8"
#: are the scale-per-payload quantized codecs below.
WIRE_QUANTS = ("none", "bf16", "int8", "fp8")

#: largest finite float8_e4m3fn magnitude — the fp8 codec maps each
#: payload's amax onto it so small-gradient tensors don't flush to zero
#: (e4m3's min normal is ~1.6e-2; raw-cast gradients of ~1e-4 would vanish)
FP8_E4M3_MAX = 448.0


def _dither_uniform(v):
    """Deterministic per-element uniform in [0, 1) for stochastic rounding,
    derived by hashing the value's own float bits (splitmix/murmur-style
    integer finalizer) — no RNG key to thread through the engines, identical
    across topologies and replays, and decorrelated across elements/rounds
    because the hashed bits change with the value. 24-bit mantissa-exact."""
    bits = jax.lax.bitcast_convert_type(v.astype(jnp.float32), jnp.uint32)
    h = bits * jnp.uint32(0x9E3779B9)
    h = h ^ (h >> jnp.uint32(16))
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> jnp.uint32(13))
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> jnp.uint32(16))
    return (h >> jnp.uint32(8)).astype(jnp.float32) * jnp.float32(1.0 / (1 << 24))


def _payload_amax_scale(xf, batched: bool, grid_max: float):
    """Per-payload symmetric scale mapping ``amax`` onto the codec grid's
    largest representable magnitude. ``batched=True`` treats the LEADING axis
    as the virtual-site axis (one scale per packed row — each virtual site
    quantizes its own payload, matching the per-member semantics of the
    classic one-site-per-device form). All-zero (a masked dead site's
    where-zeroed payload) and non-finite amax fall back to scale 1.0, so the
    codec never manufactures NaN out of a 0/0."""
    axes = tuple(range(1, xf.ndim)) if batched else None
    amax = jnp.max(jnp.abs(xf), axis=axes, keepdims=batched)
    ok = jnp.isfinite(amax) & (amax > 0)
    return jnp.where(ok, amax / jnp.float32(grid_max), 1.0)


@dataclasses.dataclass(frozen=True)
class WireCodec:
    """One wire-quantization codec: what a collective payload is QUANTIZED to
    before it ships, and how it is restored after.

    ``compress`` follows the repo's established bf16-wire pattern
    (:func:`wire_compress`): the payload round-trips through the wire grid
    and the collective itself accumulates in f32 — reductions never run in a
    narrow dtype, and dequantization distributes over the sum exactly
    (``Σ_s scale_s·q_s`` is the same value whether each member dequantizes
    before the reduce or a transport dequantizes after it; the traced
    program carries the quantize→collective chain, which checks/semantic.py
    S002/S004 resolve to the wire dtype to PROVE the byte shrink). ``dtype``
    is what crosses the wire per element — int8/fp8 are 1 byte, a 4× shrink
    over f32; a physical transport adds one f32 scale scalar per payload
    (modeled as negligible, not counted in ``Engine.wire_bytes``).

    ``quant="none"`` reproduces the legacy ``precision_bits`` round-trip
    bit-for-bit — engines keep their historical code path there, so the
    disabled codec is program-identical (S005-gated).

    ``stochastic=True`` (int8 only) rounds stochastically on the quant grid
    — ``floor(v + u)``, ``u ~ U[0,1)`` from :func:`_dither_uniform` — making
    the quantizer unbiased in expectation; fp8 keeps round-to-nearest-even
    (hardware cast semantics)."""

    quant: str  # "none" | "bf16" | "int8" | "fp8"
    dtype: Any  # numpy dtype on the wire (what Engine.wire_dtype reports)
    stochastic: bool = False

    def compress(self, x, batched: bool = False):
        """Round-trip one payload leaf through the wire grid (f32 in/out).
        ``batched=True``: leading axis is the packed virtual-site axis —
        scale per row (see :func:`_payload_amax_scale`)."""
        xf = x.astype(jnp.float32)
        if self.quant == "none":
            return wire_compress(xf, self.dtype)
        if self.quant == "bf16":
            return wire_compress(xf, jnp.bfloat16)
        if self.quant == "int8":
            scale = _payload_amax_scale(xf, batched, 127.0)
            v = xf / scale
            if self.stochastic:
                q = jnp.floor(v + _dither_uniform(v))
            else:
                q = jnp.round(v)
            q = jnp.clip(q, -127.0, 127.0).astype(jnp.int8)
            return q.astype(jnp.float32) * scale
        if self.quant == "fp8":
            scale = _payload_amax_scale(xf, batched, FP8_E4M3_MAX)
            q = (xf / scale).astype(jnp.float8_e4m3fn)
            return q.astype(jnp.float32) * scale
        raise ValueError(f"unknown wire codec {self.quant!r}")


def resolve_wire_codec(precision_bits="32", wire_quant: str = "none",
                       stochastic: bool = False) -> WireCodec:
    """Resolve ``(precision_bits, TrainConfig.wire_quant)`` to the engine's
    wire codec. ``wire_quant="none"`` defers entirely to ``precision_bits``
    (the legacy wire); any other value overrides the WIRE dtype only — the
    power-iteration matmul precision stays governed by ``precision_bits``
    (engines/rankdad.py ``mm_dtype``), the two knobs compose."""
    import numpy as np

    if wire_quant not in WIRE_QUANTS:
        raise ValueError(
            f"wire_quant must be one of {WIRE_QUANTS}, got {wire_quant!r}"
        )
    if wire_quant == "none":
        dtype = np.dtype(_PAYLOAD_DTYPES[precision_bits])
    elif wire_quant == "bf16":
        dtype = np.dtype(jnp.bfloat16)
    elif wire_quant == "int8":
        dtype = np.dtype(np.int8)
    else:  # fp8
        if not hasattr(jnp, "float8_e4m3fn"):  # pragma: no cover - old jax
            raise ValueError(
                "wire_quant='fp8' needs jnp.float8_e4m3fn (ml_dtypes); "
                "this jax build lacks it — use 'int8' or 'bf16'"
            )
        dtype = np.dtype(jnp.float8_e4m3fn)
    # factory kwarg, never a tracer: TrainConfig.wire_stochastic is static
    return WireCodec(
        quant=wire_quant, dtype=dtype,
        stochastic=bool(stochastic) and wire_quant == "int8",  # jaxlint: disable=R005
    )


def resolve_dcn_codec(precision_bits="32", wire_quant: str = "none",
                      dcn_wire_quant: str = "", stochastic: bool = False):
    """Resolve ``TrainConfig.dcn_wire_quant`` to the inter-slice codec, or
    ``None`` — the FUSED form (no re-quantization at the slice boundary;
    tiers 1+2 are one collective, sliced==unsliced stays bit-exact).

    ``""`` (the config default) follows ``wire_quant``, so quantized wires
    land their shrink on BOTH tiers unless the operator splits them;
    ``"none"`` explicitly opts the DCN tier out while the ICI wire stays
    quantized. Single-slice meshes never consult this — there is no DCN
    tier to codec."""
    eff = dcn_wire_quant or wire_quant
    if eff == "none":
        return None
    return resolve_wire_codec(precision_bits, eff, stochastic)


# ---------------------------------------------------------------------------
# byzantine-robust site-axis reducers (r17)
# ---------------------------------------------------------------------------

#: accepted TrainConfig.robust_agg / engine robust_agg values. "none" keeps
#: the legacy renormalizing weighted mean program-identically (S005-gated);
#: "norm_clip" clips each site's gradient norm to a robust (weighted-median)
#: threshold before the SAME weighted-mean wire (composes with quantized
#: wires); "trimmed_mean" / "coordinate_median" replace the psum-shaped
#: exchange with a cross-site gather and reduce per coordinate over the
#: global site axis — the classic byzantine-robust estimators, at a
#: genuinely larger wire (every site's payload must reach every device).
ROBUST_AGGS = ("none", "norm_clip", "trimmed_mean", "coordinate_median")


def _sorted_site_axis(vals, weight):
    """Sort ``vals [S, ...]`` along the site axis per coordinate and carry
    the per-site weights with each coordinate's permutation. Returns
    ``(v_sorted, w_sorted, cum, total)`` where ``cum`` is the inclusive
    cumulative weight in sorted order and ``total`` the (broadcastable)
    weight total."""
    order = jnp.argsort(vals, axis=0)
    v_sorted = jnp.take_along_axis(vals, order, axis=0)
    w = jnp.asarray(weight, jnp.float32).reshape(
        (vals.shape[0],) + (1,) * (vals.ndim - 1)
    )
    w_sorted = jnp.take_along_axis(
        jnp.broadcast_to(w, vals.shape), order, axis=0
    )
    cum = jnp.cumsum(w_sorted, axis=0)
    return v_sorted, w_sorted, cum, cum[-1:]


def weighted_trimmed_mean(vals, weight, trim_frac: float):
    """Per-coordinate WEIGHTED trimmed mean over the leading site axis:
    sort each coordinate's S values, drop ``trim_frac`` of the total live
    weight from each tail, average what remains (each sorted entry
    contributes the overlap of its weight interval with the kept band —
    exact for fractional trims and for dead sites, whose weight is 0 and
    who therefore never shift the band). ``trim_frac`` is a trace-time
    static in [0, 0.5); an all-dead coordinate (total weight 0) reduces to
    0, matching the weighted mean's zero-total guard."""
    # factory kwarg, never a tracer: TrainConfig.robust_trim_frac is static
    if not 0.0 <= float(trim_frac) < 0.5:  # jaxlint: disable=R005
        raise ValueError(
            f"trim_frac must be in [0, 0.5), got {trim_frac}"
        )
    v_sorted, w_sorted, cum, total = _sorted_site_axis(vals, weight)
    lo = jnp.float32(trim_frac) * total
    hi = (1.0 - jnp.float32(trim_frac)) * total
    keep = jnp.clip(
        jnp.minimum(cum, hi) - jnp.maximum(cum - w_sorted, lo), 0.0, None
    )
    denom = jnp.sum(keep, axis=0)
    out = jnp.sum(keep * v_sorted, axis=0) / jnp.maximum(denom, 1e-12)
    return jnp.where(total[0] > 0, out, jnp.zeros_like(out))


def weighted_coordinate_median(vals, weight):
    """Per-coordinate WEIGHTED (lower) median over the leading site axis:
    the sorted value whose cumulative weight interval contains half the
    total live weight. Dead sites (weight 0) never get selected; an
    all-dead coordinate reduces to 0 like the weighted mean's zero-total
    guard. Breakdown point 1/2 — the strongest of the robust reducers, at
    the same gathered wire as the trimmed mean."""
    v_sorted, w_sorted, cum, total = _sorted_site_axis(vals, weight)
    mid = 0.5 * total
    keep = (
        (cum - w_sorted < mid) & (cum >= mid) & (w_sorted > 0)
    ).astype(jnp.float32)
    out = jnp.sum(keep * v_sorted, axis=0) / jnp.maximum(
        jnp.sum(keep, axis=0), 1.0
    )
    return jnp.where(total[0] > 0, out, jnp.zeros_like(out))


def robust_site_reduce(vals, weight, mode: str, trim_frac: float = 0.2):
    """Dispatch one gathered ``[S, ...]`` payload through the configured
    robust reducer (``mode`` is a trace-time static)."""
    if mode == "trimmed_mean":
        return weighted_trimmed_mean(vals, weight, trim_frac)
    if mode == "coordinate_median":
        return weighted_coordinate_median(vals, weight)
    raise ValueError(f"unknown robust site reducer {mode!r}")


def robust_clip_scales(nsq, weight, axis_name, clip_mult: float):
    """Norm-clip defense: per-site multiplicative clip scales from a ROBUST
    norm threshold.

    ``nsq`` is each site's squared gradient norm (a scalar under the
    classic vmapped axes, the ``[K]`` virtual-site vector under a
    :class:`PackedAxis`); the threshold is ``clip_mult ×`` the live-weighted
    MEDIAN site norm across the global site axis — an attacker scaling its
    gradient cannot move a median it does not own, so the clip threshold
    stays anchored to the honest cohort. The cross-site exchange is two
    tiny gathers (the per-site norm and weight vectors — modeled in the
    engines' robust-mode ``wire_shapes``); the gradient payload itself then
    rides the engine's UNCHANGED weighted-mean wire, which is why norm_clip
    composes with the quantized wire codecs.
    """
    ns_all = site_all_gather(jnp.asarray(nsq, jnp.float32), axis_name)
    w_all = site_all_gather(jnp.asarray(weight, jnp.float32), axis_name)
    med = weighted_coordinate_median(jnp.sqrt(ns_all), w_all)
    tau = jnp.float32(clip_mult) * med
    norm = jnp.sqrt(jnp.asarray(nsq, jnp.float32))
    return jnp.where(norm > tau, tau / jnp.maximum(norm, 1e-30), 1.0)


def clip_site_gradients(grads, weight, axis_name, clip_mult: float):
    """Apply the norm-clip defense to a per-site gradient pytree (leaves
    carry the leading ``[K]`` pack axis under a :class:`PackedAxis`,
    are unbatched per vmapped member otherwise). Returns the clipped tree;
    weights are untouched — clipping bounds a hostile site's INFLUENCE,
    the weighted mean still renormalizes as usual."""
    packed = isinstance(axis_name, PackedAxis)
    if packed:
        k = axis_name.pack
        nsq = jnp.zeros((k,), jnp.float32)
        for leaf in jax.tree.leaves(grads):
            nsq = nsq + jnp.sum(
                jnp.square(leaf.astype(jnp.float32)).reshape(k, -1), axis=1
            )
    else:
        nsq = jnp.zeros((), jnp.float32)
        for leaf in jax.tree.leaves(grads):
            nsq = nsq + jnp.sum(jnp.square(leaf.astype(jnp.float32)))
    scale = robust_clip_scales(nsq, weight, axis_name, clip_mult)
    return jax.tree.map(
        lambda g: (
            g.astype(jnp.float32)
            * scale.reshape(scale.shape + (1,) * (g.ndim - scale.ndim))
        ).astype(g.dtype),
        grads,
    )


def site_index(axis_name=SITE_AXIS):
    if isinstance(axis_name, PackedAxis):
        # per-device block start: virtual site d*K + j lives at row j of the
        # packed leaf on mesh member d (device-major global order; sliced
        # meshes linearize slice-major over the (slice, site) pair — the
        # same order the P((slice, site)) data layout shards to)
        if axis_name.name is None:
            base = 0
        else:
            base = jax.lax.axis_index(axis_name.reduce_axes())
        return base * axis_name.pack
    return jax.lax.axis_index(axis_name)


def site_count(axis_name=SITE_AXIS):
    if isinstance(axis_name, PackedAxis):
        n = 1 if axis_name.name is None else axis_size(axis_name.name)
        if axis_name.slice_name is not None:
            n = n * axis_size(axis_name.slice_name)
        return n * axis_name.pack
    return axis_size(axis_name)
