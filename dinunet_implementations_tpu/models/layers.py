"""Shared layers.

The one nontrivial piece is :class:`BatchNorm`: the reference uses two
different torch BatchNorm configurations —

- ``BatchNorm1d(h, track_running_stats=False)`` in MSANNet
  (``comps/fs/models.py:15``): batch statistics are used in *both* train and
  eval, nothing is tracked;
- ``BatchNorm1d(256)`` (track_running_stats=True) in the ICALstm classifier
  head (``comps/icalstm/models.py:97``): train uses batch stats and updates
  running stats (momentum 0.1, unbiased var), eval uses the running stats.

Because our SPMD batches are dense ``[B, ...]`` blocks with weight-0 padding
rows (data/batching.py), batch statistics must be **mask-weighted** — a padded
row must not shift the mean/var. With an all-ones mask this reduces exactly to
torch's biased batch variance.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


def compute_dtype_of(compute_dtype):
    """Resolve a model's ``compute_dtype`` field ("bfloat16" / "" / None /
    a dtype) to ``jnp.dtype | None`` — the one place the mixed-precision
    sentinel convention lives."""
    return jnp.dtype(compute_dtype) if compute_dtype else None


def masked_moments(x, mask, axis=0, eps_count: float = 1.0):
    """Weighted mean/var over ``axis`` (an int or tuple — e.g. ``(0,1,2,3)``
    for per-channel conv statistics). ``mask`` broadcasts against ``x`` with
    trailing feature dims of size 1. Biased variance (torch normalization)."""
    if mask is None:
        mean = jnp.mean(x, axis=axis, keepdims=True)
        var = jnp.mean(jnp.square(x - mean), axis=axis, keepdims=True)
        if isinstance(axis, int):
            count = x.shape[axis]
        else:
            count = 1
            for a in axis:
                count *= x.shape[a]
        return mean, var, count
    # the count must tally every reduced x-position the (broadcast) mask
    # covers — e.g. a [B,1,1,1,1] mask over (0,1,2,3) counts B·D·H·W, not B
    w = jnp.broadcast_to(mask, x.shape)
    count = jnp.maximum(jnp.sum(w, axis=axis, keepdims=True), eps_count)
    mean = jnp.sum(x * w, axis=axis, keepdims=True) / count
    var = jnp.sum(w * jnp.square(x - mean), axis=axis, keepdims=True) / count
    return mean, var, count


class BatchNorm(nn.Module):
    """Torch-faithful BatchNorm1d with optional running stats and masking.

    ``reduce_axes`` selects the statistics axes: 0 (default, BatchNorm1d over
    ``[B, F]``) or a tuple like ``(0, 1, 2, 3)`` for per-channel conv stats
    over ``[B, D, H, W, C]`` (BatchNorm3d semantics, channels-last)."""

    features: int
    track_running_stats: bool = False
    momentum: float = 0.1  # torch convention: new = (1-m)*old + m*batch
    eps: float = 1e-5
    reduce_axes: int | tuple = 0

    @nn.compact
    def __call__(self, x, train: bool = True, mask=None):
        scale = self.param("scale", nn.initializers.ones, (self.features,))
        bias = self.param("bias", nn.initializers.zeros, (self.features,))

        if self.track_running_stats:
            ra_mean = self.variable(
                "batch_stats", "mean", lambda: jnp.zeros((self.features,))
            )
            ra_var = self.variable(
                "batch_stats", "var", lambda: jnp.ones((self.features,))
            )

        m = None if mask is None else mask.reshape(mask.shape[0], *([1] * (x.ndim - 1)))
        use_batch = train or not self.track_running_stats
        if use_batch:
            mean, var, count = masked_moments(x, m, axis=self.reduce_axes)
            if self.track_running_stats and not self.is_initializing():
                # torch tracks the *unbiased* variance
                unbiased = var * (count / jnp.maximum(count - 1, 1))
                ra_mean.value = (1 - self.momentum) * ra_mean.value + self.momentum * mean.reshape(-1)
                ra_var.value = (1 - self.momentum) * ra_var.value + self.momentum * unbiased.reshape(-1)
            y = (x - mean) * jnp.reciprocal(jnp.sqrt(var + self.eps))
        else:
            y = (x - ra_mean.value) * jnp.reciprocal(jnp.sqrt(ra_var.value + self.eps))
        return y * scale + bias


class TorchLinearInit:
    """Torch ``nn.Linear`` initialization (kaiming-uniform weights,
    fan-in-uniform bias) — used so warm starts / parity comparisons against the
    reference start from the same distribution family."""

    @staticmethod
    def kernel(key, shape, dtype=jnp.float32):
        # flax Dense kernel shape is (fan_in, fan_out)
        fan_in = shape[0]
        # torch kaiming_uniform_(a=sqrt(5)): gain = sqrt(2/(1+5)) = sqrt(1/3),
        # bound = sqrt(3) * gain / sqrt(fan_in) = 1/sqrt(fan_in)
        bound = jnp.sqrt(1.0 / fan_in)
        import jax

        return jax.random.uniform(key, shape, dtype, -bound, bound)

    @staticmethod
    def bias_for(fan_in):
        def init(key, shape, dtype=jnp.float32):
            import jax

            bound = jnp.sqrt(1.0 / fan_in)
            return jax.random.uniform(key, shape, dtype, -bound, bound)

        return init


def dense(features: int, use_bias: bool = True, name=None, fan_in: int | None = None,
          dtype=None):
    """``nn.Dense`` with torch-style init. ``dtype`` sets the computation
    dtype (e.g. bf16 mixed precision); params stay f32."""
    return nn.Dense(
        features,
        use_bias=use_bias,
        name=name,
        dtype=dtype,
        param_dtype=jnp.float32,
        kernel_init=TorchLinearInit.kernel,
        bias_init=TorchLinearInit.bias_for(fan_in) if fan_in else nn.initializers.zeros,
    )
