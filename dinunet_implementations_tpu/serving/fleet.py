"""ReplicaSet — the serving fleet: N InferenceEngines, sharded sessions.

The serving path production-shaped (r21): instead of ONE engine on one
device, a :class:`ReplicaSet` runs N :class:`~.engine.InferenceEngine`
replicas, each pinned to its own device (round-robin over
``jax.devices()``), behind one routing front door. Three disciplines, all
reused from the training side rather than invented:

- **Replica membership is a MembershipTable** (robustness/membership.py):
  replica slots are the fixed axis, each (re)start of a replica joins at a
  bumped GENERATION — the auditable record that incarnation N+1 started
  with fresh state (a rebuilt engine: new session table, new zeroed carry
  rows, current live weights). The table's epoch bumps on every transition,
  exactly like site churn in the elastic-rounds daemon.
- **Sessions SHARD by id hash — never broadcast.** A streaming session's
  home replica is ``crc32(session_id) % capacity``; its chunks all route
  there, so its O(1) carry lives on exactly one device and the per-replica
  SessionTables partition the session space (capacity scales with the
  fleet instead of being replicated N times). When the home replica is
  down, routing probes forward to the next live slot; when a session MOVES
  (re-home on crash, or home coming back), the router closes it on the
  replica it left — the stale-carry kill: without the close, a session
  that bounced A→B→A and then loses A again would resolve on B as KNOWN
  and stream onto B's stale carry from its earlier sojourn. With it, every
  re-home re-enters through the fresh gate (carry zeroed in-trace, bumped
  session generation), so a re-homed stream replays bit-exact as a fresh
  session — the property tests/test_fleet.py pins.
- **Supervision is the PR 14 pattern in-process**: a supervisor thread
  probes each replica's lane threads (the in-process heartbeat) on an
  interval; a dead replica leaves the table, its engine is torn down, and
  a fresh engine rejoins at the next generation — with the CURRENT live
  weights, so a replica restarted after a hot-swap serves the published
  params, not the boot checkpoint.

Batched (sessionless) requests route to the least-loaded live replica
(queue depth, ties to the lowest slot). Params hot-swaps fan out to every
live replica (serving/publish.py drives them); each engine's donated-graft
swap keeps its own CompileGuard at zero, and :meth:`assert_no_compiles`
is the fleet-wide proof.
"""

from __future__ import annotations

import threading
import time
import zlib

from ..core.config import TrainConfig
from ..robustness.membership import MembershipTable
from ..telemetry.tracer import NULL_TRACER
from .engine import InferenceEngine, ServingError


def home_slot(session_id: str, capacity: int) -> int:
    """The session's home replica slot: a stable hash of the id over the
    fixed replica axis (crc32 — cheap, deterministic across processes, no
    PYTHONHASHSEED dependence)."""
    return zlib.crc32(str(session_id).encode()) % capacity


class ReplicaSet:
    """See module docstring. Construct, :meth:`warmup`, submit/stream,
    :meth:`close` (or use as a context manager)."""

    def __init__(self, cfg: TrainConfig, *, replicas: int = 2,
                 checkpoint: str | None = None, params=None,
                 batch_stats=None, supervise_interval_s: float = 0.2,
                 tracer=None, sink=None, bus=None, devices=None,
                 **engine_kwargs):
        import jax

        from ..telemetry.bus import NULL_BUS
        from ..trainer.checkpoint import load_inference_state

        if replicas < 1:
            raise ServingError(f"need >= 1 replica, got {replicas}")
        self.cfg = cfg
        self.tracer = tracer or NULL_TRACER
        self.sink = sink
        self.bus = bus if bus is not None else NULL_BUS
        self.meta: dict = {}
        if checkpoint is not None:
            params, batch_stats, self.meta = load_inference_state(checkpoint)
        if params is None:
            raise ServingError("need a checkpoint path or explicit params")
        # ONE host-side copy of the live weights;每 replica device_puts its
        # own. Updated on every successful swap so a restarted replica
        # serves the published weights, not the boot checkpoint.
        self._host_weights = (params, batch_stats or {})
        self._engine_kwargs = dict(engine_kwargs)
        # device pinning (r22): the fleet scheduler backfills idle slices
        # with serving replicas by handing the set the slice band's devices;
        # default (None) keeps the r21 behavior — replicas round-robin over
        # every visible device
        self._devices = list(devices) if devices else jax.devices()
        self.capacity = int(replicas)
        self.table = MembershipTable(capacity=self.capacity)
        self._engines: list = [None] * self.capacity
        # session id -> replica slot currently hosting it (the router's
        # memory — what lets a MOVE close the session at its old host)
        self._routes: dict = {}
        # one lock for table + engines + routes + weights: membership
        # transitions, routing and swaps are rare next to dispatches, and
        # dispatches don't take it (they run inside each engine)
        self._lock = threading.RLock()
        self._warm = False
        self.restarts = 0
        self.supervise_interval_s = float(supervise_interval_s)
        self._supervisor_stop = threading.Event()
        self._supervisor = threading.Thread(
            target=self._supervise, name="fleet-supervisor", daemon=True
        )
        self._t0 = time.monotonic()

    # -- replica lifecycle -----------------------------------------------

    def _replica_id(self, slot: int) -> str:
        return f"replica-{slot}"

    def _build_engine(self, slot: int) -> InferenceEngine:
        params, stats = self._host_weights
        return InferenceEngine(
            self.cfg, params=params, batch_stats=stats,
            device=self._devices[slot % len(self._devices)],
            bus_labels={"replica": str(slot)},
            tracer=self.tracer, sink=self.sink, bus=self.bus,
            close_sink=False, **self._engine_kwargs,
        )

    def _start_replica(self, slot: int) -> dict:
        """Build + warm one replica, THEN join it into the membership table
        at a bumped generation (a failed build leaves the table showing the
        slot down — consistent with reality, and the supervisor retries).
        Returns the warmup times. Caller holds the lock."""
        eng = self._build_engine(slot)
        times = eng.warmup()
        self.table, _, gen = self.table.join(self._replica_id(slot))
        self._engines[slot] = eng
        self.bus.gauge("serving_replicas_live", self.table.occupied)
        self.bus.counter("serving_replica_starts_total", replica=str(slot))
        if self.sink is not None:
            self.sink.append({
                "kind": "event", "name": "replica-start",
                "replica": slot, "generation": gen,
                "membership_epoch": self.table.epoch,
            })
        return times

    def warmup(self) -> dict:
        """Warm every replica (each AOT-compiles its own executable set on
        its own device) and start the supervisor. Returns
        ``{"replica-<i>/<lane>/<bucket>": seconds}``."""
        times = {}
        with self._lock:
            for slot in range(self.capacity):
                for k, v in self._start_replica(slot).items():
                    times[f"{self._replica_id(slot)}/{k}"] = v
            self._warm = True
        self._supervisor.start()
        return times

    def _replica_alive(self, slot: int) -> bool:
        eng = self._engines[slot]
        if eng is None or not eng._warm:
            return False
        try:
            return all(probe() for probe in eng.health_probes().values())
        except Exception:
            return False

    def kill_replica(self, slot: int) -> None:
        """Simulate a replica crash (tests, CI fault drills): wedge its
        lanes closed WITHOUT the orderly engine close. The supervisor's
        next probe sees the dead lanes and restarts the slot."""
        with self._lock:
            eng = self._engines[slot]
            if eng is None:
                return
            for lane in (getattr(eng, "_infer_lane", None),
                         getattr(eng, "_stream_lane", None)):
                if lane is not None:
                    lane.close(timeout=2.0)

    def restart_replica(self, slot: int) -> None:
        """Leave + rejoin the slot at a bumped generation with a FRESH
        engine on the current live weights. Every session homed or re-homed
        there loses its route (their next chunk re-resolves through the
        new, empty session table — the fresh gate)."""
        with self._lock:
            old = self._engines[slot]
            self._engines[slot] = None
            rid = self._replica_id(slot)
            if self.table.slot_of(rid) is not None:
                self.table, _ = self.table.leave(rid)
            self._routes = {
                sid: s for sid, s in self._routes.items() if s != slot
            }
            if old is not None:
                for lane in (getattr(old, "_infer_lane", None),
                             getattr(old, "_stream_lane", None)):
                    if lane is not None:
                        lane.close(timeout=2.0)
            self.restarts += 1
            self.bus.counter(
                "serving_replica_restarts_total", replica=str(slot)
            )
            self._start_replica(slot)

    def _supervise(self) -> None:
        """The PR 14 supervisor loop, in-process: probe every slot's lane
        threads; restart dead replicas at the next generation."""
        while not self._supervisor_stop.wait(self.supervise_interval_s):
            with self._lock:
                if not self._warm:
                    continue
                dead = [
                    slot for slot in range(self.capacity)
                    if not self._replica_alive(slot)
                ]
            for slot in dead:
                if self._supervisor_stop.is_set():
                    return
                try:
                    self.restart_replica(slot)
                except Exception:
                    # build/warmup failed — the slot stays down and the
                    # next probe retries; never kill the supervisor
                    self.bus.counter(
                        "serving_replica_restart_failures_total",
                        replica=str(slot),
                    )

    # -- routing ---------------------------------------------------------

    def _live_slots(self) -> list:
        return [
            s for s in range(self.capacity) if self._replica_alive(s)
        ]

    def _route_session(self, session_id: str) -> int:
        """The session's CURRENT replica: its home slot when live, else the
        next live slot (linear probe). A move closes the session at the
        replica it left — see the module docstring's stale-carry kill."""
        with self._lock:
            home = home_slot(session_id, self.capacity)
            slot = None
            for probe in range(self.capacity):
                cand = (home + probe) % self.capacity
                if self._replica_alive(cand):
                    slot = cand
                    break
            if slot is None:
                raise ServingError("no live replica to route to")
            prev = self._routes.get(session_id)
            if prev is not None and prev != slot:
                prev_eng = self._engines[prev]
                if prev_eng is not None and self._replica_alive(prev):
                    try:
                        prev_eng.close_session(session_id)
                    except Exception:
                        pass  # never resolved there (or already closed)
                self.bus.counter(
                    "serving_session_rehomes_total", replica=str(slot)
                )
            self._routes[session_id] = slot
            return slot

    def _least_loaded(self) -> int:
        """Batched requests have no affinity: lowest queue depth wins,
        ties to the lowest slot."""
        with self._lock:
            live = self._live_slots()
            if not live:
                raise ServingError("no live replica to route to")
            return min(
                live,
                key=lambda s: (self._engines[s]._infer_lane.depth(), s),
            )

    # -- request front door ----------------------------------------------

    def submit(self, rows, weights=None, trace_id=None, priority: int = 0,
               deadline_ms=None):
        self._ensure_warm()
        slot = self._least_loaded()
        return self._engines[slot].submit(
            rows, weights=weights, trace_id=trace_id, priority=priority,
            deadline_ms=deadline_ms,
        )

    def stream(self, session_id: str, windows, trace_id=None,
               priority: int = 0):
        self._ensure_warm()
        slot = self._route_session(session_id)
        return self._engines[slot].stream(
            session_id, windows, trace_id=trace_id, priority=priority
        )

    def close_session(self, session_id: str) -> None:
        with self._lock:
            slot = self._routes.pop(session_id, None)
            if slot is not None and self._engines[slot] is not None:
                self._engines[slot].close_session(session_id)

    def replica_of(self, session_id: str):
        """Where the router last placed a session (None = never routed)."""
        with self._lock:
            return self._routes.get(session_id)

    def _ensure_warm(self) -> None:
        if not self._warm:
            raise ServingError("call warmup() before submitting requests")

    @property
    def streaming(self) -> bool:
        """Whether the replicas run a streaming lane (uniform with the
        single-engine surface for the CLI)."""
        return any(
            e.streaming for e in self._engines if e is not None
        )

    @property
    def warmup_seconds(self) -> float:
        return round(sum(
            e.warmup_seconds for e in self._engines if e is not None
        ), 4)

    def drain(self, timeout: float = 30.0) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                engines = [e for e in self._engines if e is not None]
            if all(
                L.depth() == 0
                for e in engines
                for L in (getattr(e, "_infer_lane", None),
                          getattr(e, "_stream_lane", None)) if L
            ):
                return
            time.sleep(0.002)

    # -- publish plane (serving/publish.py drives these) ------------------

    def weights(self) -> tuple:
        """Host-side (params, batch_stats) — the rollback retention target
        (device-agnostic: a later swap-back device_puts per replica)."""
        with self._lock:
            return self._host_weights

    def shadow_score(self, params, batch_stats=None) -> dict:
        """Score a candidate on ONE live replica's mirrored traffic (the
        executables are identical across replicas — one shadow pass proves
        the candidate for the fleet)."""
        with self._lock:
            live = self._live_slots()
            if not live:
                raise ServingError("no live replica to shadow-score on")
            eng = self._engines[live[0]]
        return eng.shadow_score(params, batch_stats)

    def swap_params(self, params, batch_stats=None) -> dict:
        """Fan the donated hot-swap out to every live replica; the host
        weight copy updates so later restarts serve the new params.
        Returns per-replica pause plus the max (the fleet's publish-window
        pause figure).

        The candidate is snapshotted to HOST arrays first: each engine's
        swap donates the buffers it is handed, and when the candidate
        already lives on some replica's device the first swap would delete
        the very arrays the next replica needs. From the host snapshot,
        every engine device_puts (and donates) its own private copy."""
        import jax
        import numpy as np

        params = jax.tree.map(np.asarray, params)
        batch_stats = (
            jax.tree.map(np.asarray, batch_stats)
            if batch_stats is not None else None
        )
        with self._lock:
            self._ensure_warm()
            pauses = {}
            for slot in self._live_slots():
                got = self._engines[slot].swap_params(params, batch_stats)
                pauses[self._replica_id(slot)] = got["pause_ms"]
            self._host_weights = (params, batch_stats or {})
        return {
            "pause_ms": max(pauses.values()) if pauses else 0.0,
            "per_replica": pauses,
        }

    # -- proofs + rollup --------------------------------------------------

    def assert_no_compiles(self) -> None:
        """The fleet-wide zero-compile proof — every replica's guard, so N
        replicas and K swaps later the request path still never traced."""
        with self._lock:
            engines = [e for e in self._engines if e is not None]
        for eng in engines:
            eng.assert_no_compiles()

    def compiles_after_warmup(self) -> dict:
        with self._lock:
            engines = list(enumerate(self._engines))
        return {
            f"replica-{i}/{k}": v
            for i, e in engines if e is not None
            for k, v in e.compiles_after_warmup().items()
        }

    def health_probes(self) -> dict:
        probes = {"warm": lambda: self._warm}
        for slot in range(self.capacity):
            probes[f"replica_{slot}"] = (
                lambda s=slot: self._replica_alive(s)
            )
        return probes

    def status(self) -> dict:
        with self._lock:
            statuses = {
                self._replica_id(i): e.status()
                for i, e in enumerate(self._engines) if e is not None
            }
            return {
                "task_id": self.cfg.task_id,
                "warm": self._warm,
                "replicas": self.capacity,
                # the device band the replicas round-robin over (r22: the
                # scheduler pins backfill lanes to idle slices' devices)
                "devices": [str(d) for d in self._devices],
                "replicas_live": self.table.occupied,
                "membership": self.table.to_json(),
                "routed_sessions": len(self._routes),
                "restarts": self.restarts,
                "per_replica": statuses,
            }

    def summary(self) -> dict:
        """The fleet rollup serve_summary row: per-replica summaries merged
        (requests/samples summed, latency percentiles over the union via
        the merged bus histogram when available)."""
        with self._lock:
            parts = [
                e.summary() for e in self._engines if e is not None
            ]
        agg = {
            "kind": "serve_summary",
            "task_id": self.cfg.task_id,
            "replica": "fleet",
            "replicas": self.capacity,
            "restarts": self.restarts,
            "swaps": sum(p["swaps"] for p in parts),
            "requests": sum(p["requests"] for p in parts),
            "samples": sum(p["samples"] for p in parts),
            "stream_chunks": sum(p["stream_chunks"] for p in parts),
            "dispatches": sum(p["dispatches"] for p in parts),
            "deferrals": sum(p["deferrals"] for p in parts),
            "shed": sum(p["shed"] for p in parts),
            "warmup_seconds": round(
                sum(p["warmup_seconds"] for p in parts), 4
            ),
            "compiles_after_warmup": sum(
                p["compiles_after_warmup"] for p in parts
            ),
            "max_queue_depth": max(
                (p["max_queue_depth"] for p in parts), default=0
            ),
        }
        elapsed = max(time.monotonic() - self._t0, 1e-9)
        agg["requests_per_s"] = round(agg["requests"] / elapsed, 2)
        agg["samples_per_s"] = round(agg["samples"] / elapsed, 2)
        # pad waste + bucket hit rate: dispatch-weighted means of the
        # per-replica figures
        disp = max(agg["dispatches"], 1)
        agg["bucket_hit_rate"] = round(
            sum(p["bucket_hit_rate"] * p["dispatches"] for p in parts)
            / disp, 4,
        )
        agg["pad_waste_pct"] = round(
            sum(p["pad_waste_pct"] * p["dispatches"] for p in parts)
            / disp, 2,
        )
        hist = self.bus.merged_histogram("serving_request_latency_ms")
        if hist is not None and hist.count:
            pct = hist.percentiles()
            agg["latency_ms_p50"] = pct["p50"]
            agg["latency_ms_p95"] = pct["p95"]
            agg["latency_ms_p99"] = pct["p99"]
        else:
            lat = sorted(
                v for p in parts
                for v in [p["latency_ms_p50"], p["latency_ms_p95"],
                          p["latency_ms_p99"]]
                if v is not None
            )
            agg["latency_ms_p50"] = lat[0] if lat else None
            agg["latency_ms_p95"] = lat[len(lat) // 2] if lat else None
            agg["latency_ms_p99"] = lat[-1] if lat else None
        agg["per_replica"] = parts
        return agg

    def close(self) -> dict:
        """Stop supervision, close every replica (each appends its own
        serve_summary row), emit the fleet rollup row, close the shared
        sink once, and re-assert the fleet-wide zero-compile proof."""
        self._supervisor_stop.set()
        if self._supervisor.is_alive():
            self._supervisor.join(5.0)
        with self._lock:
            engines = [e for e in self._engines if e is not None]
        for eng in engines:
            eng.close()
        summary = self.summary()
        if self.sink is not None:
            self.sink.append(summary)
            self.sink.close()
        self.assert_no_compiles()
        return summary

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
