"""Slice-tier worker supervision (r19) — preemption-tolerant multi-slice runs.

At multi-slice scale, a preempted slice or a crashed/wedged worker process is
the NORMAL failure mode, not the exception (PAPERS.md: the TPUv4 pjit
playbook treats slice restarts as routine). This module is the host-side
machinery that turns "one dead ``dcn_worker`` kills the run" into "the run
completes":

- :class:`Heartbeat` — each worker process writes an atomic JSON heartbeat
  (pid, slice, epoch/round progress) every ``interval_s`` from a daemon
  thread; :func:`heartbeat_age_s` is the supervisor's staleness probe.
- **Liveness spool** (:func:`mark_slice_dead` / :func:`mark_slice_alive` /
  :func:`read_slice_liveness`) — an append-only event directory recording
  every slice death (reason, last heartbeat age, restart generation) and
  revival. The shared, machine-readable record of slice churn: the flight
  recorder notes the same events, the spool survives the supervisor itself.
- **Cross-slice checkpoint consensus** (:func:`consensus_round`) — every
  supervised worker rotates a per-slice checkpoint sidecar whose meta
  carries ``(round, params_sha256)`` (runner/dcn_worker.py). After a slice
  death the supervisor picks the NEWEST round at which all surviving
  slices' candidates (latest AND ``.prev`` — a torn primary falls back per
  the PR 2 contract) agree by params digest, and installs that generation
  as the fleet's resume point. Params are replicated by the aggregation
  collectives, so digest agreement at a round means the fleet state is ONE
  state — the restarted slice rejoins the run mid-flight by plain
  ``--resume``, bit-exact with a run that never faulted.
- :class:`SliceSupervisor` — the restart state machine: LAUNCH the
  per-slice workers → MONITOR exits and heartbeat staleness (the staleness
  verdict runs under :func:`~..robustness.retry.with_retry` deadline
  semantics, so one slow NFS stat never declares a slice dead) → on death,
  DRAIN the survivors (SIGTERM → they checkpoint and exit ``128+15`` via
  the PreemptionGuard; SIGKILL after a grace window for workers wedged in
  a collective — a dead peer leaves the others blocked in the DCN reduce
  forever, which is exactly why the supervisor exists) → CONSENSUS →
  RELAUNCH with ``--resume`` until the run completes or ``max_restarts``
  is exhausted.

jax.distributed cannot (today) shrink or regrow a live process group, so
the restart unit is the worker FLEET, not the single process: the run
degrades to checkpoint granularity on a fault, never to zero. That is the
same recovery contract real multi-slice TPU training uses.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import threading
import time

from ..robustness.retry import with_retry

#: supervisor exit code: a slice kept dying past max_restarts
SUPERVISOR_GAVE_UP_RC = 69

HEARTBEAT_DIR = "heartbeats"
LIVENESS_DIR = "slice_liveness"
SLICE_CKPT_DIR = "slices"


def _atomic_json(path: str, payload: dict) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(payload, fh)
    os.replace(tmp, path)


# ---------------------------------------------------------------------------
# heartbeats
# ---------------------------------------------------------------------------


def heartbeat_path(out_dir: str, slice_id: int) -> str:
    return os.path.join(out_dir, HEARTBEAT_DIR, f"slice_{slice_id}.json")


class Heartbeat:
    """A worker's liveness pulse: an atomically-replaced JSON file carrying
    pid / slice / wall-clock plus whatever progress the worker last noted
    (epoch, global round). The pulse rides a daemon TIMER thread, so
    staleness means the process is hard-frozen (SIGSTOP, scheduler
    starvation) or its out_dir writes block (dead shared mount) — not
    merely slow. A worker wedged in a collective whose peer died keeps
    beating; THAT failure mode is recovered through the peer's observable
    exit + the supervisor's drain, and the heartbeat is the backstop for
    deaths with no exit to observe. One writer per slice (the slice-lead
    rank, runner/dcn_worker.py) keeps the file's semantics crisp.

    Since r23 each pulse also carries the pod-observability discovery
    fields: ``started_unix`` (construction wall time — a recycled pid
    cannot impersonate the worker that wrote the file),
    ``perf``/``time_unix`` sampled back-to-back (the per-process
    monotonic→wall offset the trace assembler aligns clocks with), and —
    once the worker advertises it via ``beat(statusz_port=...)`` — the
    process's live /statusz port, so the PodCollector
    (telemetry/collector.py) scrapes the fleet with zero extra config."""

    def __init__(self, path: str, slice_id: int, interval_s: float = 2.0):
        self.path = path
        self.slice_id = slice_id
        self.interval_s = interval_s
        self.started_unix = time.time()
        self._extra: dict = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    def beat(self, **extra) -> None:
        """Write one pulse now; ``extra`` (epoch/round progress, the
        advertised statusz port) persists into subsequent background
        pulses."""
        if extra:
            self._extra.update(extra)
        try:
            _atomic_json(self.path, {
                "pid": os.getpid(),
                "slice": self.slice_id,
                "started_unix": self.started_unix,
                # perf and time_unix sampled adjacently: their difference
                # IS this process's monotonic→wall clock offset
                "perf": time.perf_counter(),
                "time_unix": time.time(),
                **self._extra,
            })
        except OSError:
            pass  # a full disk must not kill the worker it monitors

    def start(self) -> "Heartbeat":
        self.beat()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"heartbeat-slice{self.slice_id}",
        )
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.beat()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval_s + 1.0)
            self._thread = None


def read_heartbeat(path: str) -> dict | None:
    """The last pulse, or None (unreadable/missing — a beat may be mid-
    replace, which os.replace makes atomic, so unreadable means absent)."""
    try:
        with open(path) as fh:
            return json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None


def heartbeat_age_s(path: str, now: float | None = None) -> float | None:
    """Seconds since the last pulse, or None when no pulse exists yet."""
    hb = read_heartbeat(path)
    if hb is None or "time_unix" not in hb:
        return None
    return max((now if now is not None else time.time()) - hb["time_unix"], 0.0)


# ---------------------------------------------------------------------------
# the shared slice-liveness spool
# ---------------------------------------------------------------------------


def _spool_event(liveness_dir: str, event: dict) -> str:
    os.makedirs(liveness_dir, exist_ok=True)
    event = {"time_unix": time.time(), **event}
    # monotonic sequence names keep sorted-order == event order, the same
    # convention as the daemon's ingest spool (runner/fed_runner.py)
    seq = len([n for n in os.listdir(liveness_dir) if n.endswith(".json")])
    path = os.path.join(
        liveness_dir, f"ev{seq:06d}_slice{event.get('slice', 'x')}.json"
    )
    _atomic_json(path, event)
    return path


def mark_slice_dead(liveness_dir: str, slice_id: int, reason: str,
                    heartbeat_age: float | None = None,
                    generation: int = 0) -> str:
    """Record a slice death in the shared liveness spool. Returns the event
    path."""
    return _spool_event(liveness_dir, {
        "event": "dead", "slice": int(slice_id), "reason": reason,
        "heartbeat_age_s": heartbeat_age, "generation": int(generation),
    })


def mark_slice_alive(liveness_dir: str, slice_id: int,
                     generation: int) -> str:
    """Record a slice revival (supervised restart, generation bumped)."""
    return _spool_event(liveness_dir, {
        "event": "alive", "slice": int(slice_id),
        "generation": int(generation),
    })


def read_slice_liveness(liveness_dir: str) -> list:
    """Every liveness event, oldest first (sorted-name order)."""
    try:
        names = sorted(
            n for n in os.listdir(liveness_dir) if n.endswith(".json")
        )
    except OSError:
        return []
    out = []
    for n in names:
        try:
            with open(os.path.join(liveness_dir, n)) as fh:
                out.append(json.load(fh))
        except (OSError, json.JSONDecodeError):
            continue
    return out


# ---------------------------------------------------------------------------
# cross-slice checkpoint consensus
# ---------------------------------------------------------------------------


def slice_ckpt_dir(out_dir: str, slice_id: int) -> str:
    return os.path.join(out_dir, SLICE_CKPT_DIR, f"slice_{slice_id}")


def slice_ckpt_candidates(ckpt_dir: str) -> dict:
    """``{round: (sha, path)}`` from one slice's rotating checkpoint pair —
    latest AND ``.prev`` are SEPARATE candidates (a torn primary's round
    must not shadow the intact previous generation)."""
    from ..trainer.checkpoint import CorruptCheckpointError, load_meta

    path = os.path.join(ckpt_dir, "checkpoint_latest.msgpack")
    out: dict = {}
    for cand in (path + ".prev", path):  # latest last: it wins ties
        if not os.path.exists(cand):
            continue
        try:
            meta = load_meta(cand, fallback=False)
        except (OSError, CorruptCheckpointError):
            continue  # torn/corrupt generation: not a candidate
        rnd, sha = meta.get("round"), meta.get("params_sha256")
        if rnd is None or not sha:
            continue
        out[int(rnd)] = (sha, cand)
    return out


def consensus_round(slice_dirs: dict) -> tuple | None:
    """The newest global round at which EVERY surviving slice holds a
    rotating checkpoint candidate with the SAME params digest.

    ``slice_dirs`` maps slice id → its sidecar checkpoint dir. Returns
    ``(round, sha, path)`` — ``path`` is one of the agreed checkpoint files
    (they are bit-identical by digest, so any serves as the fleet's resume
    point) — or None when no common agreed round exists (the fleet then
    restarts from whatever the shared fold checkpoint holds, or from
    scratch)."""
    per_slice = {
        sl: slice_ckpt_candidates(d) for sl, d in slice_dirs.items()
    }
    if not per_slice or any(not c for c in per_slice.values()):
        return None
    common = None
    for cands in per_slice.values():
        rounds = set(cands)
        common = rounds if common is None else (common & rounds)
    agreed = []
    for rnd in sorted(common or (), reverse=True):
        shas = {cands[rnd][0] for cands in per_slice.values()}
        if len(shas) == 1:
            sha = shas.pop()
            path = next(iter(per_slice.values()))[rnd][1]
            agreed.append((rnd, sha, path))
            break
    return agreed[0] if agreed else None


# ---------------------------------------------------------------------------
# the supervisor state machine
# ---------------------------------------------------------------------------


class SliceSupervisor:
    """Launch, monitor, and restart a fleet of per-slice worker processes
    (module docstring: the restart unit is the fleet; recovery granularity
    is the consensus checkpoint).

    ``spawn(process_id, generation)`` returns a started
    ``subprocess.Popen`` for one worker — the supervisor owns nothing about
    the worker's command line, which keeps the state machine unit-testable
    with stub scripts (tests/test_supervisor.py) and reusable by the real
    ``dcn_worker --supervise`` entry. ``on_consensus(generation,
    dead_slice)`` (optional) runs between drain and relaunch — the real
    entry installs the consensus checkpoint as the fleet resume point
    there. ``passthrough_rcs`` exit
    codes (e.g. the rc-66 capability skip) propagate immediately instead of
    counting as a slice death."""

    def __init__(
        self,
        spawn,
        num_processes: int,
        out_dir: str,
        slice_of_process=None,
        heartbeat_timeout_s: float = 30.0,
        max_restarts: int = 2,
        poll_s: float = 0.5,
        grace_s: float = 20.0,
        flight=None,
        bus=None,
        on_consensus=None,
        passthrough_rcs: tuple = (),
    ):
        self.spawn = spawn
        self.num_processes = num_processes
        self.out_dir = out_dir
        self.slice_of_process = slice_of_process or (lambda pid_: pid_)
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.max_restarts = max_restarts
        self.poll_s = poll_s
        self.grace_s = grace_s
        self.flight = flight
        self.bus = bus
        self.on_consensus = on_consensus
        self.passthrough_rcs = tuple(passthrough_rcs)
        self.generation = 0
        self.restarts = 0
        self.liveness_dir = os.path.join(out_dir, LIVENESS_DIR)

    # -- probes ------------------------------------------------------------

    def _note(self, name: str, **attrs) -> None:
        if self.flight is not None:
            self.flight.note(name, **attrs)

    def _count(self, name: str, **labels) -> None:
        if self.bus is not None:
            # API-boundary forward: NAME is a literal at every call site
            self.bus.counter(name, **labels)  # jaxlint: disable=R007

    def _stale_verdict(self, slice_id: int) -> float | None:
        """Heartbeat-staleness verdict for one slice, under with_retry
        DEADLINE semantics: a missing/old pulse is re-probed with backoff
        until the staleness budget is spent — one slow shared-FS stat (or
        a beat landing mid-probe) never declares a live slice dead. Returns
        the final heartbeat age when the slice is STALE past the deadline,
        None when a fresh pulse appeared."""
        path = heartbeat_path(self.out_dir, slice_id)

        class _Stale(OSError):
            pass

        def probe():
            age = heartbeat_age_s(path)
            if age is None or age > self.heartbeat_timeout_s:
                raise _Stale(f"heartbeat age {age}")
            return age

        try:
            with_retry(
                probe, attempts=8, base_delay=0.25,
                retry_on=(_Stale,),
                deadline_s=self.heartbeat_timeout_s,
                describe=f"slice {slice_id} heartbeat",
            )()
            return None
        except _Stale:
            return heartbeat_age_s(path)

    # -- fleet control -----------------------------------------------------

    def _launch(self) -> list:
        self.generation += 1
        # clear the previous generation's heartbeats: a restarted worker
        # needs its jax-import warmup before the first pulse, and a stale
        # file from the DEAD generation would otherwise get the fresh
        # fleet judged wedged during startup (age None = not stale)
        hb_dir = os.path.join(self.out_dir, HEARTBEAT_DIR)
        try:
            for name in os.listdir(hb_dir):
                os.remove(os.path.join(hb_dir, name))
        except OSError:
            pass
        procs = []
        for r in range(self.num_processes):
            procs.append(self.spawn(r, self.generation))
        self._note("fleet-launch", generation=self.generation,
                   processes=self.num_processes)
        return procs

    def _drain(self, procs: list, skip: int | None = None) -> None:
        """SIGTERM the surviving workers (they checkpoint and exit via the
        PreemptionGuard), escalating to SIGKILL after the grace window —
        a worker wedged in a collective whose peer died never reaches its
        epoch-boundary signal poll, and waiting on it would wedge the
        supervisor too."""
        for i, p in enumerate(procs):
            if i == skip or p.poll() is not None:
                continue
            try:
                p.send_signal(signal.SIGTERM)
            except OSError:
                pass
        deadline = time.monotonic() + self.grace_s
        for i, p in enumerate(procs):
            if i == skip:
                continue
            try:
                p.wait(timeout=max(deadline - time.monotonic(), 0.1))
            except subprocess.TimeoutExpired:
                self._note("worker-wedged", process=i,
                           generation=self.generation)
                p.kill()
                p.wait()

    def _slice_death(self, procs: list, process_id: int, reason: str,
                     hb_age: float | None) -> None:
        slice_id = self.slice_of_process(process_id)
        # the flight dump's reason carries slice id + last heartbeat age —
        # the post-mortem an operator reads first
        self._note("slice-death", slice=slice_id, process=process_id,
                   reason=reason, heartbeat_age_s=hb_age,
                   generation=self.generation)
        self._count("supervisor_slice_deaths_total", slice=str(slice_id))
        if "heartbeat" in reason:
            self._count("dcn_heartbeat_timeouts_total", slice=str(slice_id))
        mark_slice_dead(
            self.liveness_dir, slice_id, reason,
            heartbeat_age=hb_age, generation=self.generation,
        )
        if self.flight is not None:
            age = "none" if hb_age is None else f"{hb_age:.1f}s"
            self.flight.dump(
                f"slice-death:slice={slice_id}:hb_age={age}:{reason}"
            )
        self._drain(procs, skip=process_id)

    def run(self) -> int:
        """The supervise loop. Returns the fleet's exit code: 0 on a
        completed run, a passthrough rc verbatim (capability skips), the
        first worker's failing rc when restarts are exhausted (signal
        deaths mapped to the shell's ``128+signum``), or
        :data:`SUPERVISOR_GAVE_UP_RC` when a slice keeps dying."""
        while True:
            procs = self._launch()
            death: tuple | None = None  # (process_id, reason, hb_age)
            while death is None:
                states = [p.poll() for p in procs]
                if all(rc == 0 for rc in states):
                    self._note("fleet-complete", generation=self.generation)
                    return 0
                for r, rc in enumerate(states):
                    if rc is None or rc == 0:
                        continue
                    if rc in self.passthrough_rcs:
                        # capability skip (rc 66): not a fault — drain and
                        # propagate so CI skips instead of restarting
                        self._drain(procs, skip=r)
                        return rc
                    sig = f" (signal {-rc})" if rc < 0 else ""
                    death = (r, f"exit rc={rc}{sig}", heartbeat_age_s(
                        heartbeat_path(self.out_dir,
                                       self.slice_of_process(r))))
                    break
                if death is not None:
                    break
                # exits clean so far: probe heartbeats of the still-running
                # workers for wedge detection
                for r, rc in enumerate(states):
                    if rc is not None:
                        continue
                    path = heartbeat_path(
                        self.out_dir, self.slice_of_process(r)
                    )
                    age = heartbeat_age_s(path)
                    if age is not None and age > self.heartbeat_timeout_s:
                        # suspicious: confirm under the retry deadline
                        # before killing a live worker
                        stale = self._stale_verdict(self.slice_of_process(r))
                        if stale is not None and procs[r].poll() is None:
                            procs[r].kill()
                            procs[r].wait()
                            death = (r, "heartbeat stale", stale)
                            break
                if death is None:
                    time.sleep(self.poll_s)
            process_id, reason, hb_age = death
            self._slice_death(procs, process_id, reason, hb_age)
            self.restarts += 1
            if self.restarts > self.max_restarts:
                self._note("supervisor-give-up", restarts=self.restarts)
                rc = procs[process_id].poll()
                if rc is None or rc == 0:
                    return SUPERVISOR_GAVE_UP_RC
                # Popen reports signal deaths as -signum; sys.exit would
                # wrap that mod 256 into an undocumented status (e.g. 247)
                # — map to the shell's 128+signum convention instead
                return 128 - rc if rc < 0 else rc
            if self.on_consensus is not None:
                self.on_consensus(
                    self.generation, self.slice_of_process(process_id)
                )
            mark_slice_alive(
                self.liveness_dir, self.slice_of_process(process_id),
                self.generation + 1,
            )
            self._count("supervisor_restarts_total")
            self._note("fleet-restart", generation=self.generation + 1,
                       after_slice=self.slice_of_process(process_id))
