"""Training-step bit-parity vs the PyTorch reference (VERDICT r2 #3).

The north star (BASELINE.json) demands "bit-matching parity to the PyTorch
remote.py aggregator". Round 2 proved model-forward parity and dSGD==pooled;
this closes the remaining gap: a FULL federated dSGD round — forward → NLL
loss → backward → example-weighted cross-site average → Adam — run in both
frameworks from identical weights and batches must land on the same params.

Torch side reimplements the reference round semantics explicitly
(``local.py:49`` per-site grads; ``remote.py:37`` dSGD weighted average;
coinstac-dinunet trains with torch.optim.Adam) against the reference's own
MSANNet loaded from ``/root/reference/comps/fs/models.py``.

Optimizer-math alignment (the "hard part" SURVEY §7 flagged): optax.adam and
torch.optim.Adam agree exactly here — both use update = m̂ / (√v̂ + ε) with
bias correction and ε OUTSIDE the sqrt but AFTER it (optax eps_root=0 ≡ torch
denom = √v̂ + ε), default β=(0.9, 0.999), ε=1e-8. No remapping needed. The
gradient averaging is example-count weighted on the jax side; torch mirrors
it (equal per-site batches here, so it equals the plain mean).
"""

import importlib.util
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# torch lives in the optional [test] extra; environments without it (e.g. the
# CI tier-1 job, which installs [dev] only) skip the parity suite cleanly
# instead of failing collection
torch = pytest.importorskip("torch")
import torch.nn.functional as F  # noqa: E402

from dinunet_implementations_tpu.engines import make_engine
from dinunet_implementations_tpu.models import MSANNet
from dinunet_implementations_tpu.trainer import (
    FederatedTask,
    init_train_state,
    make_optimizer,
    make_train_epoch_fn,
)

IN, HIDDEN, OUT = 12, (16, 8), 2
SITES, B, LR = 2, 6, 1e-3

_REF_MODELS = "/root/reference/comps/fs/models.py"

#: the module-level tests below that load the reference's own torch MSANNet
#: must skip (not error) on containers without the reference checkout — the
#: same needs_reference contract as tests/test_runner.py
needs_reference_models = pytest.mark.skipif(
    not os.path.exists(_REF_MODELS), reason="reference checkout not mounted"
)


def _load_ref_msannet():
    spec = importlib.util.spec_from_file_location(
        "ref_fs_models", "/root/reference/comps/fs/models.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.MSANNet(in_size=IN, hidden_sizes=list(HIDDEN), out_size=OUT)


def _copy_params_to_torch(params, tm):
    """jax param tree → the reference torch module (kernels transpose)."""
    with torch.no_grad():
        for i in range(len(HIDDEN)):
            lin, bn = tm.layers[i][0], tm.layers[i][1]
            lin.weight.copy_(torch.tensor(np.asarray(params[f"linear_{i}"]["kernel"]).T))
            bn.weight.copy_(torch.tensor(np.asarray(params[f"bn_{i}"]["scale"])))
            bn.bias.copy_(torch.tensor(np.asarray(params[f"bn_{i}"]["bias"])))
        tm.fc_out.weight.copy_(torch.tensor(np.asarray(params["fc_out"]["kernel"]).T))
        tm.fc_out.bias.copy_(torch.tensor(np.asarray(params["fc_out"]["bias"])))


def _torch_params_as_tree(tm):
    out = {}
    for i in range(len(HIDDEN)):
        out[f"linear_{i}"] = {"kernel": tm.layers[i][0].weight.detach().numpy().T}
        out[f"bn_{i}"] = {
            "scale": tm.layers[i][1].weight.detach().numpy(),
            "bias": tm.layers[i][1].bias.detach().numpy(),
        }
    out["fc_out"] = {
        "kernel": tm.fc_out.weight.detach().numpy().T,
        "bias": tm.fc_out.bias.detach().numpy(),
    }
    return out


@pytest.mark.slow
@needs_reference_models
def test_federated_dsgd_adam_round_matches_torch():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(SITES, 1, B, IN)).astype(np.float32)
    y = (rng.random((SITES, 1, B)) > 0.5).astype(np.int64)
    w = np.ones((SITES, 1, B), np.float32)
    rounds = 3

    # --- jax side: one jitted SPMD round per epoch call
    model = MSANNet(in_size=IN, hidden_sizes=HIDDEN, out_size=OUT)
    task = FederatedTask(model)
    engine = make_engine("dSGD")
    opt = make_optimizer("adam", LR)
    state = init_train_state(
        task, engine, opt, jax.random.PRNGKey(0), jnp.asarray(x[0, 0]),
        num_sites=SITES,
    )
    epoch_fn = make_train_epoch_fn(task, engine, opt, mesh=None, local_iterations=1)

    # --- torch side: the reference round, from the SAME initial weights
    tm = _load_ref_msannet()
    _copy_params_to_torch(state.params, tm)
    topt = torch.optim.Adam(tm.parameters(), lr=LR)
    tm.train()

    tx = [torch.tensor(x[s, 0]) for s in range(SITES)]
    ty = [torch.tensor(y[s, 0]) for s in range(SITES)]

    for _ in range(rounds):
        state, _ = epoch_fn(
            state, jnp.asarray(x), jnp.asarray(y), jnp.asarray(w)
        )

        site_grads = []
        for s in range(SITES):
            tm.zero_grad()
            out = tm(tx[s])
            loss = F.nll_loss(F.log_softmax(out, dim=1), ty[s])
            loss.backward()
            site_grads.append([p.grad.detach().clone() for p in tm.parameters()])
        # remote.py dSGD: example-weighted average (equal batches → mean)
        topt.zero_grad()
        for p, *gs in zip(tm.parameters(), *site_grads):
            p.grad = sum(gs) / len(gs)
        topt.step()

    jax_tree = jax.tree.map(np.asarray, state.params)
    torch_tree = _torch_params_as_tree(tm)
    flat_j = jax.tree_util.tree_leaves_with_path(jax_tree)
    flat_t = jax.tree.leaves(torch_tree)
    assert len(flat_j) == len(flat_t)
    for (path, a), b in zip(flat_j, flat_t):
        np.testing.assert_allclose(
            a, b, atol=2e-6,
            err_msg=f"param mismatch after {rounds} federated rounds at "
                    f"{jax.tree_util.keystr(path)}",
        )


@pytest.mark.slow
@needs_reference_models
def test_unequal_site_batches_weighted_average_matches_torch():
    """Heterogeneous site sizes (the 73-120 subject spread, SURVEY §7): the
    jax engine weights by example count; torch mirror must too."""
    rng = np.random.default_rng(1)
    b1, b2 = 6, 3  # site 1 pads to 6 with zero-weight rows
    x = rng.normal(size=(SITES, 1, b1, IN)).astype(np.float32)
    y = (rng.random((SITES, 1, b1)) > 0.5).astype(np.int64)
    w = np.ones((SITES, 1, b1), np.float32)
    w[1, 0, b2:] = 0.0  # mask the padding rows of the smaller site

    model = MSANNet(in_size=IN, hidden_sizes=HIDDEN, out_size=OUT)
    task = FederatedTask(model)
    engine = make_engine("dSGD")
    opt = make_optimizer("adam", LR)
    state = init_train_state(
        task, engine, opt, jax.random.PRNGKey(0), jnp.asarray(x[0, 0]),
        num_sites=SITES,
    )
    epoch_fn = make_train_epoch_fn(task, engine, opt, mesh=None, local_iterations=1)

    tm = _load_ref_msannet()
    _copy_params_to_torch(state.params, tm)
    topt = torch.optim.Adam(tm.parameters(), lr=LR)
    tm.train()

    state, _ = epoch_fn(state, jnp.asarray(x), jnp.asarray(y), jnp.asarray(w))

    counts = [b1, b2]
    site_grads = []
    for s, n in enumerate(counts):
        tm.zero_grad()
        out = tm(torch.tensor(x[s, 0, :n]))
        loss = F.nll_loss(F.log_softmax(out, dim=1), torch.tensor(y[s, 0, :n]))
        loss.backward()
        site_grads.append([p.grad.detach().clone() for p in tm.parameters()])
    topt.zero_grad()
    total = sum(counts)
    for p, *gs in zip(tm.parameters(), *site_grads):
        p.grad = sum(n * g for n, g in zip(counts, gs)) / total
    topt.step()

    jax_tree = jax.tree.map(np.asarray, state.params)
    torch_tree = _torch_params_as_tree(tm)
    for (path, a), b in zip(
        jax.tree_util.tree_leaves_with_path(jax_tree), jax.tree.leaves(torch_tree)
    ):
        np.testing.assert_allclose(
            a, b, atol=2e-6,
            err_msg=f"weighted-average mismatch at {jax.tree_util.keystr(path)}",
        )
