"""Test harness: simulate a multi-chip TPU mesh with 8 virtual CPU devices.

This is the TPU-build replacement for the reference's Docker-based COINSTAC
simulator (SURVEY.md §4): N local containers + 1 remote container on one machine
become N virtual jax devices on a "site" mesh axis.

Env vars must be set before jax initializes — hence module level, before any
jax import.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the shell may pin JAX_PLATFORMS=axon (real TPU)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# sitecustomize may have imported jax already (axon PJRT registration), so the
# env var alone is too late — set the config knob directly. The device-count
# knob only exists on newer jax (older versions honor the XLA_FLAGS env var
# set above instead), so tolerate its absence.
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass
jax.config.update("jax_enable_x64", False)
