"""Overlapped rounds (r14 — trainer/steps.py overlap_rounds).

The pipelined round applies round t's stashed payload while round t+1's
gradients compute. The contract under test:

- the very first round of a fit applies NOTHING (empty stash: params/opt
  hold, NaN loss, health/telemetry untouched);
- round t+1 then applies round t's payload EXACTLY as the legacy round
  would have (first applied update bit-equal to the legacy one-round fit);
- the stash rides TrainState across epoch boundaries (no round dropped)
  and through checkpoint/resume bit-exactly;
- liveness masks apply to the round the DATA came from;
- one compiled program (CompileGuard);
- overlap + buffered-async is rejected (two staleness semantics).

The off-form's program identity (overlap_rounds=False == legacy, bitwise)
is gated in tests/test_lowering_identity.py / S005.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dinunet_implementations_tpu.checks.semantic import (
    TraceCell,
    build_cell_inputs,
)
from dinunet_implementations_tpu.trainer.checkpoint import (
    load_checkpoint,
    save_checkpoint,
)
from dinunet_implementations_tpu.trainer.steps import (
    default_overlap_stash,
    init_train_state,
    make_train_epoch_fn,
)


@pytest.fixture(scope="module")
def corner():
    return build_cell_inputs(TraceCell("dSGD", "vmap", "host"))


def _leaves_equal(a, b):
    return all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


def test_first_round_applies_nothing_and_first_apply_is_legacy_bit_exact(
    corner,
):
    task, engine, opt, state, args, mesh = corner
    x, y, w = args[1:]
    legacy = make_train_epoch_fn(task, engine, opt, mesh=mesh)
    overlap = make_train_epoch_fn(task, engine, opt, mesh=mesh,
                                  overlap_rounds=True)
    s_ov, losses = overlap(state, x, y, w)
    losses = np.asarray(losses)
    # round 0: empty stash — NaN loss, params/opt untouched
    assert np.isnan(losses[0])
    # rounds 1..: each applies the previous round's payload; with a 2-round
    # epoch the final params equal the LEGACY params after exactly round 0
    # (bit-for-bit: same grads at the same initial params, same optimizer
    # step from the same initial moments)
    s_legacy1, l_legacy = legacy(state, x[:, :1], y[:, :1], w[:, :1])
    assert _leaves_equal(s_ov.params, s_legacy1.params)
    assert _leaves_equal(s_ov.opt_state, s_legacy1.opt_state)
    np.testing.assert_array_equal(losses[1], np.asarray(l_legacy)[0])
    # the stash now holds round 1's payload, valid everywhere
    np.testing.assert_array_equal(np.asarray(s_ov.overlap["valid"]), 1.0)


def test_stash_survives_epoch_boundary(corner):
    """Nothing is dropped at an epoch boundary: epoch 2's first round
    applies epoch 1's last stash (finite loss at step 0 of epoch 2)."""
    task, engine, opt, state, args, mesh = corner
    x, y, w = args[1:]
    fn = make_train_epoch_fn(task, engine, opt, mesh=mesh,
                             overlap_rounds=True)
    s1, l1 = fn(state, x, y, w)
    s2, l2 = fn(s1, x, y, w)
    assert np.isnan(np.asarray(l1)[0])
    assert np.isfinite(np.asarray(l2)).all()  # the carried stash applied


def test_overlap_checkpoint_roundtrip_bit_exact(corner, tmp_path):
    task, engine, opt, state, args, mesh = corner
    x, y, w = args[1:]
    fn = make_train_epoch_fn(task, engine, opt, mesh=mesh,
                             overlap_rounds=True)
    s1, _ = fn(state, x, y, w)
    path = str(tmp_path / "ov.msgpack")
    save_checkpoint(path, s1)
    like = init_train_state(
        task, engine, opt, jax.random.PRNGKey(0), x[0, 0],
        num_sites=x.shape[0], overlap_rounds=True,
    )
    restored = load_checkpoint(path, like)
    assert _leaves_equal(s1.overlap, restored.overlap)
    sa, la = fn(s1, x, y, w)
    sb, lb = fn(restored, x, y, w)
    assert _leaves_equal(sa, sb)
    np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_overlap_resumed_without_flag_drops_stash(corner, tmp_path):
    """An overlapped fit's checkpoint resumed with overlap OFF: the stash
    is dropped once (documented) and the legacy program runs."""
    task, engine, opt, state, args, mesh = corner
    x, y, w = args[1:]
    ov = make_train_epoch_fn(task, engine, opt, mesh=mesh,
                             overlap_rounds=True)
    legacy = make_train_epoch_fn(task, engine, opt, mesh=mesh)
    s1, _ = ov(state, x, y, w)
    s2, l2 = legacy(s1, x, y, w)
    assert s2.overlap is None
    assert np.isfinite(np.asarray(l2)).all()


def test_overlap_liveness_applies_to_the_data_round(corner):
    """A site dead in round 0 contributes nothing when round 0's stash
    applies (at step 1) — masking follows the data, not the apply step."""
    task, engine, opt, state, args, mesh = corner
    x, y, w = args[1:]
    S, rounds = x.shape[0], x.shape[1]
    fn = make_train_epoch_fn(task, engine, opt, mesh=mesh,
                             overlap_rounds=True)
    all_live = jnp.ones((S, rounds), jnp.float32)
    dead0 = all_live.at[:, 0].set(0.0)  # every site dead in ROUND 0
    s_live, l_live = fn(state, x, y, w, all_live)
    s_dead, l_dead = fn(state, x, y, w, dead0)
    # round 0's payload applies at step 1: all-dead round 0 → step-1 apply
    # holds params (and reports NaN), exactly like a legacy all-dead round
    assert np.isnan(np.asarray(l_dead)[1])
    assert np.isfinite(np.asarray(l_live)[1])
    assert _leaves_equal(s_dead.params, state.params)  # 2-round epoch:
    # round 1's payload is still in flight, round 0's was masked — nothing
    # has applied yet
    assert not _leaves_equal(s_live.params, state.params)


def test_overlap_health_not_counted_on_empty_stash(corner):
    """The valid gate: the empty-stash first round must not count a skip
    against every site."""
    task, engine, opt, state, args, mesh = corner
    x, y, w = args[1:]
    fn = make_train_epoch_fn(task, engine, opt, mesh=mesh,
                             overlap_rounds=True)
    s1, _ = fn(state, x, y, w)
    # 2 rounds ran; only round 1 (the first valid apply) touched health,
    # and with healthy data it recorded no skips
    np.testing.assert_array_equal(np.asarray(s1.health["skips"]), 0)
    np.testing.assert_array_equal(np.asarray(s1.health["quarantined"]), 0)


def test_overlap_packed_mesh_matches_vmap_trajectory():
    """The packed two-level form and the vmap fold run the same overlapped
    math (same data, same seeds → same loss trajectory)."""
    cell_v = TraceCell("dSGD", "vmap", "host")
    cell_m = TraceCell("dSGD", "mesh", "host")
    task_v, eng_v, opt_v, st_v, args_v, _ = build_cell_inputs(cell_v)
    task_m, eng_m, opt_m, st_m, args_m, mesh = build_cell_inputs(cell_m)
    fn_v = make_train_epoch_fn(task_v, eng_v, opt_v, mesh=None,
                               overlap_rounds=True)
    fn_m = make_train_epoch_fn(task_m, eng_m, opt_m, mesh=mesh,
                               overlap_rounds=True)
    _, l_v = fn_v(st_v, *args_v[1:])
    _, l_m = fn_m(st_m, *args_m[1:])
    np.testing.assert_allclose(
        np.asarray(l_v), np.asarray(l_m), rtol=1e-5
    )


def test_overlap_epoch_compiles_once(corner):
    """Chained overlapped epochs are ONE compiled program — provided the
    initial state carries the stash (init_train_state(overlap_rounds=True),
    what the trainer does; a stash-less state costs one structural warmup
    compile by design, same as resuming a telemetry run)."""
    from dinunet_implementations_tpu.checks.sanitize import jit_cache_size

    task, engine, opt, state, args, mesh = corner
    x, y, w = args[1:]
    fn = make_train_epoch_fn(task, engine, opt, mesh=mesh,
                             overlap_rounds=True)
    s = init_train_state(
        task, engine, opt, jax.random.PRNGKey(0), x[0, 0],
        num_sites=x.shape[0], overlap_rounds=True,
    )
    for _ in range(3):
        s, _ = fn(s, x, y, w)
    jax.tree.map(np.asarray, s)
    assert jit_cache_size(fn) == 1


def test_overlap_rejects_buffered_async(corner):
    task, engine, opt, *_ = corner
    with pytest.raises(ValueError, match="mutually exclusive"):
        make_train_epoch_fn(task, engine, opt, overlap_rounds=True,
                            staleness_bound=2)


def test_default_overlap_stash_structure():
    params = {"w": jnp.ones((3, 2))}
    stats = {"bn": {"mean": jnp.zeros((2,))}}
    ov = default_overlap_stash(4, params, stats)
    assert ov["grads"]["w"].shape == (4, 3, 2)
    assert ov["stats"]["bn"]["mean"].shape == (4, 2)
    for k in ("weight", "loss", "live", "valid"):
        assert ov[k].shape == (4,)
    np.testing.assert_array_equal(np.asarray(ov["valid"]), 0.0)
