from .config import (
    AggEngine,
    FSArgs,
    ICAArgs,
    MultimodalArgs,
    NNComputation,
    PretrainArgs,
    SMRI3DArgs,
    TrainConfig,
    export_compspec,
    load_inputspec,
    resolve_site_configs,
)
