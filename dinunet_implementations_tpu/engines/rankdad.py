"""rankDAD — distributed-AD low-rank gradient compression.

Reference capability (``comps/__init__.py:15``; knobs
``compspec.json:236-238``; measured run ``nnlogs.ipynb`` cell 2): each site
compresses its per-layer gradient to rank-r factors via power iteration and
ships factors instead of full gradients; the aggregate is the weighted mean of
the sites' rank-r reconstructions.

TPU shape of the exchange (SURVEY.md §2.2): ``all_gather`` of the
``[m, r]``/``[n, r]`` factors over the ``site`` axis — comm volume
``r·(m+n)`` per site instead of ``m·n`` — followed by one batched einsum
reconstruction, which XLA maps straight onto the MXU. 1-D leaves (biases, BN
scales) are aggregated densely like dSGD.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..parallel.collectives import payload_dtype, site_all_gather, site_weight_scale
from .base import Engine, register_engine
from .lowrank import (
    from_matrix,
    is_compressible,
    subspace_iteration_multi,
    to_matrix,
)


@register_engine("rankDAD")
def make_rankdad(
    dad_reduction_rank: int = 10,
    dad_num_pow_iters: int = 5,
    dad_tol: float = 1e-3,
    precision_bits="32",
    **_unused,
) -> Engine:
    pdtype = payload_dtype(precision_bits)

    def init(grads):
        return {}

    def aggregate(grads, state, weight, axis_name):
        scale = site_weight_scale(weight, axis_name)

        def reconstruct(g, P, Q):
            # weight one factor so the gathered reconstruction sums to the
            # weighted mean; cast payload like the reference's precision_bits
            P_pay = P.astype(pdtype)
            Q_pay = (Q * scale).astype(pdtype)
            P_all = site_all_gather(P_pay, axis_name)  # [S, m, r]
            Q_all = site_all_gather(Q_pay, axis_name)  # [S, n, r]
            G_hat = jnp.einsum(
                "smr,snr->mn",
                P_all.astype(jnp.float32),
                Q_all.astype(jnp.float32),
            )
            return from_matrix(G_hat, g)

        leaves, treedef = jax.tree.flatten(grads)
        out: list = [None] * len(leaves)
        # layers sharing an effective rank factorize in LOCKSTEP so the tiny
        # [r, r] Cholesky custom-calls batch across the group (engine
        # wall-clock was dominated by issuing them per layer per iteration —
        # see lowrank._cholqr_once_multi)
        groups: dict[int, list[int]] = {}
        for i, g in enumerate(leaves):
            if is_compressible(g):
                m, n = to_matrix(g).shape
                groups.setdefault(min(dad_reduction_rank, m, n), []).append(i)
            else:
                # dense dSGD path for 1-D leaves (biases, BN affines)
                out[i] = jax.lax.psum(
                    g.astype(jnp.float32) * scale, axis_name
                ).astype(g.dtype)
        for r, idxs in groups.items():
            pqs = subspace_iteration_multi(
                [to_matrix(leaves[i]) for i in idxs],
                r, dad_num_pow_iters, dad_tol,
            )
            for i, (P, Q) in zip(idxs, pqs):
                out[i] = reconstruct(leaves[i], P, Q)
        return jax.tree.unflatten(treedef, out), state

    return Engine("rankDAD", init, aggregate)
