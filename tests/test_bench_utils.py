"""The bench measurement utilities (bench.py) — the estimator math must be
right, because every recorded throughput number flows through it."""

import importlib.util
import os


def _bench():
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(__file__), "..", "bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_least_contended_marginal_recovers_truth_under_contention():
    """Synthetic chains: T(k) = k·c + fetch + contention-noise. The estimator
    must recover c when at least one run per endpoint is uncontended."""
    bench = _bench()
    c, fetch = 0.010, 4.5
    # deterministic "contention" schedule: some runs get hit, some don't
    hits = iter([3.0, 0.0, 1.2, 0.0, 2.0, 0.4])

    def run_chain(k):
        return k * c + fetch + next(hits)

    dt = bench.least_contended_marginal(run_chain, n=100, repeats=3)
    assert abs(dt - c) < 1e-9, dt


def test_least_contended_marginal_uses_pre_full_sample():
    bench = _bench()
    c, fetch = 0.010, 4.5
    # every fresh full-chain run is contended; only the pre-observed one is clean
    def run_chain(k):
        return k * c + fetch + (0.0 if k < 60 else 5.0)

    clean_full = 101 * c + fetch
    dt = bench.least_contended_marginal(run_chain, n=100, repeats=2,
                                        pre_full=clean_full)
    assert abs(dt - c) < 1e-9, dt


def test_least_contended_marginal_floor_guards_nonpositive():
    bench = _bench()
    # pathological: full chain faster than half chain → clamped, not negative
    times = {51: 10.0, 101: 9.0}
    dt = bench.least_contended_marginal(lambda k: times[k], n=100, repeats=1)
    assert dt == 1e-9


def test_flops_per_sample_matches_hand_count():
    """The MFU denominator, pinned against an INDEPENDENT hand count (not
    the module's own formula) for the flagship dims: 98 windows, encoder
    1000→256, biLSTM H=174/direction, head 348→256→64→2, train = 3× fwd.

    enc  = 98·1000·256·2                         =  50,176,000
    lstm = 98·2dirs·(256·(4·174) + 174·(4·174))·2 = 117,317,760
    head = 348·256·2 + 256·64·2 + 64·2·2          =     211,200
    """
    bench = _bench()
    assert bench.flops_per_sample() == 3.0 * (50_176_000 + 117_317_760 + 211_200)


def test_compile_epoch_aot_matches_epoch_fn():
    """AOT + AUTO input layout is a pure perf knob: same math, same outputs."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dinunet_implementations_tpu.engines import make_engine
    from dinunet_implementations_tpu.models import MSANNet
    from dinunet_implementations_tpu.trainer import (
        FederatedTask,
        compile_epoch_aot,
        init_train_state,
        make_optimizer,
        make_train_epoch_fn,
    )

    model = MSANNet(in_size=6, hidden_sizes=(8,), out_size=2)
    task = FederatedTask(model)
    engine = make_engine("dSGD")
    opt = make_optimizer("adam", 1e-2)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(3, 2, 4, 6)).astype(np.float32))
    y = jnp.asarray((rng.random((3, 2, 4)) > 0.5).astype(np.int32))
    w = jnp.ones((3, 2, 4), jnp.float32)
    state0 = init_train_state(task, engine, opt, jax.random.PRNGKey(0), x[0, 0],
                              num_sites=3)
    epoch_fn = make_train_epoch_fn(task, engine, opt, mesh=None)
    ref_state, ref_losses = epoch_fn(state0, x, y, w)
    comp, put_x = compile_epoch_aot(epoch_fn, state0, x, y, w)
    aot_state, aot_losses = comp(state0, put_x(x), y, w)
    np.testing.assert_allclose(np.asarray(aot_losses), np.asarray(ref_losses),
                               rtol=1e-6)
    for a, b in zip(jax.tree.leaves(ref_state.params),
                    jax.tree.leaves(aot_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
