"""jaxlint — codebase-specific SPMD-invariant analysis + runtime sanitizer.

The last two PRs each shipped fixes for bug classes that are mechanically
detectable (the fold-crossing ``self.cfg`` mutation; blanket handlers that
would have swallowed ``Preempted``). This package checks those invariants
up front instead of re-discovering them per PR:

Static rules (``python -m dinunet_implementations_tpu.checks``):

- **R001** no ``print()`` outside the CLI/demo/report allowlist — library
  output goes through the level-gated logger in ``trainer/logs.py``;
- **R002** no bare ``except:`` / ``except BaseException:`` anywhere (the
  ``Preempted`` shutdown contract), and no silently-swallowing
  ``except Exception`` inside ``robustness/``, ``trainer/``, ``runner/``;
- **R003** collective axis names resolve to the ``parallel/mesh.py``
  constants (``SITE_AXIS``/``MODEL_AXIS``/``FOLD_AXIS``), never ad-hoc
  string literals;
- **R004** no mutation of ``cfg``/``self.cfg`` fields outside
  ``core/config.py`` — TrainConfig is shared across folds;
- **R005** no tracer-escaping casts (``float``/``int``/``np.asarray``/
  ``.item()``) inside jit-traced code (engines, models, ops, collectives,
  the step builders, and any ``@jax.jit`` function);
- **R006** ``TrainState`` fields round-trip through the checkpoint
  serializer's key set (schema-drift guard).

Findings support inline ``# jaxlint: disable=Rxxx`` suppression and a
checked-in baseline (``checks/baseline.json``, shipped empty). The analyzer
half is stdlib-only; the runtime sanitizer (``sanitize.py``,
``DINUNET_SANITIZE=1``) adds a compile-counter guard, leak checking, and
debug-NaN mode around real fits.
"""

from .core import (
    DEFAULT_BASELINE,
    PACKAGE_ROOT,
    Finding,
    apply_baseline,
    load_baseline,
    run_checks,
    save_baseline,
)
from .sanitize import (
    CompileGuard,
    SanitizerViolation,
    jit_cache_size,
    sanitize_enabled,
    sanitize_flags,
    sanitized_fit,
)

__all__ = [
    "CompileGuard",
    "DEFAULT_BASELINE",
    "Finding",
    "PACKAGE_ROOT",
    "SanitizerViolation",
    "apply_baseline",
    "jit_cache_size",
    "load_baseline",
    "run_checks",
    "sanitize_enabled",
    "sanitize_flags",
    "sanitized_fit",
    "save_baseline",
]
