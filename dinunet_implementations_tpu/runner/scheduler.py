"""Fleet scheduler — one pod, many tenants (r22).

A TPU pod running ONE study at a time is mostly idle: quorum holds, spool
gaps between cohorts, and admission waits all leave slices parked while
the daemon polls. This module packs multiple concurrent studies
(*tenants*) plus serving lanes onto the shared slice pool, so the pod's
slice-seconds go to whoever can use them — without ever violating the
repo's one-compile-per-fit law.

Design (in the order the pieces compose):

- **Tenant model.** Each tenant is a :class:`TenantSpec` — a
  FedDaemon-shaped fit (config + data tree + capacity/quorum) plus
  scheduling attributes (priority band, weight, slice quota). Tenants
  arrive through the scheduler's own JSON-event spool (``register`` /
  ``deregister`` / ``shutdown``, same sorted-filename / remove-on-apply /
  ``.rejected``-quarantine discipline as the membership spool) or via
  :meth:`FleetScheduler.register`. Every tenant gets its OWN spool,
  checkpoint dir, telemetry sink (manifest-tagged ``{"tenant": id}``) and
  ε ledger under ``<root>/tenants/<id>/`` — isolation is directory-deep,
  not best-effort — while live metrics publish through a
  :class:`~..telemetry.bus.LabeledBusView` of the ONE pod bus, so a
  single /statusz exporter serves the whole pod with every series
  tenant-labeled.

- **Fair share.** :func:`fair_share` allocates integer slices in strictly
  descending priority bands; within a band, weighted max-min — one slice
  at a time to the least-served-per-unit-weight tenant, deterministic
  tiebreak by tenant id. Capped by each tenant's quota and demand
  (a holding tenant demands 0 — granting slices to a fit that would only
  hold wastes them). Leftover slices fall through to backfill.

- **Preempt-and-yield.** A grant shrink is checkpoint-then-yield: the
  tenant's daemon saves its rotating checkpoint (exit-clean, the same
  artifact SIGTERM preemption writes), then the scheduler flips the
  tenant's ``[num_slices]`` slice-grant mask — which folds into the r19
  slice-liveness window INSIDE the already-compiled epoch program, so
  shrinking 4→2 slices is a traced-input flip plus renormalized
  aggregation, never a retrace. Resume is the mirror: reload the
  checkpoint through the real CRC-framed msgpack path into the same
  state template, regrant the mask. A CompileGuard per tenant asserts
  ONE epoch compile across any grow/shrink/preempt/restore sequence,
  and the resumed tenant continues bit-exact (params-digest-provable,
  tests/test_scheduler.py).

- **Backfill.** Slices no tenant can use this tick (quorum holds, empty
  pool tail, grants below a tenant's slice-quorum floor) host a
  :class:`BackfillLane` — a serving ReplicaSet (r21) pinned to the idle
  band's devices, lazily warmed on first grant and drained through the
  same yield discipline (a lane never blocks a training grant: it only
  ever runs on this tick's leftover).

- **Goodput accounting.** The scheduler integrates busy-slice-seconds
  over wall time and keeps every preemption pause; ``bench.py
  --tenants N`` uses these to prove scheduled-concurrent packing beats
  serialized studies on aggregate throughput (docs/bench_tenants_r22
  .jsonl).

The scheduler never spawns threads for training: one process, one tick
loop, tenants time-multiplexed deterministically (priority-desc, then
tenant id) — so runs are reproducible and the one-compile law is
checkable per tenant.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time

import numpy as np

from ..core.config import TrainConfig
from .fed_runner import FedDaemon

#: event kinds the scheduler spool accepts
SCHED_SPOOL_EVENTS = ("register", "deregister", "shutdown")

#: append-only grant-decision log under the scheduler root — postmortem
#: input (telemetry/postmortem.py reads the same name)
GRANTS_FILE = "grants.jsonl"


class SchedulerError(ValueError):
    """A tenant spec or scheduler-spool event that cannot be honored."""


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One study's admission ticket: the fit shape plus how it shares.

    ``config`` is either a flat override dict (the spool-event form,
    applied via ``TrainConfig.with_overrides`` exactly like a join
    event's ``config`` key) or a prebuilt :class:`TrainConfig` (the API
    form tests and benches use). ``slice_quota`` caps how many pod
    slices the tenant may hold at once (default: its own mesh width);
    ``priority`` picks the band (higher preempts lower), ``weight`` the
    share within the band. ``max_epochs`` ends the study (``None`` =
    runs until a ``deregister``/``shutdown`` event).
    """

    tenant: str
    data_path: str | None = None
    config: object = None  # flat override dict | TrainConfig | None
    capacity: int = 4
    quorum: int = 1
    priority: float = 1.0
    weight: float = 1.0
    slice_quota: int | None = None
    max_epochs: int | None = None
    inventory_rows: int | None = None
    steps: int | None = None
    resume: bool = False
    fault_plan: object = None
    attack_plan: object = None

    @classmethod
    def from_event(cls, ev: dict) -> "TenantSpec":
        """Build a spec from a scheduler-spool ``register`` event.
        Fault/attack plans arrive in the same JSON forms the CLI accepts
        (``robustness.faults.parse_fault_plan`` / ``parse_attack_plan``).
        """
        from ..robustness.attacks import parse_attack_plan
        from ..robustness.faults import parse_fault_plan

        tenant = str(ev.get("tenant") or "")
        if not tenant or "/" in tenant or tenant.startswith("."):
            raise SchedulerError(f"bad tenant id {tenant!r}")
        faults = ev.get("faults")
        attacks = ev.get("attacks")
        return cls(
            tenant=tenant,
            data_path=ev.get("data_path"),
            config=ev.get("config") or {},
            capacity=int(ev.get("capacity", 4)),
            quorum=int(ev.get("quorum", 1)),
            priority=float(ev.get("priority", 1.0)),
            weight=float(ev.get("weight", 1.0)),
            slice_quota=(
                None if ev.get("slice_quota") is None
                else int(ev["slice_quota"])
            ),
            max_epochs=(
                None if ev.get("max_epochs") is None
                else int(ev["max_epochs"])
            ),
            inventory_rows=(
                None if ev.get("inventory_rows") is None
                else int(ev["inventory_rows"])
            ),
            steps=None if ev.get("steps") is None else int(ev["steps"]),
            resume=bool(ev.get("resume", False)),
            fault_plan=(
                parse_fault_plan(json.dumps(faults)) if faults else None
            ),
            attack_plan=(
                parse_attack_plan(json.dumps(attacks)) if attacks else None
            ),
        )


def fair_share(pool: int, requests: list[dict]) -> dict[str, int]:
    """Integer slice allocation: strictly descending priority bands,
    weighted max-min inside a band.

    ``requests`` rows carry ``tenant`` (id), ``priority``, ``weight`` and
    ``demand`` (max useful slices — 0 when the tenant would only hold).
    Within a band, slices go one at a time to the tenant with the lowest
    grants-per-unit-weight (deterministic tiebreak: tenant id), stopping
    at each tenant's demand. A higher band drains the pool before a
    lower band sees it — that asymmetry IS preemption: when a high-
    priority tenant arrives, the reallocation shrinks the lower band's
    grants and the scheduler turns each shrink into checkpoint-then-
    yield. Whatever no band can use is the backfill residue.
    """
    grants = {str(r["tenant"]): 0 for r in requests}
    remaining = int(pool)
    for prio in sorted({float(r["priority"]) for r in requests},
                       reverse=True):
        band = [
            r for r in requests
            if float(r["priority"]) == prio and int(r["demand"]) > 0
        ]
        while remaining > 0:
            open_ = [
                r for r in band
                if grants[str(r["tenant"])] < int(r["demand"])
            ]
            if not open_:
                break
            pick = min(
                open_,
                key=lambda r: (
                    grants[str(r["tenant"])]
                    / max(float(r.get("weight", 1.0)), 1e-9),
                    str(r["tenant"]),
                ),
            )
            grants[str(pick["tenant"])] += 1
            remaining -= 1
    return grants


class Tenant:
    """One scheduled study: a FedDaemon plus its scheduling state.

    The daemon is built with a per-tenant spool/output/telemetry tree
    under ``<root>/tenants/<id>/`` and a :class:`LabeledBusView` of the
    pod bus (every series it publishes carries ``tenant="<id>"``; the
    fixed label wins, so a tenant cannot publish under another's name).
    The slice-grant mask is installed as all-zeros BEFORE the first
    epoch, so the very first compile already takes the mask as a traced
    input — every later grant flip stays inside that one program
    (per-tenant CompileGuard, checked at close).
    """

    def __init__(self, spec: TenantSpec, root: str, bus,
                 verbose: bool = False):
        from ..checks.sanitize import CompileGuard
        from ..telemetry.bus import LabeledBusView

        self.spec = spec
        base = os.path.join(root, "tenants", spec.tenant)
        self.spool_dir = os.path.join(base, "spool")
        self.out_dir = os.path.join(base, "output")
        self.bus = LabeledBusView(bus, tenant=spec.tenant)
        if isinstance(spec.config, TrainConfig):
            cfg, overrides = spec.config, {}
        else:
            cfg, overrides = None, dict(spec.config or {})
        self.daemon = FedDaemon(
            cfg,
            capacity=spec.capacity,
            spool_dir=self.spool_dir,
            out_dir=self.out_dir,
            data_path=spec.data_path,
            quorum=spec.quorum,
            poll_s=0.0,
            fault_plan=spec.fault_plan,
            attack_plan=spec.attack_plan,
            inventory_rows=spec.inventory_rows,
            steps=spec.steps,
            resume=spec.resume,
            verbose=verbose,
            bus=self.bus,
            sink_tags={"tenant": spec.tenant},
            **overrides,
        )
        # the mask must exist from the FIRST trace (None↔mask flips
        # change the traced program; zeros↔ones flips do not)
        self.daemon.set_slice_grant(
            np.zeros(self.daemon.num_slices, np.float32)
        )
        self.guard = CompileGuard(
            {"epoch_fn": self.daemon.trainer.epoch_fn},
            max_compiles=1, label=f"tenant:{spec.tenant}",
        )
        self.granted = 0
        self.status = "active"  # active | done | stopped
        self.preempted = False
        self.preempt_count = 0
        self.pauses_ms: list[float] = []
        self.busy_slice_s = 0.0  # granted×trained integral (fairness)

    # -- scheduling predicates --------------------------------------------

    @property
    def num_slices(self) -> int:
        return self.daemon.num_slices

    @property
    def quota(self) -> int:
        q = self.spec.slice_quota
        return self.num_slices if q is None else max(int(q), 0)

    @property
    def finished(self) -> bool:
        return (
            self.spec.max_epochs is not None
            and self.daemon.epochs_run >= self.spec.max_epochs
        )

    def runnable(self) -> bool:
        return (
            self.status == "active"
            and not self.finished
            and self.daemon.trainable()
        )

    def demand(self) -> int:
        """Max USEFUL slices this tick: 0 while the fit would hold
        (below quorum / no trainable batch), else quota ∧ mesh width.
        An unsliced tenant demands one pod slice (time-multiplexing)."""
        if not self.runnable():
            return 0
        return max(min(self.quota, self.num_slices), 1)

    # -- spool / membership ------------------------------------------------

    def pump_spool(self) -> bool:
        """Drain the tenant's OWN membership spool (joins/leaves/shutdown
        — the churn surface is unchanged under scheduling)."""
        changed = self.daemon.ingest()
        if changed:
            self.daemon._on_membership_change()
        if self.daemon._stop and self.status == "active":
            self.status = "stopped"
        return changed

    # -- the yield protocol ------------------------------------------------

    def apply_grant(self, n: int) -> float:
        """Move this tenant to ``n`` granted slices; returns the pause in
        ms (0.0 when nothing changed).

        Shrink (``n < granted``) is checkpoint-THEN-yield: the rotating
        checkpoint is written first (exit-clean — the same artifact the
        SIGTERM path saves), then the mask drops. A shrink to zero marks
        the tenant preempted. Grow out of preemption reloads that
        checkpoint through the real msgpack path into the existing state
        template before the mask rises — the resumed trajectory is
        bit-exact with a never-preempted run (proven in
        tests/test_scheduler.py), and neither direction retraces.
        """
        n = max(int(n), 0)
        if n == self.granted:
            return 0.0
        t0 = time.perf_counter()
        phase = "yield" if n < self.granted else "resume"
        if n < self.granted:
            self.daemon.checkpoint()
            if n == 0 and self.status == "active" \
                    and self.daemon.state is not None:
                self.preempted = True
                self.preempt_count += 1
        elif self.granted == 0 and self.preempted:
            self.daemon.reload_checkpoint()
            self.preempted = False
        mask = np.zeros(self.num_slices, np.float32)
        mask[:min(n, self.num_slices)] = 1.0
        self.daemon.set_slice_grant(mask)
        self.granted = n
        pause_ms = (time.perf_counter() - t0) * 1e3
        self.pauses_ms.append(pause_ms)
        self.bus.observe("sched_preempt_pause_ms", pause_ms, phase=phase)
        return pause_ms

    # -- training / lifecycle ----------------------------------------------

    def train_epoch(self):
        return self.daemon.train_epoch()

    def params_digest(self):
        from ..trainer.checkpoint import params_digest

        if self.daemon.state is None:
            return None
        return params_digest(
            self.daemon.state.params,
            getattr(self.daemon.state, "batch_stats", None),
        )

    def status_view(self) -> dict:
        return {
            "tenant": self.spec.tenant,
            "status": self.status,
            "priority": self.spec.priority,
            "weight": self.spec.weight,
            "quota": self.quota,
            "granted": self.granted,
            "preempted": self.preempted,
            "preempt_count": self.preempt_count,
            "epochs_run": self.daemon.epochs_run,
            "held_rounds": self.daemon.held_rounds,
            "trainable": self.daemon.trainable(),
            "daemon": self.daemon.status(),
        }

    def close(self) -> dict:
        summary = self.daemon.close()
        summary["tenant"] = self.spec.tenant
        summary["preempt_count"] = self.preempt_count
        # the one-compile law, per tenant, across every grant flip
        summary["epoch_compiles"] = self.guard.check(
            f"tenant {self.spec.tenant!r} close "
            f"(preemptions={self.preempt_count})"
        ).get("epoch_fn", 0)
        return summary


class BackfillLane:
    """A serving lane that soaks up the tick's leftover slices.

    Wraps an r21 :class:`~..serving.fleet.ReplicaSet`, built lazily on
    the FIRST grant (AOT warmup is the lane's one-time admission cost)
    and pinned to the idle band's devices. Each ``run_quantum`` submits a
    bounded burst from ``feed`` — the lane never owns the pod, it rents
    this tick's residue, and draining it is just not granting the next
    quantum (the ReplicaSet keeps no training state to checkpoint).
    """

    def __init__(self, cfg: TrainConfig, feed, *, params=None,
                 batch_stats=None, checkpoint: str | None = None,
                 replicas: int = 1, requests_per_quantum: int = 4,
                 name: str = "backfill", engine_kwargs: dict | None = None):
        if feed is None:
            raise SchedulerError(
                "BackfillLane needs a feed() callable returning one "
                "request's rows"
            )
        self.cfg = cfg
        self.feed = feed
        self.params = params
        self.batch_stats = batch_stats
        self.checkpoint = checkpoint
        self.replicas = int(replicas)
        self.requests_per_quantum = int(requests_per_quantum)
        self.name = name
        self.engine_kwargs = dict(engine_kwargs or {})
        self.requests_served = 0
        self.samples_served = 0
        self.quanta = 0
        self._set = None

    def _ensure(self, bus, devices) -> None:
        if self._set is not None:
            return
        from ..serving.fleet import ReplicaSet
        from ..telemetry.bus import LabeledBusView

        self._set = ReplicaSet(
            self.cfg, replicas=self.replicas, params=self.params,
            batch_stats=self.batch_stats, checkpoint=self.checkpoint,
            bus=LabeledBusView(bus, lane=self.name) if bus is not None
            else None,
            devices=list(devices) if devices else None,
            **self.engine_kwargs,
        )
        self._set.warmup()

    def run_quantum(self, bus=None, devices=None) -> dict:
        """One bounded serving burst on the granted band; returns
        ``{"requests": n, "samples": m}``."""
        self._ensure(bus, devices)
        bursts = []
        for _ in range(self.requests_per_quantum):
            rows = self.feed()
            bursts.append((self._set.submit(rows), len(rows)))
        requests = samples = 0
        for fut, n in bursts:
            fut.result()
            requests += 1
            samples += n
        self.requests_served += requests
        self.samples_served += samples
        self.quanta += 1
        return {"requests": requests, "samples": samples}

    def status(self) -> dict:
        return {
            "name": self.name,
            "started": self._set is not None,
            "replicas": self.replicas,
            "requests_served": self.requests_served,
            "samples_served": self.samples_served,
            "quanta": self.quanta,
            "fleet": None if self._set is None else self._set.status(),
        }

    def close(self) -> dict:
        out = {
            "name": self.name,
            "requests_served": self.requests_served,
            "samples_served": self.samples_served,
            "quanta": self.quanta,
        }
        if self._set is not None:
            self._set.assert_no_compiles()
            out["fleet"] = self._set.close()
            self._set = None
        return out


class FleetScheduler:
    """The pod-level tick loop: drain spools, allocate, yield/resume,
    train one epoch per granted tenant, backfill the residue, account.

    ``pod_slices`` is the shared pool's width in slices (on the CPU
    emulation: virtual-device bands). The scheduler is single-threaded
    and deterministic — tenants train in (priority desc, tenant id)
    order — so a run is reproducible and each tenant's one-compile
    guard is meaningful. Goodput integrals (busy-slice-seconds over
    wall) and every preemption pause are kept for ``bench.py
    --tenants``; live gauges publish tenant-labeled into the ONE pod
    bus for the single /statusz exporter.
    """

    def __init__(self, root: str, pod_slices: int = 1, bus=None,
                 poll_s: float = 0.05, verbose: bool = True,
                 backfill: BackfillLane | None = None):
        from ..telemetry.bus import global_bus

        if pod_slices < 1:
            raise SchedulerError(
                f"pod_slices must be >= 1, got {pod_slices}"
            )
        self.root = root
        self.spool_dir = os.path.join(root, "spool")
        os.makedirs(self.spool_dir, exist_ok=True)
        self.pod_slices = int(pod_slices)
        self.bus = bus if bus is not None else global_bus()
        self.poll_s = poll_s
        self.verbose = verbose
        self.backfill = backfill
        self.tenants: dict[str, Tenant] = {}
        self._stop = False
        self._preempted = False
        self._last_grants: dict | None = None
        self.ticks = 0
        self._wall_s = 0.0
        self._busy_slice_s = 0.0
        # per-slice device bands (emulated pod): backfill pins to the
        # TAIL band — fair_share packs tenants from the front, so the
        # residue lives at the tail by construction
        import jax

        devs = jax.devices()
        k = max(len(devs) // self.pod_slices, 1)
        self._slice_devices = [
            devs[i * k:(i + 1) * k] for i in range(self.pod_slices)
        ]
        self.bus.gauge("sched_pod_slices", self.pod_slices)

    def _log(self, msg: str) -> None:
        if self.verbose:
            from ..trainer.logs import log_info

            log_info(msg)

    # -- tenant admission --------------------------------------------------

    def register(self, spec: TenantSpec) -> Tenant:
        if spec.tenant in self.tenants:
            raise SchedulerError(
                f"tenant {spec.tenant!r} already registered"
            )
        t = Tenant(spec, self.root, self.bus, verbose=self.verbose)
        self.tenants[spec.tenant] = t
        self.bus.counter("sched_events_total", kind="register")
        self.bus.gauge("sched_tenants", len(self.tenants))
        self._log(
            f"[sched] register tenant {spec.tenant!r} "
            f"(priority {spec.priority}, quota {t.quota}, "
            f"mesh slices {t.num_slices})"
        )
        return t

    def deregister(self, tenant: str) -> None:
        t = self.tenants.get(tenant)
        if t is None or t.status != "active":
            return
        t.status = "stopped"  # before the grant drop (not a preemption)
        t.apply_grant(0)
        self.bus.counter("sched_events_total", kind="deregister")
        self._log(f"[sched] deregister tenant {tenant!r}")

    def ingest(self) -> bool:
        """Drain the scheduler spool (sorted-filename order, remove on
        apply, ``.rejected`` quarantine for malformed files) — the same
        event discipline the membership spool taught operators."""
        from ..trainer.logs import log_warning

        changed = False
        for name in sorted(os.listdir(self.spool_dir)):
            if not name.endswith(".json"):
                continue
            path = os.path.join(self.spool_dir, name)
            try:
                with open(path) as fh:
                    ev = json.load(fh)
            except (OSError, json.JSONDecodeError) as e:
                log_warning(f"[sched] unreadable spool file {path}: {e}")
                try:
                    os.replace(path, path + ".rejected")
                except OSError:
                    pass
                continue
            os.remove(path)
            if not isinstance(ev, dict):
                log_warning(f"[sched] spool file {path} is not an object")
                continue
            kind = ev.get("event")
            try:
                if kind == "register":
                    self.register(TenantSpec.from_event(ev))
                    changed = True
                elif kind == "deregister":
                    self.deregister(str(ev.get("tenant") or ""))
                    changed = True
                elif kind == "shutdown":
                    self._stop = True
                    self._log("[sched] shutdown event received")
                    break
                else:
                    log_warning(
                        f"[sched] unknown spool event {ev!r} — ignored"
                    )
                    self.bus.counter("sched_events_total", kind="rejected")
            except (SchedulerError, ValueError, TypeError) as e:
                log_warning(f"[sched] bad spool event {ev!r}: {e}")
                self.bus.counter("sched_events_total", kind="rejected")
        return changed

    def _log_grants(self, grants: dict, preempt_pause_ms: float) -> None:
        """Append one grant decision to ``<root>/grants.jsonl`` — the
        postmortem plane (telemetry/postmortem.py) replays this log to
        show who held the pod around an incident. Written only when the
        allocation CHANGES, so the log is a decision history, not a
        per-tick heartbeat."""
        try:
            with open(os.path.join(self.root, GRANTS_FILE), "a") as fh:
                fh.write(json.dumps({
                    "time_unix": time.time(),
                    "tick": self.ticks,
                    "grants": grants,
                    "preempt_pause_ms": round(preempt_pause_ms, 3),
                }) + "\n")
        except OSError:
            pass  # a full disk must not take the scheduler down

    # -- the tick ----------------------------------------------------------

    def _order(self) -> list[Tenant]:
        return sorted(
            self.tenants.values(),
            key=lambda t: (-t.spec.priority, t.spec.tenant),
        )

    def tick(self, sleep_when_idle: bool = True) -> dict:
        """One scheduling round: spools → allocation → shrink-before-grow
        → one epoch per granted tenant → backfill the residue → account.

        Shrink-before-grow matters: a freed slice must exist before it
        is granted elsewhere, so every yield (with its checkpoint) lands
        before any resume (with its reload) — the pool is never
        oversubscribed mid-tick.
        """
        t0 = time.perf_counter()
        changed = self.ingest()
        for t in self._order():
            changed |= t.pump_spool()
            if t.finished and t.status == "active":
                t.status = "done"  # before the grant drop: a natural
                t.apply_grant(0)   # finish is not a preemption
                self._log(
                    f"[sched] tenant {t.spec.tenant!r} done "
                    f"({t.daemon.epochs_run} epochs)"
                )
                changed = True
        requests = [
            {
                "tenant": t.spec.tenant,
                "priority": t.spec.priority,
                "weight": t.spec.weight,
                "demand": t.demand(),
            }
            for t in self._order()
        ]
        grants = fair_share(self.pod_slices, requests)
        # a grant below the tenant's slice-quorum floor would only buy
        # held rounds inside its compiled program — return it to the
        # residue instead
        for t in self._order():
            g = grants.get(t.spec.tenant, 0)
            if 0 < g < int(getattr(t.daemon.cfg, "min_slices", 1) or 1):
                grants[t.spec.tenant] = 0
        preempt_pause_ms = 0.0
        for t in self._order():  # shrinks first: free before granting
            g = grants.get(t.spec.tenant, 0)
            if g < t.granted:
                preempt_pause_ms += t.apply_grant(g)
        for t in self._order():
            g = grants.get(t.spec.tenant, 0)
            if g > t.granted:
                preempt_pause_ms += t.apply_grant(g)
        if grants != self._last_grants:
            self._log_grants(grants, preempt_pause_ms)
            self._last_grants = dict(grants)
        trained = 0
        busy = 0
        trained_tenants = []
        for t in self._order():
            if t.granted > 0 and t.status == "active":
                loss = t.train_epoch()
                if loss is not None:
                    trained += 1
                    busy += t.granted
                    trained_tenants.append(t)
        leftover = self.pod_slices - sum(
            t.granted for t in self.tenants.values()
        )
        served = {"requests": 0, "samples": 0}
        if self.backfill is not None and leftover > 0:
            served = self.backfill.run_quantum(
                bus=self.bus, devices=self._slice_devices[-1],
            )
            if served["requests"]:
                busy += leftover
        dt = time.perf_counter() - t0
        idle_tick = (
            trained == 0 and not served["requests"] and not changed
        )
        if sleep_when_idle and idle_tick and not self._stop:
            time.sleep(self.poll_s)
            dt += self.poll_s
        self._wall_s += dt
        self._busy_slice_s += min(busy, self.pod_slices) * dt
        for t in trained_tenants:  # fairness ledger: who GOT the pod
            t.busy_slice_s += t.granted * dt
        self.ticks += 1
        for t in self.tenants.values():
            self.bus.gauge("sched_granted_slices", t.granted,
                           tenant=t.spec.tenant)
        self.bus.counter("sched_ticks_total")
        self.bus.gauge("sched_idle_fraction", self.idle_fraction())
        self.bus.gauge("sched_backfill_requests",
                       0 if self.backfill is None
                       else self.backfill.requests_served)
        return {
            "trained": trained,
            "grants": grants,
            "busy_slices": busy,
            "leftover": leftover,
            "served": served,
            "changed": changed,
            "preempt_pause_ms": round(preempt_pause_ms, 3),
        }

    # -- lifecycle ---------------------------------------------------------

    def done(self) -> bool:
        return bool(self.tenants) and all(
            t.status in ("done", "stopped") for t in self.tenants.values()
        )

    def run(self, max_wall_s: float | None = None,
            max_ticks: int | None = None) -> dict:
        """Tick until every tenant is done/stopped, a shutdown event or
        signal arrives, or a bound trips. SIGTERM/SIGINT is the pod's
        OWN preemption: every tenant checkpoints (exit-clean) and the
        whole fleet resumes from its tenant trees."""
        from ..robustness.preemption import PreemptionGuard

        t_start = time.monotonic()
        with PreemptionGuard() as guard:
            while not self._stop:
                self.tick()
                if guard.requested is not None:
                    self._preempted = True
                    for t in self._order():
                        t.apply_grant(0)
                    self._log(
                        "[sched] preemption signal — all tenants "
                        "checkpointed and yielded"
                    )
                    break
                if self.done():
                    break
                if max_ticks is not None and self.ticks >= max_ticks:
                    break
                if max_wall_s is not None \
                        and time.monotonic() - t_start >= max_wall_s:
                    break
        return self.close()

    def idle_fraction(self) -> float:
        denom = self.pod_slices * self._wall_s
        if denom <= 0:
            return 0.0
        return round(1.0 - self._busy_slice_s / denom, 6)

    def goodput(self) -> dict:
        """The packing proof's raw material: integrated busy/idle slice
        time, preemption pauses, and per-tenant progress."""
        pauses = [
            p for t in self.tenants.values() for p in t.pauses_ms
        ]
        return {
            "pod_slices": self.pod_slices,
            "wall_s": round(self._wall_s, 4),
            "busy_slice_s": round(self._busy_slice_s, 4),
            "slice_idle_fraction": self.idle_fraction(),
            "ticks": self.ticks,
            "preempt_count": sum(
                t.preempt_count for t in self.tenants.values()
            ),
            "preempt_pause_ms_p50": (
                round(float(np.percentile(pauses, 50)), 3) if pauses
                else 0.0
            ),
            "preempt_pause_ms_p99": (
                round(float(np.percentile(pauses, 99)), 3) if pauses
                else 0.0
            ),
            "epochs": {
                t.spec.tenant: t.daemon.epochs_run
                for t in self.tenants.values()
            },
            "busy_slice_s_per_tenant": {
                t.spec.tenant: round(t.busy_slice_s, 4)
                for t in self.tenants.values()
            },
            "backfill": (
                None if self.backfill is None else {
                    "requests": self.backfill.requests_served,
                    "samples": self.backfill.samples_served,
                }
            ),
        }

    # -- live observability ------------------------------------------------

    def status(self) -> dict:
        """The pod /statusz payload: scheduler state plus EVERY tenant's
        own daemon status, tenant-labeled — one exporter, many fits."""
        return {
            "mode": "scheduler",
            "pod_slices": self.pod_slices,
            "ticks": self.ticks,
            "preempted": self._preempted,
            "goodput": self.goodput(),
            "spool_dir": self.spool_dir,
            "tenants": {
                name: t.status_view()
                for name, t in sorted(self.tenants.items())
            },
            "backfill": (
                None if self.backfill is None else self.backfill.status()
            ),
        }

    def health_probes(self) -> dict:
        probes = {"spool": lambda: os.path.isdir(self.spool_dir)}
        for name, t in self.tenants.items():
            probes[f"tenant_{name}"] = (
                lambda t=t: t.status in ("active", "done", "stopped")
            )
        return probes

    def close(self) -> dict:
        """Checkpoint + close every tenant (each asserts its own
        one-compile guard), close the backfill lane, return the fleet
        summary."""
        summaries = {}
        for name, t in sorted(self.tenants.items()):
            summaries[name] = t.close()
        out = {
            "tenants": summaries,
            "goodput": self.goodput(),
            "preempted": self._preempted,
        }
        if self.backfill is not None:
            out["backfill"] = self.backfill.close()
        return out
