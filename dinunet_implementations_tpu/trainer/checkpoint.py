"""Checkpoint / resume.

The reference's persistence is implicit: cross-round module-level ``CACHE``
dicts plus library-side best-model files implied by ``best_val_epoch``
(SURVEY.md §5 checkpoint/resume). Here it is explicit and complete: params +
batch_stats + optimizer state + engine state + RNG + round counter, serialized
with flax msgpack. ``save_best``/warm-start covers the reference's
``pretrain`` largest-site warm start (``compspec.json:120-127``).
"""

from __future__ import annotations

import json
import os
from typing import Any

import flax.serialization
import jax
import jax.numpy as jnp

from .steps import TrainState


def _atomic_write(path: str, data):
    """Write via temp file + os.replace so a kill mid-write never leaves a
    truncated file at ``path`` (resume exists to survive kills)."""
    mode = "wb" if isinstance(data, bytes) else "w"
    tmp = path + ".tmp"
    with open(tmp, mode) as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def save_checkpoint(path: str, state: TrainState, meta: dict | None = None) -> str:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    payload = {
        "params": state.params,
        "batch_stats": state.batch_stats,
        "opt_state": state.opt_state,
        "engine_state": state.engine_state,
        "rng": state.rng,
        "round": state.round,
        # meta rides INSIDE the msgpack so state+meta are one atomic unit (a
        # kill between two separate files would pair epoch-N state with
        # epoch-(N-1) bookkeeping and resume from the wrong epoch)
        "meta_json": json.dumps(meta or {}),
    }
    _atomic_write(path, flax.serialization.to_bytes(payload))
    if meta is not None:  # human-readable sidecar (non-authoritative)
        _atomic_write(path + ".meta.json", json.dumps(meta, indent=2, default=float))
    return path


def load_checkpoint(path: str, like: TrainState, with_meta: bool = False):
    """Restore into the structure of ``like`` (shapes/treedef must match).
    ``with_meta=True`` also returns the embedded (atomically-paired) meta.

    The ENGINE state restores tolerantly: its structure is an engine
    implementation detail (powerSGD's q/e, rankDAD's warm-start Ω — absent
    entirely in checkpoints saved before r6, or when ``dad_warm_start``
    differs between save and resume), and a mismatch falls back to ``like``'s
    freshly-initialized engine state with a warning instead of failing the
    whole resume. That cold-restarts the warm-start/error-feedback carry —
    mathematically safe — while params/optimizer/rng resume exactly."""
    template = {
        "params": like.params,
        "batch_stats": like.batch_stats,
        "opt_state": like.opt_state,
        "rng": like.rng,
        "round": like.round,
    }
    with open(path, "rb") as fh:
        raw = flax.serialization.msgpack_restore(fh.read())
    # meta_json restored tolerantly: checkpoints written before it existed
    # (pre-0.2.0) must still resume rather than fail the template match
    meta_json = raw.pop("meta_json", None)
    eng_raw = raw.pop("engine_state", None)
    restored = flax.serialization.from_state_dict(template, raw)
    restored["meta_json"] = meta_json
    try:
        engine_state = flax.serialization.from_state_dict(
            like.engine_state, eng_raw
        )
    except (KeyError, TypeError, ValueError):
        print(
            f"[warn] checkpoint {path}: stored engine state does not match "
            "the current engine's structure (engine or its knobs — e.g. "
            "dad_warm_start — changed since the save); resuming with fresh "
            "engine state."
        )
        engine_state = like.engine_state
    state = TrainState(
        params=restored["params"],
        batch_stats=restored["batch_stats"],
        opt_state=restored["opt_state"],
        engine_state=engine_state,
        rng=jnp.asarray(restored["rng"]),
        round=jnp.asarray(restored["round"]),
    )
    if with_meta:
        meta = restored.get("meta_json")
        if isinstance(meta, bytes):
            meta = meta.decode()
        return state, json.loads(meta or "{}")
    return state


def load_params(path: str, like_params: Any):
    """Warm-start: load only params from a checkpoint (pretrain semantics)."""
    with open(path, "rb") as fh:
        raw = flax.serialization.msgpack_restore(fh.read())
    return flax.serialization.from_state_dict(like_params, raw["params"])


def load_eval_state(path: str, like_params: Any, like_stats: Any):
    """Inference-only restore: (params, batch_stats, meta) — no dependency on
    optimizer/engine-state shapes, so a ``mode="test"`` run works even when
    its site count differs from the training run's."""
    with open(path, "rb") as fh:
        raw = flax.serialization.msgpack_restore(fh.read())
    params = flax.serialization.from_state_dict(like_params, raw["params"])
    stats = flax.serialization.from_state_dict(like_stats, raw.get("batch_stats", {}))
    meta = raw.get("meta_json") or "{}"
    if isinstance(meta, bytes):
        meta = meta.decode()
    return params, stats, json.loads(meta)


def checkpoint_meta(path: str) -> dict:
    mpath = path + ".meta.json"
    if os.path.exists(mpath):
        with open(mpath) as fh:
            return json.load(fh)
    return {}
