"""Runners: single-site (SiteRunner parity) and federated over a dataset tree.

- :class:`SiteRunner` — the reference's standalone debug harness
  (``comps/fs/site_run.py:4-6``, ``comps/icalstm/site_run.py:5-9``): train one
  site from a ``datasets/<name>`` folder + its ``inputspec.json``, no
  aggregation (a 1-site federation).
- :class:`FedRunner` — the replacement for the COINSTAC simulator (SURVEY.md
  §4.1): discovers ``input/local*/simulatorRun`` site dirs (the reference's
  fixture convention), builds per-site datasets/splits, and trains them as one
  SPMD program on a site mesh (or folded onto one chip with ``mesh=None``).
  Supports split-ratio and k-fold drivers.
"""

from __future__ import annotations

import glob
import os
import re

from ..core.config import TrainConfig, resolve_site_configs
from ..data.api import build_site_dataset
from ..data.splits import resolve_splits
from ..parallel.mesh import host_mesh, packed_site_mesh
from ..trainer.loop import FederatedTrainer
from .registry import get_task, task_cache


def _site_dir_key(path: str):
    """Numeric-then-lexicographic sort key for a ``local*`` site dir.

    The site number is taken from the ``local*`` path segment ONLY (not the
    whole path — a digit elsewhere in the tree must not reorder sites), via
    ``re.search``: mixed trees with a bare ``local`` dir (no digits) or
    decorated names (``local_backup``, unicode digit lookalikes that
    ``str.isdigit`` accepts but ``int()`` rejects) sort first instead of
    crashing the runner. The full path tie-breaks duplicates
    deterministically.
    """
    segment = os.path.basename(os.path.dirname(path))
    m = re.search(r"([0-9]+)", segment)
    return (int(m.group(1)) if m else -1, path)


def discover_site_dirs(dataset_dir: str) -> list[str]:
    """Reference fixture layout: ``<dataset_dir>/input/local{i}/simulatorRun``
    (``datasets/test_fsl``); falls back to ``dataset_dir`` itself as a single
    site when no local* dirs exist."""
    pattern = os.path.join(dataset_dir, "input", "local*", "simulatorRun")
    dirs = sorted(glob.glob(pattern), key=_site_dir_key)
    return dirs or [dataset_dir]


def load_site_splits(
    cfg: TrainConfig, site_dirs: list[str], site_cfgs: list[TrainConfig] | None = None
):
    """Build per-site datasets and per-fold splits.

    Returns ``folds``: list (per fold) of dicts with ``train``/``validation``/
    ``test`` lists of :class:`SiteArrays` (one entry per site).
    """
    site_cfgs = site_cfgs or [cfg] * len(site_dirs)
    spec = get_task(cfg.task_id)
    site_arrays = []
    site_splits = []
    for i, (d, scfg) in enumerate(zip(site_dirs, site_cfgs)):
        ds = build_site_dataset(
            spec.dataset_cls, spec.handle_cls, task_cache(scfg), {"baseDirectory": d},
            mode=scfg.mode,
        )
        arrs = ds.as_arrays()
        site_arrays.append(arrs)
        args = scfg.task_args()
        site_splits.append(
            resolve_splits(
                len(arrs),
                split_ratio=scfg.split_ratio,
                num_folds=scfg.num_folds,
                split_files=tuple(getattr(args, "split_files", ()) or ()),
                base_dir=d,
                seed=scfg.seed + i,
            )
        )
    num_folds = min(len(s) for s in site_splits)
    folds = []
    for k in range(num_folds):
        fold = {"train": [], "validation": [], "test": []}
        for arrs, splits in zip(site_arrays, site_splits):
            for key in fold:
                fold[key].append(arrs.take(splits[k][key]))
        folds.append(fold)
    return folds


class FedRunner:
    """Federated training over a reference-style dataset tree."""

    def __init__(
        self,
        cfg: TrainConfig | None = None,
        data_path: str = ".",
        out_dir: str | None = None,
        mesh="auto",
        fault_plan=None,
        **overrides,
    ):
        cfg = (cfg or TrainConfig()).with_overrides(overrides)
        self.data_path = data_path
        # deterministic chaos injection (robustness/faults.py), threaded into
        # every fold's trainer; None = no faults
        self.fault_plan = fault_plan
        self.site_dirs = discover_site_dirs(data_path)
        self.site_cfgs = resolve_site_configs(cfg, data_path, num_sites=len(self.site_dirs))
        # owner-scoped fields come from site 0 (the reference GUI sends one
        # owner config; per-site inputspecs override member fields)
        self.cfg = self.site_cfgs[0].replace(num_sites=len(self.site_dirs))
        self.out_dir = out_dir or os.path.join(data_path, "output")
        if mesh == "auto":
            import jax

            n = len(self.site_dirs)
            m = max(self.cfg.model_axis_size, 1)
            k = max(self.cfg.sites_per_device, 1)
            if n % k:
                raise ValueError(
                    f"sites_per_device={k} must divide the site count ({n})"
                )
            n_mesh = n // k  # mesh site-axis size; k sites pack per device
            devs = jax.devices()
            cpus = [d for d in devs if d.platform == "cpu"]
            if jax.process_count() > 1:
                # multi-host runtime (distributed_init): hybrid mesh — the
                # model axis stays on each host's ICI, sites span DCN
                from ..parallel.distributed import multihost_site_mesh

                if n_mesh % jax.process_count():
                    raise ValueError(
                        f"{n_mesh} mesh sites must divide evenly over "
                        f"{jax.process_count()} processes"
                    )
                mesh = multihost_site_mesh(
                    sites_per_process=n_mesh // jax.process_count(),
                    model_axis_size=m,
                )
            elif len(devs) >= n_mesh * m:
                # the packed topology (parallel/mesh.py): k virtual sites
                # per mesh member, two-level aggregation in the epoch
                mesh = packed_site_mesh(n, k, devs, model_axis_size=m)
            elif len(cpus) >= n_mesh * m:
                mesh = host_mesh(n_mesh, model_axis_size=m)
            elif m > 1:
                raise ValueError(
                    f"model_axis_size={m} with {n_mesh} mesh sites needs "
                    f"{n_mesh * m} devices (have {len(devs)}); sequence "
                    "parallelism cannot fold onto one device"
                )
            else:
                mesh = None  # fold all sites onto the local device via vmap
        self.mesh = mesh

    def run(self, folds=None, verbose: bool = True, resume: bool = False) -> list[dict]:
        """``resume=True`` continues each fold from its last
        validation-boundary checkpoint; ``cfg.mode == "test"`` skips training
        and evaluates each fold's best checkpoint."""
        all_folds = load_site_splits(self.cfg, self.site_dirs, self.site_cfgs)
        fold_ids = list(range(len(all_folds)))
        if folds is not None:
            all_folds = [all_folds[k] for k in folds]
            fold_ids = list(folds)
        from ..checks.sanitize import sanitized_fit

        results = []
        for k, fold in zip(fold_ids, all_folds):
            trainer = FederatedTrainer(
                self.cfg, get_task(self.cfg.task_id).build_model(self.cfg),
                self.mesh, out_dir=self.out_dir, fault_plan=self.fault_plan,
            )
            # DINUNET_SANITIZE=1 (or CLI --sanitize): compile-counter guard +
            # leak/NaN checking around the fit — each fold's trainer is one
            # (engine, topology) program, so the per-fit guard IS the
            # one-compilation-per-program gate. No-op when disabled.
            with sanitized_fit(
                trainer, label=f"{self.cfg.agg_engine}/fold{k}"
            ) as report:
                res = trainer.fit(
                    fold["train"], fold["validation"], fold["test"], fold=k,
                    verbose=verbose, resume=resume,
                )
                if report is not None:
                    report.note_result(res)
            results.append(res)
        return results


class SiteRunner:
    """Single-site harness (reference ``SiteRunner``; the ``taks_id`` typo is
    the library's kwarg — accepted here for drop-in parity)."""

    def __init__(
        self,
        taks_id: str | None = None,
        task_id: str | None = None,
        data_path: str = ".",
        mode: str = "train",
        seed: int = 0,
        site_index: int = 0,
        split_ratio=(0.8, 0.1, 0.1),
        monitor_metric: str = "auc",
        metric_direction: str = "maximize",
        log_header: str = "Loss|AUC",
        batch_size: int = 16,
        out_dir: str | None = None,
        **kw,
    ):
        # the reference's taks_id is a short name ('FSL', 'ICA'); map to tasks
        tid = task_id or {"FSL": "FS-Classification", "ICA": "ICA-Classification"}.get(
            taks_id, taks_id
        )
        self.site_index = site_index
        self.cfg = TrainConfig(
            task_id=tid,
            mode=mode,
            seed=seed,
            split_ratio=tuple(split_ratio),
            monitor_metric=monitor_metric,
            metric_direction=metric_direction,
            log_header=log_header,
            batch_size=batch_size,
        ).with_overrides(kw)
        self.data_path = data_path
        self.out_dir = out_dir

    def run(self, trainer_cls=None, dataset_cls=None, handle_cls=None, verbose=True):
        """Positional (Trainer, Dataset, DataHandle) accepted for reference
        signature parity; the registry supplies defaults."""
        site_dirs = discover_site_dirs(self.data_path)
        site_cfgs = resolve_site_configs(
            self.cfg, self.data_path, num_sites=len(site_dirs)
        )
        ix = min(self.site_index, len(site_dirs) - 1)
        cfg = site_cfgs[ix]
        spec = get_task(cfg.task_id)
        dataset_cls = dataset_cls or spec.dataset_cls
        handle_cls = handle_cls or spec.handle_cls
        ds = build_site_dataset(
            dataset_cls, handle_cls, task_cache(cfg),
            {"baseDirectory": site_dirs[ix]}, mode=cfg.mode,
        )
        arrs = ds.as_arrays()
        args = cfg.task_args()
        splits = resolve_splits(
            len(arrs),
            split_ratio=cfg.split_ratio,
            num_folds=cfg.num_folds,
            split_files=tuple(getattr(args, "split_files", ()) or ()),
            base_dir=site_dirs[ix],
            seed=cfg.seed,
        )
        from ..checks.sanitize import sanitized_fit

        results = []
        for k, split in enumerate(splits):
            trainer = FederatedTrainer(
                cfg, spec.build_model(cfg), mesh=None, out_dir=self.out_dir
            )
            with sanitized_fit(
                trainer, label=f"{cfg.agg_engine}/site{ix}/fold{k}"
            ) as report:
                res = trainer.fit(
                    [arrs.take(split["train"])],
                    [arrs.take(split["validation"])],
                    [arrs.take(split["test"])],
                    fold=k,
                    verbose=verbose,
                )
                if report is not None:
                    report.note_result(res)
            results.append(res)
        return results
