"""Fault tolerance for federated rounds.

The reference runs one container per hospital site under a coordinator that
must survive flaky sites and restarts (SURVEY §0 trust topology); DrJAX and
the Podracer architectures (PAPERS.md) both treat partial participation and
worker loss as the normal case. This package makes the SPMD round loop match
that contract:

- :mod:`faults` — :class:`FaultPlan`, a deterministic fault-injection config
  (site-drop schedule, seeded flaky-site drops, NaN poisoning, kill-at-round)
  threaded through the trainer loop and data layer so every failure mode has
  a reproducible chaos test;
- :mod:`health` — the per-site health state (non-finite streak / skip /
  quarantine counters) carried through the jitted epoch scan and surfaced in
  ``logs.json``;
- :mod:`preemption` — SIGTERM/SIGINT save-and-exit for preemptible workers
  (:class:`PreemptionGuard`, :class:`Preempted`);
- :mod:`retry` — jittered exponential backoff for transient failures
  (``distributed_init``, native IO reads), with wall-clock deadlines and
  per-attempt timeouts so a HUNG remote fails fast instead of retrying
  forever;
- :mod:`membership` — the elastic-rounds membership table (r13): logical
  sites mapped onto a fixed padded virtual-site axis, join/leave/rejoin as
  pure state transitions with generation counters and host-side slot-state
  resets — churn never retraces the epoch program;
- :mod:`attacks` — :class:`AttackPlan`, the hostile twin of FaultPlan (r17):
  declarative byzantine-site attacks (sign-flip, gradient scaling, additive
  noise, free-riding, colluding cliques) rendered into a traced ``[S,
  rounds]`` code mask; defenses are the engines' ``robust_agg`` reducers
  (parallel/collectives.py) plus the anomaly-scored reputation layer riding
  :mod:`health`.

The liveness-mask/quarantine math itself lives *inside* the compiled epoch
(trainer/steps.py + the engines' ``live`` argument): masks are traced array
inputs, so a different fault pattern never recompiles the program.
"""

from .attacks import (
    AttackPlan,
    attack_window,
    make_attack_fn,
    parse_attack_plan,
)
from .faults import FaultPlan, fault_window, parse_fault_plan, poison_inputs
from .health import default_health, health_summary
from .membership import (
    MembershipError,
    MembershipTable,
    membership_rollup,
    move_slot_state,
    reset_slot_state,
)
from .preemption import Preempted, PreemptionGuard
from .retry import RetryTimeout, with_retry

__all__ = [
    "AttackPlan",
    "attack_window",
    "make_attack_fn",
    "parse_attack_plan",
    "FaultPlan",
    "fault_window",
    "MembershipError",
    "MembershipTable",
    "membership_rollup",
    "move_slot_state",
    "Preempted",
    "PreemptionGuard",
    "default_health",
    "health_summary",
    "parse_fault_plan",
    "poison_inputs",
    "reset_slot_state",
    "RetryTimeout",
    "with_retry",
]
