"""Multi-host (DCN) layer — single-process behavior and mesh topology.

True multi-process execution needs a pod; what IS testable on one host (and
what these tests pin) is the contract everything else relies on:
``distributed_init`` no-ops for single-process runs, ``multihost_site_mesh``
degenerates to the plain ``(site, model)`` mesh, and the mesh it builds
carries working collectives. The hybrid-DCN branch itself is exercised by the
same ``mesh_utils.create_hybrid_device_mesh`` JAX ships for pod meshes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from dinunet_implementations_tpu.parallel import (
    MODEL_AXIS,
    SITE_AXIS,
    distributed_init,
    multihost_site_mesh,
)


def test_single_process_init_is_noop():
    assert distributed_init() is False
    assert distributed_init(num_processes=1) is False


def test_mesh_shape_and_axis_names():
    mesh = multihost_site_mesh(sites_per_process=4, model_axis_size=2)
    assert dict(mesh.shape) == {SITE_AXIS: 4, MODEL_AXIS: 2}
    assert mesh.axis_names == (SITE_AXIS, MODEL_AXIS)


def test_mesh_defaults_fill_the_process():
    mesh = multihost_site_mesh()
    assert dict(mesh.shape) == {SITE_AXIS: len(jax.devices()), MODEL_AXIS: 1}


def test_mesh_uses_leading_subset_when_devices_surplus():
    # 3 sites x model=2 on 8 devices: 6 used, 2 idle (same contract as
    # make_site_mesh's devices[:need] on one host)
    mesh = multihost_site_mesh(sites_per_process=3, model_axis_size=2)
    assert dict(mesh.shape) == {SITE_AXIS: 3, MODEL_AXIS: 2}
    assert list(mesh.devices.flat) == jax.devices()[:6]


def test_mesh_rejects_oversubscription():
    with pytest.raises(ValueError, match="devices per process"):
        multihost_site_mesh(sites_per_process=5, model_axis_size=2)


def test_collectives_run_on_the_mesh():
    mesh = multihost_site_mesh(sites_per_process=4, model_axis_size=2)
    x = jnp.arange(8.0).reshape(4, 2)

    out = jax.jit(
        shard_map(
            lambda v: jax.lax.psum(v, (SITE_AXIS, MODEL_AXIS)),
            mesh=mesh,
            in_specs=P(SITE_AXIS, MODEL_AXIS),
            out_specs=P(SITE_AXIS, MODEL_AXIS),
        )
    )(x)
    np.testing.assert_allclose(np.asarray(out), np.full((4, 2), x.sum()))
