"""On-device round metrics — the device half of the telemetry layer.

The epoch's rounds scan (trainer/steps.py) computes, per site per round, a
small set of scalars the operator otherwise cannot see without rerunning
under a bespoke harness:

- ``grad_sq_last`` — this round's squared gradient norm (``Σ g²`` over the
  site's accumulated round gradient). NaN/Inf survives here verbatim — "site
  3's gradients blew up" is the signal, and the health counters say when;
- ``grad_sq_sum`` / ``grad_sq_max`` — finite-only accumulators across rounds
  (a non-finite round would poison the sums forever, so it is excluded there
  and visible in ``last`` + ``health.streak`` instead);
- ``residual_sq_sum`` — the engine aggregation residual ``Σ ‖g_site − ĝ‖²``:
  how far the engine's aggregate moved this site's raw gradient. For
  compression engines (rankDAD/powerSGD) on homogeneous sites this IS the
  compression error; for dSGD it measures cross-site gradient disagreement;
- ``update_sq_last`` / ``update_sq_sum`` — squared norm of the applied
  optimizer update (replicated per site: the update is global);
- ``payload_bytes`` — modeled collective wire bytes shipped per round PER
  PHYSICAL DEVICE (:func:`payload_bytes_of`, from the engine's
  ``wire_bytes`` model at the run's pack factor: under site packing the
  in-register pack-axis reduce is free, so the same figure lands in every
  virtual site's row and reads as "what my device ships each round");
- ``rounds`` — rounds counted into the accumulators.

All leaves carry a leading ``[num_sites]`` axis and ride ``TrainState
.telemetry`` sharded ``P(site)`` exactly like ``health`` (trainer/steps.py
``_state_specs``): no extra host syncs per round, no recompiles (the values
are traced), checkpointed (trainer/checkpoint.py), and distinct arrays so
state donation never aliases a buffer twice. ``TrainConfig.telemetry="off"``
compiles all of it out — the epoch program is bitwise-identical to the
pre-telemetry one (tests/test_telemetry.py).
"""

from __future__ import annotations

import inspect
import math

import numpy as np


def _accepts_pack(fn) -> bool:
    """True when a wire-model hook takes the r12 ``pack=`` kwarg. Resolved
    from the signature — NOT by calling under ``except TypeError``, which
    would misread a genuine TypeError raised inside a pack-aware model as
    "pack-unaware" and silently fall back to K-invariant bytes."""
    try:
        params = inspect.signature(fn).parameters
    except (TypeError, ValueError):  # builtins/C callables: assume legacy
        return False
    return "pack" in params or any(
        p.kind is inspect.Parameter.VAR_KEYWORD for p in params.values()
    )

#: metric keys of the TrainState.telemetry pytree (trace-stable; keep sorted)
TELEMETRY_KEYS = (
    "dcn_bytes",
    "grad_sq_last",
    "grad_sq_max",
    "grad_sq_sum",
    "held_rounds",
    "payload_bytes",
    "residual_sq_sum",
    "rounds",
    "update_sq_last",
    "update_sq_sum",
)

#: integer accumulators (the rest are f32). ``held_rounds`` (r19) counts
#: rounds the slice-quorum floor declined to train — frozen params/opt,
#: NaN loss (trainer/steps.py); 0 everywhere quorum machinery is off.
_INT_KEYS = ("rounds", "held_rounds")


def default_round_telemetry(num_sites: int) -> dict:
    """Fresh all-zero accumulators with the per-site leading axis."""
    # jax deferred to the call, same reasoning as robustness/health.py:
    # keep this module importable without locking in jax backend config
    import jax.numpy as jnp

    # distinct arrays per key (not one shared buffer): the epoch program
    # donates the carried state and XLA rejects twice-donated buffers
    return {
        k: (jnp.zeros((num_sites,), jnp.int32) if k in _INT_KEYS
            else jnp.zeros((num_sites,), jnp.float32))
        for k in TELEMETRY_KEYS
    }


def tree_sq_sum(tree):
    """``Σ x²`` over every leaf, accumulated in f32 leaf-by-leaf in tree
    order. The SAME helper runs inside the compiled epoch and in the
    host-recomputation tests — bit-exact equality depends on both sides
    reducing in this order."""
    import jax
    import jax.numpy as jnp

    s = jnp.zeros((), jnp.float32)
    for leaf in jax.tree.leaves(tree):
        s = s + jnp.sum(jnp.square(leaf.astype(jnp.float32)))
    return s


def payload_bytes_of(engine, grads_template, pack: int = 1) -> float:
    """Modeled per-round collective payload bytes for one PHYSICAL DEVICE.

    Uses the engine's own ``wire_bytes`` model (engines/base.py) when it has
    one; otherwise the dense-f32 fallback (every leaf shipped whole).
    ``pack`` is the site-packing factor K (parallel/collectives.py
    PackedAxis): under packing the local pack-axis reduce is free, so
    psum-shaped exchanges stay K-invariant and only a gathered per-site
    payload (rankDAD's factor exchange) scales with K; ``pack=1`` is the
    classic one-site-per-member figure (also used for the vmap-folded
    single-device topology, where there is no physical wire and the figure
    models the notional per-site exchange, as it always has). A static
    Python float — computed once at trace time from the gradient pytree's
    shapes, never a traced value. Since r11 this figure is VERIFIED, not
    just modeled: checks/semantic.py rule S002 cross-checks it against the
    traced epoch program's actual collective operand shapes/dtypes — since
    r12 at the cell's real pack factor. Engines with a pack-unaware model
    (external/test fixtures) are treated as pack-invariant."""
    wb = getattr(engine, "wire_bytes", None)
    if wb is not None:
        if _accepts_pack(wb):
            return float(wb(grads_template, pack=pack))
        return float(wb(grads_template))
    import jax

    # model-less engines: every leaf shipped whole AT THE ENGINE'S DECLARED
    # WIRE dtype — the modeled bytes must follow wire_dtype, not assume the
    # f32 compute itemsize, or telemetry's payload_bytes_per_round (and the
    # logs.json rollups) silently overstate a quantized wire 4x (r14 fix;
    # S002 enforces the figure against the traced program)
    isz = np.dtype(getattr(engine, "wire_dtype", None) or np.float32).itemsize
    return float(sum(
        math.prod(leaf.shape) * isz
        for leaf in jax.tree.leaves(grads_template)
    ))


def dcn_bytes_of(engine, grads_template, pack: int = 1,
                 sites_per_slice: int = 1, slices: int = 1) -> float:
    """Modeled per-round INTER-SLICE (DCN) payload bytes for one SLICE —
    the r18 twin of :func:`payload_bytes_of`, split per tier so telemetry,
    ``logs.json`` and the ``/statusz`` bus report ICI and DCN traffic
    separately. ``slices <= 1`` (single-slice meshes, the vmap fold) is
    0.0 — there is no inter-slice hop to model. Uses the engine's own
    ``dcn_bytes`` model (engines/base.py) when it has one; the fallback
    ships every leaf's per-slice partial whole at the engine's DCN (else
    ICI wire, else f32) dtype. Verified against the traced sliced programs
    by checks/semantic.py — a figure, like the ICI one, that is proven,
    not just modeled."""
    if slices <= 1:
        return 0.0
    db = getattr(engine, "dcn_bytes", None)
    if db is not None:
        return float(db(grads_template, pack=pack,
                        sites_per_slice=sites_per_slice))
    import jax

    d = np.dtype(
        getattr(engine, "dcn_dtype", None)
        or getattr(engine, "wire_dtype", None)
        or np.float32
    )
    return float(sum(
        math.prod(leaf.shape) * d.itemsize
        for leaf in jax.tree.leaves(grads_template)
    ))


def modeled_dcn_shapes(engine, grads_template, pack: int = 1,
                       sites_per_slice: int = 1) -> list:
    """The structured model behind :func:`dcn_bytes_of`: ``[(shape, numpy
    dtype), ...]`` — one entry per inter-slice hop payload per round per
    slice (``Engine.dcn_wire_shapes``), with the same dense fallback as
    the bytes model."""
    ds = getattr(engine, "dcn_wire_shapes", None)
    if ds is not None:
        return [
            (tuple(s), np.dtype(d))
            for s, d in ds(grads_template, pack=pack,
                           sites_per_slice=sites_per_slice)
        ]
    import jax

    d = np.dtype(
        getattr(engine, "dcn_dtype", None)
        or getattr(engine, "wire_dtype", None)
        or np.float32
    )
    return [
        (tuple(leaf.shape), d) for leaf in jax.tree.leaves(grads_template)
    ]


def modeled_wire_shapes(engine, grads_template, pack: int = 1) -> list:
    """The structured payload model behind :func:`payload_bytes_of`:
    ``[(shape, numpy dtype), ...]`` — one entry per collective payload
    operand the engine ships per round per device (``Engine.wire_shapes``,
    engines/base.py) at pack factor ``pack``, falling back to one dense-f32
    operand per leaf for engines without the hook (pack-unaware hooks are
    treated as pack-invariant). checks/semantic.py matches every entry
    against a traced collective operand and requires the byte sum to equal
    ``wire_bytes`` exactly."""
    ws = getattr(engine, "wire_shapes", None)
    if ws is not None:
        shapes = (
            ws(grads_template, pack=pack) if _accepts_pack(ws)
            else ws(grads_template)
        )
        return [(tuple(s), np.dtype(d)) for s, d in shapes]
    import jax

    # fallback mirrors payload_bytes_of: dense leaves at the engine's
    # declared wire dtype (f32 only when the engine declares nothing)
    d = np.dtype(getattr(engine, "wire_dtype", None) or np.float32)
    return [
        (tuple(leaf.shape), d)
        for leaf in jax.tree.leaves(grads_template)
    ]


def telemetry_summary(telemetry) -> dict | None:
    """Host-side rollup of a fit's final ``TrainState.telemetry`` for results
    dicts / ``logs.json`` / ``metrics.jsonl``: plain float lists, norms
    un-squared. ``None`` in → ``None`` out (telemetry off)."""
    if telemetry is None:
        return None
    t = {k: np.asarray(v) for k, v in telemetry.items()}
    rounds = np.maximum(t["rounds"].astype(np.float64), 1.0)

    def norms(a):
        return [float(v) for v in np.sqrt(np.maximum(a.astype(np.float64), 0.0))]

    return {
        "site_grad_norm_last": [float(v) for v in np.sqrt(t["grad_sq_last"])],
        "site_grad_norm_max": norms(t["grad_sq_max"]),
        "site_grad_norm_mean": norms(t["grad_sq_sum"] / rounds),
        "site_residual_norm_mean": norms(t["residual_sq_sum"] / rounds),
        "update_norm_last": float(np.sqrt(max(float(t["update_sq_last"][0]), 0.0))),
        "payload_bytes_per_round": float(t["payload_bytes"][0] / rounds[0]),
        # r18 per-tier split: the inter-slice (DCN) hop's per-slice figure;
        # 0.0 on single-slice runs (and on pre-r18 accumulators)
        "dcn_bytes_per_round": (
            float(t["dcn_bytes"][0] / rounds[0]) if "dcn_bytes" in t else 0.0
        ),
        "rounds": int(t["rounds"][0]),
        # r19 slice elasticity: rounds the slice-quorum floor held back
        # (0 on pre-r19 accumulators and whenever quorum machinery is off)
        "held_rounds": (
            int(t["held_rounds"][0]) if "held_rounds" in t else 0
        ),
    }
