"""Benchmark: ICA-LSTM federated training throughput, 32 simulated sites.

The north-star metric (BASELINE.json): samples/sec/chip for the ICA-LSTM
fMRI classifier trained across 32 simulated federated sites, vs the
CPU reference baseline. One chip simulates all 32 sites via the vmap-folded
site axis (trainer/steps.py); the measured step is the FULL federated round:
per-site grad, dSGD example-weighted aggregation across the 32 sites, Adam
update — i.e. what the reference needs a 32-container COINSTAC deployment
plus a remote to do.

MEASUREMENT METHODOLOGY (important — the axon tunnel is a lazy backend):
the tunneled PJRT backend evaluates LAZILY PER FETCHED BUFFER. Fetching one
cheap output (a round counter) materializes only that buffer's dependency
chain and can skip nearly all of the training compute; block_until_ready
does not synchronize either. Verified empirically on v5e: fetching
``state.round`` after an epoch cost ~24 ms while materializing the FULL
state cost ~570 ms, and a 3 s host sleep did not advance device work (fully
fetch-driven). Earlier rounds' bench numbers were inflated by this. The
honest recipe used here:

1. chain N epochs (each consumes the previous state),
2. materialize EVERY leaf of the final state (np.asarray over the tree) —
   forcing the entire chain,
3. report the MARGINAL epoch cost between two LONG chains,
   (min T(N) - min T(N/2)) / (N/2), minimizing each chain length over three
   runs SEPARATELY: the tunnel is shared infrastructure whose contention
   only ever ADDS time (observed 2× swings minutes apart), so the minimum
   per endpoint is its least-contended observation. (Minimizing the paired
   differences instead would be downward-biased — contention in the half
   chain subtracts from the difference.)

Baseline: the reference's torch ICALstm (loaded from
/root/reference/comps/icalstm/models.py) doing fwd+bwd+Adam on one CPU site
measured in this environment = 67.3 samples/sec (B=16, 238 ms/iter; falls back
to this recorded constant when the live measurement is unavailable).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} (plus an
``mfu`` field — fraction of v5e bf16 peak sustained by the model's matmul
FLOPs at the measured throughput).
"""

import json
import statistics
import sys
import time

# Recorded in this environment (see module docstring); re-measured live when
# --live-baseline is passed.
CPU_BASELINE_SAMPLES_PER_SEC = 67.3

NUM_SITES = 32
BATCH_PER_SITE = 16
STEPS_PER_EPOCH = 2
TIMED_EPOCHS = 100  # long chains: the marginal compute must dwarf fetch jitter

# flagship model dims (HCP inputspec, datasets/icalstm/inputspec.json:32-43)
WINDOWS, COMPS, WLEN = 98, 100, 10
ENC_IN, ENC_OUT, HIDDEN = COMPS * WLEN, 256, 348

V5E_BF16_PEAK_FLOPS = 197e12


def chain_epochs(epoch_fn, state0, x, y, w, n: int, live=None,
                 attack=None, slice_live=None) -> float:
    """Run ``n`` chained epochs from ``state0`` and FULLY materialize the
    final state (np.asarray over every leaf) — the only synchronization the
    lazy tunneled backend honors. Returns wall-clock seconds. This is the
    shared measurement primitive for bench.py and bench_matrix.py; any
    methodology fix belongs here, once. ``live`` is the optional ``[S,
    rounds]`` liveness mask (``--faults``): the same device array feeds every
    epoch (throughput of the masked program, not of a changing schedule);
    ``attack`` is the optional ``[S, rounds]`` attack-code mask
    (``--attacks``, robustness/attacks.py) riding after it;
    ``slice_live`` the optional ``[num_slices, rounds]`` slice-liveness
    mask (r19 — sliced meshes under a slice-fault plan)."""
    import jax
    import numpy as np

    s = state0
    t0 = time.time()
    for _ in range(n):
        if slice_live is not None:
            s, _ = epoch_fn(s, x, y, w, live, attack, slice_live)
        elif attack is not None:
            s, _ = epoch_fn(s, x, y, w, live, attack)
        elif live is not None:
            s, _ = epoch_fn(s, x, y, w, live)
        else:
            s, _ = epoch_fn(s, x, y, w)
    jax.tree.map(np.asarray, s)
    return time.time() - t0


def least_contended_marginal(run_chain, n: int, repeats: int = 3,
                             pre_full: float | None = None) -> float:
    """Marginal seconds/epoch between an ``n``-epoch and an ``n/2``-epoch
    chain, taking the MINIMUM of ``repeats`` runs PER ENDPOINT (module
    docstring step 3): tunnel contention only adds time, so each endpoint's
    minimum is its least-contended observation; minimizing paired
    differences instead would be downward-biased. ``run_chain(k)`` must
    return wall-clock seconds for a k-epoch fully-materialized chain.
    ``pre_full`` feeds an already-observed (n+1)-chain timing into the
    full-endpoint minimum (valid for a min estimator; saves a chain)."""
    half = n // 2
    t_half = min(run_chain(half + 1) for _ in range(repeats))
    fulls = [run_chain(n + 1) for _ in range(repeats)]
    if pre_full is not None:
        fulls.append(pre_full)
    return max((min(fulls) - t_half) / (n - half), 1e-9)


def marginal_distribution(pairs, n: int, pre_full: float | None = None) -> dict:
    """Distribution summary over N paired (half-chain, full-chain) timings.

    ``pairs`` is a list of ``(T(n/2+1), T(n+1))`` wall-clock observations.
    The headline ``marginal_seconds_per_epoch`` is the least-contended
    estimator (endpoint minima — module docstring step 3); the
    ``per_observation`` marginals pair each observation's own endpoints,
    giving the contention distribution that retires single-observation
    claims: ``min``/``median``/``spread`` (max − min) are all in
    seconds/epoch. An observation whose half chain was contended can come
    out non-positive (full ≤ half); those are recorded verbatim in
    ``per_observation`` and counted in ``contended``, but EXCLUDED from the
    min/median/spread summary — a clamped near-zero marginal would
    otherwise masquerade as an absurd throughput outlier. If even the
    ENDPOINT-MIN estimate is non-positive (every full chain beat by a half
    chain — heavy contention), the record is flagged ``unreliable`` rather
    than reporting the clamp as a measurement. The headline is the number to
    cite, the spread is the error bar.

    ``pre_full`` feeds an already-observed full-chain timing into the
    HEADLINE's endpoint minimum only (valid for a min estimator; saves a
    chain) — it is NOT paired into the distribution, whose observations must
    be adjacent in time.
    """
    half = n // 2
    denom = n - half
    halves = [h for h, _ in pairs]
    fulls = [f for _, f in pairs]
    per_obs = [(f - h) / denom for h, f in pairs]
    valid = [v for v in per_obs if v > 0]
    headline = (min(fulls + ([pre_full] if pre_full is not None else []))
                - min(halves)) / denom
    out = {
        "marginal_seconds_per_epoch": max(headline, 1e-9),
        "observations": len(pairs),
        "per_observation": [round(v, 9) for v in per_obs],
        "contended": len(per_obs) - len(valid),
    }
    if headline <= 0:
        out["unreliable"] = True
    if valid:
        out.update(
            min=min(valid), median=statistics.median(valid),
            spread=max(valid) - min(valid),
        )
    return out


def throughput_stats(dist: dict, samples_per_epoch: float) -> dict:
    """Convert a :func:`marginal_distribution` summary to samples/sec/chip:
    ``value`` from the least-contended headline; min/median over the VALID
    (positive-marginal) per-observation points (min throughput = slowest
    observation); ``spread`` = max − min. Contended (non-positive)
    observations are excluded from the summary and surfaced as a count; an
    ``unreliable`` distribution (even the endpoint-min estimate was
    contention-dominated) reports ``value: None`` instead of the 1e-9
    clamp's absurd implied throughput."""
    per = [samples_per_epoch / v for v in dist["per_observation"] if v > 0]
    out = {
        "value": (None if dist.get("unreliable") else round(
            samples_per_epoch / dist["marginal_seconds_per_epoch"], 2)),
        "observations": dist["observations"],
        "contended": dist.get("contended", 0),
    }
    if dist.get("unreliable"):
        out["unreliable"] = True
    if per:
        out.update(
            min=round(min(per), 2),
            median=round(statistics.median(per), 2),
            spread=round(max(per) - min(per), 2),
        )
    return out


def interleaved_ab(run_chains: dict, n: int, obs: int = 5) -> dict:
    """Paired interleaved A/B over named arms, N observations per arm.

    ``run_chains[name](k)`` must return wall-clock seconds for a k-epoch
    fully-materialized chain of that arm (arms pre-compiled by their first
    call). Per observation round, every arm's half chain is timed
    back-to-back, then every arm's full chain, with the arm ORDER alternating
    between rounds — a minutes-long contention window lands on all arms
    instead of one (sequential whole-arm A/Bs flipped sign between runs, r5).
    Returns ``{name: marginal_distribution(...)}``.
    """
    names = list(run_chains)
    pairs = {k: [] for k in names}
    halves = {}
    for i in range(obs):
        order = names if i % 2 == 0 else names[::-1]
        for k in order:
            halves[k] = run_chains[k](n // 2 + 1)
        for k in order:
            pairs[k].append((halves[k], run_chains[k](n + 1)))
    return {k: marginal_distribution(v, n) for k, v in pairs.items()}


def flops_per_sample_dims(windows: int, enc_in: int, enc_out: int,
                          hidden: int) -> float:
    """Matmul FLOPs for one training sample at arbitrary flagship-family
    dims (fwd ≈ enc + biLSTM + head; train ≈ 3× fwd for fwd+bwd)."""
    h = hidden // 2  # per direction
    enc = windows * enc_in * enc_out * 2
    lstm = windows * 2 * (enc_out * 4 * h + h * 4 * h) * 2  # both directions
    head = hidden * 256 * 2 + 256 * 64 * 2 + 64 * 2 * 2
    return 3.0 * (enc + lstm + head)


def flops_per_sample() -> float:
    """Matmul FLOPs for one training sample at the flagship HCP dims."""
    return flops_per_sample_dims(WINDOWS, ENC_IN, ENC_OUT, HIDDEN)


def _flagship_arm(engine_name: str = "dSGD", engine_kw: dict | None = None,
                  dims: dict | None = None, fused_bidir: bool | None = None):
    """Shared flagship-arm construction for every bench mode: the dims dict
    (flagship HCP defaults overridden by ``--small``), the ICA-LSTM
    model/task/engine/optimizer, and the synthetic per-site epoch data as
    NUMPY arrays (one RNG draw sequence — arms agree bit-for-bit on their
    inputs). A dims/model/dtype policy change lands here ONCE and every
    arm — steady-state, pipeline A/B, packed sites sweep — measures the
    same configuration.

    bf16 matmuls AND streamed activations with f32 carries/accumulation;
    the fused Pallas kernel keeps W_ih/W_hh resident in VMEM and streams
    the raw x once per step (ops/lstm_pallas.py). ``fused_bidir=False`` is
    the A/B arm: two single-direction kernel sweeps instead of the fused
    bidirectional pooled kernel (VERDICT r4 #1b)."""
    import numpy as np

    from dinunet_implementations_tpu.engines import make_engine
    from dinunet_implementations_tpu.models import ICALstm
    from dinunet_implementations_tpu.trainer import (
        FederatedTask,
        make_optimizer,
    )

    d = dict(sites=NUM_SITES, steps=STEPS_PER_EPOCH, batch=BATCH_PER_SITE,
             windows=WINDOWS, comps=COMPS, wlen=WLEN, enc_out=ENC_OUT,
             hidden=HIDDEN, compute_dtype="bfloat16")
    d.update(dims or {})
    model = ICALstm(input_size=d["enc_out"], hidden_size=d["hidden"],
                    num_comps=d["comps"], window_size=d["wlen"], num_cls=2,
                    compute_dtype=d["compute_dtype"], fused_bidir=fused_bidir)
    task = FederatedTask(model)
    engine = make_engine(engine_name, **(engine_kw or {}))
    opt = make_optimizer("adam", 1e-3)
    S, steps, B = d["sites"], d["steps"], d["batch"]
    rng = np.random.default_rng(0)
    np_x = rng.normal(
        size=(S, steps, B, d["windows"], d["comps"], d["wlen"])
    ).astype(np.float32)
    np_y = (rng.random((S, steps, B)) > 0.5).astype(np.int32)
    np_w = np.ones((S, steps, B), np.float32)
    return d, task, engine, opt, np_x, np_y, np_w


def _setup_epoch(engine_name: str = "dSGD", engine_kw: dict | None = None,
                 fused_bidir: bool | None = None, dims: dict | None = None,
                 fault_plan=None, epoch_kw: dict | None = None):
    """Build the compiled flagship epoch for one bench arm.

    Returns ``(run_chain, samples_per_epoch)``: ``run_chain(k)`` times a
    k-epoch fully-materialized chain (compile happens on the first call —
    call ``run_chain(1)`` once to warm up before timing). ``dims`` overrides
    the flagship model/data dims (``--small`` harness-validation mode).
    ``fault_plan`` (a robustness.FaultPlan) measures the fault-masked round:
    its epoch-0 liveness mask feeds every chained epoch. ``epoch_kw``
    threads extra ``make_train_epoch_fn`` kwargs (the r20 privacy arms:
    dp_clip / dp_noise_multiplier / personalize)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dinunet_implementations_tpu.trainer import (
        compile_epoch_aot,
        init_train_state,
        make_train_epoch_fn,
    )

    d, task, engine, opt, np_x, np_y, np_w = _flagship_arm(
        engine_name, engine_kw, dims, fused_bidir
    )
    S, steps, B = d["sites"], d["steps"], d["batch"]
    # ship inputs pre-cast to the model's compute dtype (what the input
    # pipeline does for a bf16 model): halves the resident input footprint
    # and removes XLA's whole-input convert+layout copy from the epoch
    x = jnp.asarray(
        np_x,
        dtype=jnp.bfloat16 if d["compute_dtype"] == "bfloat16" else None,
    )
    y = jnp.asarray(np_y)
    w = jnp.asarray(np_w)

    state0 = init_train_state(
        task, engine, opt, jax.random.PRNGKey(0), x[0, 0], num_sites=S
    )
    epoch_fn = make_train_epoch_fn(
        task, engine, opt, mesh=None, local_iterations=1,
        **(epoch_kw or {}),
    )
    live = None
    if fault_plan is not None and fault_plan.injects_faults():
        # rounds == steps at local_iterations=1; the first epoch's window
        live = jnp.asarray(fault_plan.liveness(S, 0, steps))

    from dinunet_implementations_tpu.checks.sanitize import (
        CompileGuard,
        sanitize_enabled,
    )

    guard = None
    if sanitize_enabled():
        # --sanitize / DINUNET_SANITIZE=1: keep the PLAIN jitted epoch (its
        # compile cache is introspectable; the AOT path compiles exactly once
        # by construction, so there is nothing to guard there) and check the
        # compile counter after every timed chain — a chain that recompiles
        # is measuring compilation, not the federated round.
        guard = CompileGuard({"epoch_fn": epoch_fn}, label=engine_name)
    else:
        # resident epoch inputs live in the layout the executable wants (the
        # per-epoch on-device relayout copy moves into this one-time
        # device_put)
        epoch_fn, put_x = compile_epoch_aot(epoch_fn, state0, x, y, w, live=live)
        x = put_x(x)

    def run_chain(k: int) -> float:
        t = chain_epochs(epoch_fn, state0, x, y, w, k, live=live)
        if guard is not None:
            guard.check(context=f"engine={engine_name}, chain={k} epochs")
        return t

    return run_chain, S * steps * B


def measure_tpu(fused_bidir: bool | None = None, repeats: int = 5,
                with_distribution: bool = False, fault_plan=None,
                dims: dict | None = None):
    run_chain, samples = _setup_epoch(fused_bidir=fused_bidir,
                                      fault_plan=fault_plan, dims=dims)
    run_chain(1)  # compile + lazy-runtime warmup
    # N paired observations per endpoint: contended windows last minutes, so
    # more samples raise the odds of catching an uncontended one; the pairs
    # also give the min/median/spread distribution the JSON now carries
    pairs = [
        (run_chain(TIMED_EPOCHS // 2 + 1), run_chain(TIMED_EPOCHS + 1))
        for _ in range(repeats)
    ]
    dist = marginal_distribution(pairs, TIMED_EPOCHS)
    # n_chips = 1: the folded site axis runs on one chip, so per-chip ==
    # absolute. value is None when every observation was contention-dominated
    # (throughput_stats unreliable gate).
    stats = throughput_stats(dist, samples)
    if with_distribution:
        return stats["value"], stats
    return stats["value"]


# rankDAD A/B arms (--ab-rankdad): the r6 levers against the r5 baseline and
# the dSGD ceiling. "warm" = warm-started subspaces (engine-state Ω, the
# default); "bf16-iter" = mixed-precision power iteration via the bf16 wire;
# "cold-f32" = the r5 behavior (stateless, f32 everything).
RANKDAD_AB_ARMS = {
    "dsgd-ceiling": ("dSGD", {}),
    "rankdad-cold-f32": ("rankDAD", dict(
        dad_reduction_rank=10, dad_num_pow_iters=5, dad_tol=1e-3,
        dad_warm_start=False)),
    "rankdad-warm-f32": ("rankDAD", dict(
        dad_reduction_rank=10, dad_num_pow_iters=5, dad_tol=1e-3,
        dad_warm_start=True)),
    "rankdad-warm-bf16-iter": ("rankDAD", dict(
        dad_reduction_rank=10, dad_num_pow_iters=5, dad_tol=1e-3,
        dad_warm_start=True, precision_bits="16")),
}


def measure_rankdad_ab(obs: int = 5, n: int = TIMED_EPOCHS,
                       dims: dict | None = None) -> list[dict]:
    """Paired interleaved A/B of the rankDAD levers (one JSON record per
    arm). All arms compile up front; observations interleave per round
    (:func:`interleaved_ab`)."""
    import jax

    chains = {}
    samples = None
    for arm, (engine, kw) in RANKDAD_AB_ARMS.items():
        chains[arm], samples = _setup_epoch(engine, kw, dims=dims)
        chains[arm](1)  # compile + warm up before any timing starts
    dists = interleaved_ab(chains, n, obs=obs)
    records = []
    for arm, dist in dists.items():
        engine, kw = RANKDAD_AB_ARMS[arm]
        rec = {
            "metric": "samples/sec/chip (ICA-LSTM federated round, interleaved A/B)",
            "arm": arm,
            "engine": engine,
            "engine_kw": kw,
            "sites": (dims or {}).get("sites", NUM_SITES),
            "backend": jax.default_backend(),
            "chain_epochs": n,
            "samples_per_sec": throughput_stats(dist, samples),
            "unit": "samples/sec/chip",
        }
        if dims:
            rec["dims"] = dims
        elif rec["samples_per_sec"]["value"] is not None:
            # flagship dims: the MFU model applies
            rec["mfu"] = round(
                rec["samples_per_sec"]["value"] * flops_per_sample()
                / V5E_BF16_PEAK_FLOPS, 4,
            )
        records.append(rec)
    return records


# fused power-iteration A/B arms (--ab-poweriter, r14): the Pallas kernel
# (ops/poweriter_pallas.py) against the legacy XLA loop, warm- and
# cold-started (cold runs the full dad_num_pow_iters trip count — the
# kernel's HBM-round-trip savings scale with trips), with the dSGD ceiling
# for scale. On CPU the kernel runs in interpret mode — the artifact records
# the kernel mode so a CPU number is never mistaken for a TPU one.
_DAD10 = dict(dad_reduction_rank=10, dad_num_pow_iters=5, dad_tol=1e-3)
POWERITER_AB_ARMS = {
    "dsgd-ceiling": ("dSGD", {}),
    "rankdad-warm-legacy": ("rankDAD", dict(
        _DAD10, dad_warm_start=True, fused_poweriter=False)),
    "rankdad-warm-fused": ("rankDAD", dict(
        _DAD10, dad_warm_start=True, fused_poweriter=True)),
    "rankdad-cold-legacy": ("rankDAD", dict(
        _DAD10, dad_tol=0.0, dad_warm_start=False, fused_poweriter=False)),
    "rankdad-cold-fused": ("rankDAD", dict(
        _DAD10, dad_tol=0.0, dad_warm_start=False, fused_poweriter=True)),
}


def _engine_ab_records(arms: dict, metric: str, obs: int, n: int,
                       dims: dict | None, extra=None) -> list[dict]:
    """Shared paired-interleaved engine A/B driver (the --ab-rankdad
    protocol): compile every arm up front, interleave observations, one JSON
    record per arm. ``extra(arm, rec)`` may decorate each record."""
    import jax

    chains = {}
    samples = None
    for arm, (engine, kw) in arms.items():
        chains[arm], samples = _setup_epoch(engine, kw, dims=dims)
        chains[arm](1)  # compile + warm up before any timing starts
    dists = interleaved_ab(chains, n, obs=obs)
    records = []
    for arm, dist in dists.items():
        engine, kw = arms[arm]
        rec = {
            "metric": metric,
            "arm": arm,
            "engine": engine,
            "engine_kw": kw,
            "sites": (dims or {}).get("sites", NUM_SITES),
            "backend": jax.default_backend(),
            "chain_epochs": n,
            "samples_per_sec": throughput_stats(dists[arm], samples),
            "unit": "samples/sec/chip",
        }
        if dims:
            rec["dims"] = dims
        elif rec["samples_per_sec"]["value"] is not None:
            rec["mfu"] = round(
                rec["samples_per_sec"]["value"] * flops_per_sample()
                / V5E_BF16_PEAK_FLOPS, 4,
            )
        if extra is not None:
            extra(arm, rec)
        records.append(rec)
    return records


def measure_poweriter_ab(obs: int = 5, n: int = TIMED_EPOCHS,
                         dims: dict | None = None) -> list[dict]:
    """Paired interleaved A/B of the fused power-iteration kernel
    (``--ab-poweriter``), one JSON record per arm."""
    import jax

    def extra(arm, rec):
        if "fused" in arm:
            rec["poweriter_kernel"] = (
                "pallas" if jax.default_backend() == "tpu"
                else "pallas-interpret"
            )
        elif "rankdad" in arm:
            rec["poweriter_kernel"] = "xla-legacy"

    return _engine_ab_records(
        POWERITER_AB_ARMS,
        "samples/sec/chip (ICA-LSTM federated round, fused power-iteration "
        "A/B)",
        obs, n, dims, extra=extra,
    )


def _flagship_params_template(engine_name: str, dims: dict | None):
    """The flagship parameter tree (shapes only matter), built ONCE — the
    wire-byte models are pure shape arithmetic over it, so per-arm byte
    figures never rebuild the arm's dataset/state."""
    import jax
    import jax.numpy as jnp

    from dinunet_implementations_tpu.trainer import init_train_state

    # sites/steps/batch don't shape the parameters — shrink them so the
    # template build never allocates the (multi-GB at flagship dims)
    # synthetic dataset just to read shapes
    tiny = {**(dims or {}), "sites": 1, "steps": 1, "batch": 1}
    d, task, engine, opt, np_x, _, _ = _flagship_arm(engine_name, None, tiny)
    state = init_train_state(
        task, engine, opt, jax.random.PRNGKey(0), jnp.asarray(np_x[0, 0]),
        num_sites=1,
    )
    return state.params


def measure_wirequant_ab(quants, obs: int = 5, n: int = TIMED_EPOCHS,
                         dims: dict | None = None,
                         engine_name: str = "dSGD") -> list[dict]:
    """Paired interleaved A/B of the wire-quantization codecs
    (``--wire-quant bf16,int8,fp8``) against the f32 wire, one JSON record
    per arm with the MODELED per-device wire bytes and the shrink vs f32 —
    the same figures S002 verifies against the traced program."""
    from dinunet_implementations_tpu.engines import make_engine
    from dinunet_implementations_tpu.telemetry.metrics import payload_bytes_of

    arms = {"wire-f32": (engine_name, {})}
    for q in quants:
        arms[f"wire-{q}"] = (engine_name, dict(wire_quant=q))
    params = _flagship_params_template(engine_name, dims)
    bytes_by_arm = {
        arm: int(payload_bytes_of(make_engine(e, **kw), params))
        for arm, (e, kw) in arms.items()
    }

    def extra(arm, rec):
        rec["wire_quant"] = arms[arm][1].get("wire_quant", "none")
        rec["wire_bytes_per_device_round"] = bytes_by_arm[arm]
        rec["wire_shrink_vs_f32"] = round(
            bytes_by_arm["wire-f32"] / max(bytes_by_arm[arm], 1), 2
        )

    return _engine_ab_records(
        arms,
        "samples/sec/chip (ICA-LSTM federated round, quantized-wire A/B)",
        obs, n, dims, extra=extra,
    )


def measure_attacks_ab(attack_plan, robust: str = "trimmed_mean",
                       obs: int = 5, n: int = TIMED_EPOCHS,
                       dims: dict | None = None,
                       engine_name: str = "dSGD") -> list[dict]:
    """Hostile-site A/B (``--attacks``, r17): three paired interleaved arms
    of the flagship federated round —

    - ``clean``            : no attack, legacy aggregation (the baseline);
    - ``attacked-open``    : the AttackPlan injected, defense OFF (the
      documented-degradation arm);
    - ``attacked-<robust>``: the same attack with the robust reducer + the
      anomaly reputation layer ON (the defense-cost arm — the gather
      reducers' wire/compute overhead is the throughput claim under test,
      and the loss trajectory is the robustness claim).

    Each record carries throughput stats, the final chained epoch's mean
    train loss (the quality signal: defense-off diverges, defense-on
    tracks clean), the plan JSON, and the robust-mode modeled per-device
    wire bytes (the figure S002 proves against the traced program). The
    AUC-level robustness gates live in tests/test_golden.py; this artifact
    records the measured arms a claim can cite.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dinunet_implementations_tpu.checks.sanitize import (
        CompileGuard,
        sanitize_enabled,
    )
    from dinunet_implementations_tpu.robustness.attacks import attack_window
    from dinunet_implementations_tpu.telemetry.metrics import payload_bytes_of
    from dinunet_implementations_tpu.trainer import (
        init_train_state,
        make_train_epoch_fn,
    )

    arm_specs = {
        "clean": (False, "none"),
        "attacked-open": (True, "none"),
        f"attacked-{robust}": (True, robust),
    }
    chains, states, fns, data, byte_model = {}, {}, {}, {}, {}
    samples = None
    for arm, (attacked, mode) in arm_specs.items():
        d, task, engine, opt, np_x, np_y, np_w = _flagship_arm(
            engine_name, dict(robust_agg=mode), dims
        )
        S, steps = d["sites"], d["steps"]
        x = jnp.asarray(
            np_x,
            dtype=jnp.bfloat16 if d["compute_dtype"] == "bfloat16" else None,
        )
        y, w = jnp.asarray(np_y), jnp.asarray(np_w)
        state0 = init_train_state(
            task, engine, opt, jax.random.PRNGKey(0), x[0, 0], num_sites=S,
            reputation=mode != "none",
        )
        fn = make_train_epoch_fn(
            task, engine, opt, mesh=None, local_iterations=1,
            attack_plan=attack_plan if attacked else None, robust_agg=mode,
        )
        am = (
            jnp.asarray(attack_window(attack_plan, S, 0, steps))
            if attacked else None
        )
        guard = (
            CompileGuard({"epoch_fn": fn}, label=arm)
            if sanitize_enabled() else None
        )

        def run_chain(k, fn=fn, state0=state0, x=x, y=y, w=w, am=am,
                      guard=guard, arm=arm):
            t = chain_epochs(fn, state0, x, y, w, k, live=None, attack=am)
            if guard is not None:
                guard.check(context=f"arm={arm}, chain={k} epochs")
            return t

        run_chain(1)  # compile + warm up before any timing starts
        chains[arm] = run_chain
        states[arm], fns[arm], data[arm] = state0, fn, (x, y, w, am)
        byte_model[arm] = int(payload_bytes_of(engine, state0.params))
        samples = S * steps * d["batch"]
    dists = interleaved_ab(chains, n, obs=obs)
    records = []
    for arm, (attacked, mode) in arm_specs.items():
        # quality probe: n chained TRAINING epochs, last epoch's mean loss —
        # the measured defense-on-tracks-clean / defense-off-diverges signal
        s = states[arm]
        x, y, w, am = data[arm]
        losses = None
        for _ in range(max(n, 2)):
            if am is not None:
                s, losses = fns[arm](s, x, y, w, None, am)
            else:
                s, losses = fns[arm](s, x, y, w)
        lv = np.asarray(losses)
        lv = lv[np.isfinite(lv)]
        rec = {
            "metric": "samples/sec/chip (ICA-LSTM federated round, "
                      "hostile-site A/B)",
            "arm": arm,
            "engine": engine_name,
            "attacked": attacked,
            "robust_agg": mode,
            "attacks": attack_plan.to_json(),
            "sites": (dims or {}).get("sites", NUM_SITES),
            "backend": jax.default_backend(),
            "chain_epochs": n,
            "samples_per_sec": throughput_stats(dists[arm], samples),
            "unit": "samples/sec/chip",
            "final_epoch_loss": (
                round(float(lv.mean()), 6) if lv.size else None
            ),
            "wire_bytes_per_device_round": byte_model[arm],
        }
        if dims:
            rec["dims"] = dims
        records.append(rec)
    return records


def measure_privacy_ab(dp_noise: float = 0.5, dp_clip: float = 1.0,
                       secure_mode: str = "mask", obs: int = 5,
                       n: int = TIMED_EPOCHS, dims: dict | None = None,
                       engine_name: str = "dSGD") -> list[dict]:
    """Privacy-plane A/B (``--dp-noise`` / ``--secure-agg``, r20): paired
    interleaved arms of the flagship federated round —

    - ``clean``        : the legacy program (the baseline);
    - ``dp``           : in-scan DP-SGD (clip ``dp_clip`` + ``dp_noise``·C
      Gaussian noise per site per round, privacy/dpsgd.py) — the
      mechanism-cost arm, with the RDP accountant's ``epsilon_final`` for
      the timed chain length recorded next to the throughput;
    - ``dp+secureagg`` : the same mechanism with the masked fixed-point
      wire on top at ``secure_mode`` ("mask", or "mask-nopads" — the
      verification arm — recorded VERBATIM in the record; "off" drops the
      arm). Without DP noise the masked arm runs standalone
      (``secureagg``).

    Each record carries throughput stats, the modeled per-device wire bytes
    (the figure S002 proves — int32 grid == f32 bytes for the masked
    arms), the spent ε at the recorded chain length, and the privacy knobs
    verbatim. The accuracy-floor gates live in tests/test_golden.py; this
    artifact records the measured arms a claim can cite
    (docs/bench_privacy_ab_r20.jsonl)."""
    import jax

    from dinunet_implementations_tpu.engines import make_engine
    from dinunet_implementations_tpu.privacy import (
        RdpAccountant,
        effective_noise_multiplier,
        sampling_fraction,
    )
    from dinunet_implementations_tpu.telemetry.metrics import payload_bytes_of

    from dinunet_implementations_tpu.privacy import secure_agg_enabled

    secure = secure_agg_enabled(secure_mode)  # validates the mode string
    dp_kw = dict(dp_clip=dp_clip, dp_noise_multiplier=dp_noise)
    arms = {"clean": ({}, {})}
    if dp_noise > 0:
        arms["dp"] = ({}, dp_kw)
        if secure:
            arms["dp+secureagg"] = ({"secure_agg": secure_mode}, dp_kw)
    elif secure:
        arms["secureagg"] = ({"secure_agg": secure_mode}, {})

    chains = {}
    samples = None
    byte_model = {}
    params = _flagship_params_template(engine_name, dims)  # arm-invariant
    for arm, (eng_kw, epoch_kw) in arms.items():
        chains[arm], samples = _setup_epoch(
            engine_name, eng_kw, dims=dims, epoch_kw=epoch_kw
        )
        chains[arm](1)  # compile + warm up before any timing starts
        byte_model[arm] = int(
            payload_bytes_of(make_engine(engine_name, **eng_kw), params)
        )
    dists = interleaved_ab(chains, n, obs=obs)
    d = dict(sites=NUM_SITES, steps=STEPS_PER_EPOCH, batch=BATCH_PER_SITE)
    d.update(dims or {})
    # the synthetic flagship pool: each site holds steps·batch examples and
    # each round consumes batch of them — the accountant's q for the arm
    q = sampling_fraction(d["batch"], 1, [d["steps"] * d["batch"]])
    records = []
    for arm, (eng_kw, epoch_kw) in arms.items():
        eps = None
        if epoch_kw.get("dp_noise_multiplier", 0) > 0:
            acct = RdpAccountant().step(
                effective_noise_multiplier(epoch_kw["dp_noise_multiplier"]),
                q, steps=n * d["steps"],
            )
            eps = round(acct.epsilon(1e-5)[0], 4)
        rec = {
            "metric": "samples/sec/chip (ICA-LSTM federated round, "
                      "privacy-plane A/B)",
            "arm": arm,
            "engine": engine_name,
            "dp_clip": epoch_kw.get("dp_clip", 0.0),
            "dp_noise_multiplier": epoch_kw.get("dp_noise_multiplier", 0.0),
            "secure_agg": eng_kw.get("secure_agg", "off"),
            "epsilon_final": eps,
            "dp_delta": 1e-5 if eps is not None else None,
            "sampling_fraction": round(q, 6),
            "sites": (dims or {}).get("sites", NUM_SITES),
            "backend": jax.default_backend(),
            "chain_epochs": n,
            "samples_per_sec": throughput_stats(dists[arm], samples),
            "unit": "samples/sec/chip",
            "wire_bytes_per_device_round": byte_model[arm],
        }
        if dims:
            rec["dims"] = dims
        records.append(rec)
    return records


def _setup_pipeline_arm(arm: str, dims: dict | None = None,
                        donate: bool = True):
    """One input-pipeline A/B arm (``--pipeline``): unlike the steady-state
    bench arms above (which pre-place the epoch inputs once), these chains
    model the TRAINER's per-epoch input path —

    - ``host``: the dense ``[S, steps, B, ...]`` epoch tensor is re-shipped
      to the device every epoch (cast to the compute dtype in flight), i.e.
      what FederatedTrainer's host pipeline pays each epoch;
    - ``device``: the inventory is uploaded once outside the timed region and
      each epoch ships only the ``[S, steps, B]`` int32 index plan; batches
      are gathered on-device inside the jitted epoch (trainer/steps.py
      ``pipeline="device"``), with the carried state donated.

    Returns ``(run_chain, samples_per_epoch, info)``; ``info`` carries
    ``transfer_bytes_per_epoch`` and a :class:`SpanTracer` whose ``feed``
    spans time the per-epoch host-blocked input path (plan build + transfer
    dispatch — the work the device waits on between fused epoch dispatches).
    The tracer replaced the hand-rolled ``host_s``/``epochs`` timer dict
    (telemetry/tracer.py is the one timing helper). Both arms run the plain
    jitted epoch (no AOT layouts) so the comparison isolates the input
    path."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dinunet_implementations_tpu.telemetry import SpanTracer
    from dinunet_implementations_tpu.trainer import (
        init_train_state,
        make_train_epoch_fn,
    )

    d, task, engine, opt, np_x, np_y, np_w = _flagship_arm(dims=dims)
    S, steps, B = d["sites"], d["steps"], d["batch"]
    dt = jnp.bfloat16 if d["compute_dtype"] == "bfloat16" else jnp.float32
    state0 = init_train_state(
        task, engine, opt, jax.random.PRNGKey(0), jnp.asarray(np_x[0, 0]),
        num_sites=S,
    )
    info = {"tracer": SpanTracer()}

    if arm == "host":
        epoch_fn = make_train_epoch_fn(
            task, engine, opt, mesh=None, local_iterations=1,
            pipeline="host", donate_state=donate,
        )

        def feed():
            with info["tracer"].span("feed"):
                return (jnp.asarray(np_x, dtype=dt), jnp.asarray(np_y),
                        jnp.asarray(np_w))

        info["transfer_bytes_per_epoch"] = (
            np_x.size * np.dtype(dt).itemsize + np_y.nbytes + np_w.nbytes
        )
    else:
        epoch_fn = make_train_epoch_fn(
            task, engine, opt, mesh=None, local_iterations=1,
            pipeline="device", donate_state=donate,
        )
        # inventory: each bench site owns exactly steps*B samples; uploaded
        # ONCE, outside the timed chains (what the trainer pays per fit)
        inv_x = jnp.asarray(np_x.reshape((S, steps * B) + np_x.shape[3:]),
                            dtype=dt)
        inv_y = jnp.asarray(np_y.reshape(S, steps * B))
        np_idx = np.broadcast_to(
            np.arange(steps * B, dtype=np.int32).reshape(1, steps, B),
            (S, steps, B),
        ).copy()

        def feed():
            with info["tracer"].span("feed"):
                return (inv_x, inv_y, jnp.asarray(np_idx))

        info["transfer_bytes_per_epoch"] = np_idx.nbytes

    from dinunet_implementations_tpu.checks.sanitize import (
        CompileGuard,
        sanitize_enabled,
    )

    guard = (
        CompileGuard({"epoch_fn": epoch_fn}, label=f"pipeline-{arm}")
        if sanitize_enabled() else None
    )

    def run_chain(k: int) -> float:
        # donation consumes the input state's buffers: every chain starts
        # from a fresh copy so state0 stays reusable across chains (the copy
        # is one epoch-state clone, amortized over the chain and cancelled by
        # the marginal estimator anyway)
        s = jax.tree.map(jnp.copy, state0) if donate else state0
        t0 = time.time()
        for _ in range(k):
            s, _ = epoch_fn(s, *feed())
        jax.tree.map(np.asarray, s)
        t = time.time() - t0
        if guard is not None:
            guard.check(context=f"pipeline={arm}, chain={k} epochs")
        return t

    return run_chain, S * steps * B, info


def measure_pipeline_ab(mode: str = "ab", obs: int = 5, n: int = TIMED_EPOCHS,
                        dims: dict | None = None,
                        donate: bool = True) -> list[dict]:
    """Input-pipeline A/B (``--pipeline host|device|ab``): one JSON record
    per arm with the throughput distribution plus the pipeline-specific
    fields — ``transfer_bytes_per_epoch`` (the per-epoch host→device bytes;
    the device arm ships index-plan bytes, not dataset bytes) and
    ``host_blocked_ms_per_epoch`` (measured host time building/shipping epoch
    inputs). Arms are interleaved per observation round like --ab-rankdad."""
    import jax

    arms = ("host", "device") if mode == "ab" else (mode,)
    chains, infos = {}, {}
    samples = None
    for arm in arms:
        chains[arm], samples, infos[arm] = _setup_pipeline_arm(
            arm, dims=dims, donate=donate
        )
        chains[arm](1)  # compile + warm up before any timing starts
        infos[arm]["tracer"].reset()  # exclude warmup from the feed stats
    if len(arms) == 2:
        dists = interleaved_ab(chains, n, obs=obs)
    else:
        pairs = [
            (chains[arms[0]](n // 2 + 1), chains[arms[0]](n + 1))
            for _ in range(obs)
        ]
        dists = {arms[0]: marginal_distribution(pairs, n)}
    records = []
    for arm in arms:
        info = infos[arm]
        rec = {
            "metric": "samples/sec/chip (ICA-LSTM federated round, "
                      "input-pipeline A/B)",
            "arm": f"pipeline-{arm}",
            "pipeline": arm,
            "sites": (dims or {}).get("sites", NUM_SITES),
            "backend": jax.default_backend(),
            "chain_epochs": n,
            "donate_state": donate,
            "transfer_bytes_per_epoch": int(info["transfer_bytes_per_epoch"]),
            "host_blocked_ms_per_epoch": round(
                1e3 * info["tracer"].total_seconds("feed")
                / max(info["tracer"].count("feed"), 1), 3
            ),
            "samples_per_sec": throughput_stats(dists[arm], samples),
            "unit": "samples/sec/chip",
        }
        if arm == "device" and "host" in infos:
            rec["transfer_reduction_vs_host"] = round(
                infos["host"]["transfer_bytes_per_epoch"]
                / max(info["transfer_bytes_per_epoch"], 1), 1,
            )
        if dims:
            rec["dims"] = dims
        elif rec["samples_per_sec"]["value"] is not None:
            rec["mfu"] = round(
                rec["samples_per_sec"]["value"] * flops_per_sample()
                / V5E_BF16_PEAK_FLOPS, 4,
            )
        records.append(rec)
    return records


def _ensure_host_devices(want: int) -> None:
    """Provision ``want`` virtual CPU devices for the sites-scaling sweep —
    BEFORE jax initializes (bench imports jax lazily inside the measure
    functions, so calling this first in main() is early enough). Only the
    host-platform device count is touched — never JAX_PLATFORMS — so an
    accelerator host (pinned or auto-detected) keeps its hardware mesh and
    the flag only takes effect where jax resolves to the CPU backend."""
    import os

    plat = os.environ.get("JAX_PLATFORMS", "")
    if plat and plat != "cpu":
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={want}"
        ).strip()


def _setup_packed_epoch(S: int, K: int, engine_name: str = "dSGD",
                        engine_kw: dict | None = None,
                        dims: dict | None = None, fault_plan=None,
                        staleness_bound: int = 0, attack_plan=None,
                        robust_agg: str = "none", slices: int = 1,
                        dcn_quant: str = "", epoch_kw: dict | None = None):
    """One sites-scaling arm: S virtual sites packed K per device on a real
    ``(site,)`` mesh — the full federated round as ONE compiled SPMD program
    with two-level aggregation (trainer/steps.py packed path). Epoch inputs
    and state are committed to their steady-state shardings up front, so the
    chains measure the round, not placement, and the program compiles
    exactly once (asserted under --sanitize).

    Returns ``(run_chain, samples_per_epoch, info)``; ``info`` records the
    mesh size and the per-device modeled wire bytes (the figure S002
    verifies against the traced program).

    ``fault_plan`` threads a liveness mask (drops / flaky / delay_at
    stragglers, robustness/faults.py) through the packed round — the churn
    smoke's arm; ``staleness_bound > 0`` additionally measures the
    staleness-bounded buffered-async round (trainer/steps.py, r13), where a
    straggling virtual site's buffered update keeps contributing at decayed
    weight. ``attack_plan`` + ``robust_agg`` (r17, robustness/attacks.py)
    compose on top: the CI hostile-site smoke measures the byzantine-
    attacked, robustly-aggregated packed round as one compiled program.

    ``slices > 1`` (r18) lays the three-tier ``(slice, site)`` topology over
    the same device set — the sweep then ALSO records the per-tier wire
    split (``ici_bytes_per_device_round`` vs ``dcn_bytes_per_slice_round``,
    the latter quantized by ``dcn_quant``; both figures are what the sliced
    semantic cells prove against the traced program)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dinunet_implementations_tpu.parallel.mesh import (
        packed_site_mesh,
        site_axis_of,
        sliced_site_mesh,
    )
    from dinunet_implementations_tpu.telemetry.metrics import (
        dcn_bytes_of,
        payload_bytes_of,
    )
    from dinunet_implementations_tpu.trainer import (
        init_train_state,
        make_train_epoch_fn,
    )
    from dinunet_implementations_tpu.trainer.steps import _state_specs

    if slices > 1:
        if S % slices:
            raise SystemExit(
                f"--slices {slices} must divide the site count ({S}) — "
                f"every slice holds the same number of virtual sites"
            )
        mesh = sliced_site_mesh(slices, S // slices, K)
    else:
        mesh = packed_site_mesh(S, K)
    site_part = site_axis_of(mesh)
    engine_kw = {**(engine_kw or {}), "robust_agg": robust_agg,
                 "dcn_wire_quant": dcn_quant}
    d, task, engine, opt, np_x, np_y, np_w = _flagship_arm(
        engine_name, engine_kw, {**(dims or {}), "sites": S}
    )
    x = jnp.asarray(
        np_x,
        dtype=jnp.bfloat16 if d["compute_dtype"] == "bfloat16" else None,
    )
    y, w = jnp.asarray(np_y), jnp.asarray(np_w)
    state0 = init_train_state(
        task, engine, opt, jax.random.PRNGKey(0), x[0, 0], num_sites=S,
        staleness_bound=staleness_bound,
        reputation=robust_agg != "none",
    )
    live = None
    if fault_plan is not None and fault_plan.injects_faults():
        # rounds == steps at local_iterations=1; the first epoch's window
        live = jnp.asarray(fault_plan.liveness(S, 0, d["steps"]))
    slice_live = None
    if (
        slices > 1 and fault_plan is not None
        and fault_plan.injects_slice_faults()
    ):
        # the r19 slice-tier chaos arm: throughput of the slice-masked
        # three-tier program (replicated mask, one program per pattern)
        slice_live = jnp.asarray(
            fault_plan.slice_liveness(slices, 0, d["steps"])
        )
    attack = None
    if attack_plan is not None and attack_plan.injects_attacks():
        from dinunet_implementations_tpu.robustness.attacks import (
            attack_window,
        )

        attack = jnp.asarray(attack_window(attack_plan, S, 0, d["steps"]))
    ici_bytes = int(payload_bytes_of(engine, state0.params, pack=K))
    info = {
        "mesh_devices": int(mesh.devices.size),
        "wire_bytes_per_device_round": ici_bytes,
        "ici_bytes_per_device_round": ici_bytes,
        # the per-slice inter-slice hop figure (0 on single-slice meshes)
        "dcn_bytes_per_slice_round": int(dcn_bytes_of(
            engine, state0.params, pack=K,
            sites_per_slice=S // max(slices, 1), slices=slices,
        )),
    }
    # commit everything to its steady-state sharding: inputs split over the
    # site tier(s) into [K, ...] device blocks, state to the epoch's own
    # specs (the trainer's _place_state move — avoids a warmup recompile)
    site_sh = NamedSharding(mesh, P(site_part))
    x, y, w = (jax.device_put(a, site_sh) for a in (x, y, w))
    if live is not None:
        live = jax.device_put(live, site_sh)
    if slice_live is not None:
        # replicated: every member reads its own slice's row (r19)
        slice_live = jax.device_put(slice_live, NamedSharding(mesh, P()))
    if attack is not None:
        # the attack mask rides after `live` positionally; live stays None
        # for attack-only runs — the same program form the runner CLI
        # compiles (chain_epochs passes live=None through)
        attack = jax.device_put(attack, site_sh)
    state0 = jax.tree.map(
        lambda a, spec: jax.device_put(a, NamedSharding(mesh, spec)),
        state0, _state_specs(state0, site_part),
    )
    epoch_fn = make_train_epoch_fn(
        task, engine, opt, mesh=mesh, local_iterations=1,
        staleness_bound=staleness_bound, attack_plan=attack_plan,
        robust_agg=robust_agg,
        # r20 privacy arms: dp_clip / dp_noise_multiplier via --dp-noise
        **(epoch_kw or {}),
    )

    from dinunet_implementations_tpu.checks.sanitize import (
        CompileGuard,
        sanitize_enabled,
    )

    guard = (
        CompileGuard(
            {"epoch_fn": epoch_fn},
            label=f"sites{S}-pack{K}" + (f"-slices{slices}" if slices > 1
                                         else ""),
        )
        if sanitize_enabled() else None
    )

    def run_chain(k: int) -> float:
        t = chain_epochs(epoch_fn, state0, x, y, w, k, live=live,
                         attack=attack, slice_live=slice_live)
        if guard is not None:
            guard.check(context=f"sites={S}, pack={K}, chain={k} epochs")
        return t

    return run_chain, S * d["steps"] * d["batch"], info


def measure_sites_scaling(sites_list, packs=None, obs: int = 3,
                          n: int = TIMED_EPOCHS, dims: dict | None = None,
                          engine_name: str = "dSGD",
                          engine_kw: dict | None = None, fault_plan=None,
                          staleness_bound: int = 0, attack_plan=None,
                          robust_agg: str = "none",
                          slices_list=None, dcn_quant: str = "",
                          epoch_kw: dict | None = None) -> list[dict]:
    """The sites-scaling sweep (``--sites``): for each virtual site count S,
    run the packed federated round on the available device mesh and emit one
    JSON record with ``sites`` / ``sites_per_chip`` / ``pack_factor`` — the
    proof that site count is no longer capped at device count. ``packs``
    gives an explicit pack factor per S; default picks the smallest K that
    divides S with an S/K-member site mesh fitting the device set (every
    device used when device_count divides S; e.g. 12 sites on 8 devices
    auto-pack K=2 onto a 6-member mesh).

    ``slices_list`` (r18, ``--slices``) crosses each S with the given slice
    counts on the three-tier ``(slice, site)`` topology: every record then
    carries ``slices`` / ``sites_per_slice`` and the per-TIER wire split —
    ``ici_bytes_per_device_round`` (unchanged by slicing: tiers 0+1 are the
    packed two-level reduce) vs ``dcn_bytes_per_slice_round`` (the
    inter-slice hop, quantized by ``dcn_quant``) with the codec's
    shrink-vs-f32 ratio, the figures the sliced semantic cells prove
    against traced operand shapes."""
    import jax

    def auto_pack(S: int, n_dev: int) -> int:
        k = max(-(-S // n_dev), 1)  # ceil: the densest packing that fits
        while S % k:  # walk up to the next divisor of S
            k += 1
        return k

    records = []
    n_dev = len(jax.devices())
    for i, S in enumerate(sites_list):
        K = packs[i] if packs is not None else auto_pack(S, n_dev)
        for slices in (slices_list or [1]):
            run_chain, samples, info = _setup_packed_epoch(
                S, K, engine_name=engine_name, engine_kw=engine_kw,
                dims=dims, fault_plan=fault_plan,
                staleness_bound=staleness_bound,
                attack_plan=attack_plan, robust_agg=robust_agg,
                slices=slices, dcn_quant=dcn_quant, epoch_kw=epoch_kw,
            )
            run_chain(1)  # compile + warm up outside the timing
            pairs = [
                (run_chain(n // 2 + 1), run_chain(n + 1)) for _ in range(obs)
            ]
            dist = marginal_distribution(pairs, n)
            rec = {
                "metric": "samples/sec (ICA-LSTM federated round, packed "
                          "sites-scaling sweep)",
                "engine": engine_name,
                "sites": S,
                "pack_factor": K,
                "sites_per_chip": K,
                "mesh_devices": info["mesh_devices"],
                "devices_available": n_dev,
                "wire_bytes_per_device_round":
                    info["wire_bytes_per_device_round"],
                "ici_bytes_per_device_round":
                    info["ici_bytes_per_device_round"],
                "backend": jax.default_backend(),
                "chain_epochs": n,
                "samples_per_sec": throughput_stats(dist, samples),
                "unit": "samples/sec (whole mesh)",
            }
            if slices_list is not None:
                rec.update(
                    slices=slices,
                    sites_per_slice=S // max(slices, 1),
                    dcn_bytes_per_slice_round=
                        info["dcn_bytes_per_slice_round"],
                )
                if slices > 1:
                    # codec shrink on the expensive hop: the same sliced
                    # topology's f32 (no-DCN-codec) figure over this one
                    from dinunet_implementations_tpu.engines import (
                        make_engine,
                    )
                    from dinunet_implementations_tpu.telemetry.metrics \
                        import dcn_bytes_of

                    base_kw = {
                        k: v for k, v in (engine_kw or {}).items()
                        if k not in ("wire_quant", "dcn_wire_quant")
                    }
                    ref = make_engine(
                        engine_name, robust_agg=robust_agg, **base_kw
                    )
                    params = _flagship_params_template(engine_name, dims)
                    f32 = dcn_bytes_of(
                        ref, params, pack=K,
                        sites_per_slice=S // slices, slices=slices,
                    )
                    if info["dcn_bytes_per_slice_round"]:
                        rec["dcn_shrink_vs_f32"] = round(
                            f32 / info["dcn_bytes_per_slice_round"], 3
                        )
                if dcn_quant:
                    rec["dcn_wire_quant"] = dcn_quant
            if engine_kw:
                rec["engine_kw"] = engine_kw
            if dims:
                rec["dims"] = {**dims, "sites": S}
            if fault_plan is not None:
                rec["faults"] = fault_plan.to_json()
                steps = (dims or {}).get("steps", STEPS_PER_EPOCH)
                rec["dead_site_rounds"] = int(
                    (fault_plan.liveness(S, 0, steps) == 0).sum()
                )
            if staleness_bound:
                rec["staleness_bound"] = staleness_bound
            if attack_plan is not None:
                rec["attacks"] = attack_plan.to_json()
            if robust_agg != "none":
                rec["robust_agg"] = robust_agg
            # r20 privacy composition (--sites --dp-noise / --secure-agg):
            # the sweep records the mechanism knobs + the spent ε for the
            # timed chain, next to the (S002-proven) wire figures
            sigma = (epoch_kw or {}).get("dp_noise_multiplier", 0.0)
            if sigma > 0:
                from dinunet_implementations_tpu.privacy import (
                    RdpAccountant,
                    effective_noise_multiplier,
                    sampling_fraction,
                )

                steps = (dims or {}).get("steps", STEPS_PER_EPOCH)
                batch = (dims or {}).get("batch", BATCH_PER_SITE)
                q = sampling_fraction(batch, 1, [steps * batch])
                rec["dp_clip"] = (epoch_kw or {}).get("dp_clip", 0.0)
                rec["dp_noise_multiplier"] = sigma
                rec["epsilon_final"] = round(
                    RdpAccountant()
                    .step(effective_noise_multiplier(sigma), q,
                          steps=n * steps)
                    .epsilon(1e-5)[0], 4,
                )
            if (engine_kw or {}).get("secure_agg", "off") != "off":
                rec["secure_agg"] = engine_kw["secure_agg"]
            records.append(rec)
    return records


def measure_cpu_baseline() -> float:
    """Live re-measurement of the torch reference (optional)."""
    import importlib.util

    import torch

    spec = importlib.util.spec_from_file_location(
        "ref_ica", "/root/reference/comps/icalstm/models.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    m = mod.ICALstm(input_size=ENC_OUT, hidden_size=HIDDEN, bidirectional=True,
                    num_cls=2, num_comps=COMPS, window_size=WLEN)
    opt = torch.optim.Adam(m.parameters(), lr=1e-3)
    crit = torch.nn.CrossEntropyLoss()
    B = 16
    x = torch.randn(B, WINDOWS, COMPS, WLEN)
    y = torch.randint(0, 2, (B,))
    for _ in range(2):
        opt.zero_grad(); out, _ = m(x); crit(out, y).backward(); opt.step()
    t = time.time()
    iters = 4
    for _ in range(iters):
        opt.zero_grad(); out, _ = m(x); crit(out, y).backward(); opt.step()
    return iters * B / (time.time() - t)


def _serving_setup(dims: dict | None):
    """Tiny shared builder for the serving arms: an unidirectional ICA-LSTM
    config (the streaming-capable flagship shape) + initialized params."""
    import jax
    import jax.numpy as jnp

    from dinunet_implementations_tpu.core.config import (
        NNComputation,
        TrainConfig,
    )
    from dinunet_implementations_tpu.runner.registry import get_task
    from dinunet_implementations_tpu.trainer.steps import FederatedTask

    d = dims or {}
    windows = d.get("windows", WINDOWS)
    comps = d.get("comps", COMPS)
    wlen = d.get("wlen", WLEN)
    cfg = TrainConfig(task_id=NNComputation.TASK_ICA).with_overrides({
        "ica_args": {
            "num_components": comps, "window_size": wlen,
            "temporal_size": windows * wlen, "window_stride": wlen,
            "input_size": d.get("enc_out", ENC_OUT),
            "hidden_size": d.get("hidden", HIDDEN),
            "bidirectional": False,
        },
    })
    task = FederatedTask(get_task(cfg.task_id).build_model(cfg))
    params, stats = task.init_variables(
        jax.random.PRNGKey(0), jnp.ones((2, windows, comps, wlen))
    )
    return cfg, task, params, stats, (windows, comps, wlen)


def measure_serving(requests: int = 100, dims: dict | None = None,
                    stream_T: int = 512, chunk: int = 8,
                    cache_dir: str | None = None):
    """The serving-path arms (r15), one JSON record each:

    - ``startup``: engine warmup (AOT-compiling every bucket executable)
      COLD vs against the PR 4 persistent compile cache the cold run just
      populated — the restart-time win the cache exists for;
    - ``batched``: a mixed-bucket request storm through the full
      submit→microbatch→executable path: p50/p95/p99 request latency,
      requests/s, samples/s, pad-waste %, bucket hit-rate, and the
      zero-compiles-after-warmup count;
    - ``stream-o1``: per-STEP latency of the streaming executable as a
      session's history grows 0 → ``stream_T`` timesteps (direct
      executable timing, admission delay excluded) — the O(1) claim is
      this curve being FLAT in history length;
    - ``recompute``: the alternative a session cache avoids — re-running
      the full batched forward over the whole prefix at T ∈ {8, 64,
      stream_T}: per-step cost of the recompute path grows with T (and
      each length needs its own compiled program).
    """
    import os
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np

    from dinunet_implementations_tpu.serving.engine import InferenceEngine
    from dinunet_implementations_tpu.serving.session import init_carry_table
    from dinunet_implementations_tpu.trainer.steps import eval_forward

    cfg, task, params, stats, (windows, comps, wlen) = _serving_setup(dims)
    cache = cache_dir or tempfile.mkdtemp(prefix="dinunet-serve-cache-")
    cfg = cfg.replace(compile_cache_dir=cache)
    backend = jax.default_backend()
    base = {
        "unit": None, "backend": backend,
        "dims": dims or {"windows": windows, "comps": comps, "wlen": wlen,
                         "enc_out": ENC_OUT, "hidden": HIDDEN},
    }

    def build(streaming=None):
        eng = InferenceEngine(
            cfg, params=params, batch_stats=stats,
            row_buckets=(1, 2, 4, 8), stream_buckets=(1, 4),
            stream_chunk=chunk, stream_slots=16, max_delay_ms=1.0,
            streaming=streaming,
        )
        eng.warmup()
        return eng

    # -- startup: cold compile vs persistent-cache-warm restart, on the
    # batched-only shape (a STREAMING engine bypasses the cache by design —
    # the donated-table/cache-deserialization runtime bug, see
    # serving/engine.py warmup — so the cache win is a batched-lane claim)
    cold = build(streaming=False)
    cold_s = cold.warmup_seconds
    cold.close()
    warm = build(streaming=False)  # same shapes: XLA compiles load from disk
    records = [{
        **base,
        "metric": "serving start time (AOT warmup, batched buckets, cold "
                  "vs persistent-compile-cache warm)",
        "arm": "startup", "unit": "seconds",
        "cold_start_s": cold_s, "cachewarm_start_s": warm.warmup_seconds,
        "speedup": round(cold_s / max(warm.warmup_seconds, 1e-9), 2),
        "compile_cache_dir": cache,
        "executables": len(warm._exec),
    }]
    warm.close()
    # drop the warm engine's CACHE-DESERIALIZED executables before any
    # streaming runs: a donated-table stream step with deserialized
    # executables alive in the process is the documented heap-corruption
    # condition (serving/engine.py warmup) — release them and collect so
    # the traffic arms stream against fresh-compiled code only
    del cold, warm
    import gc

    gc.collect()
    eng = build()  # the streaming engine serving the traffic arms

    try:
        # -- batched traffic: mixed request sizes over every bucket
        rng = np.random.default_rng(0)
        sizes = (1, 2, 3, 4, 8)
        futures = [
            eng.submit(rng.normal(
                size=(sizes[i % len(sizes)], windows, comps, wlen)
            ).astype(np.float32))
            for i in range(requests)
        ]
        for f in futures:
            f.result()
        s = eng.summary()
        records.append({
            **base,
            "metric": "serving request latency / throughput (batched lane)",
            "arm": "batched", "unit": "ms",
            "requests": s["requests"], "dispatches": s["dispatches"],
            "latency_ms_p50": s["latency_ms_p50"],
            "latency_ms_p95": s["latency_ms_p95"],
            "latency_ms_p99": s["latency_ms_p99"],
            "requests_per_s": s["requests_per_s"],
            "samples_per_s": s["samples_per_s"],
            "pad_waste_pct": s["pad_waste_pct"],
            "bucket_hit_rate": s["bucket_hit_rate"],
            "compiles_after_warmup": s["compiles_after_warmup"],
        })

        # -- streaming O(1): per-step executable latency vs session history
        exec1 = eng._exec[("stream", 1)]
        a = cfg.ica_args
        n_chunks = stream_T // chunk
        x = rng.normal(size=(1, chunk, comps, wlen)).astype(np.float32)
        sv = np.ones((1, chunk), np.float32)
        valid = np.ones((1,), np.float32)
        per_chunk = [float("inf")] * n_chunks
        for _ in range(3):  # least-contended minimum per position
            table = jax.device_put(init_carry_table(16, a.hidden_size))
            fresh = np.ones((1,), np.float32)
            for i in range(n_chunks):
                t0 = time.perf_counter()
                probs, table = exec1(
                    params, stats, table, np.zeros((1,), np.int32),
                    fresh, jnp.asarray(x), jnp.asarray(sv),
                    jnp.asarray(valid),
                )
                np.asarray(probs)
                per_chunk[i] = min(
                    per_chunk[i], time.perf_counter() - t0
                )
                fresh = np.zeros((1,), np.float32)
        early = per_chunk[0] / chunk
        late = per_chunk[-1] / chunk
        records.append({
            **base,
            "metric": "streaming per-step latency vs session history "
                      "(O(1) session cache)",
            "arm": "stream-o1", "unit": "ms/step",
            "chunk_windows": chunk, "history_steps": stream_T,
            "per_step_ms_at_T%d" % chunk: round(1e3 * early, 4),
            "per_step_ms_at_T%d" % stream_T: round(1e3 * late, 4),
            "flatness_ratio": round(late / max(early, 1e-12), 3),
        })

        # -- the counterfactual: full-prefix recompute per new chunk
        recompute = {}
        for T in sorted({chunk, 64, stream_T}):
            xt = jnp.asarray(rng.normal(
                size=(1, T, comps, wlen)
            ).astype(np.float32))
            w1 = jnp.ones((1,), jnp.float32)
            fn = jax.jit(
                lambda p, s, xx, ww: eval_forward(task, p, s, xx, None, ww)
            )
            np.asarray(fn(params, stats, xt, w1))  # compile (one per T!)
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                np.asarray(fn(params, stats, xt, w1))
                best = min(best, time.perf_counter() - t0)
            recompute[str(T)] = {
                "full_ms": round(1e3 * best, 4),
                "per_step_ms": round(1e3 * best / T, 4),
            }
        records.append({
            **base,
            "metric": "full-sequence recompute cost vs prefix length "
                      "(what the session cache avoids)",
            "arm": "recompute", "unit": "ms", "per_T": recompute,
        })
    finally:
        eng.close()
    return records


def measure_fleet(replicas_list=(1, 2, 4), requests: int = 120,
                  swaps: int = 4, dims: dict | None = None,
                  devices=None, topology: dict | None = None):
    """The serving-fleet arms (r21, serving/fleet.py + publish.py):

    - ``fleet-scale`` (one record per replica count): the same mixed-bucket
      request storm against a ReplicaSet of 1 → 2 → 4 replicas (one engine
      per virtual device) — aggregate requests/s (the scale-out claim),
      per-replica request occupancy (the least-loaded router spreading
      work), and per-replica bucket hit-rate;
    - ``fleet-swap`` (at the largest replica count): K donated hot-swaps
      fired INTO live traffic — per-swap pause (max across replicas, the
      publish-window figure), its p99 across the K publishes, and the p99
      request latency of the swap-storm window vs the steady window before
      it (``LogHistogram.delta`` between merged-bus snapshots), plus the
      fleet-wide compiles-after-warmup count proving the guard held
      through every publish.

    ``devices``/``topology`` (r22): ``--slices S --pack K`` composes with
    the fleet arms — the emulated pod is sized to S slice-bands of K
    devices, replicas are pinned slice-major over the bands (replica i on
    band i % S, so replicas spread ACROSS slices before doubling up
    within one), and every record carries the active topology so a
    reader can tell a 4-replica/1-slice row from a 4-replica/4-slice
    one.
    """
    import jax
    import numpy as np

    from dinunet_implementations_tpu.serving.fleet import ReplicaSet
    from dinunet_implementations_tpu.telemetry.bus import MetricsBus

    cfg, task, params, stats, (windows, comps, wlen) = _serving_setup(dims)
    backend = jax.default_backend()
    base = {
        "unit": None, "backend": backend,
        "dims": dims or {"windows": windows, "comps": comps, "wlen": wlen,
                         "enc_out": ENC_OUT, "hidden": HIDDEN},
        "topology": topology or {
            "slices": 1,
            "devices": len(devices) if devices else len(jax.devices()),
        },
    }
    rng = np.random.default_rng(0)
    sizes = (1, 2, 3, 4, 8)

    def storm(fleet, n):
        t0 = time.perf_counter()
        futures = [
            fleet.submit(rng.normal(
                size=(sizes[i % len(sizes)], windows, comps, wlen)
            ).astype(np.float32))
            for i in range(n)
        ]
        for f in futures:
            f.result()
        return time.perf_counter() - t0

    records = []
    for n_replicas in replicas_list:
        bus = MetricsBus()
        fleet = ReplicaSet(
            cfg, replicas=n_replicas, params=params, batch_stats=stats,
            bus=bus, row_buckets=(1, 2, 4, 8), streaming=False,
            max_delay_ms=1.0, devices=devices,
        )
        fleet.warmup()
        try:
            elapsed = storm(fleet, requests)
            parts = [
                e.summary() for e in fleet._engines if e is not None
            ]
            records.append({
                **base,
                "metric": "fleet aggregate throughput / per-replica "
                          "occupancy vs replica count",
                "arm": "fleet-scale", "unit": "req/s",
                "replicas": n_replicas,
                "requests": requests,
                "requests_per_s": round(requests / elapsed, 2),
                "per_replica_requests": [p["requests"] for p in parts],
                "per_replica_bucket_hit_rate": [
                    p["bucket_hit_rate"] for p in parts
                ],
                "compiles_after_warmup": sum(
                    p["compiles_after_warmup"] for p in parts
                ),
            })
        finally:
            fleet.close()

    # -- hot-swap under load, at the largest fleet
    n_replicas = max(replicas_list)
    bus = MetricsBus()
    fleet = ReplicaSet(
        cfg, replicas=n_replicas, params=params, batch_stats=stats,
        bus=bus, row_buckets=(1, 2, 4, 8), streaming=False,
        max_delay_ms=1.0,
    )
    fleet.warmup()
    try:
        storm(fleet, requests)  # steady window
        steady = bus.merged_histogram("serving_request_latency_ms")
        pauses = []
        per_swap = max(requests // max(swaps, 1), len(sizes))
        for k in range(swaps):
            futures = [
                fleet.submit(rng.normal(
                    size=(sizes[i % len(sizes)], windows, comps, wlen)
                ).astype(np.float32))
                for i in range(per_swap)
            ]
            cand = jax.tree.map(
                lambda x, _k=k: np.asarray(x) + 1e-4 * (_k + 1), params
            )
            pauses.append(fleet.swap_params(cand, stats)["pause_ms"])
            for f in futures:
                f.result()
        swap_hist = bus.merged_histogram(
            "serving_request_latency_ms"
        ).delta(steady)
        fleet.assert_no_compiles()
        records.append({
            **base,
            "metric": "hot-swap pause and in-swap request latency vs "
                      "steady (donated publish under load)",
            "arm": "fleet-swap", "unit": "ms",
            "replicas": n_replicas, "swaps": swaps,
            "swap_pause_ms_p99": round(
                sorted(pauses)[max(int(0.99 * len(pauses)) - 1, 0)], 4
            ),
            "swap_pause_ms_max": round(max(pauses), 4),
            "steady_latency_ms_p99": steady.quantile(0.99),
            "in_swap_latency_ms_p99": swap_hist.quantile(0.99),
            "compiles_after_warmup": 0,  # assert_no_compiles passed
        })
    finally:
        fleet.close()
    return records


def measure_tenants(tenants: int = 2, pod_slices: int = 2,
                    epochs: int = 6, gap_s: float = 3.0):
    """The fleet-scheduler goodput arms (r22, runner/scheduler.py): K
    identical studies, each with a mid-study quorum gap (every site
    leaves after a staggered epoch mark and rejoins ``gap_s``
    wall-seconds later — the cohort-turnover shape real federations
    idle through), run two ways on the SAME emulated pod:

    - ``tenants-serialized``: one study at a time, each on its own
      scheduler — the pod idles through every gap (the status-quo cost
      of running studies back to back);
    - ``tenants-concurrent``: all K studies on ONE scheduler — weighted
      fair share packs them onto the pod, a holding tenant's slices are
      reclaimed via checkpoint-then-yield, and every gap is overlapped
      by the other tenants' training.

    Records aggregate samples/s, BOTH arms' slice-idle fraction, the
    preemption pause p99 (exit-clean checkpoint on yield + msgpack
    reload on resume) and the per-tenant fairness ratio (min/max busy
    slice-seconds per unit weight) — docs/bench_tenants_r22.jsonl.
    """
    import os
    import tempfile

    import jax
    import numpy as np

    from dinunet_implementations_tpu.core.config import (
        FSArgs, TrainConfig,
    )
    from dinunet_implementations_tpu.data.demo import make_fs_demo_tree
    from dinunet_implementations_tpu.runner.fed_runner import (
        discover_site_dirs,
    )
    from dinunet_implementations_tpu.runner.scheduler import (
        FleetScheduler, TenantSpec,
    )
    from dinunet_implementations_tpu.telemetry.bus import MetricsBus

    work = tempfile.mkdtemp(prefix="bench_tenants_")
    n_sites, subjects, feat = 4, 32, 8

    def spec_for(i: int) -> TenantSpec:
        tree = os.path.join(work, f"tree{i}")
        if not os.path.isdir(tree):
            make_fs_demo_tree(tree, n_sites=n_sites, subjects=subjects,
                              n_features=feat, seed=i)
        cfg = TrainConfig(
            task_id="FS-Classification", batch_size=4,
            staleness_bound=2, num_slices=pod_slices,
            fs_args=FSArgs(input_size=feat, hidden_sizes=(8,)),
        )
        return TenantSpec(
            tenant=f"study{i}", data_path=tree, config=cfg,
            capacity=n_sites, inventory_rows=subjects + 16,
            max_epochs=epochs,
        )

    def gap_after(i: int) -> int:
        # staggered gap marks: tenant i holds after a different epoch,
        # so the concurrent arm's gaps overlap training, not each other
        return max(1, (epochs // (tenants + 1)) * (i + 1))

    def seed_gap(sched, spec: TenantSpec) -> dict:
        t = sched.tenants[spec.tenant]
        dirs = discover_site_dirs(spec.data_path)
        g = gap_after(int(spec.tenant.removeprefix("study")))
        for j in range(len(dirs)):
            path = os.path.join(t.spool_dir, f"gap{j:03d}.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump({"event": "leave", "site": f"local{j}",
                           "after_epoch": g}, fh)
            os.replace(tmp, path)
        # rejoin events must carry each site's config overrides
        # (labels_file / columns, from the tree's inputspec) exactly as
        # the pre-join admission recorded them — a bare join can't load
        # the site's covariates
        return {"tenant": spec.tenant, "t0": None, "rejoined": False,
                "rejoin": [
                    (f"local{j}", d,
                     dict(t.daemon._overrides.get(f"local{j}", {})))
                    for j, d in enumerate(dirs)
                ]}

    def drive(sched, gaps: list) -> float:
        t0 = time.monotonic()
        deadline = t0 + 600.0
        while not sched.done() and time.monotonic() < deadline:
            sched.tick()
            now = time.monotonic()
            for gap in gaps:
                t = sched.tenants[gap["tenant"]]
                if t.status != "active" or gap["rejoined"]:
                    continue
                if gap["t0"] is None and not t.daemon.trainable() \
                        and t.daemon.epochs_run >= 1:
                    gap["t0"] = now  # the hold was observed: clock it
                elif gap["t0"] is not None and now - gap["t0"] >= gap_s:
                    for j, (site, d, conf) in enumerate(gap["rejoin"]):
                        path = os.path.join(
                            t.spool_dir, f"zz_rejoin{j:03d}.json"
                        )
                        tmp = path + ".tmp"
                        with open(tmp, "w") as fh:
                            json.dump({"event": "join", "site": site,
                                       "data_dir": d, "config": conf},
                                      fh)
                        os.replace(tmp, path)
                    gap["rejoined"] = True
        return time.monotonic() - t0

    def samples_per_epoch(t) -> int:
        rows = t.daemon._rows or 10 ** 9
        return sum(
            min(len(v), rows) for v in t.daemon._data.values()
        )

    base = {
        "backend": jax.default_backend(), "tenants": tenants,
        "pod_slices": pod_slices, "epochs_per_study": epochs,
        "gap_s": gap_s, "unit": "samples/s",
        "metric": "aggregate training throughput: K gap-interrupted "
                  "studies serialized vs scheduled-concurrent on one "
                  "emulated pod",
    }
    records = []

    # -- serialized arm: one study at a time, pod idles through gaps
    ser_wall = ser_busy = ser_samples = 0.0
    ser_pauses: list = []
    for i in range(tenants):
        sched = FleetScheduler(
            os.path.join(work, f"solo{i}"), pod_slices=pod_slices,
            bus=MetricsBus(), poll_s=0.02, verbose=False,
        )
        spec = spec_for(i)
        sched.register(spec)
        gaps = [seed_gap(sched, spec)]
        wall = drive(sched, gaps)
        t = sched.tenants[spec.tenant]
        ser_samples += t.daemon.epochs_run * samples_per_epoch(t)
        ser_pauses.extend(t.pauses_ms)
        gp = sched.goodput()
        ser_wall += wall
        ser_busy += gp["busy_slice_s"]
        sched.close()
    ser_idle = round(1.0 - ser_busy / (pod_slices * ser_wall), 4)
    records.append({
        **base, "arm": "tenants-serialized",
        "wall_s": round(ser_wall, 3),
        "samples_per_s": round(ser_samples / ser_wall, 2),
        "slice_idle_fraction": ser_idle,
        "preempt_pause_ms_p99": (
            round(float(np.percentile(ser_pauses, 99)), 3)
            if ser_pauses else 0.0
        ),
    })

    # -- concurrent arm: all K studies on ONE scheduler
    sched = FleetScheduler(
        os.path.join(work, "packed"), pod_slices=pod_slices,
        bus=MetricsBus(), poll_s=0.02, verbose=False,
    )
    specs = [spec_for(i) for i in range(tenants)]
    gaps = []
    for spec in specs:
        sched.register(spec)
        gaps.append(seed_gap(sched, spec))
    conc_wall = drive(sched, gaps)
    conc_samples = sum(
        sched.tenants[s.tenant].daemon.epochs_run
        * samples_per_epoch(sched.tenants[s.tenant])
        for s in specs
    )
    conc_pauses = [
        p for s in specs for p in sched.tenants[s.tenant].pauses_ms
    ]
    gp = sched.goodput()
    per_tenant = [
        gp["busy_slice_s_per_tenant"][s.tenant] / max(s.weight, 1e-9)
        for s in specs
    ]
    fairness = (
        round(min(per_tenant) / max(per_tenant), 4)
        if min(per_tenant) > 0 else 0.0
    )
    sched.close()
    conc_rate = conc_samples / conc_wall
    records.append({
        **base, "arm": "tenants-concurrent",
        "wall_s": round(conc_wall, 3),
        "samples_per_s": round(conc_rate, 2),
        "slice_idle_fraction": round(
            1.0 - gp["busy_slice_s"] / (pod_slices * conc_wall), 4
        ),
        "preempt_pause_ms_p99": (
            round(float(np.percentile(conc_pauses, 99)), 3)
            if conc_pauses else 0.0
        ),
        "preempt_count": gp["preempt_count"],
        "fairness_ratio": fairness,
        "epochs": gp["epochs"],
        "speedup_vs_serialized": round(
            conc_rate / (ser_samples / ser_wall), 3
        ),
    })
    return records


SMALL_DIMS = dict(sites=32, steps=2, batch=4, windows=6, comps=8, wlen=4,
                  enc_out=16, hidden=16, compute_dtype="bfloat16")


def main():
    if "--sanitize" in sys.argv:
        # runtime sanitizer (dinunet_implementations_tpu/checks/sanitize.py):
        # compile-counter guard over the bench's epoch program — same env
        # contract as the trainer CLI: the explicit flag WINS over any
        # DINUNET_SANITIZE value left in the shell (incl. "0")
        import os

        os.environ["DINUNET_SANITIZE"] = "compile"
    if "--tenants" in sys.argv:
        # fleet-scheduler goodput arms (r22, runner/scheduler.py): K
        # gap-interrupted studies serialized vs scheduled-concurrent on
        # the same emulated pod (docs/bench_tenants_r22.jsonl; regen on
        # TPU with the same command, e.g. `--tenants 2`)
        tenants = int(sys.argv[sys.argv.index("--tenants") + 1])
        pod_slices = (int(sys.argv[sys.argv.index("--pod-slices") + 1])
                      if "--pod-slices" in sys.argv else 2)
        n = (int(sys.argv[sys.argv.index("--epochs") + 1])
             if "--epochs" in sys.argv else 6)
        gap_s = (float(sys.argv[sys.argv.index("--gap-s") + 1])
                 if "--gap-s" in sys.argv else 3.0)
        _ensure_host_devices(8)
        for rec in measure_tenants(
            tenants=tenants, pod_slices=pod_slices, epochs=n,
            gap_s=gap_s,
        ):
            print(json.dumps(rec), flush=True)
        return
    if "--serve" in sys.argv:
        # serving-path arms (r15, serving/): AOT warmup cold vs
        # compile-cache-warm, mixed-bucket request latency/throughput
        # through the continuous microbatcher, streaming per-step flatness
        # vs session history (the O(1) session-cache claim), and the
        # full-prefix recompute counterfactual. One JSON line per arm
        # (docs/bench_serving_r15.jsonl; regen on TPU with the same command).
        requests = (int(sys.argv[sys.argv.index("--requests") + 1])
                    if "--requests" in sys.argv else 100)
        stream_T = (int(sys.argv[sys.argv.index("--stream-t") + 1])
                    if "--stream-t" in sys.argv else 512)
        dims = SMALL_DIMS if "--small" in sys.argv else None
        if "--replicas" in sys.argv or "--swap" in sys.argv:
            # fleet arms (r21): `--serve --replicas 1,2,4 --swap 4` — the
            # ReplicaSet scale-out sweep plus hot-swaps under load
            # (docs/bench_fleet_r21.jsonl; regen on TPU, same command).
            # Replicas need distinct devices: size the virtual CPU mesh.
            replicas_list = tuple(
                int(r) for r in (
                    sys.argv[sys.argv.index("--replicas") + 1].split(",")
                    if "--replicas" in sys.argv else ("1", "2", "4")
                )
            )
            swaps = (int(sys.argv[sys.argv.index("--swap") + 1])
                     if "--swap" in sys.argv else 4)
            # --slices/--pack compose with the fleet arms (r22): the
            # emulated pod is S slice-bands of K devices and replicas
            # pin slice-major across the bands; every row records the
            # active topology (previously these flags were silently
            # ignored in the fleet branch)
            devices = topology = None
            if "--slices" in sys.argv:
                slices = int(sys.argv[sys.argv.index("--slices") + 1])
                pack = (int(sys.argv[sys.argv.index("--pack") + 1])
                        if "--pack" in sys.argv else 1)
                _ensure_host_devices(max(slices * pack,
                                         max(replicas_list)))
                import jax

                devs = jax.devices()[:slices * pack]
                bands = [devs[b * pack:(b + 1) * pack]
                         for b in range(slices)]
                devices = [bands[b][j] for j in range(pack)
                           for b in range(slices)]
                topology = {"slices": slices, "devices_per_slice": pack,
                            "placement": "slice-major"}
            else:
                _ensure_host_devices(max(replicas_list))
            for rec in measure_fleet(
                replicas_list=replicas_list, requests=requests,
                swaps=swaps, dims=dims, devices=devices,
                topology=topology,
            ):
                print(json.dumps(rec), flush=True)
            return
        for rec in measure_serving(
            requests=requests, dims=dims, stream_T=stream_T,
        ):
            print(json.dumps(rec), flush=True)
        return
    if "--slices" in sys.argv and "--sites" not in sys.argv:
        raise SystemExit(
            "--slices composes with the --sites sweep (e.g. "
            "`--sites 128,512 --slices 1,2,4`); give a site count to "
            "spread over the slices"
        )
    if "--sites" in sys.argv:
        # sites-scaling sweep: S virtual sites packed K per device on a real
        # site mesh (two-level aggregation, trainer/steps.py), one JSON line
        # per S — e.g. `--sites 8,32,128,512 --small` proves 512 sites train
        # on an 8-device virtual CPU mesh in one compiled program
        # (docs/bench_sites_scaling_r12.jsonl; regen on TPU with the same
        # command). `--pack auto` (default) packs every device; an explicit
        # comma list pins K per S. `--devices N` sizes the virtual CPU mesh
        # (ignored when a real accelerator platform is pinned).
        want = (int(sys.argv[sys.argv.index("--devices") + 1])
                if "--devices" in sys.argv else 8)
        _ensure_host_devices(want)
        sites_list = [
            int(s) for s in sys.argv[sys.argv.index("--sites") + 1].split(",")
        ]
        packs = None
        if "--pack" in sys.argv:
            raw = sys.argv[sys.argv.index("--pack") + 1]
            if raw != "auto":
                packs = [int(p) for p in raw.split(",")]
                if len(packs) == 1:
                    packs = packs * len(sites_list)
        obs = int(sys.argv[sys.argv.index("--obs") + 1]) if "--obs" in sys.argv else 3
        n = (int(sys.argv[sys.argv.index("--epochs") + 1])
             if "--epochs" in sys.argv else TIMED_EPOCHS)
        dims = SMALL_DIMS if "--small" in sys.argv else None
        engine_name = (sys.argv[sys.argv.index("--engine") + 1]
                       if "--engine" in sys.argv else "dSGD")
        # quantized wires compose with the packed sweep (r14): the sweep's
        # wire_bytes_per_device_round then records the codec-grid bytes —
        # the CI int8 packed smoke rides this path
        engine_kw = None
        if "--wire-quant" in sys.argv:
            wq = sys.argv[sys.argv.index("--wire-quant") + 1]
            if "," in wq:
                # the comma-list syntax belongs to the standalone A/B mode;
                # the composed sweep runs ONE codec per invocation
                raise SystemExit(
                    f"--sites composes with a single --wire-quant codec, "
                    f"got {wq!r} (run one sweep per codec)"
                )
            engine_kw = {"wire_quant": wq}
        # churn smoke composition (r13): `--faults` threads a liveness mask
        # (drops / delay_at stragglers) through the PACKED round, and
        # `--staleness N` switches it to the buffered-async aggregation —
        # one compiled program either way (asserted under --sanitize)
        plan = None
        if "--faults" in sys.argv:
            from dinunet_implementations_tpu.robustness import parse_fault_plan

            plan = parse_fault_plan(sys.argv[sys.argv.index("--faults") + 1])
        staleness = (int(sys.argv[sys.argv.index("--staleness") + 1])
                     if "--staleness" in sys.argv else 0)
        # hostile-site composition (r17): `--attacks` threads the byzantine
        # code mask through the packed round and `--robust-agg` switches the
        # engines to robust aggregation — the CI hostile smoke's path; the
        # CompileGuard asserts one compiled program for the attacked,
        # defended, packed chain
        attack = None
        if "--attacks" in sys.argv:
            from dinunet_implementations_tpu.robustness import (
                parse_attack_plan,
            )

            attack = parse_attack_plan(
                sys.argv[sys.argv.index("--attacks") + 1]
            )
        robust = (sys.argv[sys.argv.index("--robust-agg") + 1]
                  if "--robust-agg" in sys.argv else "none")
        # multi-slice composition (r18): `--slices 1,2,4` crosses each S
        # with the three-tier (slice, site) topology — records gain the
        # per-tier wire split (ici vs dcn bytes + codec shrink). The DCN
        # codec follows --wire-quant unless --dcn-wire-quant overrides it
        # (TrainConfig.dcn_wire_quant semantics). The CI multislice smoke
        # rides this path: --slices 2 --sites 64 --pack 8 --wire-quant int8.
        slices_list = None
        if "--slices" in sys.argv:
            slices_list = [
                int(s)
                for s in sys.argv[sys.argv.index("--slices") + 1].split(",")
            ]
        dcn_quant = (sys.argv[sys.argv.index("--dcn-wire-quant") + 1]
                     if "--dcn-wire-quant" in sys.argv else "")
        # privacy composition (r20): `--dp-noise SIGMA [--dp-clip C]`
        # threads in-scan DP-SGD through the packed round (records gain
        # the mechanism knobs + epsilon_final) and `--secure-agg MODE`
        # switches the engine to the masked fixed-point wire — the CI
        # privacy smoke's path, one compiled program under --sanitize
        epoch_kw = None
        if "--dp-noise" in sys.argv:
            epoch_kw = {
                "dp_noise_multiplier": float(
                    sys.argv[sys.argv.index("--dp-noise") + 1]
                ),
                "dp_clip": (
                    float(sys.argv[sys.argv.index("--dp-clip") + 1])
                    if "--dp-clip" in sys.argv else 1.0
                ),
            }
        if "--secure-agg" in sys.argv:
            engine_kw = {
                **(engine_kw or {}),
                "secure_agg": sys.argv[sys.argv.index("--secure-agg") + 1],
            }
        for rec in measure_sites_scaling(
            sites_list, packs=packs, obs=obs, n=n, dims=dims,
            engine_name=engine_name, engine_kw=engine_kw, fault_plan=plan,
            staleness_bound=staleness, attack_plan=attack,
            robust_agg=robust, slices_list=slices_list, dcn_quant=dcn_quant,
            epoch_kw=epoch_kw,
        ):
            print(json.dumps(rec), flush=True)
        return
    baseline = CPU_BASELINE_SAMPLES_PER_SEC
    if "--live-baseline" in sys.argv:
        try:
            baseline = measure_cpu_baseline()
        except Exception:
            pass
    if "--ab-rankdad" in sys.argv:
        # paired interleaved A/B of the rankDAD levers, one JSON line per
        # arm (≥5 observations each; see docs/bench_rankdad_ab_r6.jsonl).
        # --small shrinks the model to harness-validation dims (records the
        # dims + backend so the artifact cannot be mistaken for a TPU
        # flagship number).
        obs = int(sys.argv[sys.argv.index("--obs") + 1]) if "--obs" in sys.argv else 5
        n = (int(sys.argv[sys.argv.index("--epochs") + 1])
             if "--epochs" in sys.argv else TIMED_EPOCHS)
        dims = SMALL_DIMS if "--small" in sys.argv else None
        for rec in measure_rankdad_ab(obs=obs, n=n, dims=dims):
            print(json.dumps(rec), flush=True)
        return
    if "--ab-poweriter" in sys.argv:
        # paired interleaved A/B of the fused Pallas power-iteration kernel
        # against the legacy XLA loop (r14; same protocol as --ab-rankdad).
        # On CPU the kernel runs in interpret mode and the records say so —
        # regen on TPU with the same command for the flagship numbers.
        obs = int(sys.argv[sys.argv.index("--obs") + 1]) if "--obs" in sys.argv else 5
        n = (int(sys.argv[sys.argv.index("--epochs") + 1])
             if "--epochs" in sys.argv else TIMED_EPOCHS)
        dims = SMALL_DIMS if "--small" in sys.argv else None
        for rec in measure_poweriter_ab(obs=obs, n=n, dims=dims):
            print(json.dumps(rec), flush=True)
        return
    if "--wire-quant" in sys.argv:
        # quantized-wire A/B (r14): the listed codecs (comma list from
        # {bf16,int8,fp8}) against the f32 wire, paired interleaved; each
        # record carries the MODELED per-device wire bytes + shrink-vs-f32
        # that checks/semantic.py S002 proves against the traced program.
        # (With --sites this flag instead threads the codec into the packed
        # sweep — handled above.)
        quants = [
            q for q in
            sys.argv[sys.argv.index("--wire-quant") + 1].split(",") if q
        ]
        obs = int(sys.argv[sys.argv.index("--obs") + 1]) if "--obs" in sys.argv else 5
        n = (int(sys.argv[sys.argv.index("--epochs") + 1])
             if "--epochs" in sys.argv else TIMED_EPOCHS)
        dims = SMALL_DIMS if "--small" in sys.argv else None
        engine_name = (sys.argv[sys.argv.index("--engine") + 1]
                       if "--engine" in sys.argv else "dSGD")
        for rec in measure_wirequant_ab(
            quants, obs=obs, n=n, dims=dims, engine_name=engine_name
        ):
            print(json.dumps(rec), flush=True)
        return
    if "--pipeline" in sys.argv:
        # input-pipeline A/B: host (dense per-epoch transfer, the legacy
        # trainer path) vs device (resident inventory + per-epoch index
        # plan + donated state). `--pipeline ab` interleaves both arms;
        # a single arm name runs just that arm (the CI CPU smoke uses
        # `--pipeline device --small --sanitize` to exercise the device
        # path + donation under the CompileGuard on every PR).
        mode = sys.argv[sys.argv.index("--pipeline") + 1]
        if mode not in ("host", "device", "ab"):
            raise SystemExit(f"--pipeline expects host|device|ab, got {mode!r}")
        obs = int(sys.argv[sys.argv.index("--obs") + 1]) if "--obs" in sys.argv else 5
        n = (int(sys.argv[sys.argv.index("--epochs") + 1])
             if "--epochs" in sys.argv else TIMED_EPOCHS)
        dims = SMALL_DIMS if "--small" in sys.argv else None
        for rec in measure_pipeline_ab(
            mode=mode, obs=obs, n=n, dims=dims,
            donate="--no-donate" not in sys.argv,
        ):
            print(json.dumps(rec), flush=True)
        return
    if "--dp-noise" in sys.argv or "--secure-agg" in sys.argv:
        if "--attacks" in sys.argv:
            # without this guard the privacy branch would return before the
            # attacks branch and the plan would be silently dropped
            raise SystemExit(
                "--dp-noise/--secure-agg and --attacks are separate "
                "standalone A/B modes; compose them through the packed "
                "sweep instead (--sites ... --attacks ... --dp-noise ...) "
                "or run two invocations"
            )
        # privacy-plane A/B (r20): clean vs dp vs dp+secureagg paired
        # interleaved arms — throughput (the clip/noise + masked-wire
        # cost) plus the accountant's epsilon_final for the timed chain,
        # one JSON line per arm (docs/bench_privacy_ab_r20.jsonl; regen on
        # TPU with the same command). --secure-agg alone runs the
        # clean-vs-masked pair. (With --sites these flags instead thread
        # into the packed sweep — handled above.)
        sigma = (float(sys.argv[sys.argv.index("--dp-noise") + 1])
                 if "--dp-noise" in sys.argv else 0.0)
        clip = (float(sys.argv[sys.argv.index("--dp-clip") + 1])
                if "--dp-clip" in sys.argv else 1.0)
        obs = int(sys.argv[sys.argv.index("--obs") + 1]) if "--obs" in sys.argv else 5
        n = (int(sys.argv[sys.argv.index("--epochs") + 1])
             if "--epochs" in sys.argv else TIMED_EPOCHS)
        dims = SMALL_DIMS if "--small" in sys.argv else None
        mode = (sys.argv[sys.argv.index("--secure-agg") + 1]
                if "--secure-agg" in sys.argv else "off")
        for rec in measure_privacy_ab(
            dp_noise=sigma, dp_clip=clip,
            secure_mode=mode, obs=obs, n=n, dims=dims,
        ):
            print(json.dumps(rec), flush=True)
        return
    if "--attacks" in sys.argv:
        # hostile-site A/B (r17): clean vs attacked-undefended vs
        # attacked-defended paired interleaved arms — throughput (the robust
        # reducers' gather/compute overhead) plus the final-epoch-loss
        # quality signal, one JSON line per arm
        # (docs/bench_attacks_ab_r17.jsonl; regen on TPU with the same
        # command). --robust-agg picks the defense (default trimmed_mean).
        from dinunet_implementations_tpu.robustness import parse_attack_plan

        plan = parse_attack_plan(sys.argv[sys.argv.index("--attacks") + 1])
        robust = (sys.argv[sys.argv.index("--robust-agg") + 1]
                  if "--robust-agg" in sys.argv else "trimmed_mean")
        obs = int(sys.argv[sys.argv.index("--obs") + 1]) if "--obs" in sys.argv else 5
        n = (int(sys.argv[sys.argv.index("--epochs") + 1])
             if "--epochs" in sys.argv else TIMED_EPOCHS)
        dims = SMALL_DIMS if "--small" in sys.argv else None
        engine_name = (sys.argv[sys.argv.index("--engine") + 1]
                       if "--engine" in sys.argv else "dSGD")
        for rec in measure_attacks_ab(
            plan, robust=robust, obs=obs, n=n, dims=dims,
            engine_name=engine_name,
        ):
            print(json.dumps(rec), flush=True)
        return
    if "--faults" in sys.argv:
        # fault-masked federated round throughput: same flagship epoch with a
        # FaultPlan's liveness mask threaded through the engines (the masking
        # overhead is the claim under test — the program is identical for any
        # mask, so one measurement covers every fault pattern of this shape)
        from dinunet_implementations_tpu.robustness import parse_fault_plan

        plan = parse_fault_plan(sys.argv[sys.argv.index("--faults") + 1])
        dims = SMALL_DIMS if "--small" in sys.argv else None
        value, stats = measure_tpu(with_distribution=True, fault_plan=plan,
                                   dims=dims)
        sites = (dims or {}).get("sites", NUM_SITES)
        live = plan.liveness(sites, 0, (dims or {}).get("steps", STEPS_PER_EPOCH))
        rec = {
            "metric": "samples/sec/chip (ICA-LSTM federated round, fault-masked)",
            "value": value,
            "unit": "samples/sec/chip",
            "samples_per_sec": stats,
            "faults": plan.to_json(),
            "dead_site_rounds": int((live == 0).sum()),
        }
        if dims:
            # --small: record the dims, omit vs_baseline — the CPU baseline
            # is the FLAGSHIP config's, and a toy-dims ratio would masquerade
            # as a real number (same policy as --ab-rankdad)
            rec["dims"] = dims
        elif value is not None:
            rec["vs_baseline"] = round(value / baseline, 2)
        print(json.dumps(rec))
        return
    if "--ab-bidir" in sys.argv:
        # A/B the fused bidirectional pooled kernel against two
        # single-direction sweeps, same process, interleaved endpoints are
        # not needed — each arm uses the least-contended-minimum estimator.
        for arm, fused in (("fused-bidir", True), ("per-direction", False)):
            v, stats = measure_tpu(fused_bidir=fused, repeats=3,
                                   with_distribution=True)
            rec = {
                "metric": f"samples/sec/chip (flagship, {arm})",
                "arm": arm, "value": v,
                "unit": "samples/sec/chip",
                "samples_per_sec": stats,
            }
            if v is not None:
                rec["mfu"] = round(v * flops_per_sample() / V5E_BF16_PEAK_FLOPS, 4)
            print(json.dumps(rec), flush=True)
        return
    value, stats = measure_tpu(with_distribution=True)
    rec = {
        "metric": "samples/sec/chip (ICA-LSTM, 32 sites, full federated round)",
        "value": value,
        "unit": "samples/sec/chip",
        "samples_per_sec": stats,  # min/median/spread over the N observations
    }
    if value is not None:
        rec["vs_baseline"] = round(value / baseline, 2)
        rec["mfu"] = round(value * flops_per_sample() / V5E_BF16_PEAK_FLOPS, 4)
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
