"""Tests for the TPU-build extension workloads (VERDICT round-1 #3/#6):
MultimodalNet transformer, SMRI3DNet 3D-CNN, their datasets, and full
federated runs for both tasks through FedRunner on synthetic site trees."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dinunet_implementations_tpu import TrainConfig
from dinunet_implementations_tpu.models.cnn3d import SMRI3DNet
from dinunet_implementations_tpu.models.transformer import MultimodalNet
from dinunet_implementations_tpu.runner import FedRunner


# ---------------------------------------------------------------------------
# model-level: forward + grad
# ---------------------------------------------------------------------------


def _tiny_multimodal():
    return MultimodalNet(
        fs_input_size=6, num_comps=3, window_size=2, embed_dim=16, num_heads=2,
        num_layers=2, mlp_ratio=2, num_cls=2,
    )


@pytest.mark.slow
def test_multimodal_forward_and_grad():
    model = _tiny_multimodal()
    B, S = 4, 5
    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(B, 6 + S * 3 * 2)).astype(np.float32)
    )
    key = jax.random.PRNGKey(0)
    variables = model.init({"params": key, "dropout": key}, x, train=True)
    out = model.apply(variables, x, train=False)
    assert out.shape == (B, 2)
    assert np.isfinite(np.asarray(out)).all()

    def loss(params):
        logits = model.apply(
            {"params": params}, x, train=True, rngs={"dropout": key}
        )
        return jnp.mean(jax.nn.logsumexp(logits, -1) - logits[:, 0])

    grads = jax.grad(loss)(variables["params"])
    leaves = jax.tree.leaves(grads)
    assert all(np.isfinite(np.asarray(g)).all() for g in leaves)
    assert any(float(jnp.abs(g).max()) > 0 for g in leaves)


@pytest.mark.slow
def test_multimodal_token_count_static_under_jit():
    """CLS + 1 FS token + S ICA tokens; jit must see static shapes."""
    model = _tiny_multimodal()
    x = jnp.ones((2, 6 + 4 * 3 * 2))
    key = jax.random.PRNGKey(1)
    variables = model.init({"params": key, "dropout": key}, x, train=True)
    assert variables["params"]["pos_embed"].shape == (1, 1 + 1 + 4, 16)
    fwd = jax.jit(lambda v, xx: model.apply(v, xx, train=False))
    assert fwd(variables, x).shape == (2, 2)


@pytest.mark.slow
def test_smri3d_forward_and_grad():
    model = SMRI3DNet(channels=(4, 8), num_cls=2)
    x = jnp.asarray(
        np.random.default_rng(1).normal(size=(3, 8, 8, 8)).astype(np.float32)
    )
    key = jax.random.PRNGKey(0)
    variables = model.init({"params": key, "dropout": key}, x, train=True)
    out = model.apply(variables, x, train=False)
    assert out.shape == (3, 2)

    def loss(params):
        logits = model.apply({"params": params}, x, train=True,
                             rngs={"dropout": key})
        return jnp.mean(jnp.square(logits))

    grads = jax.grad(loss)(variables["params"])
    assert all(np.isfinite(np.asarray(g)).all() for g in jax.tree.leaves(grads))


def test_smri3d_masked_batchnorm_ignores_padding():
    """A padding row (weight 0) must not change the batch statistics."""
    model = SMRI3DNet(channels=(4,), num_cls=2, dropout_rate=0.0)
    rng = np.random.default_rng(2)
    x3 = jnp.asarray(rng.normal(size=(3, 8, 8, 8)).astype(np.float32))
    key = jax.random.PRNGKey(0)
    variables = model.init({"params": key, "dropout": key}, x3, train=True)
    base = model.apply(variables, x3, train=True, mask=jnp.ones(3),
                       rngs={"dropout": key})
    x4 = jnp.concatenate([x3, 100.0 * jnp.ones((1, 8, 8, 8))], 0)
    padded = model.apply(variables, x4, train=True,
                         mask=jnp.asarray([1.0, 1.0, 1.0, 0.0]),
                         rngs={"dropout": key})
    np.testing.assert_allclose(np.asarray(padded[:3]), np.asarray(base), atol=1e-5)


# ---------------------------------------------------------------------------
# task-level: federated e2e on synthetic site trees
# ---------------------------------------------------------------------------


def _make_smri_tree(root, n_sites=2, subjects=16, shape=(8, 8, 8), seed=11):
    rng = np.random.default_rng(seed)
    spec = []
    for i in range(n_sites):
        d = root / "input" / f"local{i}" / "simulatorRun"
        d.mkdir(parents=True)
        y = rng.integers(0, 2, subjects)
        X = rng.normal(size=(subjects,) + shape).astype(np.float32)
        X += (y[:, None, None, None] * 1.5).astype(np.float32)
        np.savez(d / "volumes.npz", X)
        with open(d / "labels.csv", "w") as fh:
            fh.write("index,label\n")
            for j in range(subjects):
                fh.write(f"{j},{int(y[j])}\n")
        spec.append({
            "data_file": {"value": "volumes.npz"},
            "labels_file": {"value": "labels.csv"},
            "channels": {"value": [4, 8]},
        })
    (root / "inputspec.json").write_text(json.dumps(spec))


@pytest.mark.slow
def test_smri_fed_runner_end_to_end(tmp_path):
    _make_smri_tree(tmp_path)
    cfg = TrainConfig(
        task_id="sMRI-3D-Classification", epochs=3, batch_size=8,
        split_ratio=(0.6, 0.2, 0.2),
    )
    r = FedRunner(cfg, data_path=str(tmp_path), out_dir=str(tmp_path / "output"))
    assert r.cfg.smri3d_args.channels == (4, 8)
    res = r.run(verbose=False)[0]
    assert np.isfinite(res["epoch_losses"]).all()
    assert 0 <= res["test_metrics"][0][1] <= 1
    log = json.load(open(
        tmp_path / "output/remote/simulatorRun/sMRI-3D-Classification/fold_0/logs.json"
    ))
    assert log["agg_engine"] == "dSGD"


def _write_aseg(path, vals):
    with open(path, "w") as fh:
        fh.write("name\tvalue\n")
        for i, v in enumerate(vals):
            fh.write(f"region{i}\t{v}\n")


def _make_multimodal_tree(root, n_sites=2, subjects=14, fs_dim=6, comps=3,
                          temporal=8, window=2, seed=13):
    rng = np.random.default_rng(seed)
    spec = []
    for i in range(n_sites):
        d = root / "input" / f"local{i}" / "simulatorRun"
        d.mkdir(parents=True)
        y = rng.integers(0, 2, subjects)
        tc = rng.normal(size=(subjects, comps, temporal)).astype(np.float32)
        tc += (y[:, None, None] * 1.5).astype(np.float32)
        np.savez(d / "timecourses.npz", tc)
        with open(d / "cov.csv", "w") as fh:
            fh.write("freesurferfile,isControl\n")
            for j in range(subjects):
                f = f"sub{j}.txt"
                _write_aseg(d / f, np.abs(rng.normal(size=fs_dim)) + 0.1 + y[j])
                fh.write(f"{f},{str(bool(y[j])).lower()}\n")
        spec.append({
            "data_file": {"value": "timecourses.npz"},
            "labels_file": {"value": "cov.csv"},
            "fs_input_size": {"value": fs_dim},
            "num_components": {"value": comps},
            "temporal_size": {"value": temporal},
            "window_size": {"value": window},
            "window_stride": {"value": window},
            "embed_dim": {"value": 16},
            "num_heads": {"value": 2},
            "num_layers": {"value": 2},
            "mlp_ratio": {"value": 2},
        })
    (root / "inputspec.json").write_text(json.dumps(spec))


@pytest.mark.slow
def test_multimodal_fed_runner_end_to_end(tmp_path):
    _make_multimodal_tree(tmp_path)
    cfg = TrainConfig(
        task_id="Multimodal-Classification", epochs=3, batch_size=8,
        split_ratio=(0.6, 0.2, 0.2),
    )
    r = FedRunner(cfg, data_path=str(tmp_path), out_dir=str(tmp_path / "output"))
    assert r.cfg.multimodal_args.embed_dim == 16
    res = r.run(verbose=False)[0]
    assert np.isfinite(res["epoch_losses"]).all()
    assert 0 <= res["test_metrics"][0][1] <= 1
    # packed vector layout: fs_dim + S*C*W with S = temporal//window
    log = json.load(open(
        tmp_path / "output/local0/simulatorRun/Multimodal-Classification/fold_0/logs.json"
    ))
    assert log["agg_engine"] == "dSGD"


@pytest.mark.slow
def test_multimodal_bf16_tracks_f32():
    """Mixed precision for the transformer: bf16 matmuls with f32
    softmax/LayerNorm must track the f32 forward within bf16 tolerance."""
    rng = np.random.default_rng(21)
    S, C, W = 4, 3, 4
    f32m = MultimodalNet(
        fs_input_size=5, num_comps=C, window_size=W, embed_dim=16,
        num_heads=2, num_layers=2, num_cls=2,
    )
    b16m = f32m.clone(compute_dtype="bfloat16")
    x = jnp.asarray(rng.normal(size=(3, 5 + S * C * W)).astype(np.float32))
    variables = f32m.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        x, train=False,
    )
    out_f = f32m.apply(variables, x, train=False)
    out_b = b16m.apply(variables, x, train=False)
    assert out_b.dtype == jnp.float32  # head returns f32
    np.testing.assert_allclose(np.asarray(out_b), np.asarray(out_f), atol=0.05)

    def loss(v, m):
        return (m.apply(v, x, train=False) ** 2).mean()

    g_f = jax.grad(loss)(variables, f32m)["params"]
    g_b = jax.grad(loss)(variables, b16m)["params"]
    for (path, a), b in zip(
        jax.tree_util.tree_leaves_with_path(g_f), jax.tree.leaves(g_b)
    ):
        denom = max(float(np.abs(np.asarray(a)).max()), 1e-3)
        assert float(np.abs(np.asarray(a) - np.asarray(b, np.float32)).max()) / denom < 0.1, (
            jax.tree_util.keystr(path)
        )


def test_smri3d_bf16_tracks_f32():
    rng = np.random.default_rng(22)
    f32m = SMRI3DNet(channels=(4, 8), num_cls=2)
    b16m = f32m.clone(compute_dtype="bfloat16")
    x = jnp.asarray(rng.normal(size=(2, 16, 16, 16)).astype(np.float32))
    variables = f32m.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        x, train=False,
    )
    out_f = f32m.apply(variables, x, train=False)
    out_b = b16m.apply(variables, x, train=False)
    assert out_b.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(out_b), np.asarray(out_f), atol=0.05)


def test_smri3d_space_to_depth_mapping():
    """The model's 2x2x2 space-to-depth fold (cnn3d.space_to_depth_222) must
    be a faithful relayout: voxel (2i+di, 2j+dj, 2k+dk) lands in channel
    di*4+dj*2+dk at (i, j, k)."""
    from dinunet_implementations_tpu.models.cnn3d import space_to_depth_222

    B, D = 1, 4
    x = jnp.arange(B * D * D * D, dtype=jnp.float32).reshape(B, D, D, D, 1)
    folded = space_to_depth_222(x)
    assert folded.shape == (B, D // 2, D // 2, D // 2, 8)
    for di in range(2):
        for dj in range(2):
            for dk in range(2):
                np.testing.assert_array_equal(
                    np.asarray(folded[0, :, :, :, di * 4 + dj * 2 + dk]),
                    np.asarray(x[0, di::2, dj::2, dk::2, 0]),
                )
    # the model path uses the fold when enabled: the first conv kernel sees 8
    # input channels (vs 1 with it off) — proves the model really routes
    # through space_to_depth_222, not just that a local copy is correct
    m = SMRI3DNet(channels=(4, 8), num_cls=2, space_to_depth=True)
    v = m.init({"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
               jnp.ones((2, 16, 16, 16)), train=False)
    assert v["params"]["conv_0"]["kernel"].shape == (3, 3, 3, 8, 4)
    out = m.apply(v, jnp.ones((2, 16, 16, 16)), train=False)
    assert out.shape == (2, 2) and np.isfinite(np.asarray(out)).all()
    m_off = SMRI3DNet(channels=(4, 8), num_cls=2, space_to_depth=False)
    v2 = m_off.init({"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
                    jnp.ones((2, 16, 16, 16)), train=False)
    assert v2["params"]["conv_0"]["kernel"].shape == (3, 3, 3, 1, 4)
    out2 = m_off.apply(v2, jnp.ones((2, 16, 16, 16)), train=False)
    assert out2.shape == (2, 2) and np.isfinite(np.asarray(out2)).all()


@pytest.mark.golden
def test_smri_converges_golden(tmp_path):
    """Extension-task golden floor: the 3D-CNN must actually LEARN the
    planted signal, not just run (measured AUC 0.8125 at seed 0)."""
    _make_smri_tree(tmp_path, subjects=24, seed=31)
    cfg = TrainConfig(
        task_id="sMRI-3D-Classification", epochs=30, patience=12,
        batch_size=8, split_ratio=(0.6, 0.2, 0.2), seed=0,
    )
    res = FedRunner(cfg, data_path=str(tmp_path), out_dir=str(tmp_path / "out")).run(
        verbose=False
    )[0]
    assert res["test_metrics"][0][1] >= 0.75, res["test_metrics"]


@pytest.mark.golden
def test_multimodal_converges_golden(tmp_path):
    """Extension-task golden floor: the multimodal transformer must learn
    the planted cross-modality signal (measured AUC 1.0 at seed 0 on the r5
    v5e/newer-jax harness, 0.867 on the jax-0.4.37 CPU container — version
    numerics shift the trajectory; the floor gates at the weaker one)."""
    _make_multimodal_tree(tmp_path, subjects=20, seed=37)
    cfg = TrainConfig(
        task_id="Multimodal-Classification", epochs=30, patience=12,
        batch_size=8, split_ratio=(0.6, 0.2, 0.2), seed=0,
    )
    res = FedRunner(cfg, data_path=str(tmp_path), out_dir=str(tmp_path / "out")).run(
        verbose=False
    )[0]
    assert res["test_metrics"][0][1] >= 0.85, res["test_metrics"]


def test_smri3d_space_to_depth_rejects_invalid_input():
    """Review regression (r3): a configured fold must never silently
    self-disable — odd dims or multi-channel input raise."""
    m = SMRI3DNet(channels=(4,), num_cls=2, space_to_depth=True)
    key = jax.random.PRNGKey(0)
    with pytest.raises(ValueError, match="space_to_depth"):
        m.init({"params": key, "dropout": key}, jnp.ones((2, 7, 8, 8)),
               train=False)
    with pytest.raises(ValueError, match="space_to_depth"):
        m.init({"params": key, "dropout": key}, jnp.ones((2, 8, 8, 8, 3)),
               train=False)


def test_space_to_depth_np_matches_model_fold():
    """Pipeline fold (data/smri.py) == model fold (cnn3d) channel-for-channel,
    and the two training programs are numerically identical."""
    from dinunet_implementations_tpu.data.smri import space_to_depth_222_np
    from dinunet_implementations_tpu.models.cnn3d import space_to_depth_222

    rng = np.random.default_rng(12)
    vols = rng.normal(size=(3, 8, 8, 8)).astype(np.float32)
    np.testing.assert_array_equal(
        space_to_depth_222_np(vols),
        np.asarray(space_to_depth_222(jnp.asarray(vols)[..., None])),
    )
    # trailing singleton channel accepted; odd dims rejected
    np.testing.assert_array_equal(
        space_to_depth_222_np(vols[..., None]), space_to_depth_222_np(vols)
    )
    with pytest.raises(ValueError, match="even spatial dims"):
        space_to_depth_222_np(vols[:, :7])

    m_in = SMRI3DNet(channels=(4, 8), num_cls=2, space_to_depth=True)
    m_pre = SMRI3DNet(channels=(4, 8), num_cls=2, space_to_depth=False)
    raw = jnp.asarray(vols)[..., None]
    pre = jnp.asarray(space_to_depth_222_np(vols))
    v = m_in.init({"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(0)},
                  raw, train=True)
    out_in = m_in.apply(v, raw, train=False)
    out_pre = m_pre.apply(v, pre, train=False)  # SAME params restore
    np.testing.assert_allclose(np.asarray(out_in), np.asarray(out_pre), atol=1e-6)
    # the s2d-flagged model recognizes pre-folded 8-channel input (no-op
    # fold) — covers a custom dataset_cls that folds, or the registry path
    out_both = m_in.apply(v, pre, train=False)
    np.testing.assert_allclose(np.asarray(out_both), np.asarray(out_in), atol=1e-6)
    # multi-channel raw volumes are rejected, not silently truncated
    with pytest.raises(ValueError, match="single-channel"):
        space_to_depth_222_np(np.repeat(vols[..., None], 2, axis=-1))


@pytest.mark.slow
def test_smri_fed_runner_space_to_depth_pipeline(tmp_path):
    """SMRI3DArgs.space_to_depth=True folds in the DATA pipeline (dataset
    load) and builds the model unfolded — the e2e run must train."""
    _make_smri_tree(tmp_path)
    cfg = TrainConfig(
        task_id="sMRI-3D-Classification", epochs=2, batch_size=8,
        split_ratio=(0.6, 0.2, 0.2),
    )
    cfg.smri3d_args.space_to_depth = True
    from dinunet_implementations_tpu.runner import FedRunner

    res = FedRunner(cfg, data_path=str(tmp_path),
                    out_dir=str(tmp_path / "out")).run(verbose=False)[0]
    assert 0 <= res["test_metrics"][0][1] <= 1
