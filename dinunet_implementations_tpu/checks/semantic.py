"""jaxprlint — semantic SPMD verification over TRACED programs (tier 2).

The AST tier (rules.py, R001-R007) checks what the source text promises; the
properties the repo actually stakes correctness and perf claims on live in
the traced/lowered/compiled program: "every collective runs over a declared
mesh axis", "`wire_bytes` models what really goes over the wire", "donated
buffers really alias", "the bf16 wire is really bf16", "off == compiled
out". This module traces the REAL fit programs for a small
engine × topology × pipeline matrix on CPU virtual devices and verifies
them semantically:

- **S001** — collective/mesh audit: walking every ClosedJaxpr (recursing
  into scan/while/pjit/shard_map sub-jaxprs), each collective primitive
  (``psum``, ``all_gather``, ``reduce_scatter``, …) may name only the
  declared mesh-axis constants (``parallel/mesh.py``; vmap-resolved fold
  axes appear as positional ints and are fine), and no cross-site
  communication may sit outside the rounds scan — at 512+ packed sites a
  per-round stray collective is a silent synchronization cliff.
- **S002** — wire-byte proof: the per-round PER-DEVICE collective payload,
  computed from the TRACED operand shapes/dtypes, must match the engine's
  static ``wire_bytes`` model exactly — at the cell's site-packing factor
  (r12: packed cells verify that psum-shaped exchanges reduce over the
  packed virtual-site axis in-register BEFORE the wire and stay
  K-invariant, while the factor gather's ``[K, Σ(m+n), r]`` block is
  modeled as genuinely K-scaling). Matching is structural: every entry of
  the engine's ``wire_shapes`` introspection hook (engines/base.py) must
  appear as a traced collective operand literally, every traced
  payload-sized operand must be covered by the model, and the byte totals
  must agree. The telemetry layer's ``payload_bytes`` figures
  (telemetry/metrics.py) become verified, not modeled.
- **S003** — donation proof: for ``donate_epoch_state`` builds, the compiled
  executable's input-output aliasing must actually contain every donated
  TrainState buffer. A donated-but-unaliased arg is a silent HBM/perf bug —
  jax warns once to stderr and the epoch quietly doubles its params+opt
  residency.
- **S004** — precision-flow lint on the aggregation path: each payload
  operand's wire dtype (resolved through its producer chain, so the
  ``wire_compress`` bf16→f32 round-trip counts as bf16) must not be wider
  than the engine's modeled payload dtype, and a ``precision_bits="16"``
  compression engine must actually lower low-precision ``dot_general`` ops
  for its power-iteration products (engines/lowrank.py ``lp_matmul``).
- **S005** — program-identity gate over the normalized-lowering differ
  (checks/lowering.py): telemetry-off, faults-off(-by-default), and the
  sanitizer's observation modes must be lowering-identical to the baseline
  program, and the static opt-outs (``quarantine_rounds=-1``,
  ``telemetry=True``) must genuinely diverge — if the "compiled out"
  machinery stops being compiled out, this gate fails.

Run with ``python -m dinunet_implementations_tpu.checks --semantic`` (CPU;
the CLI provisions virtual devices). Findings ride the same
:class:`~.core.Finding`/baseline machinery as the AST tier, keyed on
``(rule, trace://<cell>, snippet)`` — grandfathering goes through
``checks/baseline_semantic.json`` (shipped EMPTY); there is no inline
suppression for traced programs.
"""

from __future__ import annotations

import dataclasses
import math
import os
import re

from .core import Finding
from .rules import COLLECTIVE_AXIS_ARG

#: the semantic tier's grandfather list (empty == every traced program clean)
SEMANTIC_BASELINE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "baseline_semantic.json"
)

# -- collective tables ------------------------------------------------------
# Derived from the AST tier's COLLECTIVE_AXIS_ARG so the two tiers agree on
# what counts as a collective (tests/test_semantic.py asserts the mapping is
# total). Some lax APIs trace to differently-named primitives:
API_TO_PRIM = {
    "psum_scatter": "reduce_scatter",
    "pmean": "psum",  # pmean is psum / axis_size sugar
    "axis_size": "psum",  # old-jax spelling: psum(1, axis)
}

#: traced primitives that move data across the site/model axes
COMM_PRIMS = frozenset({
    "psum", "pmax", "pmin", "ppermute", "all_gather", "all_to_all",
    "reduce_scatter", "pbroadcast",
})
#: traced primitives that only QUERY the axis (no payload; exempt from the
#: in-scan and wire-byte rules, still axis-name audited)
QUERY_PRIMS = frozenset({"axis_index"})


def prim_for(api_name: str) -> str:
    """Traced-primitive name for a lax collective API name."""
    return API_TO_PRIM.get(api_name, api_name)


# tier agreement, enforced at import (a hard raise, not an assert — it must
# survive python -O): every collective the AST tier knows must trace to a
# primitive this tier audits
_unmapped = [
    n for n in COLLECTIVE_AXIS_ARG
    if prim_for(n) not in COMM_PRIMS | QUERY_PRIMS
]
if _unmapped:
    raise RuntimeError(
        f"rules.COLLECTIVE_AXIS_ARG and the semantic tier's COMM/QUERY "
        f"primitive tables have drifted: {_unmapped} have no traced-"
        f"primitive mapping (extend API_TO_PRIM/COMM_PRIMS)"
    )


def ensure_cpu_devices(min_devices: int = 2, want: int = 8) -> None:
    """Provision virtual CPU devices for the trace matrix.

    Must run before the jax backend initializes (the CLI path — jax is
    imported by the package but uninitialized until first device use); in an
    already-initialized process (pytest under tests/conftest.py) it is a
    no-op and the session's device count is used.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={want}"
        ).strip()
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except (RuntimeError, ValueError):
        pass  # backend already initialized; run on what the session has
    cpus = [d for d in jax.devices() if d.platform == "cpu"]
    if len(cpus) < min_devices:
        raise RuntimeError(
            f"the semantic tier traces mesh programs and needs >= "
            f"{min_devices} CPU devices, have {len(cpus)}; run via `python "
            f"-m dinunet_implementations_tpu.checks --semantic` (which sets "
            f"XLA_FLAGS before jax initializes) or export XLA_FLAGS="
            f"--xla_force_host_platform_device_count={want}"
        )


# ---------------------------------------------------------------------------
# jaxpr walking
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class CollectiveSite:
    """One collective primitive found in a traced program."""

    prim: str
    named_axes: tuple  # str axis names only (vmap-resolved folds are ints)
    operands: tuple  # operand avals
    scan_depth: int  # 0 == outside every scan/while
    wire_itemsizes: tuple  # per operand: effective float itemsize of the
    # payload it carries (_payload_itemsize; None for non-float operands)


@dataclasses.dataclass
class ProgramAudit:
    """Everything the S-rules need from one traced program."""

    collectives: list
    dots: list  # (lhs_itemsize, rhs_itemsize, scan_depth) per dot_general


#: value-preserving / scaling ops the wire-dtype walk may look through: the
#: payload chain between "quantized to the wire dtype" and "handed to the
#: collective" is casts, scale multiplies, liveness selects and layout
#: moves — plus the r14 quantized-wire codec's grid ops (round/floor to the
#: int8 grid, clamp to its range): parallel/collectives.py WireCodec
_PASSTHROUGH = frozenset({
    "convert_element_type", "mul", "div", "add", "sub", "neg", "select_n",
    "reshape", "transpose", "broadcast_in_dim", "squeeze", "expand_dims",
    "concatenate", "slice", "stop_gradient", "copy",
    "round", "floor", "clamp", "max", "min",
})

#: the elementwise/broadcasting subset of _PASSTHROUGH: only here may an
#: operand smaller than the output be dismissed as a broadcasting scale
_ELEMENTWISE = frozenset({
    "mul", "div", "add", "sub", "select_n", "max", "min", "clamp",
})


def _sub_jaxprs(params: dict):
    """All jaxprs nested in one eqn's params (scan/while/pjit/shard_map/
    custom_* — any param that is a Jaxpr, a ClosedJaxpr, or a sequence of
    them)."""
    import jax

    closed = jax.core.ClosedJaxpr
    plain = jax.core.Jaxpr
    for v in params.values():
        if isinstance(v, closed):
            yield v.jaxpr
        elif isinstance(v, plain):
            yield v
        elif isinstance(v, (list, tuple)):
            for vv in v:
                if isinstance(vv, closed):
                    yield vv.jaxpr
                elif isinstance(vv, plain):
                    yield vv


def _float_itemsize(dtype):
    """Itemsize when ``dtype`` is a float (incl. the ml_dtypes extension
    floats — bfloat16/float8 have numpy kind 'V', not 'f'), else None."""
    import jax.numpy as jnp
    import numpy as np

    d = np.dtype(dtype)
    if d.kind == "f" or jnp.issubdtype(d, jnp.floating):
        return d.itemsize
    return None


def _wire_itemsize_of(dtype):
    """Effective wire itemsize of one dtype in a payload chain: floats carry
    their own width; a SUB-WORD integer is a quantization grid (the r14 int8
    wire codec's ``convert→int8→convert`` round-trip — the value the chain
    carries from there on fits in that many bytes); word-size-and-up
    integers (indices, counters) are not payloads at all → None."""
    import numpy as np

    f = _float_itemsize(dtype)
    if f is not None:
        return f
    d = np.dtype(dtype)
    if d.kind in "iu" and d.itemsize < 4:
        return d.itemsize
    return None


def _is_scale_operand(var, producers: dict) -> bool:
    """True when ``var`` enters an arithmetic op as a scale/mask rather than
    as the payload itself: a literal, a scalar, or a broadcast of something
    smaller than itself. A narrow float there perturbs the payload but does
    not quantize it, so the wire-dtype walk must not let it narrow the
    result."""
    aval = getattr(var, "aval", None)
    if aval is None:  # jaxpr Literal
        return True
    shape = tuple(getattr(aval, "shape", ()))
    if math.prod(shape) <= 1:
        return True
    eqn = producers.get(id(var))
    if eqn is not None and eqn.primitive.name == "broadcast_in_dim":
        src = getattr(eqn.invars[0], "aval", None)
        if src is not None and (
            math.prod(tuple(getattr(src, "shape", ()))) < math.prod(shape)
        ):
            return True
    return False


def _payload_itemsize(var, producers: dict, max_depth: int = 10):
    """Effective float itemsize of the value ``var`` carries onto the wire —
    the dtype the payload was QUANTIZED to, even when an f32-accumulating
    collective consumes the f32 round-trip of a bf16 value
    (``parallel/collectives.py wire_compress``).

    The walk follows the payload's own dataflow, not every contributor: a
    cast chain can only narrow (min with the input), an n-ary arithmetic op
    is only as narrow as its WIDEST data-carrying operand (combining a
    quantized tensor with a full-precision one leaves the quantized grid —
    an f32 payload multiplied by a mask that touched bf16 must still read
    f32), and scale/mask operands (:func:`_is_scale_operand`) are skipped
    entirely (an f32 grad scaled by a shared bf16 scalar is not a bf16
    wire, and a bf16 payload scaled by an f32 weight still is one)."""

    def eff(v, depth):
        aval = getattr(v, "aval", None)
        if aval is None:
            return None
        # sub-word integers count as quantization grids (_wire_itemsize_of):
        # the int8 wire codec's round-trip passes through an int8 value, and
        # everything downstream of that cast carries ≤ 1 byte of payload
        storage = _wire_itemsize_of(aval.dtype)
        if storage is None:
            return None
        eqn = producers.get(id(v))
        if eqn is None or depth >= max_depth:
            return storage
        if eqn.primitive.name not in _PASSTHROUGH:
            return storage
        out_elems = math.prod(tuple(getattr(aval, "shape", ())))
        elementwise = eqn.primitive.name in _ELEMENTWISE

        def _scale_like(iv):
            # a scale/mask never carries the payload: literals, scalars,
            # explicit broadcasts (_is_scale_operand) — and, for
            # ELEMENTWISE ops only, any operand STRICTLY SMALLER than the
            # output, i.e. one that broadcasts against the payload (the
            # r14 packed per-row [K, 1, 1] quant scale reaches the mul at
            # its own rank-kept shape, no broadcast_in_dim in the jaxpr).
            # Shape-composing ops (concatenate, slice) keep every operand
            # as data: their inputs are legitimately smaller than the
            # output without being scales.
            if _is_scale_operand(iv, producers):
                return True
            if not elementwise:
                return False
            a = getattr(iv, "aval", None)
            if a is None:
                return True
            return math.prod(tuple(getattr(a, "shape", ()))) < out_elems

        data = [
            iv for iv in eqn.invars
            if len(eqn.invars) == 1 or not _scale_like(iv)
        ]
        subs = [s for s in (eff(iv, depth + 1) for iv in data) if s is not None]
        if not subs:
            return storage
        return min(storage, max(subs))

    return eff(var, 0)


def _named_axes(params: dict) -> tuple:
    ax = params.get("axes", params.get("axis_name"))
    if ax is None:
        return ()
    if not isinstance(ax, (tuple, list)):
        ax = (ax,)
    return tuple(a for a in ax if isinstance(a, str))


def audit_jaxpr(closed_jaxpr) -> ProgramAudit:
    """Walk a ClosedJaxpr (recursing into every sub-jaxpr) and collect all
    collective sites + dot_general precision info."""
    collectives: list = []
    dots: list = []

    def walk(jaxpr, scan_depth: int):
        producers: dict = {}
        for eqn in jaxpr.eqns:
            for ov in eqn.outvars:
                producers[id(ov)] = eqn
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if name in COMM_PRIMS or name in QUERY_PRIMS:
                ops = tuple(getattr(v, "aval", None) for v in eqn.invars)
                wis = tuple(
                    _payload_itemsize(v, producers) for v in eqn.invars
                )
                collectives.append(CollectiveSite(
                    prim=name,
                    named_axes=_named_axes(eqn.params),
                    operands=ops,
                    scan_depth=scan_depth,
                    wire_itemsizes=wis,
                ))
            elif name == "dot_general":
                sizes = [
                    _float_itemsize(v.aval.dtype)
                    if getattr(v, "aval", None) is not None else None
                    for v in eqn.invars[:2]
                ]
                dots.append((sizes[0], sizes[1], scan_depth))
            inner_depth = scan_depth + (1 if name in ("scan", "while") else 0)
            for sub in _sub_jaxprs(eqn.params):
                walk(sub, inner_depth)

    walk(closed_jaxpr.jaxpr, 0)
    return ProgramAudit(collectives=collectives, dots=dots)


# ---------------------------------------------------------------------------
# S001 — collective/mesh audit
# ---------------------------------------------------------------------------


def check_collective_axes(
    collectives: list, path: str, allowed_axes=None,
    require_in_scan: bool = True,
) -> list:
    """S001: every collective names only declared mesh-axis constants, and
    cross-site communication lives inside the rounds scan."""
    if allowed_axes is None:
        from ..parallel.mesh import MODEL_AXIS, SITE_AXIS

        allowed_axes = {SITE_AXIS, MODEL_AXIS}
    findings = []
    for site in collectives:
        rogue = [a for a in site.named_axes if a not in allowed_axes]
        if rogue:
            findings.append(Finding(
                rule="S001", path=path, line=0, col=0,
                message=(
                    f"collective '{site.prim}' runs over undeclared axis "
                    f"name(s) {rogue} (declared mesh axes: "
                    f"{sorted(allowed_axes)}) — it reduces over something "
                    f"other than the site/model mesh"
                ),
                snippet=f"{site.prim} axes={rogue}",
                fixit="bind collectives to the parallel/mesh.py axis "
                      "constants (SITE_AXIS/MODEL_AXIS; folded sites ride "
                      "vmap and resolve positionally)",
            ))
        if require_in_scan and site.prim in COMM_PRIMS and site.scan_depth == 0:
            findings.append(Finding(
                rule="S001", path=path, line=0, col=0,
                message=(
                    f"cross-site collective '{site.prim}' appears OUTSIDE "
                    f"the rounds scan — per-epoch stray communication that "
                    f"the round loop cannot overlap or amortize"
                ),
                snippet=f"{site.prim} outside-scan",
                fixit="move cross-site communication inside the rounds scan "
                      "(trainer/steps.py one_round) so it ships once per "
                      "round with the aggregation traffic",
            ))
    return findings


# ---------------------------------------------------------------------------
# S002 / S004 — wire-byte proof + precision flow
# ---------------------------------------------------------------------------


def _match_payload(collectives: list, expected: list):
    """Assign modeled payload entries to traced collective operands.

    ``expected`` is ``[(shape, np.dtype), ...]`` from the engine's wire
    model AT THE CELL'S PACK FACTOR; traced operands are matched by shape
    literally — since the two-level aggregation (r12) the mesh collectives
    carry exactly the per-device payloads the model describes (psum partials
    unbatched, the factor gather with its leading ``[pack]`` virtual-site
    axis), so there is no site-block normalization to undo. Returns
    ``(matches, missing, leftovers)`` where matches are ``(shape,
    model_dtype, traced_itemsize, prim)``, missing are unmatched model
    entries, and leftovers are traced COMM operands covered by nothing
    (excluding the scalar bookkeeping collectives: loss and
    weight-normalization psums)."""
    import numpy as np

    traced = []
    for site in collectives:
        if site.prim not in COMM_PRIMS:
            continue
        for aval, wi in zip(site.operands, site.wire_itemsizes):
            if aval is None:
                continue
            isz = wi if wi is not None else np.dtype(aval.dtype).itemsize
            traced.append({
                "shape": tuple(aval.shape), "itemsize": isz, "prim": site.prim,
                "matched": False,
            })
    matches, missing = [], []
    for shape, dtype in expected:
        # prefer an operand at exactly the modeled itemsize so two same-shape
        # payloads at different dtypes (a bf16 factor next to an f32 dense
        # leaf) cannot cross-pair; fall back to shape-only so a genuine
        # upcast still pairs with its model entry (and S004 flags it)
        # instead of reading as a coverage hole. Stat-shaped operands can't
        # be excluded here: a dense payload may legitimately share a stat's
        # shape AND dtype, and then either pairing is byte-identical.
        cands = [t for t in traced if not t["matched"] and t["shape"] == shape]
        hit = next(
            (t for t in cands if t["itemsize"] == dtype.itemsize),
            cands[0] if cands else None,
        )
        if hit is None:
            missing.append((shape, dtype))
            continue
        hit["matched"] = True
        matches.append((shape, dtype, hit["itemsize"], hit["prim"]))
    leftovers = [
        t for t in traced if not t["matched"] and t["shape"] != ()
    ]
    return matches, missing, leftovers


def check_wire_bytes(
    collectives: list, engine, params_template, pack: int, path: str,
    stats_shapes=(),
) -> list:
    """S002: traced collective payload bytes == ``Engine.wire_bytes``,
    exactly, with structural coverage both ways — evaluated at the cell's
    site-packing factor ``pack`` (the k virtual sites per device), so a
    model that ignores packing (per-site instead of per-device accounting)
    is flagged on the packed cells."""
    from ..telemetry.metrics import modeled_wire_shapes, payload_bytes_of

    expected = modeled_wire_shapes(engine, params_template, pack=pack)
    model_total = sum(
        math.prod(s) * d.itemsize for s, d in expected
    )
    wb = int(payload_bytes_of(engine, params_template, pack=pack))
    findings = []
    if model_total != wb:
        findings.append(Finding(
            rule="S002", path=path, line=0, col=0,
            message=(
                f"engine '{engine.name}': wire_shapes model sums to "
                f"{model_total} B but wire_bytes reports {wb} B — the "
                f"structured and scalar payload models have drifted"
            ),
            snippet="model-inconsistent",
            fixit="keep Engine.wire_shapes and Engine.wire_bytes derived "
                  "from the same shape arithmetic (engines/lowrank.py "
                  "lowrank_rank_groups)",
        ))
    matches, missing, leftovers = _match_payload(collectives, expected)
    for shape, dtype in missing:
        findings.append(Finding(
            rule="S002", path=path, line=0, col=0,
            message=(
                f"engine '{engine.name}': modeled payload operand "
                f"{shape}@{dtype} never appears as a traced collective "
                f"operand — the wire model OVERCOUNTS what ships"
            ),
            snippet=f"missing {shape}",
            fixit="make Engine.wire_shapes mirror the collectives the "
                  "aggregate actually launches",
        ))
    for t in leftovers:
        if t["shape"] in tuple(stats_shapes):
            continue  # sync-BN running-stat psums are not engine payload
        findings.append(Finding(
            rule="S002", path=path, line=0, col=0,
            message=(
                f"engine '{engine.name}': traced collective '{t['prim']}' "
                f"ships an operand shaped {t['shape']} that no wire-model "
                f"entry covers — the wire model UNDERCOUNTS what ships"
            ),
            snippet=f"unmodeled {t['prim']} {t['shape']}",
            fixit="add the payload to Engine.wire_shapes/wire_bytes (or "
                  "stop shipping it)",
        ))
    traced_total = sum(
        math.prod(shape) * isz for shape, _, isz, _ in matches
    )
    if not findings and traced_total != wb:
        findings.append(Finding(
            rule="S002", path=path, line=0, col=0,
            message=(
                f"engine '{engine.name}': traced payload is {traced_total} "
                f"B/round/device but wire_bytes models {wb} B at pack="
                f"{pack} — telemetry's payload_bytes figures are wrong"
            ),
            snippet="bytes-mismatch",
            fixit="reconcile the traced operand dtypes with the modeled "
                  "payload dtype (see the S004 findings for which operand "
                  "widened)",
        ))
    return findings


def check_dcn_wire(
    collectives: list, engine, params_template, pack: int,
    sites_per_slice: int, path: str, stats_shapes=(), slices: int = 2,
) -> list:
    """The DCN-tier audit for sliced cells (r18): every collective touching
    the slice axis is either the split inter-slice hop (names EXACTLY
    ``(slice,)`` — the re-quantized per-slice partial / the hierarchical
    gather's slice leg) or a fused ``(slice, site)`` reduce (bookkeeping and
    the no-DCN-codec payload form — one collective spanning both tiers,
    bit-identical to the flat reduce); anything else (a slice+model mix, a
    site-inner ordering) is a mis-laid axis (S001). The payloads of those
    collectives must then match the engine's ``dcn_wire_shapes`` model both
    ways at the cell's pack factor and per-slice site count, and the byte
    totals must agree with ``Engine.dcn_bytes`` — so the
    ``dcn_bytes_per_slice_round`` telemetry/bench figure is PROVEN against
    traced operand shapes, codec shrink included (S002)."""
    from ..parallel.mesh import SITE_AXIS, SLICE_AXIS
    from ..telemetry.metrics import dcn_bytes_of, modeled_dcn_shapes

    findings = []
    dcn_colls = []
    for site in collectives:
        if SLICE_AXIS not in site.named_axes:
            continue
        if tuple(site.named_axes) not in (
            (SLICE_AXIS,), (SLICE_AXIS, SITE_AXIS),
        ):
            findings.append(Finding(
                rule="S001", path=path, line=0, col=0,
                message=(
                    f"collective '{site.prim}' touches the slice axis with "
                    f"axes {tuple(site.named_axes)} — the DCN tier is "
                    f"slice-only (the split hop) or the fused (slice, "
                    f"site) reduce; any other mix re-orders the hierarchy"
                ),
                snippet=f"{site.prim} axes={tuple(site.named_axes)}",
                fixit="route inter-slice traffic through "
                      "parallel/collectives.py three_level_psum / "
                      "site_all_gather (the PackedAxis slice forms)",
            ))
            continue
        if site.prim in COMM_PRIMS and site.scan_depth == 0:
            findings.append(Finding(
                rule="S001", path=path, line=0, col=0,
                message=(
                    f"inter-slice collective '{site.prim}' appears OUTSIDE "
                    f"the rounds scan — stray per-epoch DCN traffic"
                ),
                snippet=f"{site.prim} dcn-outside-scan",
                fixit="keep the DCN hop inside the rounds scan "
                      "(trainer/steps.py one_round)",
            ))
        if site.prim in COMM_PRIMS:
            dcn_colls.append(site)
    expected = modeled_dcn_shapes(
        engine, params_template, pack=pack, sites_per_slice=sites_per_slice
    )
    model_total = sum(math.prod(s) * d.itemsize for s, d in expected)
    db = int(dcn_bytes_of(
        engine, params_template, pack=pack, sites_per_slice=sites_per_slice,
        slices=slices,
    ))
    if model_total != db:
        findings.append(Finding(
            rule="S002", path=path, line=0, col=0,
            message=(
                f"engine '{engine.name}': dcn_wire_shapes model sums to "
                f"{model_total} B but dcn_bytes reports {db} B — the "
                f"structured and scalar DCN payload models have drifted"
            ),
            snippet="dcn-model-inconsistent",
            fixit="derive Engine.dcn_bytes and Engine.dcn_wire_shapes from "
                  "the same shape arithmetic",
        ))
    matches, missing, leftovers = _match_payload(dcn_colls, expected)
    for shape, dtype in missing:
        findings.append(Finding(
            rule="S002", path=path, line=0, col=0,
            message=(
                f"engine '{engine.name}': modeled DCN payload "
                f"{shape}@{dtype} never appears as an operand of a "
                f"slice-axis collective — the DCN wire model OVERCOUNTS "
                f"what crosses the inter-slice hop"
            ),
            snippet=f"dcn-missing {shape}",
            fixit="make Engine.dcn_wire_shapes mirror the slice-axis "
                  "collectives the aggregate actually launches",
        ))
    for t in leftovers:
        if t["shape"] in tuple(stats_shapes):
            continue  # fused sync-BN stat reduces are not engine payload
        findings.append(Finding(
            rule="S002", path=path, line=0, col=0,
            message=(
                f"engine '{engine.name}': slice-axis collective "
                f"'{t['prim']}' ships an operand shaped {t['shape']} that "
                f"no DCN wire-model entry covers — the DCN model "
                f"UNDERCOUNTS what crosses the inter-slice hop"
            ),
            snippet=f"dcn-unmodeled {t['prim']} {t['shape']}",
            fixit="add the payload to Engine.dcn_wire_shapes/dcn_bytes (or "
                  "stop shipping it across slices)",
        ))
    traced_total = sum(
        math.prod(shape) * isz for shape, _, isz, _ in matches
    )
    if not findings and traced_total != db:
        findings.append(Finding(
            rule="S002", path=path, line=0, col=0,
            message=(
                f"engine '{engine.name}': traced DCN payload is "
                f"{traced_total} B/round/slice but dcn_bytes models {db} B "
                f"at pack={pack}, sites_per_slice={sites_per_slice} — the "
                f"per-tier telemetry figures are wrong"
            ),
            snippet="dcn-bytes-mismatch",
            fixit="reconcile the slice-collective operand dtypes with the "
                  "modeled DCN payload dtype (is the codec re-quantization "
                  "really happening at the slice boundary?)",
        ))
    return findings


def check_precision_flow(
    collectives: list, engine, params_template, pack: int, path: str,
    require_lowp_dot: bool = False, dots=(),
) -> list:
    """S004: no payload rides the wire wider than the engine's modeled
    payload dtype, and a 16-bit wire on a compression engine really lowers
    low-precision dots for the power-iteration products. ``pack`` selects
    the wire model's site-packing factor like :func:`check_wire_bytes`."""
    from ..telemetry.metrics import modeled_wire_shapes

    expected = modeled_wire_shapes(engine, params_template, pack=pack)
    matches, _, _ = _match_payload(collectives, expected)
    findings = []
    for shape, dtype, traced_isz, prim in matches:
        if traced_isz is not None and traced_isz > dtype.itemsize:
            findings.append(Finding(
                rule="S004", path=path, line=0, col=0,
                message=(
                    f"engine '{engine.name}': payload {shape} rides "
                    f"'{prim}' at {traced_isz * 8}-bit floats but the wire "
                    f"model says {dtype} — an accidental upcast on the "
                    f"wire path (the precision_bits compression is not "
                    f"happening)"
                ),
                snippet=f"upcast {prim} {shape}",
                fixit="quantize the payload to the wire dtype before the "
                      "collective (parallel/collectives.py payload_cast / "
                      "wire_compress)",
            ))
    if require_lowp_dot:
        lowp = any(
            a is not None and b is not None and a < 4 and b < 4
            for a, b, _ in dots
        )
        if not lowp:
            findings.append(Finding(
                rule="S004", path=path, line=0, col=0,
                message=(
                    f"engine '{engine.name}' with a 16-bit wire lowers no "
                    f"low-precision dot_general — the mixed-precision "
                    f"power-iteration matmuls (engines/lowrank.py "
                    f"lp_matmul) silently run full f32"
                ),
                snippet="no-lowp-dot",
                fixit="thread matmul_dtype=jnp.bfloat16 through the "
                      "engine's factorization path when the wire is 16-bit",
            ))
    return findings


# ---------------------------------------------------------------------------
# S003 — donation proof
# ---------------------------------------------------------------------------

#: one `{out_idx}: (param_num, {param_idx}, kind)` entry of the optimized
#: HLO module's input_output_alias attribute
_ALIAS_ENTRY_RE = re.compile(
    r"\{[\d,\s]*\}:\s*\((\d+),\s*\{[^{}]*\},\s*(?:may|must)-alias\)"
)


def check_donation(
    compiled, args: tuple, donate_argnums: tuple, path: str
) -> list:
    """S003: every leaf of every donated argument appears in the compiled
    executable's input-output aliasing. Parameter numbers in the optimized
    HLO correspond to the flattened argument leaves in order."""
    import jax

    aliased = {int(p) for p in _ALIAS_ENTRY_RE.findall(compiled.as_text())}
    findings = []
    flat_index = 0
    for argnum, arg in enumerate(args):
        leaves = jax.tree_util.tree_flatten_with_path(arg)[0]
        for keypath, leaf in leaves:
            if argnum in tuple(donate_argnums) and flat_index not in aliased:
                kp = jax.tree_util.keystr(keypath)
                findings.append(Finding(
                    rule="S003", path=path, line=0, col=0,
                    message=(
                        f"donated buffer arg{argnum}{kp} "
                        f"({tuple(leaf.shape)} {leaf.dtype}) is NOT in the "
                        f"compiled executable's input-output aliasing — "
                        f"donation silently dropped, the epoch holds a "
                        f"second copy of this buffer"
                    ),
                    snippet=f"unaliased arg{argnum}{kp}",
                    fixit="give the donated leaf a same-shape/dtype output "
                          "to alias into (or stop donating it); see "
                          "trainer/steps.py donate_state",
                ))
            flat_index += 1
    return findings


# ---------------------------------------------------------------------------
# S005 — program-identity gate
# ---------------------------------------------------------------------------


def check_lowering_identity(pairs: list, path_prefix: str = "lowering://") -> list:
    """S005: each ``(label, text_a, text_b, expect_identical)`` pair is run
    through the normalized differ; an unexpected divergence (or an expected
    divergence that vanished — the opt-out no longer removes anything) is a
    finding."""
    from .lowering import diff_report

    findings = []
    for label, text_a, text_b, expect_identical in pairs:
        report = diff_report(text_a, text_b, "baseline", label)
        if expect_identical and report is not None:
            first = "\n".join(report.splitlines()[:6])
            findings.append(Finding(
                rule="S005", path=path_prefix + label, line=0, col=0,
                message=(
                    f"'{label}' must be lowering-identical to its baseline "
                    f"but diverges:\n{first}"
                ),
                snippet=f"divergent {label}",
                fixit="gate the feature behind a trace-time static branch "
                      "so the off-form compiles the exact baseline program "
                      "(the telemetry/quarantine_rounds pattern, "
                      "trainer/steps.py)",
            ))
        if not expect_identical and report is None:
            findings.append(Finding(
                rule="S005", path=path_prefix + label, line=0, col=0,
                message=(
                    f"'{label}' was expected to DIVERGE from its baseline "
                    f"but the programs are identical — the static opt-out "
                    f"no longer changes the compiled program (dead flag, "
                    f"or the machinery is no longer compiled out)"
                ),
                snippet=f"non-divergent {label}",
                fixit="check the trace-time gate (telemetry= / "
                      "quarantine_rounds) still switches the program form",
            ))
    return findings


# ---------------------------------------------------------------------------
# the trace matrix
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class TraceCell:
    """One (engine, topology, pipeline) corner of the verification matrix."""

    engine: str
    # "vmap" (all sites on one device) | "mesh" (1 site/device) |
    # "fold" (2 packed/device) | "fold4" (4 packed/device — the deeper
    # site-packing corner, r12) | "sliced" (2 slices × 2 members, K=2 —
    # the r18 three-tier topology) | "sliced4" (2 slices × 2 members, K=4
    # — packed fold4 under slicing)
    topology: str
    pipeline: str  # "host" | "device"
    precision_bits: str = "32"
    donate: bool = False
    dense_model: bool = False  # non-compressible fallback workload
    engine_kw: tuple = ()  # sorted (key, value) engine kwargs
    # staleness_bound for the buffered-async aggregation mode (r13); 0 =
    # the bulk-sync program. Async cells verify that the buffered round's
    # collectives still carry exactly the modeled per-device wire (S002) —
    # buffering happens in registers/HBM, never on the wire.
    staleness: int = 0
    # wire codec (r14, parallel/collectives.py WireCodec): quantized-wire
    # cells verify S002's byte proof resolves the quant→collective→dequant
    # chain to the codec itemsize (the ~4x shrink, proven not modeled) and
    # S004 does not read the dequantized f32 operand as an upcast.
    wire_quant: str = "none"
    # overlapped-rounds mode (r14): the double-buffered stash round —
    # overlap cells verify the stash apply ships the SAME per-device wire
    # as the legacy round and keeps every collective inside the scan
    overlap: bool = False
    # byzantine-robust aggregation mode (r17, parallel/collectives.py
    # ROBUST_AGGS): robust cells verify the robust-mode wire models — the
    # gather-based reducers' genuinely pack-scaling per-site payload
    # gathers, and norm_clip's unchanged psum wire plus its two tiny
    # bookkeeping gathers — against the traced program, plus S001 (the
    # reputation layer's scalar psums stay inside the scan)
    robust: str = "none"
    # inter-slice (DCN) wire codec for the sliced topologies (r18,
    # TrainConfig.dcn_wire_quant semantics: "" follows wire_quant). Sliced
    # cells verify the per-TIER wire models: S002's ICI proof ignores
    # slice-only collectives, and the DCN-tier check proves the engine's
    # dcn_wire_shapes against exactly the collectives that touch the slice
    # axis — so "the expensive hop carries one codec-quantized per-slice
    # partial per round" is a traced property, not a modeled one.
    dcn_quant: str = ""
    # slice-fault cells (r19, robustness/faults.py slice windows): feed the
    # [num_slices, rounds] slice-liveness mask (with a dead-slice round)
    # and build with a min_slices=2 quorum — the wire rules must hold
    # UNCHANGED ("engines unchanged under masking"): the mask rides a
    # replicated input and local reductions, zero new collectives, so
    # S002's ICI proof and the DCN-tier check verify the same figures as
    # the fault-free sliced cells
    slice_faults: bool = False
    # r20 privacy plane: extra make_train_epoch_fn kwargs for cells whose
    # machinery lives in the epoch BUILDER rather than the engine
    # (dp_clip / dp_noise_multiplier / personalize) — sorted (key, value)
    # pairs like engine_kw; the personalize patterns also thread into the
    # cell's state init (per-site head rows) and shrink the wire template
    # to the shared subtree
    epoch_kw: tuple = ()
    # free-form label suffix for cells distinguished only by engine_kw
    # (e.g. "+fused" for the Pallas power-iteration corner) — labels key
    # the semantic baseline, so they must stay unique per cell
    tag: str = ""

    @property
    def label(self) -> str:
        name = self.engine
        if self.dense_model:
            name += "-dense"
        if self.precision_bits != "32":
            name += f"@{self.precision_bits}"
        if self.wire_quant != "none":
            name += f"@{self.wire_quant}"
        if self.dcn_quant:
            name += f"@dcn-{self.dcn_quant}"
        if self.donate:
            name += "+donate"
        if self.staleness:
            name += f"+async{self.staleness}"
        if self.robust != "none":
            name += f"+{self.robust}"
        if self.slice_faults:
            name += "+slfault"
        name += self.tag
        return f"{name}/{self.topology}/{self.pipeline}"

    @property
    def sliced(self) -> bool:
        return self.topology.startswith("sliced")


@dataclasses.dataclass
class CellProgram:
    """A traced matrix cell plus everything the rules consume."""

    cell: TraceCell
    engine: object
    state: object
    args: tuple
    block: int  # k sites folded per device (vmap: all of them)
    audit: ProgramAudit
    compiled: object  # only for donate cells
    path: str
    # the r18 sliced topology, derived from the cell's ACTUAL mesh (never
    # hardcoded by the rule driver): 1 / 0 on unsliced cells
    slices: int = 1
    sites_per_slice: int = 0
    # the params template the wire models charge (r20): the SHARED subtree
    # on personalized cells — head leaves never ship, so charging them
    # would make S002's proof vacuous — the full tree otherwise
    wire_template: object = None


def build_cell_inputs(cell: TraceCell, engine=None) -> tuple:
    """``(task, engine, opt, state, args, mesh)`` for one matrix cell — the
    ONE place the tiny CPU corner (model dims, shapes, RNG seeds) is
    defined. :func:`trace_cell`, the S005 identity gate and the tier-1
    identity harness (tests/test_lowering_identity.py) all build from here,
    so a change to the epoch signature or the corner's shapes is made once.
    ``engine`` overrides the registry engine — the hook test fixtures use it
    to trace deliberately-broken engines."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..engines import make_engine
    from ..models import MSANNet
    from ..parallel.mesh import host_mesh, sliced_site_mesh
    from ..trainer.steps import (
        FederatedTask,
        init_train_state,
        make_optimizer,
    )

    S = {"fold": 4, "fold4": 8, "sliced": 8, "sliced4": 16}.get(
        cell.topology, 2
    )
    steps, B, N = 2, 4, 8
    if cell.dense_model:
        # every leaf non-compressible ([1, 2] kernel + bias): the low-rank
        # engines' dense fallback path carries the whole wire
        model = MSANNet(in_size=1, hidden_sizes=(), out_size=2)
    else:
        model = MSANNet(in_size=6, hidden_sizes=(8,), out_size=2)
    D = model.in_size
    task = FederatedTask(model)
    if engine is None:
        engine = make_engine(
            cell.engine, precision_bits=cell.precision_bits,
            wire_quant=cell.wire_quant, robust_agg=cell.robust,
            dcn_wire_quant=cell.dcn_quant,
            **dict(cell.engine_kw),
        )
    opt = make_optimizer("adam", 1e-2)
    if cell.topology in ("mesh", "fold", "fold4"):
        mesh = host_mesh(2)
    elif cell.sliced:
        # the r18 three-tier corner: 2 slices × 2 site members over 4 CPU
        # devices, with K = S/4 virtual sites packed per member
        mesh = sliced_site_mesh(2, S // 2, S // 4)
    else:
        mesh = None
    state = init_train_state(
        task, engine, opt, jax.random.PRNGKey(0),
        jnp.ones((B, D), jnp.float32), num_sites=S,
        staleness_bound=cell.staleness,
        overlap_rounds=cell.overlap,
        reputation=cell.robust != "none",
        personalize=dict(cell.epoch_kw).get("personalize", ()),
    )
    rng = np.random.default_rng(0)
    if cell.pipeline == "device":
        args = (
            state,
            jnp.asarray(rng.normal(size=(S, N, D)).astype(np.float32)),
            jnp.zeros((S, N), jnp.int32),
            jnp.zeros((S, steps, B), jnp.int32),
        )
    else:
        args = (
            state,
            jnp.asarray(rng.normal(size=(S, steps, B, D)).astype(np.float32)),
            jnp.zeros((S, steps, B), jnp.int32),
            jnp.ones((S, steps, B), jnp.float32),
        )
    if cell.slice_faults:
        # the r19 slice-liveness input: [num_slices, rounds] with slice 1
        # dead in round 0 — fed after the positional optional inputs
        # (live / [poison] / attack), which ride as empty-pytree Nones
        slice_mask = jnp.asarray([[1.0, 1.0], [0.0, 1.0]], jnp.float32)
        pad = (None, None, None) if cell.pipeline == "device" else (None, None)
        args = args + pad + (slice_mask,)
    return task, engine, opt, state, args, mesh


def trace_cell(cell: TraceCell, engine=None) -> CellProgram:
    """Build and trace one matrix cell's REAL epoch program (tiny shapes,
    CPU)."""
    from ..parallel.mesh import pack_factor
    from ..trainer.steps import epoch_program_artifacts, make_train_epoch_fn

    task, engine, opt, state, args, mesh = build_cell_inputs(cell, engine)
    fn = make_train_epoch_fn(
        task, engine, opt, mesh=mesh, pipeline=cell.pipeline,
        donate_state=cell.donate, staleness_bound=cell.staleness,
        overlap_rounds=cell.overlap, robust_agg=cell.robust,
        # slice-fault cells trace the FULL r19 machinery (mask gate +
        # quorum hold) so the wire proofs cover it
        min_slices=2 if cell.slice_faults else 1,
        # privacy-plane cells (r20): dp / personalize live in the builder
        **dict(cell.epoch_kw),
    )
    closed, _, comp = epoch_program_artifacts(fn, *args, compiled=cell.donate)
    S = args[1].shape[0]
    block = S if mesh is None else pack_factor(mesh, S)
    from ..parallel.mesh import slice_count

    slices = slice_count(mesh)
    # personalized cells charge the SHARED subtree only — exactly what the
    # traced program ships (trainer/steps.py _eng_grads)
    wire_tmpl = state.params
    pers = dict(cell.epoch_kw).get("personalize", ())
    if pers:
        from ..privacy.personalize import head_leaf_paths, strip_tree

        wire_tmpl = strip_tree(
            state.params, head_leaf_paths(state.params, pers),
            keep_head=False,
        )
    return CellProgram(
        cell=cell, engine=engine, state=state, args=args, block=block,
        audit=audit_jaxpr(closed), compiled=comp,
        path=f"trace://{cell.label}",
        slices=slices,
        sites_per_slice=S // slices if slices > 1 else 0,
        wire_template=wire_tmpl,
    )


#: engine corners: the three registry engines plus the low-rank engines'
#: non-compressible fallback (the "fourth engine" — same registry entry,
#: dense-only workload, entirely different wire)
_ENGINE_CORNERS = (
    ("dSGD", (), False),
    ("rankDAD", (("dad_num_pow_iters", 2), ("dad_reduction_rank", 2)), False),
    ("powerSGD", (("dad_reduction_rank", 2),), False),
    ("rankDAD", (("dad_reduction_rank", 4),), True),
)


def default_matrix() -> list:
    """The full engine × topology × pipeline matrix plus the precision-flow
    and donation-audit corners."""
    cells = [
        TraceCell(name, topo, pipe, engine_kw=kw, dense_model=dense)
        for name, kw, dense in _ENGINE_CORNERS
        for topo in ("vmap", "mesh", "fold")
        for pipe in ("host", "device")
    ]
    # bf16 wire: S002's byte proof must survive quantization and S004 must
    # see the low-precision dots
    cells += [
        TraceCell(name, "mesh", "host", precision_bits="16", engine_kw=kw)
        for name, kw, dense in _ENGINE_CORNERS
        if not dense
    ]
    # deeper site packing (K=4/device, r12): the per-device wire proof at a
    # pack factor where a per-site model would be 4x wrong — the K-scaling
    # factor gather (rankDAD), the K-invariant psum wire (dSGD, device
    # pipeline), and the quantized packed partial (bf16 dSGD)
    cells += [
        TraceCell(
            "rankDAD", "fold4", "host",
            engine_kw=(("dad_num_pow_iters", 2), ("dad_reduction_rank", 2)),
        ),
        TraceCell("dSGD", "fold4", "device"),
        TraceCell("dSGD", "fold4", "host", precision_bits="16"),
    ]
    # buffered-async cells (r13): every engine corner under the staleness
    # mode on a real mesh — S001 (buffer selects stay inside the scan, no
    # stray collectives) and S002 (the buffered round's wire is EXACTLY the
    # bulk-sync wire: buffering spends HBM, never bytes) — plus a packed
    # async corner (per-device buffers on the [K] block) and an async
    # donation proof (the buffer leaves must alias like every other carried
    # state, or async mode silently doubles a params-sized residency)
    cells += [
        TraceCell(name, "mesh", "host", engine_kw=kw, dense_model=dense,
                  staleness=2)
        for name, kw, dense in _ENGINE_CORNERS
    ]
    cells += [
        TraceCell("dSGD", "fold", "device", staleness=2),
        TraceCell("dSGD", "vmap", "device", donate=True, staleness=2),
    ]
    # donation proof: compiled executables for the trainer's real default
    # (device pipeline + donated state) on both topologies
    cells += [
        TraceCell("dSGD", "vmap", "device", donate=True),
        TraceCell(
            "powerSGD", "mesh", "device", donate=True,
            engine_kw=(("dad_reduction_rank", 2),),
        ),
    ]
    # quantized wires (r14): int8 across the engine corners plus fp8, the
    # stochastic-rounding chain, and a packed-partial re-quantization cell —
    # S002 must prove the codec-itemsize bytes against the traced
    # quant→collective→dequant chain, and S004 must not read the
    # dequantized f32 operand as an upcast
    cells += [
        TraceCell(name, "mesh", "host", engine_kw=kw, wire_quant="int8")
        for name, kw, dense in _ENGINE_CORNERS
        if not dense
    ]
    cells += [
        TraceCell("dSGD", "mesh", "host", wire_quant="fp8"),
        TraceCell(
            "rankDAD", "mesh", "host", wire_quant="fp8",
            engine_kw=(("dad_num_pow_iters", 2), ("dad_reduction_rank", 2)),
        ),
        TraceCell("dSGD", "fold4", "device", wire_quant="int8"),
        TraceCell(
            "dSGD", "mesh", "host", wire_quant="int8",
            engine_kw=(("wire_stochastic", True),), tag="+sr",
        ),
        # fused Pallas power iteration in the traced program (interpret on
        # CPU): the kernel changes where the factorization computes, never
        # what ships — S001/S002 must stay green with the pallas_call in
        # the jaxpr, incl. on a packed (K=4) cell where the kernel runs
        # under the custom_vmap member fold
        TraceCell(
            "rankDAD", "mesh", "host", tag="+fused",
            engine_kw=(("dad_num_pow_iters", 2), ("dad_reduction_rank", 2),
                       ("fused_poweriter", True)),
        ),
        TraceCell(
            "rankDAD", "fold4", "host", tag="+fused", wire_quant="int8",
            engine_kw=(("dad_num_pow_iters", 2), ("dad_reduction_rank", 2),
                       ("fused_poweriter", True)),
        ),
        # overlapped rounds (r14): the stash apply's collectives are the
        # SAME wire as the legacy round (S002), and the stash selects stay
        # inside the rounds scan (S001)
        TraceCell("dSGD", "mesh", "host", tag="+overlap", overlap=True),
        TraceCell("dSGD", "fold", "device", tag="+overlap", overlap=True),
        # the stash must alias like every other carried state under
        # donation, or overlap mode silently doubles a grads-sized residency
        TraceCell("dSGD", "vmap", "device", donate=True, overlap=True,
                  tag="+overlap"),
    ]
    # byzantine-robust aggregation (r17): the robust-mode wire models proved
    # against the traced programs — the gather reducers' genuinely
    # pack-scaling per-site payload gathers (S002 on packed AND unpacked
    # cells: a pack-unaware robust model would be 4x wrong on fold4),
    # norm_clip's unchanged psum wire + two tiny bookkeeping gathers
    # (composing with the int8 codec), rankDAD's factor gather unchanged
    # with only the dense half switching to gathers, and powerSGD's factor
    # psums becoming factor gathers. The reputation layer's scalar psums
    # must stay inside the rounds scan (S001) on every robust cell.
    cells += [
        TraceCell("dSGD", "mesh", "host", robust="trimmed_mean"),
        TraceCell("dSGD", "fold4", "device", robust="trimmed_mean"),
        TraceCell("dSGD", "mesh", "host", robust="norm_clip"),
        TraceCell("dSGD", "mesh", "host", robust="norm_clip",
                  wire_quant="int8"),
        TraceCell(
            "rankDAD", "mesh", "host", robust="coordinate_median",
            engine_kw=(("dad_num_pow_iters", 2), ("dad_reduction_rank", 2)),
        ),
        TraceCell(
            "rankDAD", "fold4", "host", robust="coordinate_median",
            engine_kw=(("dad_num_pow_iters", 2), ("dad_reduction_rank", 2)),
        ),
        TraceCell(
            "powerSGD", "mesh", "host", robust="trimmed_mean",
            engine_kw=(("dad_reduction_rank", 2),),
        ),
    ]
    # multi-slice cells (r18): the three-tier topology across the engine
    # corners — the per-TIER wire proofs. The fused (no DCN codec) form
    # must show the ICI model unchanged with the (slice, site) reduces
    # covering the DCN model at the intra wire dtype; the int8-DCN split
    # cells must show slice-ONLY collectives carrying exactly one
    # codec-quantized per-slice partial per payload (dSGD: the whole tree
    # as ONE fused vector) at ≤ ¼ the f32 bytes — proven against traced
    # operand shapes, incl. the packed K=4 corner (sliced4) where a
    # per-device-charged DCN model would be 4x wrong.
    cells += [
        TraceCell(name, "sliced", "host", engine_kw=kw, dense_model=dense)
        for name, kw, dense in _ENGINE_CORNERS
    ]
    cells += [
        TraceCell("dSGD", "sliced", "host", dcn_quant="int8"),
        TraceCell("dSGD", "sliced4", "device", wire_quant="int8",
                  dcn_quant="int8"),
        TraceCell(
            "rankDAD", "sliced4", "host", wire_quant="int8",
            dcn_quant="int8",
            engine_kw=(("dad_num_pow_iters", 2), ("dad_reduction_rank", 2)),
        ),
        TraceCell(
            "powerSGD", "sliced", "host", dcn_quant="int8",
            engine_kw=(("dad_reduction_rank", 2),),
        ),
        # robust × sliced (the review corner): the gather reducers' dense
        # payload must cross the slice hop DCN-re-quantized exactly as the
        # engines' dcn models charge it — the powerSGD dense-gather path
        # shipped f32 across DCN against an int8 model until this cell
        TraceCell(
            "powerSGD", "sliced", "host", dcn_quant="int8",
            robust="trimmed_mean", engine_kw=(("dad_reduction_rank", 2),),
        ),
        TraceCell("dSGD", "sliced", "host", dcn_quant="int8",
                  robust="norm_clip"),
    ]
    # slice-fault cells (r19): the slice-liveness mask + min_slices=2
    # quorum in the traced program — "engines unchanged under masking":
    # S002's ICI figures and the DCN-tier proof must verify the SAME wire
    # as the fault-free sliced cells (the mask is a replicated input and
    # local reductions, zero new collectives), incl. the packed
    # int8-both-tiers corner and the device pipeline.
    cells += [
        TraceCell(name, "sliced", "host", engine_kw=kw, slice_faults=True)
        for name, kw, dense in _ENGINE_CORNERS
        if not dense
    ]
    cells += [
        TraceCell("dSGD", "sliced4", "device", wire_quant="int8",
                  dcn_quant="int8", slice_faults=True),
    ]
    # secure-aggregation masked wires (r20, privacy/secure_agg.py): S002
    # must prove the int32 grid model — the SAME dense shapes as the legacy
    # psum at 4 B/element, the masked partial K-invariant under packing —
    # against the traced padded program (the per-leaf amax pmax scalars are
    # genuine collectives but carry () operands, outside payload
    # accounting), S001 must keep the whole pad→psum chain inside the
    # rounds scan, and the sliced cell must show the fused exact
    # (slice, site) int32 reduce covering the DCN model with no
    # slice-boundary re-quantization.
    cells += [
        TraceCell("dSGD", "mesh", "host",
                  engine_kw=(("secure_agg", "mask"),), tag="+secureagg"),
        TraceCell("dSGD", "fold4", "device",
                  engine_kw=(("secure_agg", "mask"),), tag="+secureagg"),
        TraceCell("dSGD", "vmap", "device", donate=True,
                  engine_kw=(("secure_agg", "mask"),), tag="+secureagg"),
        TraceCell("dSGD", "sliced", "host",
                  engine_kw=(("secure_agg", "mask"),), tag="+secureagg"),
    ]
    # DP-SGD + personalized heads (r20): the mechanism/partition live in
    # the epoch builder, not the engine — their wire impact is proven on
    # dedicated cells below via epoch_kw (dp adds ZERO collectives; the
    # personalized cell's wire model covers the SHARED subtree only)
    cells += [
        TraceCell("dSGD", "fold4", "device", tag="+dp",
                  epoch_kw=(("dp_clip", 1.0),
                            ("dp_noise_multiplier", 0.5))),
        TraceCell("dSGD", "mesh", "host", tag="+personal",
                  epoch_kw=(("personalize", ("fc_out",)),)),
    ]
    return cells


#: the S005 identity pairs, declaratively: label -> (epoch-build kwargs,
#: expect_identical). Off-forms (True) must compile the exact baseline
#: program; opt-outs/opt-ins (False) must genuinely change it — if those
#: stop diverging, "compiled out" has silently stopped being true. ``None``
#: kwargs means the DEFAULT build traced under ``jax.checking_leaks`` (the
#: sanitizer's observation mode, which must not perturb what it observes).
#: tests/test_lowering_identity.py is the tier-1 mirror of exactly this
#: table — extend it here and both the CLI gate and the tests pick it up.
IDENTITY_CASES = {
    "telemetry-off": (dict(telemetry=False), True),
    "faults-default": (dict(quarantine_rounds=3), True),
    "sanitize-leaks": (None, True),
    "faults-opt-out": (dict(quarantine_rounds=-1), False),
    "telemetry-on": (dict(telemetry=True), False),
    # elastic rounds (r13): staleness_bound=0 must compile the EXACT
    # bulk-sync program (the async machinery statically out), and a positive
    # bound must genuinely add the buffered round
    "async-off": (dict(staleness_bound=0), True),
    "async-on": (dict(staleness_bound=2), False),
    # quantized wires (r14): wire_quant="none" must keep the legacy
    # precision_bits program byte-for-byte, and each codec must genuinely
    # change the wire path. The reserved "engine" key rebuilds the corner's
    # engine with the given make_engine overrides (the knob lives in the
    # engine, not the epoch builder).
    "wirequant-off": (dict(engine=dict(wire_quant="none")), True),
    "wirequant-bf16": (dict(engine=dict(wire_quant="bf16")), False),
    "wirequant-int8": (dict(engine=dict(wire_quant="int8")), False),
    # overlapped rounds (r14): off = the exact legacy round, on = the
    # double-buffered stash apply genuinely in the program
    "overlap-off": (dict(overlap_rounds=False), True),
    "overlap-on": (dict(overlap_rounds=True), False),
    # byzantine-robust aggregation (r17): robust_agg="none" must compile the
    # EXACT legacy program (engine AND epoch builder both off — the
    # acceptance gate), and each robust mode must genuinely change it (the
    # inverse divergence gate: if the gather reducers / norm clip / the
    # reputation layer stop appearing, "robust" has silently become a no-op)
    "robust-off": (
        dict(robust_agg="none", engine=dict(robust_agg="none")), True,
    ),
    "robust-trimmed": (
        dict(robust_agg="trimmed_mean",
             engine=dict(robust_agg="trimmed_mean")),
        False,
    ),
    "robust-normclip": (
        dict(robust_agg="norm_clip", engine=dict(robust_agg="norm_clip")),
        False,
    ),
    # privacy plane (r20): every off-form must compile the EXACT legacy
    # program — dp_clip=dp_noise_multiplier=0 (privacy/dpsgd.py),
    # secure_agg="off" (privacy/secure_agg.py, an engine knob) and
    # personalize=() (privacy/personalize.py) — and each on-form must
    # genuinely inject its machinery (the inverse gate: a dp-on program
    # that stops diverging is a mechanism that silently stopped running,
    # and every ε it reports is a lie)
    "dp-off": (dict(dp_clip=0.0, dp_noise_multiplier=0.0), True),
    "dp-on": (dict(dp_clip=1.0, dp_noise_multiplier=0.5), False),
    "dp-clip-only": (dict(dp_clip=1.0), False),
    "secureagg-off": (dict(engine=dict(secure_agg="off")), True),
    "secureagg-on": (dict(engine=dict(secure_agg="mask")), False),
    "personalize-off": (dict(personalize=()), True),
    "personalize-on": (dict(personalize=("fc_out",)), False),
}

#: the rankDAD corner's cases — the fused power-iteration kernel only
#: exists in the compression engines' program. The BASE cell pins
#: fused_poweriter=False (not the auto default, which resolves per backend
#: — on a TPU host auto=ON would flip both pairs' expectations and fail the
#: gate spuriously), so off == baseline and on must inject the pallas_call
#: on EVERY backend.
IDENTITY_CASES_RANKDAD = {
    "poweriter-fused-off": (dict(engine=dict(fused_poweriter=False)), True),
    "poweriter-fused-on": (dict(engine=dict(fused_poweriter=True)), False),
}

#: the corner IDENTITY_CASES_RANKDAD runs on (small ranks keep the trace
#: cheap; vmap/host is the cheapest topology with a factorization path)
RANKDAD_IDENTITY_CELL = TraceCell(
    "rankDAD", "vmap", "host",
    engine_kw=(("dad_num_pow_iters", 2), ("dad_reduction_rank", 2),
               ("fused_poweriter", False)),
)


def identity_text_fn(cell: TraceCell):
    """``text(**case_kw)`` builder for one identity corner — the ONE
    implementation behind the S005 CLI gate and the tier-1 mirror
    (tests/test_lowering_identity.py). ``case_kw`` may carry the reserved
    ``engine`` key: a dict of ``make_engine`` overrides layered onto the
    cell's engine kwargs (for knobs that live in the engine — wire_quant,
    fused_poweriter)."""
    from ..engines import make_engine
    from ..trainer.steps import make_train_epoch_fn

    task, engine, opt, _, args, mesh = build_cell_inputs(cell)

    def text(**kw):
        kw = dict(kw)
        eng_kw = kw.pop("engine", None)
        eng = engine
        if eng_kw:
            eng = make_engine(
                cell.engine, precision_bits=cell.precision_bits,
                **{"wire_quant": cell.wire_quant,
                   **dict(cell.engine_kw), **eng_kw},
            )
        fn = make_train_epoch_fn(task, eng, opt, mesh=mesh, **kw)
        return fn.lower(*args).as_text()

    return text


def slices_identity_pairs() -> list:
    """The r18 S005 pairs, as ``(label, text_a, text_b, expect_identical)``:

    - ``slices-off`` — the ``num_slices=1`` opt-out must lower the EXACT
      legacy single-mesh program (sliced_site_mesh(1, ...) collapses to
      packed_site_mesh; if it ever starts building a 1-deep slice axis
      instead, this gate trips before any perf number does);
    - ``slices-on`` — the sliced topology must genuinely change the program
      (the inverse gate: a "sliced" mesh that silently flattens back would
      make every multi-slice claim vacuous);
    - ``slices-dcn-int8`` — the DCN codec must genuinely split the
      inter-slice hop (re-quantized slice-only collectives in the program)
      vs the fused no-codec form;
    - ``slicefaults-off`` (r19) — a sliced epoch built WITH a min_slices
      quorum but fed NO slice mask must lower the exact r18 sliced program
      (the slice-fault machinery gates on the mask's presence, not the
      config knob — all-slices-live IS the PR 13 program);
    - ``slicefaults-on`` (r19) — feeding the slice mask must genuinely
      change the program (the inverse gate: if the gate/hold ops stop
      appearing, slice faults have silently become a no-op).

    Shared by the CLI S005 gate and the tier-1 mirror
    (tests/test_multislice.py)."""
    import jax.numpy as jnp
    import numpy as np

    from ..engines import make_engine
    from ..models import MSANNet
    from ..parallel.mesh import packed_site_mesh, sliced_site_mesh
    from ..trainer.steps import (
        FederatedTask,
        init_train_state,
        make_optimizer,
        make_train_epoch_fn,
    )

    import jax

    S, steps, B, D = 8, 2, 4, 6
    model = MSANNet(in_size=D, hidden_sizes=(8,), out_size=2)
    task = FederatedTask(model)
    opt = make_optimizer("adam", 1e-2)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(S, steps, B, D)).astype(np.float32))
    y = jnp.zeros((S, steps, B), jnp.int32)
    w = jnp.ones((S, steps, B), jnp.float32)

    def text(mesh, slice_live=None, min_slices=1, **engine_kw):
        engine = make_engine("dSGD", **engine_kw)
        state = init_train_state(
            task, engine, opt, jax.random.PRNGKey(0),
            jnp.ones((B, D), jnp.float32), num_sites=S,
        )
        fn = make_train_epoch_fn(
            task, engine, opt, mesh=mesh, min_slices=min_slices
        )
        if slice_live is None:
            return fn.lower(state, x, y, w).as_text()
        return fn.lower(state, x, y, w, None, None, slice_live).as_text()

    legacy = text(packed_site_mesh(S, 2))
    off = text(sliced_site_mesh(1, S, 2))
    sliced = text(sliced_site_mesh(2, S // 2, 2))
    sliced_dcn = text(
        sliced_site_mesh(2, S // 2, 2), dcn_wire_quant="int8"
    )
    # r19: the slice-fault gate keys on the MASK input, not the quorum knob
    mask = jnp.asarray(np.array([[1.0, 1.0], [0.0, 1.0]], np.float32))
    slfault_off = text(sliced_site_mesh(2, S // 2, 2), min_slices=2)
    slfault_on = text(
        sliced_site_mesh(2, S // 2, 2), slice_live=mask, min_slices=2
    )
    return [
        ("slices-off", legacy, off, True),
        ("slices-on", legacy, sliced, False),
        ("slices-dcn-int8", sliced, sliced_dcn, False),
        ("slicefaults-off", sliced, slfault_off, True),
        ("slicefaults-on", sliced, slfault_on, False),
    ]


def _identity_gate() -> list:
    """The S005 program-identity pairs (:data:`IDENTITY_CASES` on the
    flagship dSGD corner, :data:`IDENTITY_CASES_RANKDAD` on the rankDAD
    one, plus the r18 multi-slice pairs)."""
    import jax

    pairs = []
    for cell, cases in (
        (TraceCell("dSGD", "vmap", "host"), IDENTITY_CASES),
        (RANKDAD_IDENTITY_CELL, IDENTITY_CASES_RANKDAD),
    ):
        text = identity_text_fn(cell)
        base = text()
        for label, (kw, expect_identical) in cases.items():
            if kw is None:
                with jax.checking_leaks():
                    variant = text()
            else:
                variant = text(**kw)
            pairs.append((label, base, variant, expect_identical))
    pairs += slices_identity_pairs()
    return check_lowering_identity(pairs)


# ---------------------------------------------------------------------------
# serving cells (r15)
# ---------------------------------------------------------------------------


def check_no_collectives(collectives: list, path: str) -> list:
    """S001, serving form: the REQUEST PATH must contain ZERO cross-device
    collectives — inference is replicated per device, and a stray psum in a
    serving program would stall every request on every other device's
    traffic (the training rule merely confines collectives to the rounds
    scan; serving forbids them outright)."""
    findings = []
    for site in collectives:
        if site.prim not in COMM_PRIMS:
            continue
        findings.append(Finding(
            rule="S001", path=path, line=0, col=0,
            message=(
                f"serving request path contains a cross-device collective "
                f"'{site.prim}' (axes {site.named_axes or '(positional)'}) "
                f"— inference must be replicated, never synchronized"
            ),
            snippet=f"{site.prim} in-request-path",
            fixit="keep collectives out of eval_forward/ICALstmStream; "
                  "multi-device serving replicates the engine per device",
        ))
    return findings


def build_serving_cell():
    """The real serving programs on a tiny CPU corner: the engine's batched
    (``eval_forward``) and streaming (session gather→step→scatter) jitted
    entries, exactly as :class:`~..serving.engine.InferenceEngine` compiles
    them at warmup. Returns the engine plus per-lane ``(fn, args)``."""
    import jax
    import jax.numpy as jnp

    from ..core.config import NNComputation, TrainConfig
    from ..runner.registry import get_task
    from ..serving.engine import InferenceEngine
    from ..trainer.steps import FederatedTask

    cfg = TrainConfig(task_id=NNComputation.TASK_ICA).with_overrides({
        "ica_args": {
            "num_components": 3, "window_size": 4, "temporal_size": 32,
            "window_stride": 4, "input_size": 8, "hidden_size": 6,
            "bidirectional": False,
        },
    })
    task = FederatedTask(get_task(cfg.task_id).build_model(cfg))
    params, stats = task.init_variables(
        jax.random.PRNGKey(0), jnp.ones((2, 8, 3, 4))
    )
    engine = InferenceEngine(
        cfg, params=params, batch_stats=stats, row_buckets=(4,),
        stream_buckets=(2,), stream_chunk=4, stream_slots=4,
    )
    infer_args = (
        engine._params, engine._stats,
        jnp.zeros((4, 8, 3, 4), jnp.float32), jnp.ones((4,), jnp.float32),
    )
    stream_args = (
        engine._params, engine._stats, engine._table,
        jnp.zeros((2,), jnp.int32), jnp.zeros((2,), jnp.float32),
        jnp.zeros((2, 4, 3, 4), jnp.float32), jnp.ones((2, 4), jnp.float32),
        jnp.ones((2,), jnp.float32),
    )
    return engine, (engine._infer_jit, infer_args), (
        engine._stream_jit, stream_args
    )


def run_serving_checks() -> list:
    """The serving S-rule cells (r15): S001 zero collectives on both lanes,
    S003 the donated session-carry table fully aliases in the compiled
    streaming step, S005 the batched serving program is lowering-identical
    to the trainer's eval forward (the bit-exactness bridge as a program
    property, not just a test vector) — and the streaming program genuinely
    diverges from it (the differ is not trivially green)."""
    import jax

    from ..trainer.steps import epoch_program_artifacts, eval_forward

    findings: list = []
    engine, (infer_jit, infer_args), (stream_jit, stream_args) = (
        build_serving_cell()
    )
    infer_jaxpr, infer_low, _ = epoch_program_artifacts(
        infer_jit, *infer_args, lowered=True
    )
    findings += check_no_collectives(
        audit_jaxpr(infer_jaxpr).collectives, "trace://serving/infer"
    )
    stream_jaxpr, stream_low, stream_comp = epoch_program_artifacts(
        stream_jit, *stream_args, lowered=True, compiled=True
    )
    findings += check_no_collectives(
        audit_jaxpr(stream_jaxpr).collectives, "trace://serving/stream"
    )
    # S003: the session-carry table (stream arg 2, donated) must alias into
    # the returned table — the in-place O(1) session cache claim
    findings += check_donation(
        stream_comp, stream_args, (2,), "trace://serving/stream"
    )
    # S005: the batched lane IS the eval forward — prove it at the lowering
    # level against an independently-built reference program
    task = engine.task
    ref = jax.jit(
        lambda p, s, x, w: eval_forward(task, p, s, x, None, w)
    ).lower(*infer_args).as_text()
    findings += check_lowering_identity(
        [
            ("serve-infer-is-eval-forward", ref, infer_low.as_text(), True),
            ("serve-stream-diverges", ref, stream_low.as_text(), False),
        ],
        path_prefix="lowering://serving/",
    )
    # S003, publish plane (r21): the hot-swap graft must alias EVERY
    # params and batch-stats leaf input→output — a publish is pure buffer
    # donation, so any unaliased leaf means the swap copies (and the
    # "pause is a graft, not a transfer" claim is false)
    swap_args = engine._live
    swap_comp = engine._swap_jit.lower(*swap_args).compile()
    findings += check_donation(
        swap_comp, swap_args, (0, 1), "trace://serving/swap"
    )
    # S001 on the same program: a collective in the swap graft would stall
    # every replica's publish on cross-device traffic
    swap_jaxpr, _, _ = epoch_program_artifacts(engine._swap_jit, *swap_args)
    findings += check_no_collectives(
        audit_jaxpr(swap_jaxpr).collectives, "trace://serving/swap"
    )
    return findings


def run_semantic_checks(cells=None) -> list:
    """Trace the matrix and run every S-rule; returns findings sorted like
    the AST tier's. The CLI gates on this list (after the semantic
    baseline); tests assert it is empty."""
    ensure_cpu_devices()
    findings: list = []
    for cell in (default_matrix() if cells is None else cells):
        prog = trace_cell(cell)
        allowed = None
        if cell.sliced:
            from ..parallel.mesh import MODEL_AXIS, SITE_AXIS, SLICE_AXIS

            allowed = {SITE_AXIS, MODEL_AXIS, SLICE_AXIS}
        findings += check_collective_axes(
            prog.audit.collectives, prog.path, allowed_axes=allowed
        )
        if cell.topology in ("mesh", "fold", "fold4") or cell.sliced:
            # the vmap topology folds all sites onto one device — its
            # "collectives" are local reductions with no wire, so the
            # byte/precision proofs run where communication is real
            import jax

            stats_shapes = tuple(
                tuple(leaf.shape)
                for leaf in jax.tree_util.tree_leaves(prog.state.batch_stats)
            )
            ici_colls = prog.audit.collectives
            if cell.sliced:
                # the ICI proof covers tiers 0+1: slice-ONLY collectives
                # are the DCN tier's (proven by check_dcn_wire below);
                # fused (slice, site) reduces still carry the per-device
                # payload the ICI model describes
                from ..parallel.mesh import SLICE_AXIS

                ici_colls = [
                    c for c in prog.audit.collectives
                    if tuple(c.named_axes) != (SLICE_AXIS,)
                ]
            findings += check_wire_bytes(
                ici_colls, prog.engine, prog.wire_template,
                prog.block, prog.path, stats_shapes=stats_shapes,
            )
            findings += check_precision_flow(
                ici_colls, prog.engine, prog.wire_template,
                prog.block, prog.path,
                require_lowp_dot=(
                    cell.precision_bits == "16"
                    and cell.engine in ("rankDAD", "powerSGD")
                    and not cell.dense_model
                ),
                dots=prog.audit.dots,
            )
            if cell.sliced:
                findings += check_dcn_wire(
                    prog.audit.collectives, prog.engine, prog.wire_template,
                    prog.block, prog.sites_per_slice, prog.path,
                    stats_shapes=stats_shapes, slices=prog.slices,
                )
        if cell.donate:
            findings += check_donation(
                prog.compiled, prog.args, (0,), prog.path
            )
    findings += _identity_gate()
    if cells is None:
        findings += run_serving_checks()
    findings.sort(key=lambda f: (f.path, f.rule, f.snippet))
    return findings
