from .fed_runner import (
    FedDaemon,
    FedRunner,
    SiteRunner,
    auto_site_mesh,
    discover_site_dirs,
    load_site_splits,
)
from .registry import TASKS, TaskSpec, get_task, register_task, task_cache
