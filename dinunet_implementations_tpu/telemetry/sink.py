"""Per-fit telemetry artifacts: ``manifest.json`` + ``metrics.jsonl`` +
trace files.

One :class:`FitTelemetry` per fit (fold), rooted at
``<out_dir>/telemetry/fold_<k>/`` (or ``TrainConfig.telemetry_dir``):

- ``manifest.json`` — written at open: config hash, jax/jaxlib versions,
  backend, mesh topology, engine/task, git rev, package version. The "what
  exactly ran" record every perf/robustness claim should ship with.
- ``metrics.jsonl`` — appended as the fit runs (one fsync-free line per
  record, crash-tolerant): per-epoch rows (loss, per-site grad/residual
  norms, transfer bytes, epoch seconds), instant events (checkpoint,
  preempted, quarantine), and a final summary row (compile count, prefetch
  stall, site health).
- ``trace.jsonl`` / ``trace.chrome.json`` — the span tracer's two output
  forms, written at close (open the chrome one in Perfetto).

The validators at the bottom are the schema contract: the report CLI's
``--validate`` mode (and the CI telemetry smoke job) gate on them.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math
import numbers
import os
import subprocess

from .tracer import SpanTracer

SCHEMA_VERSION = 1

MANIFEST_FILE = "manifest.json"
METRICS_FILE = "metrics.jsonl"
TRACE_JSONL_FILE = "trace.jsonl"
TRACE_CHROME_FILE = "trace.chrome.json"

#: manifest keys every consumer may rely on. fault_plan / attack_plan are
#: REQUIRED (null when no chaos/attack was injected): a fault- or
#: attack-arm's artifact must be reproducible from the manifest alone —
#: before r17 only the config hash landed there and the active plan JSON
#: lived in the shell history. privacy (r20) is the same contract for the
#: DP/secure-agg/personalization knobs: a DP run's artifact carries the
#: exact mechanism parameters its ε claim depends on (null when the whole
#: privacy plane is off).
#: tags (r22) is the fleet-scheduler identity contract: a tenant's
#: artifact carries {"tenant": "<id>"} so a pod packing many studies
#: yields per-study artifacts that self-identify (null for solo fits).
MANIFEST_REQUIRED = frozenset({
    "schema_version", "config_hash", "task_id", "agg_engine", "num_sites",
    "pipeline", "fold", "jax_version", "jaxlib_version", "backend", "mesh",
    "package_version", "git_rev", "fault_plan", "attack_plan", "privacy",
    "tags",
})

#: required metrics.jsonl keys by row kind
ROW_REQUIRED = {
    "epoch": frozenset({
        "kind", "fold", "epoch", "train_loss", "epoch_seconds",
        "transfer_bytes", "site_grad_sq_last", "site_grad_sq_sum",
        "site_residual_sq_sum", "update_sq_last", "payload_bytes",
        # r18 per-tier wire split: inter-slice (DCN) bytes, 0.0 off-slice
        "dcn_bytes", "rounds",
        # r20 privacy plane: spent ε so far (null = DP off/noiseless) —
        # required, so a DP run's per-epoch ε trail cannot silently vanish
        "dp_epsilon",
    }),
    "event": frozenset({"kind", "name"}),
    "summary": frozenset({
        "kind", "fold", "epochs_run", "epoch_compiles", "best_val_epoch",
        # elastic-rounds rollup (robustness/membership.py membership_rollup):
        # a dict for daemon-mode serves, null for batch-job fits — the key
        # itself is part of the schema contract
        "membership",
    }),
    # serving path (r15, serving/engine.py): one row per microbatch dispatch
    # (queue/padding visibility) ...
    "dispatch": frozenset({
        "kind", "lane", "bucket", "rows", "pad_rows", "queue_depth",
    }),
    # ... and the run's rollup. The latency percentiles are REQUIRED keys —
    # the CI serving smoke gates on `report --validate`, so a serving run
    # that lost its latency record cannot validate.
    "serve_summary": frozenset({
        "kind", "task_id", "requests", "samples", "dispatches",
        "latency_ms_p50", "latency_ms_p95", "latency_ms_p99",
        "requests_per_s", "samples_per_s", "pad_waste_pct",
        "bucket_hit_rate", "warmup_seconds", "compiles_after_warmup",
    }),
    # train-to-serve CD (r21, serving/publish.py): one row per attempted
    # publish — outcome is "swapped" / "rejected-shadow" / "rejected-stale";
    # pause_ms is the donated-swap wall time (null when nothing swapped)
    "publish": frozenset({
        "kind", "digest", "outcome", "pause_ms", "shadow",
    }),
    # ... and one per SLO-burn rollback decision after a swap: burn is the
    # post-swap window's error-budget burn, rolled_back whether the previous
    # weights were grafted back
    "rollback": frozenset({
        "kind", "digest", "burn", "rolled_back", "window_samples",
    }),
}


def _finite(value):
    """Recursively replace non-finite reals with ``None`` (see
    :meth:`FitTelemetry.append` — strict-JSON output contract). Covers
    numpy float scalars too, so a stray un-cast ``np.float32(nan)`` cannot
    slip past to ``allow_nan=False`` and crash the append."""
    if isinstance(value, numbers.Real) and not isinstance(
            value, numbers.Integral):
        f = float(value)
        return f if math.isfinite(f) else None
    if isinstance(value, dict):
        return {k: _finite(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_finite(v) for v in value]
    return value


def _git_rev(repo_hint: str | None = None) -> str:
    """Best-effort ``git rev-parse HEAD`` of the code's checkout; "" when the
    package runs from a wheel / outside any repo."""
    cwd = repo_hint or os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=cwd, capture_output=True,
            text=True, timeout=5,
        )
        return out.stdout.strip() if out.returncode == 0 else ""
    except (OSError, subprocess.SubprocessError):
        # no git binary / not a checkout: the manifest simply records ""
        return ""


def config_hash(cfg) -> str:
    """Stable hash of a TrainConfig (or any jsonable mapping/dataclass)."""
    if dataclasses.is_dataclass(cfg):
        cfg = dataclasses.asdict(cfg)
    blob = json.dumps(cfg, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def mesh_topology(mesh) -> dict | None:
    """``{axis: size}`` for a mesh, ``None`` for the vmap-folded path."""
    if mesh is None:
        return None
    return {str(k): int(v) for k, v in dict(mesh.shape).items()}


def privacy_manifest(cfg) -> dict | None:
    """The active privacy-plane configuration, verbatim (r20) — ``None``
    when the whole plane is off (dp off, secure_agg off, no personalized
    heads), so a legacy run's manifest reads exactly like before with one
    extra null key. The dict carries every knob the artifact's ε /
    masked-wire / personalization claims depend on: a DP run is
    reproducible from the manifest alone."""
    dp_clip = float(getattr(cfg, "dp_clip", 0.0) or 0.0)
    dp_noise = float(getattr(cfg, "dp_noise_multiplier", 0.0) or 0.0)
    secure = getattr(cfg, "secure_agg", "off") or "off"
    personalize = tuple(getattr(cfg, "personalize", ()) or ())
    if dp_clip <= 0.0 and dp_noise <= 0.0 and secure == "off" \
            and not personalize:
        return None
    return {
        "dp_clip": dp_clip,
        "dp_noise_multiplier": dp_noise,
        "dp_seed": int(getattr(cfg, "dp_seed", 0) or 0),
        "dp_delta": float(getattr(cfg, "dp_delta", 1e-5)),
        "dp_epsilon_budget": float(
            getattr(cfg, "dp_epsilon_budget", 0.0) or 0.0
        ),
        "secure_agg": secure,
        "secure_agg_seed": int(getattr(cfg, "secure_agg_seed", 0) or 0),
        "personalize": list(personalize),
    }


def build_manifest(cfg, mesh=None, fold: int = 0, fault_plan=None,
                   attack_plan=None, tags: dict | None = None) -> dict:
    import jax
    import jaxlib

    from .. import __version__

    return {
        "schema_version": SCHEMA_VERSION,
        "config_hash": config_hash(cfg),
        "task_id": cfg.task_id,
        "agg_engine": cfg.agg_engine,
        "num_sites": int(getattr(cfg, "num_sites", 1)),
        "pipeline": cfg.pipeline,
        "fold": int(fold),
        "jax_version": jax.__version__,
        "jaxlib_version": jaxlib.__version__,
        "backend": jax.default_backend(),
        "mesh": mesh_topology(mesh),
        "package_version": __version__,
        "git_rev": _git_rev(),
        # the active chaos/attack plans, verbatim (null = none): a fault or
        # attack arm is reproducible from the artifact alone (r17)
        "fault_plan": fault_plan.to_json() if fault_plan is not None else None,
        "attack_plan": (
            attack_plan.to_json() if attack_plan is not None else None
        ),
        # the active privacy-plane knobs, verbatim (r20; null = plane off):
        # DP runs are reproducible from the artifact alone
        "privacy": privacy_manifest(cfg),
        # scheduler identity tags (r22; null = solo fit): which tenant of a
        # packed pod this artifact belongs to — the per-tenant isolation
        # story is auditable from the artifacts alone
        "tags": dict(tags) if tags else None,
        "config": cfg.to_dict(),
    }


class FitTelemetry:
    """The per-fit artifact sink. Construct via :meth:`open`; feed epoch rows
    and events as the fit runs; :meth:`close` writes the trace files (called
    from the trainer's ``finally``, so ``Preempted``/crashes still leave
    complete artifacts)."""

    def __init__(self, dirpath: str, tracer: SpanTracer):
        self.dir = dirpath
        self.tracer = tracer
        self._closed = False
        os.makedirs(dirpath, exist_ok=True)

    @classmethod
    def open(cls, dirpath: str, cfg, mesh=None, fold: int = 0,
             tracer: SpanTracer | None = None, fault_plan=None,
             attack_plan=None, tags: dict | None = None) -> "FitTelemetry":
        sink = cls(dirpath, tracer or SpanTracer())
        manifest = build_manifest(
            cfg, mesh=mesh, fold=fold, fault_plan=fault_plan,
            attack_plan=attack_plan, tags=tags,
        )
        with open(os.path.join(dirpath, MANIFEST_FILE), "w") as fh:
            json.dump(manifest, fh, indent=2, default=str)
        # truncate any stale rows from a previous run of this fold — rows
        # within ONE fit then append crash-tolerantly
        open(os.path.join(dirpath, METRICS_FILE), "w").close()
        return sink

    def append(self, row: dict) -> None:
        """One metrics.jsonl record (kind: epoch | event | summary).

        Strict RFC 8259 output: Python's ``json.dumps`` would happily emit a
        bare ``NaN`` token (valid for json.loads, fatal for JSON.parse / jq /
        most JSONL ingesters), and NaN is exactly what ``grad_sq_last`` and
        an all-dead epoch's ``train_loss`` carry by design — so non-finite
        floats are serialized as ``null`` (null == "non-finite here", the
        blow-up signal survives), enforced by ``allow_nan=False``."""
        if self._closed:
            return
        with open(os.path.join(self.dir, METRICS_FILE), "a") as fh:
            fh.write(
                json.dumps(_finite(row), default=float, allow_nan=False)
                + "\n"
            )

    def event(self, name: str, **attrs) -> None:
        """Instant event, recorded in BOTH artifacts: the trace (timeline
        position) and metrics.jsonl (greppable next to the epoch rows)."""
        # API-boundary forward: the NAME was already a literal/constant at
        # this method's (linted) call site
        self.tracer.event(name, **attrs)  # jaxlint: disable=R007
        self.append({"kind": "event", "name": name, **attrs})

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.tracer.write_jsonl(os.path.join(self.dir, TRACE_JSONL_FILE))
        self.tracer.write_chrome_trace(
            os.path.join(self.dir, TRACE_CHROME_FILE)
        )


# ---------------------------------------------------------------------------
# schema validation — the contract CI gates on
# ---------------------------------------------------------------------------


def validate_manifest(manifest: dict) -> list[str]:
    """Problems with a manifest dict ([] == valid)."""
    problems = []
    if not isinstance(manifest, dict):
        return [f"manifest is {type(manifest).__name__}, not an object"]
    missing = MANIFEST_REQUIRED - set(manifest)
    if missing:
        problems.append(f"manifest missing keys: {sorted(missing)}")
    if manifest.get("schema_version") not in (SCHEMA_VERSION,):
        problems.append(
            f"manifest schema_version {manifest.get('schema_version')!r} != "
            f"{SCHEMA_VERSION}"
        )
    return problems


def validate_metrics_rows(rows: list[dict]) -> list[str]:
    """Problems with a metrics.jsonl row list ([] == valid). Unknown kinds
    are findings (a typo'd kind would silently vanish from the report)."""
    problems = []
    for i, row in enumerate(rows):
        kind = row.get("kind")
        required = ROW_REQUIRED.get(kind)
        if required is None:
            problems.append(f"row {i}: unknown kind {kind!r}")
            continue
        missing = required - set(row)
        if missing:
            problems.append(f"row {i} ({kind}): missing {sorted(missing)}")
    return problems


def load_metrics(path: str) -> list[dict]:
    rows = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if line:
                rows.append(json.loads(line))
    return rows
