"""Output/observability writers — byte-compatible with what the reference
notebooks consume (SURVEY.md §5 metrics/logging):

- ``logs.json`` keys: ``agg_engine``, ``test_metrics`` (nested list, e.g.
  ``[[loss, auc]]``), ``best_val_epoch``, ``cumulative_total_duration`` (list,
  cumulative — last entry is the total), ``time_spent_on_computation``
  (per-round list), ``local_iter_duration`` / ``remote_iter_duration``
  (``nnlogs.ipynb`` cell 2; ``NB.ipynb`` cells 2-3, 34-36);
- ``test_metrics.csv``: header + one row where columns [1]=accuracy, [2]=f1
  (parsed by ``NB.ipynb`` cell 6);
- directory layout ``<out>/<site>/simulatorRun/<task_id>/fold_<k>/`` as read
  back by ``NB.ipynb`` cells 33-35, plus the remote's zipped global results
  (``nnlogs.ipynb`` cell 2 unzips it).

The point: the reference's analysis notebooks should run unmodified against
our outputs (SURVEY.md §7 'cheap, strong parity check').
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import sys
import zipfile

# the ONE duration-list helper now lives with the span tracer
# (telemetry/tracer.py); re-exported here for the established import path
from ..telemetry.tracer import duration  # noqa: F401

# ---------------------------------------------------------------------------
# Level-gated logger — the ONE sanctioned output path for library code
# (jaxlint R001: print() is reserved for CLI/demo/report surfaces). Messages
# go to stdout in plain form, byte-compatible with the print() lines they
# replaced, but gated by DINUNET_LOG_LEVEL (default INFO) so hot-path
# progress lines can be silenced without touching verbose flags.
# ---------------------------------------------------------------------------

_LOGGER_NAME = "dinunet_implementations_tpu"


class _StdoutHandler(logging.StreamHandler):
    """StreamHandler that resolves sys.stdout at emit time (pytest capsys /
    notebook redirections swap the stream object after import)."""

    def emit(self, record):
        self.stream = sys.stdout
        super().emit(record)


def get_logger() -> logging.Logger:
    logger = logging.getLogger(_LOGGER_NAME)
    if not logger.handlers:
        handler = _StdoutHandler()
        handler.setFormatter(logging.Formatter("%(message)s"))
        logger.addHandler(handler)
        logger.propagate = False
        level = os.environ.get("DINUNET_LOG_LEVEL", "INFO").upper()
        logger.setLevel(getattr(logging, level, logging.INFO))
    return logger


def log_info(msg: str) -> None:
    """Progress lines (per-epoch readouts, pretrain status)."""
    get_logger().info(msg)


def log_warning(msg: str) -> None:
    """Recoverable-but-noteworthy conditions (clamps, empty splits)."""
    get_logger().warning(msg)


def fold_dir(out_dir: str, site: str, task_id: str, fold: int) -> str:
    d = os.path.join(out_dir, site, "simulatorRun", task_id, f"fold_{fold}")
    os.makedirs(d, exist_ok=True)
    return d


def write_logs_json(
    dirpath: str,
    agg_engine: str,
    test_metrics: list,
    best_val_epoch: int,
    cumulative_total_duration: list,
    time_spent_on_computation: list,
    iter_durations: list,
    side: str = "local",
    extra: dict | None = None,
) -> str:
    log = {
        "agg_engine": agg_engine,
        "test_metrics": test_metrics,
        "best_val_epoch": int(best_val_epoch),
        "cumulative_total_duration": [round(x, 6) for x in cumulative_total_duration],
        "time_spent_on_computation": [round(x, 6) for x in time_spent_on_computation],
        f"{side}_iter_duration": [round(x, 6) for x in iter_durations],
    }
    if extra:
        log.update(extra)
    os.makedirs(dirpath, exist_ok=True)
    path = os.path.join(dirpath, "logs.json")
    with open(path, "w") as fh:
        json.dump(log, fh, indent=2)
    return path


def health_log_fields(site_health: dict | None, site_index: int | None = None) -> dict:
    """``logs.json`` fields for the per-site fault-tolerance counters
    (robustness/health.py): rounds each site skipped (scheduled drop,
    non-finite gradient, or quarantine) and whether it ended the fit
    quarantined. ``site_index=None`` returns the remote-side full lists;
    an index returns that one site's scalars (for ``local{i}/logs.json``).
    Returns ``{}`` when no health state was tracked (e.g. ``mode="test"``)."""
    if not site_health:
        return {}
    if site_index is None:
        out = {
            "site_skipped_rounds": list(site_health["site_skipped_rounds"]),
            "site_quarantined": list(site_health["site_quarantined"]),
        }
        if "site_anomaly_score" in site_health:  # reputation layer (r17)
            out["site_anomaly_score"] = [
                round(v, 6) for v in site_health["site_anomaly_score"]
            ]
            out["site_suspect_streak"] = list(
                site_health["site_suspect_streak"]
            )
        return out
    out = {
        "skipped_rounds": site_health["site_skipped_rounds"][site_index],
        "quarantined": site_health["site_quarantined"][site_index],
    }
    if "site_anomaly_score" in site_health:
        out["anomaly_score"] = round(
            site_health["site_anomaly_score"][site_index], 6
        )
        out["suspect_streak"] = site_health["site_suspect_streak"][site_index]
    return out


def telemetry_log_fields(summary: dict | None, site_index: int | None = None) -> dict:
    """``logs.json`` fields for the per-site telemetry rollup
    (telemetry/metrics.py ``telemetry_summary``): grad-norm statistics next
    to the health counters, so the notebook-facing contract surfaces them
    too. ``site_index=None`` returns the remote-side full lists; an index
    returns that one site's scalars (for ``local{i}/logs.json``). ``{}``
    when telemetry was off."""
    if not summary:
        return {}
    if site_index is None:
        return {
            "site_grad_norm_last": list(summary["site_grad_norm_last"]),
            "site_grad_norm_max": list(summary["site_grad_norm_max"]),
            "site_grad_norm_mean": list(summary["site_grad_norm_mean"]),
            "site_residual_norm_mean": list(summary["site_residual_norm_mean"]),
            "update_norm_last": summary["update_norm_last"],
            "payload_bytes_per_round": summary["payload_bytes_per_round"],
            # r18 per-tier split: the inter-slice hop's per-slice figure
            # (0.0 on single-slice runs)
            "dcn_bytes_per_round": summary.get("dcn_bytes_per_round", 0.0),
        }
    return {
        "grad_norm_last": summary["site_grad_norm_last"][site_index],
        "grad_norm_max": summary["site_grad_norm_max"][site_index],
        "grad_norm_mean": summary["site_grad_norm_mean"][site_index],
        "residual_norm_mean": summary["site_residual_norm_mean"][site_index],
    }


def privacy_log_fields(results: dict) -> dict:
    """``logs.json`` fields for the spent differential privacy (r20,
    privacy/accounting.py): the fit's final (ε, δ) next to the health and
    telemetry rollups — absent entirely when the DP mechanism was off or
    noiseless (no guarantee to misreport)."""
    if "dp_epsilon" not in results:
        return {}
    return {
        "dp_epsilon": results["dp_epsilon"],
        "dp_delta": results["dp_delta"],
    }


def write_test_metrics_csv(dirpath: str, fold: int, metrics: dict) -> str:
    """``metrics``: mapping name → value; accuracy and f1 must be present (the
    notebook indexes columns 1 and 2)."""
    names = ["accuracy", "f1"] + [k for k in metrics if k not in ("accuracy", "f1")]
    os.makedirs(dirpath, exist_ok=True)
    path = os.path.join(dirpath, "test_metrics.csv")
    with open(path, "w") as fh:
        fh.write("fold," + ",".join(names) + "\n")
        fh.write(f"fold_{fold}," + ",".join(f"{metrics[n]:.5f}" for n in names) + "\n")
    return path


def zip_global_results(
    out_dir: str, remote_site: str = "remote", num_sites: int = 0,
    task_id: str | None = None,
) -> str:
    """Zip the remote's result tree into the transfer output, like the
    reference remote does, and distribute a copy into each local site's
    output dir (the COINSTAC remote's transfer lands in every site's
    output). ``nnlogs.ipynb`` cell 2 walks a site dir, finds the ``.zip``
    NEXT TO the task dir, and extracts ``fold_k/logs.json`` from it — so
    the zip lives inside ``simulatorRun/``, beside ``<task_id>/``, and
    archive paths start at the FOLD level (``fold_k/...``).

    ``task_id`` selects which task dir to archive (two tasks sharing one
    out_dir would otherwise collide on ``fold_k/`` archive names); ``None``
    falls back to the single task dir present and raises when ambiguous.
    """
    remote_dir = os.path.join(out_dir, remote_site, "simulatorRun")
    if task_id is None:
        tasks = [t for t in sorted(os.listdir(remote_dir))
                 if os.path.isdir(os.path.join(remote_dir, t))]
        if len(tasks) != 1:
            raise ValueError(
                f"out_dir holds {len(tasks)} task dirs {tasks}; pass task_id"
            )
        task_id = tasks[0]
    task_dir = os.path.join(remote_dir, task_id)
    zpath = os.path.join(remote_dir, "global_results.zip")
    with zipfile.ZipFile(zpath, "w") as zf:
        for root, _, files in os.walk(task_dir):
            for f in files:
                full = os.path.join(root, f)
                zf.write(full, os.path.relpath(full, task_dir))
    for i in range(num_sites):
        site_dir = os.path.join(out_dir, f"local{i}", "simulatorRun")
        if os.path.isdir(site_dir):
            shutil.copyfile(
                zpath, os.path.join(site_dir, "global_results.zip")
            )
    return zpath
