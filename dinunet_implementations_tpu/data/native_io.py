"""ctypes bridge to the native batch TSV reader (native/fastio.cpp).

Replaces the reference's torch-DataLoader native worker pool for the
FreeSurfer ingest path (reference ``comps/fs/__init__.py:33-39`` +
``num_workers``): one call parses and max-normalizes every subject file on
C++ threads. Bit-identical to :func:`data.freesurfer.read_aseg_stats`
(strtod == Python float(); f64 normalize; f32 cast) — pinned by
tests/test_native_io.py. Any failure (no compiler, malformed file, ragged
feature counts) returns ``None`` and callers fall back to the Python path.
"""

from __future__ import annotations

import ctypes

import numpy as np

from ..robustness.retry import with_retry

_lib = None
_tried = False


class NativeReadError(OSError):
    """The native batch reader reported a failure (rc != 0)."""


def _load():
    global _lib, _tried
    if not _tried:
        _tried = True
        from ..native import build_and_load

        lib = build_and_load("fastio")
        if lib is not None:
            lib.fastio_read_aseg_batch.restype = ctypes.c_int
            lib.fastio_read_aseg_batch.argtypes = [
                ctypes.POINTER(ctypes.c_char_p), ctypes.c_long, ctypes.c_long,
                ctypes.POINTER(ctypes.c_float), ctypes.c_char_p, ctypes.c_long,
            ]
        _lib = lib
    return _lib


# Shared-filesystem reads (the deployment target: site data on NFS/GCS-fuse)
# fail transiently under load; retry the whole batch read briefly before
# falling back to the Python reader. Malformed-file failures are deterministic
# and burn two short sleeps — an accepted cost for not classifying the native
# error string. The deadline/timeout pair (r13) turns a HUNG read — a dead
# NFS mount blocks in the kernel, it does not error — into a fast fallback to
# the Python reader instead of a wedged epoch.
@with_retry(attempts=3, base_delay=0.05, max_delay=0.5,
            retry_on=(NativeReadError,), describe="native aseg batch read",
            deadline_s=30.0, timeout_s=10.0)
def _read_batch_native(lib, paths: list[str], n_feats: int) -> np.ndarray:
    enc = [p.encode() for p in paths]
    arr = (ctypes.c_char_p * len(enc))(*enc)
    out = np.empty((len(paths), n_feats), np.float32)
    errbuf = ctypes.create_string_buffer(512)
    rc = lib.fastio_read_aseg_batch(
        arr, len(paths), n_feats,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        errbuf, len(errbuf),
    )
    if rc != 0:
        raise NativeReadError(errbuf.value.decode(errors="replace"))
    return out


def read_aseg_batch(paths: list[str], n_feats: int) -> np.ndarray | None:
    """Parse ``paths`` into a ``[len(paths), n_feats]`` float32 matrix, or
    ``None`` when the native path is unavailable or any file fails (after
    the transient-failure retries)."""
    lib = _load()
    if lib is None or not paths or n_feats <= 0:
        return None
    from ..robustness.retry import RetryTimeout

    try:
        return _read_batch_native(lib, paths, n_feats)
    # RetryTimeout: the read HUNG (dead NFS mount blocking in the kernel)
    # and with_retry abandoned it — same fallback as a native parse error,
    # which is the whole point of the r13 timeout
    except (NativeReadError, RetryTimeout) as e:
        import logging

        logging.getLogger(__name__).warning(
            "native aseg read failed (%s); falling back to the Python reader", e
        )
        return None
