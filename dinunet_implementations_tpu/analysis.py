"""Analysis tooling: the reference's two result notebooks, reproduced in-repo.

1. :func:`pretrain_study` — reference ``NB.ipynb`` cells 6-17: 10-fold
   FS-classification trained from scratch vs warm-started by pretraining on
   the largest site (``compspec.json:120-127``), reading per-fold
   ``logs.json`` / ``test_metrics.csv`` and reporting the mean early-stop
   epoch (68.5 scratch vs 42.7 pretrained in the reference's published run)
   plus accuracy/F1 boxplot data.
2. :func:`engine_comparison` — reference ``nnlogs.ipynb`` cell 2: per
   aggregation engine, the test ``[loss, AUC]`` plus total and compute-only
   wall-clock, parsed from the run's ``logs.json`` (the table SURVEY.md §6
   uses as the perf baseline).

Both re-read the ``logs.json`` files the runner wrote, which keeps the
notebook-compatible log schema honest.

Usage::

    from dinunet_implementations_tpu.analysis import pretrain_study
    report = pretrain_study("datasets/test_fsl", "out/study", num_folds=10)
    print(report["summary_markdown"])
"""

from __future__ import annotations

import csv
import json
import os

from .core.config import PretrainArgs, TrainConfig
from .runner.fed_runner import FedRunner
from .trainer.logs import fold_dir


def _read_fold_logs(out_dir: str, task_id: str, fold_ids: list[int]) -> list[dict]:
    logs = []
    for k in fold_ids:
        path = os.path.join(fold_dir(out_dir, "remote", task_id, k), "logs.json")
        with open(path) as fh:
            logs.append(json.load(fh))
    return logs


def _arm_stats(logs: list[dict]) -> dict:
    epochs = [lg["best_val_epoch"] for lg in logs]
    aucs = [lg["test_metrics"][0][1] for lg in logs]
    losses = [lg["test_metrics"][0][0] for lg in logs]
    n = max(len(logs), 1)
    return {
        "folds": len(logs),
        "best_val_epochs": epochs,
        "test_aucs": aucs,
        "test_losses": losses,
        "mean_best_val_epoch": sum(epochs) / n,
        "mean_test_auc": sum(aucs) / n,
        "mean_test_loss": sum(losses) / n,
    }


def engine_comparison(
    data_path: str,
    out_dir: str,
    engines: tuple[str, ...] = ("dSGD", "rankDAD", "powerSGD"),
    base_cfg: TrainConfig | None = None,
    fold: int = 0,
    verbose: bool = False,
) -> dict:
    """The ``nnlogs.ipynb`` cell-2 table from our own runs.

    Trains ``data_path`` once per engine, then parses each run's remote
    ``logs.json`` exactly as the notebook does: test ``[loss, AUC]``,
    cumulative wall-clock, and summed compute-only time. Returns per-engine
    rows plus a rendered ``summary_markdown`` (written to
    ``<out_dir>/engine_comparison.md``).
    """
    cfg = base_cfg or TrainConfig(agg_engine="dSGD", epochs=101, patience=35,
                                  seed=0)
    rows: dict = {}
    for engine in engines:
        arm_out = os.path.join(out_dir, engine)
        runner = FedRunner(cfg.replace(agg_engine=engine),
                           data_path=data_path, out_dir=arm_out)
        runner.run(folds=[fold], verbose=verbose)
        lg = _read_fold_logs(arm_out, runner.cfg.task_id, [fold])[0]
        rows[engine] = {
            "test_metrics": lg["test_metrics"][0],  # [loss, auc]
            "total_duration": (lg["cumulative_total_duration"] or [0.0])[-1],
            "computation_time": sum(lg["time_spent_on_computation"]),
            "best_val_epoch": lg["best_val_epoch"],
        }
    lines = [
        "# Aggregation-engine comparison (nnlogs.ipynb cell 2 equivalent)",
        "",
        f"Dataset: `{data_path}`, fold {fold}",
        "",
        "| engine | test [loss, AUC] | total s | compute s | best epoch |",
        "|---|---|---|---|---|",
    ]
    for engine, r in rows.items():
        loss, auc = r["test_metrics"]
        lines.append(
            f"| {engine} | [{loss:.5f}, {auc:.5f}] | "
            f"{r['total_duration']:.1f} | {r['computation_time']:.1f} | "
            f"{r['best_val_epoch']} |"
        )
    report = {"engines": rows, "summary_markdown": "\n".join(lines)}
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, "engine_comparison.md"), "w") as fh:
        fh.write(report["summary_markdown"] + "\n")
    return report


def write_study_figures(out_dir: str, score_rows: list, epoch_rows: list) -> list[str]:
    """Emit the pretrain study's two boxplot figures (reference ``NB.ipynb``
    cells 8-11: ``assets/perf_box.png`` — accuracy/F1 per experiment —
    and ``assets/pretrain_box.png`` — stop epoch per experiment).

    ``score_rows``: ``[experiment, score_name, value]`` triples (the
    notebook's ``SCORE`` table); ``epoch_rows``: ``[experiment, epoch]``
    pairs (its ``EPOCH`` table). Uses matplotlib when importable (Agg
    backend, no display) and returns the written paths; returns ``[]`` when
    matplotlib is unavailable (the markdown/CSV artifacts always exist).
    """
    try:
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except Exception:  # matplotlib genuinely optional
        return []
    assets = os.path.join(out_dir, "assets")
    os.makedirs(assets, exist_ok=True)
    paths = []

    experiments = list(dict.fromkeys(r[0] for r in score_rows))
    scores = list(dict.fromkeys(r[1] for r in score_rows))
    fig, ax = plt.subplots(figsize=(8, 5))
    width, colors = 0.18, ["#4c72b0", "#dd8452", "#55a868", "#c44e52"]
    for si, score in enumerate(scores):
        data = [
            [r[2] for r in score_rows if r[0] == e and r[1] == score]
            for e in experiments
        ]
        pos = [i + (si - (len(scores) - 1) / 2) * (width * 1.2)
               for i in range(len(experiments))]
        bp = ax.boxplot(data, positions=pos, widths=width, showmeans=True,
                        patch_artist=True)
        for box in bp["boxes"]:
            box.set_facecolor(colors[si % len(colors)])
    ax.set_xticks(range(len(experiments)))
    ax.set_xticklabels(experiments)
    ax.set_ylabel("Value")
    ax.set_title("Test performance: scratch vs pre-training "
                 "k-fold boxplot (higher is better)")
    ax.legend(
        handles=[plt.Rectangle((0, 0), 1, 1, fc=colors[i % len(colors)])
                 for i in range(len(scores))],
        labels=scores,
    )
    p = os.path.join(assets, "perf_box.png")
    fig.savefig(p, dpi=120, bbox_inches="tight")
    plt.close(fig)
    paths.append(p)

    experiments = list(dict.fromkeys(r[0] for r in epoch_rows))
    fig, ax = plt.subplots(figsize=(8, 5))
    ax.boxplot(
        [[r[1] for r in epoch_rows if r[0] == e] for e in experiments],
        widths=0.25, showmeans=True,
    )
    # set labels via the axis, not the boxplot kwarg: the kwarg was renamed
    # labels→tick_labels in matplotlib 3.9, so neither spelling spans versions
    ax.set_xticks(range(1, len(experiments) + 1))
    ax.set_xticklabels(experiments)
    ax.set_ylabel("Stopped on epoch")
    ax.set_title("Train from scratch vs with pre-training "
                 "k-fold boxplot (lower is better)")
    p = os.path.join(assets, "pretrain_box.png")
    fig.savefig(p, dpi=120, bbox_inches="tight")
    plt.close(fig)
    paths.append(p)
    return paths


def pretrain_study(
    data_path: str,
    out_dir: str,
    num_folds: int = 10,
    pretrain_epochs: int = 20,
    base_cfg: TrainConfig | None = None,
    folds: list[int] | None = None,
    verbose: bool = False,
) -> dict:
    """Run both study arms and report convergence statistics.

    Returns a dict with per-arm stats, the epoch speedup, and a rendered
    ``summary_markdown``; also writes ``pretrain_study.md`` and
    ``pretrain_study.csv`` under ``out_dir``.
    """
    cfg = base_cfg or TrainConfig(
        agg_engine="dSGD", epochs=101, patience=35, seed=0
    )
    cfg = cfg.replace(num_folds=num_folds)
    arms = {
        "scratch": cfg.replace(pretrain=False),
        "pretrained": cfg.replace(
            pretrain=True,
            pretrain_args=PretrainArgs(epochs=pretrain_epochs),
        ),
    }
    report: dict = {"arms": {}}
    for name, arm_cfg in arms.items():
        arm_out = os.path.join(out_dir, name)
        runner = FedRunner(arm_cfg, data_path=data_path, out_dir=arm_out)
        results = runner.run(folds=folds, verbose=verbose)
        # the reference study reads logs.json back — do the same, which also
        # regression-checks the on-disk schema against live results. Fold
        # directories are named by the REAL fold id (fold_3 for folds=[1,3]),
        # so read by id, not by position.
        fold_ids = list(folds) if folds is not None else list(range(len(results)))
        logs = _read_fold_logs(arm_out, runner.cfg.task_id, fold_ids)
        stats = _arm_stats(logs)
        stats["fold_ids"] = fold_ids
        # per-fold accuracy/F1, read from test_metrics.csv EXACTLY as
        # NB.ipynb cell 6 does (line 1, columns 1 and 2)
        accs, f1s = [], []
        for k in fold_ids:
            path = os.path.join(
                fold_dir(arm_out, "remote", runner.cfg.task_id, k),
                "test_metrics.csv",
            )
            line = open(path).readlines()[1].split(",")
            accs.append(float(line[1]))
            f1s.append(float(line[2]))
        stats["test_accuracies"] = accs
        stats["test_f1s"] = f1s
        for lg, res in zip(logs, results):
            assert lg["best_val_epoch"] == res["best_val_epoch"], (
                "logs.json disagrees with the in-memory result"
            )
        report["arms"][name] = stats

    s, p = report["arms"]["scratch"], report["arms"]["pretrained"]
    report["epoch_speedup"] = (
        s["mean_best_val_epoch"] / p["mean_best_val_epoch"]
        if p["mean_best_val_epoch"]
        else float("inf")
    )
    report["reference"] = {
        "mean_stop_epoch_scratch": 68.5,  # NB.ipynb cell 12
        "mean_stop_epoch_pretrained": 42.7,  # NB.ipynb cell 14
    }
    lines = [
        "# Pretrain convergence study",
        "",
        f"Dataset: `{data_path}` — {s['folds']} folds, "
        f"pretrain_epochs={pretrain_epochs}",
        "",
        "| arm | mean best_val_epoch | mean test AUC | mean test loss |",
        "|---|---|---|---|",
        f"| scratch | {s['mean_best_val_epoch']:.1f} | "
        f"{s['mean_test_auc']:.4f} | {s['mean_test_loss']:.4f} |",
        f"| pretrained | {p['mean_best_val_epoch']:.1f} | "
        f"{p['mean_test_auc']:.4f} | {p['mean_test_loss']:.4f} |",
        "",
        f"Convergence speedup (scratch/pretrained epochs): "
        f"**{report['epoch_speedup']:.2f}×** — the reference's 10-fold study "
        "reports 68.5 vs 42.7 (1.60×, NB.ipynb cells 12-14).",
    ]
    report["summary_markdown"] = "\n".join(lines)
    os.makedirs(out_dir, exist_ok=True)
    # the notebook's SCORE/EPOCH tables (cells 6, 10) → boxplot figures
    label = {"scratch": "Acc. from scratch", "pretrained": "Acc. with pre-training"}
    elabel = {"scratch": "Convergence from scratch.",
              "pretrained": "Convergence with pre-training."}
    score_rows, epoch_rows = [], []
    for name, stats in report["arms"].items():
        for a, f in zip(stats["test_accuracies"], stats["test_f1s"]):
            score_rows.append([label[name], "Accuracy", a])
            score_rows.append([label[name], "F1", f])
        for e in stats["best_val_epochs"]:
            epoch_rows.append([elabel[name], e])
    report["figures"] = write_study_figures(out_dir, score_rows, epoch_rows)
    with open(os.path.join(out_dir, "pretrain_study.md"), "w") as fh:
        fh.write(report["summary_markdown"] + "\n")
    with open(os.path.join(out_dir, "pretrain_study.csv"), "w", newline="") as fh:
        wr = csv.writer(fh)
        wr.writerow(["arm", "fold", "best_val_epoch", "test_auc", "test_loss"])
        for name, stats in report["arms"].items():
            rows = zip(
                stats["fold_ids"], stats["best_val_epochs"],
                stats["test_aucs"], stats["test_losses"],
            )
            for k, ep, auc, loss in rows:
                wr.writerow([name, k, ep, auc, loss])
    return report
