"""Transient-failure retry: jittered exponential backoff.

The reference's coordinator/worker topology tolerates a worker that comes up
before the coordinator, or an NFS read that fails once under load, by virtue
of its message-bus retries. Here the equivalents — ``jax.distributed``
initialization racing the coordinator, native batch-IO reads on shared
filesystems — get an explicit wrapper:

    @with_retry(attempts=3, base_delay=0.5, retry_on=(RuntimeError, OSError))
    def connect(): ...

    init = with_retry(jax.distributed.initialize, attempts=3)

Backoff for attempt ``i`` is ``min(base_delay * 2**i, max_delay)`` scaled by
a jitter factor in ``[0.5, 1.5)`` — jittered so a fleet of workers retrying
the same dead coordinator doesn't thundering-herd it. Pass ``seed`` for a
deterministic jitter sequence (tests), and ``sleep`` to observe/skip the
waits.
"""

from __future__ import annotations

import functools
import logging
import random
import time

_log = logging.getLogger("dinunet_implementations_tpu.robustness.retry")


def with_retry(
    fn=None,
    *,
    attempts: int = 3,
    base_delay: float = 0.1,
    max_delay: float = 5.0,
    retry_on: tuple = (OSError,),
    seed: int | None = None,
    sleep=time.sleep,
    describe: str | None = None,
):
    """Wrap ``fn`` (decorator or call form) with jittered exponential backoff.

    Retries only exceptions matching ``retry_on``; anything else propagates
    immediately. After ``attempts`` failures the last exception propagates.
    """
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")

    def deco(f):
        @functools.wraps(f)
        def wrapped(*args, **kwargs):
            rng = random.Random(seed)
            name = describe or getattr(f, "__name__", repr(f))
            for attempt in range(attempts):
                try:
                    return f(*args, **kwargs)
                except retry_on as e:
                    if attempt == attempts - 1:
                        raise
                    delay = min(base_delay * (2 ** attempt), max_delay)
                    delay *= 0.5 + rng.random()  # jitter in [0.5, 1.5)
                    _log.warning(
                        "%s failed (attempt %d/%d): %s — retrying in %.2fs",
                        name, attempt + 1, attempts, e, delay,
                    )
                    sleep(delay)

        return wrapped

    return deco if fn is None else deco(fn)
