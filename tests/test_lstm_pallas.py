"""Pallas fused LSTM kernel vs the XLA scan reference path (interpret mode on
CPU; the same kernel compiles on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dinunet_implementations_tpu.models.icalstm import ICALstm, LSTMCell


def _params(key, D, H):
    ks = jax.random.split(key, 4)
    return {
        "w_ih": jax.random.normal(ks[0], (D, 4 * H)) * 0.2,
        "b_ih": jax.random.normal(ks[1], (4 * H,)) * 0.1,
        "w_hh": jax.random.normal(ks[2], (H, 4 * H)) * 0.2,
        "b_hh": jax.random.normal(ks[3], (4 * H,)) * 0.1,
    }


@pytest.mark.parametrize("B,T,D,H", [(4, 7, 5, 8), (16, 11, 6, 12)])
def test_pallas_forward_matches_scan(B, T, D, H):
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (B, T, D))
    params = _params(key, D, H)
    scan = LSTMCell(H, use_pallas=False)
    pal = LSTMCell(H, use_pallas=True)
    hs_s, (h_s, c_s) = scan.apply({"params": params}, x)
    hs_p, (h_p, c_p) = pal.apply({"params": params}, x)
    np.testing.assert_allclose(np.asarray(hs_p), np.asarray(hs_s), atol=1e-5)
    np.testing.assert_allclose(np.asarray(h_p), np.asarray(h_s), atol=1e-5)
    np.testing.assert_allclose(np.asarray(c_p), np.asarray(c_s), atol=1e-5)


def test_pallas_backward_matches_scan():
    B, T, D, H = 8, 6, 5, 8
    key = jax.random.PRNGKey(1)
    x = jax.random.normal(key, (B, T, D))
    params = _params(key, D, H)

    def loss(params, module):
        hs, (hT, cT) = module.apply({"params": params}, x)
        # use hs, hT AND cT so every cotangent path is exercised
        return jnp.sum(hs**2) + jnp.sum(jnp.sin(hT)) + jnp.sum(cT**2)

    g_scan = jax.grad(loss)(params, LSTMCell(H, use_pallas=False))
    g_pal = jax.grad(loss)(params, LSTMCell(H, use_pallas=True))
    for k in params:
        np.testing.assert_allclose(
            np.asarray(g_pal[k]), np.asarray(g_scan[k]), atol=1e-4, err_msg=k
        )


def test_pallas_input_grad_matches_scan():
    B, T, D, H = 4, 5, 6, 8
    key = jax.random.PRNGKey(2)
    x = jax.random.normal(key, (B, T, D))
    params = _params(key, D, H)

    def loss_x(x, module):
        hs, _ = module.apply({"params": params}, x)
        return jnp.sum(hs**3)

    gx_s = jax.grad(loss_x)(x, LSTMCell(H, use_pallas=False))
    gx_p = jax.grad(loss_x)(x, LSTMCell(H, use_pallas=True))
    np.testing.assert_allclose(np.asarray(gx_p), np.asarray(gx_s), atol=1e-4)


def test_pallas_under_vmap():
    """The folded-sites trainer vmaps over a leading site axis — the kernel
    must batch correctly."""
    S, B, T, D, H = 3, 4, 5, 6, 8
    key = jax.random.PRNGKey(3)
    x = jax.random.normal(key, (S, B, T, D))
    params = _params(key, D, H)
    scan = LSTMCell(H, use_pallas=False)
    pal = LSTMCell(H, use_pallas=True)
    f_s = jax.vmap(lambda xx: scan.apply({"params": params}, xx)[0])
    f_p = jax.vmap(lambda xx: pal.apply({"params": params}, xx)[0])
    np.testing.assert_allclose(np.asarray(f_p(x)), np.asarray(f_s(x)), atol=1e-5)


def test_pallas_batch_padding():
    """B not a multiple of the kernel tile is padded and sliced back."""
    B, T, D, H = 5, 4, 3, 8  # B=5: odd size
    key = jax.random.PRNGKey(4)
    x = jax.random.normal(key, (B, T, D))
    params = _params(key, D, H)
    hs_s, _ = LSTMCell(H, use_pallas=False).apply({"params": params}, x)
    hs_p, _ = LSTMCell(H, use_pallas=True).apply({"params": params}, x)
    np.testing.assert_allclose(np.asarray(hs_p), np.asarray(hs_s), atol=1e-5)


def test_icalstm_pallas_end_to_end_grad():
    """Full ICALstm model trains identically (small tolerance) on both paths."""
    key = jax.random.PRNGKey(5)
    x = jax.random.normal(key, (4, 6, 5, 4))
    y = jnp.array([0, 1, 0, 1])
    m_scan = ICALstm(input_size=16, hidden_size=12, num_comps=5, window_size=4)
    variables = m_scan.init({"params": key, "dropout": key}, x, train=True)

    def loss(v, module):
        logits = module.apply(v, x, train=False)
        return -jnp.mean(
            jnp.take_along_axis(jax.nn.log_softmax(logits), y[:, None], 1)
        )

    # same params work on both paths (param structure is identical)
    g_s = jax.grad(loss)(variables, m_scan)["params"]
    m_pal = ICALstm(
        input_size=16, hidden_size=12, num_comps=5, window_size=4, use_pallas=True
    )
    g_p = jax.grad(loss)(variables, m_pal)["params"]
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4
        ),
        g_p,
        g_s,
    )


def test_multi_tile_dw_accumulation():
    """Review finding regression: with B > one kernel tile, dW must accumulate
    across ALL batch tiles (was wiped at each tile's first step)."""
    from dinunet_implementations_tpu.ops import lstm_pallas

    old = lstm_pallas.B_TILE
    lstm_pallas.B_TILE = 8  # force 3 tiles at B=24 without a huge test
    try:
        B, T, D, H = 24, 5, 4, 8
        key = jax.random.PRNGKey(6)
        x = jax.random.normal(key, (B, T, D))
        params = _params(key, D, H)

        def loss(p, module):
            hs, _ = module.apply({"params": p}, x)
            return jnp.sum(hs**2)

        g_s = jax.grad(loss)(params, LSTMCell(H, use_pallas=False))
        g_p = jax.grad(loss)(params, LSTMCell(H, use_pallas=True))
        np.testing.assert_allclose(
            np.asarray(g_p["w_hh"]), np.asarray(g_s["w_hh"]), atol=1e-4
        )
    finally:
        lstm_pallas.B_TILE = old


def test_bf16_inputs_roundtrip():
    """Review finding regression: non-f32 inputs must work and preserve dtype."""
    B, T, D, H = 4, 5, 6, 8
    key = jax.random.PRNGKey(7)
    x = jax.random.normal(key, (B, T, D)).astype(jnp.bfloat16)
    params = jax.tree.map(lambda a: a.astype(jnp.bfloat16), _params(key, D, H))
    hs, (hT, cT) = LSTMCell(H, use_pallas=True).apply({"params": params}, x)
    assert hs.dtype == jnp.bfloat16
    hs_s, _ = LSTMCell(H, use_pallas=False).apply({"params": params}, x)
    np.testing.assert_allclose(
        np.asarray(hs, np.float32), np.asarray(hs_s, np.float32), atol=0.05
    )


def test_lstm_recurrence_rejects_indivisible_batch():
    from dinunet_implementations_tpu.ops import lstm_pallas

    old = lstm_pallas.B_TILE
    lstm_pallas.B_TILE = 8
    try:
        D, H = 5, 4
        with pytest.raises(AssertionError, match="multiple of the kernel tile"):
            lstm_pallas.lstm_recurrence_fused(
                jnp.ones((3, 12, D)), jnp.ones((4, D, H)), jnp.ones((4, H)),
                jnp.ones((4, H, H)), jnp.ones((12, H)), jnp.ones((12, H)),
            )
    finally:
        lstm_pallas.B_TILE = old


def test_compute_dtype_bf16_close_to_f32():
    """Mixed-precision mode (bf16 matmuls/streams, f32 carries+accum) must
    track the f32 path closely — forward and gradients — incl. under vmap."""
    S, B, T, D, H = 3, 4, 6, 5, 8
    key = jax.random.PRNGKey(8)
    x = jax.random.normal(key, (S, B, T, D))
    params = _params(key, D, H)
    f32 = LSTMCell(H, use_pallas=True)
    b16 = LSTMCell(H, use_pallas=True, compute_dtype="bfloat16")

    out_f = jax.vmap(lambda xx: f32.apply({"params": params}, xx)[0])(x)
    out_b = jax.vmap(lambda xx: b16.apply({"params": params}, xx)[0])(x)
    np.testing.assert_allclose(np.asarray(out_b), np.asarray(out_f), atol=0.05)

    def loss(p, module):
        hs = jax.vmap(lambda xx: module.apply({"params": p}, xx)[0])(x)
        return jnp.sum(hs**2)

    g_f = jax.grad(loss)(params, f32)
    g_b = jax.grad(loss)(params, b16)
    for k in params:
        a, b = np.asarray(g_b[k], np.float32), np.asarray(g_f[k])
        denom = max(np.abs(b).max(), 1.0)
        assert np.abs(a - b).max() / denom < 0.06, k


def test_scan_path_bf16_carry_types():
    """Review regression: the lax.scan fallback with compute_dtype set must
    not violate scan carry-type invariance (bf16 h0 vs f32 carry)."""
    key = jax.random.PRNGKey(9)
    x = jax.random.normal(key, (2, 4, 5))
    params = _params(key, 5, 8)
    hs, (hT, cT) = LSTMCell(8, use_pallas=False, compute_dtype="bfloat16").apply(
        {"params": params}, x
    )
    assert np.isfinite(np.asarray(hs, np.float32)).all()
    hs_f, _ = LSTMCell(8, use_pallas=False).apply({"params": params}, x)
    np.testing.assert_allclose(
        np.asarray(hs, np.float32), np.asarray(hs_f), atol=0.05
    )


def test_lstm_recurrence_direct_f32_x_bf16_compute_grad():
    """ADVICE r2 regression (dtype-contract class): a direct
    lstm_recurrence_fused call with f32 x and compute_dtype='bfloat16' must
    return an f32 dx cotangent (custom_vjp requires cotangent avals to match
    the primal avals)."""
    from dinunet_implementations_tpu.ops.lstm_pallas import lstm_recurrence_fused

    B, T, D, H = 4, 5, 6, 8
    key = jax.random.PRNGKey(9)
    x = jax.random.normal(key, (T, B, D))
    wih4 = jax.random.normal(key, (4, D, H)) * 0.2
    b4 = jnp.zeros((4, H))
    whh4 = jax.random.normal(key, (4, H, H)) * 0.2
    h0 = jnp.zeros((B, H))
    c0 = jnp.zeros((B, H))

    def loss(x):
        hs, (hT, cT) = lstm_recurrence_fused(x, wih4, b4, whh4, h0, c0, jnp.bfloat16)
        return jnp.sum(hs.astype(jnp.float32) ** 2) + jnp.sum(hT + cT)

    g = jax.grad(loss)(x)
    assert g.dtype == jnp.float32
    assert np.isfinite(np.asarray(g)).all()


def test_fused_grad_with_bf16_weights_matches_primal_dtypes():
    """Review regression (r3): a direct lstm_recurrence_fused call with
    non-f32 weights must return cotangents at the PRIMAL dtypes (custom_vjp
    aval check) — dwih/db/dwhh, not just dx."""
    from dinunet_implementations_tpu.ops.lstm_pallas import lstm_recurrence_fused

    B, T, D, H = 4, 5, 6, 8
    key = jax.random.PRNGKey(11)
    bf16 = jnp.bfloat16
    x = jax.random.normal(key, (T, B, D)).astype(bf16)
    wih4 = (jax.random.normal(key, (4, D, H)) * 0.2).astype(bf16)
    b4 = jnp.zeros((4, H), bf16)
    whh4 = (jax.random.normal(key, (4, H, H)) * 0.2).astype(bf16)
    h0 = jnp.zeros((B, H))
    c0 = jnp.zeros((B, H))

    def loss(x, wih4, b4, whh4):
        hs, _ = lstm_recurrence_fused(x, wih4, b4, whh4, h0, c0, bf16)
        return jnp.sum(hs.astype(jnp.float32) ** 2)

    gx, gwih, gb, gwhh = jax.grad(loss, argnums=(0, 1, 2, 3))(x, wih4, b4, whh4)
    assert gx.dtype == bf16 and gwih.dtype == bf16
    assert gb.dtype == bf16 and gwhh.dtype == bf16
    for g in (gx, gwih, gb, gwhh):
        assert np.isfinite(np.asarray(g, np.float32)).all()


def test_fused_terminal_carry_is_f32_even_under_bf16():
    """Ring-relay contract: (hT, cT) come from the kernel's f32 scratch, not
    the bf16 streams — so chunk-boundary relays never quantize the carry."""
    from dinunet_implementations_tpu.ops.lstm_pallas import lstm_forward_fused

    B, T, D, H = 4, 6, 5, 8
    key = jax.random.PRNGKey(10)
    x = jax.random.normal(key, (B, T, D)).astype(jnp.bfloat16)
    p = _params(key, D, H)
    hs, (hT, cT) = lstm_forward_fused(
        x, p["w_ih"], p["b_ih"] + p["b_hh"], p["w_hh"],
        jnp.zeros((B, H)), jnp.zeros((B, H)), compute_dtype=jnp.bfloat16,
    )
    assert hs.dtype == jnp.bfloat16
    assert hT.dtype == jnp.float32 and cT.dtype == jnp.float32
    # and the f32 carry is strictly more precise than the bf16 stream's last
    # step: they agree to bf16 resolution
    np.testing.assert_allclose(
        np.asarray(hs[:, -1].astype(jnp.float32)), np.asarray(hT), atol=0.01
    )
