"""Pallas fused LSTM kernel vs the XLA scan reference path (interpret mode on
CPU; the same kernel compiles on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dinunet_implementations_tpu.models.icalstm import ICALstm, LSTMCell


def _params(key, D, H):
    ks = jax.random.split(key, 4)
    return {
        "w_ih": jax.random.normal(ks[0], (D, 4 * H)) * 0.2,
        "b_ih": jax.random.normal(ks[1], (4 * H,)) * 0.1,
        "w_hh": jax.random.normal(ks[2], (H, 4 * H)) * 0.2,
        "b_hh": jax.random.normal(ks[3], (4 * H,)) * 0.1,
    }


@pytest.mark.parametrize("B,T,D,H", [(4, 7, 5, 8), (16, 11, 6, 12)])
def test_pallas_forward_matches_scan(B, T, D, H):
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (B, T, D))
    params = _params(key, D, H)
    scan = LSTMCell(H, use_pallas=False)
    pal = LSTMCell(H, use_pallas=True)
    hs_s, (h_s, c_s) = scan.apply({"params": params}, x)
    hs_p, (h_p, c_p) = pal.apply({"params": params}, x)
    np.testing.assert_allclose(np.asarray(hs_p), np.asarray(hs_s), atol=1e-5)
    np.testing.assert_allclose(np.asarray(h_p), np.asarray(h_s), atol=1e-5)
    np.testing.assert_allclose(np.asarray(c_p), np.asarray(c_s), atol=1e-5)


def test_pallas_backward_matches_scan():
    B, T, D, H = 8, 6, 5, 8
    key = jax.random.PRNGKey(1)
    x = jax.random.normal(key, (B, T, D))
    params = _params(key, D, H)

    def loss(params, module):
        hs, (hT, cT) = module.apply({"params": params}, x)
        # use hs, hT AND cT so every cotangent path is exercised
        return jnp.sum(hs**2) + jnp.sum(jnp.sin(hT)) + jnp.sum(cT**2)

    g_scan = jax.grad(loss)(params, LSTMCell(H, use_pallas=False))
    g_pal = jax.grad(loss)(params, LSTMCell(H, use_pallas=True))
    for k in params:
        np.testing.assert_allclose(
            np.asarray(g_pal[k]), np.asarray(g_scan[k]), atol=1e-4, err_msg=k
        )


def test_pallas_input_grad_matches_scan():
    B, T, D, H = 4, 5, 6, 8
    key = jax.random.PRNGKey(2)
    x = jax.random.normal(key, (B, T, D))
    params = _params(key, D, H)

    def loss_x(x, module):
        hs, _ = module.apply({"params": params}, x)
        return jnp.sum(hs**3)

    gx_s = jax.grad(loss_x)(x, LSTMCell(H, use_pallas=False))
    gx_p = jax.grad(loss_x)(x, LSTMCell(H, use_pallas=True))
    np.testing.assert_allclose(np.asarray(gx_p), np.asarray(gx_s), atol=1e-4)


def test_pallas_under_vmap():
    """The folded-sites trainer vmaps over a leading site axis — the kernel
    must batch correctly."""
    S, B, T, D, H = 3, 4, 5, 6, 8
    key = jax.random.PRNGKey(3)
    x = jax.random.normal(key, (S, B, T, D))
    params = _params(key, D, H)
    scan = LSTMCell(H, use_pallas=False)
    pal = LSTMCell(H, use_pallas=True)
    f_s = jax.vmap(lambda xx: scan.apply({"params": params}, xx)[0])
    f_p = jax.vmap(lambda xx: pal.apply({"params": params}, xx)[0])
    np.testing.assert_allclose(np.asarray(f_p(x)), np.asarray(f_s(x)), atol=1e-5)


def test_pallas_batch_padding():
    """B not a multiple of the kernel tile is padded and sliced back."""
    B, T, D, H = 5, 4, 3, 8  # B=5: odd size
    key = jax.random.PRNGKey(4)
    x = jax.random.normal(key, (B, T, D))
    params = _params(key, D, H)
    hs_s, _ = LSTMCell(H, use_pallas=False).apply({"params": params}, x)
    hs_p, _ = LSTMCell(H, use_pallas=True).apply({"params": params}, x)
    np.testing.assert_allclose(np.asarray(hs_p), np.asarray(hs_s), atol=1e-5)


@pytest.mark.slow
def test_icalstm_pallas_end_to_end_grad():
    """Full ICALstm model trains identically (small tolerance) on both paths."""
    key = jax.random.PRNGKey(5)
    x = jax.random.normal(key, (4, 6, 5, 4))
    y = jnp.array([0, 1, 0, 1])
    m_scan = ICALstm(input_size=16, hidden_size=12, num_comps=5, window_size=4)
    variables = m_scan.init({"params": key, "dropout": key}, x, train=True)

    def loss(v, module):
        logits = module.apply(v, x, train=False)
        return -jnp.mean(
            jnp.take_along_axis(jax.nn.log_softmax(logits), y[:, None], 1)
        )

    # same params work on both paths (param structure is identical)
    g_s = jax.grad(loss)(variables, m_scan)["params"]
    m_pal = ICALstm(
        input_size=16, hidden_size=12, num_comps=5, window_size=4,
        use_pallas=True, fused_bidir=True,  # cover the opt-in fused arm too
    )
    g_p = jax.grad(loss)(variables, m_pal)["params"]
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4
        ),
        g_p,
        g_s,
    )


@pytest.mark.slow
def test_multi_tile_dw_accumulation():
    """Review finding regression: with B > one kernel tile, dW must accumulate
    across ALL batch tiles (was wiped at each tile's first step)."""
    from dinunet_implementations_tpu.ops import lstm_pallas

    old = lstm_pallas.B_TILE
    lstm_pallas.B_TILE = 8  # force 3 tiles at B=24 without a huge test
    try:
        B, T, D, H = 24, 5, 4, 8
        key = jax.random.PRNGKey(6)
        x = jax.random.normal(key, (B, T, D))
        params = _params(key, D, H)

        def loss(p, module):
            hs, _ = module.apply({"params": p}, x)
            return jnp.sum(hs**2)

        g_s = jax.grad(loss)(params, LSTMCell(H, use_pallas=False))
        g_p = jax.grad(loss)(params, LSTMCell(H, use_pallas=True))
        np.testing.assert_allclose(
            np.asarray(g_p["w_hh"]), np.asarray(g_s["w_hh"]), atol=1e-4
        )
    finally:
        lstm_pallas.B_TILE = old


def test_bf16_inputs_roundtrip():
    """Review finding regression: non-f32 inputs must work and preserve dtype."""
    B, T, D, H = 4, 5, 6, 8
    key = jax.random.PRNGKey(7)
    x = jax.random.normal(key, (B, T, D)).astype(jnp.bfloat16)
    params = jax.tree.map(lambda a: a.astype(jnp.bfloat16), _params(key, D, H))
    hs, (hT, cT) = LSTMCell(H, use_pallas=True).apply({"params": params}, x)
    assert hs.dtype == jnp.bfloat16
    hs_s, _ = LSTMCell(H, use_pallas=False).apply({"params": params}, x)
    np.testing.assert_allclose(
        np.asarray(hs, np.float32), np.asarray(hs_s, np.float32), atol=0.05
    )


def test_lstm_recurrence_rejects_indivisible_batch():
    from dinunet_implementations_tpu.ops import lstm_pallas

    old = lstm_pallas.B_TILE
    lstm_pallas.B_TILE = 8
    try:
        D, H = 5, 4
        with pytest.raises(AssertionError, match="multiple of the kernel tile"):
            lstm_pallas.lstm_recurrence_fused(
                jnp.ones((3, 12, D)), jnp.ones((4, D, H)), jnp.ones((4, H)),
                jnp.ones((4, H, H)), jnp.ones((12, H)), jnp.ones((12, H)),
            )
    finally:
        lstm_pallas.B_TILE = old


@pytest.mark.slow
def test_compute_dtype_bf16_close_to_f32():
    """Mixed-precision mode (bf16 matmuls/streams, f32 carries+accum) must
    track the f32 path closely — forward and gradients — incl. under vmap."""
    S, B, T, D, H = 3, 4, 6, 5, 8
    key = jax.random.PRNGKey(8)
    x = jax.random.normal(key, (S, B, T, D))
    params = _params(key, D, H)
    f32 = LSTMCell(H, use_pallas=True)
    b16 = LSTMCell(H, use_pallas=True, compute_dtype="bfloat16")

    out_f = jax.vmap(lambda xx: f32.apply({"params": params}, xx)[0])(x)
    out_b = jax.vmap(lambda xx: b16.apply({"params": params}, xx)[0])(x)
    np.testing.assert_allclose(np.asarray(out_b), np.asarray(out_f), atol=0.05)

    def loss(p, module):
        hs = jax.vmap(lambda xx: module.apply({"params": p}, xx)[0])(x)
        return jnp.sum(hs**2)

    g_f = jax.grad(loss)(params, f32)
    g_b = jax.grad(loss)(params, b16)
    for k in params:
        a, b = np.asarray(g_b[k], np.float32), np.asarray(g_f[k])
        denom = max(np.abs(b).max(), 1.0)
        assert np.abs(a - b).max() / denom < 0.06, k


def test_scan_path_bf16_carry_types():
    """Review regression: the lax.scan fallback with compute_dtype set must
    not violate scan carry-type invariance (bf16 h0 vs f32 carry)."""
    key = jax.random.PRNGKey(9)
    x = jax.random.normal(key, (2, 4, 5))
    params = _params(key, 5, 8)
    hs, (hT, cT) = LSTMCell(8, use_pallas=False, compute_dtype="bfloat16").apply(
        {"params": params}, x
    )
    assert np.isfinite(np.asarray(hs, np.float32)).all()
    hs_f, _ = LSTMCell(8, use_pallas=False).apply({"params": params}, x)
    np.testing.assert_allclose(
        np.asarray(hs, np.float32), np.asarray(hs_f), atol=0.05
    )


def test_lstm_recurrence_direct_f32_x_bf16_compute_grad():
    """ADVICE r2 regression (dtype-contract class): a direct
    lstm_recurrence_fused call with f32 x and compute_dtype='bfloat16' must
    return an f32 dx cotangent (custom_vjp requires cotangent avals to match
    the primal avals)."""
    from dinunet_implementations_tpu.ops.lstm_pallas import lstm_recurrence_fused

    B, T, D, H = 4, 5, 6, 8
    key = jax.random.PRNGKey(9)
    x = jax.random.normal(key, (T, B, D))
    wih4 = jax.random.normal(key, (4, D, H)) * 0.2
    b4 = jnp.zeros((4, H))
    whh4 = jax.random.normal(key, (4, H, H)) * 0.2
    h0 = jnp.zeros((B, H))
    c0 = jnp.zeros((B, H))

    def loss(x):
        hs, (hT, cT) = lstm_recurrence_fused(x, wih4, b4, whh4, h0, c0, jnp.bfloat16)
        return jnp.sum(hs.astype(jnp.float32) ** 2) + jnp.sum(hT + cT)

    g = jax.grad(loss)(x)
    assert g.dtype == jnp.float32
    assert np.isfinite(np.asarray(g)).all()


def test_fused_grad_with_bf16_weights_matches_primal_dtypes():
    """Review regression (r3): a direct lstm_recurrence_fused call with
    non-f32 weights must return cotangents at the PRIMAL dtypes (custom_vjp
    aval check) — dwih/db/dwhh, not just dx."""
    from dinunet_implementations_tpu.ops.lstm_pallas import lstm_recurrence_fused

    B, T, D, H = 4, 5, 6, 8
    key = jax.random.PRNGKey(11)
    bf16 = jnp.bfloat16
    x = jax.random.normal(key, (T, B, D)).astype(bf16)
    wih4 = (jax.random.normal(key, (4, D, H)) * 0.2).astype(bf16)
    b4 = jnp.zeros((4, H), bf16)
    whh4 = (jax.random.normal(key, (4, H, H)) * 0.2).astype(bf16)
    h0 = jnp.zeros((B, H))
    c0 = jnp.zeros((B, H))

    def loss(x, wih4, b4, whh4):
        hs, _ = lstm_recurrence_fused(x, wih4, b4, whh4, h0, c0, bf16)
        return jnp.sum(hs.astype(jnp.float32) ** 2)

    gx, gwih, gb, gwhh = jax.grad(loss, argnums=(0, 1, 2, 3))(x, wih4, b4, whh4)
    assert gx.dtype == bf16 and gwih.dtype == bf16
    assert gb.dtype == bf16 and gwhh.dtype == bf16
    for g in (gx, gwih, gb, gwhh):
        assert np.isfinite(np.asarray(g, np.float32)).all()


def test_fused_terminal_carry_is_f32_even_under_bf16():
    """Ring-relay contract: (hT, cT) come from the kernel's f32 scratch, not
    the bf16 streams — so chunk-boundary relays never quantize the carry."""
    from dinunet_implementations_tpu.ops.lstm_pallas import lstm_forward_fused

    B, T, D, H = 4, 6, 5, 8
    key = jax.random.PRNGKey(10)
    x = jax.random.normal(key, (B, T, D)).astype(jnp.bfloat16)
    p = _params(key, D, H)
    hs, (hT, cT) = lstm_forward_fused(
        x, p["w_ih"], p["b_ih"] + p["b_hh"], p["w_hh"],
        jnp.zeros((B, H)), jnp.zeros((B, H)), compute_dtype=jnp.bfloat16,
    )
    assert hs.dtype == jnp.bfloat16
    assert hT.dtype == jnp.float32 and cT.dtype == jnp.float32
    # and the f32 carry is strictly more precise than the bf16 stream's last
    # step: they agree to bf16 resolution
    np.testing.assert_allclose(
        np.asarray(hs[:, -1].astype(jnp.float32)), np.asarray(hT), atol=0.01
    )


# ---------------------------------------------------------------------------
# fused BIDIRECTIONAL kernels (ADVICE r4: direct parity tests; VERDICT r4 #2:
# the production composition — vmapped over a site axis — must be exercised
# by the suite, not only at bench time on the TPU)
# ---------------------------------------------------------------------------


def _blocked(key, D, H):
    """Params in LSTMCell blocked layout (w_ih [D,4H], b [4H], w_hh [H,4H])."""
    ks = jax.random.split(key, 3)
    return (
        jax.random.normal(ks[0], (D, 4 * H)) * 0.2,
        jax.random.normal(ks[1], (4 * H,)) * 0.1,
        jax.random.normal(ks[2], (H, 4 * H)) * 0.2,
    )


def _scan_lstm(x, p, h0, c0):
    w_ih, b, w_hh = p
    H = w_hh.shape[0]
    xi = x @ w_ih + b

    def step(carry, xt):
        h, c = carry
        pre = xt + h @ w_hh
        i = jax.nn.sigmoid(pre[..., :H])
        f = jax.nn.sigmoid(pre[..., H : 2 * H])
        o = jax.nn.sigmoid(pre[..., 2 * H : 3 * H])
        g = jnp.tanh(pre[..., 3 * H :])
        c = f * c + i * g
        h = o * jnp.tanh(c)
        return (h, c), h

    (hT, cT), hs = jax.lax.scan(step, (h0, c0), jnp.swapaxes(xi, 0, 1))
    return jnp.swapaxes(hs, 0, 1), (hT, cT)


def _scan_bilstm_pool(x, pf, pr, h02, c02):
    hsf, (hTf, cTf) = _scan_lstm(x, pf, h02[0], c02[0])
    hsr, (hTr, cTr) = _scan_lstm(jnp.flip(x, 1), pr, h02[1], c02[1])
    pooled = jnp.concatenate([hsf.mean(1), hsr.mean(1)], -1)
    return pooled, (jnp.stack([hTf, hTr]), jnp.stack([cTf, cTr]))


@pytest.mark.parametrize("B,T,D,H", [(4, 6, 5, 8), (3, 5, 4, 8)])
def test_bilstm_forward_fused_matches_scan(B, T, D, H):
    """bilstm_forward_fused vs two scan LSTMCells, incl. the x-time (flip)
    convention of hs_r and the terminal carries."""
    from dinunet_implementations_tpu.ops.lstm_pallas import bilstm_forward_fused

    key = jax.random.PRNGKey(20)
    x = jax.random.normal(key, (B, T, D))
    pf = _blocked(jax.random.PRNGKey(21), D, H)
    pr = _blocked(jax.random.PRNGKey(22), D, H)
    hsf, hsr, (hT2, cT2) = bilstm_forward_fused(x, pf, pr)
    z = jnp.zeros((B, H))
    ref_f, (hTf, cTf) = _scan_lstm(x, pf, z, z)
    ref_r_own, (hTr, cTr) = _scan_lstm(jnp.flip(x, 1), pr, z, z)
    np.testing.assert_allclose(np.asarray(hsf), np.asarray(ref_f), atol=1e-5)
    # hs_r is stored in x-time convention: flip of the rev scan's own-time seq
    np.testing.assert_allclose(
        np.asarray(hsr), np.asarray(jnp.flip(ref_r_own, 1)), atol=1e-5
    )
    np.testing.assert_allclose(np.asarray(hT2[0]), np.asarray(hTf), atol=1e-5)
    np.testing.assert_allclose(np.asarray(hT2[1]), np.asarray(hTr), atol=1e-5)
    np.testing.assert_allclose(np.asarray(cT2[0]), np.asarray(cTf), atol=1e-5)
    np.testing.assert_allclose(np.asarray(cT2[1]), np.asarray(cTr), atol=1e-5)


def test_bilstm_pool_fused_matches_scan_with_carries():
    from dinunet_implementations_tpu.ops.lstm_pallas import (
        bilstm_pool_forward_fused,
    )

    B, T, D, H = 4, 6, 5, 8
    key = jax.random.PRNGKey(23)
    x = jax.random.normal(key, (B, T, D))
    pf = _blocked(jax.random.PRNGKey(24), D, H)
    pr = _blocked(jax.random.PRNGKey(25), D, H)
    h02 = jax.random.normal(jax.random.PRNGKey(26), (2, B, H)) * 0.3
    c02 = jax.random.normal(jax.random.PRNGKey(27), (2, B, H)) * 0.3
    pooled, (hT2, cT2) = bilstm_pool_forward_fused(x, pf, pr, h02, c02)
    ref_p, (ref_h, ref_c) = _scan_bilstm_pool(x, pf, pr, h02, c02)
    np.testing.assert_allclose(np.asarray(pooled), np.asarray(ref_p), atol=1e-5)
    np.testing.assert_allclose(np.asarray(hT2), np.asarray(ref_h), atol=1e-5)
    np.testing.assert_allclose(np.asarray(cT2), np.asarray(ref_c), atol=1e-5)


def test_pool_bwd_row_padded_carry_cotangents():
    """ADVICE r4 (medium) regression: when the unbatched pool path row-pads
    the batch (B not a tile multiple), dh02/dc02 must come back [2, B, H] —
    not [2, Bp, H] — and match the scan-path gradient exactly."""
    from dinunet_implementations_tpu.ops import lstm_pallas

    old = lstm_pallas.B_TILE
    lstm_pallas.B_TILE = 8
    try:
        B, T, D, H = 12, 5, 4, 8  # pads to Bp=16
        x = jax.random.normal(jax.random.PRNGKey(28), (B, T, D))
        pf = _blocked(jax.random.PRNGKey(29), D, H)
        pr = _blocked(jax.random.PRNGKey(30), D, H)
        h02 = jax.random.normal(jax.random.PRNGKey(31), (2, B, H)) * 0.3
        c02 = jax.random.normal(jax.random.PRNGKey(32), (2, B, H)) * 0.3

        def loss(fused):
            def f(x, h02, c02):
                if fused:
                    pooled, (hT2, cT2) = lstm_pallas.bilstm_pool_forward_fused(
                        x, pf, pr, h02, c02
                    )
                else:
                    pooled, (hT2, cT2) = _scan_bilstm_pool(x, pf, pr, h02, c02)
                return (
                    jnp.sum(pooled**2)
                    + jnp.sum(jnp.sin(hT2))
                    + jnp.sum(cT2**2)
                )

            return f

        gx, gh, gc = jax.grad(loss(True), argnums=(0, 1, 2))(x, h02, c02)
        rx, rh, rc = jax.grad(loss(False), argnums=(0, 1, 2))(x, h02, c02)
        assert gh.shape == (2, B, H), gh.shape
        assert gc.shape == (2, B, H), gc.shape
        np.testing.assert_allclose(np.asarray(gx), np.asarray(rx), atol=1e-4)
        np.testing.assert_allclose(np.asarray(gh), np.asarray(rh), atol=1e-4)
        np.testing.assert_allclose(np.asarray(gc), np.asarray(rc), atol=1e-4)
    finally:
        lstm_pallas.B_TILE = old


@pytest.mark.slow
def test_pool_vmapped_grad_parity():
    """The production composition (VERDICT r4 #2): the trainer vmaps the
    pooled op over a leading site axis — the 4D dispatch rules must agree
    with the scan path, forward AND backward (shared weights sum over
    sites)."""
    from dinunet_implementations_tpu.ops.lstm_pallas import (
        bilstm_pool_forward_fused,
    )

    S, B, T, D, H = 3, 4, 6, 5, 8
    x = jax.random.normal(jax.random.PRNGKey(33), (S, B, T, D))
    pf = _blocked(jax.random.PRNGKey(34), D, H)
    pr = _blocked(jax.random.PRNGKey(35), D, H)

    def loss(params, fused):
        pf, pr = params

        def per_site(xs):
            if fused:
                pooled, (hT2, cT2) = bilstm_pool_forward_fused(xs, pf, pr)
            else:
                z = jnp.zeros((2, xs.shape[0], H))
                pooled, (hT2, cT2) = _scan_bilstm_pool(xs, pf, pr, z, z)
            return jnp.sum(pooled**2) + jnp.sum(jnp.sin(hT2) + cT2**2)

        return jnp.sum(jax.vmap(per_site)(x))

    out_f = loss((pf, pr), True)
    out_s = loss((pf, pr), False)
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_s), rtol=1e-5)
    g_f = jax.grad(loss)((pf, pr), True)
    g_s = jax.grad(loss)((pf, pr), False)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4
        ),
        g_f,
        g_s,
    )


@pytest.mark.slow
def test_pool_vmapped_site_padding_branch():
    """S not a multiple of the site tile: the _pad_sites branch inside the 4D
    rules must pad and slice back, forward and backward."""
    from dinunet_implementations_tpu.ops import lstm_pallas

    old = lstm_pallas.B_TILE
    lstm_pallas.B_TILE = 8
    try:
        S, B, T, D, H = 3, 4, 5, 4, 8  # st = 8//4 = 2 → S pads 3 → 4
        assert lstm_pallas._pool_s_tile(S, B) == 2
        x = jax.random.normal(jax.random.PRNGKey(36), (S, B, T, D))
        pf = _blocked(jax.random.PRNGKey(37), D, H)
        pr = _blocked(jax.random.PRNGKey(38), D, H)

        def loss(x, fused):
            def per_site(xs):
                if fused:
                    pooled, (hT2, cT2) = lstm_pallas.bilstm_pool_forward_fused(
                        xs, pf, pr
                    )
                else:
                    z = jnp.zeros((2, xs.shape[0], H))
                    pooled, (hT2, cT2) = _scan_bilstm_pool(xs, pf, pr, z, z)
                return jnp.sum(pooled**2) + jnp.sum(hT2 + cT2)

            return jnp.sum(jax.vmap(per_site)(x))

        np.testing.assert_allclose(
            np.asarray(loss(x, True)), np.asarray(loss(x, False)), rtol=1e-5
        )
        gx_f = jax.grad(loss)(x, True)
        gx_s = jax.grad(loss)(x, False)
        np.testing.assert_allclose(
            np.asarray(gx_f), np.asarray(gx_s), atol=1e-4
        )
    finally:
        lstm_pallas.B_TILE = old


@pytest.mark.slow
def test_pool_per_element_weights_lax_map_branch():
    """vmap with BATCHED weights (per-element params) must take the lax.map
    fallback in both the forward and backward custom_vmap rules."""
    from dinunet_implementations_tpu.ops.lstm_pallas import (
        bilstm_pool_forward_fused,
    )

    S, B, T, D, H = 2, 4, 5, 4, 8
    x = jax.random.normal(jax.random.PRNGKey(39), (S, B, T, D))
    pfs = jax.vmap(lambda k: _blocked(k, D, H))(
        jax.random.split(jax.random.PRNGKey(40), S)
    )
    prs = jax.vmap(lambda k: _blocked(k, D, H))(
        jax.random.split(jax.random.PRNGKey(41), S)
    )

    def loss(params, fused):
        pfs, prs = params

        def per_site(xs, pf, pr):
            if fused:
                pooled, (hT2, cT2) = bilstm_pool_forward_fused(xs, pf, pr)
            else:
                z = jnp.zeros((2, xs.shape[0], H))
                pooled, (hT2, cT2) = _scan_bilstm_pool(xs, pf, pr, z, z)
            return jnp.sum(pooled**2) + jnp.sum(hT2 * cT2)

        return jnp.sum(jax.vmap(per_site)(x, pfs, prs))

    np.testing.assert_allclose(
        np.asarray(loss((pfs, prs), True)),
        np.asarray(loss((pfs, prs), False)),
        rtol=1e-5,
    )
    g_f = jax.grad(loss)((pfs, prs), True)
    g_s = jax.grad(loss)((pfs, prs), False)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4
        ),
        g_f,
        g_s,
    )


@pytest.mark.slow
@pytest.mark.parametrize("fused_bidir", [False, True])
def test_icalstm_pallas_vmapped_over_sites_end_to_end(fused_bidir):
    """The EXACT program the federated bench compiles: the full
    ICALstm(use_pallas=True) model vmapped over a leading site axis — logits
    and parameter gradients must match the scan path. Covers BOTH kernel
    arms: per-direction (the measured default) and the opt-in fused
    bidirectional pooled kernel."""
    S = 3
    key = jax.random.PRNGKey(42)
    x = jax.random.normal(key, (S, 4, 6, 5, 4))  # [S, B, windows, C, W]
    y = jnp.tile(jnp.array([0, 1, 0, 1]), (S, 1))
    kwargs = dict(input_size=16, hidden_size=12, num_comps=5, window_size=4)
    m_scan = ICALstm(use_pallas=False, **kwargs)
    m_pal = ICALstm(use_pallas=True, fused_bidir=fused_bidir, **kwargs)
    variables = m_scan.init({"params": key, "dropout": key}, x[0], train=True)

    def loss(v, module):
        def per_site(xs, ys):
            logits = module.apply(v, xs, train=False)
            return -jnp.mean(
                jnp.take_along_axis(
                    jax.nn.log_softmax(logits), ys[:, None], 1
                )
            )

        return jnp.mean(jax.vmap(per_site)(x, y))

    np.testing.assert_allclose(
        np.asarray(loss(variables, m_pal)),
        np.asarray(loss(variables, m_scan)),
        rtol=1e-5,
    )
    g_p = jax.grad(loss)(variables, m_pal)["params"]
    g_s = jax.grad(loss)(variables, m_scan)["params"]
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4
        ),
        g_p,
        g_s,
    )
