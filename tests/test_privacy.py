"""Privacy plane (r20, privacy/) — DP-SGD + RDP accounting, secure-
aggregation masked wires, and personalized per-site heads.

The load-bearing claims, each pinned here:

- the RDP accountant's math (closed forms, monotonicity, serialization)
  and the trainer-surfaced ε matching a from-scratch host recompute;
- DP noise counter-keyed by (seed, site, round) — chunk/resume/packing-
  independent — and the clip actually bounding what ships;
- checkpoint/resume continuing ε accumulation EXACTLY (no double count,
  no reset) and the ε budget stopping a fit cleanly;
- masked == unmasked (pads vs the pads-zeroed verification arm)
  BIT-EXACT, at full liveness AND with dead sites, packed and unpacked —
  the integer-pad cancellation argument as a test vector;
- the documented composition refusals (int8/fp8 codecs, gather-mode
  robust reducers, DCN codecs, the low-rank engines);
- personalized head rows training per site, staying out of the wire,
  checkpoint round-tripping, and rejoin-reset zeroing the head but not
  the cohort ε;
- the r20 jaxprlint fixtures: a mask psum leaking outside the rounds scan
  trips S001, and a dp-on program claiming the dp-off identity trips
  S005's divergence gate.
"""

import json
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dinunet_implementations_tpu import TrainConfig
from dinunet_implementations_tpu.engines import make_engine
from dinunet_implementations_tpu.models import MSANNet
from dinunet_implementations_tpu.privacy import (
    RdpAccountant,
    make_dp_fn,
    sampling_fraction,
)
from dinunet_implementations_tpu.privacy.accounting import (
    rdp_sampled_gaussian,
)
from dinunet_implementations_tpu.privacy.secure_agg import fraction_bits
from dinunet_implementations_tpu.trainer.steps import (
    FederatedTask,
    init_train_state,
    make_eval_fn,
    make_optimizer,
    make_train_epoch_fn,
)

S, STEPS, B, D = 4, 2, 4, 6


def _corner():
    model = MSANNet(in_size=D, hidden_sizes=(8,), out_size=2)
    task = FederatedTask(model)
    opt = make_optimizer("adam", 1e-2)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(S, STEPS, B, D)).astype(np.float32))
    y = jnp.asarray((rng.random((S, STEPS, B)) > 0.5).astype(np.int32))
    w = jnp.ones((S, STEPS, B), jnp.float32)
    return task, opt, (x, y, w)


def _state(task, engine, opt, personalize=()):
    return init_train_state(
        task, engine, opt, jax.random.PRNGKey(0),
        jnp.ones((B, D), jnp.float32), num_sites=S, personalize=personalize,
    )


def _leaves_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    return all(
        np.array_equal(np.asarray(u), np.asarray(v)) for u, v in zip(la, lb)
    )


# ---------------------------------------------------------------------------
# RDP accountant
# ---------------------------------------------------------------------------


def test_rdp_closed_form_at_full_sampling():
    """q == 1 is the plain Gaussian mechanism: RDP_α = α/(2σ²)."""
    for sigma in (0.5, 1.0, 4.0):
        for order in (2, 8, 64):
            assert rdp_sampled_gaussian(1.0, sigma, order) == pytest.approx(
                order / (2 * sigma**2)
            )


def test_rdp_subsampling_amplifies_and_noise_helps():
    """Smaller q and larger σ both shrink the per-step RDP; σ = 0 is ∞."""
    assert rdp_sampled_gaussian(0.1, 1.0, 8) < rdp_sampled_gaussian(1.0, 1.0, 8)
    assert rdp_sampled_gaussian(0.5, 2.0, 8) < rdp_sampled_gaussian(0.5, 0.5, 8)
    assert math.isinf(rdp_sampled_gaussian(0.5, 0.0, 8))
    assert rdp_sampled_gaussian(0.0, 1.0, 8) == 0.0


def test_accountant_epsilon_monotone_and_serializes():
    acct = RdpAccountant()
    assert acct.epsilon(1e-5) == (0.0, None)
    eps = []
    for _ in range(5):
        acct.step(0.8, 0.5, steps=3)
        eps.append(acct.epsilon(1e-5)[0])
    assert all(b > a for a, b in zip(eps, eps[1:])), eps
    # JSON round trip restores the exact ledger (the resume contract)
    clone = RdpAccountant.from_json(json.loads(json.dumps(acct.to_json())))
    assert clone.epsilon(1e-5) == acct.epsilon(1e-5)
    assert clone.steps == acct.steps
    # a noiseless ledger reports infinity, never a fake finite ε
    none = RdpAccountant().step(0.0, 0.5, steps=3)
    assert math.isinf(none.epsilon(1e-5)[0])


def test_sampling_fraction_takes_the_smallest_site():
    assert sampling_fraction(8, 1, [64, 16, 32]) == pytest.approx(0.5)
    assert sampling_fraction(8, 2, [16]) == 1.0  # clamped
    assert sampling_fraction(8, 1, []) == 0.0
    assert sampling_fraction(8, 1, [0, 32]) == pytest.approx(0.25)


# ---------------------------------------------------------------------------
# DP-SGD transform
# ---------------------------------------------------------------------------


def test_dp_noise_is_counter_keyed():
    """Noise depends only on (seed, site, round, leaf) — the chunk/resume/
    packing-independence contract (the AttackPlan-noise pattern)."""
    dp = make_dp_fn(1.0, 0.5, dp_seed=7)
    g = {"a": jnp.zeros((3, 2)), "b": jnp.zeros((4,))}
    out1 = jax.jit(lambda: dp(g, jnp.int32(5), jnp.int32(2)))()
    out2 = jax.jit(lambda: dp(g, jnp.int32(5), jnp.int32(2)))()
    assert _leaves_equal(out1, out2)
    other_round = jax.jit(lambda: dp(g, jnp.int32(6), jnp.int32(2)))()
    assert not _leaves_equal(out1, other_round)
    other_site = jax.jit(lambda: dp(g, jnp.int32(5), jnp.int32(3)))()
    assert not _leaves_equal(out1, other_site)


def test_dp_clip_bounds_the_shipped_gradient():
    dp = make_dp_fn(0.5, 0.0)  # clip only
    g = {"a": jnp.full((10,), 100.0), "b": jnp.full((5,), -40.0)}
    out = dp(g, jnp.int32(0), jnp.int32(0))
    norm = math.sqrt(sum(
        float(jnp.sum(jnp.square(v))) for v in jax.tree.leaves(out)
    ))
    assert norm == pytest.approx(0.5, rel=1e-5)
    # a small gradient passes through untouched (scale clamps at 1)
    small = {"a": jnp.full((10,), 1e-3), "b": jnp.full((5,), 1e-3)}
    assert _leaves_equal(dp(small, jnp.int32(0), jnp.int32(0)), small)


def test_dp_noise_without_clip_is_rejected():
    from dinunet_implementations_tpu.privacy import dp_enabled

    with pytest.raises(ValueError, match="dp_clip"):
        make_dp_fn(0.0, 0.5)
    with pytest.raises(ValueError, match="dp_clip"):
        dp_enabled(0.0, 0.5)
    assert not dp_enabled(0.0, 0.0)
    assert dp_enabled(1.0, 0.0)  # clip-only is a valid (ε = ∞) transform


def test_dp_packed_matches_unpacked():
    """K=2 on a 2-device mesh trains like K=1 on a 4-device mesh under DP —
    the noise keys on GLOBAL site ids, so packing never reshuffles the
    mechanism (the test_packing equivalence policy: allclose at 1e-6)."""
    from dinunet_implementations_tpu.parallel.mesh import host_mesh

    task, opt, data = _corner()
    engine = make_engine("dSGD")
    kw = dict(dp_clip=1.0, dp_noise_multiplier=0.5)

    def run(mesh):
        st = _state(task, engine, opt)
        fn = make_train_epoch_fn(task, engine, opt, mesh=mesh, **kw)
        s, losses = fn(st, *data)
        return s, np.asarray(losses)

    s2, l2 = run(host_mesh(2))
    s1, l1 = run(host_mesh(4))
    np.testing.assert_allclose(l2, l1, atol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-6),
        s2.params, s1.params,
    )


# ---------------------------------------------------------------------------
# trainer-level ε surfaces, recompute, budget, resume
# ---------------------------------------------------------------------------


def _fs_runner(tmp_path, **cfg_kw):
    from dinunet_implementations_tpu.data.demo import make_fs_demo_tree
    from dinunet_implementations_tpu.runner import FedRunner

    root = str(tmp_path / "tree")
    if not os.path.isdir(root):
        make_fs_demo_tree(root, n_sites=2, subjects=16)
    kw = dict(
        epochs=2, patience=10, batch_size=8, telemetry="on",
        dp_clip=1.0, dp_noise_multiplier=0.8,
        # donation off: an earlier test may have enabled the GLOBAL XLA
        # compile cache, and this jaxlib corrupts the heap when a
        # cache-DESERIALIZED executable runs with donated buffers (the
        # documented serving/engine.py warmup bug) — these tests re-fit
        # identical programs, the exact cache-hit recipe
        donate_epoch_state=False,
    )
    kw.update(cfg_kw)
    cfg = TrainConfig(**kw)
    return FedRunner(cfg, data_path=root,
                     out_dir=str(tmp_path / "out")), cfg, root


def test_fit_epsilon_matches_host_recompute(tmp_path):
    """Acceptance: the trainer-reported ε equals a from-scratch accountant
    recompute over the same (σ, q, rounds) trajectory — and the per-epoch
    trail in metrics.jsonl is monotone."""
    runner, cfg, root = _fs_runner(tmp_path)
    res = runner.run(verbose=False)[0]
    tdir = os.path.join(str(tmp_path / "out"), "telemetry", "fold_0")
    from dinunet_implementations_tpu.telemetry.sink import load_metrics

    rows = load_metrics(os.path.join(tdir, "metrics.jsonl"))
    epochs = [r for r in rows if r["kind"] == "epoch"]
    eps_trail = [r["dp_epsilon"] for r in epochs]
    assert all(e is not None for e in eps_trail)
    assert all(b > a for a, b in zip(eps_trail, eps_trail[1:]))
    man = json.load(open(os.path.join(tdir, "manifest.json")))
    assert man["privacy"]["dp_noise_multiplier"] == cfg.dp_noise_multiplier
    # from-scratch recompute: q from the real per-site train-split sizes
    # the runner's fold built (the conservative smallest-site corner) and
    # the per-epoch round counts the telemetry recorded
    from dinunet_implementations_tpu.runner.fed_runner import (
        FedRunner as FR,
        load_site_splits,
    )

    runner2 = FR(cfg, data_path=root, out_dir=str(tmp_path / "out2"))
    fold0 = load_site_splits(
        runner2.cfg, runner2.site_dirs, runner2.site_cfgs
    )[0]
    q = sampling_fraction(
        cfg.batch_size, cfg.local_iterations,
        [len(s) for s in fold0["train"]],
    )
    rounds = [r["rounds"] for r in epochs]
    per_epoch = [b - a for a, b in zip([0] + rounds[:-1], rounds)]
    from dinunet_implementations_tpu.privacy import (
        effective_noise_multiplier,
    )

    acct = RdpAccountant()
    for n_rounds in per_epoch:
        # the trainer composes at σ/2 — clip-of-mean sensitivity is 2C
        acct.step(
            effective_noise_multiplier(cfg.dp_noise_multiplier), q,
            steps=n_rounds,
        )
    expected, _ = acct.epsilon(cfg.dp_delta)
    assert res["dp_epsilon"] == pytest.approx(expected, rel=1e-12)
    assert res["dp_delta"] == cfg.dp_delta
    # logs.json carries the same figures (the notebook-facing surface)
    logs = json.load(open(os.path.join(
        str(tmp_path / "out"), "remote", "simulatorRun", cfg.task_id,
        "fold_0", "logs.json",
    )))
    assert logs["dp_epsilon"] == pytest.approx(res["dp_epsilon"])


def test_epsilon_budget_stops_fit_cleanly(tmp_path):
    """A tiny ε budget stops training after the first epoch that exhausts
    it — checkpointed, event recorded, best-state test still produced."""
    runner, cfg, _ = _fs_runner(
        tmp_path, epochs=8, dp_epsilon_budget=1e-3,
    )
    res = runner.run(verbose=False)[0]
    assert res["stopped_epoch"] == 1  # the very first epoch exhausts 1e-3
    assert res["dp_epsilon"] >= 1e-3
    assert "test_metrics" in res
    from dinunet_implementations_tpu.telemetry.sink import load_metrics

    rows = load_metrics(os.path.join(
        str(tmp_path / "out"), "telemetry", "fold_0", "metrics.jsonl"
    ))
    events = [r for r in rows if r.get("name") == "dp-budget"]
    assert events and events[0]["epsilon"] >= 1e-3
    # the budget stop landed AFTER the rotating checkpoint: resumable
    assert os.path.exists(os.path.join(
        str(tmp_path / "out"), "remote", "simulatorRun", cfg.task_id,
        "fold_0", "checkpoint_latest.msgpack",
    ))


def test_resume_continues_epsilon_exactly(tmp_path):
    """Checkpoint/resume of the accountant: 2 epochs + resume to 4 equals
    an uninterrupted 4-epoch run's ε EXACTLY (no double count, no reset)."""
    from dinunet_implementations_tpu.data.demo import make_fs_demo_tree
    from dinunet_implementations_tpu.runner import FedRunner

    root = str(tmp_path / "tree")
    make_fs_demo_tree(root, n_sites=2, subjects=16)
    # donation off — see _fs_runner: three identical fits in one process
    # are the documented deserialized-executable + donated-buffer segfault
    # recipe on this jaxlib
    kw = dict(patience=10, batch_size=8, telemetry="off",
              dp_clip=1.0, dp_noise_multiplier=0.8,
              donate_epoch_state=False)
    full = FedRunner(
        TrainConfig(epochs=4, **kw), data_path=root,
        out_dir=str(tmp_path / "full"),
    ).run(verbose=False)[0]
    out2 = str(tmp_path / "split")
    FedRunner(
        TrainConfig(epochs=2, **kw), data_path=root, out_dir=out2,
    ).run(verbose=False)
    resumed = FedRunner(
        TrainConfig(epochs=4, **kw), data_path=root, out_dir=out2,
    ).run(resume=True, verbose=False)[0]
    assert resumed["dp_epsilon"] == pytest.approx(
        full["dp_epsilon"], rel=1e-12
    )


# ---------------------------------------------------------------------------
# secure aggregation
# ---------------------------------------------------------------------------


def test_fraction_bits_bounds_the_int32_sum():
    assert fraction_bits(2) == 29
    assert fraction_bits(512) == 21
    for s in (2, 7, 512, 4096):
        assert s * 2 ** fraction_bits(s) <= 2**31


def test_masked_equals_nopads_bitexact_full_liveness():
    """THE secure-agg claim: real pads vs the pads-zeroed verification arm
    are BIT-IDENTICAL — integer cancellation is exact in any reduction
    order."""
    task, opt, data = _corner()
    outs = {}
    for mode in ("mask", "mask-nopads"):
        engine = make_engine("dSGD", secure_agg=mode)
        st = _state(task, engine, opt)
        fn = make_train_epoch_fn(task, engine, opt, mesh=None)
        s, losses = fn(st, *data)
        outs[mode] = (s.params, np.asarray(losses))
    assert _leaves_equal(outs["mask"][0], outs["mask-nopads"][0])
    np.testing.assert_array_equal(outs["mask"][1], outs["mask-nopads"][1])


def test_masked_equals_nopads_bitexact_with_dead_sites():
    """Dropout handling: pads gate per PAIR on the round's liveness, so
    cancellation stays exact over the SURVIVING cohort — bit-identical
    params with a site dead mid-epoch, packed and unpacked."""
    from dinunet_implementations_tpu.parallel.mesh import host_mesh

    task, opt, data = _corner()
    live = np.ones((S, STEPS), np.float32)
    live[1, :] = 0.0  # site 1 never arrives
    live[3, 1] = 0.0  # site 3 drops for round 1
    live = jnp.asarray(live)
    for mesh in (None, host_mesh(2)):
        outs = {}
        for mode in ("mask", "mask-nopads"):
            engine = make_engine("dSGD", secure_agg=mode)
            st = _state(task, engine, opt)
            fn = make_train_epoch_fn(task, engine, opt, mesh=mesh)
            s, _ = fn(st, *data, live)
            outs[mode] = s.params
        assert _leaves_equal(outs["mask"], outs["mask-nopads"]), (
            f"mask ≠ nopads on mesh={mesh}"
        )


def test_secure_agg_packed_matches_unpacked_bitexact():
    """Integer aggregation is reduction-order-proof: K=2 and K=1 packings
    produce BIT-IDENTICAL trajectories (stronger than the float engines'
    allclose equivalence)."""
    from dinunet_implementations_tpu.parallel.mesh import host_mesh

    task, opt, data = _corner()
    engine = make_engine("dSGD", secure_agg="mask")
    outs = []
    for mesh in (host_mesh(2), host_mesh(4)):
        st = _state(task, engine, opt)
        fn = make_train_epoch_fn(task, engine, opt, mesh=mesh)
        s, _ = fn(st, *data)
        outs.append(s.params)
    assert _leaves_equal(*outs)


def test_secure_agg_composition_refusals():
    """The documented refusal matrix: float codec grids and gather-based
    robust reducers shred/defeat the pads; the low-rank engines have no
    dense psum wire to mask. bf16 + norm_clip compose."""
    for wq in ("int8", "fp8"):
        with pytest.raises(ValueError, match="wire_quant"):
            make_engine("dSGD", secure_agg="mask", wire_quant=wq)
    with pytest.raises(ValueError, match="DCN"):
        make_engine("dSGD", secure_agg="mask", dcn_wire_quant="int8")
    with pytest.raises(ValueError, match="robust_agg"):
        make_engine("dSGD", secure_agg="mask", robust_agg="trimmed_mean")
    for eng in ("rankDAD", "powerSGD"):
        with pytest.raises(ValueError, match="dSGD"):
            make_engine(eng, secure_agg="mask")
    # allowed compositions construct fine
    make_engine("dSGD", secure_agg="mask", wire_quant="bf16")
    make_engine("dSGD", secure_agg="mask", precision_bits="16")
    make_engine("dSGD", secure_agg="mask", robust_agg="norm_clip")
    with pytest.raises(ValueError, match="secure_agg"):
        make_engine("dSGD", secure_agg="bogus")


def test_secure_agg_wire_model_is_int32_dense():
    """Wire bytes unchanged: the int32 grid matches the f32 dense wire
    byte-for-byte (+ the [pack] liveness gather), K-invariant — the model
    S002 proves on the +secureagg cells."""
    from dinunet_implementations_tpu.telemetry.metrics import (
        modeled_wire_shapes,
        payload_bytes_of,
    )

    params = {"k": jnp.zeros((6, 8)), "b": jnp.zeros((8,))}
    legacy = make_engine("dSGD")
    masked = make_engine("dSGD", secure_agg="mask")
    for pack in (1, 4):
        base = payload_bytes_of(legacy, params, pack=pack)
        sec = payload_bytes_of(masked, params, pack=pack)
        assert sec == base + 4 * pack  # + the liveness-vector gather
        shapes = modeled_wire_shapes(masked, params, pack=pack)
        total = sum(
            int(np.prod(s)) * d.itemsize for s, d in shapes
        )
        assert total == sec
        assert {str(d) for s, d in shapes if s != (pack,)} == {"int32"}


def test_secure_agg_requires_round_counter():
    """The masks are keyed per (pair, round): an aggregate call without the
    traced round counter (a legacy caller) fails loudly instead of
    silently re-using one round's pads forever."""
    engine = make_engine("dSGD", secure_agg="mask")
    g = {"k": jnp.ones((2, 3))}
    with pytest.raises(ValueError, match="round counter"):
        engine.aggregate(g, {}, jnp.float32(1.0), "site")


# ---------------------------------------------------------------------------
# personalized heads
# ---------------------------------------------------------------------------

PAT = ("fc_out",)


def test_personalized_heads_train_per_site_and_stay_off_the_wire():
    task, opt, data = _corner()
    engine = make_engine("dSGD")
    st0 = _state(task, engine, opt, personalize=PAT)
    fn = make_train_epoch_fn(task, engine, opt, mesh=None, personalize=PAT)
    st1, _ = fn(st0, *data)

    def pkey(kp):
        return "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                        for k in kp)

    before = jax.tree_util.tree_flatten_with_path(st0.params)[0]
    after = jax.tree_util.tree_flatten_with_path(st1.params)[0]
    for (kp, b), (_, a) in zip(before, after):
        if "fc_out" in pkey(kp):
            # the global head copy is FROZEN (zero aggregate → zero Adam)
            np.testing.assert_array_equal(np.asarray(b), np.asarray(a))
        else:
            assert not np.array_equal(np.asarray(b), np.asarray(a))
    # per-site head rows genuinely diverged (sites hold different data)
    rows = np.asarray(jax.tree.leaves(st1.personal["params"])[0])
    assert rows.shape[0] == S
    assert not np.allclose(rows[0], rows[1])
    # engine state was initialized on the SHARED subtree only: the wire
    # model (what ships) must not charge the head leaves
    from dinunet_implementations_tpu.privacy.personalize import (
        head_leaf_paths,
        strip_tree,
    )
    from dinunet_implementations_tpu.telemetry.metrics import (
        payload_bytes_of,
    )

    paths = head_leaf_paths(st0.params, PAT)
    shared = strip_tree(st0.params, paths, keep_head=False)
    assert payload_bytes_of(engine, shared) < payload_bytes_of(
        engine, st0.params
    )


def test_personalized_eval_uses_each_sites_head():
    task, opt, data = _corner()
    engine = make_engine("dSGD")
    st = _state(task, engine, opt, personalize=PAT)
    # give site 0 a deliberately different head row — SCALED, not shifted
    # (adding a constant to every fc_out column would move both logits
    # equally and leave the softmax untouched)
    personal = st.personal
    bumped = jax.tree.map(
        lambda leaf: leaf.at[0].set(leaf[0] * 3.0), personal["params"]
    )
    st = st.replace(personal={**personal, "params": bumped})
    eval_fn = make_eval_fn(task, mesh=None, personalize=PAT)
    x = jnp.broadcast_to(data[0][0:1], data[0].shape)  # same inputs per site
    probs, _, _ = eval_fn(st, x, data[1], data[2])
    probs = np.asarray(probs)
    assert not np.allclose(probs[0], probs[1])  # site 0's head differs
    np.testing.assert_allclose(probs[1], probs[2], atol=1e-6)


def test_personalized_checkpoint_roundtrip_and_resume(tmp_path):
    from dinunet_implementations_tpu.trainer.checkpoint import (
        load_checkpoint,
        save_checkpoint,
    )

    task, opt, data = _corner()
    engine = make_engine("dSGD")
    st = _state(task, engine, opt, personalize=PAT)
    fn = make_train_epoch_fn(task, engine, opt, mesh=None, personalize=PAT)
    st1, _ = fn(st, *data)
    path = str(tmp_path / "ck.msgpack")
    save_checkpoint(path, st1)
    restored = load_checkpoint(path, _state(task, engine, opt,
                                            personalize=PAT))
    assert _leaves_equal(restored.personal, st1.personal)
    # a legacy (unpersonalized) checkpoint restores into a personalized run
    # with fresh common-model rows, never a failed resume
    st_plain = _state(task, engine, opt)
    save_checkpoint(str(tmp_path / "legacy.msgpack"), st_plain)
    fresh = load_checkpoint(
        str(tmp_path / "legacy.msgpack"),
        _state(task, engine, opt, personalize=PAT),
    )
    assert fresh.personal is not None


def test_rejoin_resets_head_row_but_not_cohort_epsilon():
    """The membership contract (satellite): reset_slot_state zeroes the
    rejoining slot's head back to the CURRENT global copy and resets its
    optimizer row — while the cohort's privacy ledger (trainer-side, a
    property of the mechanism's history) is untouched."""
    from dinunet_implementations_tpu.privacy.personalize import (
        head_leaf_paths,
        strip_tree,
    )
    from dinunet_implementations_tpu.robustness.membership import (
        reset_slot_state,
    )

    task, opt, data = _corner()
    engine = make_engine("dSGD")
    st = _state(task, engine, opt, personalize=PAT)
    fn = make_train_epoch_fn(task, engine, opt, mesh=None, personalize=PAT)
    st1, _ = fn(st, *data)
    acct = RdpAccountant().step(0.8, 0.5, steps=4)
    ledger_before = json.dumps(acct.to_json())
    st2 = reset_slot_state(st1, slot=1, engine=engine)
    paths = head_leaf_paths(st1.params, PAT)
    fresh_head = strip_tree(st1.params, paths, keep_head=True)
    for leaf, fresh in zip(
        jax.tree.leaves(st2.personal["params"]),
        jax.tree.leaves(fresh_head),
    ):
        # slot 1 back to the (frozen) global head copy
        np.testing.assert_array_equal(np.asarray(leaf)[1], np.asarray(fresh))
    # the other slots keep their personalized rows
    for a, b in zip(
        jax.tree.leaves(st2.personal["params"]),
        jax.tree.leaves(st1.personal["params"]),
    ):
        np.testing.assert_array_equal(np.asarray(a)[0], np.asarray(b)[0])
    # the cohort ε is not slot state: the ledger is untouched by rejoin
    assert json.dumps(acct.to_json()) == ledger_before


@pytest.mark.parametrize("engine_name,kw", [
    ("rankDAD", dict(dad_reduction_rank=2, dad_num_pow_iters=2)),
    ("powerSGD", dict(dad_reduction_rank=2)),
])
def test_rejoin_reset_works_with_stateful_engines(engine_name, kw):
    """Review regression: under personalization, engine state lives on the
    SHARED subtree — reset_slot_state must re-init the rejoining slot's
    engine row from that subtree too, or rankDAD/powerSGD rejoins crash on
    a tree-structure mismatch (dSGD's empty engine state hid this)."""
    from dinunet_implementations_tpu.robustness.membership import (
        reset_slot_state,
    )

    task, opt, data = _corner()
    engine = make_engine(engine_name, **kw)
    st = _state(task, engine, opt, personalize=PAT)
    fn = make_train_epoch_fn(task, engine, opt, mesh=None, personalize=PAT)
    st1, _ = fn(st, *data)
    st2 = reset_slot_state(st1, slot=1, engine=engine)
    # slot 1's engine row is fresh; the others survive
    for leaf1, leaf2 in zip(
        jax.tree.leaves(st1.engine_state), jax.tree.leaves(st2.engine_state)
    ):
        np.testing.assert_array_equal(
            np.asarray(leaf1)[0], np.asarray(leaf2)[0]
        )


def test_personalize_pattern_validation():
    from dinunet_implementations_tpu.privacy.personalize import (
        head_leaf_paths,
    )

    task, opt, _ = _corner()
    engine = make_engine("dSGD")
    st = _state(task, engine, opt)
    with pytest.raises(ValueError, match="no parameter leaf"):
        head_leaf_paths(st.params, ("nonexistent_layer",))
    with pytest.raises(ValueError, match="EVERY parameter"):
        head_leaf_paths(st.params, ("kernel", "bias", "scale", "mean", "var"))


# ---------------------------------------------------------------------------
# jaxprlint negative fixtures (satellite)
# ---------------------------------------------------------------------------


def test_mask_psum_outside_rounds_scan_trips_s001():
    """A secure-agg implementation whose pad material crosses the site axis
    OUTSIDE the rounds scan is per-epoch stray communication — S001 must
    flag it (the r20 mirror of the training rule's outside-scan case)."""
    from dinunet_implementations_tpu.checks.semantic import (
        audit_jaxpr,
        check_collective_axes,
    )
    from dinunet_implementations_tpu.core.jaxcompat import shard_map
    from dinunet_implementations_tpu.parallel.mesh import SITE_AXIS, host_mesh
    from jax.sharding import PartitionSpec as P

    mesh = host_mesh(2)

    def leaky(x):
        # the pad psum OUTSIDE any scan — the leak under test
        pad = jax.lax.bitcast_convert_type(
            jax.random.bits(jax.random.PRNGKey(0), x.shape, jnp.uint32),
            jnp.int32,
        )
        tot = jax.lax.psum(x.astype(jnp.int32) + pad, SITE_AXIS)

        def body(c, _):
            return c + jax.lax.psum(x, SITE_AXIS), None

        out, _ = jax.lax.scan(body, jnp.zeros_like(x), None, length=2)
        return out + tot.astype(x.dtype)

    fn = lambda x: shard_map(  # noqa: E731
        leaky, mesh=mesh, in_specs=P(SITE_AXIS), out_specs=P(SITE_AXIS),
        check_vma=False,
    )(x)
    jaxpr = jax.make_jaxpr(fn)(jnp.ones((2, 3), jnp.float32))
    findings = check_collective_axes(
        audit_jaxpr(jaxpr).collectives, "trace://fixture/secureagg-leak"
    )
    assert any("OUTSIDE" in f.message for f in findings), findings


def test_dp_on_claiming_dp_off_identity_trips_s005():
    """A dp-on program claiming the dp-off wire/program model must trip the
    S005 divergence gate — and the real dp-on pair must genuinely
    diverge (the inverse gate that keeps 'the mechanism ran' honest)."""
    from dinunet_implementations_tpu.checks.semantic import (
        TraceCell,
        check_lowering_identity,
        identity_text_fn,
    )

    text = identity_text_fn(TraceCell("dSGD", "vmap", "host"))
    base = text()
    dp_text = text(dp_clip=1.0, dp_noise_multiplier=0.5)
    # the lie: "my dp-on program is the dp-off program" → finding
    lied = check_lowering_identity(
        [("dp-claims-off", base, dp_text, True)]
    )
    assert lied and lied[0].rule == "S005"
    # the truth: dp-on genuinely diverges → no finding
    assert check_lowering_identity(
        [("dp-on", base, dp_text, False)]
    ) == []


# ---------------------------------------------------------------------------
# manifest + schema surfaces
# ---------------------------------------------------------------------------


def test_privacy_manifest_is_required_and_verbatim():
    from dinunet_implementations_tpu.telemetry.sink import (
        build_manifest,
        validate_manifest,
    )

    cfg = TrainConfig()
    man = build_manifest(cfg)
    assert validate_manifest(man) == []
    assert man["privacy"] is None  # plane off → explicit null
    stripped = {k: v for k, v in man.items() if k != "privacy"}
    assert any("privacy" in p for p in validate_manifest(stripped))
    on = build_manifest(cfg.replace(
        dp_clip=1.0, dp_noise_multiplier=0.5, secure_agg="mask",
        personalize=("fc_out",),
    ))
    assert on["privacy"] == {
        "dp_clip": 1.0, "dp_noise_multiplier": 0.5, "dp_seed": 0,
        "dp_delta": 1e-5, "dp_epsilon_budget": 0.0, "secure_agg": "mask",
        "secure_agg_seed": 0, "personalize": ["fc_out"],
    }


def test_epoch_row_schema_requires_dp_epsilon():
    from dinunet_implementations_tpu.telemetry.sink import (
        ROW_REQUIRED,
        validate_metrics_rows,
    )

    assert "dp_epsilon" in ROW_REQUIRED["epoch"]
    row = {k: 0 for k in ROW_REQUIRED["epoch"] if k != "dp_epsilon"}
    row["kind"] = "epoch"
    assert any("dp_epsilon" in p for p in validate_metrics_rows([row]))
