"""Back-compat shim: the r8 test fixture graduated into the real multi-host
entry point ``dinunet_implementations_tpu/runner/dcn_worker.py`` (r18). The
test harness's legacy positional invocation

    python dcn_worker.py <port> <num_processes> <process_id> \
        <data_path> <out_dir> <report_path>

maps onto the module CLI; new capabilities (``--slices``,
``--dcn-wire-quant``, ``--set``) are flags on the module itself.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dinunet_implementations_tpu.runner import dcn_worker  # noqa: E402

port, nproc, pid, data_path, out_dir, report = sys.argv[1:7]
extra = sys.argv[7:]  # optional module flags appended by newer harnesses

sys.exit(dcn_worker.main([
    "--coordinator", f"127.0.0.1:{port}",
    "--num-processes", nproc,
    "--process-id", pid,
    "--data-path", data_path,
    "--out-dir", out_dir,
    "--report", report,
    *extra,
]))
