"""MSANNet — the FreeSurfer-volume MLP classifier.

Architecture parity with reference ``comps/fs/models.py:4-31``: per hidden
layer ``Linear(bias=False) → BatchNorm(track_running_stats=False) → ReLU
[→ Dropout(0.5) if layer index ∈ dropout_in]``, then a biased ``Linear`` head.
Defaults 66 → (256,128,64,32) → 2 (``compspec.json:227-235``).

TPU notes: the whole net is a chain of small matmuls — XLA fuses the
BN/ReLU/dropout elementwise chain into the matmuls; batch stats are
mask-weighted so SPMD padding rows don't perturb them (see models/layers.py).
"""

from __future__ import annotations

import flax.linen as nn

from .layers import BatchNorm, dense


class MSANNet(nn.Module):
    in_size: int = 66
    hidden_sizes: tuple = (256, 128, 64, 32)
    out_size: int = 2
    dropout_in: tuple = ()
    dropout_rate: float = 0.5

    @nn.compact
    def __call__(self, x, train: bool = True, mask=None):
        fan_in = self.in_size
        for i, h in enumerate(self.hidden_sizes):
            x = dense(h, use_bias=False, name=f"linear_{i}")(x)
            x = BatchNorm(h, track_running_stats=False, name=f"bn_{i}")(
                x, train=train, mask=mask
            )
            x = nn.relu(x)
            if i in self.dropout_in:
                x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
            fan_in = h
        return dense(self.out_size, fan_in=fan_in, name="fc_out")(x)
