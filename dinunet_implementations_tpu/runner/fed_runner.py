"""Runners: single-site (SiteRunner parity) and federated over a dataset tree.

- :class:`SiteRunner` — the reference's standalone debug harness
  (``comps/fs/site_run.py:4-6``, ``comps/icalstm/site_run.py:5-9``): train one
  site from a ``datasets/<name>`` folder + its ``inputspec.json``, no
  aggregation (a 1-site federation).
- :class:`FedRunner` — the replacement for the COINSTAC simulator (SURVEY.md
  §4.1): discovers ``input/local*/simulatorRun`` site dirs (the reference's
  fixture convention), builds per-site datasets/splits, and trains them as one
  SPMD program on a site mesh (or folded onto one chip with ``mesh=None``).
  Supports split-ratio and k-fold drivers.
- :class:`FedDaemon` — the long-running SERVICE form (elastic rounds, r13):
  a persistent loop over one compiled epoch program with a fixed
  ``[capacity]`` virtual-site axis, absorbing site joins / leaves / rejoins
  from a filesystem ingest spool (``robustness/membership.py``
  MembershipTable), holding rounds below a quorum floor, checkpointing on
  membership epochs, and — with ``TrainConfig.staleness_bound > 0`` —
  aggregating under the staleness-bounded buffered-async semantics so
  stragglers fade instead of stalling. CLI: ``dinunet-tpu --serve``.
"""

from __future__ import annotations

import glob
import json
import os
import re
import time

import numpy as np

from ..core.config import TrainConfig, resolve_site_configs
from ..data.api import SiteArrays, build_site_dataset
from ..data.splits import resolve_splits
from ..parallel.mesh import SITE_AXIS, host_mesh, packed_site_mesh
from ..trainer.loop import FederatedTrainer
from .registry import get_task, task_cache


def _site_dir_key(path: str):
    """Numeric-then-lexicographic sort key for a ``local*`` site dir.

    The site number is taken from the ``local*`` path segment ONLY (not the
    whole path — a digit elsewhere in the tree must not reorder sites), via
    ``re.search``: mixed trees with a bare ``local`` dir (no digits) or
    decorated names (``local_backup``, unicode digit lookalikes that
    ``str.isdigit`` accepts but ``int()`` rejects) sort first instead of
    crashing the runner. The full path tie-breaks duplicates
    deterministically.
    """
    segment = os.path.basename(os.path.dirname(path))
    m = re.search(r"([0-9]+)", segment)
    return (int(m.group(1)) if m else -1, path)


def discover_site_dirs(dataset_dir: str) -> list[str]:
    """Reference fixture layout: ``<dataset_dir>/input/local{i}/simulatorRun``
    (``datasets/test_fsl``); falls back to ``dataset_dir`` itself as a single
    site when no local* dirs exist."""
    pattern = os.path.join(dataset_dir, "input", "local*", "simulatorRun")
    dirs = sorted(glob.glob(pattern), key=_site_dir_key)
    return dirs or [dataset_dir]


def auto_site_mesh(cfg: TrainConfig, num_sites: int):
    """Resolve the ``mesh="auto"`` topology for ``num_sites`` virtual sites:
    multi-host hybrid mesh when a distributed runtime is up, the packed
    ``(site, model)`` mesh when the devices fit (k = cfg.sites_per_device
    virtual sites per member, r12), CPU host devices as the simulator
    fallback, and ``None`` (fold every site onto one device via vmap)
    otherwise. ``cfg.num_slices > 1`` (r18) lays the outer DCN slice axis
    over either form — processes map to slices on a multi-host runtime,
    virtual devices emulate them in one process. Shared by the batch
    :class:`FedRunner` and the daemon-mode :class:`FedDaemon`, so both
    resolve churn-capacity and fold topologies identically."""
    import jax

    m = max(cfg.model_axis_size, 1)
    k = max(cfg.sites_per_device, 1)
    n_slices = max(cfg.num_slices, 1)
    if num_sites % k:
        raise ValueError(
            f"sites_per_device={k} must divide the site count ({num_sites})"
        )
    n_mesh = num_sites // k  # mesh site-axis size; k sites pack per device
    if n_slices > 1 and num_sites % (k * n_slices):
        raise ValueError(
            f"num_slices={n_slices} × sites_per_device={k} must divide the "
            f"site count ({num_sites})"
        )
    devs = jax.devices()
    cpus = [d for d in devs if d.platform == "cpu"]
    if jax.process_count() > 1:
        # multi-host runtime (distributed_init): hybrid mesh — the model
        # axis stays on each host's ICI, sites span DCN; with num_slices > 1
        # processes become slice granules and the inter-slice hop is the
        # only per-round DCN traffic (the multi-slice deployment shape,
        # one runner/dcn_worker.py process per slice)
        if n_slices > 1:
            from ..parallel.distributed import multihost_sliced_site_mesh

            return multihost_sliced_site_mesh(
                num_slices=n_slices,
                sites_per_slice=num_sites // n_slices,
                sites_per_device=k,
                model_axis_size=m,
            )
        from ..parallel.distributed import multihost_site_mesh

        if n_mesh % jax.process_count():
            raise ValueError(
                f"{n_mesh} mesh sites must divide evenly over "
                f"{jax.process_count()} processes"
            )
        return multihost_site_mesh(
            sites_per_process=n_mesh // jax.process_count(),
            model_axis_size=m,
        )
    if n_slices > 1:
        # single-process emulation of the sliced topology over virtual
        # devices — the whole tier-1 suite exercises the DCN tier this way
        from ..parallel.mesh import sliced_site_mesh

        if len(devs) < n_mesh * m and len(cpus) >= n_mesh * m:
            devs = cpus
        return sliced_site_mesh(
            n_slices, num_sites // n_slices, k, devs, model_axis_size=m
        )
    if len(devs) >= n_mesh * m:
        # the packed topology (parallel/mesh.py): k virtual sites per mesh
        # member, two-level aggregation in the epoch
        return packed_site_mesh(num_sites, k, devs, model_axis_size=m)
    if len(cpus) >= n_mesh * m:
        return host_mesh(n_mesh, model_axis_size=m)
    if m > 1:
        raise ValueError(
            f"model_axis_size={m} with {n_mesh} mesh sites needs "
            f"{n_mesh * m} devices (have {len(devs)}); sequence "
            "parallelism cannot fold onto one device"
        )
    return None  # fold all sites onto the local device via vmap


def load_site_splits(
    cfg: TrainConfig, site_dirs: list[str], site_cfgs: list[TrainConfig] | None = None
):
    """Build per-site datasets and per-fold splits.

    Returns ``folds``: list (per fold) of dicts with ``train``/``validation``/
    ``test`` lists of :class:`SiteArrays` (one entry per site).
    """
    site_cfgs = site_cfgs or [cfg] * len(site_dirs)
    spec = get_task(cfg.task_id)
    site_arrays = []
    site_splits = []
    for i, (d, scfg) in enumerate(zip(site_dirs, site_cfgs)):
        ds = build_site_dataset(
            spec.dataset_cls, spec.handle_cls, task_cache(scfg), {"baseDirectory": d},
            mode=scfg.mode,
        )
        arrs = ds.as_arrays()
        site_arrays.append(arrs)
        args = scfg.task_args()
        site_splits.append(
            resolve_splits(
                len(arrs),
                split_ratio=scfg.split_ratio,
                num_folds=scfg.num_folds,
                split_files=tuple(getattr(args, "split_files", ()) or ()),
                base_dir=d,
                seed=scfg.seed + i,
            )
        )
    num_folds = min(len(s) for s in site_splits)
    folds = []
    for k in range(num_folds):
        fold = {"train": [], "validation": [], "test": []}
        for arrs, splits in zip(site_arrays, site_splits):
            for key in fold:
                fold[key].append(arrs.take(splits[k][key]))
        folds.append(fold)
    return folds


class FedRunner:
    """Federated training over a reference-style dataset tree."""

    def __init__(
        self,
        cfg: TrainConfig | None = None,
        data_path: str = ".",
        out_dir: str | None = None,
        mesh="auto",
        fault_plan=None,
        attack_plan=None,
        **overrides,
    ):
        cfg = (cfg or TrainConfig()).with_overrides(overrides)
        self.data_path = data_path
        # deterministic chaos injection (robustness/faults.py), threaded into
        # every fold's trainer; None = no faults. attack_plan is the hostile
        # twin (robustness/attacks.py, r17) — byzantine gradient transforms.
        self.fault_plan = fault_plan
        self.attack_plan = attack_plan
        self.site_dirs = discover_site_dirs(data_path)
        self.site_cfgs = resolve_site_configs(cfg, data_path, num_sites=len(self.site_dirs))
        # owner-scoped fields come from site 0 (the reference GUI sends one
        # owner config; per-site inputspecs override member fields)
        self.cfg = self.site_cfgs[0].replace(num_sites=len(self.site_dirs))
        self.out_dir = out_dir or os.path.join(data_path, "output")
        if mesh == "auto":
            mesh = auto_site_mesh(self.cfg, len(self.site_dirs))
        self.mesh = mesh

    def run(self, folds=None, verbose: bool = True, resume: bool = False) -> list[dict]:
        """``resume=True`` continues each fold from its last
        validation-boundary checkpoint; ``cfg.mode == "test"`` skips training
        and evaluates each fold's best checkpoint."""
        all_folds = load_site_splits(self.cfg, self.site_dirs, self.site_cfgs)
        fold_ids = list(range(len(all_folds)))
        if folds is not None:
            all_folds = [all_folds[k] for k in folds]
            fold_ids = list(folds)
        from ..checks.sanitize import sanitized_fit

        results = []
        for k, fold in zip(fold_ids, all_folds):
            trainer = FederatedTrainer(
                self.cfg, get_task(self.cfg.task_id).build_model(self.cfg),
                self.mesh, out_dir=self.out_dir, fault_plan=self.fault_plan,
                attack_plan=self.attack_plan,
            )
            # DINUNET_SANITIZE=1 (or CLI --sanitize): compile-counter guard +
            # leak/NaN checking around the fit — each fold's trainer is one
            # (engine, topology) program, so the per-fit guard IS the
            # one-compilation-per-program gate. No-op when disabled.
            with sanitized_fit(
                trainer, label=f"{self.cfg.agg_engine}/fold{k}"
            ) as report:
                res = trainer.fit(
                    fold["train"], fold["validation"], fold["test"], fold=k,
                    verbose=verbose, resume=resume,
                )
                if report is not None:
                    report.note_result(res)
            results.append(res)
        return results


class SiteRunner:
    """Single-site harness (reference ``SiteRunner``; the ``taks_id`` typo is
    the library's kwarg — accepted here for drop-in parity)."""

    def __init__(
        self,
        taks_id: str | None = None,
        task_id: str | None = None,
        data_path: str = ".",
        mode: str = "train",
        seed: int = 0,
        site_index: int = 0,
        split_ratio=(0.8, 0.1, 0.1),
        monitor_metric: str = "auc",
        metric_direction: str = "maximize",
        log_header: str = "Loss|AUC",
        batch_size: int = 16,
        out_dir: str | None = None,
        **kw,
    ):
        # the reference's taks_id is a short name ('FSL', 'ICA'); map to tasks
        tid = task_id or {"FSL": "FS-Classification", "ICA": "ICA-Classification"}.get(
            taks_id, taks_id
        )
        self.site_index = site_index
        self.cfg = TrainConfig(
            task_id=tid,
            mode=mode,
            seed=seed,
            split_ratio=tuple(split_ratio),
            monitor_metric=monitor_metric,
            metric_direction=metric_direction,
            log_header=log_header,
            batch_size=batch_size,
        ).with_overrides(kw)
        self.data_path = data_path
        self.out_dir = out_dir

    def run(self, trainer_cls=None, dataset_cls=None, handle_cls=None, verbose=True):
        """Positional (Trainer, Dataset, DataHandle) accepted for reference
        signature parity; the registry supplies defaults."""
        site_dirs = discover_site_dirs(self.data_path)
        site_cfgs = resolve_site_configs(
            self.cfg, self.data_path, num_sites=len(site_dirs)
        )
        ix = min(self.site_index, len(site_dirs) - 1)
        cfg = site_cfgs[ix]
        spec = get_task(cfg.task_id)
        dataset_cls = dataset_cls or spec.dataset_cls
        handle_cls = handle_cls or spec.handle_cls
        ds = build_site_dataset(
            dataset_cls, handle_cls, task_cache(cfg),
            {"baseDirectory": site_dirs[ix]}, mode=cfg.mode,
        )
        arrs = ds.as_arrays()
        args = cfg.task_args()
        splits = resolve_splits(
            len(arrs),
            split_ratio=cfg.split_ratio,
            num_folds=cfg.num_folds,
            split_files=tuple(getattr(args, "split_files", ()) or ()),
            base_dir=site_dirs[ix],
            seed=cfg.seed,
        )
        from ..checks.sanitize import sanitized_fit

        results = []
        for k, split in enumerate(splits):
            trainer = FederatedTrainer(
                cfg, spec.build_model(cfg), mesh=None, out_dir=self.out_dir
            )
            with sanitized_fit(
                trainer, label=f"{cfg.agg_engine}/site{ix}/fold{k}"
            ) as report:
                res = trainer.fit(
                    [arrs.take(split["train"])],
                    [arrs.take(split["validation"])],
                    [arrs.take(split["test"])],
                    fold=k,
                    verbose=verbose,
                )
                if report is not None:
                    report.note_result(res)
            results.append(res)
        return results


# ---------------------------------------------------------------------------
# daemon mode — elastic rounds (r13)
# ---------------------------------------------------------------------------

#: spool event files are JSON objects with an "event" key:
#:   {"event": "join", "site": "<id>", "data_dir": "<path>"}
#:   {"event": "leave", "site": "<id>"}
#:   {"event": "shutdown"}
#: plus an optional "after_epoch": N — the event is held in the spool until
#: the daemon has trained N epochs (deterministic churn scheduling for tests
#: and the CI smoke). Files are processed in sorted-filename order and
#: removed once applied.
SPOOL_EVENTS = ("join", "leave", "shutdown")


class FedDaemon:
    """Daemon-mode federated training: a persistent service over ONE
    compiled epoch program.

    The virtual-site axis is pinned at ``capacity`` slots for the life of
    the service; logical sites float over it through a
    :class:`~..robustness.membership.MembershipTable`. Membership events
    arrive as JSON files in ``spool_dir`` (see :data:`SPOOL_EVENTS`);
    admission (dataset load) is deadline-bounded via
    :func:`~..robustness.retry.with_retry` so a half-written site directory
    fails fast instead of wedging the service. Every traced shape — the
    ``[capacity, N, ...]`` inventory grid, the ``[capacity, steps, B]``
    index plan, the liveness mask — is pinned at service start, so churn
    NEVER retraces (CompileGuard-assertable: one epoch compile across any
    join → straggle → leave → rejoin sequence).

    Degradation: below ``quorum`` occupied slots the service HOLDS — rounds
    are counted but not aggregated — rather than training on a sliver of
    the federation. Checkpoints rotate every epoch and on every membership
    epoch, with the table (and each member's data dir) embedded in the
    atomically-paired meta, so ``resume=True`` restores the exact slot map
    and re-admits the members' data.
    """

    def __init__(
        self,
        cfg: TrainConfig | None = None,
        capacity: int = 8,
        spool_dir: str | None = None,
        out_dir: str | None = None,
        data_path: str | None = None,
        quorum: int = 1,
        poll_s: float = 0.5,
        mesh="auto",
        fault_plan=None,
        attack_plan=None,
        admission_deadline_s: float = 10.0,
        inventory_rows: int | None = None,
        steps: int | None = None,
        resume: bool = False,
        verbose: bool = True,
        bus=None,
        flight=None,
        sink_tags: dict | None = None,
        **overrides,
    ):
        from ..robustness.membership import MembershipTable
        from ..telemetry.bus import global_bus
        from ..telemetry.flight import FlightRecorder

        cfg = (cfg or TrainConfig()).with_overrides(overrides)
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if not 1 <= quorum <= capacity:
            raise ValueError(
                f"quorum must be in [1, capacity={capacity}], got {quorum}"
            )
        self.cfg = cfg.replace(num_sites=capacity)
        self.capacity = capacity
        self.quorum = quorum
        self.poll_s = poll_s
        self.fault_plan = fault_plan
        self.attack_plan = attack_plan
        self.admission_deadline_s = admission_deadline_s
        self.verbose = verbose
        self.spool_dir = spool_dir or (
            os.path.join(data_path, "spool") if data_path else "spool"
        )
        self.out_dir = out_dir or (
            os.path.join(data_path, "output") if data_path else "output"
        )
        os.makedirs(self.spool_dir, exist_ok=True)
        # live observability (r16): the daemon always publishes into a
        # MetricsBus (the process-wide one unless injected) — host-side
        # bookkeeping only, readable by the /statusz exporter — and always
        # keeps a flight recorder ring so a crash/SIGTERM dumps the final
        # seconds even when file telemetry is off
        self.bus = bus if bus is not None else global_bus()
        if mesh == "auto":
            mesh = auto_site_mesh(self.cfg, capacity)
        self.mesh = mesh
        # multi-slice (r18): slot → slice mapping for membership events /
        # gauges — one trace id is then followable spool→slice→aggregation→
        # publish. 1 on single-slice meshes (every slot reads slice 0).
        from ..parallel.mesh import slice_count

        self.num_slices = slice_count(mesh)
        self.trainer = FederatedTrainer(
            self.cfg, get_task(self.cfg.task_id).build_model(self.cfg),
            mesh, out_dir=self.out_dir, fault_plan=fault_plan, bus=self.bus,
            attack_plan=attack_plan,
        )
        self.flight = flight if flight is not None else FlightRecorder(
            self.out_dir, bus=self.bus, tracer=self.trainer.tracer,
        )
        self.trainer._num_sites = capacity
        self.table = MembershipTable(capacity)
        self.state = None  # built lazily at first admission (needs shapes)
        self.epochs_run = 0
        self.held_rounds = 0
        self._stop = False
        self._preempted = False
        self._idle = False  # held-state latch (serve loop + ingest release)
        self._data: dict = {}  # site id -> SiteArrays
        self._dirs: dict = {}  # site id -> data dir (for resume re-admission)
        # site id -> flat config-override dict (a join event's "config" key /
        # the tree's inputspec entry): JSON-able, checkpointed in meta so
        # resume re-admits each member under its own labels/data columns
        self._overrides: dict = {}
        # site id -> trace id (a join event's "trace_id"): cross-process
        # trace propagation — flows into the membership telemetry events
        # and the checkpoint meta, so a served checkpoint can name the
        # spool events whose data trained it
        self._traces: dict = {}
        # ONE cached zero-row placeholder for free slots: _ensure_inventory's
        # content fingerprint is id()-keyed, and fresh placeholders per epoch
        # would silently re-stack + re-upload the whole inventory grid every
        # epoch whenever any slot is free
        self._empty_site = None
        self._feat = None  # feature shape, fixed at first admission
        self._rows = inventory_rows  # pinned inventory grid height
        self._steps = steps  # pinned per-epoch step-grid height
        self._compiles0 = None
        self._sink = None
        ckpt_dir = os.path.join(self.out_dir, "serve")
        self.ckpt_path = os.path.join(ckpt_dir, "checkpoint_latest.msgpack")
        if self.cfg.telemetry == "on":
            from ..telemetry.sink import FitTelemetry

            self._sink = FitTelemetry.open(
                os.path.join(
                    self.cfg.telemetry_dir
                    or os.path.join(self.out_dir, "telemetry"),
                    "serve",
                ),
                self.cfg, mesh=self.mesh, fold=0, tracer=self.trainer.tracer,
                fault_plan=fault_plan, attack_plan=attack_plan,
                tags=sink_tags,
            )
        resumed = self._resume() if resume else False
        if not resumed and data_path:
            # pre-join the tree's existing local* sites (the batch runner's
            # discovery + per-site inputspec overrides), so `--serve` on a
            # simulator tree starts training immediately and the spool only
            # carries the churn
            from ..core.config import load_inputspec

            spec_path = os.path.join(data_path, "inputspec.json")
            per_site = (
                load_inputspec(spec_path) if os.path.exists(spec_path)
                else [{}]
            )
            for i, d in enumerate(discover_site_dirs(data_path)):
                self.apply_event({
                    "event": "join", "site": f"local{i}", "data_dir": d,
                    "config": per_site[i % len(per_site)],
                })
            if self.table.occupied:
                self._on_membership_change()

    # -- logging / telemetry helpers -------------------------------------

    def _log(self, msg: str) -> None:
        if self.verbose:
            from ..trainer.logs import log_info

            log_info(msg)

    def _event(self, name: str, **attrs) -> None:
        if self._sink is not None:
            # API-boundary forward: NAME is a literal at every call site
            self._sink.event(name, **attrs)  # jaxlint: disable=R007

    # -- admission --------------------------------------------------------

    def _load_site(self, data_dir: str, overrides: dict | None = None):
        """Deadline-bounded dataset load for one joining site: a spool event
        can point at a directory still being rsynced — retry briefly, then
        reject the join instead of wedging the service (with_retry
        deadline_s semantics, robustness/retry.py). ``overrides`` is the
        site's flat config-override dict (its inputspec entry / the join
        event's "config") — per-site labels files and data columns resolve
        exactly as in the batch runner."""
        from ..robustness.retry import with_retry

        scfg = self.cfg.with_overrides(overrides or {})
        spec = get_task(scfg.task_id)

        def load():
            ds = build_site_dataset(
                spec.dataset_cls, spec.handle_cls, task_cache(scfg),
                {"baseDirectory": data_dir}, mode=scfg.mode,
            )
            return ds.as_arrays()

        return with_retry(
            load, attempts=3, base_delay=0.2,
            retry_on=(OSError, ValueError, KeyError, RuntimeError),
            deadline_s=self.admission_deadline_s,
            # per-attempt cap too: a read that HANGS (dead mount) never
            # errors, so the deadline alone would never fire — the abandoned
            # attempt runs on a daemon thread and the serve loop moves on
            timeout_s=self.admission_deadline_s,
            describe=f"site admission {data_dir}",
        )()

    def _admit(self, site: str, data_dir: str, overrides: dict | None = None):
        """Load + shape-gate one joining site's data; returns SiteArrays or
        None (rejected, with the reason logged + a telemetry event)."""
        from ..trainer.logs import log_warning

        try:
            arrays = self._load_site(data_dir, overrides)
        except (OSError, ValueError, KeyError, RuntimeError, TimeoutError) as e:
            log_warning(
                f"[serve] join rejected for {site!r}: admission failed "
                f"within deadline_s={self.admission_deadline_s} ({e})"
            )
            self._event("join-rejected", site=site, reason=str(e))
            return None
        if not len(arrays):
            log_warning(f"[serve] join rejected for {site!r}: empty dataset")
            self._event("join-rejected", site=site, reason="empty dataset")
            return None
        feat = arrays.inputs.shape[1:]
        if self._feat is None:
            self._feat = feat
        elif feat != self._feat:
            log_warning(
                f"[serve] join rejected for {site!r}: feature shape {feat} "
                f"!= the service's {self._feat}"
            )
            self._event("join-rejected", site=site, reason="shape mismatch")
            return None
        if self._rows is None:
            # pin the inventory grid at the first site's size (headroom is
            # the operator's call via inventory_rows) — every traced shape
            # is fixed from here on
            self._rows = max(len(arrays), self.cfg.batch_size)
        if len(arrays) > self._rows:
            log_warning(
                f"[serve] site {site!r} has {len(arrays)} samples; the "
                f"service's inventory grid is pinned at {self._rows} rows — "
                f"truncating (start the daemon with a larger inventory_rows "
                "for headroom)"
            )
            arrays = arrays.take(np.arange(self._rows))
        if len(arrays) < self.cfg.batch_size:
            log_warning(
                f"[serve] site {site!r} has {len(arrays)} samples < "
                f"batch_size={self.cfg.batch_size}: with drop_last batching "
                "it will yield no batches and contribute nothing"
            )
        return arrays

    # -- membership transitions -------------------------------------------

    def apply_event(self, ev: dict) -> bool:
        """Apply one spool event; returns True when membership changed.
        Invalid events are logged and skipped — a malformed spool file must
        not take the service down."""
        from ..robustness.membership import MembershipError
        from ..trainer.logs import log_warning

        kind = ev.get("event")
        if kind == "shutdown":
            self._stop = True
            self._log("[serve] shutdown event received")
            return False
        trace_id = str(ev.get("trace_id") or "") or None
        try:
            if kind == "join":
                site = str(ev["site"])
                data_dir = str(ev.get("data_dir", ""))
                overrides = ev.get("config") or {}
                arrays = self._admit(site, data_dir, overrides)
                if arrays is None:
                    self.bus.counter("serve_spool_events_total",
                                     result="rejected")
                    return False
                self.table, slot, gen = self.table.join(site)
                sl = self.table.slice_of(slot, self.num_slices)
                self._data[site] = arrays
                self._dirs[site] = data_dir
                self._overrides[site] = overrides
                if trace_id:
                    self._traces[site] = trace_id
                self._ensure_state()
                self._reset_slot(slot, site=site, generation=gen)
                self._log(
                    f"[serve] join {site!r} → slot {slot} (slice {sl}, "
                    f"generation {gen})"
                )
                self._event("membership-join", site=site, slot=slot,
                            slice=sl, generation=gen, trace=trace_id)
                self.flight.note("membership-join", site=site, slot=slot,
                                 slice=sl, trace=trace_id)
                self.bus.counter("serve_spool_events_total", result="applied")
                self.bus.gauge("serve_member_generation", gen, site=site)
                self._publish_slice_gauges()
                return True
            if kind == "leave":
                site = str(ev["site"])
                self.table, slot = self.table.leave(site)
                sl = self.table.slice_of(slot, self.num_slices)
                self._data.pop(site, None)
                self._dirs.pop(site, None)
                self._overrides.pop(site, None)
                self._traces.pop(site, None)
                self._log(
                    f"[serve] leave {site!r} (slot {slot}, slice {sl} freed)"
                )
                self._event("membership-leave", site=site, slot=slot,
                            slice=sl, trace=trace_id)
                self.flight.note("membership-leave", site=site, slot=slot,
                                 slice=sl)
                self.bus.counter("serve_spool_events_total", result="applied")
                self.bus.clear_gauge("serve_member_generation", site=site)
                self._publish_slice_gauges()
                return True
        except (MembershipError, KeyError) as e:
            log_warning(f"[serve] bad membership event {ev!r}: {e}")
            self._event("membership-error", reason=str(e))
            self.bus.counter("serve_spool_events_total", result="rejected")
            return False
        log_warning(f"[serve] unknown spool event {ev!r} — ignored")
        self.bus.counter("serve_spool_events_total", result="rejected")
        return False

    def _publish_slice_gauges(self) -> None:
        """Per-slice membership gauges (r18): one ``serve_slice_members``
        gauge per slice, so the /statusz surface shows WHERE on the sliced
        topology the federation sits — a slice draining to 0 is the
        operator's cue before the quorum trips."""
        for sl, n in enumerate(
            self.table.slice_occupancy(self.num_slices)
        ):
            self.bus.gauge("serve_slice_members", n, slice=str(sl))

    def _reset_slot(self, slot: int, site: str = "", generation: int = 0):
        """Fresh state rows for a newly-assigned slot (generation semantics:
        a rejoining site can never resurrect its previous incarnation's
        engine/health/buffer state). Emits quarantine-lift when the slot's
        previous occupant left it quarantined."""
        from ..robustness.membership import reset_slot_state

        if self.state is None:
            return
        if self.state.health is not None:
            quarantined = int(
                np.asarray(self.state.health["quarantined"])[slot]
            )
            if quarantined:
                self._log(
                    f"[serve] slot {slot} was quarantined — lifted for "
                    f"{site!r} generation {generation}"
                )
                self._event("quarantine-lift", site=site, slot=slot)
        self.state = self.trainer._place_state(
            reset_slot_state(self.state, slot, engine=self.trainer.engine)
        )

    def _ensure_state(self):
        if self.state is not None or self._feat is None:
            return
        import jax.numpy as jnp

        self.state = self.trainer.init_state(
            jnp.ones((self.cfg.batch_size,) + self._feat, jnp.float32),
            num_sites=self.capacity,
        )
        if getattr(self, "_pending_ckpt_load", False):
            # empty-membership resume (see _resume): the first join shaped
            # the template — restore the checkpointed params/state now
            from ..trainer.checkpoint import load_checkpoint

            self._pending_ckpt_load = False
            self.state = self.trainer._place_state(
                load_checkpoint(self.ckpt_path, self.state)
            )
        from ..checks.sanitize import jit_cache_size

        self._compiles0 = jit_cache_size(self.trainer.epoch_fn) or 0

    def _on_membership_change(self):
        """Post-transition housekeeping: rebalance packed slot assignment,
        refresh the occupancy mask, and checkpoint the membership epoch."""
        from ..robustness.membership import move_slot_state

        from ..parallel.mesh import slice_count

        # packing granules: one per (slice, site)-axis member — under a
        # sliced mesh rebalancing evens occupancy across slices too (the
        # per-device [K] blocks tile slice-major, parallel/mesh.py)
        num_blocks = (
            dict(self.mesh.shape)[SITE_AXIS] * slice_count(self.mesh)
            if self.mesh is not None else 1
        )
        self.table, moves = self.table.rebalance(num_blocks)
        for site, src, dst in moves:
            self._log(
                f"[serve] rebalance: {site!r} slot {src} → {dst} (packed "
                "block occupancy)"
            )
            if self.state is not None:
                self.state = self.trainer._place_state(move_slot_state(
                    self.state, src, dst, engine=self.trainer.engine
                ))
            self._event("membership-rebalance", site=site, src=src, dst=dst)
        self.trainer.membership_mask = self.table.occupancy()
        self._event("membership-epoch", epoch=self.table.epoch,
                    occupied=self.table.occupied)
        self.checkpoint()

    # -- the ingest spool --------------------------------------------------

    def ingest(self) -> bool:
        """Drain applicable spool events (sorted-filename order); an event
        with ``after_epoch`` > epochs trained stays queued. Returns True
        when membership changed."""
        from ..trainer.logs import log_warning

        changed = False
        # while HELD, release scheduled events (epochs_run is frozen; see
        # below) — but only until the first applied transition: that may be
        # the join that lifts the hold, and later-scheduled events (e.g. a
        # shutdown) must then wait for their trained-epoch mark again
        release = self._idle
        for name in sorted(os.listdir(self.spool_dir)):
            if not name.endswith(".json"):
                continue
            path = os.path.join(self.spool_dir, name)
            try:
                with open(path) as fh:
                    ev = json.load(fh)
            except (OSError, json.JSONDecodeError) as e:
                log_warning(f"[serve] unreadable spool file {path}: {e}")
                try:
                    os.replace(path, path + ".rejected")
                except OSError:
                    pass
                continue
            if not isinstance(ev, dict):
                log_warning(f"[serve] spool file {path} is not an object")
                os.remove(path)
                continue
            try:
                after = int(ev.get("after_epoch", 0) or 0)
            except (TypeError, ValueError):
                log_warning(
                    f"[serve] spool file {path}: bad after_epoch "
                    f"{ev.get('after_epoch')!r}"
                )
                try:
                    os.replace(path, path + ".rejected")
                except OSError:
                    pass
                continue
            # scheduled events wait for N TRAINED epochs — except while the
            # service is HELD (below quorum / nothing trainable): epochs_run
            # is frozen then, and the scheduled join/shutdown may be exactly
            # what lifts or ends the hold
            if after > self.epochs_run and not release:
                continue  # scheduled for later — leave it queued
            os.remove(path)
            applied = self.apply_event(ev)
            changed |= applied
            if applied:
                release = False  # the hold may have lifted — back to strict
            if self._stop:
                break
        self.bus.gauge("serve_spool_ingest_lag_s", self._spool_lag())
        return changed

    def _spool_lag(self) -> float:
        """Age in seconds of the OLDEST spool file still pending after a
        drain (scheduled events waiting their epoch mark, or backlog the
        loop hasn't reached) — the bus gauge an operator watches to see
        ingest falling behind. 0.0 with an empty spool."""
        oldest = None
        try:
            for name in os.listdir(self.spool_dir):
                if not name.endswith(".json"):
                    continue
                try:
                    mtime = os.path.getmtime(
                        os.path.join(self.spool_dir, name)
                    )
                except OSError:
                    continue  # consumed/renamed mid-scan
                if oldest is None or mtime < oldest:
                    oldest = mtime
        except OSError:
            return 0.0
        if oldest is None:
            return 0.0
        return round(max(time.time() - oldest, 0.0), 3)

    # -- scheduler surface (runner/scheduler.py, r22) ----------------------

    def set_slice_grant(self, grant) -> None:
        """Install the fleet scheduler's ``[num_slices]`` slice-grant mask
        (1.0 = this service may aggregate on that slice this round-window).
        The mask folds into the r19 slice-liveness window inside the SAME
        compiled epoch program — growing, shrinking or zeroing the grant is
        a traced-input flip plus renormalized aggregation, never a retrace.
        ``None`` removes scheduler control (full pod, r19 behavior) — but
        flipping between None and a mask CHANGES the traced program, so a
        scheduled tenant keeps a mask for its whole life."""
        self.trainer.slice_grant = (
            None if grant is None else np.asarray(grant, np.float32)
        )

    def trainable(self) -> bool:
        """Would :meth:`train_epoch` train right now (vs HOLD)? The
        scheduler's runnable predicate: granting slices to a tenant that
        would only hold wastes the grant — those slices backfill instead."""
        if self.table.occupied < self.quorum or self.state is None:
            return False
        return any(
            len(self._data[s]) >= self.cfg.batch_size
            for s in self.table.members()
        )

    def reload_checkpoint(self) -> bool:
        """Restore params/engine state from the rotating checkpoint into
        the EXISTING state template (same shapes, same sharding — the
        compiled program is untouched). The scheduler's resume half of
        checkpoint-then-yield: a preempted tenant continues bit-exact from
        what :meth:`checkpoint` saved, through the real CRC-framed msgpack
        path. Returns False when there is nothing to restore."""
        from ..trainer.checkpoint import load_checkpoint

        if self.state is None or not (
            os.path.exists(self.ckpt_path)
            or os.path.exists(self.ckpt_path + ".prev")
        ):
            return False
        self.state = self.trainer._place_state(
            load_checkpoint(self.ckpt_path, self.state)
        )
        return True

    # -- training ----------------------------------------------------------

    def _slot_sites(self) -> list:
        """The padded per-slot site list the epoch trains on: occupants'
        arrays at their slots, the shared empty placeholder (zero samples —
        the plan masks them, the occupancy mask zeroes their liveness)
        elsewhere."""
        if self._empty_site is None:
            self._empty_site = SiteArrays(
                np.zeros((0,) + self._feat, np.float32),
                np.zeros((0,), np.int32), np.zeros((0,), np.int32),
            )
        return [
            self._data[s] if s is not None else self._empty_site
            for s in self.table.slots
        ]

    def train_epoch(self):
        """One training epoch over the current membership; returns the epoch
        loss, or None when the service HELD: below the quorum floor, no
        state yet, or no member large enough to yield a batch. Each hold
        counts one epoch's worth of rounds into ``held_rounds`` (the serve
        loop then idles until membership changes, so the figure counts
        declined epochs, not poll-loop iterations)."""
        rounds = max(
            (self._steps or 1) // max(self.cfg.local_iterations, 1), 1
        )
        if self.table.occupied < self.quorum or self.state is None:
            self.held_rounds += rounds
            self._event("round-hold", occupied=self.table.occupied,
                        quorum=self.quorum)
            self._note_hold(rounds)
            return None
        if not any(
            len(self._data[s]) >= self.cfg.batch_size
            for s in self.table.members()
        ):
            # every member is smaller than the batch: drop_last batching
            # yields zero batches and the plan builder would (rightly)
            # refuse — hold rather than crash the service
            self.held_rounds += rounds
            self._event("round-hold", occupied=self.table.occupied,
                        quorum=self.quorum, reason="no trainable batch")
            self._note_hold(rounds)
            return None
        if self._steps is None:
            # pin the step grid on first contact with data (membership can
            # only change it downward-wrapping/truncating from here)
            from ..data.batching import epoch_steps

            self._steps = epoch_steps(
                [s for s in self._slot_sites() if len(s)],
                self.cfg.batch_size,
            )
            self.trainer.fixed_steps = self._steps
        self.trainer.fixed_steps = self._steps
        self.trainer.fixed_inventory_rows = self._rows
        self.epochs_run += 1
        t0 = time.perf_counter()  # the tracer's clock (duration contract)
        with self.trainer.tracer.span("epoch", epoch=self.epochs_run):
            self.state, losses = self.trainer.run_epoch(
                self.state, self._slot_sites(), self.epochs_run,
                batch_size=self.cfg.batch_size,
            )
        lived = losses[np.isfinite(losses)]
        loss = float(lived.mean()) if lived.size else float("nan")
        if self._sink is not None:
            self.trainer._fit_tel = self._sink
            self.trainer._epoch_row(0, self.epochs_run, loss, t0, self.state)
        # live metrics + flight ring: values already on the host
        self.bus.gauge("serve_epoch", self.epochs_run)
        self.bus.gauge("serve_train_loss", loss)
        self.bus.gauge("serve_members", self.table.occupied)
        self.bus.counter("serve_epochs_total")
        self.bus.observe(
            "serve_epoch_ms", (time.perf_counter() - t0) * 1e3
        )
        self.flight.note("serve-epoch", epoch=self.epochs_run, loss=loss,
                         occupied=self.table.occupied)
        self._log(
            f"[serve] epoch {self.epochs_run}: train_loss={loss:.4f} "
            f"({self.table.occupied}/{self.capacity} slots)"
        )
        # ε-budget exhaustion is a CLEAN stop for THIS daemon only: the
        # ledger (privacy/accounting.py, stepped inside run_epoch) crossing
        # the budget checkpoints the model and latches the service stop —
        # under the fleet scheduler each tenant owns its ledger, so one
        # study exhausting its budget cannot perturb another (isolation
        # proven bit-exact in tests/test_scheduler.py).
        budget = float(getattr(self.cfg, "dp_epsilon_budget", 0.0) or 0.0)
        eps = self.trainer._dp_epsilon
        if budget > 0 and eps is not None and eps >= budget:
            self._event("dp-budget", epsilon=eps, budget=budget)
            self.bus.counter("serve_dp_budget_stops_total")
            self._log(
                f"[serve] dp ε-budget exhausted: ε={eps:.3f} ≥ {budget} "
                f"— checkpointing and stopping"
            )
            self.checkpoint()
            self._stop = True
        return loss

    def _note_hold(self, rounds: int) -> None:
        self.bus.counter("serve_held_rounds_total", rounds)
        self.bus.gauge("serve_members", self.table.occupied)
        self.flight.note("round-hold", occupied=self.table.occupied,
                         quorum=self.quorum)

    def checkpoint(self):
        """Rotating checkpoint with the membership table (and member data
        dirs) embedded in the atomically-paired meta."""
        from ..trainer.checkpoint import save_checkpoint

        if self.state is None or not self.trainer._coordinator():
            return
        with self.trainer.tracer.span("checkpoint"):
            save_checkpoint(
                self.ckpt_path, self.state,
                meta={
                    "epoch": self.epochs_run,
                    "held_rounds": self.held_rounds,
                    "steps": self._steps,
                    "rows": self._rows,
                    "membership": self.table.to_json(),
                    "data_dirs": dict(self._dirs),
                    "site_overrides": dict(self._overrides),
                    # trace propagation: which spool joins' data trained
                    # the published model — the serving engine surfaces
                    # these from the checkpoint it loads
                    "traces": dict(self._traces),
                },
                rotate=True,
            )
        self._event("checkpoint-publish", epoch=self.epochs_run,
                    traces=dict(self._traces))
        self.flight.note("checkpoint-publish", epoch=self.epochs_run)
        self.bus.counter("serve_checkpoints_total")
        self._announce_publish()

    def _announce_publish(self) -> None:
        """Atomically drop ``publish.json`` beside the rotating checkpoint —
        the train-to-serve CD announcement (serving/publish.py
        CheckpointWatcher): the content digest lets a watching fleet skip
        loading the msgpack at all when the weights didn't change (held
        rounds re-checkpoint the same params)."""
        from ..trainer.checkpoint import params_digest

        note = {
            "path": self.ckpt_path,
            "epoch": self.epochs_run,
            "digest": params_digest(
                self.state.params, getattr(self.state, "batch_stats", None)
            ),
            "membership_epoch": self.table.epoch,
        }
        tmp = self.ckpt_path + ".publish.tmp"
        with open(tmp, "w") as fh:
            json.dump(note, fh)
        os.replace(tmp, os.path.join(
            os.path.dirname(self.ckpt_path), "publish.json"
        ))

    def _resume(self) -> bool:
        """Restore the service from its last checkpoint: membership table +
        member data (re-admitted from the recorded dirs) + train state —
        surviving sites' trajectories continue bit-exact. Returns False when
        there is nothing to resume from (the caller then falls back to the
        fresh-start path, pre-joining the tree's sites)."""
        from ..robustness.membership import MembershipTable
        from ..trainer.checkpoint import load_checkpoint, load_meta

        if not (
            os.path.exists(self.ckpt_path)
            or os.path.exists(self.ckpt_path + ".prev")
        ):
            self._log("[serve] resume requested but no checkpoint — "
                      "starting fresh")
            return False
        meta = load_meta(self.ckpt_path)
        self.table = MembershipTable.from_json(meta["membership"])
        if self.table.capacity != self.capacity:
            raise ValueError(
                f"checkpointed capacity {self.table.capacity} != daemon "
                f"capacity {self.capacity} — the virtual-site axis is "
                "pinned for the life of the service"
            )
        self.epochs_run = int(meta.get("epoch", 0))
        self.held_rounds = int(meta.get("held_rounds", 0))
        self._steps = meta.get("steps") or self._steps
        self._rows = meta.get("rows") or self._rows
        self._dirs = dict(meta.get("data_dirs", {}))
        self._overrides = dict(meta.get("site_overrides", {}))
        self._traces = dict(meta.get("traces", {}))
        for site, slot in sorted(
            self.table.members().items(), key=lambda kv: kv[1]
        ):
            arrays = self._admit(
                site, self._dirs.get(site, ""), self._overrides.get(site)
            )
            if arrays is None:
                raise RuntimeError(
                    f"resume: cannot re-admit member {site!r} from "
                    f"{self._dirs.get(site)!r}"
                )
            self._data[site] = arrays
        self._ensure_state()
        if self.state is not None:
            self.state = self.trainer._place_state(
                load_checkpoint(self.ckpt_path, self.state)
            )
        else:
            # a service checkpointed with ZERO members (everyone left) has
            # no data to shape a state template from — resume idle; the
            # first join builds the template and THEN restores the
            # checkpointed params (deferred load below), so the model the
            # departed federation trained is not lost
            self._pending_ckpt_load = True
            self._log("[serve] resumed with an empty membership table — "
                      "idling until a site joins")
        self.trainer.membership_mask = self.table.occupancy()
        self.trainer.fixed_steps = self._steps
        self.trainer.fixed_inventory_rows = self._rows
        self._log(
            f"[serve] resumed at epoch {self.epochs_run} with "
            f"{self.table.occupied}/{self.capacity} slots (membership "
            f"epoch {self.table.epoch})"
        )
        return True

    # -- the service loop --------------------------------------------------

    def serve(self, max_epochs: int | None = None,
              max_wall_s: float | None = None) -> dict:
        """The daemon loop: drain the spool, hold below quorum, train,
        checkpoint — until a shutdown event, SIGTERM/SIGINT (clean
        checkpointed exit), ``max_epochs`` trained epochs or ``max_wall_s``
        wall-clock. Returns a summary dict (and writes the telemetry
        summary row when telemetry is on)."""
        from ..robustness.preemption import PreemptionGuard

        t0 = time.monotonic()
        trained_here = 0
        # held-state latch (self._idle): after a hold (below quorum / no
        # state / nothing trainable) the loop idles on the spool instead of
        # re-holding every poll iteration — held_rounds counts declined
        # EPOCHS, only a membership change lifts the hold, and ingest()
        # releases after_epoch-scheduled events while held (epochs_run is
        # frozen then, and a scheduled join/shutdown may be the lift)
        self._idle = False
        with PreemptionGuard() as guard:
            while not self._stop:
                changed = self.ingest()
                if changed:
                    self._on_membership_change()
                    self._idle = False
                if self._stop:
                    break
                loss = None
                if not self._idle:
                    loss = self.train_epoch()
                    if loss is None:
                        self._idle = True
                    else:
                        trained_here += 1
                        self.checkpoint()
                if guard.requested is not None:
                    self._preempted = True
                    self._log(
                        f"[serve] signal {guard.requested} — checkpointed, "
                        "shutting down"
                    )
                    self.checkpoint()
                    # the guard owns the signal handlers here, so the
                    # flight recorder dumps cooperatively: final spans +
                    # bus snapshot land in flight_<pid>.json before exit
                    self.flight.note("signal", signum=guard.requested)
                    self.flight.dump(f"signal:{guard.requested}")
                    break
                if max_epochs is not None and trained_here >= max_epochs:
                    break
                if max_wall_s is not None and time.monotonic() - t0 >= max_wall_s:
                    break
                if loss is None and not changed:
                    # idle (held below quorum, empty spool): poll gently
                    time.sleep(self.poll_s)
        return self.close()

    # -- live observability (exporter plumbing) ----------------------------

    def health_probes(self) -> dict:
        """Per-subsystem readiness for ``/healthz``: the service is ready
        when it has a state template, meets quorum, and can reach its
        spool."""
        return {
            "state": lambda: self.state is not None,
            "quorum": lambda: self.table.occupied >= self.quorum,
            "spool": lambda: os.path.isdir(self.spool_dir),
        }

    def status(self) -> dict:
        """The live ``/statusz`` payload: what round the service is on,
        who is a member (with generations and propagated trace ids), and
        the hold/ingest state — everything an operator previously had to
        infer from logs after the fact."""
        return {
            "mode": "daemon",
            "task_id": self.cfg.task_id,
            "epoch": self.epochs_run,
            "held_rounds": self.held_rounds,
            "capacity": self.capacity,
            "quorum": self.quorum,
            "occupied": self.table.occupied,
            "holding": self._idle,
            "members": {
                site: {
                    "slot": slot,
                    "slice": self.table.slice_of(slot, self.num_slices),
                    "generation": self.table.generation_of(site),
                    "samples": len(self._data.get(site, ())),
                    "trace_id": self._traces.get(site),
                }
                for site, slot in sorted(self.table.members().items())
            },
            "num_slices": self.num_slices,
            # r19 slice elasticity: the slice-quorum floor (trainer/steps.py
            # holds rounds below it) — surfaced so an operator reading
            # /statusz sees WHY rounds are holding under slice faults
            "min_slices": self.cfg.min_slices,
            # r22 fleet scheduler: the current slice-grant mask (None = the
            # service owns the whole pod) — /statusz shows WHICH slices the
            # scheduler has this tenant on right now
            "slice_grant": (
                None if self.trainer.slice_grant is None
                else [float(g) for g in np.asarray(self.trainer.slice_grant)]
            ),
            "slice_occupancy": self.table.slice_occupancy(self.num_slices),
            "membership_epoch": self.table.epoch,
            "steps": self._steps,
            "inventory_rows": self._rows,
            "spool_dir": self.spool_dir,
            "spool_lag_s": self._spool_lag(),
            "preempted": self._preempted,
        }

    def close(self) -> dict:
        """Final checkpoint + telemetry summary; returns the service
        summary."""
        from ..checks.sanitize import jit_cache_size
        from ..robustness.membership import membership_rollup

        self.checkpoint()
        rollup = membership_rollup(
            self.table, self.state, held_rounds=self.held_rounds
        )
        summary = {
            "epochs_run": self.epochs_run,
            "held_rounds": self.held_rounds,
            "membership": rollup,
            "table": self.table.to_json(),
            "preempted": self._preempted,
        }
        if self._sink is not None:
            compiles = (
                (jit_cache_size(self.trainer.epoch_fn) or 0)
                - (self._compiles0 or 0)
            )
            self._sink.append({
                "kind": "summary", "fold": 0,
                "epochs_run": self.epochs_run,
                "epoch_compiles": compiles,
                "best_val_epoch": 0,
                "membership": rollup,
            })
            self._sink.close()
            self._sink = None
        return summary
