"""Train/val/test splitting: ratio-based and k-fold.

Capability parity with the reference library's split machinery as exercised by
call sites: ``split_ratio=[0.8,0.1,0.1]`` / ``[0.7,0.15,0.15]``
(``local.py:34``, ``compspec.json:205-215``), ``num_folds`` k-fold CV
(``compspec.json:217-224``, 10-fold study in ``NB.ipynb``), and predefined
``split_files`` (``compspec.json:249,263``).
"""

from __future__ import annotations

import json
import os

import numpy as np

SPLIT_KEYS = ("train", "validation", "test")


def split_by_ratio(n: int, ratio, seed: int = 0) -> dict:
    """Shuffle ``n`` samples and split by ``ratio`` (train, val, test).

    Sizes: train/val floor to ``int(n*r)``; test takes the remainder so every
    sample lands somewhere.
    """
    ratio = list(ratio)
    test_share = len(ratio) > 2 and ratio[2] > 0
    rng = np.random.default_rng(seed)
    perm = rng.permutation(n)
    n_train = int(n * ratio[0])
    # with no test share, flooring remainders go to validation, not test
    n_val = (n - n_train) if not test_share else int(n * ratio[1])
    return {
        "train": np.sort(perm[:n_train]),
        "validation": np.sort(perm[n_train : n_train + n_val]),
        "test": np.sort(perm[n_train + n_val :]),
    }


def kfold_splits(n: int, k: int, seed: int = 0) -> list[dict]:
    """K-fold CV (k ≥ 2): fold ``i`` is the test set, fold ``(i+1) % k`` is
    validation, the rest train. With k == 2 there is no fold left for
    validation, so validation is empty and the other fold is train. (Design
    choice documented; the reference library's exact val-fold rule is internal
    to coinstac-dinunet.)"""
    if k < 2:
        raise ValueError(f"num_folds must be >= 2, got {k}")
    rng = np.random.default_rng(seed)
    perm = rng.permutation(n)
    folds = np.array_split(perm, k)
    out = []
    for i in range(k):
        test = folds[i]
        if k == 2:
            val = np.array([], int)
            train = folds[(i + 1) % k]
        else:
            val_j = (i + 1) % k
            val = folds[val_j]
            train = np.concatenate([folds[j] for j in range(k) if j not in (i, val_j)])
        out.append(
            {"train": np.sort(train), "validation": np.sort(val), "test": np.sort(test)}
        )
    return out


def load_split_file(path: str) -> dict:
    """Load a predefined split JSON: {"train": [...], "validation": [...],
    "test": [...]} — entries may be inventory positions or file names."""
    with open(path) as fh:
        spec = json.load(fh)
    return {k: list(spec.get(k, [])) for k in SPLIT_KEYS}


def resolve_splits(
    n: int,
    split_ratio=None,
    num_folds: int | None = None,
    split_files=(),
    base_dir: str = "",
    seed: int = 0,
) -> list[dict]:
    """One-stop resolution mirroring config precedence: ``split_files`` (if
    given) > ``num_folds`` k-fold > ``split_ratio``. Returns a list of folds
    (length 1 unless k-fold/multiple files)."""
    if split_files:
        return [load_split_file(os.path.join(base_dir, f)) for f in split_files]
    if num_folds:
        return kfold_splits(n, int(num_folds), seed)
    return [split_by_ratio(n, split_ratio or (0.8, 0.1, 0.1), seed)]
