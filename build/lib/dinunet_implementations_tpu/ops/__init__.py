from .lstm_pallas import lstm_forward_fused, lstm_recurrence_fused
