"""jaxlint rules R001-R006 — the codebase-specific SPMD invariants.

Every rule carries the invariant it protects and the incident that motivated
it (see docs/ARCHITECTURE.md "Static analysis & sanitizer" for the operator
view). Rules are pure AST passes over :class:`~.core.SourceFile`; scoping is
by path relative to the scan root, so the same rules run unchanged over the
real package and over test fixture trees.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Callable, Iterable, Iterator

from .core import Finding, SourceFile

# -- scoping tables ---------------------------------------------------------

#: R001 — modules whose print() IS the product (CLI/report/demo surfaces).
PRINT_ALLOWED_FILES = {
    "runner/cli.py",  # the operational CLI: JSON result lines on stdout
    "data/demo.py",  # demo-tree generator CLI
    "analysis.py",  # notebook-parity report CLI (prints summary_markdown)
    "checks/__main__.py",  # this analyzer's own CLI
    "telemetry/report.py",  # telemetry run-summary CLI (tables on stdout)
    "telemetry/assemble.py",  # pod trace assembly CLI (r23 source summary)
    "telemetry/postmortem.py",  # incident timeline CLI (r23)
    "serving/__main__.py",  # serving CLI: summary/latency JSON on stdout
    # multi-host worker CLI (r18): the UNSUPPORTED capability-probe line on
    # stdout IS the product — the launcher greps it next to rc 66
    "runner/dcn_worker.py",
}

#: R002 — packages where a swallowed ``except Exception`` can eat the
#: ``Preempted``/fault-tolerance contract's neighbors (broad handlers around
#: round/checkpoint/runner code hid real faults twice before PR 2).
#: parallel/ and native/ joined the scope when their grandfathered broad
#: handlers were narrowed to concrete types (this PR).
SWALLOW_SCOPED_DIRS = ("robustness/", "trainer/", "runner/", "parallel/", "native/")

#: R003 — collective ops and the positional index of their axis-name operand.
#: This table is the SHARED definition of "what counts as a collective": the
#: semantic tier (semantic.py) audits the traced-primitive form of exactly
#: this set (see semantic.API_TO_PRIM), so the two tiers cannot drift.
COLLECTIVE_AXIS_ARG = {
    "psum": 1,
    "pmean": 1,
    "pmax": 1,
    "pmin": 1,
    "psum_scatter": 1,
    "all_gather": 1,
    "all_to_all": 1,
    "ppermute": 1,
    "pbroadcast": 1,
    "axis_index": 0,
    "axis_size": 0,
}

#: R003 — keyword spellings of an axis-name argument, on ANY call: the lax
#: collectives' ``axis_name=``, shard_map/vmap-style ``axis_names=`` /
#: ``spmd_axis_name=``.
AXIS_NAME_KWARGS = ("axis_name", "axis_names", "spmd_axis_name")

#: R005 — modules whose function bodies execute under jit tracing by design
#: (reached from the compiled epoch/eval step): every engine/model/kernel,
#: the collectives/sequence helpers, and the step builders themselves.
TRACED_MODULE_DIRS = ("engines/", "models/", "ops/")
TRACED_MODULE_FILES = {
    "trainer/steps.py",
    "parallel/collectives.py",
    "parallel/sequence.py",
}

#: R005 — host-only escapes: these force a traced value concrete and either
#: crash under jit or silently freeze a runtime value into the compiled
#: program as a constant.
ESCAPE_NAME_CALLS = {"float", "int", "bool"}
ESCAPE_NP_ATTRS = {"asarray", "array"}
ESCAPE_METHOD_CALLS = {"item", "tolist"}
NUMPY_MODULE_NAMES = {"np", "numpy", "onp"}

#: R004 — the one module allowed to construct/mutate TrainConfig state.
CONFIG_MODULE = "core/config.py"

#: R006 — the two files whose schemas must agree.
TRAIN_STATE_FILE = "trainer/steps.py"
CHECKPOINT_FILE = "trainer/checkpoint.py"
#: payload keys that are serializer bookkeeping, not TrainState fields
CHECKPOINT_EXTRA_KEYS = {"meta_json"}

#: R007 — telemetry API calls whose NAME argument (positional 0 or ``name=``)
#: must be trace-stable (telemetry/tracer.py span/event/counter + the
#: MetricsBus publishers gauge/observe — bus series names feed /metrics and
#: must be as greppable as span names; ``counter`` already covers the bus's
#: counter method).
TELEMETRY_NAME_CALLS = {"span", "event", "counter", "gauge", "observe"}


# -- registry ---------------------------------------------------------------


@dataclasses.dataclass
class Rule:
    id: str
    title: str
    fixit: str
    fn: Callable
    project: bool = False

    def _wrap(self, sf_or_path, hits: Iterable) -> Iterator[Finding]:
        for hit in hits:
            if isinstance(hit, Finding):
                yield hit
                continue
            line, col, message = hit
            sf = sf_or_path
            yield Finding(
                rule=self.id, path=sf.relpath, line=line, col=col,
                message=message, snippet=sf.snippet(line), fixit=self.fixit,
            )

    def check(self, sf: SourceFile) -> Iterator[Finding]:
        return self._wrap(sf, self.fn(sf))

    def check_project(self, files: dict[str, SourceFile]) -> Iterator[Finding]:
        return iter(self.fn(files))


RULES: dict[str, Rule] = {}
PROJECT_RULES: dict[str, Rule] = {}


def rule(id: str, title: str, fixit: str, project: bool = False):
    def deco(fn):
        r = Rule(id=id, title=title, fixit=fixit, fn=fn, project=project)
        (PROJECT_RULES if project else RULES)[id] = r
        return fn

    return deco


# -- AST helpers ------------------------------------------------------------


def _callee_name(node: ast.Call) -> str | None:
    """Trailing name of the called thing: ``psum`` for ``jax.lax.psum``."""
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def _is_numpy_attr(f: ast.expr) -> bool:
    return (
        isinstance(f, ast.Attribute)
        and f.attr in ESCAPE_NP_ATTRS
        and isinstance(f.value, ast.Name)
        and f.value.id in NUMPY_MODULE_NAMES
    )


def _names_exception(node: ast.expr | None, name: str) -> bool:
    """Does an ``except`` type expression mention ``name`` (directly or in a
    tuple)?"""
    if node is None:
        return False
    if isinstance(node, ast.Name):
        return node.id == name
    if isinstance(node, ast.Attribute):
        return node.attr == name
    if isinstance(node, ast.Tuple):
        return any(_names_exception(e, name) for e in node.elts)
    return False


_LOGGING_ATTRS = {
    "warn", "warning", "error", "exception", "critical", "info", "debug", "log",
    # the project's own level-gated logger (trainer/logs.py) — R001 routes
    # library output through these, so they count as surfacing for R002 too
    "log_info", "log_warning",
}


def _handler_surfaces(handler: ast.ExceptHandler) -> bool:
    """True when the handler body re-raises or logs — i.e. the failure is
    surfaced somewhere instead of silently swallowed."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in _LOGGING_ATTRS:
                return True
            if isinstance(f, ast.Name) and f.id in {"print"} | _LOGGING_ATTRS:
                return True
    return False


def _is_jit_decorator(dec: ast.expr) -> bool:
    """``@jit`` / ``@jax.jit`` / ``@jax.jit(...)`` / ``@partial(jax.jit, ...)``."""
    if isinstance(dec, ast.Call):
        f = dec.func
        if isinstance(f, (ast.Name, ast.Attribute)):
            name = f.id if isinstance(f, ast.Name) else f.attr
            if name == "jit":
                return True
            if name == "partial" and dec.args and _is_jit_decorator(dec.args[0]):
                return True
        return False
    if isinstance(dec, ast.Name):
        return dec.id == "jit"
    if isinstance(dec, ast.Attribute):
        return dec.attr == "jit"
    return False


def _in_traced_module(relpath: str) -> bool:
    return relpath in TRACED_MODULE_FILES or any(
        relpath.startswith(d) for d in TRACED_MODULE_DIRS
    )


def _is_cfg_expr(node: ast.expr) -> bool:
    """``cfg`` / ``self.cfg`` / ``<anything>.cfg`` — the shared TrainConfig
    object."""
    if isinstance(node, ast.Name):
        return node.id == "cfg"
    if isinstance(node, ast.Attribute):
        return node.attr == "cfg"
    return False


# -- R001 -------------------------------------------------------------------


@rule(
    "R001",
    "no print() in library code",
    "route output through trainer/logs.py (level-gated logger: log_info / "
    "log_warning), or allowlist the module if its stdout IS the product",
)
def r001_no_print(sf: SourceFile):
    """Hot-path ``print()`` bypasses log levels, multi-host coordinator
    gating, and every downstream consumer of the structured logs — PR 2's
    round loop printed per-epoch lines that could not be silenced or
    captured. Only CLI/demo/report surfaces may print."""
    if sf.relpath in PRINT_ALLOWED_FILES:
        return
    for node in ast.walk(sf.tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "print"
        ):
            yield (
                node.lineno, node.col_offset,
                "print() outside the CLI/demo allowlist",
            )


# -- R002 -------------------------------------------------------------------


@rule(
    "R002",
    "no bare/blanket exception handlers",
    "name the concrete exception types the code can actually raise (with a "
    "comment naming the failure mode); never catch BaseException — it "
    "swallows Preempted/KeyboardInterrupt (the robustness/preemption.py "
    "shutdown contract)",
)
def r002_exception_hygiene(sf: SourceFile):
    """``Preempted(BaseException)`` exists precisely so recovery code cannot
    eat a shutdown request; a bare ``except:`` or ``except BaseException``
    re-opens that hole anywhere, and inside robustness/trainer/runner even an
    ``except Exception`` that silently swallows hides real faults (the bug
    class PR 2 was built to kill)."""
    scoped = sf.relpath.startswith(SWALLOW_SCOPED_DIRS)
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            yield (
                node.lineno, node.col_offset,
                "bare 'except:' catches BaseException (incl. Preempted / "
                "KeyboardInterrupt)",
            )
        elif _names_exception(node.type, "BaseException"):
            yield (
                node.lineno, node.col_offset,
                "'except BaseException' swallows the Preempted shutdown "
                "contract",
            )
        elif (
            scoped
            and _names_exception(node.type, "Exception")
            and not _handler_surfaces(node)
        ):
            yield (
                node.lineno, node.col_offset,
                "'except Exception' here swallows failures without re-raise "
                "or logging (fault-tolerance scope: robustness/, trainer/, "
                "runner/)",
            )


# -- R003 -------------------------------------------------------------------


@rule(
    "R003",
    "collective axis names come from parallel/mesh.py constants",
    "use SITE_AXIS / MODEL_AXIS / FOLD_AXIS from parallel/mesh.py (or a "
    "variable bound to them) instead of an ad-hoc string literal",
)
def r003_axis_constants(sf: SourceFile):
    """Every collective across the ~10 modules using them must agree on the
    mesh/fold axis names; a duplicated string literal compiles fine until one
    call site drifts, and then the psum silently reduces over the wrong axis
    (the DrJAX axis-name-consistency invariant, arXiv:2403.07128)."""
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _callee_name(node)
        axis_args: list[ast.expr] = []
        if name in COLLECTIVE_AXIS_ARG:
            pos = COLLECTIVE_AXIS_ARG[name]
            if len(node.args) > pos:
                axis_args.append(node.args[pos])
        for kw in node.keywords:
            if kw.arg in AXIS_NAME_KWARGS:
                axis_args.append(kw.value)
        for arg in axis_args:
            consts: list[ast.Constant] = []
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                consts.append(arg)
            elif isinstance(arg, ast.Tuple):
                consts.extend(
                    e for e in arg.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                )
            for c in consts:
                yield (
                    c.lineno, c.col_offset,
                    f"axis name string literal {c.value!r} in collective/"
                    f"axis argument",
                )


# -- R004 -------------------------------------------------------------------


@rule(
    "R004",
    "TrainConfig is immutable outside core/config.py",
    "build a NEW config with cfg.replace(field=...) and thread it locally; "
    "the config object is shared across folds and callers",
)
def r004_no_cfg_mutation(sf: SourceFile):
    """PR 1's fold bug: the batch-size clamp wrote ``self.cfg.batch_size``,
    and because FedRunner hands ONE config object to every fold's trainer, a
    fold with small sites silently shrank the batch for all later folds.
    Mutation of ``cfg``/``self.cfg`` fields anywhere outside construction is
    that bug waiting to recur."""
    if sf.relpath == CONFIG_MODULE:
        return
    for node in ast.walk(sf.tree):
        targets: list[ast.expr] = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        elif isinstance(node, ast.Call):
            if (
                isinstance(node.func, ast.Name)
                and node.func.id == "setattr"
                and node.args
                and _is_cfg_expr(node.args[0])
            ):
                yield (
                    node.lineno, node.col_offset,
                    "setattr on a shared TrainConfig object",
                )
            continue
        for t in targets:
            if isinstance(t, ast.Attribute) and _is_cfg_expr(t.value):
                yield (
                    t.lineno, t.col_offset,
                    f"mutates shared TrainConfig field '.{t.attr}' outside "
                    f"{CONFIG_MODULE}",
                )


# -- R005 -------------------------------------------------------------------


@rule(
    "R005",
    "no tracer-escaping casts in jit-traced code",
    "keep the value traced (jnp ops) or move the cast to the host side of "
    "the jit boundary; a genuinely static shape/int needs an inline "
    "'# jaxlint: disable=R005' with a comment saying why it is static",
)
def r005_no_tracer_escapes(sf: SourceFile):
    """``float()``/``int()``/``np.asarray``/``.item()`` on a traced value
    either raises ``ConcretizationTypeError`` mid-refactor or — worse —
    silently bakes a runtime value into the compiled program as a constant,
    which then recompiles per distinct value (the one-compilation-per-program
    invariant the sanitizer's compile counter enforces at runtime)."""
    traced_module = _in_traced_module(sf.relpath)

    def scan(body: list[ast.stmt], traced: bool):
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn_traced = traced or traced_module or any(
                    _is_jit_decorator(d) for d in stmt.decorator_list
                )
                yield from scan(stmt.body, fn_traced)
                continue
            if isinstance(stmt, ast.ClassDef):
                yield from scan(stmt.body, traced)
                continue
            if not traced:
                # still need to find nested defs inside non-traced statements
                for node in ast.walk(stmt):
                    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        fn_traced = traced_module or any(
                            _is_jit_decorator(d) for d in node.decorator_list
                        )
                        if fn_traced:
                            yield from scan(node.body, True)
                continue
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                if isinstance(f, ast.Name) and f.id in ESCAPE_NAME_CALLS:
                    yield (
                        node.lineno, node.col_offset,
                        f"'{f.id}()' concretizes a traced value",
                    )
                elif _is_numpy_attr(f):
                    yield (
                        node.lineno, node.col_offset,
                        f"'np.{f.attr}' pulls a traced value to host numpy",
                    )
                elif (
                    isinstance(f, ast.Attribute)
                    and f.attr in ESCAPE_METHOD_CALLS
                    and not node.args
                ):
                    yield (
                        node.lineno, node.col_offset,
                        f"'.{f.attr}()' forces a device transfer",
                    )

    yield from scan(sf.tree.body, False)


# -- R007 -------------------------------------------------------------------


def _is_trace_stable_name(arg: ast.expr) -> bool:
    """A span/metric name the trace consumer can grep for: a string literal,
    or an UPPER_CASE module-level-constant reference (``SPAN_EPOCH``,
    ``tracer_names.FIT``)."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return True
    if isinstance(arg, ast.Name):
        return arg.id == arg.id.upper()
    if isinstance(arg, ast.Attribute):
        return arg.attr == arg.attr.upper()
    return False


@rule(
    "R007",
    "telemetry span/metric names are string literals or constants",
    "pass a string literal (or an UPPER_CASE module-level constant) as the "
    "span/event/counter name — f-strings and runtime-built names make traces "
    "ungreppable and unstable across runs; put variable parts in keyword "
    "attributes instead (tracer.span('epoch', epoch=e))",
)
def r007_telemetry_names(sf: SourceFile):
    """The telemetry artifacts are only as useful as their names are stable:
    a span named ``f"epoch-{i}"`` explodes one logical phase into N trace
    rows, breaks the report CLI's phase table, and defeats grepping a trace
    for a known phase. Names must be literals (or constants); the variable
    part belongs in span attributes."""
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        if _callee_name(node) not in TELEMETRY_NAME_CALLS:
            continue
        args = [a for a in node.args]
        for kw in node.keywords:
            if kw.arg == "name":
                args.insert(0, kw.value)
        if not args:
            continue
        if not _is_trace_stable_name(args[0]):
            yield (
                args[0].lineno, args[0].col_offset,
                "telemetry name is not a string literal or UPPER_CASE "
                "constant (trace-stability contract)",
            )


# -- R006 -------------------------------------------------------------------


def _train_state_fields(sf: SourceFile) -> list[str] | None:
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.ClassDef) and node.name == "TrainState":
            return [
                s.target.id
                for s in node.body
                if isinstance(s, ast.AnnAssign) and isinstance(s.target, ast.Name)
            ]
    return None


def _dict_str_keys(d: ast.Dict) -> list[str]:
    return [
        k.value for k in d.keys
        if isinstance(k, ast.Constant) and isinstance(k.value, str)
    ]


def _assigned_dict_keys(fn: ast.FunctionDef, var: str) -> list[str] | None:
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Assign)
            and any(
                isinstance(t, ast.Name) and t.id == var for t in node.targets
            )
            and isinstance(node.value, ast.Dict)
        ):
            return _dict_str_keys(node.value)
    return None


def _popped_keys(fn: ast.FunctionDef) -> set[str]:
    keys: set[str] = set()
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in ("pop", "get")
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            keys.add(node.args[0].value)
    return keys


@rule(
    "R006",
    "TrainState fields round-trip through the checkpoint serializer",
    "add the field to save_checkpoint's payload dict AND to "
    "load_checkpoint's template/pop set in trainer/checkpoint.py (or remove "
    "the stale payload key)",
    project=True,
)
def r006_checkpoint_schema(files: dict[str, SourceFile]):
    """A ``TrainState`` field the serializer does not carry silently resets
    on every resume (the ``health`` counters were one checkpoint-schema edit
    away from exactly that in PR 2); a payload key with no backing field is a
    stale schema that masks the next drift. Verified statically: field set ==
    save-payload key set == load-side (template + tolerant-pop) key set."""
    steps = files.get(TRAIN_STATE_FILE)
    ckpt = files.get(CHECKPOINT_FILE)
    if steps is None or ckpt is None:
        return []  # fixture trees without the pair: nothing to verify
    out: list[Finding] = []

    def finding(sf: SourceFile, line: int, msg: str) -> Finding:
        return Finding(
            rule="R006", path=sf.relpath, line=line, col=0, message=msg,
            snippet=sf.snippet(line), fixit=PROJECT_RULES["R006"].fixit,
        )

    fields = _train_state_fields(steps)
    if fields is None:
        return [finding(steps, 1, "TrainState class not found — cannot "
                                  "verify checkpoint schema")]
    save_fn = next(
        (n for n in ast.walk(ckpt.tree)
         if isinstance(n, ast.FunctionDef) and n.name == "save_checkpoint"),
        None,
    )
    load_fn = next(
        (n for n in ast.walk(ckpt.tree)
         if isinstance(n, ast.FunctionDef) and n.name == "load_checkpoint"),
        None,
    )
    if save_fn is None or load_fn is None:
        return [finding(ckpt, 1, "save_checkpoint/load_checkpoint not found "
                                 "— cannot verify checkpoint schema")]
    payload = _assigned_dict_keys(save_fn, "payload")
    if payload is None:
        return [finding(ckpt, save_fn.lineno,
                        "save_checkpoint has no literal 'payload' dict — "
                        "cannot verify checkpoint schema")]
    template = _assigned_dict_keys(load_fn, "template") or []
    load_keys = set(template) | _popped_keys(load_fn)
    for f in fields:
        if f not in payload:
            out.append(finding(
                ckpt, save_fn.lineno,
                f"TrainState field '{f}' is not serialized by "
                f"save_checkpoint — it silently resets on resume",
            ))
        if f not in load_keys:
            out.append(finding(
                ckpt, load_fn.lineno,
                f"TrainState field '{f}' is not restored by load_checkpoint",
            ))
    for k in payload:
        if k not in fields and k not in CHECKPOINT_EXTRA_KEYS:
            out.append(finding(
                ckpt, save_fn.lineno,
                f"checkpoint payload key '{k}' has no TrainState field "
                f"(stale schema)",
            ))
    return out
