"""MetricsBus — the process-wide live-metrics registry.

Everything post-hoc telemetry writes to files (metrics.jsonl, trace.jsonl),
the bus holds LIVE: named counters, gauges and :class:`~.hist.LogHistogram`
latency histograms that the trainer loop, the daemon's serve loop, the
serving microbatcher and the session table publish into as they run, and
that the ``/metrics`` / ``/statusz`` exporter (exporter.py) and the flight
recorder (flight.py) read out.

Contract:

- **Publishing is host-side bookkeeping only.** Every value published comes
  from data the caller already holds on the host (an epoch loss that was
  already fetched, a queue length, a wall-clock delta) — publishing never
  forces a device sync and never touches a traced program, so the bus's
  existence cannot perturb the compiled epoch (the S005 lowering-identity
  gate keeps proving it).
- **Snapshot-consistent reads.** :meth:`snapshot` copies the whole registry
  under ONE lock acquisition: a scrape never sees counter A from before a
  dispatch and gauge B from after it.
- **A NULL bus, not None-checks.** :data:`NULL_BUS` is a disabled instance
  whose methods return immediately — call sites thread a bus object
  unconditionally, exactly like :data:`~.tracer.NULL_TRACER`.
- **Series names are literals** (jaxlint R007 covers ``counter``/``gauge``/
  ``observe`` names); the variable part goes in label kwargs
  (``bus.counter("serving_requests_total", lane="infer")``).

One process-wide default lives behind :func:`global_bus` — the daemon CLI
and serving CLI publish and scrape through it; tests build private
instances.
"""

from __future__ import annotations

import threading

from .hist import DEFAULT_HI, DEFAULT_LO, DEFAULT_PER_DECADE, LogHistogram


def _escape_label(value) -> str:
    """Prometheus text-format label-value escaping (backslash, quote,
    newline). Applied when the series key is BUILT, so arbitrary label
    values — a site name with a quote in it — can never corrupt the
    /metrics exposition (or tear the key apart in a snapshot)."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def series_key(name: str, labels: dict) -> str:
    """The rendered series identity: ``name`` or ``name{k="v",...}`` with
    labels sorted — the same (name, labels) always lands on the same key."""
    if not labels:
        return name
    inner = ",".join(
        f'{k}="{_escape_label(v)}"' for k, v in sorted(labels.items())
    )
    return f"{name}{{{inner}}}"


class MetricsBus:
    """See module docstring."""

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, object] = {}
        self._hists: dict[str, LogHistogram] = {}

    # -- publishing -------------------------------------------------------

    def counter(self, name: str, n=1, **labels) -> None:
        """Monotonic counter increment (``*_total`` naming convention)."""
        if not self.enabled:
            return
        key = series_key(name, labels)
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + n

    def gauge(self, name: str, value, **labels) -> None:
        """Point-in-time value (queue depth, current epoch, occupancy)."""
        if not self.enabled:
            return
        key = series_key(name, labels)
        with self._lock:
            self._gauges[key] = value

    def clear_gauge(self, name: str, **labels) -> None:
        """Drop a gauge series (a member left; its liveness gauge must not
        linger at its last value)."""
        if not self.enabled:
            return
        with self._lock:
            self._gauges.pop(series_key(name, labels), None)

    def observe(self, name: str, value, *, lo: float = DEFAULT_LO,
                hi: float = DEFAULT_HI,
                per_decade: int = DEFAULT_PER_DECADE, **labels) -> None:
        """One sample into the named log-histogram (created on first use
        with the given shape; conventional unit: milliseconds)."""
        if not self.enabled:
            return
        key = series_key(name, labels)
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = LogHistogram(lo, hi, per_decade)
            h.record(value)

    # -- reading ----------------------------------------------------------

    def histogram(self, name: str, **labels) -> LogHistogram | None:
        """A COPY of the named histogram (merge-safe to aggregate further),
        or ``None`` when nothing has been observed into it."""
        with self._lock:
            h = self._hists.get(series_key(name, labels))
            return h.copy() if h is not None else None

    def merged_histogram(self, name: str) -> LogHistogram | None:
        """All label variants of ``name`` merged into one histogram — the
        cross-lane/cross-process rollup the SLO burn reads (merge order is
        irrelevant by the hist's associativity guarantee)."""
        with self._lock:
            parts = [
                h for key, h in self._hists.items()
                if key == name or key.startswith(name + "{")
            ]
            if not parts:
                return None
            out = LogHistogram(
                parts[0].lo, parts[0].hi, parts[0].per_decade
            )
            for h in parts:
                out.merge(h)
            return out

    def snapshot(self) -> dict:
        """Consistent point-in-time copy of every series, JSON-able:
        ``{"counters": {...}, "gauges": {...}, "histograms": {key:
        hist.to_dict()}}``."""
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {
                    k: h.to_dict() for k, h in self._hists.items()
                },
            }

    def reset(self) -> None:
        """Drop every series (tests; a bench excluding warmup)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()


class LabeledBusView:
    """A :class:`MetricsBus` facade that stamps fixed labels (e.g.
    ``tenant="studyA"``) onto every published series.

    The fleet scheduler (runner/scheduler.py, r22) hands each tenant's
    daemon a view of the ONE pod-wide bus: all tenants publish into the
    same registry — one snapshot, one /metrics exporter for the whole pod —
    but every series a tenant emits carries its identity, so
    ``serve_epoch{tenant="a"}`` and ``serve_epoch{tenant="b"}`` never
    collide. The fixed labels WIN over caller kwargs on collision: a
    tenant's code cannot (accidentally or otherwise) publish under another
    tenant's label. Reads (snapshot, histograms) delegate unfiltered to the
    underlying bus — a view is a publishing scope, not a privacy boundary;
    label-scoped reads use the label kwargs as usual.
    """

    def __init__(self, bus: MetricsBus, **labels):
        self._bus = bus
        self._labels = dict(labels)

    @property
    def enabled(self) -> bool:
        return self._bus.enabled

    @property
    def labels(self) -> dict:
        return dict(self._labels)

    # -- publishing (label-stamped) ---------------------------------------

    def counter(self, name: str, n=1, **labels) -> None:
        self._bus.counter(name, n, **{**labels, **self._labels})  # jaxlint: disable=R007

    def gauge(self, name: str, value, **labels) -> None:
        self._bus.gauge(name, value, **{**labels, **self._labels})  # jaxlint: disable=R007

    def clear_gauge(self, name: str, **labels) -> None:
        self._bus.clear_gauge(name, **{**labels, **self._labels})  # jaxlint: disable=R007

    def observe(self, name: str, value, *, lo: float = DEFAULT_LO,
                hi: float = DEFAULT_HI,
                per_decade: int = DEFAULT_PER_DECADE, **labels) -> None:
        self._bus.observe(
            name, value, lo=lo, hi=hi,  # jaxlint: disable=R007
            per_decade=per_decade, **{**labels, **self._labels},
        )

    # -- reading (delegated; label kwargs stamp like publishes) ------------

    def histogram(self, name: str, **labels):
        return self._bus.histogram(name, **{**labels, **self._labels})

    def merged_histogram(self, name: str):
        return self._bus.merged_histogram(name)

    def snapshot(self) -> dict:
        return self._bus.snapshot()

    def reset(self) -> None:
        self._bus.reset()


#: shared disabled instance — thread it where live metrics are off
NULL_BUS = MetricsBus(enabled=False)

#: the process-wide bus the CLIs publish and scrape through
_GLOBAL_BUS = MetricsBus()


def global_bus() -> MetricsBus:
    return _GLOBAL_BUS
