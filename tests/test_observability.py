"""Live observability plane tests (r16): mergeable log-histograms, the
MetricsBus, the /metrics /healthz /statusz /tracez exporter, the flight
recorder, microbatch queue-depth sampling, and cross-process trace
propagation (spool ingest → checkpoint publish → serve).
"""

import json
import math
import os
import random
import re
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from dinunet_implementations_tpu import TrainConfig
from dinunet_implementations_tpu.core.config import FSArgs
from dinunet_implementations_tpu.data.demo import make_fs_demo_tree
from dinunet_implementations_tpu.runner.fed_runner import FedDaemon
from dinunet_implementations_tpu.telemetry.bus import (
    NULL_BUS,
    MetricsBus,
    series_key,
)
from dinunet_implementations_tpu.telemetry.exporter import (
    StatusExporter,
    render_prometheus,
    slo_burn,
)
from dinunet_implementations_tpu.telemetry.flight import (
    FlightRecorder,
    flight_files,
)
from dinunet_implementations_tpu.telemetry.hist import (
    HistogramShapeError,
    LogHistogram,
    bucket_bounds,
)
from dinunet_implementations_tpu.telemetry.tracer import (
    SpanTracer,
    new_trace_id,
)

# ---------------------------------------------------------------------------
# LogHistogram
# ---------------------------------------------------------------------------


def test_hist_bounds_shared_and_validated():
    a, b = LogHistogram(), LogHistogram()
    assert a.bounds is b.bounds  # one cached tuple per shape
    assert bucket_bounds(1.0, 1000.0, 2) == pytest.approx(
        (1.0, 10 ** 0.5, 10.0, 10 ** 1.5, 100.0, 10 ** 2.5, 1000.0)
    )
    with pytest.raises(ValueError):
        LogHistogram(lo=0.0)
    with pytest.raises(ValueError):
        LogHistogram(lo=10.0, hi=1.0)
    with pytest.raises(ValueError):
        LogHistogram(per_decade=0)


def test_hist_quantile_bound_guarantee():
    """quantile(q) never understates the true empirical quantile and
    overstates it by at most one bucket ratio (10**(1/per_decade)) for
    in-range samples — the SLO math's conservative direction."""
    rng = random.Random(7)
    h = LogHistogram()
    vals = [rng.lognormvariate(1.0, 2.0) for _ in range(2000)]
    for v in vals:
        h.record(v)
    ranked = sorted(vals)
    growth = 10 ** (1 / h.per_decade)
    for q in (0.01, 0.25, 0.5, 0.9, 0.95, 0.99, 1.0):
        true = ranked[max(math.ceil(q * len(ranked)), 1) - 1]
        est = h.quantile(q)
        assert true <= est <= true * growth * (1 + 1e-12), (q, true, est)
    assert h.count == len(vals)
    assert h.min == min(vals) and h.max == max(vals)
    assert h.mean() == pytest.approx(sum(vals) / len(vals))


def test_hist_merge_associativity_exact():
    """Merging is exactly associative on the quantile-determining state:
    any merge tree over the same shards lands on identical counts, count,
    min/max — and therefore identical quantiles."""
    rng = random.Random(11)
    shards = [LogHistogram() for _ in range(3)]
    whole = LogHistogram()
    for i in range(999):
        v = rng.lognormvariate(0.0, 3.0)
        shards[i % 3].record(v)
        whole.record(v)
    a, b, c = shards
    left = a.copy().merge(b).merge(c)          # (a + b) + c
    right = a.copy().merge(b.copy().merge(c))  # a + (b + c)
    assert left.counts == right.counts == whole.counts
    assert left.count == right.count == whole.count
    assert left.min == right.min == whole.min
    assert left.max == right.max == whole.max
    for q in (0.5, 0.95, 0.99):
        assert left.quantile(q) == right.quantile(q) == whole.quantile(q)
    # merged() is non-destructive
    keep = a.count
    m = a.merged(b)
    assert a.count == keep and m.count == a.count + b.count
    with pytest.raises(HistogramShapeError):
        a.merge(LogHistogram(per_decade=3))


def test_hist_out_of_range_and_serialization():
    h = LogHistogram(lo=1.0, hi=100.0, per_decade=1)
    for v in (1e-9, 0.5, 5.0, 1e6):
        h.record(v)
    h.record(float("nan"))  # dropped: carries no rank information
    assert h.count == 4
    assert h.quantile(0.25) == 1.0     # underflow reports the lo edge
    assert h.quantile(1.0) == 1e6      # overflow reports the observed max
    d = json.loads(json.dumps(h.to_dict()))
    h2 = LogHistogram.from_dict(d)
    assert h2.counts == h.counts and h2.count == h.count
    assert h2.quantile(0.5) == h.quantile(0.5)
    assert h2.min == h.min and h2.max == h.max
    # cumulative exposition: monotone, ends at (+Inf, count)
    cum = h.cumulative()
    assert [c for _, c in cum] == sorted(c for _, c in cum)
    assert cum[-1][0] == math.inf and cum[-1][1] == h.count


# ---------------------------------------------------------------------------
# MetricsBus
# ---------------------------------------------------------------------------


def test_bus_series_and_snapshot_consistency():
    bus = MetricsBus()
    bus.counter("requests_total", 2, lane="infer")
    bus.counter("requests_total", lane="infer")
    bus.counter("requests_total", lane="stream")
    bus.gauge("epoch", 4)
    bus.observe("latency_ms", 10.0, lane="infer")
    bus.observe("latency_ms", 20.0, lane="stream")
    snap = bus.snapshot()
    assert snap["counters"][series_key("requests_total", {"lane": "infer"})] == 3
    assert snap["gauges"]["epoch"] == 4
    # snapshot is a copy: later publishes don't mutate it
    bus.gauge("epoch", 5)
    assert snap["gauges"]["epoch"] == 4
    # merged histogram rolls all label variants up (associative, so order
    # is irrelevant)
    merged = bus.merged_histogram("latency_ms")
    assert merged.count == 2
    assert bus.histogram("latency_ms", lane="infer").count == 1
    assert bus.histogram("latency_ms", lane="missing") is None
    bus.clear_gauge("epoch")
    assert "epoch" not in bus.snapshot()["gauges"]


def test_bus_snapshot_consistent_under_concurrent_writers():
    """A reader never sees a torn registry: writers bump two counters in
    lockstep; every snapshot must see them equal (both reads happen under
    the one snapshot lock)."""
    bus = MetricsBus()
    stop = threading.Event()

    def writer():
        while not stop.is_set():
            # lockstep: one call increments both series atomically from the
            # snapshot's point of view only if snapshot is lock-consistent
            with bus._lock:
                bus._counters["a_total"] = bus._counters.get("a_total", 0) + 1
                bus._counters["b_total"] = bus._counters.get("b_total", 0) + 1

    t = threading.Thread(target=writer, daemon=True)
    t.start()
    try:
        for _ in range(200):
            snap = bus.snapshot()
            assert snap["counters"].get("a_total", 0) == \
                snap["counters"].get("b_total", 0)
    finally:
        stop.set()
        t.join(5)


def test_label_values_escaped_in_series_and_exposition():
    """Arbitrary label values (a site name with quotes/backslashes/newlines
    — spool events are operator input) must not corrupt the series key or
    the /metrics exposition."""
    bus = MetricsBus()
    bus.gauge("serve_member_generation", 2, site='lab"1\\x\n')
    key = series_key("serve_member_generation", {"site": 'lab"1\\x\n'})
    assert bus.snapshot()["gauges"][key] == 2
    text = render_prometheus(bus.snapshot())
    _assert_valid_exposition(text)
    assert 'site="lab\\"1\\\\x\\n"' in text


def test_null_bus_is_inert():
    NULL_BUS.counter("x_total")
    NULL_BUS.gauge("g", 1)
    NULL_BUS.observe("h_ms", 1.0)
    snap = NULL_BUS.snapshot()
    assert snap == {"counters": {}, "gauges": {}, "histograms": {}}


# ---------------------------------------------------------------------------
# Prometheus exposition + SLO burn
# ---------------------------------------------------------------------------

#: exposition-format line shapes (text format 0.0.4)
_TYPE_RE = re.compile(r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* "
                      r"(counter|gauge|histogram)$")
_LABEL_VAL = r'"(?:[^"\\]|\\.)*"'  # escaped \" \\ \n allowed inside
_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"                        # metric name
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=" + _LABEL_VAL +        # first label
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=" + _LABEL_VAL + r")*\})? "  # more labels
    r"(-?\d+(\.\d+)?([eE][+-]?\d+)?|\+Inf|-Inf|NaN)$"   # value
)


def _assert_valid_exposition(text: str) -> None:
    assert text.endswith("\n")
    for line in text.splitlines():
        if not line:
            continue
        assert _TYPE_RE.match(line) or _SAMPLE_RE.match(line), line


def test_prometheus_exposition_valid():
    bus = MetricsBus()
    bus.counter("serving_requests_total", 5, lane="infer")
    bus.gauge("serve_epoch", 12)
    bus.gauge("weird name-with.chars", 1.5)
    for v in (0.5, 3.0, 3.0, 2e6):  # incl. one overflow sample
        bus.observe("request_latency_ms", v)
    text = render_prometheus(bus.snapshot())
    _assert_valid_exposition(text)
    assert 'dinunet_serving_requests_total{lane="infer"} 5' in text
    assert "dinunet_serve_epoch 12" in text
    assert "dinunet_weird_name_with_chars 1.5" in text  # sanitized
    # histogram contract: le-labeled cumulative buckets, monotone, the +Inf
    # bucket equals _count, and _sum is present
    buckets = [
        ln for ln in text.splitlines()
        if ln.startswith("dinunet_request_latency_ms_bucket")
    ]
    counts = [int(ln.rsplit(" ", 1)[1]) for ln in buckets]
    assert counts == sorted(counts)
    assert buckets[-1].startswith(
        'dinunet_request_latency_ms_bucket{le="+Inf"}'
    )
    assert counts[-1] == 4
    assert "dinunet_request_latency_ms_count 4" in text
    assert any(
        ln.startswith("dinunet_request_latency_ms_sum")
        for ln in text.splitlines()
    )


def test_slo_burn_math():
    h = LogHistogram()
    for _ in range(990):
        h.record(10.0)   # well under target
    for _ in range(10):
        h.record(5000.0)  # violations
    burn = slo_burn(h, p99_target=100.0)
    assert burn["samples"] == 1000 and burn["violations"] == 10
    assert burn["violation_rate"] == pytest.approx(0.01)
    assert burn["burn"] == pytest.approx(1.0)  # exactly at budget
    # conservative: a bucket straddling the target never counts
    assert slo_burn(h, p99_target=5000.0)["violations"] == 0
    empty = slo_burn(LogHistogram(), p99_target=100.0)
    assert empty["burn"] is None and empty["samples"] == 0
    assert slo_burn(None, p99_target=100.0)["burn"] is None


# ---------------------------------------------------------------------------
# exporter endpoints
# ---------------------------------------------------------------------------


def _get(url: str):
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def test_exporter_endpoints_live():
    bus = MetricsBus()
    bus.gauge("serve_epoch", 3)
    for v in (10.0, 20.0, 9000.0):
        bus.observe("serve_epoch_ms", v)
    tracer = SpanTracer()
    flight = FlightRecorder("/tmp/unused-obs", bus=bus, tracer=tracer)
    with tracer.span("epoch", epoch=3):
        pass
    ready = {"state": True}
    ex = StatusExporter(
        bus, port=0, tracer=tracer, flight=flight,
        health={"state": lambda: ready["state"],
                "broken": lambda: 1 / 0},
        statusz=lambda: {"round": 3},
        slo={"histogram": "serve_epoch_ms", "p99_target_ms": 100.0},
    )
    with ex:
        port = ex.port
        assert port > 0
        code, text = _get(f"http://127.0.0.1:{port}/metrics")
        assert code == 200
        _assert_valid_exposition(text)
        assert "dinunet_serve_epoch 3" in text
        # /healthz: the broken probe's error is a per-subsystem finding
        code, text = _get(f"http://127.0.0.1:{port}/healthz")
        payload = json.loads(text)
        assert code == 503 and payload["status"] == "unavailable"
        assert payload["subsystems"]["state"]["ready"]
        assert not payload["subsystems"]["broken"]["ready"]
        assert "division" in payload["subsystems"]["broken"]["error"]
        # /statusz: SLO burn from the real histogram + the caller's status
        code, text = _get(f"http://127.0.0.1:{port}/statusz")
        payload = json.loads(text)
        assert code == 200
        assert payload["status"]["round"] == 3
        assert payload["slo"]["samples"] == 3
        assert payload["slo"]["violations"] == 1  # the 9000ms epoch
        assert payload["slo"]["burn"] == pytest.approx(
            (1 / 3) / 0.01, rel=1e-3
        )
        assert payload["metrics"]["gauges"]["serve_epoch"] == 3
        # /tracez: the span is visible without waiting for trace.jsonl
        code, text = _get(f"http://127.0.0.1:{port}/tracez")
        payload = json.loads(text)
        assert code == 200 and payload["count"] >= 1
        assert any(e.get("name") == "epoch" for e in payload["recent"])
        code, _ = _get(f"http://127.0.0.1:{port}/nope")
        assert code == 404
    # stopped: connections refused
    with pytest.raises(urllib.error.URLError):
        urllib.request.urlopen(
            f"http://127.0.0.1:{port}/statusz", timeout=1
        )


def test_exporter_healthz_all_ready():
    ex = StatusExporter(MetricsBus(), health={"a": lambda: True})
    code, payload = ex.healthz()
    assert code == 200 and payload["status"] == "ok"


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def test_flight_ring_bounded_and_dump(tmp_path):
    bus = MetricsBus()
    bus.gauge("serve_epoch", 9)
    tracer = SpanTracer()
    flight = FlightRecorder(
        str(tmp_path), capacity=8, bus=bus, tracer=tracer
    )
    for i in range(50):
        with tracer.span("epoch", epoch=i):
            pass
    assert len(flight.recent(100)) == 8  # bounded ring, newest kept
    assert flight.recent(100)[-1]["epoch"] == 49
    flight.note("round-hold", occupied=0)
    path = flight.dump("signal:15")
    assert path is not None and os.path.exists(path)
    with open(path) as fh:
        payload = json.load(fh)
    assert payload["reason"] == "signal:15"
    assert payload["pid"] == os.getpid()
    names = [e["name"] for e in payload["events"]]
    assert "epoch" in names and "round-hold" in names
    assert payload["bus"]["gauges"]["serve_epoch"] == 9
    # a second dump doesn't clobber the first (crash during shutdown)
    path2 = flight.dump("crash:RuntimeError")
    assert path2 != path and os.path.exists(path) and os.path.exists(path2)
    assert flight_files(str(tmp_path)) == sorted([path, path2])


def test_flight_excepthook_chains_and_dumps(tmp_path):
    flight = FlightRecorder(str(tmp_path))
    prev_hook = sys.excepthook
    seen = []
    sys.excepthook = lambda *a: seen.append(a)
    try:
        flight.install(signals=())  # hooks only; no signal handlers
        assert sys.excepthook is not prev_hook
        try:
            raise RuntimeError("boom")
        except RuntimeError:
            sys.excepthook(*sys.exc_info())
        dumps = flight_files(str(tmp_path))
        assert len(dumps) == 1
        with open(dumps[0]) as fh:
            payload = json.load(fh)
        assert payload["reason"] == "crash:RuntimeError"
        assert any(
            e["name"] == "unhandled-exception" for e in payload["events"]
        )
        assert seen  # the previous hook still ran (chained)
        flight.uninstall()
    finally:
        sys.excepthook = prev_hook


def test_flight_dump_never_raises(tmp_path):
    flight = FlightRecorder(os.path.join(str(tmp_path), "f"))
    flight.record({"name": "x", "bad": object()})  # unserializable attr...
    assert flight.dump("crash") is not None  # ...stringified by default=str
    broken = FlightRecorder("/proc/definitely-unwritable/x")
    assert broken.dump("crash") is None  # best-effort: no raise


# ---------------------------------------------------------------------------
# microbatch queue-depth sampling (r16 satellite)
# ---------------------------------------------------------------------------


class _FakeReq:
    def __init__(self, n=1):
        import concurrent.futures

        self.rows = [0] * n
        self.future = concurrent.futures.Future()
        self._submit_t = 0.0


def test_microbatch_peak_depth_sampled_on_enqueue():
    """Regression: max_queue_depth was only sampled at dispatch time, so a
    burst that arrived and drained between dispatches under-reported the
    peak. With the dispatch thread wedged, enqueues alone must move the
    peak figure (and the bus gauge)."""
    from dinunet_implementations_tpu.serving.microbatch import Microbatcher

    bus = MetricsBus()
    release = threading.Event()

    def blocking_dispatch(reqs, bucket):
        release.wait(timeout=30)
        for r in reqs:
            r.future.set_result(None)

    lane = Microbatcher(
        blocking_dispatch, buckets=(1,), max_delay_ms=0.0, name="t",
        bus=bus,
    )
    try:
        lane.submit(_FakeReq())          # picked up, wedged in dispatch
        time.sleep(0.05)
        for _ in range(3):
            lane.submit(_FakeReq())      # queue up behind the wedge
        # the peak is visible BEFORE any further dispatch happens
        assert lane.stats["max_queue_depth"] >= 3
        snap = bus.snapshot()
        assert snap["gauges"][series_key(
            "serving_queue_depth", {"lane": "t"})] >= 3
    finally:
        release.set()
        lane.close()
    assert lane.stats["dispatches"] == 4
    assert lane.stats["max_queue_depth"] >= 3


def test_microbatch_deferral_counter():
    """Overflow deferrals (a request that doesn't fit the in-flight batch)
    are counted and published."""
    from dinunet_implementations_tpu.serving.microbatch import Microbatcher

    bus = MetricsBus()
    entered = threading.Event()
    release = threading.Event()

    def blocking_dispatch(reqs, bucket):
        entered.set()
        release.wait(timeout=30)
        for r in reqs:
            r.future.set_result(None)

    lane = Microbatcher(
        blocking_dispatch, buckets=(2,), max_delay_ms=200.0, name="t",
        bus=bus,
    )
    try:
        lane.submit(_FakeReq(1))
        lane.submit(_FakeReq(1))   # fills the bucket → dispatch fires
        entered.wait(timeout=10)
        lane.submit(_FakeReq(1))   # next collect starts with this one...
        lane.submit(_FakeReq(2))   # ...and this one overflows it → deferred
        release.set()
        for _ in range(200):
            if lane.stats["requests"] == 4:
                break
            time.sleep(0.01)
    finally:
        release.set()
        lane.close()
    assert lane.stats["requests"] == 4
    assert lane.stats["deferrals"] >= 1
    counters = bus.snapshot()["counters"]
    assert sum(
        v for k, v in counters.items()
        if k.startswith("serving_deferrals_total")
    ) >= 1


# ---------------------------------------------------------------------------
# end-to-end: daemon → bus/statusz/flight + trace propagation to serving
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def obs_tree(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("obs_tree"))
    make_fs_demo_tree(root, n_sites=2, subjects=20, n_features=8, seed=6)
    return root


def test_daemon_observability_and_trace_propagation(obs_tree, tmp_path):
    """One sample's journey, live: a spool join carrying a trace_id is
    followable through the daemon's /statusz membership, the bus series a
    scrape would see, the checkpoint meta it publishes, the serving engine
    that loads that checkpoint, and the dispatch row a request lands in —
    plus a flight dump with the final spans and bus snapshot."""
    bus = MetricsBus()
    out = str(tmp_path / "out")
    daemon = FedDaemon(
        TrainConfig(
            task_id="FS-Classification", batch_size=4, telemetry="on",
            fs_args=FSArgs(input_size=8, hidden_sizes=(8,)),
        ),
        capacity=4, spool_dir=str(tmp_path / "spool"), out_dir=out,
        data_path=obs_tree, quorum=1, poll_s=0.01, inventory_rows=32,
        verbose=False, bus=bus,
    )
    tid = new_trace_id()
    ev = {
        "event": "join", "site": "late-site", "trace_id": tid,
        "data_dir": os.path.join(
            obs_tree, "input", "local1", "simulatorRun"
        ),
        "config": {"labels_file": "site2_Covariate.csv"},
    }
    with open(os.path.join(daemon.spool_dir, "ev000.json"), "w") as fh:
        json.dump(ev, fh)
    daemon.serve(max_epochs=2)

    # -- live surfaces an exporter would serve (no HTTP needed: the
    # payload builders are plain methods)
    ex = StatusExporter(
        bus, health=daemon.health_probes(), statusz=daemon.status,
        slo={"histogram": "serve_epoch_ms", "p99_target_ms": 60_000.0},
        tracer=daemon.trainer.tracer, flight=daemon.flight,
    )
    code, health = ex.healthz()
    assert code == 200, health
    status = ex.statusz_payload()
    assert status["status"]["epoch"] == 2
    assert status["status"]["occupied"] == 3
    assert status["status"]["members"]["late-site"]["trace_id"] == tid
    assert status["slo"]["samples"] == 2  # one epoch_ms sample per epoch
    gauges = status["metrics"]["gauges"]
    assert gauges["serve_epoch"] == 2 and gauges["serve_members"] == 3
    assert gauges[series_key(
        "serve_member_generation", {"site": "late-site"})] == 1
    counters = status["metrics"]["counters"]
    assert counters["serve_epochs_total"] == 2
    # 2 pre-joined tree sites + the spooled join all count as applied
    assert counters[series_key(
        "serve_spool_events_total", {"result": "applied"})] == 3
    assert counters["serve_checkpoints_total"] >= 2
    assert "serve_spool_ingest_lag_s" in gauges
    text = ex.metrics_text()
    _assert_valid_exposition(text)
    assert "dinunet_serve_epoch 2" in text
    assert "dinunet_train_epoch 2" not in text  # daemon path, not fit()
    tracez = ex.tracez_payload()
    assert any(e.get("name") == "epoch" for e in tracez["recent"])

    # -- the trace id reached the published checkpoint
    from dinunet_implementations_tpu.trainer.checkpoint import load_meta

    meta = load_meta(daemon.ckpt_path)
    assert meta["traces"] == {"late-site": tid}

    # -- a flight dump carries the final spans + bus snapshot
    path = daemon.flight.dump("signal:15")
    with open(path) as fh:
        payload = json.load(fh)
    assert payload["bus"]["gauges"]["serve_epoch"] == 2
    names = {e["name"] for e in payload["events"]}
    assert "serve-epoch" in names and "checkpoint-publish" in names
    assert "epoch" in names  # tracer spans mirrored into the ring

    # -- ...and the serving engine, loading that checkpoint, surfaces the
    # provenance and stamps request trace ids into dispatch rows
    from dinunet_implementations_tpu.serving.engine import InferenceEngine
    from dinunet_implementations_tpu.telemetry.sink import FitTelemetry

    serve_bus = MetricsBus()
    sink = FitTelemetry.open(
        str(tmp_path / "serve_tel"), daemon.cfg, fold=0
    )
    engine = InferenceEngine(
        daemon.cfg, checkpoint=daemon.ckpt_path, row_buckets=(4,),
        sink=sink, bus=serve_bus,
    )
    engine.warmup()
    assert engine.status()["checkpoint_traces"] == {"late-site": tid}
    req_tid = new_trace_id()
    rows = daemon._data["late-site"].inputs[:2]
    fut = engine.submit(rows, trace_id=req_tid)
    assert fut.trace_id == req_tid
    probs = fut.result()
    assert probs.shape == (2, 2)
    auto = engine.submit(rows[:1])
    assert re.fullmatch(r"[0-9a-f]{16}", auto.trace_id)
    auto.result()
    engine.close()
    rows_out = [
        json.loads(ln)
        for ln in open(str(tmp_path / "serve_tel" / "metrics.jsonl"))
        if ln.strip()
    ]
    dispatches = [r for r in rows_out if r["kind"] == "dispatch"]
    assert any(req_tid in r.get("trace_ids", []) for r in dispatches)
    # serving bus series: per-request latency histogram + queue gauge
    assert serve_bus.merged_histogram(
        "serving_request_latency_ms").count == 2
    lat = slo_burn(
        serve_bus.merged_histogram("serving_request_latency_ms"), 60_000.0
    )
    assert lat["samples"] == 2 and lat["violations"] == 0


def test_trainer_fit_publishes_bus(obs_tree, tmp_path):
    """The batch trainer publishes live epoch series into an injected bus
    when telemetry is on (and stays on the NULL bus when off)."""
    from dinunet_implementations_tpu.runner.fed_runner import (
        FedRunner,
        load_site_splits,
    )
    from dinunet_implementations_tpu.runner.registry import get_task
    from dinunet_implementations_tpu.trainer.loop import FederatedTrainer

    cfg = TrainConfig(
        task_id="FS-Classification", epochs=2, batch_size=4, patience=50,
        telemetry="on", fs_args=FSArgs(input_size=8, hidden_sizes=(8,)),
    )
    runner = FedRunner(cfg, data_path=obs_tree, out_dir=str(tmp_path / "o"))
    bus = MetricsBus()
    trainer = FederatedTrainer(
        cfg, get_task(cfg.task_id).build_model(cfg), runner.mesh,
        out_dir=str(tmp_path / "o"), bus=bus,
    )
    fold = load_site_splits(cfg, runner.site_dirs, runner.site_cfgs)[0]
    trainer.fit(
        fold["train"], fold["validation"], fold["test"], fold=0,
        verbose=False,
    )
    snap = bus.snapshot()
    assert snap["gauges"]["train_epoch"] == 2
    assert snap["counters"]["train_epochs_total"] == 2
    assert snap["counters"]["train_rounds_total"] >= 2
    assert "train_loss" in snap["gauges"]
    assert bus.merged_histogram("epoch_ms").count == 2
    # the off path stays on the NULL bus
    off = FederatedTrainer(
        cfg.replace(telemetry="off"),
        get_task(cfg.task_id).build_model(cfg), runner.mesh,
    )
    assert off.bus is NULL_BUS
