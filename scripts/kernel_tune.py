"""Tune the fused LSTM kernel's batch tile on the real chip.

Times fwd+bwd of lstm_recurrence_fused at the bench's folded shape
(T=98, rows=32 sites x 16 batch = 512, D=256, H=174, bf16 streams) for a
range of B_TILE values, using the chained-iteration methodology from
bench.py (the tunneled backend is lazy; only full materialization of a
long dependent chain is honest).

Usage: python scripts/kernel_tune.py [--tiles 128,256,512]
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np

from dinunet_implementations_tpu.ops import lstm_pallas

T, ROWS, D, H = 98, 512, 256, 174
CHAIN = 60


def make_step(cdt):
    def loss(x, wih4, b4, whh4, h0, c0):
        hs, (hT, cT) = lstm_pallas.lstm_recurrence_fused(
            x, wih4, b4, whh4, h0, c0, cdt
        )
        return (hs.astype(jnp.float32).sum() + hT.sum() + cT.sum())

    g = jax.grad(loss, argnums=(0, 1, 2, 3, 4, 5))

    def step(x, wih4, b4, whh4, h0, c0):
        dx, dwih, db, dwhh, dh0, dc0 = g(x, wih4, b4, whh4, h0, c0)
        # chain: feed gradient signal back into the inputs so iterations
        # depend on each other and the lazy backend cannot skip any
        return (
            x + dx.astype(x.dtype) * 1e-6,
            wih4 + dwih * 1e-6,
            b4 + db * 1e-6,
            whh4 + dwhh * 1e-6,
            h0 + dh0 * 1e-6,
            c0 + dc0 * 1e-6,
        )

    return jax.jit(step)


def run(tile, cdt="bfloat16", chain=CHAIN, repeats=3):
    lstm_pallas.B_TILE = tile
    lstm_pallas._fwd_fused_callable.cache_clear()
    lstm_pallas._bwd_callable.cache_clear()
    rng = np.random.default_rng(0)
    args = (
        jnp.asarray(rng.normal(size=(T, ROWS, D)).astype(np.float32)),
        jnp.asarray(rng.normal(size=(4, D, H)).astype(np.float32) * 0.05),
        jnp.asarray(rng.normal(size=(4, H)).astype(np.float32) * 0.05),
        jnp.asarray(rng.normal(size=(4, H, H)).astype(np.float32) * 0.05),
        jnp.zeros((ROWS, H), jnp.float32),
        jnp.zeros((ROWS, H), jnp.float32),
    )
    step = make_step(cdt)

    def chain_run(n):
        a = args
        t0 = time.time()
        for _ in range(n):
            a = step(*a)
        jax.tree.map(np.asarray, a)
        return time.time() - t0

    chain_run(2)  # compile
    from bench import least_contended_marginal  # shared clamped estimator

    dt = least_contended_marginal(chain_run, chain, repeats=repeats)
    sps = ROWS / dt
    print(f"B_TILE={tile:4d} cdt={cdt}: {dt*1e3:8.3f} ms/iter  "
          f"({sps:,.0f} rows/s)", flush=True)
    return dt


def main():
    tiles = [128, 256, 512]
    if "--tiles" in sys.argv:
        tiles = [int(t) for t in sys.argv[sys.argv.index("--tiles") + 1].split(",")]
    for tile in tiles:
        run(tile)


if __name__ == "__main__":
    main()
