"""Fused Pallas power-iteration kernel (ops/poweriter_pallas.py, r14).

Four layers:
- kernel-vs-legacy PARITY: the interpret-mode kernel must reproduce
  ``lowrank.subspace_iteration_grouped`` member-for-member across rank
  classes, shape buckets, warm starts, zero members and the empty group
  (on CPU both sides run the same LAPACK CholeskyQR, so parity is
  bit-exact; the bf16 arm gets a tolerance for batching-order float noise);
- engine level: fused rankDAD's aggregate matches legacy rankDAD's on the
  same inputs (vmap-folded and packed topologies);
- fit level: a fused full fit tracks the legacy trajectory (tight
  tolerance) and clears the same hard-SNR golden floor;
- CompileGuard: the fused epoch compiles ONCE.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dinunet_implementations_tpu.core.config import TrainConfig
from dinunet_implementations_tpu.engines import make_engine
from dinunet_implementations_tpu.engines.lowrank import (
    subspace_iteration_grouped,
)
from dinunet_implementations_tpu.ops import poweriter_pallas as pp
from dinunet_implementations_tpu.runner import FedRunner


def _mk(rng, m, n, scale=1.0):
    return jnp.asarray((rng.normal(size=(m, n)) * scale).astype(np.float32))


def _flat(results):
    return [
        a for group in results for (P, Q) in group for a in (P, Q)
    ]


def _assert_close(legacy, fused, tol=0.0):
    for a, b in zip(_flat(legacy), _flat(fused)):
        assert a.shape == b.shape
        err = float(jnp.abs(a - b).max())
        assert err <= tol, f"{a.shape}: max diff {err} > {tol}"


# ---------------------------------------------------------------------------
# kernel parity
# ---------------------------------------------------------------------------


def test_fused_matches_legacy_across_rank_classes_bit_exact():
    """All rank classes at once — mixed shapes (several buckets), a
    duplicate-shape pair (one stacked bucket), rank clamped by small dims,
    and an exactly-zero member (the CholeskyQR canonical-basis fallback).
    On CPU the kernel's interpret mode traces to the same LAPACK math as
    the legacy loop, so parity is bit-exact."""
    rng = np.random.default_rng(0)
    groups = [
        ([_mk(rng, 12, 7), _mk(rng, 9, 7), _mk(rng, 12, 7),
          _mk(rng, 20, 5)], 4, None),
        ([_mk(rng, 6, 3)], 2, None),
        ([jnp.zeros((8, 5), jnp.float32)], 3, None),
    ]
    legacy = subspace_iteration_grouped(groups, 5, 1e-3)
    fused = subspace_iteration_grouped(groups, 5, 1e-3, fused=True)
    _assert_close(legacy, fused, tol=0.0)


def test_fused_matches_legacy_with_warm_starts():
    rng = np.random.default_rng(1)
    Gs = [_mk(rng, 10, 6), _mk(rng, 14, 6)]
    oms = [_mk(rng, 6, 4), _mk(rng, 6, 4)]
    legacy = subspace_iteration_grouped([(Gs, 4, oms)], 5, 1e-3)
    fused = subspace_iteration_grouped([(Gs, 4, oms)], 5, 1e-3, fused=True)
    _assert_close(legacy, fused, tol=0.0)


def test_fused_bf16_matmuls_match_legacy():
    """The lp_matmul mixed-precision policy inside the kernel: bf16 inputs,
    f32 accumulation — small float noise vs the legacy bf16 loop from
    batching order is allowed, nothing more."""
    rng = np.random.default_rng(2)
    Gs = [_mk(rng, 16, 8), _mk(rng, 16, 8)]
    legacy = subspace_iteration_grouped(
        [(Gs, 4, None)], 5, 1e-3, matmul_dtype=jnp.bfloat16
    )
    fused = subspace_iteration_grouped(
        [(Gs, 4, None)], 5, 1e-3, matmul_dtype=jnp.bfloat16, fused=True
    )
    _assert_close(legacy, fused, tol=1e-5)


def test_fused_empty_group_and_empty_list():
    assert subspace_iteration_grouped([], 5, 1e-3, fused=True) == []
    assert pp.fused_subspace_iteration_grouped([], 5, 1e-3) == []


def test_fused_reconstruction_quality_matches_legacy():
    """The factorization is a rank-r approximation — fused and legacy must
    agree on its quality, not just its bits."""
    rng = np.random.default_rng(3)
    G = _mk(rng, 24, 12)
    P, Q = subspace_iteration_grouped([([G], 6, None)], 8, 0.0,
                                      fused=True)[0][0]
    rec = float(jnp.linalg.norm(G - P @ Q.T) / jnp.linalg.norm(G))
    Pl, Ql = subspace_iteration_grouped([([G], 6, None)], 8, 0.0)[0][0]
    rec_l = float(jnp.linalg.norm(G - Pl @ Ql.T) / jnp.linalg.norm(G))
    assert abs(rec - rec_l) < 1e-6
    assert rec < 0.75  # rank-6 of a random 24x12 captures over a quarter


def test_vmem_budget_gate_falls_back_to_legacy():
    """A class bigger than the VMEM budget must not be fused — the split is
    trace-time static and the legacy loop carries it."""
    small = [jnp.ones((8, 4), jnp.float32)]
    assert pp.class_fits_vmem(small, 2)
    huge = [jax.ShapeDtypeStruct((4096, 4096), jnp.float32)] * 4
    assert not pp.class_fits_vmem(huge, 10)
    # mixed: the small class fuses, results still line up in order
    rng = np.random.default_rng(4)
    groups = [
        ([_mk(rng, 8, 4)], 2, None),
        ([_mk(rng, 10, 5)], 3, None),
    ]
    legacy = subspace_iteration_grouped(groups, 4, 1e-3)
    fused = subspace_iteration_grouped(groups, 4, 1e-3, fused=True)
    _assert_close(legacy, fused, tol=0.0)


def test_fused_under_vmap_folds_into_member_axis():
    """The custom_vmap rule: a mapped axis folds into the kernel's member
    axis instead of a sequential grid dim — results identical to mapping
    the legacy loop."""
    rng = np.random.default_rng(5)
    Gb = jnp.asarray(rng.normal(size=(6, 12, 7)).astype(np.float32))
    omb = jnp.asarray(rng.normal(size=(6, 7, 4)).astype(np.float32))

    def leg(G, om):
        return subspace_iteration_grouped([([G], 4, [om])], 5, 1e-3)[0][0]

    def fus(G, om):
        return subspace_iteration_grouped(
            [([G], 4, [om])], 5, 1e-3, fused=True
        )[0][0]

    Pl, Ql = jax.vmap(leg)(Gb, omb)
    Pf, Qf = jax.vmap(fus)(Gb, omb)
    assert float(jnp.abs(Pl - Pf).max()) <= 1e-6
    assert float(jnp.abs(Ql - Qf).max()) <= 1e-6


# ---------------------------------------------------------------------------
# engine level
# ---------------------------------------------------------------------------


def _site_grads(rng, S):
    return {
        "enc": jnp.asarray(rng.normal(size=(S, 12, 8)).astype(np.float32)),
        "head": jnp.asarray(rng.normal(size=(S, 8, 2)).astype(np.float32)),
        "bias": jnp.asarray(rng.normal(size=(S, 8)).astype(np.float32)),
    }


def test_fused_rankdad_aggregate_matches_legacy():
    """Engine level, vmap-folded sites: the fused engine's aggregate (and
    its warm-start Ω state) must match legacy's."""
    rng = np.random.default_rng(6)
    S = 4
    grads = _site_grads(rng, S)
    row = jax.tree.map(lambda g: g[0], grads)
    results = {}
    for fused in (False, True):
        eng = make_engine("rankDAD", dad_reduction_rank=3,
                          fused_poweriter=fused)
        st = jax.tree.map(
            lambda a: jnp.stack([a] * S), eng.init(row)
        )
        agg, new_st = jax.vmap(
            lambda g, s, w: eng.aggregate(g, s, w, "site"),
            axis_name="site",
        )(grads, st, jnp.ones((S,)))
        results[fused] = (agg, new_st)
    for a, b in zip(jax.tree.leaves(results[False]),
                    jax.tree.leaves(results[True])):
        assert float(jnp.abs(a - b).max()) <= 1e-6


# ---------------------------------------------------------------------------
# fit level + CompileGuard
# ---------------------------------------------------------------------------


def _hard_tree(tmp_path):
    from tests.test_golden import _make_hard_ica_tree

    _make_hard_ica_tree(tmp_path)


def _ica_cfg(**kw):
    return TrainConfig(
        task_id="ICA-Classification", agg_engine="rankDAD", epochs=8,
        patience=8, batch_size=8, split_ratio=(0.7, 0.15, 0.15), seed=0,
        **kw,
    )


def test_fused_full_fit_tracks_legacy_trajectory(tmp_path):
    """A short fused fit must track the legacy fit's loss trajectory to
    float-noise tolerance (the kernel changes WHERE the factorization
    computes, not what it computes)."""
    _hard_tree(tmp_path)
    losses = {}
    for fused in (False, True):
        res = FedRunner(
            _ica_cfg(fused_poweriter=fused),
            data_path=str(tmp_path),
            out_dir=str(tmp_path / f"out_{fused}"),
        ).run(verbose=False)[0]
        losses[fused] = res["epoch_losses"]
    a = np.asarray(losses[False], np.float64)
    b = np.asarray(losses[True], np.float64)
    assert a.shape == b.shape
    assert float(np.nanmax(np.abs(a - b))) < 5e-4, (a, b)


def test_fused_epoch_compiles_once():
    """CompileGuard: the fused epoch is still ONE compiled program across
    chained epochs."""
    from dinunet_implementations_tpu.checks.sanitize import jit_cache_size
    from dinunet_implementations_tpu.checks.semantic import (
        TraceCell,
        build_cell_inputs,
    )
    from dinunet_implementations_tpu.trainer.steps import make_train_epoch_fn

    task, _, opt, _, args, mesh = build_cell_inputs(
        TraceCell("rankDAD", "vmap", "host")
    )
    eng = make_engine("rankDAD", dad_reduction_rank=2, dad_num_pow_iters=2,
                      fused_poweriter=True)
    from dinunet_implementations_tpu.trainer.steps import init_train_state

    state = init_train_state(
        task, eng, opt, jax.random.PRNGKey(0), args[1][0, 0],
        num_sites=args[1].shape[0],
    )
    fn = make_train_epoch_fn(task, eng, opt, mesh=mesh)
    s = state
    for _ in range(3):
        s, _ = fn(s, *args[1:])
    jax.tree.map(np.asarray, s)
    assert jit_cache_size(fn) == 1
