"""Worker process for the live multi-process DCN test (test_distributed.py).

Each invocation is one "host" of a 2-process jax.distributed CPU cluster
(the COINSTAC one-container-per-site execution model, reference
``entry.py:5`` / ``compspec.json:284-295``, collapsed to one coordinated
JAX runtime):

    python dcn_worker.py <port> <num_processes> <process_id> \
        <data_path> <out_dir> <report_path>

With ``num_processes=1`` the same script runs the single-process reference
run the test compares against. The report JSON records the per-epoch losses
(bit-compared across processes and topologies), whether the mesh actually
spans processes, and how many times this process invoked the log writer —
proving the process-0-only output contract.
"""

import json
import os
import sys

port, nproc, pid, data_path, out_dir, report = sys.argv[1:7]
nproc, pid = int(nproc), int(pid)

# Belt and braces across jax versions: the XLA_FLAGS env var is consumed at
# backend-client creation (lazy — still effective even when sitecustomize
# imported jax at interpreter start, as long as no device was queried), and
# newer jax prefers the jax_num_cpu_devices config knob. The test harness
# strips the parent's XLA_FLAGS, so set our own before any jax device use.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=4"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 4)
except AttributeError:
    pass  # older jax: the XLA_FLAGS device-count flag set above applies

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dinunet_implementations_tpu.parallel import (  # noqa: E402
    distributed_init,
    distributed_shutdown,
)

multi = distributed_init(
    coordinator_address=f"127.0.0.1:{port}", num_processes=nproc, process_id=pid,
) if nproc > 1 else distributed_init()

import dinunet_implementations_tpu.trainer.loop as loop_mod  # noqa: E402
from dinunet_implementations_tpu import TrainConfig  # noqa: E402
from dinunet_implementations_tpu.parallel.distributed import (  # noqa: E402
    spans_processes,
)
from dinunet_implementations_tpu.runner import FedRunner  # noqa: E402

writes = {"logs": 0, "ckpt": 0}
_orig_logs = loop_mod.write_logs_json
_orig_ckpt = loop_mod.save_checkpoint


def _count_logs(*a, **k):
    writes["logs"] += 1
    return _orig_logs(*a, **k)


def _count_ckpt(*a, **k):
    writes["ckpt"] += 1
    return _orig_ckpt(*a, **k)


loop_mod.write_logs_json = _count_logs
loop_mod.save_checkpoint = _count_ckpt

cfg = TrainConfig(
    task_id="FS-Classification", epochs=4, validation_epochs=2, patience=10,
    batch_size=8, split_ratio=(0.7, 0.15, 0.15), seed=0,
)
runner = FedRunner(cfg, data_path=data_path, out_dir=out_dir)
try:
    res = runner.run(verbose=False)[0]
except Exception as e:  # noqa: BLE001 — capability probe, see below
    if "Multiprocess computations aren't implemented" in str(e):
        # this jaxlib's CPU backend cannot execute cross-process collectives
        # at all (e.g. 0.4.x): report "unsupported", distinct from a real
        # failure, so the test can skip instead of failing red
        print(f"UNSUPPORTED: {e}", flush=True)
        distributed_shutdown()
        sys.exit(66)
    raise

with open(report, "w") as fh:
    json.dump({
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "global_devices": len(jax.devices()),
        "local_devices": len(jax.local_devices()),
        "multi": bool(multi),
        "mesh_spans_processes": spans_processes(runner.mesh),
        "mesh_shape": dict(runner.mesh.shape),
        "epoch_losses": [float(x) for x in res["epoch_losses"]],
        "test_metrics": res["test_metrics"],
        "n_log_writes": writes["logs"],
        "n_ckpt_writes": writes["ckpt"],
    }, fh)

# clean teardown: leave the runtime re-entrant (the coordinated barrier in
# shutdown also surfaces a wedged peer here, as a nonzero exit, instead of
# letting the test's timeout mask it)
distributed_shutdown()
