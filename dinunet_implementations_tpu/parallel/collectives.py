"""Cross-site collectives — the aggregation transport.

The reference ships JSON-serialized gradients from every site container to the
remote container, which reduces them on an ``mp.Pool`` of ``num_reducers``
processes and broadcasts the result back (reference ``local.py:26-27,49``,
``remote.py:20-21,37``; payloads optionally cast to fp16 via ``precision_bits``,
``compspec.json:161-176``). Here each of those becomes a single XLA collective
over the ``site`` mesh axis: reduction rides ICI, the "broadcast back" is simply
the collective's replicated result. ~97% of reference wall-clock was this
transport (SURVEY.md §3.1); these primitives delete that cost class.

All functions are designed for use *inside* ``shard_map``/``pjit`` with a bound
axis name.

Axis forms (r12 — site packing). ``axis_name`` may be:

- a ``str`` mesh/vmap axis name — the classic one-site-per-collective-member
  form (one site per device, or all sites vmapped onto one device);
- a ``(mesh_axis, vmap_axis)`` tuple — the legacy folded form, kept for
  compatibility: collectives resolve the vmapped half through jax's batching
  rules, which ships the whole ``[K, ...]`` batched block over the mesh axis
  (K× wire inflation — the reason PackedAxis exists);
- a :class:`PackedAxis` — the packed two-level form: every payload leaf
  carries a LEADING ``[K]`` virtual-site axis, reductions run **local
  in-register sum over the packed axis first**, the partial is (optionally)
  quantized to the wire dtype, and ONE cross-device collective ships the
  unbatched partial over the mesh axis. Per-device wire bytes are then
  independent of K for every psum-shaped exchange; only genuine per-site
  payloads (the low-rank factor all-gather) scale with K.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from ..core.jaxcompat import axis_size
from .mesh import SITE_AXIS


@dataclasses.dataclass(frozen=True)
class PackedAxis:
    """The packed (K-sites-per-device) site axis: payload pytree leaves carry
    a leading ``[pack]`` virtual-site axis; reductions are two-level (local
    sum over that axis, then one cross-device collective over ``name``).
    ``name=None`` means no mesh half (every virtual site on one device — the
    cross-device collective degenerates to the identity); trace-time static,
    safe to close over in jitted code."""

    name: str | None  # the mesh axis (from parallel/mesh.py constants)
    pack: int  # K — virtual sites per device (the leading payload axis)


def _bcast(scale, like):
    """Reshape a per-virtual-site ``[K]`` vector to broadcast against a
    ``[K, ...]``-leading payload leaf."""
    return scale.reshape(scale.shape + (1,) * (like.ndim - scale.ndim))

# precision_bits payload casting (compspec.json:161-176). On TPU, "16" means
# bfloat16 (the native 16-bit type; same byte count on the wire, wider
# exponent); "16-ieee" opts into the reference's literal IEEE fp16 payload for
# bit-level compat runs. The reduction itself always accumulates in fp32.
_PAYLOAD_DTYPES = {
    "32": jnp.float32, 32: jnp.float32,
    "16": jnp.bfloat16, 16: jnp.bfloat16,
    "16-ieee": jnp.float16,
}


def payload_dtype(precision_bits="32"):
    """Resolve the ``precision_bits`` flag to the payload dtype."""
    return _PAYLOAD_DTYPES[precision_bits]


def site_weight_scale(weight, axis_name=SITE_AXIS):
    """Per-site normalized weight ``w_s / Σ w`` with a zero-total guard (an
    all-masked round yields scale 0, keeping updates finite). Packed form:
    ``weight`` is the ``[K]`` virtual-site vector and the total spans the
    local pack AND the mesh axis; the returned scale is ``[K]``."""
    w = jnp.asarray(weight, jnp.float32)
    if isinstance(axis_name, PackedAxis):
        total = jnp.sum(w)
        if axis_name.name is not None:
            total = jax.lax.psum(total, axis_name.name)
    else:
        total = jax.lax.psum(w, axis_name)
    return jnp.where(total > 0, w / jnp.maximum(total, 1e-12), 0.0)


def payload_cast(tree, precision_bits="32"):
    """Cast a gradient pytree to the configured payload dtype before the
    collective — the TPU equivalent of the reference's fp16 payload compression."""
    dtype = _PAYLOAD_DTYPES[precision_bits]
    return jax.tree.map(lambda g: g.astype(dtype), tree)


def payload_uncast(tree, like):
    """Restore original dtypes after the collective."""
    return jax.tree.map(lambda g, l: g.astype(l.dtype), tree, like)


def two_level_psum(x, axes: PackedAxis, wire_dtype=None):
    """The packed reduction primitive: in-register sum over the leading
    ``[K]`` virtual-site axis, the partial optionally quantized to
    ``wire_dtype`` (what the device actually ships — f32 accumulation resumes
    after the collective, policy above), then ONE cross-device psum of the
    UNBATCHED partial. The wire cost is K-independent by construction."""
    part = jnp.sum(x, axis=0)
    if wire_dtype is not None:
        part = wire_compress(part, wire_dtype)
    if axes.name is None:
        return part
    return jax.lax.psum(part, axes.name)


def weighted_site_sum(g, scale, axis_name, wire_dtype=None):
    """One dense payload leaf of a weighted exchange: ``Σ_s scale_s · g_s``
    accumulated in f32. Classic axes psum the per-site scaled value; a
    :class:`PackedAxis` takes the two-level route (``scale`` is then the
    ``[K]`` vector and ``g`` carries the leading pack axis). ``wire_dtype``
    quantizes the packed partial only — on the classic path the per-member
    payload is whatever the caller already cast it to."""
    gf = g.astype(jnp.float32)
    if isinstance(axis_name, PackedAxis):
        return two_level_psum(gf * _bcast(scale, gf), axis_name, wire_dtype)
    return jax.lax.psum(gf * scale, axis_name)


def site_sum(tree, axis_name=SITE_AXIS):
    """Sum a pytree across sites (the remote's reduce)."""
    if isinstance(axis_name, PackedAxis):
        return jax.tree.map(lambda g: two_level_psum(g, axis_name), tree)
    return jax.tree.map(lambda g: jax.lax.psum(g, axis_name), tree)


def site_mean(tree, axis_name=SITE_AXIS):
    """Unweighted mean across sites."""
    if isinstance(axis_name, PackedAxis):
        n = axis_name.pack * (
            1 if axis_name.name is None else axis_size(axis_name.name)
        )
        return jax.tree.map(
            lambda g: two_level_psum(g, axis_name) / n, tree
        )
    return jax.tree.map(lambda g: jax.lax.pmean(g, axis_name), tree)


def site_weighted_mean(tree, weight, axis_name=SITE_AXIS, wire_dtype=None):
    """Example-count-weighted mean across sites.

    dSGD semantics: each site contributes its gradient weighted by how many
    examples produced it (sites hold 73–120 subjects in the FS fixture —
    heterogeneous), so the aggregate equals the pooled-data gradient. ``weight``
    is a scalar per site (e.g. this round's example count) — the ``[K]``
    vector under a :class:`PackedAxis`, where the local weighted partial is
    reduced in-register and quantized to ``wire_dtype`` before the single
    cross-device psum (the two-level form; per-device wire bytes do not scale
    with K).
    """
    scale = site_weight_scale(weight, axis_name)
    # Accumulate in fp32 even for bf16 payloads; cast back only after the psum.
    return jax.tree.map(
        lambda g: weighted_site_sum(g, scale, axis_name, wire_dtype).astype(g.dtype),
        tree,
    )


def site_all_gather(x, axis_name=SITE_AXIS, axis: int = 0, tiled: bool = False):
    """Gather per-site values to every site (used by the low-rank engines to
    share rank-r factors instead of full gradients).

    ``axis_name`` may be a (mesh_axis, vmap_axis) tuple — the folded-sites
    case, where several simulated sites ride one device as a vmapped block.
    ``jax.lax.all_gather`` rejects mixed mesh/vmap axis tuples (unlike
    ``psum``), so gather each axis in turn, innermost first, and flatten: the
    leading dim comes out in global site order (outer*fold_size + inner),
    matching ``jax.lax.axis_index(axes)``.

    A :class:`PackedAxis` gathers the device's whole ``[K, ...]`` virtual-site
    block in ONE collective and flattens to the same global (device-major)
    site order — this is the one exchange whose wire bytes genuinely scale
    with K (every virtual site's factors must reach every device)."""
    if isinstance(axis_name, str):
        return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)
    if isinstance(axis_name, PackedAxis):
        assert axis == 0 and not tiled, "packed gather stacks the leading dim only"
        if axis_name.name is None:
            return x  # every virtual site already local: [S, ...] as-is
        out = jax.lax.all_gather(x, axis_name.name, axis=0)
        return out.reshape((-1,) + x.shape[1:])
    assert axis == 0 and not tiled, "tuple-axis gather supports leading-dim stacking only"
    out = x
    for ax in reversed(tuple(axis_name)):
        out = jax.lax.all_gather(out, ax, axis=0)
    return out.reshape((-1,) + x.shape)


def site_all_gather_packed(parts, axis_name=SITE_AXIS):
    """ONE ``all_gather`` for a list of same-dtype ``[k_i, ...]`` arrays
    (matching trailing dims): concatenate along axis 0, gather, re-split into
    ``[S, k_i, ...]`` views.

    The low-rank engines otherwise issue two gathers per compressible leaf
    (P and Q); packing turns a whole rank group's factor exchange into a
    single collective launch — comm volume unchanged (``r·Σ(m_i+n_i)`` per
    site), launch count divided by ``2·|group|`` (the flagship ICA-LSTM's
    r=10 group goes from 12 gathers per round to 1).

    Under a :class:`PackedAxis` the parts carry a leading ``[K]`` virtual-site
    axis (``[K, k_i, ...]``); they concatenate on axis 1, the device's whole
    ``[K, Σk_i, ...]`` block ships in one gather, and the splits come back in
    the same global-site-order ``[S, k_i, ...]`` views as the classic form —
    downstream reconstruction code is identical either way."""
    packed = isinstance(axis_name, PackedAxis)
    cat_axis = 1 if packed else 0
    if len(parts) == 1:
        return [site_all_gather(parts[0], axis_name)]
    sizes = [p.shape[cat_axis] for p in parts]
    gathered = site_all_gather(jnp.concatenate(parts, axis=cat_axis), axis_name)
    outs, off = [], 0
    for k in sizes:
        outs.append(gathered[:, off:off + k])
        off += k
    return outs


def wire_compress(x, pdtype):
    """Round-trip ``x`` through the wire payload dtype (``precision_bits``):
    the value a collective actually transports, restored to f32 so the
    reduction itself accumulates at full precision (policy above: psum never
    runs in bf16)."""
    return x.astype(pdtype).astype(jnp.float32)


def site_index(axis_name=SITE_AXIS):
    if isinstance(axis_name, PackedAxis):
        # per-device block start: virtual site d*K + j lives at row j of the
        # packed leaf on mesh member d (device-major global order)
        base = 0 if axis_name.name is None else jax.lax.axis_index(axis_name.name)
        return base * axis_name.pack
    return jax.lax.axis_index(axis_name)


def site_count(axis_name=SITE_AXIS):
    if isinstance(axis_name, PackedAxis):
        n = 1 if axis_name.name is None else axis_size(axis_name.name)
        return n * axis_name.pack
    return axis_size(axis_name)
