"""Version compatibility for the jax APIs this repo leans on.

The codebase targets the current jax surface (top-level ``jax.shard_map``
with ``check_vma``; ``jax.experimental.layout.Format(Layout.AUTO)``), but the
pinned container may carry an older 0.4.x jaxlib where those are spelled
``jax.experimental.shard_map.shard_map(..., check_rep=...)`` and
``Layout(DeviceLocalLayout.AUTO)``. One shim owns the difference so every
trainer/test call site stays on the new spelling.
"""

from __future__ import annotations

import jax

try:  # new API (jax >= 0.6): top-level shard_map, check_vma kwarg
    from jax import shard_map as _shard_map_new

    def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
        return _shard_map_new(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
except ImportError:  # 0.4.x: experimental module, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map_old

    def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
        # 0.4.x's replication checker has no rule for `while` (the low-rank
        # engines' tol loop) and aborts instead of skipping — so the old-jax
        # shim always runs unchecked; the new-jax path keeps full checking.
        del check_vma
        return _shard_map_old(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )


def axis_size(axis_name):
    """Static size of a bound mesh/vmap axis. ``jax.lax.axis_size`` on
    current jax; older versions spell it ``psum(1, axis)`` (a compile-time
    constant either way)."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def auto_input_format():
    """The AUTO input-layout marker accepted by ``jax.jit(in_shardings=...)``
    (lets XLA choose the layout of a large resident input — see
    ``trainer.steps.compile_epoch_aot``)."""
    try:
        from jax.experimental.layout import Format, Layout

        return Format(Layout.AUTO)
    except ImportError:
        from jax.experimental.layout import DeviceLocalLayout, Layout

        return Layout(DeviceLocalLayout.AUTO)


def input_formats_of(compiled):
    """The compiled executable's chosen input layouts (name changed from
    ``input_layouts`` to ``input_formats`` across jax versions)."""
    if hasattr(compiled, "input_formats"):
        return compiled.input_formats
    return compiled.input_layouts


#: last jaxlib known to corrupt the heap when a cache-DESERIALIZED
#: executable coexists with the donated-table streaming step (see
#: serving/engine.py warmup and stream_cache_safe below)
_STREAM_CACHE_BAD_THROUGH = (0, 4)


def stream_cache_safe(version: str | None = None) -> bool:
    """Whether the persistent compile cache may stay enabled while warming
    the DONATED-table streaming executables.

    On jaxlib 0.4.x (observed 0.4.36, CPU) any cache-deserialized executable
    living in the process corrupts the heap once the streaming step — whose
    session table is an input-output-aliased donated buffer — runs
    (segfault; repro in serving/engine.py warmup docstring and the
    ``test_stream_cache_gate`` probe). The workaround used to bypass the
    cache for every streaming warmup unconditionally; this gate narrows it
    to the known-bad jaxlib range so fixed runtimes get the cache-warm
    startup back. The subprocess regression probe in tests/test_fleet.py
    re-runs the repro whenever this gate opens — a jaxlib that still has
    the bug fails the probe loudly instead of corrupting a server."""
    if version is None:
        import jaxlib

        version = jaxlib.__version__
    try:
        parts = tuple(int(p) for p in version.split(".")[:2])
    except ValueError:
        return False  # unparseable version: keep the safe bypass
    return parts > _STREAM_CACHE_BAD_THROUGH


def enable_compile_cache(path: str) -> None:
    """Point jax's persistent compilation cache at ``path`` (opt-in via
    ``TrainConfig.compile_cache_dir`` / CLI ``--compile-cache``).

    Re-runs and per-fold re-fits of the same (engine, topology) program then
    deserialize the compiled epoch instead of re-running XLA. Idempotent —
    safe to call once per trainer. The write thresholds are zeroed so even
    fast-compiling programs (CPU tests, --small benches) populate the cache;
    the knobs are best-effort across jax versions."""
    import os

    os.makedirs(path, exist_ok=True)
    try:
        from jax.experimental.compilation_cache import compilation_cache as cc

        cc.set_cache_dir(path)
        # jax latches its cache-used decision on the FIRST compilation of the
        # process (is_cache_used's once-per-task check); enabling the cache
        # mid-session (a trainer constructed after other jax work) needs the
        # latch cleared or nothing is ever written
        if hasattr(cc, "reset_cache"):
            cc.reset_cache()
    except ImportError:
        jax.config.update("jax_compilation_cache_dir", path)
    for opt, val in (
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
    ):
        try:
            jax.config.update(opt, val)
        except (AttributeError, ValueError):
            pass  # older jax without this knob: its default threshold applies


__all__ = [
    "shard_map", "auto_input_format", "input_formats_of",
    "enable_compile_cache", "stream_cache_safe",
]
