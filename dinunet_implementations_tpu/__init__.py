"""dinunet-tpu: TPU-native federated deep-learning framework.

A ground-up re-design of the capabilities of trendscenter/dinunet_implementations
(COINSTAC dinunet — decentralized NN training across sites) for TPU:

- each federated site maps to a slice of a ``jax.sharding.Mesh`` ("site" axis);
- the reference's local↔remote JSON round trip collapses into one pjit SPMD
  train step; aggregation engines (dSGD / rankDAD / powerSGD) are XLA
  collectives + in-jit low-rank compression;
- trainers/datasets/data-handles keep the reference's abstraction surface
  (SURVEY.md §2.3) with a functional JAX core.
"""

from .core.config import (
    AggEngine,
    FSArgs,
    ICAArgs,
    MultimodalArgs,
    NNComputation,
    PretrainArgs,
    SMRI3DArgs,
    TrainConfig,
    export_compspec,
    load_inputspec,
    resolve_site_configs,
)
from .parallel.mesh import (
    MODEL_AXIS,
    SITE_AXIS,
    SLICE_AXIS,
    host_mesh,
    make_site_mesh,
    sliced_site_mesh,
)

__version__ = "0.18.0"


def __getattr__(name):
    # Heavier subsystems are imported lazily so `import dinunet_implementations_tpu`
    # stays light for config-only uses.
    if name in ("run_checks", "sanitized_fit", "SanitizerViolation", "CompileGuard"):
        from . import checks

        return getattr(checks, name)
    if name in ("FedRunner", "SiteRunner"):
        from .runner import fed_runner

        return getattr(fed_runner, name)
    if name == "FederatedTrainer":
        from .trainer.loop import FederatedTrainer

        return FederatedTrainer
    if name in ("FaultPlan", "Preempted", "PreemptionGuard", "with_retry"):
        from . import robustness

        return getattr(robustness, name)
    if name in ("RdpAccountant", "SECURE_AGGS"):
        from . import privacy

        return getattr(privacy, name)
    if name in ("SpanTracer", "FitTelemetry"):
        from . import telemetry

        return getattr(telemetry, name)
    if name == "InferenceEngine":
        from .serving import InferenceEngine

        return InferenceEngine
    raise AttributeError(name)
