"""Golden-metric regression (VERDICT round-1 #2): the rebuild must reach
reference-grade accuracy on the reference's own fixture for all three
aggregation engines.

Reference numbers: 2-site FS-Classification run, ``nnlogs.ipynb`` cell 2
(BASELINE.md): dSGD [0.72688, 0.81404], rankDAD [0.38915, 0.85351],
powerSGD [0.33662, 0.90702] as test [loss, AUC]. Here the full 5-site
``datasets/test_fsl`` fixture trains to convergence (patience-based early
stop, same compspec defaults) and must meet or beat each engine's reference
AUC. Measured on this harness (seed 0): dSGD 0.967, rankDAD 0.914,
powerSGD 0.984 — wall-clock ~12-26s on the 8-device CPU simulator vs the
reference's 695-2339s per engine.
"""

import math
import os

import pytest

from dinunet_implementations_tpu import TrainConfig
from dinunet_implementations_tpu.runner import FedRunner

FSL = "/root/reference/datasets/test_fsl"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(FSL), reason="reference fixture not mounted"
)

REFERENCE_AUC = {  # nnlogs.ipynb cell 2 (BASELINE.md)
    "dSGD": 0.81404,
    "rankDAD": 0.85351,
    "powerSGD": 0.90702,
}


@pytest.mark.golden
@pytest.mark.parametrize("engine", ["dSGD", "rankDAD", "powerSGD"])
def test_engine_converges_to_reference_grade_auc(engine, tmp_path):
    cfg = TrainConfig(
        agg_engine=engine, epochs=101, patience=35,
        split_ratio=(0.7, 0.15, 0.15), seed=0,
    )
    res = FedRunner(cfg, data_path=FSL, out_dir=str(tmp_path)).run(verbose=False)[0]
    loss, auc = res["test_metrics"][0]
    ref = REFERENCE_AUC[engine]
    assert auc >= ref, (
        f"{engine}: converged test AUC {auc:.4f} below the reference's "
        f"{ref:.4f} (best_val_epoch={res['best_val_epoch']}, "
        f"stopped={res['stopped_epoch']})"
    )
    assert loss > 0 and math.isfinite(loss)
