"""Load-aware admission control — the p99-targeted max-delay autotuner.

The microbatcher's ``max_delay_ms`` is the one knob trading tail latency for
batch occupancy: a longer delay fills buckets (throughput) and a shorter one
dispatches partial buckets sooner (latency). :class:`DelayAutotuner` closes
the loop against the live per-lane latency histogram
(``serving_request_latency_ms{lane=...}``), targeting a p99 objective with
the SLO error budget from telemetry/exporter.py.

**Why the controller cannot oscillate on bucket error.** The histogram's two
estimators are conservative in OPPOSITE directions (telemetry/hist.py):

- ``over(target)`` counts only samples CERTAIN to exceed the target (buckets
  whose lower edge clears it) — it never overstates violations. The
  controller only SHRINKS the delay when ``over/count`` exceeds the error
  budget, so a shrink is always backed by real violations, never by bucket
  quantization.
- ``quantile(0.99)`` returns the bucket's UPPER edge — it never understates
  the true p99. The controller only GROWS the delay when that upper bound
  sits below ``target x headroom`` (headroom < 1), so a grow happens only
  when the true p99 provably has slack.

Between those two certainties lies a dead band (the bucket-quantization
gray zone plus the headroom margin) where the controller HOLDS. A sample
distribution sitting near the target therefore parks the knob instead of
flapping it — the classic hysteresis argument, with the hysteresis width
derived from the histogram's own error bounds rather than hand tuning.

Decisions consume WINDOW histograms (``LogHistogram.delta`` between
successive cumulative snapshots), so each step reacts to traffic since the
last step, not the process lifetime; windows with fewer than
``min_samples`` observations hold (no decision on noise).
"""

from __future__ import annotations

import threading
import time

from ..telemetry.exporter import SLO_BUDGET
from ..telemetry.hist import LogHistogram


class DelayAutotuner:
    """One per microbatcher lane. Call :meth:`step` with that lane's window
    histogram (or run :class:`AutotunerDaemon` to do it on a clock)."""

    def __init__(self, lane, *, p99_target_ms: float,
                 budget: float = SLO_BUDGET, headroom: float = 0.5,
                 shrink: float = 0.5, grow: float = 1.25,
                 min_delay_ms: float = 0.05, max_delay_ms: float = 50.0,
                 min_samples: int = 20, bus=None):
        from ..telemetry.bus import NULL_BUS

        if not 0 < headroom < 1:
            raise ValueError(f"headroom must be in (0, 1), got {headroom}")
        if not 0 < shrink < 1 < grow:
            raise ValueError(
                f"need shrink < 1 < grow, got {shrink}/{grow}"
            )
        self.lane = lane
        self.p99_target_ms = float(p99_target_ms)
        self.budget = float(budget)
        self.headroom = float(headroom)
        self.shrink = float(shrink)
        self.grow = float(grow)
        self.min_delay_ms = float(min_delay_ms)
        self.max_delay_ms = float(max_delay_ms)
        self.min_samples = int(min_samples)
        self.bus = bus if bus is not None else NULL_BUS
        self.decisions = {"shrink": 0, "grow": 0, "hold": 0}

    def step(self, window: LogHistogram | None) -> str:
        """One control decision over a window histogram; returns
        ``"shrink" | "grow" | "hold"`` and (except hold) retunes the lane's
        ``max_delay_s`` in place — the microbatcher reads it fresh at every
        collect."""
        decision = "hold"
        if window is not None and window.count >= self.min_samples:
            certain_violations = window.over(self.p99_target_ms)
            p99_upper = window.quantile(0.99)
            if certain_violations / window.count > self.budget:
                decision = "shrink"
            elif p99_upper is not None and (
                    p99_upper <= self.p99_target_ms * self.headroom):
                decision = "grow"
        if decision != "hold":
            cur_ms = self.lane.max_delay_s * 1e3
            factor = self.shrink if decision == "shrink" else self.grow
            new_ms = min(
                max(cur_ms * factor, self.min_delay_ms), self.max_delay_ms
            )
            if new_ms == cur_ms:
                decision = "hold"  # parked at a clamp
            else:
                self.lane.max_delay_s = new_ms / 1e3
        self.decisions[decision] += 1
        self.bus.gauge(
            "serving_max_delay_ms", self.lane.max_delay_s * 1e3,
            lane=self.lane.name, **getattr(self.lane, "labels", {}),
        )
        self.bus.counter(
            "serving_autotune_decisions_total", decision=decision,
            lane=self.lane.name, **getattr(self.lane, "labels", {}),
        )
        return decision


class AutotunerDaemon:
    """Clocked driver: every ``interval_s`` it snapshots each lane's
    cumulative latency histogram from the bus, forms the window delta since
    its previous snapshot, and steps that lane's :class:`DelayAutotuner`.
    Daemon thread; :meth:`stop` to halt (engines/fleets stop it in
    ``close``)."""

    def __init__(self, bus, tuners: list, *, interval_s: float = 1.0,
                 hist_name: str = "serving_request_latency_ms"):
        self.bus = bus
        self.tuners = list(tuners)
        self.interval_s = float(interval_s)
        self.hist_name = hist_name
        self._prev: dict = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="delay-autotuner", daemon=True
        )

    def start(self) -> "AutotunerDaemon":
        self._thread.start()
        return self

    def tick(self) -> None:
        """One pass over every lane (also what the thread runs on its
        clock — callable directly for deterministic tests)."""
        for tuner in self.tuners:
            labels = {
                "lane": tuner.lane.name, **getattr(tuner.lane, "labels", {}),
            }
            cum = self.bus.histogram(self.hist_name, **labels)
            if cum is None:
                continue
            key = tuple(sorted(labels.items()))
            prev = self._prev.get(key)
            self._prev[key] = cum  # bus.histogram already returns a copy
            tuner.step(cum.delta(prev) if prev is not None else None)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.tick()

    def stop(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(5.0)
