"""Composition scenario (r22): every hostile subsystem at once, ONE fit.

The repo proves its planes one at a time — faults (r14), attacks +
robust aggregation (r17), DP-SGD (r15/r20), site packing (r12), the
sliced DCN topology (r18/r19). This test turns them ALL on in a single
fit and gates the combination on the oracles those rounds established:

- packed (K=2) == unpacked (K=1): losses, final params, per-site health
  counters, and the spent ε are identical across pack factors — no plane
  re-keys on the physical topology;
- the chaos actually happened: the NaN-poisoned site is quarantined, the
  dropped site skipped rounds, ε is finite and positive;
- ``DINUNET_SANITIZE=compile`` wraps both arms — the composed program
  still compiles exactly ONCE per fit (a violation raises);
- each arm's telemetry passes ``report --validate``.
"""

import os

import jax
import numpy as np
import pytest

from dinunet_implementations_tpu.core.config import FSArgs, TrainConfig
from dinunet_implementations_tpu.data.demo import make_fs_demo_tree
from dinunet_implementations_tpu.robustness.attacks import AttackPlan
from dinunet_implementations_tpu.robustness.faults import FaultPlan
from dinunet_implementations_tpu.runner import FedRunner
from dinunet_implementations_tpu.telemetry import report


def _run_arm(tmp_path, tree, k):
    """One composed fit at pack factor ``k``: 4 virtual sites on 2 DCN
    slices, a site dropped mid-window, a NaN-poisoned site (sticky
    quarantine), a slice outage, a permanent sign-flipper plus a scaled
    burst under trimmed-mean aggregation, and DP-SGD with a live ledger."""
    cfg = TrainConfig(
        task_id="FS-Classification", epochs=2, patience=10, batch_size=4,
        seed=7, telemetry="on", donate_epoch_state=False,
        num_slices=2, staleness_bound=2, sites_per_device=k,
        robust_agg="trimmed_mean", quarantine_rounds=1,
        dp_clip=1.0, dp_noise_multiplier=0.5,
        fs_args=FSArgs(input_size=8, hidden_sizes=(8,)),
    )
    out = str(tmp_path / f"out_k{k}")
    runner = FedRunner(
        cfg, data_path=tree, out_dir=out,
        fault_plan=FaultPlan(
            drop=((3, 2, 4),),        # site 3 offline rounds 2-4
            nan_at=((1, 0),),         # site 0 poisoned at round 1
            slice_drop_at=((1, 5, 6),),  # slice 1 outage rounds 5-6
        ),
        attack_plan=AttackPlan(
            sign_flip=((2, 0, -1),),  # site 2 hostile forever
            scale=((1, 5, 8),), scale_factor=4.0,
        ),
    )
    return runner.run(verbose=False)[0], out


def test_composed_fit_packed_matches_unpacked(tmp_path, monkeypatch):
    monkeypatch.setenv("DINUNET_SANITIZE", "compile")
    tree = make_fs_demo_tree(str(tmp_path / "tree"), n_sites=4,
                             subjects=32, n_features=8, seed=0)
    r2, out2 = _run_arm(tmp_path, tree, 2)
    r1, out1 = _run_arm(tmp_path, tree, 1)
    # the packing equivalence policy (test_packing.py) survives the full
    # composition: same trajectory, same final weights
    np.testing.assert_allclose(
        r2["epoch_losses"], r1["epoch_losses"], atol=1e-6
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-6
        ),
        r2["state"].params, r1["state"].params,
    )
    # the chaos planes actually fired, and identically in both packings
    health2, health1 = r2["site_health"], r1["site_health"]
    assert health2["site_quarantined"] == health1["site_quarantined"]
    assert sum(health2["site_quarantined"]) >= 1  # the poisoned site
    assert health2["site_skipped_rounds"] == health1["site_skipped_rounds"]
    assert sum(health2["site_skipped_rounds"]) >= 1  # the dropped site
    # the ε ledger is packing-agnostic (counter keyed on GLOBAL site ids)
    assert r2["dp_epsilon"] is not None and r2["dp_epsilon"] > 0
    assert r2["dp_epsilon"] == pytest.approx(r1["dp_epsilon"], rel=1e-12)
    # each arm's telemetry is schema-valid end to end
    for out in (out2, out1):
        tdir = os.path.join(out, "telemetry", "fold_0")
        assert report.main([tdir, "--validate"]) == 0
