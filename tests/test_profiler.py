"""Opt-in jax.profiler hook (SURVEY.md §5 tracing ask; VERDICT r2 #4/#5)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dinunet_implementations_tpu.core.config import TrainConfig
from dinunet_implementations_tpu.data.api import SiteArrays
from dinunet_implementations_tpu.models import MSANNet
from dinunet_implementations_tpu.trainer import FederatedTrainer


def _sites(n=2, size=12, F=6, seed=0):
    rng = np.random.default_rng(seed)
    return [
        SiteArrays(
            rng.normal(size=(size, F)).astype(np.float32),
            (rng.random(size) > 0.5).astype(np.int64),
            np.arange(size),
        )
        for _ in range(n)
    ]


@pytest.mark.slow
def test_profile_dir_writes_trace(tmp_path):
    prof = str(tmp_path / "traces")
    cfg = TrainConfig(
        epochs=2, batch_size=4, patience=10, profile_dir=prof,
        fs_args=TrainConfig().fs_args.__class__(input_size=6, hidden_sizes=(8,)),
    )
    trainer = FederatedTrainer(
        cfg, MSANNet(in_size=6, hidden_sizes=(8,), out_size=2), mesh=None
    )
    res = trainer.fit(_sites(), _sites(seed=1), _sites(seed=2), verbose=False)
    assert np.isfinite(res["test_metrics"][0][0])
    fold_dir = os.path.join(prof, "fold_0")
    assert os.path.isdir(fold_dir)
    # jax writes a plugins/profile/<ts>/*.trace.json.gz (or .pb) tree
    found = [
        os.path.join(r, f) for r, _, fs in os.walk(fold_dir) for f in fs
    ]
    assert found, "profiler trace directory is empty"
