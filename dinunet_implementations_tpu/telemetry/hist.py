"""Fixed log-spaced mergeable latency histograms.

The live-observability substrate (r16): every latency/duration series the
MetricsBus carries — serving per-request latency, epoch wall time, spool
ingest lag — is a :class:`LogHistogram`, chosen over a raw sample list for
three properties:

- **Bounded state.** A daemon that serves for weeks records into a fixed
  ``O(decades x per_decade)`` vector of integer bucket counts; the exporter's
  ``/metrics`` and ``/statusz`` reads stay O(1) regardless of traffic.
- **Exact merge associativity.** Bucket bounds are FIXED at construction
  (pure functions of ``(lo, hi, per_decade)``), so merging two histograms is
  elementwise integer addition of counts plus min/max — ``(a+b)+c`` and
  ``a+(b+c)`` land on bit-identical quantile-determining state, whatever the
  merge tree (per-lane, per-process or per-fleet rollups all agree). The
  auxiliary ``sum`` (for means and the Prometheus ``_sum`` series) is a float
  accumulator and carries ordinary float-summation caveats; every quantile
  and count is exact.
- **Bounded quantile error.** ``quantile(q)`` returns the UPPER edge of the
  bucket holding the q-th sample, so the estimate never understates the true
  empirical quantile and overstates it by at most one bucket ratio
  (``10**(1/per_decade)``, ~26% at the default 10 buckets/decade) for
  in-range samples. SLO burn math (exporter.py) inherits the conservative
  direction: a reported-met p99 target is really met.

Deliberately stdlib-only (the exporter and flight recorder must not pull
jax in) and lock-free: the MetricsBus serializes access.
"""

from __future__ import annotations

import math

#: default bucket range: 1µs to 100s when recording milliseconds — covers
#: a microbatch dispatch on one end and a cold compile on the other
DEFAULT_LO = 1e-3
DEFAULT_HI = 1e5
DEFAULT_PER_DECADE = 10

#: shared bound vectors, keyed by (lo, hi, per_decade) — every histogram of
#: one shape aliases ONE tuple, so merge compatibility is an identity check
_BOUNDS_CACHE: dict = {}


def bucket_bounds(lo: float = DEFAULT_LO, hi: float = DEFAULT_HI,
                  per_decade: int = DEFAULT_PER_DECADE) -> tuple:
    """The finite upper bucket edges for a ``(lo, hi, per_decade)`` shape:
    ``lo * r**i`` for ``i = 0..n`` with ``r = 10**(1/per_decade)``, computed
    from integer exponents (never by repeated multiplication) so every
    histogram of one shape gets bit-identical edges."""
    key = (float(lo), float(hi), int(per_decade))
    cached = _BOUNDS_CACHE.get(key)
    if cached is not None:
        return cached
    lo_f, hi_f, per = key
    if not (0 < lo_f < hi_f):
        raise ValueError(f"need 0 < lo < hi, got lo={lo_f}, hi={hi_f}")
    if per < 1:
        raise ValueError(f"per_decade must be >= 1, got {per}")
    n = math.ceil(round(per * math.log10(hi_f / lo_f), 9))
    bounds = tuple(lo_f * 10.0 ** (i / per) for i in range(n + 1))
    _BOUNDS_CACHE[key] = bounds
    return bounds


class HistogramShapeError(ValueError):
    """Merging histograms with different bucket shapes."""


class LogHistogram:
    """See module docstring. ``record`` values in any unit you like —
    the conventional bus unit is milliseconds (``*_ms`` series names)."""

    __slots__ = ("lo", "hi", "per_decade", "bounds", "counts", "count",
                 "sum", "min", "max")

    def __init__(self, lo: float = DEFAULT_LO, hi: float = DEFAULT_HI,
                 per_decade: int = DEFAULT_PER_DECADE):
        self.lo = float(lo)
        self.hi = float(hi)
        self.per_decade = int(per_decade)
        self.bounds = bucket_bounds(lo, hi, per_decade)
        # counts[i] <-> (bounds[i-1], bounds[i]]; counts[0] is the underflow
        # bucket (-inf, lo]; counts[-1] the overflow (bounds[-1], +inf)
        self.counts = [0] * (len(self.bounds) + 1)
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    # -- recording --------------------------------------------------------

    def _bucket_of(self, value: float) -> int:
        if value <= self.bounds[0]:
            return 0
        if value > self.bounds[-1]:
            return len(self.bounds)
        # log-index guess, corrected against the exact edges (float log can
        # land one bucket off right at an edge)
        i = int(self.per_decade * math.log10(value / self.lo)) + 1
        i = min(max(i, 1), len(self.bounds) - 1)
        while value > self.bounds[i]:
            i += 1
        while i > 0 and value <= self.bounds[i - 1]:
            i -= 1
        return i

    def record(self, value) -> None:
        v = float(value)
        if math.isnan(v):
            return  # NaN carries no rank information; keep quantiles exact
        self.counts[self._bucket_of(v)] += 1
        self.count += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v

    # -- merging ----------------------------------------------------------

    def _check_shape(self, other: "LogHistogram") -> None:
        if self.bounds is not other.bounds and self.bounds != other.bounds:
            raise HistogramShapeError(
                f"cannot merge histograms of different shapes: "
                f"(lo={self.lo}, hi={self.hi}, per_decade={self.per_decade})"
                f" vs (lo={other.lo}, hi={other.hi}, "
                f"per_decade={other.per_decade})"
            )

    def merge(self, other: "LogHistogram") -> "LogHistogram":
        """In-place elementwise merge; returns self. Exactly associative on
        counts/count/min/max (see module docstring)."""
        self._check_shape(other)
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.count += other.count
        self.sum += other.sum
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)
        return self

    def merged(self, other: "LogHistogram") -> "LogHistogram":
        """Non-destructive merge into a fresh histogram."""
        out = LogHistogram(self.lo, self.hi, self.per_decade)
        return out.merge(self).merge(other)

    # -- estimation -------------------------------------------------------

    def quantile(self, q: float):
        """Upper-edge estimate of the q-th quantile (``None`` when empty).
        Guarantee for in-range samples: ``true <= quantile(q) <=
        true * 10**(1/per_decade)``. The underflow bucket reports ``lo``
        (an upper edge too); the overflow bucket reports the exact observed
        ``max`` (the one value the histogram tracks beyond its range)."""
        if self.count == 0:
            return None
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile q must be in [0, 1], got {q}")
        rank = max(1, math.ceil(q * self.count))
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= rank:
                if i >= len(self.bounds):
                    return self.max
                return self.bounds[i]
        return self.max  # unreachable; counts always sum to count

    def percentiles(self) -> dict:
        """The SLO trio, ready for a statusz/summary row."""
        return {
            "p50": self.quantile(0.50),
            "p95": self.quantile(0.95),
            "p99": self.quantile(0.99),
        }

    def mean(self):
        return self.sum / self.count if self.count else None

    def over(self, threshold: float) -> int:
        """Samples CERTAIN to exceed ``threshold`` — counts in buckets whose
        LOWER edge is >= threshold (conservative: a bucket straddling the
        threshold doesn't count, so SLO burn never overstates violations)."""
        total = 0
        for i, c in enumerate(self.counts):
            lower = -math.inf if i == 0 else self.bounds[i - 1]
            if lower >= threshold:
                total += c
        return total

    # -- (de)serialization -------------------------------------------------

    def to_dict(self) -> dict:
        """JSON-able snapshot (statusz payloads, flight-recorder dumps).
        Sparse: only non-zero buckets, keyed by index."""
        return {
            "lo": self.lo, "hi": self.hi, "per_decade": self.per_decade,
            "count": self.count, "sum": self.sum,
            "min": self.min if self.count else None,
            "max": self.max if self.count else None,
            "buckets": {
                str(i): c for i, c in enumerate(self.counts) if c
            },
            **{k: v for k, v in self.percentiles().items()},
        }

    @classmethod
    def from_dict(cls, d: dict) -> "LogHistogram":
        h = cls(d["lo"], d["hi"], d["per_decade"])
        for i, c in d.get("buckets", {}).items():
            h.counts[int(i)] = int(c)
        h.count = int(d["count"])
        h.sum = float(d["sum"])
        h.min = d["min"] if d.get("min") is not None else math.inf
        h.max = d["max"] if d.get("max") is not None else -math.inf
        return h

    def copy(self) -> "LogHistogram":
        out = LogHistogram(self.lo, self.hi, self.per_decade)
        out.merge(self)
        return out

    def delta(self, since: "LogHistogram") -> "LogHistogram":
        """The WINDOW histogram between a cumulative snapshot ``since``
        (taken earlier from the same monotone series) and now — elementwise
        integer subtraction of counts, exact for the same reason merge is.
        This is what the publish controller's post-swap SLO-burn check and
        the max-delay autotuner read: burn over the observation window, not
        the process lifetime. ``min``/``max`` of the window alone are not
        recoverable from two cumulative snapshots, so the window inherits
        the full-series envelope — ``max`` can only OVERSTATE the window's
        true max, which keeps ``quantile()``'s never-understate guarantee
        (only the overflow bucket reports ``max``)."""
        self._check_shape(since)
        out = LogHistogram(self.lo, self.hi, self.per_decade)
        for i, c in enumerate(self.counts):
            d = c - since.counts[i]
            if d < 0:
                raise HistogramShapeError(
                    "delta() needs an EARLIER snapshot of the same series; "
                    f"bucket {i} went backwards ({since.counts[i]} -> {c})"
                )
            out.counts[i] = d
        out.count = self.count - since.count
        out.sum = self.sum - since.sum
        if out.count:
            out.min, out.max = self.min, self.max
        return out

    # -- Prometheus exposition --------------------------------------------

    def cumulative(self) -> list:
        """``[(le_edge, cumulative_count), ...]`` ending with ``(inf, count)``
        — the ``_bucket{le=...}`` series of the Prometheus histogram type."""
        out = []
        running = 0
        for i, c in enumerate(self.counts):
            running += c
            le = self.bounds[i] if i < len(self.bounds) else math.inf
            out.append((le, running))
        return out
