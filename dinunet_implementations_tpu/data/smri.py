"""Structural-MRI (T1w volume) dataset — TPU-build extension.

Follows the ICA dataset's fixture convention (data/ica.py): a numpy archive of
volumes ``[N, D, H, W]`` named by ``data_file`` plus a ``labels_file`` CSV of
``[index, label]`` rows; no reference implementation exists (BASELINE.json
configs list the 3D-CNN sMRI federated classifier as a target workload).
"""

from __future__ import annotations

import numpy as np

from .api import SiteArrays, SiteDataset
from .ica import ICADataHandle, load_timecourses


def space_to_depth_222_np(vols: np.ndarray) -> np.ndarray:
    """Host-side twin of ``models.cnn3d.space_to_depth_222``: ``[N, D, H, W]``
    (or trailing singleton channel) → ``[N, D/2, H/2, W/2, 8]`` with voxel
    ``(2i+di, 2j+dj, 2k+dk)`` in channel ``di·4 + dj·2 + dk``. Applied ONCE
    at dataset load: the per-step in-model fold cost 2.0–2.6× whole-epoch
    throughput in layout copies on the 8-site bench
    (docs/bench_smri_s2d_ab_r5.jsonl; the fold itself is cheap — re-doing
    it on a [S, B, 64³, 1] resident array every step is not). Channel-order
    parity with the model fold is pinned by ``tests/test_extensions.py``."""
    if vols.ndim == 5:
        if vols.shape[-1] != 1:
            raise ValueError(
                f"space_to_depth needs single-channel volumes, got C="
                f"{vols.shape[-1]}"
            )
        vols = vols[..., 0]
    N, D, H, W = vols.shape
    if any(d % 2 for d in (D, H, W)):
        raise ValueError(
            f"space_to_depth needs even spatial dims, got {(D, H, W)}"
        )
    v = vols.reshape(N, D // 2, 2, H // 2, 2, W // 2, 2)
    return np.ascontiguousarray(
        np.transpose(v, (0, 1, 3, 5, 2, 4, 6))
    ).reshape(N, D // 2, H // 2, W // 2, 8)


class SMRIDataset(SiteDataset):
    def __init__(self, **kw):
        super().__init__(**kw)
        self.data = None

    def _load_indices(self, files, **kw):
        self.data = np.asarray(
            load_timecourses(self.path(cache_key="data_file")), np.float32
        )
        # pipeline-level fold (SMRI3DArgs.space_to_depth): the model KEEPS the
        # flag (runner/registry.py builds SMRI3DNet with space_to_depth=True)
        # and recognizes the pre-folded 8-channel input as a no-op — same
        # architecture/params as an in-model fold, none of the per-step
        # relayout cost (see space_to_depth_222_np)
        if self.cache.get("space_to_depth"):
            self.data = space_to_depth_222_np(self.data)
        self.indices += [list(f) for f in files]

    def __getitem__(self, ix) -> dict:
        data_index, y = self.indices[ix]
        return {"inputs": self.data[int(data_index)], "labels": int(y), "ix": ix}

    def as_arrays(self) -> SiteArrays:
        rows = np.asarray([int(i) for i, _ in self.indices])
        return SiteArrays(
            self.data[rows],
            np.asarray([int(y) for _, y in self.indices], np.int32),
            np.arange(len(rows), dtype=np.int32),
        )


class SMRIDataHandle(ICADataHandle):
    """Same ``[index, label]`` CSV inventory as the ICA handle."""
